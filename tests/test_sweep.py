"""photon-sweep tests: dirty-gated incremental coordinate descent
(game/sweep.py + RandomEffectCoordinate.train_model_gated, docs/SWEEPS.md).

The parity ladder under test:

1. ``gate=0`` (theta=0, grad_tol=0 — the bare ``--sweep`` default) is
   BIT-IDENTICAL to an ungated run: coefficients and the checkpointed
   residual total, across all four random-effect model types (dense,
   projected, subspace, factored-in-sequence).
2. Gated runs land inside the repo's 5e-3 coefficient band with the
   mandatory final full sweep as the backstop — and actually skip
   entities in between (the perf claim has a visible shape: ledger
   ``re_fit_wave`` rows and the refit/skipped counters).
3. A killed gated run resumes BIT-IDENTICAL to an unkilled gated run —
   in-process (KeyboardInterrupt mid-descent) and end-to-end (SIGKILL
   via ``--fault-plan`` at the ``sweep.gate_state`` seam, rerun with
   ``--resume``).
"""

import json
import os
import subprocess
import sys

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu import faults, obs
from photon_ml_tpu.api.configs import (CoordinateConfiguration,
                                       FixedEffectDataConfiguration,
                                       RandomEffectDataConfiguration,
                                       parse_sweep_config)
from photon_ml_tpu.api.estimator import GameEstimator
from photon_ml_tpu.data import synthetic
from photon_ml_tpu.data.game_data import from_synthetic
from photon_ml_tpu.game import descent
from photon_ml_tpu.game import sweep as swp
from photon_ml_tpu.game.checkpoint import CheckpointManager
from photon_ml_tpu.game.coordinates import (FixedEffectCoordinate,
                                            RandomEffectCoordinate)
from photon_ml_tpu.game.factored import FactoredRandomEffectCoordinate
from photon_ml_tpu.obs.ledger import RunLedger, fit_wave_summary, read_rows
from photon_ml_tpu.ops import losses
from photon_ml_tpu.optim import OptimizerConfig
from photon_ml_tpu.optim.problem import GLMOptimizationConfiguration
from photon_ml_tpu.optim.regularization import (RegularizationContext,
                                                RegularizationType)
from photon_ml_tpu.parallel.mesh import make_mesh
from photon_ml_tpu.types import TaskType

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), os.pardir))


@pytest.fixture(scope="module")
def mesh():
    return make_mesh()


@pytest.fixture(autouse=True)
def _clean_obs_state():
    yield
    obs.set_ledger(None)
    obs.disable()
    faults.install(None)


def _opt(l2=1.0, max_iter=40):
    return GLMOptimizationConfiguration(
        optimizer=OptimizerConfig(max_iterations=max_iter, tolerance=1e-7),
        regularization=RegularizationContext(RegularizationType.L2, l2))


def _game(rng, n=600, users=30, d_re=3):
    syn = synthetic.game_data(rng, n=n, d_global=4,
                              re_specs={"userId": (users, d_re)})
    return from_synthetic(syn)


# ------------------------------------------------------------------- units


def test_sweep_config_validation_and_gate_zero():
    assert swp.SweepConfig().gate_zero
    assert swp.SweepConfig(grad_tol=1e-4).gate_zero is False
    assert swp.SweepConfig(theta=1e-3).gate_zero is False
    with pytest.raises(ValueError, match="theta"):
        swp.SweepConfig(theta=-1.0)
    with pytest.raises(ValueError, match="grad_tol"):
        swp.SweepConfig(grad_tol=-1e-9)
    with pytest.raises(ValueError, match="min_sweeps_full"):
        swp.SweepConfig(min_sweeps_full=0)


def test_next_pow2_and_compact_lanes():
    assert [swp.next_pow2(k) for k in (1, 2, 3, 4, 5, 63, 64, 65)] == \
        [1, 2, 4, 4, 8, 64, 64, 128]
    # Floored at the entity pad multiple, capped at the tuple's lanes.
    assert swp.compact_lanes(3, 8, 256) == 8
    assert swp.compact_lanes(9, 8, 256) == 16
    assert swp.compact_lanes(200, 8, 256) == 256
    assert swp.compact_lanes(0, 8, 256) == 8


def test_parse_sweep_config():
    assert parse_sweep_config("") == swp.SweepConfig()
    got = parse_sweep_config(
        "theta=1e-3,grad_tol=1e-4,min_sweeps_full=2,final_full=false,"
        "gram=true")
    assert got == swp.SweepConfig(theta=1e-3, grad_tol=1e-4,
                                  min_sweeps_full=2,
                                  final_full_sweep=False, gram=True)
    with pytest.raises(ValueError, match="unknown"):
        parse_sweep_config("thet=1")
    with pytest.raises(ValueError):
        parse_sweep_config("final_full=maybe")


def test_gate_and_advance_semantics():
    """Drift accumulates across skipped sweeps; grad evidence defaults to
    always-dirty; untrained entities never gate in."""
    ids = np.array([0, 0, 1, 1, 2, 2], np.int32)
    st = swp.CoordinateSweepState(3, ids, scale=np.full(3, 2.0),
                                  trained=np.array([True, True, False]))
    cfg = swp.SweepConfig(theta=0.1, grad_tol=1e-3)
    o0 = jnp.zeros(6, jnp.float32)
    st.advance(o0)  # full sweep: off_ref = o0
    # No solver evidence yet (+inf grad norms) -> every TRAINED entity
    # is dirty regardless of drift.
    dirty, drift = st.gate(o0, cfg)
    np.testing.assert_array_equal(np.asarray(dirty), [True, True, False])
    np.testing.assert_array_equal(np.asarray(drift), 0.0)
    st.grad_norms = jnp.zeros(3, jnp.float32)  # converged evidence
    # Entity 1's rows drift past theta*scale = 0.2; entity 0 stays clean.
    o1 = jnp.asarray(np.array([0.01, 0.0, 0.5, 0.25, 9.0, 9.0],
                              np.float32))
    dirty, drift = st.gate(o1, cfg)
    np.testing.assert_array_equal(np.asarray(dirty), [False, True, False])
    np.testing.assert_allclose(np.asarray(drift), [0.01, 0.75, 18.0])
    # Advance moves ONLY dirty entities' references: entity 0 keeps
    # accumulating the 0.01 it already drifted.
    st.advance(o1, dirty)
    o2 = jnp.asarray(np.array([0.15, 0.1, 0.5, 0.25, 9.0, 9.0],
                              np.float32))
    dirty2, drift2 = st.gate(o2, cfg)
    np.testing.assert_allclose(np.asarray(drift2), [0.25, 0.0, 18.0])
    np.testing.assert_array_equal(np.asarray(dirty2),
                                  [True, False, False])
    # Checkpoint round-trip restores the evidence exactly.
    fresh = swp.CoordinateSweepState(3, ids, scale=np.full(3, 2.0),
                                     trained=np.array([True, True, False]))
    fresh.restore(st.to_arrays())
    np.testing.assert_array_equal(np.asarray(fresh.grad_norms),
                                  np.asarray(st.grad_norms))
    np.testing.assert_array_equal(np.asarray(fresh.off_ref),
                                  np.asarray(st.off_ref))


def test_fit_wave_summary_aggregates_per_iteration():
    rows = [
        {"kind": "re_fit_wave", "coordinate": "per-user",
         "outer_iteration": 0, "wave": 0, "seconds": 0.5,
         "entities_fit": 8, "entities_skipped": 0, "drift_p99": 0.0},
        {"kind": "re_fit_wave", "coordinate": "per-user",
         "outer_iteration": 0, "wave": 1, "seconds": 0.25,
         "entities_fit": 4, "entities_skipped": 0, "drift_p99": 0.0},
        {"kind": "re_fit_wave", "coordinate": "per-user",
         "outer_iteration": 1, "wave": 0, "seconds": 0.1,
         "entities_fit": 2, "entities_skipped": 10, "drift_p99": 3e-4},
        {"kind": "opt_iter", "coordinate": "per-user"},
    ]
    got = fit_wave_summary(rows)
    assert list(got) == ["per-user"]
    it0, it1 = got["per-user"]
    assert it0["entities_fit"] == 12 and it0["waves"] == 2
    assert it1["entities_skipped"] == 10 and it1["drift_p99"] == 3e-4


# --------------------------------------- rung 1: gate=0 bit-identity


def _variant_coordinates(variant, ds, mesh):
    """fixed + one per-user coordinate of the requested model type."""
    if variant in ("projected", "subspace"):
        opt = _opt()
        cc = {
            "fixed": CoordinateConfiguration(
                data=FixedEffectDataConfiguration("global"),
                optimization=opt),
            "per-user": CoordinateConfiguration(
                data=RandomEffectDataConfiguration(
                    "userId", "re_userId", projector="INDEX_MAP",
                    subspace_model=(variant == "subspace")),
                optimization=opt),
        }
        est = GameEstimator(TaskType.LOGISTIC_REGRESSION, cc,
                            ["fixed", "per-user"], mesh)
        return est._build_coordinates(
            ds, {cid: c.optimization for cid, c in cc.items()})
    coords = {"fixed": FixedEffectCoordinate(ds, "global", losses.LOGISTIC,
                                             _opt(), mesh)}
    if variant == "dense":
        coords["per-user"] = RandomEffectCoordinate(
            ds, "userId", "re_userId", losses.LOGISTIC, _opt(), mesh)
    else:  # factored: no make_sweep_state -> always takes the full path
        coords["per-user"] = FactoredRandomEffectCoordinate(
            ds, "userId", "re_userId", losses.LOGISTIC, _opt(), mesh,
            rank=2, alternations=1)
    return coords


def _ckpt_arrays(directory):
    """Every committed coefficients.npz + residuals.npz, flattened."""
    out = {}
    for root, _, files in os.walk(os.path.join(directory, "model")):
        for f in files:
            if f == "coefficients.npz":
                with np.load(os.path.join(root, f)) as z:
                    for k in z.files:
                        out[f"{os.path.basename(root)}/{k}"] = z[k]
    with np.load(os.path.join(directory, "residuals.npz")) as z:
        out["residual_total"] = z["total"]
    return out


@pytest.mark.parametrize("variant",
                         ["dense", "projected", "subspace", "factored"])
def test_gate_zero_is_bit_identical(rng, mesh, tmp_path, variant):
    """Rung 1: theta=0, grad_tol=0 runs HEAD's full-sweep expressions —
    bit-equal coefficients AND residual total, per model type."""
    ds = _game(rng, n=500, users=20)
    cfg = descent.CoordinateDescentConfig(["fixed", "per-user"],
                                          iterations=3)
    _, a_dir = _run(variant, ds, mesh, cfg, tmp_path, "a", sweep=None)
    _, b_dir = _run(variant, ds, mesh, cfg, tmp_path, "b",
                    sweep=swp.SweepConfig())  # gate=0
    a, b = _ckpt_arrays(a_dir), _ckpt_arrays(b_dir)
    assert sorted(a) == sorted(b)
    for k in a:
        np.testing.assert_array_equal(a[k], b[k], err_msg=k)


def _run(variant, ds, mesh, cfg, tmp_path, name, sweep):
    d = str(tmp_path / name)
    model, _ = descent.run(TaskType.LOGISTIC_REGRESSION,
                           _variant_coordinates(variant, ds, mesh), cfg,
                           checkpoint_manager=CheckpointManager(d),
                           sweep=sweep)
    return model, d


# ------------------------------ rung 2: gated band + visible skipping


def test_gated_run_skips_and_stays_in_band(rng, mesh, tmp_path):
    """Gated sweeps actually skip entities on iterations >= 2 (ledger
    rows + counters move), the final-full-sweep backstop refits every
    entity, and the final model lands in the 5e-3 band of a full run."""
    ds = _game(rng, n=800, users=30)
    cfg = descent.CoordinateDescentConfig(["fixed", "per-user"],
                                          iterations=4)
    coords = _variant_coordinates("dense", ds, mesh)
    ref, _ = descent.run(TaskType.LOGISTIC_REGRESSION, dict(coords), cfg)

    obs.enable(trace=False)
    led = RunLedger.resume(str(tmp_path / "ledger"))
    obs.set_ledger(led)
    try:
        got, _ = descent.run(
            TaskType.LOGISTIC_REGRESSION,
            _variant_coordinates("dense", ds, mesh), cfg,
            sweep=swp.SweepConfig(theta=0.05, grad_tol=0.05))
    finally:
        led.close()
        obs.set_ledger(None)

    np.testing.assert_allclose(np.asarray(got.models["per-user"].means),
                               np.asarray(ref.models["per-user"].means),
                               atol=5e-3, rtol=5e-3)

    rows, problems = read_rows(str(tmp_path / "ledger"))
    assert problems == []
    waves = [r for r in rows if r.get("kind") == "re_fit_wave"]
    assert waves, "gated run recorded no re_fit_wave rows"
    by_iter = {}
    for r in waves:
        it = r["outer_iteration"]
        by_iter.setdefault(it, [0, 0])
        by_iter[it][0] += r["entities_fit"]
        by_iter[it][1] += r["entities_skipped"]
    trained = int(coords["per-user"].bucketing.trained_entities.sum())
    # Warm-up sweep (min_sweeps_full=1) and the final backstop are full.
    assert by_iter[0] == [trained, 0]
    assert by_iter[3] == [trained, 0]
    skipped = sum(by_iter[it][1] for it in (1, 2))
    assert skipped > 0, f"gate never engaged: {by_iter}"
    assert all(f + s == trained for f, s in by_iter.values())
    # The counters tell the same story.
    snap = obs.metrics().snapshot()
    skip_keys = [k for k in snap
                 if k.startswith("photon_re_entities_skipped_total")]
    refit_keys = [k for k in snap
                  if k.startswith("photon_re_entities_refit_total")]
    assert skip_keys and sum(snap[k] for k in skip_keys) == skipped
    assert sum(snap[k] for k in refit_keys) == \
        sum(by_iter[it][0] for it in by_iter)
    # And the photon-obs diff aggregation reads them back.
    summary = fit_wave_summary(rows)
    assert [e["entities_skipped"] for e in summary["per-user"]] == \
        [by_iter[it][1] for it in sorted(by_iter)]


def test_gated_delta_matches_full_rescore(rng, mesh):
    """Coordinate-level: the scatter-added score delta equals the full
    score diff, and a second gated sweep under barely-moved offsets
    skips most entities."""
    ds = _game(rng, n=600, users=25)
    coord = RandomEffectCoordinate(ds, "userId", "re_userId",
                                   losses.LOGISTIC, _opt(), mesh)
    state = coord.make_sweep_state()
    cfg = swp.SweepConfig(theta=1e-3, grad_tol=1e-4)
    offsets = jnp.asarray(ds.offsets)
    model, delta, stats = coord.train_model_gated(
        offsets, state=state, config=cfg, force_full=True)
    assert delta is not None
    np.testing.assert_allclose(np.asarray(delta),
                               np.asarray(coord.score(model)),
                               atol=1e-4, rtol=1e-4)
    trained = int(coord.bucketing.trained_entities.sum())
    assert stats["entities_fit"] == trained
    # Offsets barely move -> the gate keeps converged entities out.
    model2, delta2, stats2 = coord.train_model_gated(
        offsets + 1e-6, state=state, config=cfg, initial=model)
    assert stats2["entities_fit"] + stats2["entities_skipped"] == trained
    assert stats2["entities_skipped"] > 0
    # Skipped entities' rows carry EXACTLY zero delta.
    refit_rows = np.zeros(ds.num_rows, bool)
    d2 = np.asarray(delta2)
    W1 = np.asarray(model.means)
    W2 = np.asarray(model2.means)
    changed = np.flatnonzero(np.any(W1 != W2, axis=1))
    refit_rows = np.isin(ds.entity_ids["userId"], changed)
    assert np.all(d2[~refit_rows] == 0.0)


# ----------------------------------------- satellite: Gram reuse


def test_gram_solver_parity_and_cache(rng, mesh):
    """Squared-loss + L2: the cached normal-equation solve matches the
    iterative solver inside the coefficient band, reuses the SAME Gram
    blocks across sweeps, and silently falls back when ineligible."""
    ds = _game(rng, n=700, users=24)
    ds.response = rng.normal(size=ds.num_rows).astype(np.float32)
    opt = _opt(l2=0.5, max_iter=80)
    coord = RandomEffectCoordinate(ds, "userId", "re_userId",
                                   losses.SQUARED, opt, mesh)
    assert coord._gram_eligible()
    state = coord.make_sweep_state()
    gcfg = swp.SweepConfig(theta=1e-3, grad_tol=1e-4, gram=True)
    offsets = jnp.asarray(ds.offsets)
    gram_model, _, _ = coord.train_model_gated(
        offsets, state=state, config=gcfg, force_full=True)
    it_model = coord.train_model(offsets)
    np.testing.assert_allclose(np.asarray(gram_model.means),
                               np.asarray(it_model.means),
                               atol=5e-3, rtol=5e-3)
    # The cache holds one block set per staged tuple and a second sweep
    # reuses it bit-for-bit.
    assert coord._gram_cache
    cached = {w: np.asarray(G) for w, G in coord._gram_cache.items()}
    coord.train_model_gated(offsets + 1e-4, state=state, config=gcfg,
                            initial=gram_model)
    for w, G in coord._gram_cache.items():
        np.testing.assert_array_equal(np.asarray(G), cached[w])
    # Ineligible without the ridge term (singular normal matrix for
    # entities with fewer samples than features) and for non-squared
    # losses — the gated path then runs the iterative solver.
    assert not RandomEffectCoordinate(
        ds, "userId", "re_userId", losses.SQUARED, _opt(l2=0.0),
        mesh)._gram_eligible()
    assert not RandomEffectCoordinate(
        ds, "userId", "re_userId", losses.LOGISTIC, opt,
        mesh)._gram_eligible()


def test_gram_descent_band(rng, mesh):
    ds = _game(rng, n=500, users=20)
    ds.response = rng.normal(size=ds.num_rows).astype(np.float32)
    cfg = descent.CoordinateDescentConfig(["fixed", "per-user"],
                                          iterations=3)

    def coords():
        return {
            "fixed": FixedEffectCoordinate(ds, "global", losses.SQUARED,
                                           _opt(l2=0.5), mesh),
            "per-user": RandomEffectCoordinate(ds, "userId", "re_userId",
                                               losses.SQUARED,
                                               _opt(l2=0.5), mesh),
        }

    ref, _ = descent.run(TaskType.LINEAR_REGRESSION, coords(), cfg)
    got, _ = descent.run(TaskType.LINEAR_REGRESSION, coords(), cfg,
                         sweep=swp.SweepConfig(theta=1e-3, grad_tol=1e-4,
                                               gram=True))
    np.testing.assert_allclose(np.asarray(got.models["per-user"].means),
                               np.asarray(ref.models["per-user"].means),
                               atol=5e-3, rtol=5e-3)


# ------------------------- rung 3: checkpointed gated resume


class _GatedKill:
    """Proxy a coordinate; raise after ``allow`` gated train calls."""

    def __init__(self, inner, allow):
        self._inner = inner
        self._allow = allow
        self.calls = 0

    def train_model_gated(self, offsets, **kw):
        self.calls += 1
        if self.calls > self._allow:
            raise KeyboardInterrupt("simulated kill")
        return self._inner.train_model_gated(offsets, **kw)

    def __getattr__(self, name):
        return getattr(self._inner, name)


def test_gated_kill_and_resume_bit_identical(rng, mesh, tmp_path):
    """Rung 3 in-process: the dirty-set evidence rides the checkpoint
    (sweep/<cid>.npz), so a gated run killed mid-descent resumes taking
    the SAME skip decisions and lands bit-identical to an unkilled gated
    run."""
    ds = _game(rng, n=600, users=20)
    cfg = descent.CoordinateDescentConfig(["fixed", "per-user"],
                                          iterations=4)
    sweep = swp.SweepConfig(theta=1e-3, grad_tol=1e-4)

    ref, ref_dir = _run("dense", ds, mesh, cfg, tmp_path, "ref",
                        sweep=sweep)
    assert os.path.exists(os.path.join(ref_dir, "sweep", "per-user.npz"))

    manager = CheckpointManager(str(tmp_path / "killed"))
    killed = _variant_coordinates("dense", ds, mesh)
    killed["per-user"] = _GatedKill(killed["per-user"], allow=2)
    with pytest.raises(KeyboardInterrupt):
        descent.run(TaskType.LOGISTIC_REGRESSION, killed, cfg,
                    checkpoint_manager=manager, sweep=sweep)
    state = manager.load()
    assert state is not None and not state.complete
    assert "per-user" in (state.sweep_states or {})

    resumed, _ = descent.run(TaskType.LOGISTIC_REGRESSION,
                             _variant_coordinates("dense", ds, mesh), cfg,
                             checkpoint_manager=manager, sweep=sweep)
    np.testing.assert_array_equal(
        np.asarray(resumed.models["per-user"].means),
        np.asarray(ref.models["per-user"].means))
    np.testing.assert_array_equal(
        np.asarray(resumed.models["fixed"].coefficients.means),
        np.asarray(ref.models["fixed"].coefficients.means))


def test_unreadable_sweep_artifact_degrades_to_full_sweep(rng, mesh,
                                                          tmp_path):
    """A corrupt sweep/<cid>.npz must not fail the resume: the
    coordinate re-tracks from a forced full sweep (correct, just less
    incremental)."""
    ds = _game(rng, n=400, users=15)
    cfg = descent.CoordinateDescentConfig(["fixed", "per-user"],
                                          iterations=3)
    sweep = swp.SweepConfig(theta=1e-3, grad_tol=1e-4)
    manager = CheckpointManager(str(tmp_path / "ckpt"))
    killed = _variant_coordinates("dense", ds, mesh)
    killed["per-user"] = _GatedKill(killed["per-user"], allow=1)
    with pytest.raises(KeyboardInterrupt):
        descent.run(TaskType.LOGISTIC_REGRESSION, killed, cfg,
                    checkpoint_manager=manager, sweep=sweep)
    art = os.path.join(str(tmp_path / "ckpt"), "sweep", "per-user.npz")
    with open(art, "wb") as f:
        f.write(b"not an npz")
    model, _ = descent.run(TaskType.LOGISTIC_REGRESSION,
                           _variant_coordinates("dense", ds, mesh), cfg,
                           checkpoint_manager=manager, sweep=sweep)
    ref, _ = descent.run(TaskType.LOGISTIC_REGRESSION,
                         _variant_coordinates("dense", ds, mesh), cfg,
                         sweep=sweep)
    np.testing.assert_allclose(np.asarray(model.models["per-user"].means),
                               np.asarray(ref.models["per-user"].means),
                               atol=5e-3, rtol=5e-3)


# ------------------- rung 3 end-to-end: SIGKILL at sweep.gate_state


def _sweep_train_args(train_dir, out, cache):
    return [
        "--train", train_dir,
        "--coordinate", "name=fixed,type=fixed,shard=global",
        "--coordinate", "name=per-user,type=random,shard=re_userId,"
                        "re=userId",
        "--update-sequence", "fixed,per-user",
        "--iterations", "4",
        "--opt-config", "per-user:optimizer=LBFGS,reg=L2,reg_weight=1.0",
        "--sweep", "theta=0.05,grad_tol=0.05",
        "--output-dir", out,
        "--staging-cache-dir", cache,
        "--staging", "workers=2,shard_entities=8",
    ]


def test_sweep_sigkill_resume_bit_identical(tmp_path):
    """The chaos drill (docs/ROBUSTNESS.md ``sweep.gate_state``): the
    driver is SIGKILLed at the dirty-set checkpoint seam mid-run; the
    ``--resume`` rerun continues from the last committed generation and
    the final coefficients are bit-identical to a never-killed gated
    run."""
    from photon_ml_tpu.data.io import save_game_dataset

    rng = np.random.default_rng(0)
    ds = _game(rng, n=600, users=25)
    train_dir = str(tmp_path / "train")
    save_game_dataset(ds, train_dir)
    out = str(tmp_path / "out-killed")

    # The site fires once per checkpointed gated save; the 5th firing
    # lands mid-run (4 iterations x 2 coordinates = 8 saves).
    plan = faults.FaultPlan(specs=(
        faults.FaultSpec(site="sweep.gate_state", kind="kill",
                         occurrences=(4,)),))
    plan_path = str(tmp_path / "plan.json")
    with open(plan_path, "w") as f:
        f.write(plan.to_json())
    env = {k: v for k, v in os.environ.items()
           if k not in ("PALLAS_AXON_POOL_IPS",)}
    env.update({"JAX_PLATFORMS": "cpu",
                "PYTHONPATH": REPO + (os.pathsep + env["PYTHONPATH"]
                                      if env.get("PYTHONPATH") else "")})
    log_path = str(tmp_path / "phase1.log")
    with open(log_path, "w") as log:
        proc = subprocess.run(
            [sys.executable, "-m", "photon_ml_tpu.cli.game_train"]
            + _sweep_train_args(train_dir, out,
                                str(tmp_path / "cache"))
            + ["--fault-plan", plan_path],
            env=env, cwd=REPO, stdout=log, stderr=subprocess.STDOUT,
            timeout=600)
    assert proc.returncode == -9, (
        f"driver survived the SIGKILL plan (rc={proc.returncode}):\n"
        + open(log_path).read()[-3000:])
    # The kill landed before the generation's commit point: a committed
    # earlier generation with sweep state is on disk.
    ckpt = os.path.join(out, "checkpoints", "grid-0")
    assert os.path.exists(os.path.join(ckpt, "state.json"))
    assert os.path.exists(os.path.join(ckpt, "sweep", "per-user.npz"))

    log_path2 = str(tmp_path / "phase2.log")
    with open(log_path2, "w") as log:
        proc = subprocess.run(
            [sys.executable, "-m", "photon_ml_tpu.cli.game_train"]
            + _sweep_train_args(train_dir, out,
                                str(tmp_path / "cache"))
            + ["--resume"],
            env=env, cwd=REPO, stdout=log, stderr=subprocess.STDOUT,
            timeout=600)
    assert proc.returncode == 0, open(log_path2).read()[-3000:]

    out_clean = str(tmp_path / "out-clean")
    log_path3 = str(tmp_path / "phase3.log")
    with open(log_path3, "w") as log:
        proc = subprocess.run(
            [sys.executable, "-m", "photon_ml_tpu.cli.game_train"]
            + _sweep_train_args(train_dir, out_clean,
                                str(tmp_path / "cache2")),
            env=env, cwd=REPO, stdout=log, stderr=subprocess.STDOUT,
            timeout=600)
    assert proc.returncode == 0, open(log_path3).read()[-3000:]

    for rel in (os.path.join("best", "random-effect", "per-user",
                             "coefficients.npz"),
                os.path.join("best", "fixed-effect", "fixed",
                             "coefficients.npz")):
        a = np.load(os.path.join(out, rel))
        b = np.load(os.path.join(out_clean, rel))
        assert sorted(a.files) == sorted(b.files)
        for k in a.files:
            np.testing.assert_array_equal(a[k], b[k], err_msg=rel)
