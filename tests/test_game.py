"""GAME engine tests: bucketing, coordinates, coordinate descent.

Mirrors the reference's integration tests (SURVEY.md §4):
``RandomEffectDatasetIntegTest`` (active/passive split, grouping),
``CoordinateDescentIntegTest`` / ``GameEstimatorIntegTest`` (mixed-effect
fits improve over fixed-only; AUC thresholds on synthetic data).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.data import synthetic
from photon_ml_tpu.data.batch import LabeledBatch
from photon_ml_tpu.data.game_data import GameDataset, from_synthetic
from photon_ml_tpu.evaluation import evaluators as ev
from photon_ml_tpu.game import buckets as bkt
from photon_ml_tpu.game import descent
from photon_ml_tpu.game.coordinates import (FixedEffectCoordinate,
                                            RandomEffectCoordinate)
from photon_ml_tpu.ops import losses
from photon_ml_tpu.optim import OptimizerConfig
from photon_ml_tpu.optim import problem as local_problem
from photon_ml_tpu.optim.problem import GLMOptimizationConfiguration
from photon_ml_tpu.optim.regularization import (RegularizationContext,
                                                RegularizationType)
from photon_ml_tpu.parallel.mesh import make_mesh
from photon_ml_tpu.types import TaskType


@pytest.fixture(scope="module")
def mesh():
    return make_mesh()


def _game_config(l2=1.0, max_iter=60):
    return GLMOptimizationConfiguration(
        optimizer=OptimizerConfig(max_iterations=max_iter, tolerance=1e-7),
        regularization=RegularizationContext(RegularizationType.L2, l2))


# ------------------------------------------------------------------ bucketing


def test_bucketing_covers_all_kept_entities(rng):
    ids = rng.integers(0, 50, size=400).astype(np.int32)
    b = bkt.build_bucketing(ids, 50, lower_bound=1)
    seen = set()
    for bucket in b.buckets:
        live = bucket.entity_rows >= 0
        for row, cnt, ex in zip(bucket.entity_rows[live],
                                bucket.counts[live],
                                bucket.example_idx[live]):
            assert row not in seen
            seen.add(row)
            got = ex[ex >= 0]
            assert len(got) == cnt
            assert np.all(ids[got] == row)
    assert seen == set(np.unique(ids))
    assert b.trained_entities.sum() == len(seen)


def test_bucketing_lower_bound_drops_small_entities(rng):
    ids = np.concatenate([np.zeros(20, np.int32), np.ones(2, np.int32),
                          np.full(5, 2, np.int32)])
    b = bkt.build_bucketing(ids, 3, lower_bound=5)
    assert bool(b.trained_entities[0]) and bool(b.trained_entities[2])
    assert not bool(b.trained_entities[1])
    assert b.num_passive_only_entities == 1
    assert b.num_passive_examples == 2


def test_bucketing_upper_bound_caps_samples(rng):
    ids = np.zeros(100, np.int32)
    b = bkt.build_bucketing(ids, 1, upper_bound=16, rng=rng)
    bucket = b.buckets[0]
    assert bucket.counts[0] == 16
    assert bucket.capacity == 16
    assert b.num_passive_examples == 84


def test_bucketing_matches_per_entity_reference(rng):
    """The vectorized builder (one padded gather per capacity class; no
    per-entity Python loops — round-2 verdict: staging at 10⁶ entities)
    must reproduce the straightforward per-entity construction exactly,
    including deterministic capping and padding."""
    def reference(ids, num_entities, lower_bound, upper_bound):
        order = np.argsort(ids, kind="stable")
        uniq, starts, counts = np.unique(ids[order], return_index=True,
                                         return_counts=True)
        capped = (counts if upper_bound is None
                  else np.minimum(counts, upper_bound))
        keep = counts >= max(1, lower_bound)
        caps = np.maximum(8, np.array([bkt._next_pow2(int(c))
                                       for c in capped]))
        out = {}
        for cap in np.unique(caps[keep]):
            sel = np.where(keep & (caps == cap))[0]
            pad_e = ((len(sel) + 7) // 8) * 8
            ex = np.full((pad_e, int(cap)), -1, np.int64)
            rows = np.full((pad_e,), -1, np.int32)
            for i, u in enumerate(sel):
                c = int(capped[u])
                ex[i, :c] = order[starts[u]: starts[u] + c]
                rows[i] = uniq[u]
            out[int(cap)] = (rows, ex)
        return out

    for trial in range(5):
        n = int(rng.integers(50, 2000))
        E = int(rng.integers(3, 60))
        ids = rng.integers(0, E, size=n).astype(np.int32)
        lb = int(rng.integers(1, 4))
        ub = None if trial % 2 else int(rng.integers(4, 40))
        got = bkt.build_bucketing(ids, E, lower_bound=lb, upper_bound=ub)
        want = reference(ids, E, lb, ub)
        assert {b.capacity for b in got.buckets} == set(want)
        for b in got.buckets:
            rows, ex = want[b.capacity]
            np.testing.assert_array_equal(b.entity_rows, rows)
            np.testing.assert_array_equal(b.example_idx, ex)


def test_bucket_weights_zero_padding(rng):
    ids = rng.integers(0, 7, size=60).astype(np.int32)
    b = bkt.build_bucketing(ids, 7)
    w = rng.uniform(0.5, 1.5, size=60).astype(np.float32)
    for bucket in b.buckets:
        wb = bkt.bucket_weights(bucket, w)
        assert np.all(wb[bucket.example_idx < 0] == 0.0)
        live = bucket.example_idx >= 0
        np.testing.assert_allclose(wb[live], w[bucket.example_idx[live]])


# ---------------------------------------------------------------- coordinates


def _tiny_game(rng, n=1500, seed_skew=1.1):
    syn = synthetic.game_data(
        rng, n=n, d_global=8,
        re_specs={"userId": (40, 4), "itemId": (25, 3)},
        entity_skew=seed_skew)
    return from_synthetic(syn)


def test_random_effect_bucketed_equals_per_entity_loop(rng, mesh):
    """THE key equivalence: vmapped bucket solves == independent solves."""
    ds = _tiny_game(rng, n=800)
    cfg = _game_config()
    coord = RandomEffectCoordinate(ds, "userId", "re_userId", losses.LOGISTIC,
                                   cfg, mesh)
    offsets = jnp.asarray(ds.offsets)
    model = coord.train_model(offsets)
    W = np.asarray(model.means)

    ids = ds.entity_ids["userId"]
    X = ds.feature_shards["re_userId"]
    for e in np.unique(ids)[:10]:
        m = ids == e
        batch = LabeledBatch.build(X[m], ds.response[m], ds.weights[m],
                                   np.asarray(offsets)[m])
        coef, _ = local_problem.run(
            losses.LOGISTIC, batch, cfg,
            intercept_index=ds.intercept_index["re_userId"])
        np.testing.assert_allclose(W[e], coef.means, rtol=2e-2, atol=2e-2)


def test_random_effect_untrained_entities_score_zero(rng, mesh):
    ds = _tiny_game(rng, n=300)
    cfg = _game_config()
    coord = RandomEffectCoordinate(ds, "userId", "re_userId", losses.LOGISTIC,
                                   cfg, mesh, lower_bound=10)
    model = coord.train_model(jnp.asarray(ds.offsets))
    W = np.asarray(model.means)
    untrained = ~coord.bucketing.trained_entities
    assert untrained.any()  # skewed data: some users have <10 samples
    assert np.all(W[untrained] == 0.0)
    # Scores for examples of untrained entities are exactly 0.
    s = np.asarray(coord.score(model))
    mask = untrained[ds.entity_ids["userId"]]
    assert np.all(s[mask] == 0.0)


def test_fixed_effect_coordinate_trains_and_scores(rng, mesh):
    ds = _tiny_game(rng, n=1000)
    coord = FixedEffectCoordinate(ds, "global", losses.LOGISTIC,
                                  _game_config(), mesh)
    model = coord.train_model(jnp.asarray(ds.offsets))
    s = np.asarray(coord.score(model))
    assert s.shape == (1000,)
    a = float(ev.auc(jnp.asarray(s), jnp.asarray(ds.response)))
    assert a > 0.6  # global effects alone predict something


# ----------------------------------------------------------- coordinate descent


def _build_coordinates(ds, mesh, l2_fixed=1.0, l2_re=1.0):
    return {
        "fixed": FixedEffectCoordinate(ds, "global", losses.LOGISTIC,
                                       _game_config(l2_fixed), mesh),
        "per-user": RandomEffectCoordinate(ds, "userId", "re_userId",
                                           losses.LOGISTIC,
                                           _game_config(l2_re), mesh),
        "per-item": RandomEffectCoordinate(ds, "itemId", "re_itemId",
                                           losses.LOGISTIC,
                                           _game_config(l2_re), mesh),
    }


def test_coordinate_descent_improves_auc(rng, mesh):
    ds = _tiny_game(rng, n=2000)
    coords = _build_coordinates(ds, mesh)
    y = jnp.asarray(ds.response)

    # Fixed-effect-only baseline:
    fixed_only, _ = descent.run(
        TaskType.LOGISTIC_REGRESSION, coords,
        descent.CoordinateDescentConfig(["fixed"], iterations=1))
    auc_fixed = float(ev.auc(fixed_only.score(ds), y))

    full, hist = descent.run(
        TaskType.LOGISTIC_REGRESSION, coords,
        descent.CoordinateDescentConfig(["fixed", "per-user", "per-item"],
                                        iterations=2))
    auc_full = float(ev.auc(full.score(ds), y))
    # Random effects must add real lift on per-entity data (GLMix claim).
    assert auc_full > auc_fixed + 0.03, (auc_fixed, auc_full)
    assert len(hist.records) == 6


def test_coordinate_descent_iterations_converge(rng, mesh):
    ds = _tiny_game(rng, n=1200)
    coords = _build_coordinates(ds, mesh)
    vals = []
    model, hist = descent.run(
        TaskType.LOGISTIC_REGRESSION, coords,
        descent.CoordinateDescentConfig(["fixed", "per-user"], iterations=3),
        validation_fn=lambda m: {
            "auc": float(ev.auc(m.score(ds), jnp.asarray(ds.response)))})
    aucs = [r["validation"]["auc"] for r in hist.records]
    # Later sweeps shouldn't degrade the training AUC materially.
    assert aucs[-1] >= aucs[0] - 1e-3


def test_warm_start_and_locked_coordinates(rng, mesh):
    ds = _tiny_game(rng, n=900)
    coords = _build_coordinates(ds, mesh)
    cfg = descent.CoordinateDescentConfig(["fixed", "per-user"], iterations=1)
    model1, _ = descent.run(TaskType.LOGISTIC_REGRESSION, coords, cfg)

    # Warm start: reuse model1's coordinates as initial models.
    model2, _ = descent.run(TaskType.LOGISTIC_REGRESSION, coords, cfg,
                            initial_models=dict(model1.models))
    y = jnp.asarray(ds.response)
    assert float(ev.auc(model2.score(ds), y)) >= float(
        ev.auc(model1.score(ds), y)) - 5e-3

    # Locked: the fixed coordinate must come back bit-identical.
    model3, _ = descent.run(
        TaskType.LOGISTIC_REGRESSION, coords, cfg,
        initial_models=dict(model1.models), locked_coordinates={"fixed"})
    np.testing.assert_array_equal(
        np.asarray(model3.models["fixed"].coefficients.means),
        np.asarray(model1.models["fixed"].coefficients.means))

    # Locked without an initial model is an error.
    with pytest.raises(ValueError):
        descent.run(TaskType.LOGISTIC_REGRESSION, coords, cfg,
                    locked_coordinates={"fixed"})


def test_descent_rejects_unknown_coordinate(rng, mesh):
    ds = _tiny_game(rng, n=300)
    coords = _build_coordinates(ds, mesh)
    with pytest.raises(ValueError):
        descent.run(TaskType.LOGISTIC_REGRESSION, coords,
                    descent.CoordinateDescentConfig(["nope"], iterations=1))


def test_fixed_effect_with_normalization_scores_raw_space(rng, mesh):
    """Regression: GAME models must hold ORIGINAL-space coefficients so that
    GameModel.score / transformer / saved models agree with the training-time
    (transformed-space) margins."""
    from photon_ml_tpu.normalization import NormalizationType, build_normalization

    ds = _tiny_game(rng, n=800)
    X = ds.feature_shards["global"]
    norm = build_normalization(
        NormalizationType.STANDARDIZATION, means=X.mean(0), variances=X.var(0),
        intercept_index=ds.intercept_index["global"])
    coord = FixedEffectCoordinate(ds, "global", losses.LOGISTIC,
                                  _game_config(), mesh, norm=norm)
    model = coord.train_model(jnp.asarray(ds.offsets))
    s_coord = np.asarray(coord.score(model))
    s_model = np.asarray(model.score(ds))  # plain X @ w path
    np.testing.assert_allclose(s_coord, s_model, rtol=1e-4, atol=1e-4)
    # And training with normalization on ill-scaled features actually works:
    a = float(ev.auc(jnp.asarray(s_model), jnp.asarray(ds.response)))
    assert a > 0.6


def test_descent_sync_updates_knob_is_behavior_neutral(rng, mesh):
    """config.sync_updates (auto/forced-on/forced-off) changes only the
    dispatch-stream barrier, never the trained model."""
    ds = _tiny_game(rng, n=600)
    coords = _build_coordinates(ds, mesh)
    outs = []
    for sync in (None, True, False):
        cfg = descent.CoordinateDescentConfig(["fixed", "per-user"],
                                              iterations=2,
                                              sync_updates=sync)
        model, _ = descent.run(TaskType.LOGISTIC_REGRESSION, coords, cfg)
        outs.append(np.asarray(model.models["fixed"].coefficients.means))
    assert np.allclose(outs[0], outs[1])
    assert np.allclose(outs[0], outs[2])
