"""Utils coverage: DateRange + date-partitioned discovery (reference
util/DateRange + IOUtils) and the training event system (reference event/).
"""

import datetime

import numpy as np
import pytest

from photon_ml_tpu.utils import events as ev
from photon_ml_tpu.utils.ranges import (DateRange, DoubleRange,
                                        input_paths_within_date_range)


class TestDateRange:
    def test_parse_reference_form(self):
        r = DateRange.parse("20160101-20160131")
        assert r.start == datetime.date(2016, 1, 1)
        assert r.end == datetime.date(2016, 1, 31)
        assert len(list(r.days())) == 31

    def test_parse_iso_form(self):
        r = DateRange.parse("2016-01-01:2016-01-03")
        assert [d.day for d in r.days()] == [1, 2, 3]

    def test_invalid(self):
        with pytest.raises(ValueError):
            DateRange.parse("20160131-20160101")
        with pytest.raises(ValueError):
            DateRange.parse("garbage")

    def test_contains(self):
        r = DateRange.parse("20160110-20160120")
        assert r.contains(datetime.date(2016, 1, 15))
        assert not r.contains(datetime.date(2016, 1, 21))

    def test_input_discovery(self, tmp_path):
        for day in (1, 2, 4):
            (tmp_path / "2016" / "01" / f"{day:02d}").mkdir(parents=True)
        r = DateRange.parse("20160101-20160105")
        found = input_paths_within_date_range(str(tmp_path), r)
        assert [p[-10:] for p in found] == ["2016/01/01", "2016/01/02",
                                           "2016/01/04"]
        with pytest.raises(FileNotFoundError):
            input_paths_within_date_range(str(tmp_path), r,
                                          errors_on_missing=True)


class TestEvents:
    def test_emit_and_listener_lifecycle(self):
        emitter = ev.EventEmitter()
        seen = []
        emitter.register(seen.append)
        emitter.emit(ev.TrainingStart(task="LOGISTIC_REGRESSION",
                                      update_sequence=("fixed",),
                                      iterations=2))
        emitter.emit(ev.CoordinateUpdate(iteration=0, coordinate="fixed",
                                         train_seconds=0.1))
        assert len(seen) == 2
        emitter.unregister(seen.append)
        emitter.emit(ev.TrainingFinish(task="LOGISTIC_REGRESSION",
                                       total_updates=2))
        assert len(seen) == 2

    def test_raising_listener_is_detached(self):
        emitter = ev.EventEmitter()
        calls = []

        def bad(event):
            calls.append(event)
            raise RuntimeError("boom")

        emitter.register(bad)
        emitter.emit(ev.TrainingFinish(task="t", total_updates=1))
        emitter.emit(ev.TrainingFinish(task="t", total_updates=2))
        assert len(calls) == 1  # detached after the first failure

    def test_descent_emits_lifecycle(self, rng):
        from photon_ml_tpu.api.configs import (CoordinateConfiguration,
                                               FixedEffectDataConfiguration)
        from photon_ml_tpu.api.estimator import GameEstimator
        from photon_ml_tpu.data import synthetic
        from photon_ml_tpu.data.game_data import from_synthetic
        from photon_ml_tpu.optim.problem import GLMOptimizationConfiguration
        from photon_ml_tpu.parallel.mesh import make_mesh
        from photon_ml_tpu.types import TaskType

        seen = []
        ev.default_emitter.register(seen.append)
        try:
            ds = from_synthetic(synthetic.game_data(rng, n=256, d_global=6,
                                                    re_specs={}))
            cc = {"fixed": CoordinateConfiguration(
                data=FixedEffectDataConfiguration("global"),
                optimization=GLMOptimizationConfiguration())}
            GameEstimator(TaskType.LOGISTIC_REGRESSION, cc, ["fixed"],
                          make_mesh(), descent_iterations=2).fit(ds)
        finally:
            ev.default_emitter.unregister(seen.append)
        kinds = [type(e).__name__ for e in seen]
        assert kinds == ["TrainingStart", "CoordinateUpdate",
                         "CoordinateUpdate", "TrainingFinish"]
        assert seen[1].coordinate == "fixed"


class TestNativeLibsvm:
    """The C++ parser must agree exactly with the Python fallback."""

    def _fixture(self, tmp_path, rng, n=200, d=30):
        import os
        X = (rng.normal(size=(n, d)) *
             (rng.random((n, d)) < 0.3)).astype(np.float32)
        y = rng.choice([-1.0, 1.0], size=n)
        path = str(tmp_path / "data.txt")
        from photon_ml_tpu.data.libsvm import write_libsvm
        write_libsvm(path, X, y)
        with open(path, "a") as f:
            f.write("\n# trailing comment line\n")
        return path, X, y

    def test_native_matches_python(self, tmp_path, rng):
        from photon_ml_tpu.data import libsvm as lsv

        path, X, y = self._fixture(tmp_path, rng)
        if lsv._load_native() is None:
            pytest.skip("no native toolchain")
        native = lsv.read_libsvm(path, dense=True)

        # Force the Python fallback and compare.
        saved = lsv._native_lib, lsv._native_failed
        lsv._native_lib, lsv._native_failed = None, True
        try:
            fallback = lsv.read_libsvm(path, dense=True)
        finally:
            lsv._native_lib, lsv._native_failed = saved

        np.testing.assert_array_equal(native.labels, fallback.labels)
        np.testing.assert_allclose(native.dense, fallback.dense,
                                   rtol=1e-6, atol=0)
        assert native.num_features == fallback.num_features
        # And against the ground truth that wrote the file.
        np.testing.assert_allclose(
            native.dense, X[:, :native.num_features], rtol=1e-4, atol=1e-6)

    def test_native_error_reporting(self, tmp_path):
        from photon_ml_tpu.data import libsvm as lsv

        path = str(tmp_path / "bad.txt")
        with open(path, "w") as f:
            f.write("1 3:0.5\n1 nonsense\n")
        if lsv._load_native() is None:
            pytest.skip("no native toolchain")
        with pytest.raises(ValueError, match="line 2"):
            lsv.read_libsvm(path)

    def test_native_strictness_parity(self, tmp_path):
        """Malformed inputs must fail identically in both parsers: dangling
        'idx:', whitespace after ':', and mid-line '#' are all errors."""
        from photon_ml_tpu.data import libsvm as lsv

        if lsv._load_native() is None:
            pytest.skip("no native toolchain")
        cases = ["1 3:\n0 5:2\n", "1 3: 0.5\n", "1 2:0.5 # note\n"]
        for i, content in enumerate(cases):
            path = str(tmp_path / f"m{i}.txt")
            with open(path, "w") as f:
                f.write(content)
            with pytest.raises(ValueError):
                lsv.read_libsvm(path)  # native
            saved = lsv._native_lib, lsv._native_failed
            lsv._native_lib, lsv._native_failed = None, True
            try:
                with pytest.raises(ValueError):
                    lsv.read_libsvm(path)  # fallback
            finally:
                lsv._native_lib, lsv._native_failed = saved

    def test_missing_file_raises_filenotfound(self, tmp_path):
        from photon_ml_tpu.data import libsvm as lsv

        with pytest.raises(FileNotFoundError):
            lsv.read_libsvm(str(tmp_path / "nope.txt"))

    def test_index_overflow_and_hex_rejected_both_paths(self, tmp_path):
        """int32-overflowing indices and hex float values must error in
        BOTH parsers (native previously wrapped / accepted them)."""
        from photon_ml_tpu.data import libsvm as lsv

        if lsv._load_native() is None:
            pytest.skip("no native toolchain")
        for content in ("1 4294967297:1.0\n", "1 2:0x1A\n"):
            path = str(tmp_path / "x.txt")
            with open(path, "w") as f:
                f.write(content)
            with pytest.raises(ValueError):
                lsv.read_libsvm(path, zero_based=True)  # native
            saved = lsv._native_lib, lsv._native_failed
            lsv._native_lib, lsv._native_failed = None, True
            try:
                with pytest.raises(ValueError):
                    lsv.read_libsvm(path, zero_based=True)  # fallback
            finally:
                lsv._native_lib, lsv._native_failed = saved

    def test_plus_one_labels(self, tmp_path):
        """LIBSVM's '+1' label form parses in both paths."""
        from photon_ml_tpu.data import libsvm as lsv

        path = str(tmp_path / "plus.txt")
        with open(path, "w") as f:
            f.write("+1 1:0.5\n-1 2:1.5\n")
        d = lsv.read_libsvm(path, dense=True)
        np.testing.assert_array_equal(d.labels, [1.0, 0.0])  # ±1 -> {0,1}

    def test_numeric_edge_parity(self, tmp_path):
        """'+-1' labels error in both paths; out-of-range magnitudes keep
        strtod/Python semantics (overflow -> inf, underflow -> 0) in both."""
        from photon_ml_tpu.data import libsvm as lsv

        if lsv._load_native() is None:
            pytest.skip("no native toolchain")

        def both(content, check):
            path = str(tmp_path / "n.txt")
            with open(path, "w") as f:
                f.write(content)
            check(lambda: lsv.read_libsvm(path, dense=True,
                                          binary_labels_to_01=False))
            saved = lsv._native_lib, lsv._native_failed
            lsv._native_lib, lsv._native_failed = None, True
            try:
                check(lambda: lsv.read_libsvm(path, dense=True,
                                              binary_labels_to_01=False))
            finally:
                lsv._native_lib, lsv._native_failed = saved

        def expect_error(f):
            with pytest.raises(ValueError):
                f()

        both("+-1 1:0.5\n", expect_error)

        def expect_inf_and_zero(f):
            d = f()
            assert np.isinf(d.dense[0, 0])
            assert d.dense[1, 0] == 0.0

        both("1 1:9e999\n0 1:1e-999\n", expect_inf_and_zero)


class TestPerfDocsRendered:
    """README/PARITY perf numbers must be rendered from the committed
    bench capture, never hand-edited (round-2 verdict: doc drift)."""

    def test_docs_in_sync_with_bench_json(self):
        import importlib.util
        import os

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        script = os.path.join(root, "dev-scripts", "render_perf_docs.py")
        spec = importlib.util.spec_from_file_location("render_perf", script)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert os.path.exists(mod.BENCH_JSON), (
            "docs/BENCH_CURRENT.json missing — capture one with "
            "`python bench.py > docs/BENCH_CURRENT.json`")
        assert mod.main(["--check"]) == 0, (
            "perf docs drifted from docs/BENCH_CURRENT.json — run "
            "dev-scripts/render_perf_docs.py")
