"""Normalization context algebra (reference: NormalizationContextTest)."""

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.normalization import (NormalizationContext,
                                         NormalizationType,
                                         build_normalization)


def _ctx(rng, d=6, kind=NormalizationType.STANDARDIZATION):
    mean = rng.normal(size=d)
    var = rng.uniform(0.5, 4.0, size=d)
    mm = rng.uniform(0.1, 9.0, size=d)
    return build_normalization(kind, means=mean, variances=var,
                               max_magnitudes=mm, intercept_index=d - 1)


def test_none_is_identity():
    ctx = build_normalization(NormalizationType.NONE)
    assert ctx.is_identity
    w = jnp.asarray([1.0, 2.0])
    w_eff, shift = ctx.effective_coefficients(w)
    np.testing.assert_allclose(w_eff, w)
    np.testing.assert_allclose(shift, 0.0)


def test_intercept_untouched(rng):
    ctx = _ctx(rng)
    assert float(ctx.factors[-1]) == 1.0
    assert float(ctx.shifts[-1]) == 0.0


def test_scale_with_std(rng):
    d = 5
    var = rng.uniform(0.5, 4.0, size=d)
    ctx = build_normalization(NormalizationType.SCALE_WITH_STANDARD_DEVIATION,
                              variances=var)
    np.testing.assert_allclose(ctx.factors, 1.0 / np.sqrt(var), rtol=1e-6)
    assert ctx.shifts is None


def test_zero_variance_gets_factor_one():
    ctx = build_normalization(NormalizationType.SCALE_WITH_STANDARD_DEVIATION,
                              variances=np.asarray([0.0, 4.0]))
    np.testing.assert_allclose(ctx.factors, [1.0, 0.5])


def test_model_space_round_trip(rng):
    ctx = _ctx(rng)
    w = jnp.asarray(rng.normal(size=6).astype(np.float32))
    back = ctx.model_to_transformed_space(ctx.model_to_original_space(w))
    np.testing.assert_allclose(back, w, rtol=1e-5, atol=1e-6)


def test_original_space_model_scores_raw_data(rng):
    """w' on x' must equal model_to_original_space(w') on raw x."""
    d = 6
    ctx = _ctx(rng, d=d)
    w_t = rng.normal(size=d).astype(np.float32)
    X = rng.normal(size=(20, d)).astype(np.float32)
    X[:, -1] = 1.0  # intercept column
    f = np.asarray(ctx.factors)
    s = np.asarray(ctx.shifts)
    scores_transformed = ((X - s) * f) @ w_t
    w_orig = np.asarray(ctx.model_to_original_space(jnp.asarray(w_t)))
    scores_raw = X @ w_orig
    np.testing.assert_allclose(scores_raw, scores_transformed, rtol=1e-4,
                               atol=1e-4)


def test_standardization_requires_intercept(rng):
    with pytest.raises(ValueError):
        build_normalization(NormalizationType.STANDARDIZATION,
                            means=np.ones(3), variances=np.ones(3),
                            intercept_index=None)
