"""Parallel, pipelined random-effect staging (game/staging.py).

The contract under test is EXACTNESS: the worker count, pool mode,
shard size, and pipeline handoff are execution choices — the staged
bytes, the column maps, the staging-cache contents, and the final GAME
coefficients must be identical to the serial whole-bucket build, bit for
bit. Plus the pipeline mechanics themselves: shard-granular cache
partial credit, lifecycle events, and the config surface.
"""

import os

import numpy as np
import pytest

from photon_ml_tpu.data.game_data import GameDataset, SparseShard
from photon_ml_tpu.game import buckets as bkt
from photon_ml_tpu.game import projector as prj
from photon_ml_tpu.game import staging as stg
from photon_ml_tpu.game import staging_cache
from photon_ml_tpu.ops import losses
from photon_ml_tpu.optim import OptimizerConfig
from photon_ml_tpu.optim.problem import GLMOptimizationConfiguration
from photon_ml_tpu.optim.regularization import (RegularizationContext,
                                                RegularizationType)
from photon_ml_tpu.parallel.mesh import make_mesh
from photon_ml_tpu.utils import events as ev


@pytest.fixture(scope="module")
def mesh():
    return make_mesh()


def _opt(max_iter=40):
    return GLMOptimizationConfiguration(
        optimizer=OptimizerConfig(max_iterations=max_iter, tolerance=1e-8),
        regularization=RegularizationContext(RegularizationType.L2, 1.0))


def _skewed_dataset(n_entities=40, d=48, nnz=4, seed=0, intercept=True,
                    dense=False):
    """Entities with 2–40 examples → several capacity buckets, each wide
    enough to split into multiple 8-lane staging shards."""
    rng = np.random.default_rng(seed)
    counts = rng.integers(2, 41, n_entities)
    ids = np.repeat(np.arange(n_entities, dtype=np.int32), counts)
    rng.shuffle(ids)
    n = ids.shape[0]
    d_draw = d - 1 if intercept else d
    idx = np.sort(rng.integers(0, d_draw, (n, nnz)).astype(np.int32),
                  axis=1)
    dup = np.zeros_like(idx, bool)
    dup[:, 1:] = idx[:, 1:] == idx[:, :-1]
    vals = rng.normal(size=(n, nnz)).astype(np.float32)
    idx[dup] = d
    vals[dup] = 0.0
    if intercept:
        idx = np.concatenate([idx, np.full((n, 1), d - 1, np.int32)],
                             axis=1)
        vals = np.concatenate([vals, np.ones((n, 1), np.float32)], axis=1)
    shard = SparseShard(idx, vals, d)
    if dense:
        X = np.zeros((n, d), np.float32)
        valid = idx < d
        np.add.at(X, (np.broadcast_to(np.arange(n)[:, None],
                                      idx.shape)[valid], idx[valid]),
                  vals[valid])
        shard = X
    y = rng.integers(0, 2, n).astype(np.float32)
    w = rng.uniform(0.5, 1.5, n).astype(np.float32)
    ds = GameDataset(
        response=y, offsets=np.zeros(n, np.float32), weights=w,
        feature_shards={"re": shard}, entity_ids={"userId": ids},
        num_entities={"userId": n_entities},
        intercept_index={"re": d - 1} if intercept else {})
    return ds


def _serial_host_tuples(bucketing, X, ii, response, weights, ratio=None,
                        f_full=None, s_full=None):
    """The pre-pipeline whole-bucket staging, verbatim — the reference
    the sharded pipeline must reproduce bit for bit."""
    coo = prj.shard_coo(X)
    trips = prj.all_bucket_triplets(bucketing.buckets, X, coo)
    out = []
    for b, trip in zip(bucketing.buckets, trips):
        proj = prj.build_bucket_projection(
            b, X, ii, labels=response if ratio is not None else None,
            features_to_samples_ratio=ratio, triplets=trip)
        Xb = prj.gather_projected_features(b, proj, X, triplets=trip)
        (yb,) = bkt.gather_bucket_arrays(b, response)
        wb = bkt.bucket_weights(b, weights)
        tup = [Xb, yb, wb, b.example_idx.astype(np.int32),
               b.entity_rows, proj.cols]
        if f_full is not None or s_full is not None:
            f_p, s_p = prj.project_norm_arrays(proj, f_full, s_full)
            if f_full is not None:
                tup.append(f_p)
            if s_full is not None:
                tup.append(s_p)
        out.append(tuple(tup))
    return out


def _drain(stager):
    got = list(stager.shards())
    stager.join()
    return got


def _merge_by_bucket(plan, shards, num_buckets):
    """Concatenate shard tuples back into whole-bucket tuples."""
    merged = []
    for bi in range(num_buckets):
        parts = [t for (b, lo, hi), t in zip(plan, shards) if b == bi]
        merged.append(tuple(
            np.concatenate([np.asarray(p[j]) for p in parts])
            for j in range(len(parts[0]))))
    return merged


def _assert_bytes_equal(got, want):
    assert len(got) == len(want)
    for tg, tw in zip(got, want):
        assert len(tg) == len(tw)
        for ag, aw in zip(tg, tw):
            ag, aw = np.asarray(ag), np.asarray(aw)
            assert ag.dtype == aw.dtype and ag.shape == aw.shape
            assert ag.tobytes() == aw.tobytes()


def _stager(ds, config, cache_dir=None, cache_key=None, ratio=None,
            f_full=None, s_full=None, emitter=None, subspace=False):
    ii = ds.intercept_index.get("re")
    bucketing = bkt.build_bucketing(np.asarray(ds.entity_ids["userId"]),
                                    ds.num_entities["userId"])
    return bucketing, stg.ProjectionStager(
        bucketing=bucketing, X=ds.feature_shards["re"],
        response=np.asarray(ds.response),
        weights=np.asarray(ds.weights), intercept_index=ii,
        features_to_samples_ratio=ratio, factors=f_full, shifts=s_full,
        config=config, cache_dir=cache_dir, cache_key=cache_key,
        expect_subspace=subspace, label="userId:re",
        emitter=emitter or ev.EventEmitter())


# ------------------------------------------------------------------ parity


@pytest.mark.parametrize("workers", [1, 2, 8])
def test_staged_shards_bit_identical_to_serial(workers):
    """THE acceptance property: staged buckets and projections from the
    sharded W-worker pipeline are byte-identical to the whole-bucket
    serial build."""
    ds = _skewed_dataset()
    cfg = stg.StagingConfig(workers=workers, shard_entities=8)
    bucketing, stager = _stager(ds, cfg)
    shards = _drain(stager)
    merged = _merge_by_bucket(stager.plan, shards,
                              len(bucketing.buckets))
    want = _serial_host_tuples(
        bucketing, ds.feature_shards["re"],
        ds.intercept_index.get("re"),
        np.asarray(ds.response), np.asarray(ds.weights))
    _assert_bytes_equal(merged, want)


def test_process_mode_bit_identical_to_thread():
    """The process-pool fallback ships work by pickle yet produces the
    same bytes (content never depends on the pool)."""
    ds = _skewed_dataset(n_entities=16, seed=3)
    _, t_stager = _stager(ds, stg.StagingConfig(workers=2,
                                                shard_entities=8))
    t_shards = _drain(t_stager)
    _, p_stager = _stager(ds, stg.StagingConfig(workers=2, mode="process",
                                                shard_entities=8))
    p_shards = _drain(p_stager)
    _assert_bytes_equal(t_shards, p_shards)


def test_dense_shard_with_normalization_parity():
    """Dense projected staging with factor+shift normalization: the
    per-shard norm projections and dense gathers merge exactly."""
    ds = _skewed_dataset(dense=True, seed=5)
    d = ds.feature_shards["re"].shape[1]
    rng = np.random.default_rng(0)
    f_full = rng.uniform(0.5, 2.0, d).astype(np.float32)
    s_full = rng.normal(size=d).astype(np.float32)
    cfg = stg.StagingConfig(workers=4, shard_entities=8)
    bucketing, stager = _stager(ds, cfg, f_full=f_full, s_full=s_full)
    merged = _merge_by_bucket(stager.plan, _drain(stager),
                              len(bucketing.buckets))
    want = _serial_host_tuples(
        bucketing, ds.feature_shards["re"],
        ds.intercept_index.get("re"), np.asarray(ds.response),
        np.asarray(ds.weights), f_full=f_full, s_full=s_full)
    _assert_bytes_equal(merged, want)


def test_pearson_ratio_path_bit_identical(rng):
    """The Pearson feature cap (stable-sorted moment sums) shards
    exactly too — the one staging stage where fp accumulation order
    could have diverged."""
    ds = _skewed_dataset(seed=7)
    ratio = 0.6
    cfg = stg.StagingConfig(workers=4, shard_entities=8)
    bucketing, stager = _stager(ds, cfg, ratio=ratio)
    merged = _merge_by_bucket(stager.plan, _drain(stager),
                              len(bucketing.buckets))
    want = _serial_host_tuples(
        bucketing, ds.feature_shards["re"],
        ds.intercept_index.get("re"), np.asarray(ds.response),
        np.asarray(ds.weights), ratio=ratio)
    _assert_bytes_equal(merged, want)


@pytest.mark.parametrize("workers", [1, 8])
def test_project_buckets_matches_per_bucket_build(workers):
    """The projection-only helper (the bench's measurement target) ==
    build_bucket_projection per bucket."""
    ds = _skewed_dataset(seed=2)
    X = ds.feature_shards["re"]
    ids = np.asarray(ds.entity_ids["userId"])
    b = bkt.build_bucketing(ids, ds.num_entities["userId"])
    ii = ds.intercept_index.get("re")
    got = stg.project_buckets(
        b, X, intercept_index=ii,
        config=stg.StagingConfig(workers=workers, shard_entities=8))
    for bucket, proj in zip(b.buckets, got):
        want = prj.build_bucket_projection(bucket, X, ii)
        assert proj.d_active == want.d_active
        np.testing.assert_array_equal(proj.cols, want.cols)


# ------------------------------------------------------- cache round trips


def test_cache_roundtrip_bit_identical(tmp_path):
    ds = _skewed_dataset(seed=11)
    cfg = stg.StagingConfig(workers=4, shard_entities=8)
    cache = str(tmp_path / "stage")
    _, cold = _stager(ds, cfg, cache_dir=cache, cache_key="k1")
    cold_shards = _drain(cold)
    emitter = ev.EventEmitter()
    seen = []
    emitter.register(seen.append)
    _, warm = _stager(ds, cfg, cache_dir=cache, cache_key="k1",
                      emitter=emitter)
    warm_shards = _drain(warm)
    assert all(e.source == "cache" for e in seen
               if isinstance(e, ev.StagingShard))
    _assert_bytes_equal(cold_shards, warm_shards)


def test_cache_partial_invalidation_restages_only_missing(tmp_path):
    """Shard-granular credit: corrupt ONE shard and only that shard
    restages — and the merged output is still byte-identical."""
    ds = _skewed_dataset(seed=13)
    cfg = stg.StagingConfig(workers=2, shard_entities=8)
    cache = str(tmp_path / "stage")
    _, cold = _stager(ds, cfg, cache_dir=cache, cache_key="k1")
    cold_shards = _drain(cold)
    assert len(cold_shards) > 2
    # Truncate one shard's arrays (the .ok marker survives — load must
    # still reject it on the unreadable array files).
    victim = 1
    entry = os.path.join(cache, "k1")
    for f in os.listdir(entry):
        if f.startswith(f"s{victim}_"):
            open(os.path.join(entry, f), "wb").close()
    assert staging_cache.load_shard(cache, "k1", victim) is None
    emitter = ev.EventEmitter()
    seen = []
    emitter.register(seen.append)
    _, again = _stager(ds, cfg, cache_dir=cache, cache_key="k1",
                       emitter=emitter)
    again_shards = _drain(again)
    staged = [e for e in seen if isinstance(e, ev.StagingShard)
              and e.source == "staged"]
    assert [e.index for e in staged] == [victim]
    _assert_bytes_equal(again_shards, cold_shards)
    # ...and the restage healed the entry on disk.
    assert staging_cache.load_shard(cache, "k1", victim) is not None


def test_cache_write_as_produced_without_full_drain(tmp_path):
    """Shards persist as they are produced — a consumer that stops early
    (killed run) still leaves the consumed prefix on disk."""
    ds = _skewed_dataset(seed=17)
    cfg = stg.StagingConfig(workers=1, shard_entities=8,
                            pipeline_depth=1)
    cache = str(tmp_path / "stage")
    _, stager = _stager(ds, cfg, cache_dir=cache, cache_key="k1")
    it = stager.shards()
    next(it)  # consume ONE shard, abandon the rest
    it.close()
    # The write trails the handoff (consumer latency comes first) by one
    # np.save; poll briefly rather than flake.
    import time

    deadline = time.monotonic() + 10.0
    while (staging_cache.load_shard(cache, "k1", 0) is None
           and time.monotonic() < deadline):
        time.sleep(0.02)
    assert staging_cache.load_shard(cache, "k1", 0) is not None
    # The abandoned entry is partial: no completion record.
    assert staging_cache.load(cache, "k1") is None


# ------------------------------------------------------ pipelined descent


def test_pipelined_descent_matches_barrier_exactly(mesh):
    """Final GAME coefficients from the lazily-consumed pipeline ==
    the fully-staged barrier path, bit for bit (same device programs in
    the same order — the handoff changes WHEN staging happens, never
    what is staged)."""
    from photon_ml_tpu.game import descent
    from photon_ml_tpu.game.coordinates import RandomEffectCoordinate
    from photon_ml_tpu.types import TaskType

    ds = _skewed_dataset(seed=19)
    cfg = _opt()
    results = {}
    for name, barrier in (("pipelined", False), ("barrier", True)):
        coord = RandomEffectCoordinate(
            ds, "userId", "re", losses.LOGISTIC, cfg, mesh,
            staging=stg.StagingConfig(workers=4, shard_entities=8))
        if barrier:
            coord.wait_staged()
        model, _ = descent.run(
            TaskType.LOGISTIC_REGRESSION, {"per-user": coord},
            descent.CoordinateDescentConfig(["per-user"], iterations=2))
        m = model.models["per-user"]
        results[name] = (np.asarray(m.means),
                         np.asarray(coord.score(m)))
    np.testing.assert_array_equal(results["pipelined"][0],
                                  results["barrier"][0])
    np.testing.assert_array_equal(results["pipelined"][1],
                                  results["barrier"][1])


def test_coordinate_staging_workers_invariant(mesh):
    """Through the coordinate front door: trained models identical for
    1 vs 8 staging workers (staged device arrays are the same bytes)."""
    from photon_ml_tpu.game.coordinates import RandomEffectCoordinate

    ds = _skewed_dataset(seed=23)
    off = np.zeros(ds.num_rows, np.float32)
    means = {}
    for workers in (1, 8):
        c = RandomEffectCoordinate(
            ds, "userId", "re", losses.LOGISTIC, _opt(), mesh,
            staging=stg.StagingConfig(workers=workers, shard_entities=8))
        means[workers] = np.asarray(c.train_model(off).means)
    np.testing.assert_array_equal(means[1], means[8])


# -------------------------------------------------------- events & config


def test_staging_events_lifecycle():
    ds = _skewed_dataset(n_entities=12, seed=29)
    emitter = ev.EventEmitter()
    seen = []
    emitter.register(seen.append)
    _, stager = _stager(ds, stg.StagingConfig(workers=2,
                                              shard_entities=8),
                        emitter=emitter)
    _drain(stager)
    kinds = [type(e).__name__ for e in seen]
    assert kinds[0] == "StagingStart"
    assert kinds.count("StagingFinish") == 1
    shard_events = [e for e in seen if isinstance(e, ev.StagingShard)]
    assert len(shard_events) == stager.num_shards
    start = next(e for e in seen if isinstance(e, ev.StagingStart))
    assert start.workers == 2 and start.mode == "thread"
    fin = next(e for e in seen if isinstance(e, ev.StagingFinish))
    assert fin.num_shards == stager.num_shards


def test_staging_config_validation_and_parse():
    from photon_ml_tpu.api.configs import parse_staging_config

    cfg = parse_staging_config("workers=8,depth=4,shard_entities=1024")
    assert cfg.workers == 8 and cfg.pipeline_depth == 4
    assert cfg.shard_entities == 1024 and cfg.mode == "thread"
    assert parse_staging_config("mode=process").mode == "process"
    with pytest.raises(ValueError, match="mode"):
        stg.StagingConfig(mode="fibers")
    with pytest.raises(ValueError, match="workers"):
        stg.StagingConfig(workers=0)
    with pytest.raises(ValueError, match="unknown staging keys"):
        parse_staging_config("wrokers=8")


def test_cli_staging_flag_round_trip():
    from photon_ml_tpu.cli import game_train

    args = game_train.build_parser().parse_args([
        "--train", "x", "--coordinate", "name=f,type=fixed,shard=global",
        "--update-sequence", "f", "--output-dir", "o",
        "--staging", "workers=2,mode=thread,depth=3"])
    from photon_ml_tpu.api.configs import parse_staging_config

    cfg = parse_staging_config(args.staging)
    assert cfg.workers == 2 and cfg.pipeline_depth == 3


def test_plan_shards_respects_pad_and_covers_every_lane():
    ds = _skewed_dataset(seed=31)
    b = bkt.build_bucketing(np.asarray(ds.entity_ids["userId"]),
                            ds.num_entities["userId"])
    plan = stg.plan_shards(b, shard_entities=10)  # rounds up to pad=8
    for bi, lo, hi in plan:
        assert lo % b.entity_pad_multiple == 0
        assert hi <= b.buckets[bi].num_entities
    for bi, bucket in enumerate(b.buckets):
        covered = sorted((lo, hi) for bj, lo, hi in plan if bj == bi)
        assert covered[0][0] == 0
        assert covered[-1][1] == bucket.num_entities
        for (_, h1), (l2, _) in zip(covered, covered[1:]):
            assert h1 == l2
