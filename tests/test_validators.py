"""Data-validation + feature-summarization-output tests.

Mirrors the reference's DataValidators coverage (per-task label checks,
finite features/offsets, weight sign) and the FeatureSummarizationResultAvro
round trip.
"""

import numpy as np
import pytest

from photon_ml_tpu.data.validators import (DataValidationLevel,
                                           validate_arrays,
                                           validate_features,
                                           validate_game_dataset,
                                           validate_labels)
from photon_ml_tpu.types import TaskType


class TestLabelValidation:
    def test_binary_ok(self):
        validate_labels(TaskType.LOGISTIC_REGRESSION,
                        np.array([0.0, 1.0, 1.0]))

    def test_binary_rejects_other_values(self):
        with pytest.raises(ValueError, match="binary"):
            validate_labels(TaskType.LOGISTIC_REGRESSION,
                            np.array([0.0, 2.0]))
        with pytest.raises(ValueError, match="binary"):
            validate_labels(TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM,
                            np.array([-1.0, 1.0]))  # {0,1} convention

    def test_poisson_rejects_negative(self):
        validate_labels(TaskType.POISSON_REGRESSION, np.array([0.0, 3.0]))
        with pytest.raises(ValueError, match="non-negative"):
            validate_labels(TaskType.POISSON_REGRESSION, np.array([-1.0]))

    def test_linear_rejects_nan(self):
        with pytest.raises(ValueError, match="non-finite"):
            validate_labels(TaskType.LINEAR_REGRESSION,
                            np.array([1.0, np.nan]))


class TestArrayValidation:
    def test_negative_weight_rejected(self):
        with pytest.raises(ValueError, match="weights"):
            validate_arrays(TaskType.LINEAR_REGRESSION,
                            np.array([1.0, 2.0]),
                            weights=np.array([1.0, -0.5]))

    def test_all_zero_weights_warn(self, caplog):
        import logging

        with caplog.at_level(logging.WARNING,
                             logger="photon_ml_tpu.data.validators"):
            validate_arrays(TaskType.LINEAR_REGRESSION,
                            np.array([1.0, 2.0]),
                            weights=np.array([0.0, 0.0]))
        assert any("zero" in r.message for r in caplog.records)
        # A single positive weight is a legal per-row mask: no warning.
        caplog.clear()
        with caplog.at_level(logging.WARNING,
                             logger="photon_ml_tpu.data.validators"):
            validate_arrays(TaskType.LINEAR_REGRESSION,
                            np.array([1.0, 2.0]),
                            weights=np.array([0.0, 1.0]))
        assert not caplog.records

    def test_nonfinite_offset_rejected(self):
        with pytest.raises(ValueError, match="offsets"):
            validate_arrays(TaskType.LINEAR_REGRESSION, np.array([1.0]),
                            offsets=np.array([np.inf]))

    def test_disabled_skips_everything(self):
        validate_arrays(TaskType.LOGISTIC_REGRESSION, np.array([5.0]),
                        level=DataValidationLevel.DISABLED)

    def test_sample_level_catches_dense_corruption(self):
        labels = np.full(50_000, 2.0)  # all invalid: any sample catches it
        with pytest.raises(ValueError, match="binary"):
            validate_arrays(TaskType.LOGISTIC_REGRESSION, labels,
                            level=DataValidationLevel.VALIDATE_SAMPLE)


class TestFeatureValidation:
    def test_dense_nan_rejected(self):
        X = np.ones((4, 3), np.float32)
        X[2, 1] = np.nan
        with pytest.raises(ValueError, match="feature shard 'g'"):
            validate_features("g", X)

    def test_sparse_shard_values_checked(self):
        from photon_ml_tpu.data.game_data import SparseShard

        shard = SparseShard(indices=np.zeros((3, 2), np.int32),
                            values=np.array([[1, 2], [np.inf, 0], [0, 0]],
                                            np.float32),
                            num_features=5)
        with pytest.raises(ValueError, match="feature shard"):
            validate_features("s", shard)


def test_game_dataset_validation(rng):
    from photon_ml_tpu.data import synthetic
    from photon_ml_tpu.data.game_data import from_synthetic

    ds = from_synthetic(synthetic.game_data(
        rng, n=200, d_global=4, re_specs={"userId": (5, 4)}))
    validate_game_dataset(TaskType.LOGISTIC_REGRESSION, ds)
    ds.feature_shards["global"][7, 1] = np.nan
    with pytest.raises(ValueError, match="global"):
        validate_game_dataset(TaskType.LOGISTIC_REGRESSION, ds)


def test_driver_rejects_bad_labels(tmp_path, rng):
    """The GLM driver fails fast at INIT (reference Driver behavior)."""
    from photon_ml_tpu.cli import train_glm

    path = str(tmp_path / "bad.libsvm")
    with open(path, "w") as f:
        f.write("3.0 1:0.5 2:0.25\n0 1:1.0\n")  # label 3.0 invalid
    with pytest.raises(ValueError, match="binary"):
        train_glm.run(train_glm.build_parser().parse_args([
            "--train", path, "--task", "LOGISTIC_REGRESSION",
            "--output-dir", str(tmp_path / "out")]))


def test_feature_summaries_roundtrip(tmp_path, rng):
    import jax.numpy as jnp

    from photon_ml_tpu.avro.summarization import (read_feature_summaries,
                                                  write_feature_summaries)
    from photon_ml_tpu.data.batch import LabeledBatch
    from photon_ml_tpu.data.statistics import summarize
    from photon_ml_tpu.index.indexmap import DefaultIndexMap

    X = rng.normal(size=(100, 3)).astype(np.float32)
    X[:, 2] = 1.0
    batch = LabeledBatch.build(X, np.ones(100, np.float32))
    stats = summarize(batch)
    imap = DefaultIndexMap.from_keys(["age", "clicks\x01day7"],
                                     add_intercept=True)
    path = str(tmp_path / "summ.avro")
    n = write_feature_summaries(path, stats, imap)
    assert n == 3
    recs = read_feature_summaries(path)
    by_name = {(r["name"], r["term"]): r for r in recs}
    assert ("clicks", "day7") in by_name
    r = by_name[("age", "")]
    np.testing.assert_allclose(r["mean"], float(X[:, 0].mean()), atol=1e-5)
    np.testing.assert_allclose(r["variance"], float(X[:, 0].var()),
                               atol=1e-4)
    assert r["count"] == 100
    assert by_name[("(INTERCEPT)", "")]["numNonzeros"] == 100.0


def test_glm_driver_writes_summaries(tmp_path, rng):
    from photon_ml_tpu.avro.summarization import read_feature_summaries
    from photon_ml_tpu.cli import train_glm

    path = str(tmp_path / "ok.libsvm")
    with open(path, "w") as f:
        for i in range(60):
            x1, x2 = rng.normal(), rng.normal()
            y = 1 if x1 + x2 > 0 else 0
            f.write(f"{y} 1:{x1:.4f} 2:{x2:.4f}\n")
    summ_dir = str(tmp_path / "summ")
    train_glm.run(train_glm.build_parser().parse_args([
        "--train", path, "--task", "LOGISTIC_REGRESSION",
        "--output-dir", str(tmp_path / "out"),
        "--summarization-output-dir", summ_dir]))
    recs = read_feature_summaries(
        str(tmp_path / "summ" / "feature-summaries.avro"))
    assert len(recs) == 3  # two features + intercept
    names = {r["name"] for r in recs}
    assert names == {"0", "1", "(INTERCEPT)"}


def test_sample_error_reports_original_row():
    """VALIDATE_SAMPLE diagnostics must name dataset rows, not positions
    inside the drawn sample."""
    labels = np.zeros(60_000)
    labels[37_123] = 5.0
    # Full pass: exact row named.
    with pytest.raises(ValueError, match=r"labels\[37123\]"):
        validate_labels(TaskType.LOGISTIC_REGRESSION, labels)
    # Sampled pass on all-bad data: whatever row is reported must be a REAL
    # bad row index (here: any of the poisoned ones).
    labels = np.full(60_000, 2.0)
    try:
        validate_arrays(TaskType.LOGISTIC_REGRESSION, labels,
                        level=DataValidationLevel.VALIDATE_SAMPLE)
        raise AssertionError("expected rejection")
    except ValueError as e:
        import re

        row = int(re.search(r"labels\[(\d+)\]", str(e)).group(1))
        assert labels[row] == 2.0


def test_full_level_does_not_copy():
    """VALIDATE_FULL checks arrays in place (idx is None → no gather)."""
    from photon_ml_tpu.data.validators import _rows

    rng = np.random.default_rng(0)
    assert _rows(10**8, DataValidationLevel.VALIDATE_FULL, rng) is None
    idx = _rows(10**8, DataValidationLevel.VALIDATE_SAMPLE, rng)
    assert idx is not None and len(idx) <= 10_000


def test_glm_driver_rejects_bad_validation_file(tmp_path, rng):
    from photon_ml_tpu.cli import train_glm

    train = str(tmp_path / "t.libsvm")
    with open(train, "w") as f:
        for i in range(40):
            x = rng.normal()
            f.write(f"{1 if x > 0 else 0} 1:{x:.4f}\n")
    bad_val = str(tmp_path / "v.libsvm")
    with open(bad_val, "w") as f:
        f.write("2 1:0.5\n")  # invalid label for logistic
    with pytest.raises(ValueError, match="binary"):
        train_glm.run(train_glm.build_parser().parse_args([
            "--train", train, "--validation", bad_val,
            "--task", "LOGISTIC_REGRESSION",
            "--output-dir", str(tmp_path / "out")]))
