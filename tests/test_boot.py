"""photon-boot: mmap model artifacts, atomic generation swap, and
device-elastic resume (ISSUE 14; docs/SERVING.md "Sub-second restart",
docs/STREAMING.md "Elastic resume").

The contracts under test:

* the mapped format is BYTE-identical to the npz layout (digest
  equality, not a tolerance), across every coordinate-model type;
* a mapped boot is zero-copy (the host store keeps the mmap tables
  whole) and serves the same bits as an npz boot — single service and
  through a real subprocess fleet;
* publication is atomic (a SIGKILL in the torn window leaves the
  previous generation current and servable byte-identically), rollback
  is a re-point, and post-CRC bit rot falls back one generation with a
  loud ``BootRecovered`` event;
* compaction of a committed DeltaStore chain equals replaying it,
  bit for bit, and refuses gapped chains;
* a streamed L-BFGS checkpoint written at D devices resumes at D′ ≠ D
  (``game_train --resume`` across forced device counts) within the
  established sharded-parity tolerance, while genuinely incompatible
  snapshots are still rejected.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from photon_ml_tpu import faults
from photon_ml_tpu.utils import events as ev

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), os.pardir))


@pytest.fixture(autouse=True)
def _no_leftover_plan():
    yield
    faults.install(None)


def _full_model(rng, E=40, d=8, A=3, rank=2):
    """One GameModel exercising every persisted coordinate type."""
    import jax.numpy as jnp

    from photon_ml_tpu.game.factored import FactoredRandomEffectModel
    from photon_ml_tpu.game.models import (FixedEffectModel, GameModel,
                                           RandomEffectModel,
                                           SubspaceRandomEffectModel)
    from photon_ml_tpu.models.coefficients import Coefficients
    from photon_ml_tpu.types import TaskType

    cols = np.sort(rng.integers(0, d, size=(E, A)).astype(np.int32),
                   axis=1)
    return GameModel(task=TaskType.LOGISTIC_REGRESSION, models={
        "fixed": FixedEffectModel("global", Coefficients(
            jnp.asarray(rng.normal(size=d).astype(np.float32)),
            jnp.asarray(rng.random(d).astype(np.float32)))),
        "per-user": RandomEffectModel(
            "userId", "re", jnp.asarray(
                rng.normal(size=(E, d)).astype(np.float32))),
        "per-song": SubspaceRandomEffectModel(
            "songId", "re", d, jnp.asarray(cols),
            jnp.asarray(rng.normal(size=(E, A)).astype(np.float32))),
        "per-artist": FactoredRandomEffectModel(
            "artistId", "re",
            projection=jnp.asarray(
                rng.normal(size=(rank, d)).astype(np.float32)),
            factors=jnp.asarray(
                rng.normal(size=(E, rank)).astype(np.float32))),
    })


def _serving_model(rng, E=64, dg=6, dr=4):
    import jax.numpy as jnp

    from photon_ml_tpu.game.models import (FixedEffectModel, GameModel,
                                           RandomEffectModel)
    from photon_ml_tpu.models.coefficients import Coefficients
    from photon_ml_tpu.types import TaskType

    return GameModel(task=TaskType.LOGISTIC_REGRESSION, models={
        "fixed": FixedEffectModel("global", Coefficients(
            jnp.asarray(rng.normal(size=dg).astype(np.float32)))),
        "per-user": RandomEffectModel(
            "userId", "re_userId", jnp.asarray(
                rng.normal(size=(E, dr)).astype(np.float32))),
    })


def _requests(rng, n, E=64, dg=6, dr=4):
    from photon_ml_tpu.serving import ScoringRequest

    return [ScoringRequest(
        features={"global": rng.normal(size=dg).astype(np.float32),
                  "re_userId": rng.normal(size=dr).astype(np.float32)},
        entity_ids={"userId": int(i % E)}) for i in range(n)]


# ------------------------------------------------------------ map format


def test_map_roundtrip_bit_parity_all_types(tmp_path):
    """Mapped write→load digests BYTE-identical to the in-memory model
    and the npz layout, for all four coordinate-model types; loaded
    tables are read-only mmap views."""
    from photon_ml_tpu import boot
    from photon_ml_tpu.models import io as model_io

    model = _full_model(np.random.default_rng(0))
    npz_dir = str(tmp_path / "npz")
    map_dir = str(tmp_path / "mapped")
    model_io.save_game_model(model, npz_dir)
    boot.write_mapped_model(model, map_dir)

    d_mem = model_io.game_model_digest(model)
    assert model_io.game_model_digest(
        model_io.load_game_model(npz_dir, host=True,
                                 mapped=False)) == d_mem
    loaded, marker = boot.load_mapped_model(map_dir)
    assert model_io.game_model_digest(loaded) == d_mem
    for cid in ("per-user", "per-song", "per-artist"):
        m = loaded.models[cid]
        arr = getattr(m, "means", None)
        if arr is None:
            arr = m.factors
        assert boot.is_mapped_array(arr)
        assert not np.asarray(arr).flags.writeable


def test_load_game_model_mapped_routing(tmp_path):
    """`mapped=True` prefers the map layout, FALLS BACK to npz when the
    directory has none; `mapped=None` auto-detects; `mapped=False`
    forces npz."""
    from photon_ml_tpu import boot
    from photon_ml_tpu.models import io as model_io

    model = _full_model(np.random.default_rng(1))
    d_mem = model_io.game_model_digest(model)
    npz_dir = str(tmp_path / "npz")
    map_dir = str(tmp_path / "mapped")
    model_io.save_game_model(model, npz_dir)
    boot.write_mapped_model(model, map_dir)

    # npz-only dir + mapped=True → npz fallback, same bytes.
    assert model_io.game_model_digest(model_io.load_game_model(
        npz_dir, host=True, mapped=True)) == d_mem
    # map dir auto-detected without any flag.
    auto = model_io.load_game_model(map_dir)
    assert model_io.game_model_digest(auto) == d_mem
    assert boot.is_mapped_array(auto.models["per-user"].means)


def test_mapped_store_zero_copy_and_scores_bit_identical():
    """A mapped boot takes the direct (no partition copy) host-store
    path and serves the same bits as the npz boot."""
    import tempfile

    from photon_ml_tpu import boot
    from photon_ml_tpu.serving import ScoringService

    rng = np.random.default_rng(2)
    model = _serving_model(rng)
    td = tempfile.mkdtemp(prefix="pml_boot_")
    map_dir = os.path.join(td, "mapped")
    boot.write_mapped_model(model, map_dir)
    mapped, _ = boot.load_mapped_model(map_dir)

    reqs = _requests(rng, 24)
    s_npz = ScoringService(model)
    expected = s_npz.score(reqs)
    s_npz.close()

    s_map = ScoringService(mapped)
    try:
        st = s_map.store.random[0].store
        assert st.mapped, "mapped model should take the direct path"
        got = s_map.score(reqs)
    finally:
        s_map.close()
    np.testing.assert_array_equal(got, expected)


def test_mapped_swap_rows_overlay_and_delta_rollback(tmp_path):
    """Row hot-swap on a mapped store lands in the overlay (the on-disk
    artifact stays pristine) and apply_delta/rollback_to stay exact."""
    from photon_ml_tpu import boot
    from photon_ml_tpu.models import io as model_io
    from photon_ml_tpu.serving.model_store import ResidentModelStore
    from photon_ml_tpu.serving.publish import DeltaStore

    rng = np.random.default_rng(3)
    model = _serving_model(rng)
    map_dir = str(tmp_path / "mapped")
    boot.write_mapped_model(model, map_dir)
    mapped, _ = boot.load_mapped_model(map_dir)
    store = ResidentModelStore(mapped)
    base_rows = store.random[0].store.fetch(np.arange(8, dtype=np.int64))

    ds = DeltaStore(str(tmp_path / "pub"))
    delta = ds.write({"per-user": (
        np.array([1, 5], np.int64),
        rng.normal(size=(2, 4)).astype(np.float32))})
    store.apply_delta(delta)
    got = store.random[0].store.fetch(np.arange(8, dtype=np.int64))
    exp = base_rows.copy()
    exp[1], exp[5] = delta.rows["per-user"][1]
    np.testing.assert_array_equal(got, exp)
    # The committed artifact on disk never mutated (swap = overlay).
    refetched, _ = boot.load_mapped_model(map_dir)
    assert model_io.game_model_digest(refetched) == \
        model_io.game_model_digest(model)
    # Rollback restores the pre-delta bytes exactly.
    store.rollback_to(0)
    np.testing.assert_array_equal(
        store.random[0].store.fetch(np.arange(8, dtype=np.int64)),
        base_rows)


# ----------------------------------------------------------- generations


def test_generation_publish_retention_and_rollback(tmp_path):
    from photon_ml_tpu import boot
    from photon_ml_tpu.models import io as model_io

    model = _serving_model(np.random.default_rng(4))
    gs = boot.GenerationStore(str(tmp_path / "gens"))
    assert gs.versions() == []
    v1, _ = gs.publish(model)
    v2, _ = gs.publish(model)
    v3, _ = gs.publish(model)
    assert (v1, v2, v3) == (1, 2, 3)
    # Two-generation retention: gen-1 pruned, current = newest.
    assert gs.versions() == [2, 3]
    assert gs.current_version() == 3
    # Rollback is a re-point; the rolled-to generation loads clean.
    assert gs.rollback() == 2
    m, marker, gen = gs.load_current()
    assert gen == 2
    assert model_io.game_model_digest(m) == \
        model_io.game_model_digest(model)
    # The pointed-at generation survives the next publish's pruning.
    gs.publish(model)
    assert 4 in gs.versions()


def test_torn_publish_invisible_under_sigkill(tmp_path):
    """SIGKILL in the torn window (blobs committed, directory marker
    not — `boot.map_write` occurrence 1): the half-written generation
    is invisible, gen-1 stays current and serves byte-identically, and
    a clean re-publish commits the same number."""
    from photon_ml_tpu import boot
    from photon_ml_tpu.models import io as model_io

    rng = np.random.default_rng(5)
    model = _serving_model(rng)
    gs = boot.GenerationStore(str(tmp_path / "gens"))
    gs.publish(model)
    d1 = model_io.game_model_digest(gs.load_current()[0])

    model2 = _serving_model(np.random.default_rng(6))
    npz2 = str(tmp_path / "model2")
    model_io.save_game_model(model2, npz2)
    plan = faults.FaultPlan(specs=(faults.FaultSpec(
        site="boot.map_write", kind="kill", occurrences=(1,)),))
    plan_path = str(tmp_path / "plan.json")
    with open(plan_path, "w") as f:
        f.write(plan.to_json())
    driver = (
        "import sys, json\n"
        "from photon_ml_tpu import faults, boot\n"
        "from photon_ml_tpu.models import io as model_io\n"
        f"with open({plan_path!r}) as f:\n"
        "    faults.install(faults.FaultPlan.from_json(f.read()))\n"
        f"m = model_io.load_game_model({npz2!r}, host=True)\n"
        f"boot.GenerationStore({str(tmp_path / 'gens')!r}).publish(m)\n")
    env = {k: v for k, v in os.environ.items()
           if k not in ("PALLAS_AXON_POOL_IPS",)}
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + (os.pathsep + env["PYTHONPATH"]
                                if env.get("PYTHONPATH") else "")
    proc = subprocess.run([sys.executable, "-c", driver], env=env,
                          cwd=REPO, capture_output=True, text=True,
                          timeout=300)
    assert proc.returncode == -9, \
        f"publisher survived the kill plan (rc={proc.returncode}):\n" \
        f"{proc.stderr[-2000:]}"

    gs2 = boot.GenerationStore(str(tmp_path / "gens"))
    # The torn gen-2 has blobs but no marker: not a committed version.
    assert gs2.versions() == [1]
    assert os.path.isdir(str(tmp_path / "gens" / "gen-000002"))
    m, _, gen = gs2.load_current()
    assert gen == 1
    assert model_io.game_model_digest(m) == d1
    # A clean re-publish commits the number the torn attempt burned.
    v, _ = gs2.publish(model2)
    assert v == 2
    assert model_io.game_model_digest(gs2.load_current()[0]) == \
        model_io.game_model_digest(model2)


def test_blob_rot_falls_back_one_generation_with_event(tmp_path):
    """Post-CRC bit rot in the CURRENT generation's blob: load_current
    detects the CRC mismatch, boots the PREVIOUS generation, and says
    so loudly (BootRecovered). Both generations rotten → the defined
    GenerationError."""
    from photon_ml_tpu import boot
    from photon_ml_tpu.models import io as model_io

    g1_model = _serving_model(np.random.default_rng(7))
    g2_model = _serving_model(np.random.default_rng(8))
    gs = boot.GenerationStore(str(tmp_path / "gens"))
    gs.publish(g1_model)
    # gen-2's per-user blob rots AFTER its CRC was recorded (the
    # corrupt hook sits post-checksum by construction).
    plan = faults.FaultPlan(specs=(faults.FaultSpec(
        site="boot.map_open", kind="corrupt", occurrences=(1,)),))
    with faults.installed(plan) as inj:
        gs.publish(g2_model)
    assert inj.fires("boot.map_open") == 1

    seen = []
    ev.default_emitter.register(seen.append)
    try:
        m, _, gen = gs.load_current()
    finally:
        ev.default_emitter.unregister(seen.append)
    assert gen == 1
    assert model_io.game_model_digest(m) == \
        model_io.game_model_digest(g1_model)
    recovered = [e for e in seen if isinstance(e, ev.BootRecovered)]
    assert recovered and recovered[0].from_version == 2 \
        and recovered[0].to_version == 1

    # Rot gen-1 too: the ladder ends in a refusal, never a guess.
    blob = str(tmp_path / "gens" / "gen-000001" / "blobs"
               / "per-user.bin")
    with open(blob, "r+b") as f:
        f.seek(8)
        f.write(b"\xff\xff\xff\xff")
    with pytest.raises(boot.GenerationError):
        gs.load_current()


def test_compaction_equals_delta_replay_bit_identical(tmp_path):
    """Folding a committed delta chain into the next generation equals
    replaying the chain onto a booted store, byte for byte — and the
    compacted generation records the folded model_version so a booted
    replica skips the chain."""
    from photon_ml_tpu import boot
    from photon_ml_tpu.serving.model_store import ResidentModelStore
    from photon_ml_tpu.serving.publish import DeltaStore

    rng = np.random.default_rng(9)
    model = _serving_model(rng)
    gs = boot.GenerationStore(str(tmp_path / "gens"))
    gs.publish(model)
    ds = DeltaStore(str(tmp_path / "pub"))
    deltas = [ds.write({"per-user": (
        np.sort(rng.choice(64, size=5, replace=False)).astype(np.int64),
        rng.normal(size=(5, 4)).astype(np.float32))}) for _ in range(3)]

    gen, _ = gs.compact(ds)
    assert gen == 2
    compacted, marker, _ = gs.load_current()
    assert marker["model_version"] == 3
    assert marker["deltas_folded"] == [1, 2, 3]

    replayed = ResidentModelStore(model)
    for d in deltas:
        replayed.apply_delta(d)
    all_ids = np.arange(64, dtype=np.int64)
    np.testing.assert_array_equal(
        ResidentModelStore(compacted).random[0].store.fetch(all_ids),
        replayed.random[0].store.fetch(all_ids))
    # Idempotent: nothing newer to fold.
    assert gs.compact(ds) is None
    # A booted service starts at the folded version: only NEWER deltas
    # apply (the chain-order check holds at the folded base).
    d4 = ds.write({"per-user": (np.array([0], np.int64),
                                rng.normal(size=(1, 4)).astype(
                                    np.float32))})
    store = ResidentModelStore(compacted, initial_version=3)
    assert store.version == 3
    store.apply_delta(d4)
    assert store.version == 4


def test_compaction_refuses_gapped_chain(tmp_path):
    """A retracted/missing delta mid-chain must refuse to fold — an
    artifact with a silent hole would serve wrong rows forever."""
    from photon_ml_tpu import boot
    from photon_ml_tpu.serving.publish import DeltaStore

    rng = np.random.default_rng(10)
    gs = boot.GenerationStore(str(tmp_path / "gens"))
    gs.publish(_serving_model(rng))
    ds = DeltaStore(str(tmp_path / "pub"))
    for _ in range(3):
        ds.write({"per-user": (np.array([1], np.int64),
                               rng.normal(size=(1, 4)).astype(
                                   np.float32))})
    ds.retract(2)
    with pytest.raises(boot.GenerationError, match="gaps"):
        gs.compact(ds)


# ------------------------------------------------- fleet + observability


def test_mmap_booted_fleet_serves_bit_identical(tmp_path):
    """A 2-replica fleet whose replicas mmap-boot the generation root
    answers bit-identically to the single-process npz oracle — the
    PR 1 parity discipline through the boot layer."""
    from photon_ml_tpu import boot
    from photon_ml_tpu.serving import ScoringService
    from photon_ml_tpu.serving.fleet import ServingFleet

    rng = np.random.default_rng(11)
    model = _serving_model(rng)
    gen_root = str(tmp_path / "gens")
    boot.GenerationStore(gen_root).publish(model)

    reqs = _requests(rng, 10)
    objs = [{"features": {k: np.asarray(v).tolist()
                          for k, v in r.features.items()},
             "entity_ids": r.entity_ids, "uid": i}
            for i, r in enumerate(reqs)]
    oracle = ScoringService(model, max_wait_ms=0.5)
    expected = np.asarray([float(oracle.submit(r).result(timeout=60))
                           for r in reqs], np.float32)
    oracle.close()

    fleet = ServingFleet(
        replica_args=["--model-dir", gen_root, "--max-wait-ms", "0.5"],
        num_replicas=2, workdir=str(tmp_path / "fleet"),
        probe_interval_s=0.1, heartbeat_deadline_s=2.0)
    try:
        fleet.start()
        # Replicas booted the generation (visible on their /healthz).
        hz = fleet._replica_get_json(0, "/healthz")
        assert hz["generation"] == 1, hz
        got = np.asarray(
            [float(fleet.score([o])["scores"][0]) for o in objs],
            np.float32)
    finally:
        fleet.close()
    np.testing.assert_array_equal(got, expected)


def test_boot_span_and_gauges(tmp_path):
    """cli/serve.create_server attributes the restart tail: a
    serving.boot span with map/compile/warmup children, the
    photon_boot_seconds{phase} gauges, and the model-generation
    gauge."""
    from photon_ml_tpu import boot, obs
    from photon_ml_tpu.cli import serve as serve_cli

    model = _serving_model(np.random.default_rng(12))
    gen_root = str(tmp_path / "gens")
    boot.GenerationStore(gen_root).publish(model)

    tracer = obs.Tracer()
    registry = obs.MetricsRegistry()
    with obs.activated(tracer, registry):
        args = serve_cli.build_parser().parse_args(
            ["--model-dir", gen_root, "--port", "0", "--boot-warmup",
             "--max-batch", "4"])
        server, service = serve_cli.create_server(args)
        server.server_close()
        service.close()
    snap = registry.snapshot()
    for phase in ("map", "compile", "warmup", "total"):
        key = f'photon_boot_seconds{{phase="{phase}"}}'
        assert key in snap and snap[key] >= 0.0, sorted(snap)
    assert snap["photon_model_generation"] == 1.0
    # Warmup re-ran owned shapes at least once → hits, not silence.
    hits = [v for k, v in snap.items()
            if k.startswith("photon_compile_cache_hits_total")]
    assert hits and sum(hits) >= 1
    trace = tracer.chrome_trace()
    names = [e["name"] for e in trace["traceEvents"]
             if e.get("ph") == "X"]
    assert "serving.boot" in names
    for child in ("boot.map", "boot.compile", "boot.warmup"):
        assert child in names, names


def test_summarize_serving_renders_boot_waterfall():
    """photon-obs summarize --serving: the boot span + children render
    as a waterfall (stdlib path, hand-built trace)."""
    from photon_ml_tpu.cli.obs import (render_serving_summary,
                                       summarize_serving)

    def span(name, sid, ts, dur, parent=None):
        args = {"span_id": sid}
        if parent is not None:
            args["parent_id"] = parent
        return {"ph": "X", "name": name, "ts": ts, "dur": dur,
                "cat": "serving", "args": args}

    trace = {"traceEvents": [
        span("serving.boot", 1, 0.0, 900e3),
        span("boot.map", 2, 10.0, 100e3, parent=1),
        span("boot.compile", 3, 110e3, 500e3, parent=1),
        span("boot.warmup", 4, 620e3, 250e3, parent=1),
    ]}
    summary = summarize_serving(trace)
    assert summary["boot"]["total_ms"] == pytest.approx(900.0)
    assert [p["phase"] for p in summary["boot"]["phases"]] == \
        ["boot.map", "boot.compile", "boot.warmup"]
    text = render_serving_summary(summary)
    assert "boot waterfall" in text and "boot.compile" in text


# ------------------------------------------------- device-elastic resume


def test_stream_snapshot_rejects_incompatible_fingerprint(tmp_path):
    """Elasticity never weakens the fingerprint: a snapshot from a
    different objective/config is still discarded, and a shape-
    incompatible history ring still raises."""
    from photon_ml_tpu.game.checkpoint import StreamingStateStore
    from photon_ml_tpu.optim.common import OptimizerConfig
    from photon_ml_tpu.optim.streaming import minimize_streaming

    snap = {"w": np.zeros(4, np.float32), "g": np.zeros(4, np.float32),
            "s_stack": np.zeros((2, 4), np.float32),
            "y_stack": np.zeros((2, 4), np.float32),
            "rho": np.zeros(2, np.float32), "m": np.int32(0),
            "it": np.int32(2), "fv": np.float32(1.0),
            "gn_prev": np.float32(1.0), "f0": np.float32(2.0),
            "gn0": np.float32(1.0), "vals": np.zeros(4, np.float32),
            "gns": np.zeros(4, np.float32)}
    store = StreamingStateStore(str(tmp_path / "ss"))
    store.save(snap, fingerprint={"dim": 4, "step": 1},
               environment={"num_devices": 1})
    # Device count is NOT identity: a different environment loads fine.
    assert store.load(expected_fingerprint={"dim": 4, "step": 1},
                      environment={"num_devices": 2}) is not None
    # A different fingerprint IS: discarded.
    assert store.load(
        expected_fingerprint={"dim": 8, "step": 1}) is None
    # A history ring from another optimizer config: defined rejection.
    with pytest.raises(ValueError, match="resume state shape mismatch"):
        minimize_streaming(
            lambda w: (np.float32(0.0), w), np.zeros(8, np.float32),
            OptimizerConfig(history_length=2, max_iterations=3),
            resume_state=snap)


def _elastic_env(devices: int) -> dict:
    env = {k: v for k, v in os.environ.items()
           if k not in ("PALLAS_AXON_POOL_IPS",)}
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = \
        f"--xla_force_host_platform_device_count={devices}"
    env["PYTHONPATH"] = REPO + (os.pathsep + env["PYTHONPATH"]
                                if env.get("PYTHONPATH") else "")
    return env


def _elastic_train_argv(train_dir, out):
    return [sys.executable, "-m", "photon_ml_tpu.cli.game_train",
            "--train", train_dir,
            "--coordinate", "name=fixed,type=fixed,shard=global",
            "--update-sequence", "fixed",
            "--opt-config",
            "fixed:optimizer=LBFGS,reg=L2,reg_weight=1.0",
            "--streaming", "chunk_rows=128,num_hot=8,workers=2",
            "--output-dir", out]


def _run_train(argv, env, log_path, expect_kill=False):
    with open(log_path, "w") as log:
        proc = subprocess.run(argv, env=env, cwd=REPO, stdout=log,
                              stderr=subprocess.STDOUT, timeout=600)
    if expect_kill:
        assert proc.returncode == -9, (
            f"driver survived its kill plan (rc={proc.returncode}):\n"
            + open(log_path).read()[-3000:])
    else:
        assert proc.returncode == 0, (
            f"game_train failed (rc={proc.returncode}):\n"
            + open(log_path).read()[-3000:])


def test_elastic_resume_d1_d2_d1_within_parity_tolerance(tmp_path):
    """THE elastic drill (ISSUE 14 acceptance): a streamed L-BFGS fit
    checkpointed at D=1 is SIGKILLed, resumes at D=2 (chunk ranges
    re-shard), is killed again, finishes back at D=1 — and the final
    coefficients agree with a never-killed D=1 run within the
    established sharded-parity tolerance (the D-vs-1 accumulation-order
    band the stream-dist suite pins)."""
    from photon_ml_tpu.data import sparse as sp
    from photon_ml_tpu.data.game_data import from_sparse_batch
    from photon_ml_tpu.data.io import save_game_dataset

    batch, _ = sp.synthetic_sparse(600, 48, 5, seed=21)
    ds = from_sparse_batch(batch)
    train_dir = str(tmp_path / "train")
    save_game_dataset(ds, train_dir)

    def plan_file(occurrence: int) -> str:
        plan = faults.FaultPlan(specs=(faults.FaultSpec(
            site="stream.checkpoint_write", kind="kill",
            occurrences=(occurrence,)),))
        path = str(tmp_path / f"plan-{occurrence}.json")
        with open(path, "w") as f:
            f.write(plan.to_json())
        return path

    out = str(tmp_path / "out-elastic")
    # Phase 1: D=1, killed at the 4th mid-step snapshot.
    _run_train(_elastic_train_argv(train_dir, out)
               + ["--fault-plan", plan_file(3)],
               _elastic_env(1), str(tmp_path / "p1.log"),
               expect_kill=True)
    ckpt = os.path.join(out, "checkpoints", "grid-0")
    assert any(d.startswith("stream-step")
               for d in os.listdir(ckpt)), \
        "no mid-step stream state survived the kill"
    # Phase 2: ELASTIC resume at D=2, killed again mid-optimization.
    _run_train(_elastic_train_argv(train_dir, out)
               + ["--resume", "--fault-plan", plan_file(1)],
               _elastic_env(2), str(tmp_path / "p2.log"),
               expect_kill=True)
    # Phase 3: back to D=1, runs to completion.
    _run_train(_elastic_train_argv(train_dir, out) + ["--resume"],
               _elastic_env(1), str(tmp_path / "p3.log"))

    # Oracle: one clean never-killed D=1 run.
    out_clean = str(tmp_path / "out-clean")
    _run_train(_elastic_train_argv(train_dir, out_clean),
               _elastic_env(1), str(tmp_path / "clean.log"))

    a = np.load(os.path.join(out, "best", "fixed-effect", "fixed",
                             "coefficients.npz"))["means"]
    b = np.load(os.path.join(out_clean, "best", "fixed-effect",
                             "fixed", "coefficients.npz"))["means"]
    # The established sharded-parity band (tests/test_stream_dist.py's
    # full-descent D-vs-1 tolerance).
    np.testing.assert_allclose(a, b, atol=5e-3, rtol=0)
    # The elastic resume actually happened (loud by contract; the
    # warning is only emitted AFTER a snapshot passed the fingerprint
    # and was accepted under a different device environment).
    p2_log = open(str(tmp_path / "p2.log")).read()
    assert "ELASTIC resume" in p2_log, p2_log[-2000:]
