"""Request-level tracing, latency attribution, and SLO accounting
through the serving path (ISSUE 8).

The contracts under test:

- every queued request becomes a ``serving.request`` span that CROSSES
  the batcher worker-thread boundary: parented into the
  ``serving.flush`` span that scored it, with one child span per stage;
- the four stages (queue wait / assemble / device score / respond) sum
  to within 10% of the measured request total — attribution that does
  not add up is worse than none;
- ``close()`` leaks zero spans;
- the SLO tracker's sliding window forgets, its error budget burns on
  shed/deadline/5xx, and the ``/slo`` + ``/metrics`` endpoints expose
  it;
- the MicroBatcher queue depth is observed (gauge + peak) and the
  observed depth rides in the ``BatcherQueueFull`` 503 body.
"""

import json
import threading
import urllib.error
import urllib.request

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu import obs
from photon_ml_tpu.cli.obs import (summarize_serving, summarize_trace,
                                   verify_trace)
from photon_ml_tpu.game.models import (FixedEffectModel, GameModel,
                                       RandomEffectModel)
from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.serving import (MicroBatcher, BatcherQueueFull,
                                   SLOTracker, ScoringRequest,
                                   ScoringService, make_http_server)
from photon_ml_tpu.serving.metrics import STAGES
from photon_ml_tpu.types import TaskType


def _tiny_model(rng, d_global=6, d_re=4, num_entities=12):
    return GameModel(task=TaskType.LOGISTIC_REGRESSION, models={
        "fixed": FixedEffectModel("global", Coefficients(
            jnp.asarray(rng.normal(size=d_global).astype(np.float32)))),
        "per-user": RandomEffectModel(
            "userId", "re_userId",
            jnp.asarray(rng.normal(size=(num_entities, d_re)
                                   ).astype(np.float32))),
    })


def _request(rng, model, eid=0):
    return ScoringRequest(
        features={"global": rng.normal(
            size=model.models["fixed"].dim).astype(np.float32),
            "re_userId": rng.normal(
                size=model.models["per-user"].dim).astype(np.float32)},
        entity_ids={"userId": int(eid)})


def _spans(trace, name=None):
    out = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
    if name is not None:
        out = [e for e in out if e["name"] == name]
    return out


# -- trace propagation across the batcher worker thread ---------------------


def test_request_spans_parent_into_flush_spans(rng):
    model = _tiny_model(rng)
    tracer = obs.Tracer()
    with obs.activated(trace_obj=tracer):
        with ScoringService(model, max_batch=4, max_wait_ms=1.0) as svc:
            futs = [svc.submit(_request(rng, model, i % 12))
                    for i in range(11)]
            scores = [f.result(timeout=30) for f in futs]
    assert len(scores) == 11
    assert tracer.open_spans() == 0  # close() leaked nothing
    trace = tracer.chrome_trace()
    assert verify_trace(trace) == []
    flush_ids = {e["args"]["span_id"]
                 for e in _spans(trace, "serving.flush")}
    requests = _spans(trace, "serving.request")
    assert len(requests) == 11
    assert flush_ids, "no serving.flush spans in trace"
    for e in requests:
        assert e["args"]["parent_id"] in flush_ids, \
            f"request span parented outside the flush spans: {e['args']}"
        assert e["args"]["request_id"] > 0
        assert e["args"]["crosses_queue"] is True


def test_attribution_children_sum_to_request_total(rng):
    model = _tiny_model(rng)
    tracer = obs.Tracer()
    with obs.activated(trace_obj=tracer):
        with ScoringService(model, max_batch=4, max_wait_ms=1.0) as svc:
            futs = [svc.submit(_request(rng, model, i % 12))
                    for i in range(9)]
            for f in futs:
                f.result(timeout=30)
    trace = tracer.chrome_trace()
    requests = _spans(trace, "serving.request")
    children_by_parent: dict = {}
    for name in ("serving.queue_wait", "serving.assemble",
                 "serving.device_score", "serving.respond"):
        for e in _spans(trace, name):
            children_by_parent.setdefault(
                e["args"]["parent_id"], []).append(e)
    for req in requests:
        kids = children_by_parent[req["args"]["span_id"]]
        assert sorted(k["name"] for k in kids) == [
            "serving.assemble", "serving.device_score",
            "serving.queue_wait", "serving.respond"]
        total_us = req["dur"]
        kid_us = sum(k["dur"] for k in kids)
        assert abs(kid_us - total_us) <= 0.10 * total_us, \
            f"stages {kid_us}us vs request {total_us}us"
        # Children are contained in the request interval.
        for k in kids:
            assert k["ts"] >= req["ts"] - 1.0
            assert k["ts"] + k["dur"] <= req["ts"] + req["dur"] + 1.0


def test_untraced_path_has_attribution_but_no_spans(rng):
    model = _tiny_model(rng)
    with ScoringService(model, max_batch=2, max_wait_ms=1.0) as svc:
        fut = svc.submit(_request(rng, model))
        fut.result(timeout=30)
        attr = fut.attribution
        assert attr is not None  # always measured
        stages = (attr["queue_wait_ms"] + attr["assemble_ms"]
                  + attr["device_score_ms"] + attr["respond_ms"])
        assert stages == pytest.approx(attr["total_ms"], rel=0.10)
        snap = svc.metrics.snapshot()
    assert snap["stage_requests_total"] == 1
    total_stage_s = sum(snap["stage_seconds_total"].values())
    assert total_stage_s == pytest.approx(
        snap["request_latency_sum_seconds"], rel=0.10)
    assert obs.tracer() is None  # nothing got enabled as a side effect


def test_summarize_serving_renders_stage_attribution(rng):
    model = _tiny_model(rng)
    tracer = obs.Tracer()
    with obs.activated(trace_obj=tracer):
        with ScoringService(model, max_batch=4, max_wait_ms=1.0) as svc:
            futs = [svc.submit(_request(rng, model, i % 12))
                    for i in range(8)]
            for f in futs:
                f.result(timeout=30)
    summary = summarize_serving(tracer.chrome_trace())
    assert summary["requests"] == 8
    assert summary["flushes"] >= 1
    assert summary["request_latency_ms"]["p99"] > 0
    assert 0.85 <= summary["attributed_fraction"] <= 1.01
    fracs = [a["frac_of_request_time"]
             for a in summary["stage_attribution"].values()]
    assert sum(fracs) == pytest.approx(
        summary["attributed_fraction"], abs=1e-6)
    wf = summary["slowest_request"]["waterfall"]
    assert [w["stage"] for w in wf] == [
        "serving.queue_wait", "serving.assemble",
        "serving.device_score", "serving.respond"]
    # The plain summarize still loads a serving trace (request spans are
    # exempt from strict head-containment, not from the summary).
    assert summarize_trace(tracer.chrome_trace())["wall_seconds"] > 0


# -- SLO tracker -------------------------------------------------------------


def test_slo_tracker_window_and_burn_rate():
    slo = SLOTracker(window_s=10.0, availability_objective=0.99)
    t0 = 1000.0
    for i in range(98):
        slo.record_ok(0.001 * (i + 1), now=t0 + i * 0.01)
    slo.record_bad("shed", now=t0 + 1.0)
    slo.record_bad("deadline", now=t0 + 1.1)
    s = slo.snapshot(now=t0 + 2.0)
    assert s["requests_in_window"] == 100
    assert s["bad_in_window"] == 2
    assert s["bad_by_kind"] == {"shed": 1, "deadline": 1}
    assert s["availability"] == pytest.approx(0.98)
    # bad_frac 2% against a 1% budget: burning at 2x sustainable.
    assert s["budget_burn_rate"] == pytest.approx(2.0)
    assert s["p50_ms"] == pytest.approx(49.5, rel=0.05)
    # The window forgets: 20s later everything has aged out.
    s2 = slo.snapshot(now=t0 + 22.0)
    assert s2["requests_in_window"] == 0
    assert s2["budget_burn_rate"] == 0.0


def test_slo_tracker_latency_objective_burns_budget():
    slo = SLOTracker(window_s=60.0, availability_objective=0.9,
                     latency_objective_ms=10.0)
    t0 = 50.0
    slo.record_ok(0.001, now=t0)  # fast: fine
    slo.record_ok(0.5, now=t0)  # slow: burns budget
    s = slo.snapshot(now=t0 + 1.0)
    assert s["requests_in_window"] == 2
    assert s["bad_by_kind"] == {"slow": 1}
    assert s["availability"] == pytest.approx(0.5)


def test_service_slo_counts_shed_and_errors(rng):
    model = _tiny_model(rng)
    with ScoringService(model, max_batch=2, max_queue=1,
                        max_wait_ms=200.0) as svc:
        svc.submit(_request(rng, model))  # occupies the queue
        with pytest.raises(BatcherQueueFull):
            svc.submit(_request(rng, model))
        svc.metrics.record_http_error(500)
        svc.metrics.record_http_error(503)  # NOT double-counted: shed
        s = svc.slo_snapshot()
    assert s["bad_by_kind"].get("shed") == 1
    assert s["bad_by_kind"].get("error") == 1
    assert s["lifetime"]["shed_total"] == 1


# -- queue depth observability ----------------------------------------------


def test_queue_depth_gauge_and_503_body():
    started = threading.Event()
    release = threading.Event()

    def slow_flush(entries):
        started.set()
        release.wait(timeout=30)
        return [0.0] * len(entries)

    from photon_ml_tpu.obs.metrics import Gauge

    gauge = Gauge()
    b = MicroBatcher(slow_flush, max_batch=1, max_wait_ms=1.0,
                     max_queue=3, depth_gauge=gauge)
    try:
        futs = [b.submit(0)]  # taken in flight (flush blocks on release)
        assert started.wait(timeout=10)
        futs += [b.submit(i) for i in (1, 2, 3)]  # exactly fills the queue
        with pytest.raises(BatcherQueueFull) as ei:
            b.submit(99)
        assert ei.value.depth == 3
        assert ei.value.max_queue == 3
        assert "3 pending, max 3" in str(ei.value)
        assert gauge.peak >= 3
        release.set()
        for f in futs:
            f.result(timeout=30)
    finally:
        release.set()
        b.close()
    assert gauge.value == 0  # drained


def test_metrics_text_has_queue_depth_stages_and_slo(rng):
    model = _tiny_model(rng)
    with ScoringService(model, max_batch=2, max_wait_ms=1.0) as svc:
        svc.submit(_request(rng, model)).result(timeout=30)
        text = svc.metrics.render_text()
    assert "photon_serving_queue_depth " in text
    assert "photon_serving_queue_depth_peak 1" in text
    for stage in STAGES:
        assert f'photon_serving_stage_seconds_total{{stage="{stage}"}}' \
            in text
    assert "photon_serving_slo_requests_in_window 1" in text
    assert "photon_serving_slo_budget_burn_rate 0" in text
    assert 'photon_serving_slo_latency_ms{quantile="p99"}' in text


# -- HTTP: /slo endpoint + opt-in attribution -------------------------------


def test_http_slo_endpoint_and_trace_flag(rng):
    model = _tiny_model(rng)
    svc = ScoringService(model, max_batch=4, max_wait_ms=1.0)
    server = make_http_server(svc, port=0)
    port = server.server_address[1]
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    try:
        def post(extra):
            body = json.dumps({"requests": [{
                "features": {
                    "global": [0.1] * model.models["fixed"].dim,
                    "re_userId":
                        [0.2] * model.models["per-user"].dim},
                "entity_ids": {"userId": 3}, "uid": "r1"}], **extra})
            return json.loads(urllib.request.urlopen(
                urllib.request.Request(
                    f"http://127.0.0.1:{port}/score",
                    data=body.encode()), timeout=30).read())

        plain = post({})
        assert "attribution" not in plain  # strictly opt-in
        traced = post({"trace": True})
        attr = traced["attribution"][0]
        assert attr["request_id"] > 0
        stages = (attr["queue_wait_ms"] + attr["assemble_ms"]
                  + attr["device_score_ms"] + attr["respond_ms"])
        assert stages == pytest.approx(attr["total_ms"], rel=0.10)
        slo = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/slo", timeout=30).read())
        assert slo["requests_in_window"] == 2
        assert slo["bad_in_window"] == 0
        assert slo["p99_ms"] > 0
        assert slo["lifetime"]["rows_total"] == 2
    finally:
        server.shutdown()
        server.server_close()
        svc.close()


def test_http_503_body_reports_queue_depth(rng):
    model = _tiny_model(rng)
    # max_batch=2 with a long wait window: a lone queued request SITS in
    # the queue waiting for batch-mates, deterministically occupying the
    # max_queue=1 budget when the HTTP request arrives.
    svc = ScoringService(model, max_batch=2, max_queue=1,
                         max_wait_ms=2000.0)
    server = make_http_server(svc, port=0)
    port = server.server_address[1]
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()

    def body():
        return json.dumps({"requests": [{
            "features": {
                "global": [0.0] * model.models["fixed"].dim,
                "re_userId": [0.0] * model.models["per-user"].dim},
            "entity_ids": {"userId": 1}}]}).encode()

    try:
        pending = svc.submit(ScoringRequest(
            features={"global": np.zeros(6, np.float32),
                      "re_userId": np.zeros(4, np.float32)},
            entity_ids={"userId": 0}))
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(urllib.request.Request(
                f"http://127.0.0.1:{port}/score", data=body()),
                timeout=30)
        assert ei.value.code == 503
        payload = json.loads(ei.value.read())
        assert payload["queue_depth"] == 1
        assert payload["max_queue"] == 1
        assert "shedding load" in payload["error"]
        assert svc.metrics.shed_total == 1
        pending.result(timeout=30)  # flushes once the window closes
    finally:
        server.shutdown()
        server.server_close()
        svc.close()


# -- photon-game-serve observability dump parity ----------------------------


def test_serve_cli_trace_and_metrics_dump(rng, tmp_path):
    """--trace-out/--metrics-dump parity with game_train: the dump path
    runs in run()'s finally; here the helper is driven directly against
    a traced, served request so a crashed server exercises the same
    code."""
    from photon_ml_tpu.cli import serve
    from photon_ml_tpu.cli.obs import load_trace, verify_trace
    from photon_ml_tpu.models import io as model_io
    from photon_ml_tpu.obs.metrics import parse_prometheus_text

    model_dir = str(tmp_path / "model")
    model_io.save_game_model(_tiny_model(rng), model_dir)
    args = serve.build_parser().parse_args([
        "--model-dir", model_dir, "--port", "0",
        "--max-batch", "4", "--max-wait-ms", "1.0",
        "--slo-window-s", "30", "--slo-availability", "0.99",
        "--trace-out", str(tmp_path / "serve-trace.json"),
        "--metrics-dump", str(tmp_path / "serve-metrics.prom"),
    ])
    obs.enable(trace=True, metrics=True)
    try:
        server, svc = serve.create_server(args)
        try:
            assert svc.metrics.slo.window_s == 30.0
            assert svc.metrics.slo.availability_objective == 0.99
            svc.submit(_request(rng, model_io.load_game_model(
                model_dir, host=True))).result(timeout=30)
        finally:
            server.server_close()
            svc.close()
            serve._dump_observability(svc, args.trace_out,
                                      args.metrics_dump)
    finally:
        obs.disable()
    trace = load_trace(str(tmp_path / "serve-trace.json"))
    assert verify_trace(trace) == []
    names = {e["name"] for e in trace["traceEvents"]
             if e.get("ph") == "X"}
    assert "serving.request" in names and "serving.flush" in names
    parsed = parse_prometheus_text(
        (tmp_path / "serve-metrics.prom").read_text())
    assert parsed.get("photon_serving_rows_total") == 1.0
    assert "photon_serving_slo_budget_burn_rate" in parsed
