"""GameEstimator / GameTransformer / model IO tests.

Mirrors ``GameEstimatorIntegTest`` + model save/load round trips (SURVEY.md
§4): grid over reg weights, best-model selection on validation AUC,
score-after-load equivalence.
"""

import dataclasses

import numpy as np
import pytest

from photon_ml_tpu.api.configs import (CoordinateConfiguration,
                                       FixedEffectDataConfiguration,
                                       RandomEffectDataConfiguration,
                                       parse_optimizer_config)
from photon_ml_tpu.api.estimator import GameEstimator
from photon_ml_tpu.api.transformer import GameTransformer
from photon_ml_tpu.data import synthetic
from photon_ml_tpu.data.game_data import from_synthetic
from photon_ml_tpu.models import io as model_io
from photon_ml_tpu.optim import OptimizerConfig, OptimizerType
from photon_ml_tpu.optim.problem import (GLMOptimizationConfiguration,
                                         VarianceComputationType)
from photon_ml_tpu.optim.regularization import (RegularizationContext,
                                                RegularizationType)
from photon_ml_tpu.parallel.mesh import make_mesh
from photon_ml_tpu.types import TaskType


@pytest.fixture(scope="module")
def mesh():
    return make_mesh()


def _datasets(rng, n=2400):
    syn = synthetic.game_data(rng, n=n, d_global=8,
                              re_specs={"userId": (30, 4)})
    ds = from_synthetic(syn)
    split = int(0.8 * n)
    idx = rng.permutation(n)
    return ds.subset(idx[:split]), ds.subset(idx[split:])


def _coordinates(grid=()):
    opt = GLMOptimizationConfiguration(
        optimizer=OptimizerConfig(max_iterations=50, tolerance=1e-7),
        regularization=RegularizationContext(RegularizationType.L2, 1.0))
    return {
        "fixed": CoordinateConfiguration(
            data=FixedEffectDataConfiguration("global"),
            optimization=opt, reg_weight_grid=grid),
        "per-user": CoordinateConfiguration(
            data=RandomEffectDataConfiguration("userId", "re_userId"),
            optimization=opt),
    }


def test_fit_evaluate_select_and_roundtrip(rng, mesh, tmp_path):
    train, val = _datasets(rng)
    est = GameEstimator(
        task=TaskType.LOGISTIC_REGRESSION,
        coordinates=_coordinates(grid=(0.1, 10.0)),
        update_sequence=["fixed", "per-user"],
        mesh=mesh,
        descent_iterations=2,
        validation_evaluators=["AUC", "AUC@userId"],
    )
    results = est.fit(train, val)
    assert len(results) == 2  # the reg-weight grid
    for r in results:
        assert r.evaluation is not None
        assert 0.5 < r.evaluation.metrics["AUC"] <= 1.0
    best = est.select_best_model(results)
    assert best.evaluation.primary_value == max(
        r.evaluation.metrics["AUC"] for r in results)

    # Transformer scores = model scores; save/load round trip is exact.
    path = str(tmp_path / "model")
    model_io.save_game_model(best.model, path)
    loaded = model_io.load_game_model(path)
    t1 = GameTransformer(best.model).transform(val)
    t2 = GameTransformer(loaded).transform(val)
    np.testing.assert_array_equal(t1.scores, t2.scores)

    _, evaluation = GameTransformer(loaded, ["AUC"]).transform_and_evaluate(val)
    np.testing.assert_allclose(evaluation.metrics["AUC"],
                               best.evaluation.metrics["AUC"], atol=1e-6)


def test_variances_computed_at_end(rng, mesh):
    train, val = _datasets(rng, n=1200)
    opt = GLMOptimizationConfiguration(
        optimizer=OptimizerConfig(max_iterations=40, tolerance=1e-7),
        regularization=RegularizationContext(RegularizationType.L2, 1.0),
        variance_computation=VarianceComputationType.SIMPLE)
    est = GameEstimator(
        task=TaskType.LOGISTIC_REGRESSION,
        coordinates={
            "fixed": CoordinateConfiguration(
                data=FixedEffectDataConfiguration("global"), optimization=opt),
            "per-user": CoordinateConfiguration(
                data=RandomEffectDataConfiguration("userId", "re_userId"),
                optimization=opt),
        },
        update_sequence=["fixed", "per-user"],
        mesh=mesh)
    results = est.fit(train)
    re_model = results[0].model.models["per-user"]
    assert re_model.variances is not None
    v = np.asarray(re_model.variances)
    # Trained entities got positive variances; untrained rows stay zero.
    trained_ids = np.unique(train.entity_ids["userId"])
    assert np.all(v[trained_ids] > 0)


def test_warm_start_through_estimator(rng, mesh):
    train, val = _datasets(rng, n=1000)
    est = GameEstimator(
        task=TaskType.LOGISTIC_REGRESSION,
        coordinates=_coordinates(),
        update_sequence=["fixed", "per-user"],
        mesh=mesh,
        validation_evaluators=["AUC"])
    first = est.fit(train, val)[0]
    second = est.fit(train, val,
                     initial_models=dict(first.model.models),
                     locked_coordinates={"fixed"})[0]
    np.testing.assert_array_equal(
        np.asarray(second.model.models["fixed"].coefficients.means),
        np.asarray(first.model.models["fixed"].coefficients.means))


def test_staged_validation_scores_exactly(rng, mesh):
    """_stage_dataset is a pure device-residency change: scoring the
    staged copy equals scoring the host dataset, dense and sparse shards
    alike, and staged arrays are device arrays (repeat evaluations add no
    host→device transfer)."""
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.data.game_data import GameDataset, SparseShard

    n, d_sparse = 512, 64
    syn = synthetic.game_data(rng, n=n, d_global=6,
                              re_specs={"userId": (10, 3)})
    ds = from_synthetic(syn)
    idx = np.sort(rng.integers(0, d_sparse, (n, 4)).astype(np.int32), axis=1)
    dup = np.zeros_like(idx, bool)
    dup[:, 1:] = idx[:, 1:] == idx[:, :-1]
    vals = rng.normal(size=(n, 4)).astype(np.float32)
    idx[dup] = d_sparse
    vals[dup] = 0.0
    shards = dict(ds.feature_shards)
    shards["sp"] = SparseShard(idx, vals, d_sparse)
    ds = dataclasses.replace(ds, feature_shards=shards)

    est = GameEstimator(TaskType.LOGISTIC_REGRESSION, _coordinates(),
                        ["fixed", "per-user"], mesh)
    staged = est._stage_dataset(ds)
    assert isinstance(staged.response, jax.Array)
    assert isinstance(staged.feature_shards["global"], jax.Array)
    assert isinstance(staged.feature_shards["sp"].indices, jax.Array)
    model = est.fit(ds)[0].model
    np.testing.assert_allclose(np.asarray(model.score(staged)),
                               np.asarray(model.score(ds)),
                               rtol=1e-6, atol=1e-6)


def test_coordinate_cache_is_content_keyed(rng, mesh):
    """An identical fresh dataset object HITS the coordinate cache (device
    staging reused); changed content MISSES and rebuilds — the cache keys
    on what the data IS, not which Python object carries it."""
    train, val = _datasets(rng, n=800)
    est = GameEstimator(TaskType.LOGISTIC_REGRESSION, _coordinates(),
                        ["fixed", "per-user"], mesh,
                        validation_evaluators=["AUC"])
    est.fit(train, val)
    staged_first = est._coord_cache["last"][1]["fixed"]._staged

    same_content = dataclasses.replace(
        train, response=train.response.copy())
    est.fit(same_content, val)
    assert est._coord_cache["last"][1]["fixed"]._staged is staged_first

    mutated = dataclasses.replace(train, response=1.0 - train.response)
    est.fit(mutated, val)
    assert est._coord_cache["last"][1]["fixed"]._staged is not staged_first


def test_parse_optimizer_config():
    cfg = parse_optimizer_config(
        "optimizer=TRON,max_iter=17,tolerance=1e-5,reg=L2,reg_weight=3.5,"
        "variance=SIMPLE,down_sampling_rate=0.25")
    assert cfg.optimizer.optimizer_type == OptimizerType.TRON
    assert cfg.optimizer.max_iterations == 17
    assert cfg.optimizer.tolerance == pytest.approx(1e-5)
    assert cfg.regularization.reg_type == RegularizationType.L2
    assert cfg.regularization.reg_weight == pytest.approx(3.5)
    assert cfg.variance_computation == VarianceComputationType.SIMPLE
    assert cfg.down_sampling_rate == pytest.approx(0.25)
    with pytest.raises(ValueError):
        parse_optimizer_config("optimizer")


def test_mismatched_validation_vocab_rejected(rng, mesh):
    """A SMALLER validation vocabulary is silent id misalignment and must
    be rejected; an EXTENSION is legal (allow_unseen_entities: new ids get
    rows past the frozen range and score with zero RE contribution)."""
    train, val = _datasets(rng, n=400)
    smaller = dataclasses.replace(
        val, num_entities={"userId": val.num_entities["userId"] - 2})
    est = GameEstimator(
        task=TaskType.LOGISTIC_REGRESSION,
        coordinates=_coordinates(),
        update_sequence=["fixed", "per-user"],
        mesh=mesh, validation_evaluators=["AUC"])
    with pytest.raises(ValueError, match="vocabulary"):
        est.fit(train, validation_data=smaller)
    extended = dataclasses.replace(
        val, num_entities={"userId": val.num_entities["userId"] + 5})
    result = est.fit(train, validation_data=extended)[0]
    assert np.isfinite(result.evaluation.primary_value)


def test_validation_vocab_provenance_tokens(rng, mesh):
    """With provenance tokens attached (AvroDataReader does), a validation
    vocabulary NOT derived from the training one is rejected even at
    identical size — the case counts cannot catch (advisor r2) — while a
    true extension passes whatever the sizes."""
    train, val = _datasets(rng, n=400)
    train = dataclasses.replace(
        train, vocab_tokens={"userId": ("tok-train", "tok-train")})
    est = GameEstimator(
        task=TaskType.LOGISTIC_REGRESSION,
        coordinates=_coordinates(),
        update_sequence=["fixed", "per-user"],
        mesh=mesh, validation_evaluators=["AUC"])
    # Same entity count, unrelated vocabulary: provenance mismatch.
    unrelated = dataclasses.replace(
        val, vocab_tokens={"userId": ("tok-other", "tok-other")})
    with pytest.raises(ValueError, match="provenance"):
        est.fit(train, validation_data=unrelated)
    # True extension: validation's BASE is training's FINAL token.
    extension = dataclasses.replace(
        val,
        num_entities={"userId": val.num_entities["userId"] + 3},
        vocab_tokens={"userId": ("tok-train", "tok-extended")})
    result = est.fit(train, validation_data=extension)[0]
    assert np.isfinite(result.evaluation.primary_value)
    # Content-identical vocabularies are aligned even when training itself
    # extended a frozen vocabulary (both datasets carry (B, F), B != F —
    # e.g. one read split via subset()).
    train_ext = dataclasses.replace(
        train, vocab_tokens={"userId": ("tok-base", "tok-train")})
    val_same = dataclasses.replace(
        val, vocab_tokens={"userId": ("tok-base", "tok-train")})
    result = est.fit(train_ext, validation_data=val_same)[0]
    assert np.isfinite(result.evaluation.primary_value)
