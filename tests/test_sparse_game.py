"""Sparse GAME end-to-end (BASELINE config 5, the Criteo regime).

Coverage:
- SparseFixedEffectCoordinate fit == dense FixedEffectCoordinate fit on the
  same (densified) data — the sparse objective is exact, not approximate.
- Full GameEstimator fit over a sparse shard on the 8-device mesh,
  including the feature-sharded (model-axis) configuration and the
  regularization grid.
- Pallas scatter kernel == XLA scatter (interpret mode on CPU).
- Sparse dataset save/load round trip through the CLI's container format.
"""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.api.configs import (CoordinateConfiguration,
                                       FixedEffectDataConfiguration)
from photon_ml_tpu.api.estimator import GameEstimator
from photon_ml_tpu.data import sparse as sp
from photon_ml_tpu.data.game_data import (GameDataset, SparseShard,
                                          from_sparse_batch)
from photon_ml_tpu.data.io import load_game_dataset, save_game_dataset
from photon_ml_tpu.game.coordinates import (FixedEffectCoordinate,
                                            RandomEffectCoordinate,
                                            SparseFixedEffectCoordinate)
from photon_ml_tpu.ops import losses
from photon_ml_tpu.optim import OptimizerConfig
from photon_ml_tpu.optim.problem import (GLMOptimizationConfiguration,
                                         VarianceComputationType)
from photon_ml_tpu.optim.regularization import (RegularizationContext,
                                                RegularizationType)
from photon_ml_tpu.parallel.mesh import make_mesh
from photon_ml_tpu.types import TaskType


@pytest.fixture(scope="module")
def mesh():
    return make_mesh()


def _sparse_data(n=1024, d=64, nnz=6, seed=0):
    batch, w_true = sp.synthetic_sparse(n, d, nnz, seed=seed, zipf=False)
    return batch, w_true


def _densify(batch) -> np.ndarray:
    n, d = batch.num_rows, batch.num_features
    X = np.zeros((n, d + 1), np.float32)
    rows = np.repeat(np.arange(n), batch.max_nnz)
    np.add.at(X, (rows, np.asarray(batch.indices).reshape(-1)),
              np.asarray(batch.values).reshape(-1))
    return X[:, :d]


def _opt(l2=1.0, max_iter=80):
    return GLMOptimizationConfiguration(
        optimizer=OptimizerConfig(max_iterations=max_iter, tolerance=1e-8),
        regularization=RegularizationContext(RegularizationType.L2, l2))


def test_sparse_coordinate_matches_dense(mesh):
    batch, _ = _sparse_data()
    sparse_ds = from_sparse_batch(batch)
    dense_ds = dataclasses.replace(
        sparse_ds, feature_shards={"global": _densify(batch)})
    cfg = _opt()
    dense = FixedEffectCoordinate(
        dense_ds, "global", losses.LOGISTIC, cfg, mesh)
    sparse = SparseFixedEffectCoordinate(
        sparse_ds, "global", losses.LOGISTIC, cfg, mesh)
    off = np.zeros(batch.num_rows, np.float32)
    m_dense = dense.train_model(off)
    m_sparse = sparse.train_model(off)
    np.testing.assert_allclose(
        np.asarray(m_sparse.coefficients.means),
        np.asarray(m_dense.coefficients.means), rtol=1e-3, atol=1e-4)
    # Scores agree too (gather margins == matmul margins).
    np.testing.assert_allclose(np.asarray(sparse.score(m_sparse)),
                               np.asarray(dense.score(m_sparse)),
                               rtol=1e-4, atol=1e-4)


def test_sparse_coordinate_feature_sharded_matches(mesh):
    batch, _ = _sparse_data(d=67)  # not a multiple of the model axis
    ds = from_sparse_batch(batch)
    cfg = _opt()
    # hybrid=False pins the replicated ELL formulation so this compares
    # the SAME objective evaluation with and without the model-axis
    # sharding (the hybrid layout sums in a different order).
    plain = SparseFixedEffectCoordinate(
        ds, "global", losses.LOGISTIC, cfg, mesh, hybrid=False)
    sharded = SparseFixedEffectCoordinate(
        ds, "global", losses.LOGISTIC, cfg, mesh, feature_sharded=True)
    off = np.zeros(batch.num_rows, np.float32)
    w_a = np.asarray(plain.train_model(off).coefficients.means)
    w_b = np.asarray(sharded.train_model(off).coefficients.means)
    assert w_a.shape == w_b.shape == (67,)
    np.testing.assert_allclose(w_a, w_b, rtol=1e-3, atol=1e-4)


def test_sparse_game_estimator_end_to_end(mesh):
    batch, _ = sp.synthetic_sparse(2048, 64, 16, seed=0, zipf=False,
                                   noise=0.1)
    ds = from_sparse_batch(batch)
    cc = {"fixed": CoordinateConfiguration(
        data=FixedEffectDataConfiguration("global"),
        optimization=_opt(),
        reg_weight_grid=(0.1, 1.0))}
    est = GameEstimator(TaskType.LOGISTIC_REGRESSION, cc, ["fixed"], mesh,
                        validation_evaluators=["AUC"])
    results = est.fit(ds, validation_data=ds)
    assert len(results) == 2
    best = est.select_best_model(results)
    assert best.evaluation.metrics["AUC"] > 0.7


def test_sparse_variances_simple(mesh):
    batch, _ = _sparse_data(n=512, d=24)
    ds = from_sparse_batch(batch)
    cfg = dataclasses.replace(
        _opt(), variance_computation=VarianceComputationType.SIMPLE)
    coord = SparseFixedEffectCoordinate(
        ds, "global", losses.LOGISTIC, cfg, mesh)
    off = np.zeros(batch.num_rows, np.float32)
    model = coord.train_model(off)
    model = coord.compute_model_variances(model, off)
    var = np.asarray(model.coefficients.variances)
    assert var.shape == (24,)
    assert np.all(var > 0)
    # Cross-check against the densified Hessian diagonal.
    X = _densify(batch)
    z = X @ np.asarray(model.coefficients.means)
    p = 1.0 / (1.0 + np.exp(-z))
    diag = (X * X * (p * (1 - p))[:, None]).sum(0) + 1.0  # + l2
    np.testing.assert_allclose(var, 1.0 / diag, rtol=2e-2, atol=1e-5)


def _sparse_re_data(n=2048, d=96, num_entities=24, nnz=5, seed=3,
                    intercept=True):
    """Sparse random-effect dataset with planted per-entity effects.

    Returns (sparse GameDataset, densified GameDataset) over one shard
    ``re`` keyed by ``userId``; labels depend on entity-specific weights so
    the random effect is identifiable.
    """
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, num_entities, n).astype(np.int32)
    idx = np.sort(rng.integers(0, d - 1 if intercept else d,
                               (n, nnz)).astype(np.int32), axis=1)
    # Canonicalize (ELL contract): duplicate columns become padding.
    dup = np.zeros_like(idx, bool)
    dup[:, 1:] = idx[:, 1:] == idx[:, :-1]
    vals = rng.normal(size=(n, nnz)).astype(np.float32)
    idx[dup] = d
    vals[dup] = 0.0
    if intercept:
        idx = np.concatenate([idx, np.full((n, 1), d - 1, np.int32)], axis=1)
        vals = np.concatenate([vals, np.ones((n, 1), np.float32)], axis=1)
    W_true = rng.normal(size=(num_entities, d)).astype(np.float32)
    margin = np.einsum(
        "nk,nk->n", vals,
        np.where(idx < d, W_true[ids[:, None], np.minimum(idx, d - 1)], 0.0))
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-margin))).astype(np.float32)
    shard = SparseShard(indices=idx, values=vals, num_features=d)
    base = dict(
        response=y, offsets=np.zeros(n, np.float32),
        weights=np.ones(n, np.float32),
        entity_ids={"userId": ids}, num_entities={"userId": num_entities},
        intercept_index={"re": d - 1 if intercept else None})
    sparse_ds = GameDataset(feature_shards={"re": shard}, **base)
    X = np.zeros((n, d), np.float32)
    np.add.at(X, (np.repeat(np.arange(n), idx.shape[1]),
                  np.minimum(idx, d - 1).reshape(-1)),
              np.where(idx < d, vals, 0.0).reshape(-1))
    dense_ds = GameDataset(feature_shards={"re": X}, **base)
    return sparse_ds, dense_ds


def test_sparse_random_effect_matches_densified_projection(mesh):
    """Sparse RE staging is exact: same fit as the dense projected path."""
    sparse_ds, dense_ds = _sparse_re_data()
    cfg = dataclasses.replace(
        _opt(), variance_computation=VarianceComputationType.SIMPLE)
    c_sparse = RandomEffectCoordinate(
        sparse_ds, "userId", "re", losses.LOGISTIC, cfg, mesh)
    assert c_sparse.projection  # implied by the sparse shard
    c_dense = RandomEffectCoordinate(
        dense_ds, "userId", "re", losses.LOGISTIC, cfg, mesh,
        projection=True)
    off = np.zeros(sparse_ds.num_rows, np.float32)
    m_sparse = c_sparse.train_model(off)
    m_dense = c_dense.train_model(off)
    np.testing.assert_allclose(np.asarray(m_sparse.means),
                               np.asarray(m_dense.means),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(c_sparse.score(m_sparse)),
                               np.asarray(c_dense.score(m_dense)),
                               rtol=1e-4, atol=1e-5)
    v_sparse = c_sparse.compute_model_variances(m_sparse, off)
    v_dense = c_dense.compute_model_variances(m_dense, off)
    np.testing.assert_allclose(np.asarray(v_sparse.variances),
                               np.asarray(v_dense.variances),
                               rtol=1e-4, atol=1e-6)
    # Model-level scoring agrees too (the CLI/validation path).
    np.testing.assert_allclose(np.asarray(m_sparse.score(sparse_ds)),
                               np.asarray(m_dense.score(dense_ds)),
                               rtol=1e-4, atol=1e-5)


def test_sparse_random_effect_pearson_ratio_matches_densified(mesh):
    """features_to_samples_ratio filters identically on sparse and dense."""
    sparse_ds, dense_ds = _sparse_re_data(n=1024, d=48, num_entities=8,
                                          seed=11)
    kw = dict(features_to_samples_ratio=0.2)
    c_sparse = RandomEffectCoordinate(
        sparse_ds, "userId", "re", losses.LOGISTIC, _opt(), mesh, **kw)
    c_dense = RandomEffectCoordinate(
        dense_ds, "userId", "re", losses.LOGISTIC, _opt(), mesh, **kw)
    off = np.zeros(sparse_ds.num_rows, np.float32)
    np.testing.assert_allclose(
        np.asarray(c_sparse.train_model(off).means),
        np.asarray(c_dense.train_model(off).means), rtol=1e-4, atol=1e-5)


def test_sparse_random_effect_large_d_never_densifies(mesh):
    """A d=100k sparse RE shard fits without the (n, d) dense matrix ever
    existing (it would be 1.6 GB here; the buckets stage at d_active ≤
    a few hundred) and recovers planted per-entity structure."""
    rng = np.random.default_rng(7)
    n, d, E, nnz = 4096, 100_000, 48, 6
    ids = rng.integers(0, E, n).astype(np.int32)
    # Each entity draws features from its own small column pool, so active
    # sets stay small and the planted effect is learnable.
    pools = rng.integers(0, d, (E, 64)).astype(np.int32)
    idx = np.sort(pools[ids[:, None],
                        rng.integers(0, 64, (n, nnz))], axis=1)
    dup = np.zeros_like(idx, bool)
    dup[:, 1:] = idx[:, 1:] == idx[:, :-1]
    vals = rng.normal(size=(n, nnz)).astype(np.float32)
    idx[dup] = d
    vals[dup] = 0.0
    w_pool = rng.normal(size=(E, 64)).astype(np.float32)
    margin = np.zeros(n, np.float32)
    for k in range(nnz):
        live = idx[:, k] < d
        match = pools[ids] == idx[:, k][:, None]  # (n, 64)
        coef = np.where(match, w_pool[ids], 0.0).sum(1)
        margin += np.where(live, vals[:, k] * coef, 0.0)
    y = (rng.random(n) < 1.0 / (1.0 + np.exp(-margin))).astype(np.float32)
    ds = GameDataset(
        response=y, offsets=np.zeros(n, np.float32),
        weights=np.ones(n, np.float32),
        feature_shards={"re": SparseShard(indices=idx, values=vals,
                                          num_features=d)},
        entity_ids={"userId": ids}, num_entities={"userId": E},
        intercept_index={})
    coord = RandomEffectCoordinate(ds, "userId", "re", losses.LOGISTIC,
                                   _opt(l2=0.3, max_iter=40), mesh)
    for arrays in coord._bucket_data:
        assert arrays[0].shape[-1] <= 1024  # staged width ≪ d
    model = coord.train_model(np.zeros(n, np.float32))
    s = np.asarray(coord.score(model))
    auc_num = (s[y > 0][:, None] > s[y == 0][None, :]).mean()
    assert auc_num > 0.8
    W = np.asarray(model.means)
    # Coefficients only on (a subset of) each entity's active columns.
    for e in range(0, E, 7):
        active = np.unique(idx[(ids == e)][idx[ids == e] < d])
        nz = np.flatnonzero(W[e])
        assert np.isin(nz, active).all()


def test_sparse_random_effect_rejects_normalization(mesh):
    from photon_ml_tpu.normalization import NormalizationContext

    sparse_ds, _ = _sparse_re_data(n=256, d=16, num_entities=4)
    with pytest.raises(ValueError, match="normalization"):
        RandomEffectCoordinate(
            sparse_ds, "userId", "re", losses.LOGISTIC, _opt(), mesh,
            norm=NormalizationContext(
                factors=np.ones(16, np.float32),
                intercept_index=15))


def test_sparse_random_effect_through_estimator(mesh):
    from photon_ml_tpu.api.configs import RandomEffectDataConfiguration

    sparse_ds, _ = _sparse_re_data(n=2048, d=64, num_entities=16, seed=5)
    cc = {"per-user": CoordinateConfiguration(
        data=RandomEffectDataConfiguration("userId", "re"),
        optimization=_opt())}
    est = GameEstimator(TaskType.LOGISTIC_REGRESSION, cc, ["per-user"],
                        mesh, validation_evaluators=["AUC"])
    results = est.fit(sparse_ds, validation_data=sparse_ds)
    assert results[0].evaluation.metrics["AUC"] > 0.75


@pytest.fixture(scope="module")
def mesh1():
    """Single-device mesh: the hybrid fast path's regime."""
    import jax
    from jax.sharding import Mesh

    return Mesh(np.asarray(jax.devices()[:1]).reshape(1, 1),
                ("data", "model"))


def _intercepted(batch):
    """Append an all-ones intercept column (id = d) to an ELL batch."""
    d = batch.num_features
    idx = np.concatenate(
        [np.asarray(batch.indices),
         np.full((batch.num_rows, 1), d, np.int32)], axis=1)
    vals = np.concatenate(
        [np.asarray(batch.values),
         np.ones((batch.num_rows, 1), np.float32)], axis=1)
    return dataclasses.replace(batch, indices=idx, values=vals,
                               num_features=d + 1)


def _ell_objective(batch, w, l2=0.0, l1=0.0, intercept=None,
                   weights=None):
    """Reference regularized objective evaluated through the ELL ops
    (both layouts must minimize this same function)."""
    from photon_ml_tpu.ops import sparse_aggregators as sagg

    b = batch if weights is None else dataclasses.replace(
        batch, weights=weights)
    v, _ = sagg.value_and_gradient(losses.LOGISTIC, jnp.asarray(w), b)
    mask = np.ones(len(w), np.float32)
    if intercept is not None:
        mask[intercept] = 0.0
    return (float(v) + 0.5 * l2 * float(np.sum((w * mask) ** 2))
            + l1 * float(np.sum(np.abs(w * mask))))


def test_hybrid_coordinate_matches_ell(mesh1):
    """The hybrid hot/cold layout minimizes the SAME objective as the ELL
    pipeline (values equal at both solutions; coefficients agree up to
    optimizer path sensitivity) and the SIMPLE variance computation is
    exact at a shared model."""
    batch, _ = sp.synthetic_sparse(2048, 256, 8, seed=4)  # zipf head
    batch = _intercepted(batch)
    ds = from_sparse_batch(batch)
    ds = dataclasses.replace(ds, intercept_index={"global": 256})
    cfg = dataclasses.replace(
        _opt(), variance_computation=VarianceComputationType.SIMPLE)
    ell = SparseFixedEffectCoordinate(
        ds, "global", losses.LOGISTIC, cfg, mesh1, hybrid=False)
    hyb = SparseFixedEffectCoordinate(
        ds, "global", losses.LOGISTIC, cfg, mesh1)
    assert hyb.hybrid and not ell.hybrid
    off = np.zeros(batch.num_rows, np.float32)
    m_ell = ell.train_model(off)
    m_hyb = hyb.train_model(off)
    w_e = np.asarray(m_ell.coefficients.means)
    w_h = np.asarray(m_hyb.coefficients.means)
    f_e = _ell_objective(batch, w_e, l2=1.0, intercept=256)
    f_h = _ell_objective(batch, w_h, l2=1.0, intercept=256)
    assert abs(f_e - f_h) < 1e-5 * abs(f_e), (f_e, f_h)
    np.testing.assert_allclose(w_h, w_e, rtol=0.1, atol=1e-3)
    # Scores at the SAME model agree exactly (scoring-path equivalence).
    np.testing.assert_allclose(np.asarray(hyb.score(m_ell)),
                               np.asarray(ell.score(m_ell)),
                               rtol=1e-4, atol=1e-4)
    # Variances at the SAME model: exact path equivalence.
    v_ell = ell.compute_model_variances(m_ell, off)
    v_hyb = hyb.compute_model_variances(m_ell, off)
    np.testing.assert_allclose(
        np.asarray(v_hyb.coefficients.variances),
        np.asarray(v_ell.coefficients.variances), rtol=1e-4, atol=1e-7)


def test_hybrid_matches_ell_owlqn_l1(mesh1):
    """L1/OWL-QN in the permuted space: the intercept's exemption follows
    the permutation and both layouts reach the same L1 objective."""
    from photon_ml_tpu.optim import OptimizerType

    batch, _ = sp.synthetic_sparse(1024, 128, 6, seed=6)
    batch = _intercepted(batch)
    ds = from_sparse_batch(batch)
    ds = dataclasses.replace(ds, intercept_index={"global": 128})
    cfg = GLMOptimizationConfiguration(
        optimizer=OptimizerConfig(optimizer_type=OptimizerType.OWLQN,
                                  max_iterations=80, tolerance=1e-8),
        regularization=RegularizationContext(RegularizationType.L1, 0.5))
    off = np.zeros(batch.num_rows, np.float32)
    w_ell = np.asarray(SparseFixedEffectCoordinate(
        ds, "global", losses.LOGISTIC, cfg, mesh1,
        hybrid=False).train_model(off).coefficients.means)
    w_hyb = np.asarray(SparseFixedEffectCoordinate(
        ds, "global", losses.LOGISTIC, cfg, mesh1,
        hybrid=True).train_model(off).coefficients.means)
    f_e = _ell_objective(batch, w_ell, l1=0.5, intercept=128)
    f_h = _ell_objective(batch, w_hyb, l1=0.5, intercept=128)
    assert abs(f_e - f_h) < 1e-4 * abs(f_e), (f_e, f_h)
    # L1 actually sparsified (sanity that the orthant path ran).
    assert (np.abs(w_hyb) < 1e-8).sum() > 0


def test_hybrid_down_sampling_matches_ell(mesh1):
    """Weight-masked down-sampling == the ELL path's row-gathered subsets
    (same seed ⇒ same draws ⇒ identical subsampled objective)."""
    batch, _ = sp.synthetic_sparse(2048, 64, 6, seed=7)
    ds = from_sparse_batch(batch)
    cfg = dataclasses.replace(_opt(), down_sampling_rate=0.5)
    off = np.zeros(batch.num_rows, np.float32)
    coords = {
        name: SparseFixedEffectCoordinate(
            ds, "global", losses.LOGISTIC, cfg, mesh1, hybrid=h,
            down_sampling_seed=9)
        for name, h in (("ell", False), ("hyb", True))}
    w = {k: np.asarray(c.train_model(off).coefficients.means)
         for k, c in coords.items()}
    # Reconstruct the draw both coordinates made (same seed, same order).
    from photon_ml_tpu.game.sampling import binary_classification_down_sample
    idx, mult = binary_classification_down_sample(
        np.random.default_rng(9), ds.response, 0.5)
    w_mask = np.zeros(ds.num_rows, np.float32)
    w_mask[idx] = np.asarray(ds.weights)[idx] * np.asarray(mult)
    f_e = _ell_objective(batch, w["ell"], l2=1.0, weights=jnp.asarray(w_mask))
    f_h = _ell_objective(batch, w["hyb"], l2=1.0, weights=jnp.asarray(w_mask))
    assert abs(f_e - f_h) < 1e-5 * abs(f_e), (f_e, f_h)


def test_hybrid_auto_selection(mesh, mesh1):
    """auto: hybrid whenever coefficients replicate — single-device uses
    the single layout, a sharded data axis the HybridShards composition;
    only feature_sharded (no replicated permuted space) keeps ELL."""
    batch, _ = _sparse_data(n=256, d=32)
    ds = from_sparse_batch(batch)
    c1 = SparseFixedEffectCoordinate(
        ds, "global", losses.LOGISTIC, _opt(), mesh1)
    assert c1.hybrid and not c1._hybrid_sharded
    c8 = SparseFixedEffectCoordinate(
        ds, "global", losses.LOGISTIC, _opt(), mesh)
    assert c8.hybrid and c8._hybrid_sharded
    assert not SparseFixedEffectCoordinate(
        ds, "global", losses.LOGISTIC, _opt(), mesh,
        feature_sharded=True).hybrid
    with pytest.raises(ValueError, match="feature_sharded"):
        SparseFixedEffectCoordinate(
            ds, "global", losses.LOGISTIC, _opt(), mesh1,
            feature_sharded=True, hybrid=True)


def test_hybrid_sharded_matches_ell(mesh, mesh1):
    """The data-sharded hybrid composition (HybridShards) minimizes the
    SAME objective as the ELL pipeline and the single-device hybrid
    layout, with exact scoring/variance path equivalence — the P3
    composition the single-shard layout could not cover."""
    batch, _ = sp.synthetic_sparse(2049, 256, 8, seed=4)  # odd: pad rows
    batch = _intercepted(batch)
    ds = from_sparse_batch(batch)
    ds = dataclasses.replace(ds, intercept_index={"global": 256})
    cfg = dataclasses.replace(
        _opt(), variance_computation=VarianceComputationType.SIMPLE)
    ell = SparseFixedEffectCoordinate(
        ds, "global", losses.LOGISTIC, cfg, mesh, hybrid=False)
    hyb = SparseFixedEffectCoordinate(
        ds, "global", losses.LOGISTIC, cfg, mesh)
    one = SparseFixedEffectCoordinate(
        ds, "global", losses.LOGISTIC, cfg, mesh1)
    assert hyb.hybrid and hyb._hybrid_sharded
    off = np.zeros(batch.num_rows, np.float32)
    m_ell = ell.train_model(off)
    m_hyb = hyb.train_model(off)
    w_e = np.asarray(m_ell.coefficients.means)
    w_h = np.asarray(m_hyb.coefficients.means)
    f_e = _ell_objective(batch, w_e, l2=1.0, intercept=256)
    f_h = _ell_objective(batch, w_h, l2=1.0, intercept=256)
    assert abs(f_e - f_h) < 1e-5 * abs(f_e), (f_e, f_h)
    np.testing.assert_allclose(w_h, w_e, rtol=0.1, atol=1e-3)
    # Scores at the SAME model: all three layouts agree exactly.
    np.testing.assert_allclose(np.asarray(hyb.score(m_ell)),
                               np.asarray(ell.score(m_ell)),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(hyb.score(m_ell)),
                               np.asarray(one.score(m_ell)),
                               rtol=1e-4, atol=1e-4)
    # Variances at the SAME model: exact path equivalence.
    v_ell = ell.compute_model_variances(m_ell, off)
    v_hyb = hyb.compute_model_variances(m_ell, off)
    np.testing.assert_allclose(
        np.asarray(v_hyb.coefficients.variances),
        np.asarray(v_ell.coefficients.variances), rtol=1e-4, atol=1e-7)


def test_hybrid_sharded_objective_is_exact(mesh):
    """Raw value/gradient/margins of the sharded hybrid objective equal
    the single-device hybrid layout's and the ELL shard_map pipeline's at
    an arbitrary w — the composition is exact, not approximate."""
    import jax.numpy as jnp

    from photon_ml_tpu.ops import hybrid_sparse as hs
    from photon_ml_tpu.parallel import sparse_objective as sobj
    from photon_ml_tpu.parallel import sparse_problem as spp

    batch, _ = sp.synthetic_sparse(1023, 300, 8, seed=0, zipf=True)
    d = batch.num_features
    w = np.random.default_rng(1).normal(size=d).astype(np.float32)

    hb = hs.build_hybrid(batch)
    shb = spp.shard_hybrid(hs.build_hybrid_shards(batch, 8), mesh)
    v1, g1 = hs.value_and_gradient(
        losses.LOGISTIC, hs.to_permuted_space(hb, jnp.asarray(w)), hb)
    g1 = np.asarray(hs.to_original_space(hb, g1))
    w8p = jnp.asarray(w)[shb.perm]
    v8, g8 = sobj.make_hybrid_value_and_gradient(
        losses.LOGISTIC, mesh, shb)(w8p)
    g8 = np.asarray(g8)[np.asarray(shb.inv_perm)]
    vE, gE = sobj.make_value_and_gradient(
        losses.LOGISTIC, mesh, spp.shard_sparse_batch(batch, mesh))(
        jnp.asarray(w))
    assert abs(float(v1) - float(v8)) < 1e-5 * abs(float(v1))
    assert abs(float(vE) - float(v8)) < 1e-5 * abs(float(vE))
    np.testing.assert_allclose(g8, g1, rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(g8, np.asarray(gE), rtol=1e-3, atol=1e-4)
    m1 = np.asarray(hs.margins(hb, hs.to_permuted_space(hb, jnp.asarray(w))))
    m8 = np.asarray(sobj.make_hybrid_margins(mesh, shb)(w8p))[:batch.num_rows]
    np.testing.assert_allclose(m8, m1, rtol=1e-4, atol=1e-5)


def test_hybrid_sharded_down_sampling_matches(mesh, mesh1):
    """Same seed ⇒ same subsampled objective across the sharded and
    single-device hybrid layouts (flat padded row order == original row
    order, so the weight mask lands on the same rows)."""
    batch, _ = sp.synthetic_sparse(2048, 64, 6, seed=7)
    ds = from_sparse_batch(batch)
    cfg = dataclasses.replace(_opt(), down_sampling_rate=0.5)
    off = np.zeros(batch.num_rows, np.float32)
    w = {}
    for name, m in (("one", mesh1), ("sharded", mesh)):
        w[name] = np.asarray(SparseFixedEffectCoordinate(
            ds, "global", losses.LOGISTIC, cfg, m,
            down_sampling_seed=9).train_model(off).coefficients.means)
    from photon_ml_tpu.game.sampling import binary_classification_down_sample
    idx, mult = binary_classification_down_sample(
        np.random.default_rng(9), ds.response, 0.5)
    w_mask = np.zeros(ds.num_rows, np.float32)
    w_mask[idx] = np.asarray(ds.weights)[idx] * np.asarray(mult)
    f_1 = _ell_objective(batch, w["one"], l2=1.0, weights=jnp.asarray(w_mask))
    f_8 = _ell_objective(batch, w["sharded"], l2=1.0,
                         weights=jnp.asarray(w_mask))
    assert abs(f_1 - f_8) < 1e-5 * abs(f_1), (f_1, f_8)


def test_hybrid_layout_roundtrip():
    """build_hybrid partitions every nonzero exactly once and the permuted
    margins/gradient match a dense reference."""
    import jax.numpy as jnp

    from photon_ml_tpu.ops import hybrid_sparse as hs

    rng = np.random.default_rng(11)
    batch, _ = sp.synthetic_sparse(512, 96, 5, seed=11)
    hb = hs.build_hybrid(batch, hot_threshold=20)
    X = _densify(batch)
    w = rng.normal(size=96).astype(np.float32)
    wp = hs.to_permuted_space(hb, jnp.asarray(w))
    np.testing.assert_allclose(
        np.asarray(hs.to_original_space(hb, wp)), w, rtol=0, atol=0)
    z = np.asarray(hs.margins(hb, wp))
    np.testing.assert_allclose(z, X @ w, rtol=1e-4, atol=1e-4)
    r = rng.normal(size=512).astype(np.float32)
    from photon_ml_tpu.ops.hybrid_sparse import _rowterm_gradient
    g = np.asarray(hs.to_original_space(hb, _rowterm_gradient(hb, jnp.asarray(r))))
    np.testing.assert_allclose(g, r @ X, rtol=1e-3, atol=1e-3)


def test_pallas_scatter_matches_xla():
    from photon_ml_tpu.ops.pallas_sparse import scatter_rowterm

    rng = np.random.default_rng(1)
    n, k, d = 333, 7, 200  # non-tile-aligned everywhere
    idx = rng.integers(0, d + 1, (n, k)).astype(np.int32)
    rv = rng.normal(size=(n, k)).astype(np.float32)
    rv[idx == d] = 0.0
    ref = np.zeros(d + 1, np.float32)
    np.add.at(ref, idx.reshape(-1), rv.reshape(-1))
    out = np.asarray(scatter_rowterm(idx, rv, d, interpret=True))
    np.testing.assert_allclose(out, ref[:d], rtol=1e-5, atol=1e-5)


def test_sparse_dataset_roundtrip(tmp_path):
    batch, _ = _sparse_data(n=128, d=32)
    ds = from_sparse_batch(batch)
    save_game_dataset(ds, str(tmp_path / "ds"))
    back = load_game_dataset(str(tmp_path / "ds"))
    shard = back.feature_shards["global"]
    assert isinstance(shard, SparseShard)
    assert shard.num_features == 32
    np.testing.assert_array_equal(shard.indices,
                                  ds.feature_shards["global"].indices)
    np.testing.assert_allclose(shard.values,
                               ds.feature_shards["global"].values)


def test_sparse_subset():
    batch, _ = _sparse_data(n=100, d=16)
    ds = from_sparse_batch(batch)
    sub = ds.subset(np.arange(10))
    assert sub.feature_shards["global"].indices.shape[0] == 10
    assert sub.shard_dim("global") == 16


def test_avro_reader_sparse_shard(tmp_path):
    """AvroDataReader with FeatureShardConfig(sparse=True) builds an ELL
    SparseShard identical in content to the dense read."""
    from photon_ml_tpu.avro import schemas
    from photon_ml_tpu.avro.container import write_records
    from photon_ml_tpu.avro.data_reader import (AvroDataReader,
                                                FeatureShardConfig)

    rng = np.random.default_rng(4)
    recs = []
    for i in range(50):
        feats = [{"name": f"f{j}", "term": "", "value": float(v)}
                 for j, v in zip(rng.choice(20, size=5, replace=False),
                                 rng.normal(size=5))]
        # one duplicated feature to exercise accumulation
        feats.append(dict(feats[0]))
        recs.append({"uid": f"u{i}", "label": float(i % 2),
                     "features": feats})
    path = str(tmp_path / "d.avro")
    write_records(path, schemas.TRAINING_EXAMPLE_AVRO, recs)

    reader = AvroDataReader()
    dense_ds, meta = reader.read(
        path, {"g": FeatureShardConfig(("features",), has_intercept=True)})
    sparse_ds, _ = reader.read(
        path, {"g": FeatureShardConfig(("features",), has_intercept=True,
                                       sparse=True)},
        index_maps=meta.index_maps)

    shard = sparse_ds.feature_shards["g"]
    assert isinstance(shard, SparseShard)
    # Densify the ELL and compare against the dense read exactly.
    n, d = shard.shape
    dense_from_sparse = np.zeros((n, d + 1), np.float32)
    rows = np.repeat(np.arange(n), shard.indices.shape[1])
    np.add.at(dense_from_sparse, (rows, shard.indices.reshape(-1)),
              shard.values.reshape(-1))
    np.testing.assert_allclose(dense_from_sparse[:, :d],
                               dense_ds.feature_shards["g"], rtol=1e-6)
    # Canonical rows: no duplicate indices (dups accumulated at read).
    for i in range(n):
        real = shard.indices[i][shard.indices[i] < d]
        assert len(real) == len(set(real.tolist()))


def test_game_train_accepts_libsvm_file(rng, tmp_path):
    """The training driver takes a LIBSVM file directly as a sparse
    fixed-effect-only dataset (Criteo-style ingestion shortcut)."""
    import json
    import os

    from photon_ml_tpu.cli import game_train
    from photon_ml_tpu.data.libsvm import write_libsvm

    X = (rng.normal(size=(600, 20)) *
         (rng.random((600, 20)) < 0.4)).astype(np.float32)
    w = rng.normal(size=20)
    y = np.where(rng.uniform(size=600) < 1 / (1 + np.exp(-X @ w)), 1, -1)
    tr = str(tmp_path / "tr.txt")
    va = str(tmp_path / "va.txt")
    write_libsvm(tr, X[:480], y[:480])
    write_libsvm(va, X[480:], y[480:])
    out = str(tmp_path / "out")
    summary = game_train.run(game_train.build_parser().parse_args([
        "--train", tr, "--validation", va,
        "--coordinate", "name=fixed,type=fixed,shard=global",
        "--update-sequence", "fixed", "--evaluators", "AUC",
        "--output-dir", out,
    ]))
    assert summary["best_metrics"]["AUC"] > 0.7


def test_staging_cache_roundtrip(mesh, tmp_path):
    """Warm staging (digest-keyed disk cache) skips the projection pass
    and reproduces the cold coordinate exactly — staged arrays, trained
    model, scores, and the subspace join tables."""
    from photon_ml_tpu.utils import events as ev

    sparse_ds, _ = _sparse_re_data()
    cfg = _opt()
    cache = str(tmp_path / "stage")
    seen = []
    ev.default_emitter.register(seen.append)
    try:
        kw = dict(staging_cache_dir=cache, subspace_model=True)
        cold = RandomEffectCoordinate(sparse_ds, "userId", "re",
                                      losses.LOGISTIC, cfg, mesh,
                                      **kw).wait_staged()
        n_staged = sum(1 for e in seen
                       if isinstance(e, ev.StagingShard)
                       and e.source == "staged")
        assert n_staged > 0
        seen.clear()
        warm = RandomEffectCoordinate(sparse_ds, "userId", "re",
                                      losses.LOGISTIC, cfg, mesh,
                                      **kw).wait_staged()
        # No projection work on the warm path: every shard a cache hit.
        shard_events = [e for e in seen if isinstance(e, ev.StagingShard)]
        assert shard_events and all(e.source == "cache"
                                    for e in shard_events)
    finally:
        ev.default_emitter.unregister(seen.append)
    assert len(warm._bucket_data) == len(cold._bucket_data)
    for tc, tw in zip(cold._bucket_data, warm._bucket_data):
        assert len(tc) == len(tw)
        for ac, aw in zip(tc, tw):
            np.testing.assert_array_equal(np.asarray(ac), np.asarray(aw))
    np.testing.assert_array_equal(cold.subspace_cols, warm.subspace_cols)
    np.testing.assert_array_equal(np.asarray(cold._sp_flatpos),
                                  np.asarray(warm._sp_flatpos))
    off = np.zeros(sparse_ds.num_rows, np.float32)
    m_cold = cold.train_model(off)
    m_warm = warm.train_model(off)
    np.testing.assert_array_equal(np.asarray(m_cold.means),
                                  np.asarray(m_warm.means))
    np.testing.assert_array_equal(np.asarray(cold.score(m_cold)),
                                  np.asarray(warm.score(m_warm)))


def test_staging_cache_keys_on_content(mesh, tmp_path):
    """Different data or staging params never hit the same cache entry."""
    from photon_ml_tpu.game import staging_cache

    sparse_ds, _ = _sparse_re_data()
    other_ds, _ = _sparse_re_data(seed=5)
    cache = str(tmp_path / "stage")
    cfg = _opt()
    c1 = RandomEffectCoordinate(sparse_ds, "userId", "re", losses.LOGISTIC,
                                cfg, mesh, staging_cache_dir=cache)
    c2 = RandomEffectCoordinate(other_ds, "userId", "re", losses.LOGISTIC,
                                cfg, mesh, staging_cache_dir=cache)
    c3 = RandomEffectCoordinate(sparse_ds, "userId", "re", losses.LOGISTIC,
                                cfg, mesh, staging_cache_dir=cache,
                                upper_bound=2)
    keys = {c._staging_cache_key for c in (c1, c2, c3)}
    assert len(keys) == 3
    # A corrupt entry is a miss, not an error: truncate every array file.
    import os
    entry = os.path.join(cache, c1._staging_cache_key)
    for f in os.listdir(entry):
        if f.endswith(".npy"):
            open(os.path.join(entry, f), "wb").close()
    assert staging_cache.load(cache, c1._staging_cache_key) is None
    c1b = RandomEffectCoordinate(sparse_ds, "userId", "re", losses.LOGISTIC,
                                 cfg, mesh, staging_cache_dir=cache)
    off = np.zeros(sparse_ds.num_rows, np.float32)
    np.testing.assert_allclose(
        np.asarray(c1b.train_model(off).means),
        np.asarray(c1.train_model(off).means), rtol=1e-5, atol=1e-6)
    # ...and the restage REPLACED the poisoned entry (no permanent miss).
    assert staging_cache.load(cache, c1._staging_cache_key) is not None


def test_random_effect_bf16_feature_storage(mesh):
    """bf16 bucket-block storage reproduces the f32 per-entity solves to
    bf16 tolerance, on both the projected (sparse) and dense RE paths,
    with equal AUC on planted effects (the dense fixed path's contract:
    storage shrinks, accumulation stays f32)."""
    sparse_ds, dense_ds = _sparse_re_data()
    cfg = _opt()
    off = np.zeros(sparse_ds.num_rows, np.float32)
    y = np.asarray(sparse_ds.response)
    from photon_ml_tpu.evaluation import evaluators as ev

    for ds_, proj in ((sparse_ds, True), (dense_ds, False)):
        c32 = RandomEffectCoordinate(ds_, "userId", "re", losses.LOGISTIC,
                                     cfg, mesh, projection=proj)
        c16 = RandomEffectCoordinate(ds_, "userId", "re", losses.LOGISTIC,
                                     cfg, mesh, projection=proj,
                                     feature_dtype="bfloat16").wait_staged()
        assert c16._bucket_data[0][0].dtype == jnp.bfloat16
        m32 = c32.train_model(off)
        m16 = c16.train_model(off)
        w32, w16 = np.asarray(m32.means), np.asarray(m16.means)
        # bf16 storage: ~1e-2 relative coefficient deltas are expected.
        np.testing.assert_allclose(w16, w32, rtol=0.3, atol=0.05)
        a32 = float(ev.auc(jnp.asarray(np.asarray(c32.score(m32))),
                           jnp.asarray(y)))
        a16 = float(ev.auc(jnp.asarray(np.asarray(c16.score(m16))),
                           jnp.asarray(y)))
        assert a16 > a32 - 0.01, (proj, a16, a32)

    with pytest.raises(ValueError, match="feature_dtype"):
        RandomEffectCoordinate(sparse_ds, "userId", "re", losses.LOGISTIC,
                               cfg, mesh, feature_dtype="int8")
