"""Optimizer convergence tests vs closed forms and scipy.

Mirrors photon-lib ``LBFGSTest`` / ``TRONTest`` / ``OWLQNTest`` (SURVEY.md
§4): convergence on quadratics and known GLM solutions, optimizer
cross-checks (LBFGS and TRON reach the same optimum), OWL-QN sparsity.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import scipy.optimize

from photon_ml_tpu.data.batch import LabeledBatch
from photon_ml_tpu.ops import aggregators as agg
from photon_ml_tpu.ops import losses
from photon_ml_tpu.optim import (OptimizerConfig, OptimizerType,
                                 l1_weights_vector, minimize_lbfgs,
                                 minimize_owlqn, minimize_tron, optimize,
                                 with_l2, with_l2_hvp)


def _quadratic(d, rng):
    A = rng.normal(size=(d, d))
    A = A @ A.T + d * np.eye(d)  # SPD, well-conditioned
    b = rng.normal(size=d)
    A_j, b_j = jnp.asarray(A, jnp.float32), jnp.asarray(b, jnp.float32)

    def vg(w):
        return 0.5 * w @ A_j @ w - b_j @ w, A_j @ w - b_j

    def hvp(w, v):
        return A_j @ v

    w_star = np.linalg.solve(A, b)
    return vg, hvp, w_star


def _logistic_problem(rng, n=200, d=8, l2=0.1):
    X = rng.normal(size=(n, d)).astype(np.float32)
    X[:, -1] = 1.0
    w_true = rng.normal(size=d)
    p = 1.0 / (1.0 + np.exp(-(X @ w_true)))
    y = (rng.uniform(size=n) < p).astype(np.float32)
    batch = LabeledBatch.build(X, y)

    def vg(w):
        return agg.value_and_gradient(losses.LOGISTIC, w, batch)

    def hvp(w, v):
        return agg.hessian_vector(losses.LOGISTIC, w, v, batch)

    vg_l2 = with_l2(vg, l2)
    hvp_l2 = with_l2_hvp(hvp, l2)

    # scipy ground truth (f64)
    def f_np(w):
        z = X.astype(np.float64) @ w
        return (np.logaddexp(0, z) - y * z).sum() + 0.5 * l2 * (w @ w)

    res = scipy.optimize.minimize(f_np, np.zeros(d), method="L-BFGS-B",
                                  jac=lambda w: X.T.astype(np.float64) @ (
                                      1/(1+np.exp(-(X @ w))) - y) + l2 * w,
                                  options={"gtol": 1e-10})
    return vg_l2, hvp_l2, res.x, batch


def test_lbfgs_quadratic(rng):
    vg, _, w_star = _quadratic(10, rng)
    out = jax.jit(lambda w0: minimize_lbfgs(vg, w0, OptimizerConfig(
        max_iterations=100, tolerance=1e-10)))(jnp.zeros(10))
    assert bool(out.converged)
    np.testing.assert_allclose(out.w, w_star, rtol=1e-3, atol=1e-3)


def test_tron_quadratic(rng):
    vg, hvp, w_star = _quadratic(10, rng)
    # f32: the gradient floor sits around 1e-4 relative; 1e-6 is achievable
    # via the value criterion, 1e-10 is not (stall would be reported failed).
    out = jax.jit(lambda w0: minimize_tron(vg, hvp, w0, OptimizerConfig(
        max_iterations=50, tolerance=1e-6)))(jnp.zeros(10))
    assert bool(out.converged)
    np.testing.assert_allclose(out.w, w_star, rtol=1e-3, atol=1e-3)


def test_lbfgs_logistic_matches_scipy(rng):
    vg, _, w_ref, _ = _logistic_problem(rng)
    out = minimize_lbfgs(vg, jnp.zeros(8), OptimizerConfig(
        max_iterations=200, tolerance=1e-9))
    np.testing.assert_allclose(out.w, w_ref, rtol=2e-2, atol=2e-2)


def test_tron_logistic_matches_scipy_and_lbfgs(rng):
    vg, hvp, w_ref, _ = _logistic_problem(rng)
    cfg = OptimizerConfig(max_iterations=100, tolerance=1e-9)
    out_t = minimize_tron(vg, hvp, jnp.zeros(8), cfg)
    out_l = minimize_lbfgs(vg, jnp.zeros(8), cfg)
    np.testing.assert_allclose(out_t.w, w_ref, rtol=2e-2, atol=2e-2)
    np.testing.assert_allclose(out_t.w, out_l.w, rtol=2e-2, atol=2e-2)


def test_linear_regression_exact_solution(rng):
    n, d = 100, 6
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = (X @ rng.normal(size=d) + 0.01 * rng.normal(size=n)).astype(np.float32)
    batch = LabeledBatch.build(X, y)
    vg = lambda w: agg.value_and_gradient(losses.SQUARED, w, batch)
    w_ols = np.linalg.lstsq(X, y, rcond=None)[0]
    out = minimize_lbfgs(vg, jnp.zeros(d), OptimizerConfig(
        max_iterations=200, tolerance=1e-10))
    np.testing.assert_allclose(out.w, w_ols, rtol=1e-2, atol=1e-2)


def test_poisson_regression_converges(rng):
    n, d = 300, 5
    X = (rng.normal(size=(n, d)) * 0.3).astype(np.float32)
    w_true = rng.normal(size=d) * 0.5
    lam = np.exp(X @ w_true)
    y = rng.poisson(lam).astype(np.float32)
    batch = LabeledBatch.build(X, y)
    vg = with_l2(lambda w: agg.value_and_gradient(losses.POISSON, w, batch), 1e-3)
    out = minimize_lbfgs(vg, jnp.zeros(d), OptimizerConfig(
        max_iterations=200, tolerance=1e-9))
    assert bool(out.converged)
    assert float(out.grad_norm) < 1e-3 * max(1.0, float(out.value))
    # Recovered rates close-ish to truth
    np.testing.assert_allclose(out.w, w_true, atol=0.3)


def test_owlqn_produces_sparsity_and_matches_scipy(rng):
    n, d = 250, 12
    X = rng.normal(size=(n, d)).astype(np.float32)
    w_true = np.zeros(d); w_true[:3] = [2.0, -1.5, 1.0]
    y = (X @ w_true + 0.1 * rng.normal(size=n)).astype(np.float32)
    batch = LabeledBatch.build(X, y)
    l1 = 25.0
    vg = lambda w: agg.value_and_gradient(losses.SQUARED, w, batch)
    l1w = jnp.full((d,), l1)
    out = minimize_owlqn(vg, jnp.zeros(d), l1w, OptimizerConfig(
        max_iterations=300, tolerance=1e-10))

    # scipy reference on the L1 problem via smooth reformulation (w = p - q).
    def f_np(wpq):
        p, q = wpq[:d], wpq[d:]
        w = p - q
        r = X.astype(np.float64) @ w - y
        return 0.5 * (r @ r) + l1 * (p.sum() + q.sum())

    def g_np(wpq):
        p, q = wpq[:d], wpq[d:]
        g = X.T.astype(np.float64) @ (X.astype(np.float64) @ (p - q) - y)
        return np.concatenate([g + l1, -g + l1])

    res = scipy.optimize.minimize(
        f_np, np.zeros(2 * d), jac=g_np, method="L-BFGS-B",
        bounds=[(0, None)] * (2 * d), options={"ftol": 1e-14, "gtol": 1e-10})
    w_ref = res.x[:d] - res.x[d:]
    np.testing.assert_allclose(out.w, w_ref, rtol=5e-2, atol=5e-2)
    # True zeros stay (numerically) zero.
    assert np.all(np.abs(np.asarray(out.w)[np.abs(w_ref) < 1e-8]) < 1e-6)


def test_owlqn_exact_zeros(rng):
    """OWL-QN's orthant projection must yield EXACT zeros, not small values."""
    n, d = 100, 8
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = (X[:, 0] * 2.0 + 0.05 * rng.normal(size=n)).astype(np.float32)
    batch = LabeledBatch.build(X, y)
    vg = lambda w: agg.value_and_gradient(losses.SQUARED, w, batch)
    out = minimize_owlqn(vg, jnp.zeros(d), jnp.full((d,), 40.0),
                         OptimizerConfig(max_iterations=200, tolerance=1e-10))
    w = np.asarray(out.w)
    assert np.sum(w == 0.0) >= d - 3  # hard zeros from projection


def test_vmapped_lbfgs_matches_individual(rng):
    """The random-effect regime: batched independent solves under vmap."""
    E, n, d = 5, 40, 4
    Xs = rng.normal(size=(E, n, d)).astype(np.float32)
    ws = rng.normal(size=(E, d)).astype(np.float32)
    ys = np.stack([
        (rng.uniform(size=n) < 1/(1+np.exp(-(Xs[i] @ ws[i])))).astype(np.float32)
        for i in range(E)])
    batches = LabeledBatch.build(Xs, ys,
                                 weights=np.ones((E, n), np.float32),
                                 offsets=np.zeros((E, n), np.float32))
    cfg = OptimizerConfig(max_iterations=100, tolerance=1e-8)

    def solve(bb, w0):
        vg = with_l2(lambda w: agg.value_and_gradient(losses.LOGISTIC, w, bb),
                     0.1)
        return minimize_lbfgs(vg, w0, cfg)

    outs = jax.jit(jax.vmap(solve))(batches, jnp.zeros((E, d)))
    for i in range(E):
        b_i = jax.tree.map(lambda a: a[i], batches)
        out_i = solve(b_i, jnp.zeros(d))
        np.testing.assert_allclose(outs.w[i], out_i.w, rtol=5e-3, atol=5e-3)
        assert bool(outs.converged[i])


def test_vmapped_tron_matches_individual(rng):
    E, n, d = 4, 30, 3
    Xs = rng.normal(size=(E, n, d)).astype(np.float32)
    ys = rng.normal(size=(E, n)).astype(np.float32)
    batches = LabeledBatch.build(Xs, ys,
                                 weights=np.ones((E, n), np.float32),
                                 offsets=np.zeros((E, n), np.float32))
    cfg = OptimizerConfig(max_iterations=50, tolerance=1e-9)

    def solve(bb, w0):
        vg = with_l2(lambda w: agg.value_and_gradient(losses.SQUARED, w, bb), 0.01)
        hvp = with_l2_hvp(
            lambda w, v: agg.hessian_vector(losses.SQUARED, w, v, bb), 0.01)
        return minimize_tron(vg, hvp, w0, cfg)

    outs = jax.jit(jax.vmap(solve))(batches, jnp.zeros((E, d)))
    for i in range(E):
        b_i = jax.tree.map(lambda a: a[i], batches)
        out_i = solve(b_i, jnp.zeros(d))
        np.testing.assert_allclose(outs.w[i], out_i.w, rtol=5e-3, atol=5e-3)


def test_history_tracking(rng):
    vg, _, _ = _quadratic(6, rng)
    out = minimize_lbfgs(vg, jnp.zeros(6), OptimizerConfig(
        max_iterations=50, tolerance=1e-10))
    it = int(out.iterations)
    vh = np.asarray(out.value_history)
    assert np.all(np.isfinite(vh[:it + 1]))
    assert np.all(np.isnan(vh[it + 1:]))
    # Values are non-increasing (monotone line search).
    assert np.all(np.diff(vh[:it + 1]) <= 1e-5)


def test_factory_dispatch_and_validation(rng):
    vg, hvp, _ = _quadratic(4, rng)
    cfg = OptimizerConfig(optimizer_type=OptimizerType.TRON, tolerance=1e-6)
    with pytest.raises(ValueError):
        optimize(vg, jnp.zeros(4), cfg)  # TRON without hvp
    out = optimize(vg, jnp.zeros(4), cfg, hvp=hvp)
    assert bool(out.converged)
    with pytest.raises(ValueError):
        optimize(vg, jnp.zeros(4),
                 OptimizerConfig(optimizer_type=OptimizerType.OWLQN))


def _ill_conditioned_quadratic(d, rng, cond=1e4):
    """SPD quadratic with eigenvalues log-spaced over ``cond``."""
    Q, _ = np.linalg.qr(rng.normal(size=(d, d)))
    eig = np.logspace(0, np.log10(cond), d)
    A = (Q * eig) @ Q.T
    b = rng.normal(size=d)
    A_j = jnp.asarray(A, jnp.float32)
    b_j = jnp.asarray(b, jnp.float32)

    def vg(w):
        return 0.5 * w @ A_j @ w - b_j @ w, A_j @ w - b_j

    def f_np(w):
        return 0.5 * w @ A @ w - b @ w

    def g_np(w):
        return A @ w - b

    return vg, f_np, g_np, np.linalg.solve(A, b)


def test_strong_wolfe_iteration_parity_vs_scipy(rng):
    """Strong-Wolfe L-BFGS should take a comparable number of iterations to
    scipy's L-BFGS-B on an ill-conditioned quadratic (breeze
    StrongWolfeLineSearch parity check: Armijo-only backtracking degrades
    badly here)."""
    d = 20
    vg, f_np, g_np, w_star = _ill_conditioned_quadratic(d, rng)
    ref = scipy.optimize.minimize(
        f_np, np.zeros(d), jac=g_np, method="L-BFGS-B",
        options={"gtol": 1e-8, "maxiter": 500})
    out = minimize_lbfgs(vg, jnp.zeros(d), OptimizerConfig(
        max_iterations=500, tolerance=1e-8))
    assert bool(out.converged)
    # f32 floor: compare against the f64 optimum loosely, iterations tightly.
    np.testing.assert_allclose(out.w, w_star, rtol=5e-2, atol=5e-2)
    assert int(out.iterations) <= 2 * ref.nit + 10


def test_strong_wolfe_conditions_hold_on_accepted_steps(rng):
    """The accepted step must satisfy BOTH strong-Wolfe conditions (which
    imply s^T y > 0) — checked directly on single optimizer steps from
    several random starts, conditions evaluated on the step s = w1 − w0
    (scale-invariant in the direction)."""
    d = 12
    vg, _, _, _ = _ill_conditioned_quadratic(d, rng)
    cfg = OptimizerConfig(max_iterations=1, tolerance=1e-12)
    c1, c2 = cfg.wolfe_c1, cfg.wolfe_c2
    for _ in range(5):
        w0 = jnp.asarray(rng.normal(size=d), jnp.float32)
        f0, g0 = vg(w0)
        out = minimize_lbfgs(vg, w0, cfg)
        s = np.asarray(out.w) - np.asarray(w0)
        assert np.linalg.norm(s) > 0  # a step was taken
        f1, g1 = vg(out.w)
        dg0 = float(np.asarray(g0) @ s)  # α·φ'(0) < 0
        dg1 = float(np.asarray(g1) @ s)  # α·φ'(α)
        assert dg0 < 0
        # Sufficient decrease: f(w1) ≤ f(w0) + c1·g0ᵀs  (small f32 slack).
        assert float(f1) <= float(f0) + c1 * dg0 + 1e-4 * abs(float(f0))
        # Strong curvature: |g1ᵀs| ≤ c2·|g0ᵀs| → implies sᵀy > 0.
        assert abs(dg1) <= c2 * abs(dg0) * (1 + 1e-3)
        assert float(np.asarray(g1 - g0) @ s) > 0  # sᵀy > 0


def test_wolfe_logistic_fewer_evals_than_tolerance_budget(rng):
    """The Wolfe search should not regress iteration counts on the standard
    logistic problem (guard against unit-step Armijo being replaced by
    something slower in the common well-scaled case)."""
    vg, _, w_ref, _ = _logistic_problem(rng)
    out = minimize_lbfgs(vg, jnp.zeros(8), OptimizerConfig(
        max_iterations=200, tolerance=1e-9))
    np.testing.assert_allclose(out.w, w_ref, rtol=2e-2, atol=2e-2)
    assert int(out.iterations) < 60
