"""Index-map tests: in-memory map, native mmap store (C++ and Python
readers), feature indexing CLI."""

import numpy as np
import pytest

from photon_ml_tpu.index.indexmap import (DefaultIndexMap, INTERCEPT_KEY,
                                          feature_key, load_index_map,
                                          split_key)
from photon_ml_tpu.index.native_store import (NativeIndexMap, _CppReader,
                                              _PyReader, build_store)


class TestDefaultIndexMap:
    def test_roundtrip(self, tmp_path):
        imap = DefaultIndexMap.from_keys(["b", "a", "c"], add_intercept=True)
        assert len(imap) == 4
        assert imap.get_index("a") == 0
        assert imap.get_index(INTERCEPT_KEY) >= 0
        assert imap.get_index("zzz") == -1
        assert imap.get_feature_name(imap.get_index("b")) == "b"
        path = str(tmp_path / "map.json")
        imap.save(path)
        loaded = load_index_map(path)
        assert len(loaded) == 4
        assert loaded.get_index("c") == imap.get_index("c")

    def test_feature_key_split(self):
        assert split_key(feature_key("n", "t")) == ("n", "t")
        assert split_key(feature_key("n")) == ("n", "")


@pytest.fixture(scope="module")
def store_path(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("pidx") / "feats.pidx")
    keys = [f"feat_{i:05d}\x01term{i % 7}" for i in range(5000)]
    build_store(keys, path)
    return path, keys


class TestNativeStore:
    @pytest.mark.parametrize("reader_cls", [_CppReader, _PyReader])
    def test_readers_agree(self, store_path, reader_cls):
        path, keys = store_path
        r = reader_cls(path)
        try:
            assert r.size == len(keys)
            rng = np.random.default_rng(0)
            for i in map(int, rng.integers(0, len(keys), 200)):
                assert r.get(keys[i].encode()) == i
                assert r.name(i) == keys[i].encode()
            assert r.get(b"missing-key") == -1
            assert r.name(len(keys)) is None
        finally:
            r.close()

    def test_native_index_map(self, store_path):
        path, keys = store_path
        imap = NativeIndexMap(path)
        assert len(imap) == len(keys)
        assert imap.get_index(keys[17]) == 17
        assert imap.get_feature_name(17) == keys[17]
        assert keys[17] in imap
        assert "nope" not in imap
        assert load_index_map(path).get_index(keys[3]) == 3
        imap.close()

    def test_duplicate_keys_rejected(self, tmp_path):
        with pytest.raises(ValueError):
            build_store(["a", "a"], str(tmp_path / "dup.pidx"))

    def test_empty_store(self, tmp_path):
        path = str(tmp_path / "empty.pidx")
        build_store([], path)
        imap = NativeIndexMap(path)
        assert len(imap) == 0
        assert imap.get_index("x") == -1


class TestFeatureIndexCli:
    def _write_data(self, tmp_path):
        from photon_ml_tpu.avro import schemas
        from photon_ml_tpu.avro.container import write_records
        recs = [{"name": "ex", "label": 0.0,
                 "features": [{"name": f"g{i % 5}", "term": "",
                               "value": 1.0}],
                 "metadataMap": None}
                for i in range(20)]
        path = str(tmp_path / "train.avro")
        write_records(path, schemas.TRAINING_EXAMPLE_AVRO, recs)
        return path

    @pytest.mark.parametrize("fmt", ["pidx", "json"])
    def test_end_to_end(self, tmp_path, fmt):
        from photon_ml_tpu.cli.feature_index import build_parser, run
        data = self._write_data(tmp_path)
        out = str(tmp_path / "index")
        args = build_parser().parse_args(
            ["--data", data, "--output", out,
             "--shard", "global:features", "--format", fmt])
        summary = run(args)
        assert summary["num_records"] == 20
        assert summary["shards"]["global"]["num_features"] == 6  # 5+intercept
        imap = load_index_map(summary["shards"]["global"]["path"])
        assert imap.get_index("g3") >= 0
        assert imap.get_index(INTERCEPT_KEY) >= 0
