"""Loss derivative checks: closed forms vs jax.grad vs finite differences.

Mirrors the reference's loss-function unit tests (photon-lib
``function/glm/*LossFunctionTest`` — derivative checks via finite
differences, SURVEY.md §4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.ops import losses


ALL_LOSSES = [losses.LOGISTIC, losses.SQUARED, losses.POISSON, losses.SMOOTHED_HINGE]


def _labels_for(loss, rng, n):
    if loss.name == "squared":
        return rng.normal(size=n).astype(np.float32)
    if loss.name == "poisson":
        return rng.poisson(3.0, size=n).astype(np.float32)
    return rng.integers(0, 2, size=n).astype(np.float32)


@pytest.mark.parametrize("loss", ALL_LOSSES, ids=lambda l: l.name)
def test_first_derivative_matches_autodiff(loss, rng):
    z = jnp.asarray(rng.normal(size=64) * 2.0, dtype=jnp.float32)
    y = jnp.asarray(_labels_for(loss, rng, 64))
    _, dl = loss.loss_and_dz(z, y)
    dl_ad = jax.vmap(jax.grad(lambda zz, yy: loss.loss(zz, yy)))(z, y)
    np.testing.assert_allclose(dl, dl_ad, rtol=1e-3, atol=1e-4)


@pytest.mark.parametrize("loss", ALL_LOSSES, ids=lambda l: l.name)
def test_second_derivative_matches_autodiff(loss, rng):
    z = jnp.asarray(rng.normal(size=64) * 2.0, dtype=jnp.float32)
    y = jnp.asarray(_labels_for(loss, rng, 64))
    # Smoothed hinge's d2 is discontinuous at t in {0,1}; keep away from kinks.
    if loss.name == "smoothed_hinge":
        z = z + 0.05
    d2 = loss.d2z(z, y)
    d2_ad = jax.vmap(jax.grad(jax.grad(lambda zz, yy: loss.loss(zz, yy))))(z, y)
    np.testing.assert_allclose(d2, d2_ad, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("loss", ALL_LOSSES, ids=lambda l: l.name)
def test_first_derivative_matches_finite_difference(loss, rng):
    z = rng.normal(size=32).astype(np.float64) * 1.5
    y = np.asarray(_labels_for(loss, rng, 32), dtype=np.float64)
    eps = 1e-3  # f32 compute: eps must sit well above float32 resolution
    lp = np.asarray(loss.loss(jnp.asarray(z + eps, jnp.float32), jnp.asarray(y, jnp.float32)), np.float64)
    lm = np.asarray(loss.loss(jnp.asarray(z - eps, jnp.float32), jnp.asarray(y, jnp.float32)), np.float64)
    fd = (lp - lm) / (2 * eps)
    _, dl = loss.loss_and_dz(jnp.asarray(z, jnp.float32), jnp.asarray(y, jnp.float32))
    np.testing.assert_allclose(np.asarray(dl), fd, rtol=5e-3, atol=5e-3)


def test_logistic_known_values():
    # At margin 0: l = log 2 regardless of label; dl = 0.5 - y.
    z = jnp.zeros((2,))
    y = jnp.asarray([0.0, 1.0])
    l, dl = losses.LOGISTIC.loss_and_dz(z, y)
    np.testing.assert_allclose(l, np.log(2.0), rtol=1e-6)
    np.testing.assert_allclose(dl, [0.5, -0.5], rtol=1e-6)


def test_logistic_extreme_margins_stable():
    z = jnp.asarray([80.0, -80.0, 500.0, -500.0])
    y = jnp.asarray([1.0, 0.0, 0.0, 1.0])
    l, dl = losses.LOGISTIC.loss_and_dz(z, y)
    assert np.all(np.isfinite(l)) and np.all(np.isfinite(dl))


def test_smoothed_hinge_piecewise_values():
    # label 1 → y=+1, t = z.
    z = jnp.asarray([-1.0, 0.5, 2.0])
    y = jnp.ones((3,))
    l, dl = losses.SMOOTHED_HINGE.loss_and_dz(z, y)
    np.testing.assert_allclose(l, [1.5, 0.125, 0.0], rtol=1e-6)
    np.testing.assert_allclose(dl, [-1.0, -0.5, 0.0], rtol=1e-6)


def test_poisson_matches_nll():
    z = jnp.asarray([0.1, -0.3, 1.2])
    y = jnp.asarray([1.0, 0.0, 4.0])
    l, dl = losses.POISSON.loss_and_dz(z, y)
    np.testing.assert_allclose(l, np.exp(z) - y * np.asarray(z), rtol=1e-5)
    np.testing.assert_allclose(dl, np.exp(z) - y, rtol=1e-5)


def test_task_mapping():
    from photon_ml_tpu.types import TaskType
    assert losses.loss_for_task(TaskType.LOGISTIC_REGRESSION) is losses.LOGISTIC
    assert losses.loss_for_task("LINEAR_REGRESSION") is losses.SQUARED
    assert losses.loss_for_task(TaskType.POISSON_REGRESSION) is losses.POISSON
    assert (losses.loss_for_task(TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM)
            is losses.SMOOTHED_HINGE)
