"""Two-process multi-host integration: the DCN seam carrying real traffic.

Reference parity: photon-test-utils ``SparkTestUtils.scala`` runs the REAL
distributed code paths in local mode (SURVEY §4); this extends that
discipline to the process dimension — two OS processes, four virtual CPU
devices each, joined by ``jax.distributed.initialize`` on a localhost
coordinator into one 8-device world. Everything the multi-host story
claims is asserted against actual execution:

- both ranks see 8 global / 4 local devices and finish rank-consistent
  (identical best-model metrics from the same SPMD programs);
- only rank 0 writes shared artifacts (model dir, summary, checkpoints);
- a killed run restarts with ``--resume`` and completes from the
  checkpoint (the lineage-free recovery model of parallel/mesh.py).

These tests spawn subprocesses with their own JAX runtime (the parent's
backend is irrelevant) and are the slowest in the suite (~1-2 min).
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

pytestmark = pytest.mark.slow

from photon_ml_tpu.data import synthetic
from photon_ml_tpu.data.game_data import from_synthetic
from photon_ml_tpu.data.io import save_game_dataset

_WRAPPER = """
import json, os, sys
sys.argv = sys.argv[:1] + sys.argv[2:]
out_dir = sys.argv[sys.argv.index("--output-dir") + 1]
from photon_ml_tpu.cli import game_train
summary = game_train.run(game_train.build_parser().parse_args(sys.argv[1:]))
import jax
info = {
    "rank": jax.process_index(),
    "process_count": jax.process_count(),
    "global_devices": jax.device_count(),
    "local_devices": jax.local_device_count(),
    "metrics": summary["best_metrics"],
}
with open(os.path.join(out_dir, f"rankinfo-{jax.process_index()}.json"),
          "w") as f:
    json.dump(info, f)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn(rank: int, port: int, wrapper: str, cli_args: list[str],
           log_path: str) -> subprocess.Popen:
    """Launch one rank. Output goes to a FILE, never a pipe: XLA's CPU AOT
    warnings alone overflow a 64 KB pipe buffer, and an undrained pipe
    blocks the child mid-training (observed as multi-minute stalls)."""
    env = {k: v for k, v in os.environ.items()
           if k not in ("PALLAS_AXON_POOL_IPS", "XLA_FLAGS",
                        "JAX_PLATFORMS")}
    repo_root = os.path.dirname(os.path.dirname(__file__))
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
        "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
        "JAX_NUM_PROCESSES": "2",
        "JAX_PROCESS_ID": str(rank),
        "PYTHONPATH": repo_root + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else ""),
    })
    log = open(log_path, "w")
    p = subprocess.Popen(
        [sys.executable, wrapper, f"rank{rank}"] + cli_args,
        env=env, cwd=repo_root, stdout=log, stderr=subprocess.STDOUT,
        text=True)
    p._log_path = log_path
    p._log_file = log
    return p


def _log_tail(p: subprocess.Popen, n: int = 500_000) -> str:
    p._log_file.close()
    with open(p._log_path) as f:
        return f.read()[-n:]


def _write_inputs(tmp_path):
    rng = np.random.default_rng(0)
    syn = synthetic.game_data(rng, n=512, d_global=6,
                              re_specs={"userId": (8, 3)})
    ds = from_synthetic(syn)
    train_dir = str(tmp_path / "train")
    save_game_dataset(ds, train_dir)
    wrapper = str(tmp_path / "mp_wrapper.py")
    with open(wrapper, "w") as f:
        f.write(_WRAPPER)
    return train_dir, wrapper


def _cli_args(train_dir: str, out: str, iterations: int = 1) -> list[str]:
    return [
        "--train", train_dir, "--validation", train_dir,
        "--coordinate", "name=fixed,type=fixed,shard=global",
        # BOTH random-effect representations cross the jax.distributed
        # seam: the dense W-table path and the subspace (projected-space)
        # path over the same shard.
        "--coordinate", "name=per-user,type=random,shard=re_userId,"
                        "re=userId",
        "--coordinate", "name=per-user-sub,type=random,shard=re_userId,"
                        "re=userId,projector=INDEX_MAP,subspace=true",
        "--update-sequence", "fixed,per-user,per-user-sub",
        "--iterations", str(iterations),
        "--evaluators", "AUC",
        "--opt-config", "fixed:optimizer=LBFGS,reg=L2,reg_weight=1.0",
        "--opt-config", "per-user:optimizer=LBFGS,reg=L2,reg_weight=1.0",
        "--opt-config",
        "per-user-sub:optimizer=LBFGS,reg=L2,reg_weight=1.0",
        "--output-dir", out,
        "--distributed",
    ]


def _run_pair(tmp_path, port, wrapper, cli_args, tag="run", timeout=420):
    procs = [_spawn(r, port, wrapper, cli_args,
                    str(tmp_path / f"{tag}-rank{r}.log")) for r in (0, 1)]
    deadline = time.time() + timeout
    try:
        for p in procs:
            p.wait(timeout=max(5.0, deadline - time.time()))
    except subprocess.TimeoutExpired:
        for q in procs:
            q.kill()
            q.wait(timeout=30)
        pytest.fail("multi-process run timed out; rank logs:\n"
                    + "\n=== next rank ===\n".join(
                        _log_tail(q, 3000) for q in procs))
    return procs, [_log_tail(p) for p in procs]


def test_two_process_training_agrees_and_rank0_writes(tmp_path):
    train_dir, wrapper = _write_inputs(tmp_path)
    out = str(tmp_path / "out")
    procs, outs = _run_pair(tmp_path, _free_port(), wrapper,
                            _cli_args(train_dir, out))
    for p, o in zip(procs, outs):
        assert p.returncode == 0, f"rank failed:\n{o[-4000:]}"

    infos = {}
    for r in (0, 1):
        with open(os.path.join(out, f"rankinfo-{r}.json")) as f:
            infos[r] = json.load(f)
    for r in (0, 1):
        assert infos[r]["process_count"] == 2
        assert infos[r]["global_devices"] == 8
        assert infos[r]["local_devices"] == 4
    # Rank agreement: the same SPMD programs must yield the same model.
    a, b = infos[0]["metrics"]["AUC"], infos[1]["metrics"]["AUC"]
    assert abs(a - b) < 1e-6, (a, b)
    assert a > 0.6
    # Rank-0-only writes: model + summary exist exactly once (the output
    # dir is the shared filesystem both ranks point at).
    assert os.path.isdir(os.path.join(out, "best"))
    assert os.path.exists(os.path.join(out, "summary.json"))


def _poll_for(path, procs, timeout=420):
    """Wait for ``path`` to appear; returns once it exists or when every
    process has exited (whichever first)."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        if os.path.exists(path):
            return True
        if all(p.poll() is not None for p in procs):
            return os.path.exists(path)
        time.sleep(0.5)
    return os.path.exists(path)


def test_two_process_kill_then_resume(tmp_path):
    train_dir, wrapper = _write_inputs(tmp_path)
    out = str(tmp_path / "out")
    ckpt_state = os.path.join(out, "checkpoints", "grid-0", "state.json")
    cli = _cli_args(train_dir, out, iterations=3)
    port = _free_port()
    procs = [_spawn(r, port, wrapper, cli,
                    str(tmp_path / f"phase1-rank{r}.log")) for r in (0, 1)]
    # Wait for the first per-coordinate checkpoint commit, then kill both
    # ranks hard (the lost-host failure model). On a loaded single-core
    # host the tiny run may finish before the poll catches it mid-flight —
    # then the relaunch below still exercises --resume from the completed
    # checkpoint state (and asserts it was read, not recomputed).
    landed = _poll_for(ckpt_state, procs)
    if not landed:
        pytest.fail("no checkpoint ever landed; rank0 output:\n"
                    + _log_tail(procs[0], 3000))
    for p in procs:
        if p.poll() is None:
            p.send_signal(signal.SIGKILL)
    for p in procs:
        p.wait(timeout=120)
    assert os.path.exists(ckpt_state)
    with open(ckpt_state) as f:
        state_before = json.load(f)

    # Relaunch with --resume on a fresh coordinator port.
    procs, outs = _run_pair(tmp_path, _free_port(), wrapper,
                            cli + ["--resume"], tag="resume")
    for p, o in zip(procs, outs):
        assert p.returncode == 0, f"resume rank failed:\n{o[-4000:]}"
    with open(os.path.join(out, "rankinfo-0.json")) as f:
        info = json.load(f)
    assert info["metrics"]["AUC"] > 0.6
    assert os.path.isdir(os.path.join(out, "best"))
    # The relaunch actually CONSUMED the checkpoint: it finished all
    # 3 iterations x 3 coordinates, and trained exactly the steps the
    # pre-kill run had not yet committed (each training step logs one
    # "CD iter" line; resumed steps are skipped before training).
    assert state_before.get("done_steps", 0) >= 1, state_before
    with open(ckpt_state) as f:
        state_after = json.load(f)
    assert state_after["complete"] and state_after["done_steps"] == 9, \
        state_after
    trained_after_resume = outs[0].count("CD iter")
    assert trained_after_resume == 9 - state_before["done_steps"], (
        trained_after_resume, state_before["done_steps"])
