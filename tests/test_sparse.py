"""Sparse (Criteo-path) tests: ELL layout, sparse aggregators vs dense,
data- and feature-sharded objectives vs unsharded, end-to-end sparse fits.

Mirrors the reference's DistributedGLMLossFunctionIntegTest equivalence
(distributed grad == local grad) for the sparse seam.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.data.batch import LabeledBatch
from photon_ml_tpu.data.sparse import (SparseBatch, from_csr, from_libsvm,
                                       synthetic_sparse)
from photon_ml_tpu.ops import aggregators as dagg
from photon_ml_tpu.ops import sparse_aggregators as sagg
from photon_ml_tpu.ops.losses import get_loss
from photon_ml_tpu.optim import (OptimizerConfig, OptimizerType,
                                 RegularizationContext, RegularizationType)
from photon_ml_tpu.optim.problem import GLMOptimizationConfiguration
from photon_ml_tpu.parallel import sparse_objective as sobj
from photon_ml_tpu.parallel import sparse_problem
from photon_ml_tpu.parallel.mesh import make_mesh


def _csr_data(n=64, d=20, seed=0):
    rng = np.random.default_rng(seed)
    indptr = [0]
    indices, values = [], []
    for _ in range(n):
        k = int(rng.integers(1, 6))
        cols = rng.choice(d, size=k, replace=False)
        cols.sort()
        indices.extend(cols)
        values.extend(rng.normal(size=k))
        indptr.append(len(indices))
    labels = rng.integers(0, 2, size=n).astype(np.float32)
    weights = rng.uniform(0.5, 2.0, size=n).astype(np.float32)
    offsets = rng.normal(size=n).astype(np.float32) * 0.1
    return (np.asarray(indptr), np.asarray(indices),
            np.asarray(values, np.float32), labels, weights, offsets, d)


def _dense_twin(sp: SparseBatch) -> LabeledBatch:
    X = np.zeros((sp.num_rows, sp.num_features), np.float32)
    idx = np.asarray(sp.indices)
    val = np.asarray(sp.values)
    for i in range(sp.num_rows):
        for k in range(sp.max_nnz):
            j = idx[i, k]
            if j < sp.num_features:
                X[i, j] += val[i, k]
    return LabeledBatch(features=jnp.asarray(X),
                        labels=jnp.asarray(sp.labels),
                        weights=jnp.asarray(sp.weights),
                        offsets=jnp.asarray(sp.offsets))


class TestEll:
    def test_from_csr_matches_dense(self):
        indptr, indices, values, labels, weights, offsets, d = _csr_data()
        sp = from_csr(indptr, indices, values, labels, d,
                      weights=weights, offsets=offsets)
        dense = _dense_twin(sp)
        # every nonzero survived
        assert np.asarray(sp.values).sum() == pytest.approx(values.sum(),
                                                            abs=1e-4)
        assert dense.features.shape == (64, 20)

    def test_overflow_keeps_largest(self):
        indptr = np.array([0, 4])
        indices = np.array([0, 1, 2, 3])
        values = np.array([0.1, -5.0, 3.0, 0.2], np.float32)
        sp = from_csr(indptr, indices, values, np.array([1.0]), 10,
                      max_nnz=2)
        kept = set(np.asarray(sp.indices)[0].tolist())
        assert kept == {1, 2}

    def test_pad_rows(self):
        indptr, indices, values, labels, weights, offsets, d = _csr_data()
        sp = from_csr(indptr, indices, values, labels, d)
        padded = sp.pad_to(100)
        assert padded.num_rows == 100
        assert np.all(np.asarray(padded.weights)[64:] == 0.0)
        assert np.all(np.asarray(padded.indices)[64:] == d)


@pytest.mark.parametrize("loss_name", ["logistic", "squared", "poisson"])
class TestSparseAggregators:
    def _setup(self, loss_name):
        indptr, indices, values, labels, weights, offsets, d = _csr_data()
        if loss_name == "poisson":
            labels = np.abs(labels) + 1.0
        sp = from_csr(indptr, indices, values, labels, d,
                      weights=weights, offsets=offsets)
        dense = _dense_twin(sp)
        rng = np.random.default_rng(7)
        w = jnp.asarray(rng.normal(size=d).astype(np.float32) * 0.3)
        return get_loss(loss_name), sp, dense, w

    def test_value_and_gradient_matches_dense(self, loss_name):
        loss, sp, dense, w = self._setup(loss_name)
        v_s, g_s = sagg.value_and_gradient(loss, w, sp)
        v_d, g_d = dagg.value_and_gradient(loss, w, dense)
        np.testing.assert_allclose(v_s, v_d, rtol=1e-4)
        np.testing.assert_allclose(g_s, g_d, rtol=1e-3, atol=1e-4)

    def test_hvp_matches_dense(self, loss_name):
        loss, sp, dense, w = self._setup(loss_name)
        v = jnp.asarray(np.random.default_rng(3).normal(
            size=w.shape).astype(np.float32))
        np.testing.assert_allclose(
            sagg.hessian_vector(loss, w, v, sp),
            dagg.hessian_vector(loss, w, v, dense), rtol=1e-3, atol=1e-4)

    def test_hessian_diagonal_matches_dense(self, loss_name):
        loss, sp, dense, w = self._setup(loss_name)
        np.testing.assert_allclose(
            sagg.hessian_diagonal(loss, w, sp),
            dagg.hessian_diagonal(loss, w, dense), rtol=1e-3, atol=1e-4)


class TestShardedSparseObjective:
    """Sharded == unsharded (the psum-equivalence tests, sparse edition)."""

    def _setup(self):
        indptr, indices, values, labels, weights, offsets, d = _csr_data(
            n=96, d=24)
        sp = from_csr(indptr, indices, values, labels, d,
                      weights=weights, offsets=offsets)
        loss = get_loss("logistic")
        rng = np.random.default_rng(11)
        w = jnp.asarray(rng.normal(size=d).astype(np.float32) * 0.2)
        v_ref, g_ref = sagg.value_and_gradient(loss, w, sp)
        return sp, loss, w, v_ref, g_ref

    def test_data_parallel(self):
        sp, loss, w, v_ref, g_ref = self._setup()
        mesh = make_mesh(num_data=8)
        batch = sparse_problem.shard_sparse_batch(sp, mesh)
        vg = sobj.make_value_and_gradient(loss, mesh, batch)
        v, g = vg(w)
        np.testing.assert_allclose(v, v_ref, rtol=1e-4)
        np.testing.assert_allclose(g, g_ref, rtol=1e-3, atol=1e-5)

    def test_feature_sharded(self):
        sp, loss, w, v_ref, g_ref = self._setup()
        mesh = make_mesh(num_data=2, num_model=4)
        batch = sparse_problem.shard_sparse_batch(sp, mesh)
        vg = sobj.make_value_and_gradient(loss, mesh, batch,
                                          feature_sharded=True)
        v, g = vg(w)  # d=24 divides 4
        np.testing.assert_allclose(v, v_ref, rtol=1e-4)
        np.testing.assert_allclose(g, g_ref, rtol=1e-3, atol=1e-5)

    def test_feature_sharded_hvp_and_diag(self):
        sp, loss, w, _, _ = self._setup()
        mesh = make_mesh(num_data=2, num_model=4)
        batch = sparse_problem.shard_sparse_batch(sp, mesh)
        vvec = jnp.asarray(np.random.default_rng(5).normal(
            size=w.shape).astype(np.float32))
        np.testing.assert_allclose(
            sobj.make_hvp(loss, mesh, batch, True)(w, vvec),
            sagg.hessian_vector(loss, w, vvec, sp), rtol=1e-3, atol=1e-5)
        np.testing.assert_allclose(
            sobj.make_hessian_diagonal(loss, mesh, batch, True)(w),
            sagg.hessian_diagonal(loss, w, sp), rtol=1e-3, atol=1e-5)


class TestSparseProblem:
    def test_lbfgs_recovers_weights(self):
        batch, w_true = synthetic_sparse(4000, 64, 8, seed=0, noise=0.05,
                                         zipf=False)
        mesh = make_mesh(num_data=8)
        coef, result = sparse_problem.run(
            get_loss("logistic"), batch, mesh,
            GLMOptimizationConfiguration(
                optimizer=OptimizerConfig(
                    optimizer_type=OptimizerType.LBFGS, max_iterations=200,
                    tolerance=1e-7),
                regularization=RegularizationContext(
                    RegularizationType.L2, 1e-3)))
        w = np.asarray(coef.means)
        corr = np.corrcoef(w, w_true)[0, 1]
        assert corr > 0.95, f"weight correlation too low: {corr}"

    def test_feature_sharded_fit_matches_replicated(self):
        batch, _ = synthetic_sparse(1000, 30, 6, seed=2)
        cfg = GLMOptimizationConfiguration(
            optimizer=OptimizerConfig(optimizer_type=OptimizerType.LBFGS,
                                      max_iterations=50, tolerance=1e-8),
            regularization=RegularizationContext(RegularizationType.L2,
                                                 1e-2))
        coef_rep, _ = sparse_problem.run(
            get_loss("logistic"), batch, make_mesh(num_data=8), cfg)
        coef_fs, _ = sparse_problem.run(
            get_loss("logistic"), batch, make_mesh(num_data=2, num_model=4),
            cfg, feature_sharded=True)  # d=30 pads to 32
        np.testing.assert_allclose(np.asarray(coef_rep.means),
                                   np.asarray(coef_fs.means),
                                   rtol=1e-2, atol=1e-3)

    def test_owlqn_sparse_l1(self):
        batch, w_true = synthetic_sparse(2000, 40, 6, seed=3, noise=0.05)
        coef, _ = sparse_problem.run(
            get_loss("logistic"), batch, make_mesh(num_data=8),
            GLMOptimizationConfiguration(
                optimizer=OptimizerConfig(
                    optimizer_type=OptimizerType.OWLQN, max_iterations=150,
                    tolerance=1e-7),
                regularization=RegularizationContext(
                    RegularizationType.L1, 10.0)))
        w = np.asarray(coef.means)
        # L1 at this strength must produce exact zeros (orthant projection)
        assert np.sum(w == 0.0) >= 20

    def test_tron_sparse(self):
        batch, _ = synthetic_sparse(1500, 25, 5, task="linear", seed=4)
        coef, result = sparse_problem.run(
            get_loss("squared"), batch, make_mesh(num_data=8),
            GLMOptimizationConfiguration(
                optimizer=OptimizerConfig(
                    optimizer_type=OptimizerType.TRON, max_iterations=60,
                    tolerance=1e-8),
                regularization=RegularizationContext(
                    RegularizationType.L2, 1e-3)))
        # cross-check against LBFGS
        coef2, _ = sparse_problem.run(
            get_loss("squared"), batch, make_mesh(num_data=8),
            GLMOptimizationConfiguration(
                optimizer=OptimizerConfig(
                    optimizer_type=OptimizerType.LBFGS, max_iterations=200,
                    tolerance=1e-9),
                regularization=RegularizationContext(
                    RegularizationType.L2, 1e-3)))
        np.testing.assert_allclose(np.asarray(coef.means),
                                   np.asarray(coef2.means),
                                   rtol=5e-2, atol=5e-3)

    def test_simple_variance(self):
        batch, _ = synthetic_sparse(500, 20, 4, seed=5)
        from photon_ml_tpu.optim.problem import VarianceComputationType
        coef, _ = sparse_problem.run(
            get_loss("logistic"), batch, make_mesh(num_data=8),
            GLMOptimizationConfiguration(
                optimizer=OptimizerConfig(
                    optimizer_type=OptimizerType.LBFGS, max_iterations=50),
                regularization=RegularizationContext(
                    RegularizationType.L2, 1e-2),
                variance_computation=VarianceComputationType.SIMPLE))
        assert coef.variances is not None
        assert coef.variances.shape == (20,)
        assert np.all(np.asarray(coef.variances) > 0.0)


def test_from_libsvm_sparse(tmp_path):
    from photon_ml_tpu.data.libsvm import read_libsvm, write_libsvm
    rng = np.random.default_rng(0)
    X = (rng.random((30, 12)) < 0.3) * rng.normal(size=(30, 12))
    y = rng.integers(0, 2, 30).astype(np.float32)
    path = str(tmp_path / "data.libsvm")
    write_libsvm(path, X.astype(np.float32), y)
    data = read_libsvm(path, num_features=12, dense=False)
    sp = from_libsvm(data)
    dense = _dense_twin(sp)
    np.testing.assert_allclose(np.asarray(dense.features), X, atol=1e-5)
