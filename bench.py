"""Benchmark: GLM gradient-step throughput on the current accelerator.

Measures the primary BASELINE.json metric — **GLM gradient-step
samples/sec/chip** on the fixed-effect data-parallel path (the reference's
``DistributedGLMLossFunction.treeAggregate`` hot loop, here one fused
jit-compiled psum objective) — plus the GAME coordinate-descent iteration
time as a secondary record.

``vs_baseline``: the reference publishes no numbers (BASELINE.md), so the
ratio is against an in-process numpy CPU implementation of the same fused
value+gradient computation — a stand-in for the reference's single-executor
per-partition aggregator loop on comparable hardware.

Prints ONE JSON line.
"""

import json
import time

import numpy as np


def _numpy_value_grad(X, y, w):
    z = X @ w
    p = 1.0 / (1.0 + np.exp(-z))
    l = np.logaddexp(0.0, z) - y * z
    r = p - y
    return l.sum(), X.T @ r


def bench_gradient_step(n=1 << 19, d=256, iters=30, warmup=5):
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.data.batch import LabeledBatch
    from photon_ml_tpu.ops import aggregators as agg
    from photon_ml_tpu.ops import losses

    rng = np.random.default_rng(0)
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = rng.integers(0, 2, size=n).astype(np.float32)
    batch = LabeledBatch.build(X, y)
    batch = jax.device_put(batch)
    w = jnp.zeros((d,), jnp.float32)

    step = jax.jit(lambda ww, bb: agg.value_and_gradient(
        losses.LOGISTIC, ww, bb))
    v, g = step(w, batch)
    jax.block_until_ready((v, g))
    for _ in range(warmup):
        jax.block_until_ready(step(w, batch))
    t0 = time.perf_counter()
    for _ in range(iters):
        v, g = step(w, batch)
    jax.block_until_ready((v, g))
    dt = (time.perf_counter() - t0) / iters
    samples_per_sec = n / dt

    # CPU numpy baseline (subsampled for time, scaled):
    n_cpu = min(n, 1 << 16)
    Xc, yc = X[:n_cpu], y[:n_cpu]
    wc = np.zeros(d, np.float32)
    _numpy_value_grad(Xc, yc, wc)
    t0 = time.perf_counter()
    reps = 3
    for _ in range(reps):
        _numpy_value_grad(Xc, yc, wc)
    cpu_dt = (time.perf_counter() - t0) / reps
    cpu_samples_per_sec = n_cpu / cpu_dt
    return samples_per_sec, cpu_samples_per_sec


def bench_game_iteration():
    """Secondary: one GAME coordinate-descent sweep (fixed + per-user)."""
    import jax

    from photon_ml_tpu.data import synthetic
    from photon_ml_tpu.data.game_data import from_synthetic
    from photon_ml_tpu.game import descent
    from photon_ml_tpu.game.coordinates import (FixedEffectCoordinate,
                                                RandomEffectCoordinate)
    from photon_ml_tpu.ops import losses
    from photon_ml_tpu.optim import OptimizerConfig
    from photon_ml_tpu.optim.problem import GLMOptimizationConfiguration
    from photon_ml_tpu.optim.regularization import (RegularizationContext,
                                                    RegularizationType)
    from photon_ml_tpu.parallel.mesh import make_mesh
    from photon_ml_tpu.types import TaskType

    rng = np.random.default_rng(0)
    ds = from_synthetic(synthetic.game_data(
        rng, n=100_000, d_global=32,
        re_specs={"userId": (2000, 8), "itemId": (500, 8)}))
    mesh = make_mesh()
    cfg = GLMOptimizationConfiguration(
        optimizer=OptimizerConfig(max_iterations=25, tolerance=1e-7),
        regularization=RegularizationContext(RegularizationType.L2, 1.0))
    coords = {
        "fixed": FixedEffectCoordinate(ds, "global", losses.LOGISTIC, cfg,
                                       mesh),
        "per-user": RandomEffectCoordinate(ds, "userId", "re_userId",
                                           losses.LOGISTIC, cfg, mesh),
        "per-item": RandomEffectCoordinate(ds, "itemId", "re_itemId",
                                           losses.LOGISTIC, cfg, mesh),
    }
    cd = descent.CoordinateDescentConfig(["fixed", "per-user", "per-item"],
                                         iterations=1)
    # Warm-up sweep compiles everything; the timed sweep is steady-state.
    descent.run(TaskType.LOGISTIC_REGRESSION, coords, cd)
    t0 = time.perf_counter()
    descent.run(TaskType.LOGISTIC_REGRESSION, coords, cd)
    return time.perf_counter() - t0


def main():
    samples_per_sec, cpu_baseline = bench_gradient_step()
    game_iter_s = bench_game_iteration()
    print(json.dumps({
        "metric": "glm_gradient_step_samples_per_sec_per_chip",
        "value": round(samples_per_sec),
        "unit": "samples/sec/chip",
        "vs_baseline": round(samples_per_sec / cpu_baseline, 3),
        "secondary": {
            "game_cd_iteration_seconds": round(game_iter_s, 3),
            "cpu_numpy_baseline_samples_per_sec": round(cpu_baseline),
        },
    }))


if __name__ == "__main__":
    main()
