"""Benchmark: GLM training throughput on the current accelerator.

Primary BASELINE.json metric — **GLM gradient-step samples/sec/chip** on the
fixed-effect data-parallel path (the reference's
``DistributedGLMLossFunction.treeAggregate`` hot loop as one fused
jit-compiled objective) — plus, as secondaries: a FULL jitted L-BFGS
iteration (value+grad + two-loop + strong-Wolfe line search) and TRON
iteration with donated buffers, the sparse/Criteo gradient step (1M-feature
ELL), the Pallas-vs-XLA scatter comparison, and the GAME coordinate-descent
sweep.

Measurement discipline: on this environment the device is behind an async
tunnel where ``block_until_ready`` can return before execution finishes
(round-1 reported 21e9 samples/s ⇒ an impossible ~21 TB/s effective HBM
rate — that artifact). Every timing here therefore chains iterations
through a data dependency and forces ONE host read-back at the end, at two
different iteration counts; the reported per-step time is the SLOPE
(t_big − t_small)/(iters_big − iters_small), which cancels both the
constant RPC overhead and the dispatch cost. Achieved FLOP/s and bytes/s
are printed next to samples/sec so the numbers can be audited against peak
(v5e: ~197 bf16 TFLOP/s, ~0.8 TB/s HBM).

``vs_baseline``: the reference publishes no numbers (BASELINE.md), so the
ratio is against an in-process numpy CPU implementation of the same fused
value+gradient pass.

Prints ONE JSON line.
"""

import json
import os
import sys
import time

import numpy as np

from photon_ml_tpu.utils.compile_cache import enable_compilation_cache

enable_compilation_cache()


def _progress(msg: str) -> None:
    """Stderr progress marker (stdout stays one JSON line). Compiles over
    the remote tunnel can take minutes each; without these markers a slow
    run is indistinguishable from a hung one."""
    print(f"[bench {time.strftime('%H:%M:%S')}] {msg}", file=sys.stderr,
          flush=True)


# --- host-line validity gating (BENCH r3–r5: a recurring ~4× builder-vs-
# driver spread on host-staging lines was surfaced but never DETECTED —
# the 1.5×-spread contention guard catches jitter, not sustained load).
# Two gates, both recorded per line so a committed JSON self-describes:
#   * load average at measurement start above LOAD_GATE (the r05 driver
#     capture ran at 0.83 on this 1-core box and measured 4× slow);
#   * the calibration micro-workload (run once per fresh-host suite)
#     exceeding CALIBRATION_GATE × the committed clean-box reference —
#     sustained background load that a momentary loadavg can miss.
# An invalid line still reports its number, but carries ``<key>_valid:
# false`` + the reason; check_bench_regression treats it as
# reported-only and render_perf_docs drops it from doc ranges.

# Min-of-5 of _calibration_workload on this 1-core CI box, measured
# near-idle (load ~0.2). Machine-specific by construction — re-measure
# when the fleet changes.
HOST_CALIBRATION_REF_S = 0.34
LOAD_GATE = 0.75
CALIBRATION_GATE = 1.5

_HOST_CAL = {"factor": None}


def _calibration_workload():
    """Fixed, allocation-light, sort-dominated — the same instruction
    mix as the staging host sections it calibrates for."""
    rng = np.random.default_rng(1234)
    a = rng.integers(0, 1 << 30, size=2_000_000)
    for _ in range(3):
        a = np.sort(a, kind="stable")[::-1].copy()


def host_calibration(out):
    """Run the calibration micro-workload and record the host's current
    speed factor vs the committed clean reference; later ``_host_line``
    calls gate their validity on it."""
    lo, samples, _ = _host_timed(_calibration_workload, n=3,
                                 label="host_calibration")
    factor = lo / HOST_CALIBRATION_REF_S
    _HOST_CAL["factor"] = factor
    out["host_calibration_seconds"] = round(lo, 3)
    out["host_calibration_samples"] = samples
    out["host_calibration_factor"] = round(factor, 2)
    if factor > CALIBRATION_GATE:
        _progress(f"WARNING host calibration {lo:.2f}s is {factor:.1f}x "
                  f"the clean-box reference {HOST_CALIBRATION_REF_S}s — "
                  "host lines in this capture will be marked invalid")
    return factor


def _host_timed(section, n=3, label=""):
    """Min-of-N timing for a HOST-side section with a contention guard.

    Tunnel jitter doesn't apply to host work, but this 1-core box does: a
    background thread (device-runtime housekeeping, another process) can
    inflate a single run 3-5× — the round-4 driver capture recorded the
    10M-row projection pass at 52 s where its standalone time is ~11 s.
    Min of N ≥ 3 runs is the contention-robust estimator; ALL samples and
    the 1-min load average are returned so a committed JSON shows when a
    capture was dirty instead of silently blessing one roll.

    Returns (min_seconds, samples, contended) — ``contended`` is True when
    the spread exceeds 1.5× the minimum, i.e. the min itself may still be
    inflated and the line should not be quoted as a clean measurement.
    """
    times = []
    for _ in range(n):
        t0 = time.perf_counter()
        section()
        times.append(time.perf_counter() - t0)
    lo, hi = min(times), max(times)
    contended = hi > 1.5 * lo + 0.05
    if contended:
        _progress(f"WARNING {label or 'host section'}: timing spread "
                  f"{lo:.2f}-{hi:.2f}s across {n} runs, load "
                  f"{os.getloadavg()[0]:.2f} — host contended; even the "
                  "min may be inflated")
    return lo, [round(t, 3) for t in times], contended


def _host_line(out, key, section, n=3):
    """Record one host-side bench line: ``key`` = min of n runs,
    ``key_samples`` = every run, ``key_contended`` only when dirty, and
    ``key_valid: false`` + reason when a load/calibration gate fired
    (the line then documents the environment instead of polluting the
    cross-round trajectory)."""
    load = os.getloadavg()[0]
    lo, samples, contended = _host_timed(section, n=n, label=key)
    out[key] = round(lo, 2)
    out[f"{key}_samples"] = samples
    if contended:
        out[f"{key}_contended"] = True
    reasons = []
    if load > LOAD_GATE:
        reasons.append(f"load_avg_1m {load:.2f} > {LOAD_GATE}")
    factor = _HOST_CAL.get("factor")
    if factor is not None and factor > CALIBRATION_GATE:
        reasons.append(f"host calibration {factor:.1f}x the clean-box "
                       f"reference")
    if reasons:
        out[f"{key}_valid"] = False
        out[f"{key}_invalid_reason"] = "; ".join(reasons)
    return lo


def _cold_line(out, key, section, warm_n=2):
    """One-time staging cost: the FIRST run in this (fresh) process is
    the number — min-of-N would report the warm re-run instead (observed
    5–30× smaller: allocator/page-cache warm-up dominates these
    allocation-heavy sections). Warm re-runs are recorded alongside for
    contrast (``key_warm``); run this only from a fresh subprocess, where
    'first' genuinely means cold."""
    t0 = time.perf_counter()
    section()
    cold = time.perf_counter() - t0
    warm = []
    for _ in range(warm_n):
        t0 = time.perf_counter()
        section()
        warm.append(time.perf_counter() - t0)
    out[key] = round(cold, 2)
    out[f"{key}_samples"] = [round(cold, 3)] + [round(w, 3) for w in warm]
    out[f"{key}_warm"] = round(min(warm), 2)
    return cold


def _numpy_value_grad(X, y, w):
    z = X @ w
    p = 1.0 / (1.0 + np.exp(-z))
    l = np.logaddexp(0.0, z) - y * z
    r = p - y
    return l.sum(), X.T @ r


def _slope(run, iters_small, iters_large):
    """Per-iteration seconds via the dependency-chain slope method.

    The span must be wide enough that (iters_large − iters_small) × step
    time dwarfs the tunnel's RPC jitter — callers pick spans per workload.
    Each endpoint takes the MIN of 5 runs: tunnel delay is additive and
    heavy-tailed (observed swings of ±50 ms between consecutive runs), so
    the minimum is the contention-robust estimator of the true cost;
    medians let one bad tail at either endpoint swing the difference.
    """
    run(iters_small)  # warm-up / compile
    t_small = min(run(iters_small) for _ in range(5))
    t_large = min(run(iters_large) for _ in range(5))
    return max(t_large - t_small, 1e-9) / (iters_large - iters_small)


def bench_gradient_step(n=1 << 19, d=256):
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.data.batch import LabeledBatch
    from photon_ml_tpu.ops import aggregators as agg
    from photon_ml_tpu.ops import losses

    rng = np.random.default_rng(0)
    X = rng.normal(size=(n, d)).astype(np.float32)
    y = rng.integers(0, 2, size=n).astype(np.float32)

    step = jax.jit(lambda ww, bb: agg.value_and_gradient(
        losses.LOGISTIC, ww, bb))

    def make_run(batch):
        def run(iters):
            w = jnp.zeros((d,), jnp.float32)
            t0 = time.perf_counter()
            for _ in range(iters):
                _, g = step(w, batch)
                w = w - 1e-9 * g  # chain: next step depends on this one
            np.asarray(w)  # force the whole chain
            return time.perf_counter() - t0
        return run

    dt = _slope(make_run(jax.device_put(LabeledBatch.build(X, y))), 20, 220)
    # bf16 feature storage: halves the streamed bytes, f32 MXU accumulation.
    # The bf16 step is ~2x faster, so the span doubles to keep the timed
    # window the same length relative to tunnel jitter.
    dt16 = _slope(make_run(jax.device_put(
        LabeledBatch.build(X, y, feature_dtype=jnp.bfloat16))), 20, 420)
    samples_per_sec = n / dt
    flops = 4.0 * n * d  # X@w and X.T@r, 2nd each
    bytes_moved = 2.0 * 4 * n * d  # X streamed twice (f32)

    # CPU numpy baseline (subsampled for time):
    n_cpu = min(n, 1 << 16)
    Xc, yc, wc = X[:n_cpu], y[:n_cpu], np.zeros(d, np.float32)
    _numpy_value_grad(Xc, yc, wc)
    t0 = time.perf_counter()
    reps = 3
    for _ in range(reps):
        _numpy_value_grad(Xc, yc, wc)
    cpu_dt = (time.perf_counter() - t0) / reps
    return {
        "samples_per_sec": samples_per_sec,
        "bf16_samples_per_sec": n / dt16,
        "achieved_gflops": flops / dt / 1e9,
        "achieved_gbytes_per_sec": bytes_moved / dt / 1e9,
        "cpu_numpy_samples_per_sec": n_cpu / cpu_dt,
    }


def bench_optimizer_steps(n=1 << 17, d=256):
    """Per-iteration cost of the FULL compiled optimizers (value+grad +
    history update + line search / CG), donated warm start.

    The problem is a deliberately ill-conditioned logistic fit and the
    tolerance is negative (convergence checks can never fire), so every
    requested iteration actually executes; the slope denominator uses the
    EXECUTED iteration counts reported by the solver, guarding against
    early line-search stalls silently zeroing the measurement.
    """
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.data.batch import LabeledBatch
    from photon_ml_tpu.ops import aggregators as agg
    from photon_ml_tpu.ops import losses
    from photon_ml_tpu.optim import (OptimizerConfig, minimize_lbfgs,
                                     minimize_tron, with_l2, with_l2_hvp)

    rng = np.random.default_rng(1)
    X = rng.normal(size=(n, d)).astype(np.float32)
    X *= np.logspace(0, 3, d, dtype=np.float32)  # condition ~1e6 in X'X
    w_true = rng.normal(size=d) / np.logspace(0, 3, d)
    y = (rng.uniform(size=n) < 1 / (1 + np.exp(-(X @ w_true)))).astype(
        np.float32)
    batch = jax.device_put(LabeledBatch.build(X, y))
    vg = with_l2(lambda w: agg.value_and_gradient(losses.LOGISTIC, w, batch),
                 1e-3)
    hvp = with_l2_hvp(
        lambda w, v: agg.hessian_vector(losses.LOGISTIC, w, v, batch), 1e-3)

    out = {}
    for name, solver in (
        ("lbfgs", lambda w0, k: minimize_lbfgs(
            vg, w0, OptimizerConfig(max_iterations=k, tolerance=-1.0))),
        ("tron", lambda w0, k: minimize_tron(
            vg, hvp, w0, OptimizerConfig(max_iterations=k, tolerance=-1.0,
                                         max_cg_iterations=10))),
    ):
        jitted = {}

        def run(iters, _solver=solver, _jitted=jitted):
            if iters not in _jitted:
                _jitted[iters] = jax.jit(
                    lambda w0, _k=iters: (
                        lambda r: (r.w, r.iterations))(_solver(w0, _k)),
                    donate_argnums=0)
            t0 = time.perf_counter()
            w, it = _jitted[iters](jnp.zeros((d,), jnp.float32))
            np.asarray(w)
            return time.perf_counter() - t0, int(it)

        # Spans wide enough that the timed difference (Δiters × step time:
        # ~200 ms for both solvers) dwarfs the tunnel's heavy-tailed jitter
        # (observed ±50 ms); the while_loop body compiles once regardless
        # of the iteration bound, so wide spans cost only run time.
        spans = {"lbfgs": (10, 510), "tron": (8, 64)}[name]
        k_small, k_large = spans
        run(k_small)  # warm-up / compile BOTH programs before timing
        run(k_large)
        t_small, e_small = min(run(k_small) for _ in range(5))
        t_large, e_large = min(run(k_large) for _ in range(5))
        executed = max(e_large - e_small, 1)
        out[f"{name}_iteration_ms"] = max(t_large - t_small, 0.0) \
            / executed * 1e3
        out[f"{name}_executed_iterations"] = (e_small, e_large)
    return out


def bench_sparse(n=1 << 17, d=1_000_000, nnz=32):
    """Criteo-regime sparse gradient step (BASELINE config 5).

    Three layouts of the SAME objective: the ELL gather/scatter pipeline
    (the multi-chip shard_map path), and the hybrid hot-dense/cold-class
    layout (ops/hybrid_sparse.py — the single-chip default) in f32 and
    bf16. The ELL figure documents the XLA random-access wall the hybrid
    split exists to avoid.
    """
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.data import sparse as sp
    from photon_ml_tpu.ops import hybrid_sparse as hs
    from photon_ml_tpu.ops import losses, sparse_aggregators as sagg

    batch, _ = sp.synthetic_sparse(n, d, nnz, seed=2)
    out = {}

    b_dev = jax.device_put(batch)
    ell_step = jax.jit(lambda ww, bb: sagg.value_and_gradient(
        losses.LOGISTIC, ww, bb))

    def run_ell(iters):
        w = jnp.zeros((d,), jnp.float32)
        t0 = time.perf_counter()
        for _ in range(iters):
            _, g = ell_step(w, b_dev)
            w = w - 1e-9 * g
        np.asarray(w[:8])
        return time.perf_counter() - t0

    dt_ell = _slope(run_ell, 3, 23)
    out["sparse_ell_samples_per_sec"] = round(n / dt_ell)

    hyb_step = jax.jit(lambda ww, hb: hs.value_and_gradient(
        losses.LOGISTIC, ww, hb))
    for name, dtype in (("", jnp.float32), ("bf16_", jnp.bfloat16)):
        # Staging cost is measured COLD in bench_fresh_host_suite (a
        # fresh subprocess) — timing it here, mid-device-phase in a warm
        # process, produced the 11.65→37.04→20.09 swings of rounds 3–4.
        hb = hs.build_hybrid(batch, feature_dtype=dtype)
        if not name:
            out["sparse_hybrid_hot_cols"] = hb.num_hot

        def run_hyb(iters, _hb=hb):
            w = jnp.zeros((d,), jnp.float32)
            t0 = time.perf_counter()
            for _ in range(iters):
                _, g = hyb_step(w, _hb)
                w = w - 1e-9 * g
            np.asarray(w[:8])
            return time.perf_counter() - t0

        dt = _slope(run_hyb, 3, 23)
        out[f"sparse_{name}samples_per_sec"] = n / dt
        out[f"sparse_{name}gnnz_per_sec"] = n * nnz / dt / 1e9

    # The data-parallel composition of the hybrid layout (HybridShards +
    # shard_map psum) on this chip's 1-device mesh: demonstrates the
    # multi-device code path runs at the single-layout rate (the psum is
    # a no-op at S=1; per-shard work is identical).
    from photon_ml_tpu.parallel import sparse_objective as sobj
    from photon_ml_tpu.parallel import sparse_problem as spp
    from photon_ml_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(num_data=1, devices=jax.devices()[:1])
    shb = spp.shard_hybrid(hs.build_hybrid_shards(batch, 1), mesh)
    # The staged batch is a jit ARGUMENT (a closed-over device array would
    # bake the ~GB hot block into the executable as a constant).
    shard_vg = jax.jit(lambda ww, sb: sobj.make_hybrid_value_and_gradient(
        losses.LOGISTIC, mesh, sb)(ww))

    def run_shard(iters):
        w = jnp.zeros((d,), jnp.float32)
        t0 = time.perf_counter()
        for _ in range(iters):
            _, g = shard_vg(w, shb)
            w = w - 1e-9 * g
        np.asarray(w[:8])
        return time.perf_counter() - t0

    dt_sh = _slope(run_shard, 3, 23)
    out["sparse_hybrid_sharded_samples_per_sec"] = round(n / dt_sh)
    return out


def _sparse_re_inputs(n=100_000, d=200_000, num_entities=1000, nnz=8):
    """Shared dataset+config for the sparse-RE fit bench and the cold
    staging line (same shapes so both describe the same workload)."""
    from photon_ml_tpu.data.game_data import GameDataset, SparseShard
    from photon_ml_tpu.optim import OptimizerConfig
    from photon_ml_tpu.optim.problem import GLMOptimizationConfiguration
    from photon_ml_tpu.optim.regularization import (RegularizationContext,
                                                    RegularizationType)

    rng = np.random.default_rng(3)
    ids = rng.integers(0, num_entities, n).astype(np.int32)
    pools = rng.integers(0, d, (num_entities, 64)).astype(np.int32)
    idx = np.sort(pools[ids[:, None], rng.integers(0, 64, (n, nnz))],
                  axis=1)
    dup = np.zeros_like(idx, bool)
    dup[:, 1:] = idx[:, 1:] == idx[:, :-1]
    vals = rng.normal(size=(n, nnz)).astype(np.float32)
    idx[dup] = d
    vals[dup] = 0.0
    y = (rng.random(n) < 0.5).astype(np.float32)
    ds = GameDataset(
        response=y, offsets=np.zeros(n, np.float32),
        weights=np.ones(n, np.float32),
        feature_shards={"re": SparseShard(idx, vals, d)},
        entity_ids={"userId": ids}, num_entities={"userId": num_entities},
        intercept_index={})
    cfg = GLMOptimizationConfiguration(
        optimizer=OptimizerConfig(max_iterations=15, tolerance=1e-7),
        regularization=RegularizationContext(RegularizationType.L2, 1.0))
    return ds, cfg


def bench_sparse_random_effect(n=100_000, d=200_000, num_entities=1000,
                               nnz=8):
    """Sparse random-effect fit at large d (SURVEY §2.1 sparse RE):
    steady-state per-train_model time (staging is measured cold in
    bench_fresh_host_suite)."""
    from photon_ml_tpu.game.coordinates import RandomEffectCoordinate
    from photon_ml_tpu.ops import losses
    from photon_ml_tpu.parallel.mesh import make_mesh

    ds, cfg = _sparse_re_inputs(n, d, num_entities, nnz)
    import shutil
    import tempfile

    # Staging cost is measured COLD in bench_fresh_host_suite (fresh
    # subprocess); here the coordinate is just built for the fit timing.
    res: dict = {}
    coord = RandomEffectCoordinate(ds, "userId", "re", losses.LOGISTIC,
                                   cfg, make_mesh()).wait_staged()
    cache_dir = tempfile.mkdtemp(prefix="pml_staging_cache_")
    try:
        RandomEffectCoordinate(ds, "userId", "re", losses.LOGISTIC,
                               cfg, make_mesh(),
                               staging_cache_dir=cache_dir
                               ).wait_staged()  # populates
        # Warm path: a fresh coordinate on the same data memory-maps the
        # staged blocks from the digest-keyed cache instead of re-running
        # the projection pass. wait_staged() = the staging barrier (the
        # pipeline otherwise defers shard loads to the first fit).
        _host_line(res, "sparse_re_staging_warm_seconds",
                   lambda: RandomEffectCoordinate(
                       ds, "userId", "re", losses.LOGISTIC, cfg,
                       make_mesh(),
                       staging_cache_dir=cache_dir).wait_staged())
        # bf16 bucket-block storage: halves the staged blocks' HBM, f32 MXU
        # accumulation (same contract as the dense fixed path). The f32
        # staging cache is dtype-independent (cast happens after load), so
        # reuse it rather than re-paying the projection pass.
        coord16 = RandomEffectCoordinate(ds, "userId", "re",
                                         losses.LOGISTIC, cfg, make_mesh(),
                                         staging_cache_dir=cache_dir,
                                         feature_dtype="bfloat16"
                                         ).wait_staged()
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)
    off = np.zeros(n, np.float32)

    def make_run(c):
        def run(iters):
            t0 = time.perf_counter()
            model = None
            for _ in range(iters):
                model = c.train_model(off, initial=model)
            np.asarray(model.means[:1])
            return time.perf_counter() - t0
        return run

    dt = _slope(make_run(coord), 1, 4)
    dt16 = _slope(make_run(coord16), 1, 4)
    res.update({
        "sparse_re_fit_seconds": round(dt, 3),
        "sparse_re_bf16_fit_seconds": round(dt16, 3),
        "sparse_re_config": f"n={n} d={d} entities={num_entities}",
    })
    return res


def bench_host_staging(n=10_000_000, num_entities=1_000_000, d=1_000_000,
                       nnz=8):
    """Host-side staging at the design-target scale (round-2 verdict:
    unmeasured): build_bucketing + per-entity subspace projection for a
    random effect over 10M rows, 1M entities, d=1M sparse features —
    all-numpy work that happens once per fit, before any device step.

    ``staging_projection_seconds`` stays the SERIAL whole-bucket pass
    (comparable across bench rounds); the ``*_parallel_*`` lines measure
    the sharded worker-pool pipeline (game/staging.py) at
    min(8, host cores) workers — the projection-wall fix, targeted at
    ≥4× on an 8-core host with byte-identical staged arrays (asserted in
    tests/test_staging_parallel.py)."""
    from photon_ml_tpu.data.game_data import SparseShard
    from photon_ml_tpu.game import staging as stg
    from photon_ml_tpu.game.buckets import build_bucketing
    from photon_ml_tpu.game.projector import (all_bucket_triplets,
                                              build_bucket_projection,
                                              shard_coo)

    rng = np.random.default_rng(11)
    ids = rng.integers(0, num_entities, n).astype(np.int32)
    idx = np.sort(rng.integers(0, d, (n, nnz)).astype(np.int32), axis=1)
    dup = np.zeros_like(idx, bool)
    dup[:, 1:] = idx[:, 1:] == idx[:, :-1]
    vals = rng.normal(size=(n, nnz)).astype(np.float32)
    idx[dup] = d
    vals[dup] = 0.0
    shard = SparseShard(idx, vals, d)

    out: dict = {"staging_load_avg_1m": round(os.getloadavg()[0], 2)}
    # Calibration FIRST: every _host_line below gates its validity on it.
    host_calibration(out)
    bucketing = build_bucketing(ids, num_entities)  # warm result for below

    def _bucketing():
        build_bucketing(ids, num_entities)

    def _projection():
        coo = shard_coo(shard)
        trips = all_bucket_triplets(bucketing.buckets, shard, coo)
        for bk, trip in zip(bucketing.buckets, trips):
            build_bucket_projection(bk, shard, None, triplets=trip)

    workers = min(8, os.cpu_count() or 1)

    def _projection_parallel():
        stg.project_buckets(bucketing, shard, intercept_index=None,
                            config=stg.StagingConfig(workers=workers))

    tb = _host_line(out, "staging_bucketing_seconds", _bucketing)
    tp = _host_line(out, "staging_projection_seconds", _projection)
    tpp = _host_line(out, "staging_projection_parallel_seconds",
                     _projection_parallel)
    out["staging_workers"] = workers
    out["staging_parallel_speedup"] = round(tp / max(tpp, 1e-9), 2)
    out["staging_parallel_efficiency"] = round(
        tp / max(tpp, 1e-9) / workers, 3)
    out["staging_seconds_10m_rows_1m_entities"] = round(tb + tp, 2)
    return out


def bench_ingest_cold_fit(n=20_000, nnz=20, entities=1000):
    """End-to-end cold fit through the ingestion layer: Avro file →
    block-parallel ingest (photon_ml_tpu/ingest) → random-effect
    coordinate staging → per-entity fits, against its standalone
    components. The overlap invariant the regression gate checks
    (dev-scripts/check_bench_regression.py):

        end_to_end_cold_fit_seconds <= 1.15 x max(ingest, staging+fit)

    With parallel decode the serial-decode wall stops serializing in
    front of the fit — demonstrable only where cores exist to fan the
    decode over, so the gate enforces on >= 4-core hosts and reports
    on this 1-core CI box (docs/INGEST.md, same caveat as the staging
    multi-worker scaling note in docs/STAGING.md). The warm line runs
    the same flow against a populated ingest cache."""
    import shutil
    import tempfile

    import jax

    from photon_ml_tpu import ingest as ing
    from photon_ml_tpu.avro import schemas
    from photon_ml_tpu.avro.container import DataFileWriter
    from photon_ml_tpu.avro.data_reader import (AvroDataReader,
                                                FeatureShardConfig)
    from photon_ml_tpu.game.coordinates import RandomEffectCoordinate
    from photon_ml_tpu.ops import losses
    from photon_ml_tpu.optim import OptimizerConfig
    from photon_ml_tpu.optim.problem import GLMOptimizationConfiguration
    from photon_ml_tpu.optim.regularization import (RegularizationContext,
                                                    RegularizationType)
    from photon_ml_tpu.parallel.mesh import make_mesh

    rng = np.random.default_rng(13)
    # Dense low-d shard: decode (nnz varints/doubles per record)
    # dominates the fold and the per-entity solves stay light — the
    # decode-bound side of the pipeline, where the ingestion layer is
    # the wall being measured.
    recs = [{
        "uid": i, "label": float(rng.integers(0, 2)),
        "weight": 1.0, "offset": 0.0,
        "features": [{"name": f"x{rng.integers(0, 32)}", "term": "",
                      "value": float(rng.normal())} for _ in range(nnz)],
        "metadataMap": {"userId": f"u{rng.integers(0, entities)}"},
    } for i in range(n)]
    td = tempfile.mkdtemp(prefix="pml_ingest_bench_")
    out: dict = {}
    try:
        p = os.path.join(td, "train.avro")
        with DataFileWriter(p, schemas.TRAINING_EXAMPLE_AVRO,
                            codec="deflate", block_records=1024) as w:
            for r in recs:
                w.append(r)
        cfgs = {"re": FeatureShardConfig(("features",), True)}
        workers = min(8, os.cpu_count() or 1)
        mesh = make_mesh()
        opt = GLMOptimizationConfiguration(
            optimizer=OptimizerConfig(max_iterations=15, tolerance=1e-7),
            regularization=RegularizationContext(
                RegularizationType.L2, 1.0))
        off = np.zeros(n, np.float32)

        def read(cfg):
            return AvroDataReader().read(
                p, cfgs, random_effect_types=["userId"], ingest=cfg)[0]

        def fit(ds):
            c = RandomEffectCoordinate(ds, "userId", "re",
                                       losses.LOGISTIC, opt, mesh)
            jax.block_until_ready(c.train_model(off).means)

        # Warm the jit caches first: a compile inside a timed region
        # would swamp every comparison below.
        ds0 = read(ing.IngestConfig(workers=1, chunk_records=1 << 30))
        fit(ds0)

        # Standalone components: the serial-decode reference (the wall
        # the parallel pipeline attacks) and staging+fit on resident data.
        t_ingest = _host_line(
            out, "ingest_cold_seconds",
            lambda: read(ing.IngestConfig(workers=1,
                                          chunk_records=1 << 30)))
        t_fit = _host_line(out, "staging_plus_fit_seconds",
                           lambda: fit(ds0))
        # The pipelined end-to-end flow (parallel decode feeding the
        # coordinate).
        par = ing.IngestConfig(workers=workers, chunk_records=2048)
        t_e2e = _host_line(out, "end_to_end_cold_fit_seconds",
                           lambda: fit(read(par)))
        out["end_to_end_overlap_ratio"] = round(
            t_e2e / max(max(t_ingest, t_fit), 1e-9), 3)
        # Warm restart: same flow against a populated ingest cache.
        cache = os.path.join(td, "icache")
        warm_cfg = ing.IngestConfig(workers=workers, chunk_records=2048,
                                    cache_dir=cache)
        fit(read(warm_cfg))  # populate
        t_warm = _host_line(out, "end_to_end_warm_fit_seconds",
                            lambda: fit(read(warm_cfg)))
        out["end_to_end_warm_speedup"] = round(
            t_e2e / max(t_warm, 1e-9), 2)
        out["ingest_bench_cores"] = os.cpu_count() or 1
    finally:
        shutil.rmtree(td, ignore_errors=True)
    return out


def bench_fresh_host_suite():
    """Everything that must be measured in a FRESH process, in one
    subprocess pass: the 10M-row staging (min-of-3 — its host sorts
    dominate, cold ≈ warm) and the COLD one-time staging lines (hybrid
    build, sparse-RE coordinate construction — allocation-heavy sections
    whose warm re-runs measure 5–30× faster, so min-of-N would misreport
    them; see _cold_line)."""
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.data import sparse as sp
    from photon_ml_tpu.game.coordinates import RandomEffectCoordinate
    from photon_ml_tpu.ops import hybrid_sparse as hs
    from photon_ml_tpu.ops import losses
    from photon_ml_tpu.parallel.mesh import make_mesh

    out = bench_host_staging()

    batch, _ = sp.synthetic_sparse(1 << 17, 1_000_000, 32, seed=2)
    # block: device_put is async — staging "done" means the blocks are
    # resident, not merely enqueued.
    _cold_line(out, "sparse_hybrid_staging_seconds",
               lambda: jax.block_until_ready(
                   hs.build_hybrid(batch, feature_dtype=jnp.float32)))

    ds, cfg = _sparse_re_inputs()
    _cold_line(out, "sparse_re_staging_seconds",
               lambda: RandomEffectCoordinate(
                   ds, "userId", "re", losses.LOGISTIC, cfg,
                   make_mesh()).wait_staged())

    # Pipelined handoff overlap (sparse-RE config): the barrier path
    # stages everything then fits; the pipelined path lets the first
    # train_model consume shards while later ones still project.
    # overlap_efficiency = hidden staging time / hideable staging time
    # (1.0 = staging fully behind the fits; ~0 on a 1-core host where
    # producer and consumer share the core).
    off = np.zeros(ds.num_rows, np.float32)
    # Warm the jit caches first: the fit kernels compile once per process
    # (several seconds), and a compile inside either timed region would
    # swamp the staging/fit overlap being measured.
    warm = RandomEffectCoordinate(ds, "userId", "re", losses.LOGISTIC,
                                  cfg, make_mesh())
    jax.block_until_ready(warm.train_model(off).means)
    t0 = time.perf_counter()
    c_bar = RandomEffectCoordinate(ds, "userId", "re", losses.LOGISTIC,
                                   cfg, make_mesh()).wait_staged()
    t_stage = time.perf_counter() - t0
    t0 = time.perf_counter()
    jax.block_until_ready(c_bar.train_model(off).means)
    t_fit = time.perf_counter() - t0
    t0 = time.perf_counter()
    c_pipe = RandomEffectCoordinate(ds, "userId", "re", losses.LOGISTIC,
                                    cfg, make_mesh())
    jax.block_until_ready(c_pipe.train_model(off).means)
    t_pipe = time.perf_counter() - t0
    out["staging_pipeline_barrier_seconds"] = round(t_stage + t_fit, 3)
    out["staging_pipeline_overlapped_seconds"] = round(t_pipe, 3)
    out["staging_overlap_efficiency"] = round(min(1.0, max(
        0.0, t_stage + t_fit - t_pipe) / max(min(t_stage, t_fit), 1e-9)), 3)

    from photon_ml_tpu.avro import native_decode

    if native_decode.native_available():
        # Ingestion layer in the same fresh process (decode rates +
        # cache lines, then the end-to-end cold-fit overlap invariant) —
        # dev-scripts/check_bench_regression.py reads these from the
        # --run-staging tail.
        out.update(bench_avro_ingest())
        out.update(bench_ingest_cold_fit())
    return out


def bench_pallas_scatter(n=1 << 17, k=32, d=512):
    """Pallas compare+accumulate scatter vs XLA sort/segment scatter at the
    moderate-d regime the kernel targets. Skipped off-TPU (the Mosaic
    kernel doesn't lower elsewhere; interpret mode is orders slower)."""
    import jax
    import jax.numpy as jnp

    if jax.default_backend() != "tpu":
        return {}

    from photon_ml_tpu.ops.pallas_sparse import scatter_rowterm

    rng = np.random.default_rng(3)
    idx = jnp.asarray(rng.integers(0, d, (n, k)).astype(np.int32))
    rv = jnp.asarray(rng.normal(size=(n, k)).astype(np.float32))

    xla = jax.jit(
        lambda i, v: jnp.zeros((d + 1,), jnp.float32)
        .at[i.reshape(-1)].add(v.reshape(-1))[:d])

    out = {}
    for name, f in (("pallas", lambda i, v: scatter_rowterm(i, v, d)),
                    ("xla", xla)):
        def run(iters, _f=f):
            v = rv
            t0 = time.perf_counter()
            for _ in range(iters):
                o = _f(idx, v)
                v = rv * (1.0 + 1e-20 * o[0])  # chain
            np.asarray(o[:4])
            return time.perf_counter() - t0

        out[f"scatter_{name}_d{d}_us"] = _slope(run, 5, 45) * 1e6
    return out


def bench_kernels():
    """Fused-vs-XLA sweep over every registry kernel (docs/KERNELS.md
    "The sweep workflow") — the evidence a registry default flip must
    cite. For each kernel in ops/kernels/ the sweep times the Pallas
    program against its registered XLA reference at the bench shapes
    and computes the parity delta between the two.

    Validity discipline: off-TPU the Pallas program only runs through
    the interpreter, which is parity-grade but orders slower than any
    real backend — those timing lines are stamped ``kernel_<name>_valid:
    false`` so check_bench_regression.py never reads an interpret wall
    as a fused-vs-XLA verdict. Parity deltas are ALWAYS computed and
    always gated: interpret mode runs the same program the TPU would.

    ``kernel_defaults_flipped`` carries the kernels whose registered
    default is ON — the committed claim "the sweep showed a win here" —
    which is exactly the set check_bench_regression.py holds to the
    fused ≤ 1.0× XLA band on timing-valid tails."""
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.ops import kernels as K

    on_tpu = jax.default_backend() == "tpu"
    reg = K.registry()
    rng = np.random.default_rng(7)

    def pick(big, small):
        return big if on_tpu else small

    # ell_scatter: the streamed RE rowterm scatter (moderate d).
    n_sc, k_sc, d_sc = pick((1 << 17, 32, 512), (2048, 8, 256))
    idx = jnp.asarray(rng.integers(0, d_sc, (n_sc, k_sc)).astype(np.int32))
    rv = jnp.asarray(rng.normal(size=(n_sc, k_sc)).astype(np.float32))

    # serving_score: gather -> int8 dequant -> einsum -> per-row scale.
    n_sv, d_sv, e_sv = pick((4096, 512, 8192), (64, 128, 256))
    mat = jnp.asarray(rng.normal(size=(n_sv, d_sv)).astype(np.float32))
    slots = jnp.asarray(rng.integers(0, e_sv, (n_sv,)).astype(np.int32))
    cache = jnp.asarray(
        rng.integers(-127, 128, (e_sv, d_sv)).astype(np.int8))
    scl = jnp.asarray(rng.uniform(1e-3, 2.0, (e_sv,)).astype(np.float32))

    # stream_margins / stream_rmatvec: the int8 hot-dense matvec pair.
    n_st, h_st = pick((1 << 15, 4096), (256, 512))
    X_hot = jnp.asarray(
        rng.integers(-127, 128, (n_st, h_st)).astype(np.int8))
    w_hot = jnp.asarray(rng.normal(size=(h_st,)).astype(np.float32))
    base = jnp.asarray(rng.normal(size=(n_st,)).astype(np.float32))
    resid = jnp.asarray(rng.normal(size=(n_st,)).astype(np.float32))

    # re_gather_rows / re_scatter_rows: bucket-solve row traffic, with
    # invalid (-1) lanes in the final ragged wave. Rows are UNIQUE
    # within the wave (the bucket-solve contract) — with duplicates the
    # two backends' last-writer orders legitimately diverge.
    e_re, d_re, b_re = pick((8192, 256, 2048), (256, 64, 64))
    W = jnp.asarray(rng.normal(size=(e_re, d_re)).astype(np.float32))
    rows_np = rng.permutation(e_re)[:b_re].astype(np.int32)
    rows_np[:: max(b_re // 8, 1)] = -1
    rows = jnp.asarray(rows_np)
    vals = jnp.asarray(rng.normal(size=(b_re, d_re)).astype(np.float32))

    from photon_ml_tpu.ops.kernels import (ell_scatter, re_rows,
                                           serving_score, stream_fused)

    # (name, pallas(*arrays, interpret=), xla(*arrays), arrays,
    #  chain_idx) — chain_idx names the float operand the dependency
    # chain perturbs so the async tunnel can't pipeline the timed loop.
    cases = [
        ("ell_scatter",
         lambda i, v, **kw: ell_scatter.scatter_rowterm_pallas(
             i, v, d_sc, **kw),
         lambda i, v: ell_scatter.scatter_rowterm_xla(i, v, d_sc),
         (idx, rv), 1),
        ("serving_score", serving_score.score_rows_pallas,
         serving_score.score_rows_xla, (mat, slots, cache, scl), 0),
        ("stream_margins", stream_fused.hot_margins_pallas,
         stream_fused.hot_margins_xla, (X_hot, w_hot, base), 1),
        ("stream_rmatvec", stream_fused.hot_rmatvec_pallas,
         stream_fused.hot_rmatvec_xla, (X_hot, resid), 1),
        ("re_gather_rows", re_rows.gather_rows_pallas,
         re_rows.gather_rows_xla, (W, rows), 0),
        ("re_scatter_rows", re_rows.scatter_rows_pallas,
         re_rows.scatter_rows_xla, (W, rows, vals), 2),
    ]

    out = {
        "kernel_sweep_backend": jax.default_backend(),
        "kernel_sweep_kernels": [c[0] for c in cases],
        "kernel_defaults_flipped": [n for n in reg.names()
                                    if reg.get(n).default_on],
    }

    for name, pallas_fn, xla_fn, arrays, ci in cases:
        _progress(f"kernel sweep: {name}")
        variants = (
            ("pallas", jax.jit(lambda *a, _f=pallas_fn:
                               _f(*a, interpret=not on_tpu))),
            ("xla", jax.jit(lambda *a, _f=xla_fn: _f(*a))),
        )
        results = {}
        for backend, f in variants:
            def run(iters, _f=f, _arrays=arrays, _ci=ci):
                a = list(_arrays)
                t0 = time.perf_counter()
                for _ in range(iters):
                    o = _f(*a)
                    a[_ci] = a[_ci] * (1.0 + 1e-20
                                       * o.ravel()[0].astype(jnp.float32)
                                       .astype(a[_ci].dtype))
                np.asarray(o.ravel()[:1])
                return time.perf_counter() - t0

            results[backend] = np.asarray(f(*arrays), np.float64)  # warm
            if on_tpu:
                out[f"kernel_{name}_{backend}_us"] = round(
                    _slope(run, 5, 45) * 1e6, 1)
            else:
                run(1)
                out[f"kernel_{name}_{backend}_us"] = round(
                    min(run(1) for _ in range(3)) * 1e6, 1)
        out[f"kernel_{name}_ratio"] = round(
            out[f"kernel_{name}_pallas_us"]
            / max(out[f"kernel_{name}_xla_us"], 1e-9), 3)
        delta = float(np.max(np.abs(results["pallas"] - results["xla"])))
        ref = float(np.max(np.abs(results["xla"])))
        out[f"kernel_{name}_parity_delta"] = delta
        out[f"kernel_{name}_parity_rel"] = delta / max(ref, 1e-9)
        if not on_tpu:
            out[f"kernel_{name}_valid"] = False
            out[f"kernel_{name}_invalid_reason"] = (
                "pallas timed through the interpreter (no TPU backend) "
                "— parity-grade only")
    return out


def bench_avro_ingest(n=20_000, nnz=20):
    """Ingestion layer (docs/INGEST.md): native block decoder vs the
    pure-Python codec through AvroDataReader.read, the block-parallel
    pipeline at min(8, cores) decode workers, and the columnar mmap
    ingest cache — cold decode vs warm mmap load at the DECODE layer
    (the work the cache eliminates; the fold runs identically on both
    paths)."""
    import os
    import tempfile

    from photon_ml_tpu import ingest as ing
    from photon_ml_tpu.avro import native_decode, schemas
    from photon_ml_tpu.avro.container import DataFileWriter
    from photon_ml_tpu.avro.data_reader import (AvroDataReader,
                                                FeatureShardConfig)

    if not native_decode.native_available():
        return {}
    rng = np.random.default_rng(7)
    recs = [{
        "uid": i, "label": float(rng.integers(0, 2)),
        "weight": 1.0, "offset": 0.0,
        "features": [{"name": f"f{rng.integers(0, 500)}", "term": "t",
                      "value": float(rng.normal())} for _ in range(nnz)],
        "metadataMap": {"userId": f"u{rng.integers(0, 500)}"},
    } for i in range(n)]
    cfgs = {"global": FeatureShardConfig(("features",), True, sparse=True)}
    workers = min(8, os.cpu_count() or 1)
    out = {"ingest_workers": workers}
    with tempfile.TemporaryDirectory() as td:
        p = os.path.join(td, "ingest.avro")
        # 1024-record blocks so the parallel pipeline has boundaries to
        # split at (chunks cover whole blocks).
        with DataFileWriter(p, schemas.TRAINING_EXAMPLE_AVRO,
                            codec="deflate", block_records=1024) as w:
            for r in recs:
                w.append(r)

        # Full-read rates: serial native (the round-comparable line),
        # pure Python, and the block-parallel pipeline.
        serial_cfg = ing.IngestConfig(workers=1, chunk_records=1 << 30)
        par_cfg = ing.IngestConfig(workers=workers, chunk_records=2048)
        for name, kwargs in (
                ("native", {"ingest": serial_cfg}),
                ("python", {"use_native": False}),
                ("parallel", {"ingest": par_cfg})):
            lo, samples, contended = _host_timed(
                lambda _kw=kwargs: AvroDataReader().read(
                    p, cfgs, random_effect_types=["userId"], **_kw),
                label=f"avro_{name}")
            key = ("ingest" if name == "parallel" else f"avro_{name}")
            out[f"{key}_records_per_sec"] = round(n / lo)
            out[f"{key}_seconds_samples"] = samples
            if contended:
                out[f"{key}_contended"] = True
        out["ingest_parallel_speedup"] = round(
            out["ingest_records_per_sec"]
            / out["avro_native_records_per_sec"], 2)

        # Decode-layer cache comparison: drain the pipeline without the
        # fold — cold = native block decode, warm = CRC-verified mmap
        # load of the columnar cache (what a warm restart actually runs
        # instead of Avro decode).
        fb = ing.scan_file(p)
        fields = AvroDataReader().fields
        captures = {
            fields.response: (native_decode.CAP_RESPONSE, 0),
            fields.offset: (native_decode.CAP_OFFSET, 0),
            fields.weight: (native_decode.CAP_WEIGHT, 0),
            fields.uid: (native_decode.CAP_UID, 0),
            fields.metadata: (native_decode.CAP_META, 0),
            "features": (native_decode.CAP_BAG, 0),
        }
        plan = native_decode.compile_plan(fb.schema, captures)
        chunks = ing.plan_chunks([fb], 16384)

        def drain(cfg, key=None):
            pipe = ing.IngestPipeline(chunks, [plan], 1, cfg,
                                      cache_key=key)
            for _ in pipe.chunks():
                pass

        t_cold = _host_line(out, "ingest_cold_decode_seconds",
                            lambda: drain(ing.IngestConfig(workers=1)))
        cache_cfg = ing.IngestConfig(
            workers=1, cache_dir=os.path.join(td, "icache"))
        cache_key = ing.ingest_key([fb], captures, 1,
                                   cache_cfg.chunk_records)
        drain(cache_cfg, cache_key)  # populate
        t_warm = _host_line(out, "ingest_warm_cache_seconds",
                            lambda: drain(cache_cfg, cache_key))
        out["ingest_warm_cache_speedup"] = round(
            t_cold / max(t_warm, 1e-9), 2)
    return out


def bench_stream_pinned(n=1 << 15, d=4096, nnz=16, chunk_rows=1 << 12):
    """``pin_chunks`` pinned-fraction scaling curve (ROADMAP item 4): the
    n=100M streamed sweep is ~95% host→device transfer, and pinning is
    the first untried lever — each pinned chunk is stream traffic saved
    on EVERY objective evaluation, so seconds-per-pass should fall
    roughly linearly in the pinned fraction on a transfer-bound pass.
    Sweeps 0/25/50/100% of chunks pinned (stream_pinned_fraction_curve)
    plus the sharded composition at every local device
    (stream_sharded_pass_seconds — D=1 on a single-chip box; the psum
    merge is then an identity, so the line doubles as its overhead
    check)."""
    import jax
    import jax.numpy as jnp

    from photon_ml_tpu.data import sparse as sp
    from photon_ml_tpu.ops import losses
    from photon_ml_tpu.ops import streaming_sparse as ss
    from photon_ml_tpu.parallel.mesh import make_mesh

    batch, _ = sp.synthetic_sparse(n, d, nnz, seed=5)

    def chunks():
        for lo in range(0, n, chunk_rows):
            hi = min(lo + chunk_rows, n)
            yield sp.SparseBatch(
                indices=np.asarray(batch.indices)[lo:hi],
                values=np.asarray(batch.values)[lo:hi],
                labels=np.asarray(batch.labels)[lo:hi],
                weights=np.asarray(batch.weights)[lo:hi],
                offsets=np.asarray(batch.offsets)[lo:hi],
                num_features=d)

    chunked = ss.build_chunked(chunks(), d, chunk_rows, num_hot=256)
    out: dict = {
        "stream_pass_config": f"n={n} d={d} chunks={chunked.num_chunks}",
    }
    w0 = jnp.zeros((d,), jnp.float32)

    def make_run(vg):
        def run(iters):
            w = w0
            t0 = time.perf_counter()
            for _ in range(iters):
                _, g = vg(w)
                w = w - 1e-9 * g  # chain: next pass depends on this one
            np.asarray(w[:8])
            return time.perf_counter() - t0
        return run

    curve = {}
    for frac in (0.0, 0.25, 0.5, 1.0):
        count = int(round(frac * chunked.num_chunks))
        pinned = ss.pin_chunks(chunked, count)
        vg = ss.make_value_and_gradient(losses.LOGISTIC, chunked,
                                        pinned=pinned)
        curve[str(int(frac * 100))] = round(_slope(make_run(vg), 2, 8), 4)
    out["stream_pinned_fraction_curve"] = curve
    out["stream_pinned_fraction_speedup"] = round(
        curve["0"] / max(curve["100"], 1e-9), 2)

    mesh = make_mesh()
    sharded = ss.ShardedChunkStream(chunked, mesh)
    out["stream_sharded_devices"] = sharded.num_devices
    out["stream_sharded_pass_seconds"] = round(
        _slope(make_run(sharded.value_and_gradient(losses.LOGISTIC)),
               2, 8), 4)
    out["stream_single_pass_seconds"] = curve["0"]
    return out


def bench_stream_quant(n=1 << 15, d=4096, nnz=16, chunk_rows=1 << 12,
                       num_hot=512):
    """The pinned×quantized scaling matrix (ROADMAP item 3's transfer
    lever): the streamed pass is transfer-bound, so its wall should
    track the storage dtype's payload bytes. Stages the SAME rows at
    f32/bf16/int8, measures pass seconds at 0%% and 100%% pinned per
    dtype (``stream_quant_matrix_seconds``), and records each dtype's
    analytic payload per pass next to the ``photon_transfer_bytes_total``
    counter's measurement of one pass (``stream_quant_metric_bytes_per_
    pass`` — bench line and metric share provenance, the ≤10%% cross-
    check check_bench_regression.py gates). ``num_hot=512`` at nnz=16
    makes the hot block the payload bulk — the flagship regime, where
    int8 lands ≤0.30× f32. Also counts kernel builds during the timed
    (post-warmup) passes: must be ZERO (the kernel caches grow a dtype
    key, not extra steady-state compiles)."""
    import jax.numpy as jnp

    from photon_ml_tpu import obs
    from photon_ml_tpu.data import sparse as sp
    from photon_ml_tpu.ops import losses
    from photon_ml_tpu.ops import streaming_sparse as ss

    batch, _ = sp.synthetic_sparse(n, d, nnz, seed=7)

    def chunks():
        for lo in range(0, n, chunk_rows):
            hi = min(lo + chunk_rows, n)
            yield sp.SparseBatch(
                indices=np.asarray(batch.indices)[lo:hi],
                values=np.asarray(batch.values)[lo:hi],
                labels=np.asarray(batch.labels)[lo:hi],
                weights=np.asarray(batch.weights)[lo:hi],
                offsets=np.asarray(batch.offsets)[lo:hi],
                num_features=d)

    w0 = jnp.zeros((d,), jnp.float32)

    def make_run(vg):
        def run(iters):
            w = w0
            t0 = time.perf_counter()
            for _ in range(iters):
                _, g = vg(w)
                w = w - 1e-9 * g  # chain: next pass depends on this one
            np.asarray(w[:8])
            return time.perf_counter() - t0
        return run

    out: dict = {
        "stream_quant_config": f"n={n} d={d} nnz={nnz} "
                               f"chunk_rows={chunk_rows} "
                               f"num_hot={num_hot}",
    }
    matrix: dict = {}
    analytic: dict = {}
    measured: dict = {}
    transfer_frac: dict = {}
    warm_builds = 0.0
    # Metrics on for the byte provenance; restored to off afterwards so
    # the accounting never perturbs the other bench phases.
    _, mx = obs.enable(trace=False, metrics=True)
    try:
        for dtype in ("float32", "bfloat16", "int8"):
            chunked = ss.build_chunked(chunks(), d, chunk_rows,
                                       num_hot=num_hot,
                                       feature_dtype=dtype)
            analytic[dtype] = int(
                sum(ss._chunk_nbytes(ch) for ch in chunked.chunks))
            vg = ss.make_value_and_gradient(losses.LOGISTIC, chunked)
            make_run(vg)(1)  # warm-up: compile + first pass
            counters = obs.parse_prometheus_text(mx.render_text())
            bytes0 = obs.metric_value(
                counters, "photon_transfer_bytes_total", default=0.0)
            secs0 = obs.metric_value(
                counters, "photon_transfer_seconds_total", default=0.0)
            builds0 = obs.metric_value(
                counters, "photon_compile_cache_misses_total",
                default=0.0)
            pass_wall = make_run(vg)(1)  # ONE measured pass (counters)
            counters = obs.parse_prometheus_text(mx.render_text())
            measured[dtype] = int(obs.metric_value(
                counters, "photon_transfer_bytes_total",
                default=0.0) - bytes0)
            transfer_frac[dtype] = round(
                (obs.metric_value(counters,
                                  "photon_transfer_seconds_total",
                                  default=0.0) - secs0)
                / max(pass_wall, 1e-9), 4)
            cells = {}
            for frac, key in ((0.0, "0"), (1.0, "100")):
                pinned = ss.pin_chunks(
                    chunked, int(round(frac * chunked.num_chunks)))
                vg_p = ss.make_value_and_gradient(losses.LOGISTIC,
                                                  chunked, pinned=pinned)
                cells[key] = round(_slope(make_run(vg_p), 2, 8), 4)
            matrix[dtype] = cells
            counters = obs.parse_prometheus_text(mx.render_text())
            warm_builds += obs.metric_value(
                counters, "photon_compile_cache_misses_total",
                default=0.0) - builds0
    finally:
        obs.disable()
    out["stream_quant_matrix_seconds"] = matrix
    out["stream_quant_bytes_per_pass"] = analytic
    out["stream_quant_metric_bytes_per_pass"] = measured
    # device_put seconds / pass wall per dtype: the wall band below is
    # only a quantization claim when the pass is actually transfer-bound
    # (on a CPU box the "transfer" is a host-side copy and the pass is
    # compute-bound — check_bench_regression reports instead of gating).
    out["stream_quant_transfer_fraction"] = transfer_frac
    out["stream_quant_int8_bytes_ratio_vs_f32"] = round(
        analytic["int8"] / max(analytic["float32"], 1), 4)
    out["stream_quant_f32_pass_seconds"] = matrix["float32"]["0"]
    out["stream_quant_int8_pass_seconds"] = matrix["int8"]["0"]
    out["stream_quant_warm_compile_misses"] = int(warm_builds)
    return out


def bench_solver_race(n=1 << 15, d=4096, nnz=16, chunk_rows=1 << 12,
                      sdca_epochs=40, lbfgs_iters=40):
    """SDCA vs L-BFGS time-to-target on ONE streamed logistic fit
    (docs/STREAMING.md "Stochastic solvers"). Both solvers consume the
    same ``ChunkedHybrid`` feed with a run ledger armed; the curves come
    from ledger provenance (``convergence_curves`` over the recorded
    ``opt_iter`` rows), the common target is the WORSE final value of
    the two plus a small relative band, and ``time_to_target`` reads
    each curve from its own start. The two final fits must also agree on
    AUC — the stochastic path is not allowed to buy wall clock with
    accuracy. Single runs, wall-clock sensitive: the line carries the
    standard load/calibration validity stamp (``solver_race_valid:
    false`` on a contended box — reported, never silently gated)."""
    import tempfile

    import jax.numpy as jnp

    from photon_ml_tpu import obs
    from photon_ml_tpu.data import sparse as sp
    from photon_ml_tpu.evaluation.evaluators import auc
    from photon_ml_tpu.obs.ledger import (RunLedger, convergence_curves,
                                          read_rows, time_to_target)
    from photon_ml_tpu.ops import losses
    from photon_ml_tpu.ops import streaming_sparse as ss
    from photon_ml_tpu.optim import OptimizerConfig
    from photon_ml_tpu.optim.stochastic import minimize_stochastic
    from photon_ml_tpu.optim.streaming import minimize_streaming

    load = os.getloadavg()[0]
    batch, _ = sp.synthetic_sparse(n, d, nnz, seed=5)
    # λ sized like the flagship sweeps (λ̄ = λ/n = 1e-4): strong enough
    # convexity for the SDCA rate to bite within the epoch budget,
    # weak enough that the fit is non-trivial.
    l2 = 1e-4 * n

    def chunks():
        for lo in range(0, n, chunk_rows):
            hi = min(lo + chunk_rows, n)
            yield sp.SparseBatch(
                indices=np.asarray(batch.indices)[lo:hi],
                values=np.asarray(batch.values)[lo:hi],
                labels=np.asarray(batch.labels)[lo:hi],
                weights=np.asarray(batch.weights)[lo:hi],
                offsets=np.asarray(batch.offsets)[lo:hi],
                num_features=d)

    chunked = ss.build_chunked(chunks(), d, chunk_rows, num_hot=256)
    vg_stream = ss.make_value_and_gradient(losses.LOGISTIC, chunked)
    v_stream = ss.make_value_only(losses.LOGISTIC, chunked)

    def vg(w):
        f, g = vg_stream(w)
        return f + 0.5 * l2 * jnp.sum(w * w), g + l2 * w

    def v(w):
        return v_stream(w) + 0.5 * l2 * jnp.sum(w * w)

    w0 = jnp.zeros((d,), jnp.float32)
    out: dict = {
        "solver_race_config":
            f"n={n} d={d} chunks={chunked.num_chunks} l2={l2:g}",
    }
    results: dict = {}
    curves: dict = {}
    walls: dict = {}
    transfer: dict = {}
    _, mx = obs.enable(trace=False, metrics=True)
    try:
        with tempfile.TemporaryDirectory(prefix="pml_race_") as td:
            for solver in ("lbfgs", "sdca"):
                led_dir = os.path.join(td, solver)
                led = RunLedger.resume(led_dir)
                prev = obs.set_ledger(led)
                counters = obs.parse_prometheus_text(mx.render_text())
                secs0 = obs.metric_value(
                    counters, "photon_transfer_seconds_total", default=0.0)
                t0 = time.perf_counter()
                try:
                    if solver == "lbfgs":
                        r = minimize_streaming(
                            vg, w0,
                            OptimizerConfig(max_iterations=lbfgs_iters,
                                            tolerance=1e-8),
                            value_only=v)
                    else:
                        r = minimize_stochastic(
                            vg, w0,
                            OptimizerConfig(max_iterations=sdca_epochs,
                                            tolerance=1e-5),
                            chunked=chunked, loss=losses.LOGISTIC,
                            l2_weight=l2, solver="sdca", value_only=v)
                finally:
                    walls[solver] = time.perf_counter() - t0
                    obs.set_ledger(prev)
                    led.close()
                counters = obs.parse_prometheus_text(mx.render_text())
                transfer[solver] = obs.metric_value(
                    counters, "photon_transfer_seconds_total",
                    default=0.0) - secs0
                rows, problems = read_rows(led_dir)
                if problems:
                    raise RuntimeError(f"race ledger {solver}: {problems}")
                curves[solver] = convergence_curves(rows)["(run)"]
                results[solver] = r
    finally:
        obs.disable()
    # device_put seconds / combined race wall: the ≤1.0x ratio gate in
    # check_bench_regression.py is only an SDCA-pays-off claim when the
    # stream is actually transfer-bound (on a CPU box the pass is
    # compute-bound and the ratio is reported only).
    out["solver_race_transfer_fraction"] = round(
        sum(transfer.values()) / max(sum(walls.values()), 1e-9), 4)

    finals = {s: float(results[s].value) for s in results}
    # Worse of the two finals, padded: BOTH curves reach it by
    # construction, so neither time_to_target can come back None.
    worst = max(finals.values())
    target = worst + 1e-4 * max(abs(worst), 1.0)
    tt = {s: time_to_target(curves[s], target) for s in curves}
    out["solver_race_target_value"] = round(target, 6)
    for s in ("lbfgs", "sdca"):
        out[f"solver_time_to_target_seconds_{s}"] = round(
            tt[s]["seconds"], 4)
        out[f"solver_race_passes_{s}"] = tt[s]["passes"]
        out[f"solver_race_final_value_{s}"] = round(finals[s], 6)
    out["solver_race_ratio"] = round(
        out["solver_time_to_target_seconds_sdca"]
        / max(out["solver_time_to_target_seconds_lbfgs"], 1e-9), 3)
    out["solver_race_final_gap_sdca"] = float(results["sdca"].grad_norm)

    # AUC of each final fit, scored sparsely: pad w with one zero so the
    # sentinel column (== d) contributes nothing to the margin.
    labels = jnp.asarray(np.asarray(batch.labels))
    idx = np.asarray(batch.indices)
    vals = np.asarray(batch.values, np.float64)
    for s in ("lbfgs", "sdca"):
        w_pad = np.append(np.asarray(results[s].w, np.float64), 0.0)
        margins = (w_pad[idx] * vals).sum(axis=1)
        out[f"solver_race_auc_{s}"] = round(
            float(auc(jnp.asarray(margins, jnp.float32), labels)), 5)
    out["solver_race_auc_delta"] = round(
        abs(out["solver_race_auc_sdca"] - out["solver_race_auc_lbfgs"]), 5)

    # Trimmed curves for the round-over-round record: [seconds-from-
    # start, value, gap] per accepted iteration/epoch, ≤ 24 points.
    for s in ("lbfgs", "sdca"):
        pts = curves[s]
        t0 = pts[0]["t"]
        stride = max(1, (len(pts) + 23) // 24)
        kept = pts[::stride] + ([pts[-1]] if (len(pts) - 1) % stride else [])
        out[f"solver_race_curve_{s}"] = [
            [round(p["t"] - t0, 4), round(p["value"], 6),
             (round(p["gap"], 8) if p.get("gap") is not None else None)]
            for p in kept]

    reasons = []
    if load > LOAD_GATE:
        reasons.append(f"load_avg_1m {load:.2f} > {LOAD_GATE}")
    factor = _HOST_CAL.get("factor")
    if factor is not None and factor > CALIBRATION_GATE:
        reasons.append(f"host calibration {factor:.1f}x the clean-box "
                       f"reference")
    if reasons:
        out["solver_race_valid"] = False
        out["solver_race_invalid_reason"] = "; ".join(reasons)
    return out


def _fabric_chunked(n, d, nnz, chunk_rows, num_hot):
    from photon_ml_tpu.data import sparse as sp
    from photon_ml_tpu.ops import streaming_sparse as ss

    batch, _ = sp.synthetic_sparse(n, d, nnz, seed=7)

    def chunks():
        for lo in range(0, n, chunk_rows):
            hi = min(lo + chunk_rows, n)
            yield sp.SparseBatch(
                indices=np.asarray(batch.indices)[lo:hi],
                values=np.asarray(batch.values)[lo:hi],
                labels=np.asarray(batch.labels)[lo:hi],
                weights=np.asarray(batch.weights)[lo:hi],
                offsets=np.asarray(batch.offsets)[lo:hi],
                num_features=d)

    return ss.build_chunked(chunks(), d, chunk_rows, num_hot=num_hot)


def _fabric_rehome_drill(out):
    """Cross-machine re-home window (docs/SERVING.md "Multi-host
    fleet"): 2 machine agents + a 2-replica remote fleet, whole-machine
    SIGKILL under live traffic. Lines: the fleet's own shard re-home
    window (``fabric_rehome_seconds``, gated <= its deadline), the full
    cross-machine respawn wall (reported), unserved + client failures
    (gated == 0), and drill-score parity vs the fleet's pre-drill bits.
    On a <4-core box agents + replicas + fleet + driver share cores and
    the walls measure scheduler contention — stamped invalid, gates
    become reported-only."""
    import signal
    import subprocess
    import tempfile
    import threading
    import urllib.request

    import jax.numpy as jnp

    from photon_ml_tpu.fabric.transport import RemoteTransport
    from photon_ml_tpu.game.models import (FixedEffectModel, GameModel,
                                           RandomEffectModel)
    from photon_ml_tpu.models import io as model_io
    from photon_ml_tpu.models.coefficients import Coefficients
    from photon_ml_tpu.serving.fleet import (ServingFleet,
                                             make_fleet_http_server)
    from photon_ml_tpu.types import TaskType

    ents, dg, dr = 32, 6, 4
    rng = np.random.default_rng(11)
    model = GameModel(task=TaskType.LOGISTIC_REGRESSION, models={
        "fixed": FixedEffectModel("global", Coefficients(
            jnp.asarray(rng.normal(size=dg).astype(np.float32)))),
        "per-user": RandomEffectModel(
            "userId", "re_userId",
            jnp.asarray(rng.normal(size=(ents, dr)).astype(np.float32))),
    })
    objs = []
    req_rng = np.random.default_rng(5)
    for i in range(12):
        objs.append({
            "features": {
                "global": req_rng.normal(size=dg).astype(
                    np.float32).tolist(),
                "re_userId": req_rng.normal(size=dr).astype(
                    np.float32).tolist()},
            "entity_ids": {"userId": int(i % ents)}, "uid": i})

    def post_one(url, obj):
        body = json.dumps({"requests": [obj]}).encode()
        req = urllib.request.Request(
            url + "/score", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30.0) as resp:
            return np.float32(json.loads(resp.read())["scores"][0])

    def start_agent(workdir, name):
        os.makedirs(workdir, exist_ok=True)
        ready = os.path.join(workdir, "agent.ready")
        env = dict(os.environ)
        repo = os.path.dirname(os.path.abspath(__file__))
        env["PYTHONPATH"] = (repo + os.pathsep + env["PYTHONPATH"]
                             if env.get("PYTHONPATH") else repo)
        with open(os.path.join(workdir, "agent.log"), "ab") as log_f:
            proc = subprocess.Popen(
                [sys.executable, "-m", "photon_ml_tpu.fabric.agent",
                 "--workdir", workdir, "--machine", name,
                 "--host", "127.0.0.1", "--port", "0",
                 "--ready-file", ready],
                stdout=log_f, stderr=subprocess.STDOUT, env=env,
                start_new_session=True)
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                raise RuntimeError(
                    f"agent {name} exited rc={proc.returncode}")
            if os.path.exists(ready):
                try:
                    with open(ready) as f:
                        info = json.load(f)
                    return proc, f"http://127.0.0.1:{int(info['port'])}"
                except (OSError, ValueError):
                    pass  # torn read mid-write; poll again
            time.sleep(0.05)
        raise RuntimeError(f"agent {name} not ready before its deadline")

    def kill_machine(proc):
        try:
            os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        except (OSError, ProcessLookupError):
            pass
        try:
            proc.wait(timeout=5.0)
        except subprocess.TimeoutExpired:
            pass

    agents, server, fleet = [], None, None
    rehome_deadline_s = 5.0
    with tempfile.TemporaryDirectory(prefix="pml_bench_fabric_") as td:
        model_dir = os.path.join(td, "model")
        model_io.save_game_model(model, model_dir)
        try:
            agents = [start_agent(os.path.join(td, f"m{m}"), f"m{m}")
                      for m in range(2)]
            fleet = ServingFleet(
                replica_args=["--model-dir", model_dir,
                              "--max-wait-ms", "0.5"],
                num_replicas=2, workdir=os.path.join(td, "work"),
                probe_interval_s=0.1, heartbeat_deadline_s=1.0,
                rehome_deadline_s=rehome_deadline_s,
                retry_backoff_s=0.4, retries=4)
            fleet.supervisor.transport = RemoteTransport(
                [u for _, u in agents], fleet._replica_argv,
                timeout_s=2.0)
            fleet.start()
            server = make_fleet_http_server(fleet, port=0)
            threading.Thread(target=server.serve_forever,
                             daemon=True).start()
            url = f"http://127.0.0.1:{server.server_address[1]}"
            expected = np.asarray([post_one(url, o) for o in objs],
                                  np.float32)
            before = fleet.metrics.snapshot()
            stop = threading.Event()
            failures, served = [], []

            def scorer():
                i = 0
                while not stop.is_set():
                    obj = objs[i % len(objs)]
                    try:
                        served.append((i % len(objs), post_one(url, obj)))
                    except Exception as e:  # noqa: BLE001 drill verdict
                        failures.append((i, repr(e)))
                    i += 1
                    time.sleep(0.05)

            t = threading.Thread(target=scorer, daemon=True)
            t.start()
            try:
                time.sleep(0.5)  # traffic flowing on both replicas
                t0 = time.monotonic()
                kill_machine(agents[0][0])  # machine 0 is GONE
                # First the supervisor must NOTICE (probe/heartbeat
                # deadline) — polling for "recovered" straight away
                # would read the pre-death state as a 0-second drill.
                deadline = time.monotonic() + 30.0
                noticed = False
                while time.monotonic() < deadline:
                    if (fleet._degraded or fleet.supervisor.states()
                            != {0: "up", 1: "up"}):
                        noticed = True
                        break
                    time.sleep(0.05)
                detect_s = time.monotonic() - t0
                deadline = time.monotonic() + 120.0
                while time.monotonic() < deadline:
                    if (fleet.supervisor.states() == {0: "up", 1: "up"}
                            and not fleet._degraded):
                        break
                    time.sleep(0.2)
                recovery_s = time.monotonic() - t0
                recovered = noticed and (
                    fleet.supervisor.states() == {0: "up", 1: "up"}
                    and not fleet._degraded)
                time.sleep(0.5)  # a post-recovery traffic tail
            finally:
                stop.set()
                t.join(timeout=60.0)
            after = fleet.metrics.snapshot()
            handle = fleet.supervisor.replicas[0]
            mismatches = sum(1 for idx, s in served
                             if s != expected[idx])
            out["fabric_rehome_seconds"] = round(
                after["rehome_seconds_max"], 3)
            out["fabric_rehome_deadline_s"] = rehome_deadline_s
            out["fabric_detect_seconds"] = round(detect_s, 3)
            out["fabric_recovery_seconds"] = round(recovery_s, 3)
            out["fabric_recovered"] = recovered
            out["fabric_crossed_machines"] = (
                handle.machine == agents[1][1])
            out["fabric_unserved_total"] = int(
                after["unserved_total"] - before["unserved_total"]
                + len(failures))
            out["fabric_drill_requests"] = len(served)
            out["fabric_drill_parity_ok"] = mismatches == 0
            out["fabric_drill_parity_mismatches"] = mismatches
        finally:
            if server is not None:
                server.shutdown()
                server.server_close()
            if fleet is not None:
                fleet.close()
            for proc, _ in agents:
                kill_machine(proc)


def bench_fabric(n=1 << 14, d=2048, nnz=16, chunk_rows=1 << 11,
                 passes=12):
    """Multi-host fabric lines (docs/STREAMING.md "Multi-host
    streaming"; gated by check_bench_regression.py):

    - ``fabric_d1_parity_max_abs_diff`` — the W=1 short-circuit's
      (value, gradient, margins) vs the local chunked stream; REQUIRED
      exactly 0.0 (single-group runs must be BIT-identical, or every
      single-host result becomes un-reproducible on the fabric path);
    - ``fabric_dcn_allreduce_ms_per_pass`` / ``_bytes_per_pass`` — a
      2-rank world (threaded hosts, real sockets) streaming the shared
      pass; the per-round DCN wall and wire bytes come from the
      fabric's own counters, so the line cross-checks the ONE-allreduce
      -per-pass design invariant (``fabric_dcn_rounds_per_pass``);
    - the cross-machine re-home drill lines (see
      ``_fabric_rehome_drill``), validity-stamped on <4-core boxes.

    Standalone (``python bench.py bench_fabric``): the drill spawns
    agents + replica subprocesses, which would contend with the device
    phases if run inside the full sweep."""
    import jax.numpy as jnp

    from photon_ml_tpu import obs
    from photon_ml_tpu.fabric.collective import FabricComm
    from photon_ml_tpu.fabric.stream import FabricChunkStream
    from photon_ml_tpu.obs.metrics import MetricsRegistry
    from photon_ml_tpu.ops import losses
    from photon_ml_tpu.ops import streaming_sparse as ss

    chunked = _fabric_chunked(n, d, nnz, chunk_rows, num_hot=64)
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=d).astype(np.float32))
    off = jnp.zeros((chunked.num_chunks * chunked.chunk_rows,))
    out: dict = {
        "fabric_pass_config":
            f"n={n} d={d} chunks={chunked.num_chunks}",
    }

    # --- D=1 single-group bit-parity (the gate) ------------------------
    comm = FabricComm(0, 1)
    try:
        fs = FabricChunkStream(chunked, comm)
        v_f, g_f = fs.value_and_gradient(losses.LOGISTIC)(w, off)
        m_f = np.asarray(fs.margins(w))
    finally:
        comm.close()
    v_l, g_l = ss.make_value_and_gradient(losses.LOGISTIC, chunked)(w, off)
    m_l = np.asarray(ss.margins_chunked(chunked, w))
    out["fabric_d1_parity_max_abs_diff"] = float(max(
        abs(float(v_f) - float(v_l)),
        float(np.max(np.abs(np.asarray(g_f) - np.asarray(g_l)))),
        float(np.max(np.abs(m_f - m_l)))))

    # --- 2-rank DCN allreduce wall per pass ----------------------------
    mx = MetricsRegistry()
    with obs.activated(metrics_obj=mx):
        comms = [FabricComm(0, 2, timeout_s=120.0)]
        comms.append(FabricComm(1, 2, coordinator=comms[0].coordinator,
                                timeout_s=120.0))
        walls = [None, None]

        def host(rank):
            fs = FabricChunkStream(chunked, comms[rank])
            vg = fs.value_and_gradient(losses.LOGISTIC)
            vg(w, off)  # warm both ranks' compiled pass
            t0 = time.perf_counter()
            for _ in range(passes):
                v, _g = vg(w, off)
            float(v)
            walls[rank] = time.perf_counter() - t0

        import threading
        threads = [threading.Thread(target=host, args=(r,), daemon=True)
                   for r in (0, 1)]
        try:
            for t in threads:
                t.start()
        finally:
            for t in threads:
                t.join(600.0)
        for c in comms:
            c.close()
    if any(wl is None for wl in walls):
        raise RuntimeError("a fabric rank never finished its passes")
    snap = mx.snapshot()
    rounds = snap.get('photon_fabric_allreduce_total{op="allreduce"}', 0)
    dcn_s = snap.get("photon_fabric_allreduce_seconds_total", 0.0)
    wire = snap.get("photon_fabric_bytes_total", 0)
    out["fabric_world"] = 2
    out["fabric_passes"] = passes
    # rounds counts per-rank completions: world x (warmup + passes).
    out["fabric_dcn_rounds_per_pass"] = round(
        rounds / (2 * (passes + 1)), 3)
    out["fabric_dcn_allreduce_ms_per_pass"] = round(
        1e3 * dcn_s / max(rounds, 1), 4)
    out["fabric_dcn_bytes_per_pass"] = round(wire / max(rounds, 1))
    out["fabric_pass_seconds"] = round(max(walls) / passes, 4)

    # --- the cross-machine drill (validity-stamped) --------------------
    _progress("fabric: cross-machine re-home drill (2 agents, "
              "whole-machine SIGKILL)")
    _fabric_rehome_drill(out)
    cores = os.cpu_count() or 1
    if cores < 4:
        out["fabric_rehome_valid"] = False
        out["fabric_rehome_invalid_reason"] = (
            f"{cores} cores < 4 — agents, replicas, fleet, and driver "
            f"share cores; the drill walls measure scheduler "
            f"contention, not re-home")
    return out


def bench_game_iteration(n=100_000, n_users=2000, n_items=500):
    """One GAME coordinate-descent sweep (fixed + per-user + per-item),
    steady-state, by the slope between 1- and 6-iteration runs."""
    from photon_ml_tpu.data import synthetic
    from photon_ml_tpu.data.game_data import from_synthetic
    from photon_ml_tpu.game import descent
    from photon_ml_tpu.game.coordinates import (FixedEffectCoordinate,
                                                RandomEffectCoordinate)
    from photon_ml_tpu.ops import losses
    from photon_ml_tpu.optim import OptimizerConfig
    from photon_ml_tpu.optim.problem import GLMOptimizationConfiguration
    from photon_ml_tpu.optim.regularization import (RegularizationContext,
                                                    RegularizationType)
    from photon_ml_tpu.parallel.mesh import make_mesh
    from photon_ml_tpu.types import TaskType

    rng = np.random.default_rng(0)
    ds = from_synthetic(synthetic.game_data(
        rng, n=n, d_global=32,
        re_specs={"userId": (n_users, 8), "itemId": (n_items, 8)}))
    mesh = make_mesh()
    cfg = GLMOptimizationConfiguration(
        optimizer=OptimizerConfig(max_iterations=25, tolerance=1e-7),
        regularization=RegularizationContext(RegularizationType.L2, 1.0))
    coords = {
        "fixed": FixedEffectCoordinate(ds, "global", losses.LOGISTIC, cfg,
                                       mesh),
        "per-user": RandomEffectCoordinate(ds, "userId", "re_userId",
                                           losses.LOGISTIC, cfg, mesh),
        "per-item": RandomEffectCoordinate(ds, "itemId", "re_itemId",
                                           losses.LOGISTIC, cfg, mesh),
    }
    seq = ["fixed", "per-user", "per-item"]

    def run(iters):
        cd = descent.CoordinateDescentConfig(seq, iterations=iters)
        t0 = time.perf_counter()
        model, _ = descent.run(TaskType.LOGISTIC_REGRESSION, coords, cd)
        np.asarray(model.models["fixed"].coefficients.means)
        np.asarray(model.models["per-user"].means[:1])
        return time.perf_counter() - t0

    # Wide span: each sweep is ~40-150 ms steady-state, so a (1, 11)
    # separation keeps tunnel RPC jitter (~10 ms/dispatch) out of the
    # reported per-iteration figure.
    return _slope(run, 1, 11)


def bench_game_20m():
    """North-star MovieLens-20M-shaped CD sweep (BASELINE config 4) —
    gated behind PML_BENCH_20M=1: generation + staging + the timed descents
    add ~10+ minutes, too slow for every capture. The measurement itself
    lives in dev-scripts/flagship_movielens.py (shared, min-of-3 slope)."""
    import importlib.util
    import os

    if os.environ.get("PML_BENCH_20M") != "1":
        return {}
    spec = importlib.util.spec_from_file_location(
        "flagship_movielens",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "dev-scripts", "flagship_movielens.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    # bf16 feature storage is the validated flagship configuration (the
    # f32 blocks pack ~2x the HBM; see dev-scripts/flagship_movielens.py).
    out = mod.run_flagship(feature_dtype="bfloat16", log=_progress)
    return {k: v for k, v in out.items()
            if k in ("game_cd_iteration_seconds_20m",
                     "flagship_validation_auc",
                     "flagship_first_descent_seconds")}


def bench_sweep(n=200_000, n_users=5_000, d_re=4, iterations=12,
                theta=0.05, grad_tol=0.05):
    """Full vs gate=0 vs dirty-gated GAME coordinate descent
    (docs/SWEEPS.md). Three arms over the SAME synthetic dataset, each
    with a run ledger armed:

    * ``full``  — HEAD's full-sweep descent (``sweep=None``).
    * ``gate0`` — ``--sweep`` with theta=0, grad_tol=0: must be
      BIT-identical to ``full`` and its wall inside the band (the
      normalization claim has a measured shape).
    * ``gated`` — the perf claim: outer iterations >= 2 refit only
      dirty entities, so their summed random-effect update wall drops;
      the final AUC must stay inside the 5e-3 band.

    Two perf lines, different claims:

    * ``sweep_steady_ratio`` — gated/full STEADY-state random-effect
      iteration wall (min ``train_seconds`` over outer iterations >= 2,
      backstop excluded). Once the skip fraction saturates, a gated
      sweep dispatches (almost) nothing — this is the per-sweep cost
      the flagship run pays for most of its iterations, and the gated
      <= 1.0x band gate in check_bench_regression.py reads it.
    * ``sweep_iter2plus_speedup`` — full/gated SUMMED random-effect
      ``train_seconds`` over outer iterations >= 2 (warm-up sweep
      excluded — full in both arms by construction; the final backstop
      stays in as part of the gated cost). This includes the gated
      arm's one-time compacted-wave program compiles, which on a CPU
      bench box are the same order as the solves themselves — so the
      >= 1.5x acceptance reading is gated only at flagship scale
      (``sweep_flagship``), where minutes-long sweeps dwarf compiles;
      at default scale it is reported only, like the quant wall.

    The skip-fraction curve and the refit/skipped counters come from
    the same ledger/metrics provenance the estimator emits in
    production. Flagship 10M-row/1M-entity scale rides behind
    PML_BENCH_SWEEP_10M=1 (generation + staging add tens of minutes);
    the default config keeps the same shape at capture-every-round
    cost."""
    import tempfile

    import jax.numpy as jnp

    from photon_ml_tpu import obs
    from photon_ml_tpu.data import synthetic
    from photon_ml_tpu.data.game_data import from_synthetic
    from photon_ml_tpu.evaluation.evaluators import auc
    from photon_ml_tpu.game import descent
    from photon_ml_tpu.game.coordinates import (FixedEffectCoordinate,
                                                RandomEffectCoordinate)
    from photon_ml_tpu.game.sweep import SweepConfig
    from photon_ml_tpu.obs.ledger import (RunLedger, fit_wave_summary,
                                          read_rows)
    from photon_ml_tpu.ops import losses
    from photon_ml_tpu.optim import OptimizerConfig
    from photon_ml_tpu.optim.problem import GLMOptimizationConfiguration
    from photon_ml_tpu.optim.regularization import (RegularizationContext,
                                                    RegularizationType)
    from photon_ml_tpu.parallel.mesh import make_mesh
    from photon_ml_tpu.types import TaskType

    flagship = os.environ.get("PML_BENCH_SWEEP_10M") == "1"
    if flagship:
        n, n_users = 10_000_000, 1_000_000

    load = os.getloadavg()[0]
    rng = np.random.default_rng(11)
    ds = from_synthetic(synthetic.game_data(
        rng, n=n, d_global=16, re_specs={"userId": (n_users, d_re)}))
    mesh = make_mesh()
    opt = GLMOptimizationConfiguration(
        optimizer=OptimizerConfig(max_iterations=40, tolerance=1e-7),
        regularization=RegularizationContext(RegularizationType.L2, 1.0))
    seq = ["fixed", "per-user"]
    cd = descent.CoordinateDescentConfig(seq, iterations=iterations)
    y = jnp.asarray(ds.response)
    arms = {
        "full": None,
        "gate0": SweepConfig(),
        "gated": SweepConfig(theta=theta, grad_tol=grad_tol),
    }
    out: dict = {
        "sweep_config": f"n={n} users={n_users} d_re={d_re} "
                        f"iters={iterations} theta={theta:g} "
                        f"grad_tol={grad_tol:g}",
        "sweep_flagship": flagship,
    }
    models: dict = {}
    waves: dict = {}
    steady: dict = {}
    # Warm-up: one short ungated descent on throwaway coordinates so
    # the shared full-sweep programs compile before any arm's clock
    # starts — otherwise whichever arm runs first eats every compile
    # and the full-vs-gate0 wall comparison measures XLA, not descent.
    descent.run(TaskType.LOGISTIC_REGRESSION, {
        "fixed": FixedEffectCoordinate(ds, "global", losses.LOGISTIC,
                                       opt, mesh),
        "per-user": RandomEffectCoordinate(ds, "userId", "re_userId",
                                           losses.LOGISTIC, opt, mesh),
    }, descent.CoordinateDescentConfig(seq, iterations=2))
    _, mx = obs.enable(trace=False, metrics=True)
    try:
        with tempfile.TemporaryDirectory(prefix="pml_sweep_") as td:
            for arm, sweep in arms.items():
                # Fresh coordinates per arm: staged buckets and jitted
                # programs must not leak between arms (the full arm's
                # compiles are part of its own first iteration, same as
                # the gated arm's compacted-wave compiles are part of
                # its).
                coords = {
                    "fixed": FixedEffectCoordinate(
                        ds, "global", losses.LOGISTIC, opt, mesh),
                    "per-user": RandomEffectCoordinate(
                        ds, "userId", "re_userId", losses.LOGISTIC,
                        opt, mesh),
                }
                led_dir = os.path.join(td, arm)
                led = RunLedger.resume(led_dir)
                prev = obs.set_ledger(led)
                t0 = time.perf_counter()
                try:
                    model, hist = descent.run(
                        TaskType.LOGISTIC_REGRESSION, coords, cd,
                        sweep=sweep)
                finally:
                    out[f"sweep_wall_seconds_{arm}"] = round(
                        time.perf_counter() - t0, 3)
                    obs.set_ledger(prev)
                    led.close()
                models[arm] = model
                rows, problems = read_rows(led_dir)
                if problems:
                    raise RuntimeError(f"sweep ledger {arm}: {problems}")
                waves[arm] = fit_wave_summary(rows).get("per-user", [])
                re_wall = {}
                for rec in hist.records:
                    if rec["coordinate"] == "per-user":
                        re_wall[rec["iteration"]] = rec["train_seconds"]
                out[f"sweep_re_wall_iter2plus_{arm}"] = round(
                    sum(s for it, s in re_wall.items() if it >= 1), 3)
                steady[arm] = round(min(
                    (s for it, s in re_wall.items()
                     if 1 <= it < iterations - 1), default=0.0), 4)
                out[f"sweep_re_steady_iter_seconds_{arm}"] = steady[arm]
                out[f"sweep_auc_{arm}"] = round(
                    float(auc(model.score(ds), y)), 5)
                _progress(f"sweep arm {arm}: "
                          f"{out[f'sweep_wall_seconds_{arm}']}s, auc "
                          f"{out[f'sweep_auc_{arm}']}")
        snap = mx.snapshot()
    finally:
        obs.disable()

    out["sweep_iter2plus_speedup"] = round(
        out["sweep_re_wall_iter2plus_full"]
        / max(out["sweep_re_wall_iter2plus_gated"], 1e-9), 3)
    out["sweep_steady_ratio"] = round(
        steady["gated"] / max(steady["full"], 1e-9), 4)
    out["sweep_auc_delta"] = round(
        abs(out["sweep_auc_gated"] - out["sweep_auc_full"]), 5)
    out["sweep_gate0_bit_identical"] = bool(
        np.array_equal(np.asarray(models["full"].models["per-user"].means),
                       np.asarray(models["gate0"].models["per-user"].means))
        and np.array_equal(
            np.asarray(models["full"].models["fixed"].coefficients.means),
            np.asarray(models["gate0"].models["fixed"].coefficients.means)))
    out["sweep_gated_coeff_max_delta"] = round(float(np.max(np.abs(
        np.asarray(models["gated"].models["per-user"].means)
        - np.asarray(models["full"].models["per-user"].means)))), 6)
    # Skip fraction per outer iteration, from the gated arm's ledger
    # provenance (the photon-obs diff overlay reads the same rows).
    out["sweep_skip_fraction_curve"] = [
        round(e["entities_skipped"]
              / max(e["entities_fit"] + e["entities_skipped"], 1), 4)
        for e in waves["gated"]]
    out["sweep_entities_refit_total"] = int(sum(
        v for k, v in snap.items()
        if k.startswith("photon_re_entities_refit_total")))
    out["sweep_entities_skipped_total"] = int(sum(
        v for k, v in snap.items()
        if k.startswith("photon_re_entities_skipped_total")))

    reasons = []
    if load > LOAD_GATE:
        reasons.append(f"load_avg_1m {load:.2f} > {LOAD_GATE}")
    factor = _HOST_CAL.get("factor")
    if factor is not None and factor > CALIBRATION_GATE:
        reasons.append(f"host calibration {factor:.1f}x the clean-box "
                       f"reference")
    if reasons:
        out["sweep_valid"] = False
        out["sweep_invalid_reason"] = "; ".join(reasons)
    return out


def bench_criteo_stream():
    """Criteo row-axis streamed fit (n=100M, d=1M, E=1M) — gated behind
    PML_BENCH_CRITEO=1: the run takes over an hour (generation + fresh
    remote compiles + a streamed descent). The measurement lives in
    dev-scripts/flagship_criteo_stream.py; committed numbers in
    docs/PARITY.md "Criteo row axis"."""
    import importlib.util
    import os

    if os.environ.get("PML_BENCH_CRITEO") != "1":
        return {}
    spec = importlib.util.spec_from_file_location(
        "flagship_criteo_stream",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "dev-scripts", "flagship_criteo_stream.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    out = mod.run_criteo_stream(log=_progress)
    return {k: v for k, v in out.items() if k.startswith("criteo_stream")}


def _staging_in_subprocess():
    """bench_host_staging in a FRESH python process. In-process, the pass
    measures 10-11 s standalone but 39-46 s after the full device-phase
    sequence has run (reproduced in two full captures; a single prior small
    phase does NOT trigger it) — some accumulation of device-runtime state
    interferes with the host-side sorts. A subprocess gives the host
    benchmark the clean environment its number is supposed to describe."""
    import subprocess
    import tempfile

    # stderr passes through: the child runs ~15 s with no other progress
    # marker, and on failure its traceback must reach the bench log. The
    # result comes back via a temp file, not stdout — stray prints from
    # the child's import chain must not corrupt the JSON.
    with tempfile.NamedTemporaryFile(mode="r", suffix=".json") as f:
        subprocess.run(
            [sys.executable, "-c",
             "import json, sys, bench;"
             " json.dump(bench.bench_fresh_host_suite(),"
             " open(sys.argv[1], 'w'))", f.name],
            cwd=os.path.dirname(os.path.abspath(__file__)), check=True)
        return json.load(f)


def main():
    # Host-side staging FIRST: after the device phases run, even a fresh
    # subprocess measures ~3x slow on this 1-core box (the parent's
    # device-runtime background threads compete for the core).
    _progress("host staging at 10M rows / 1M entities (subprocess)")
    staging = _staging_in_subprocess()
    _progress("gradient step")
    grad = bench_gradient_step()
    _progress("optimizer iterations")
    opt = bench_optimizer_steps()
    _progress("sparse 1M-feature step")
    sparse = bench_sparse()
    _progress("sparse random effect")
    sparse_re = bench_sparse_random_effect()
    _progress("streamed pass: pinned-fraction curve + sharded merge")
    stream = bench_stream_pinned()
    _progress("streamed pass: pinned x quantized dtype matrix")
    stream_quant = bench_stream_quant()
    _progress("solver race: sdca vs l-bfgs time-to-target")
    race = bench_solver_race()
    _progress("pallas scatter")
    scatter = bench_pallas_scatter()  # {} off-TPU
    _progress("kernel registry sweep: fused vs xla")
    ksweep = bench_kernels()  # interpret lines stamped invalid off-TPU
    # Avro ingestion lines ride the fresh-host subprocess suite above
    # (bench_avro_ingest + bench_ingest_cold_fit inside
    # bench_fresh_host_suite) — host-side work measured in a clean
    # process, same discipline as staging.
    _progress("GAME coordinate-descent sweep")
    game_iter_s = bench_game_iteration()
    _progress("dirty-gated sweeps: full vs gate0 vs gated")
    sweep = bench_sweep()
    game_20m = bench_game_20m()  # {} unless PML_BENCH_20M=1
    criteo = bench_criteo_stream()  # {} unless PML_BENCH_CRITEO=1
    _progress("done")
    print(json.dumps({
        "metric": "glm_gradient_step_samples_per_sec_per_chip",
        "value": round(grad["samples_per_sec"]),
        "unit": "samples/sec/chip",
        "vs_baseline": round(grad["samples_per_sec"]
                             / grad["cpu_numpy_samples_per_sec"], 3),
        "secondary": {
            "bf16_samples_per_sec": round(grad["bf16_samples_per_sec"]),
            "achieved_gflops": round(grad["achieved_gflops"], 1),
            "achieved_gbytes_per_sec": round(
                grad["achieved_gbytes_per_sec"], 1),
            "lbfgs_full_iteration_ms": round(opt["lbfgs_iteration_ms"], 3),
            "tron_full_iteration_ms": round(opt["tron_iteration_ms"], 3),
            "sparse_1m_feature_samples_per_sec": round(
                sparse["sparse_samples_per_sec"]),
            "sparse_gnnz_per_sec": round(sparse["sparse_gnnz_per_sec"], 3),
            "sparse_bf16_samples_per_sec": round(
                sparse["sparse_bf16_samples_per_sec"]),
            "sparse_ell_samples_per_sec":
                sparse["sparse_ell_samples_per_sec"],
            "sparse_hybrid_hot_cols": sparse["sparse_hybrid_hot_cols"],
            "sparse_hybrid_sharded_samples_per_sec":
                sparse["sparse_hybrid_sharded_samples_per_sec"],
            **sparse_re,
            **stream,
            **stream_quant,
            **race,
            **staging,
            **{key: round(v, 1) for key, v in scatter.items()},
            **ksweep,
            "game_cd_iteration_seconds": round(game_iter_s, 3),
            **sweep,
            **game_20m,
            **criteo,
            "cpu_numpy_baseline_samples_per_sec": round(
                grad["cpu_numpy_samples_per_sec"]),
            "timing_method": "dependency-chain slope (async-tunnel safe)",
        },
    }))


if __name__ == "__main__":
    # ``python bench.py bench_kernels`` (or any other bench_* function)
    # runs one section and prints its JSON — the sweep workflow in
    # docs/KERNELS.md commits these objects as flip evidence.
    if len(sys.argv) > 1:
        fn = globals().get(sys.argv[1])
        if not (sys.argv[1].startswith("bench_") and callable(fn)):
            print(f"unknown bench section {sys.argv[1]!r} (want one of "
                  f"{sorted(k for k in globals() if k.startswith('bench_'))})",
                  file=sys.stderr)
            sys.exit(2)
        print(json.dumps(fn()))
    else:
        main()
