"""Block-parallel, pipelined Avro decode with a deterministic merge.

BENCH_r05 pinned native Avro decode at ~123k records/s — ~81 s of
SERIAL work in front of the 10M-row cold fit, nearly 2x the entire
parallelized staging pass it feeds (docs/STAGING.md). This module is
the staging pipeline's structure applied one layer upstream: the input
splits at Avro sync-marker block boundaries (ingest/blocks.py), native
decode workers fan over the resulting chunks — a thread pool by
default, because the ctypes calls into native/avro_decode.cc release
the GIL for the whole block decode, with the spawn-process fallback
shared with staging (utils/workers.py) — and a depth-bounded
producer/consumer seam hands decoded column batches to the fold in
plan order as they finish. Scheduling never changes content: the
in-order concatenation of chunk outputs is bit-identical to the serial
whole-file read (tests/test_ingest.py parametrizes worker counts and
both pool modes against the serial reader).

The columnar ingest cache (ingest/cache.py) rides the same seam: each
chunk's decoded columns persist (atomically, CRC-committed) the moment
the chunk is decoded, so warm restarts memory-map columns instead of
re-decoding Avro and a killed run resumes with per-chunk partial
credit.

Failure contract: a chunk whose decode raises (corrupt block, bad
record) fails the read at that chunk's PLAN position — the consumer
drains in order, so the surfaced error is the first bad chunk in
record order, matching the serial reader's fail-fast point. A broken
process pool (crashed worker) quarantines the pool and re-decodes the
remaining chunks inline on the scheduler thread, bit-identically.
Faults are injectable at ``ingest.decode_block`` / ``ingest.cache_write``
/ ``ingest.cache_file`` (photon_ml_tpu/faults, docs/ROBUSTNESS.md).
"""

from __future__ import annotations

import concurrent.futures as cf
import dataclasses
import logging
import os
import threading
import time
from typing import Optional

import numpy as np

from photon_ml_tpu import faults as flt
from photon_ml_tpu.avro import native_decode as nd
from photon_ml_tpu.ingest import cache as ing_cache
from photon_ml_tpu.ingest.blocks import ChunkSpec
from photon_ml_tpu.utils import events as ev_mod
from photon_ml_tpu.utils import workers as pools

logger = logging.getLogger("photon_ml_tpu.ingest")


@dataclasses.dataclass(frozen=True)
class IngestConfig:
    """Knobs of the parallel ingestion pipeline.

    ``workers``: decode pool size (None -> os.cpu_count()). ``mode``:
    "thread" (default; the native block decode releases the GIL) or
    "process" (spawn, shared with StagingConfig — for exotic workloads
    where Python-side work dominates). ``pipeline_depth``: max
    decoded-but-unfolded chunks (None -> workers + 2) — bounds host
    memory the way StagingConfig.pipeline_depth bounds staged shards.
    ``chunk_records``: target records per decode task (chunks round up
    to whole Avro blocks). ``cache_dir``: columnar ingest cache root
    (None disables caching).
    """

    workers: Optional[int] = None
    mode: str = "thread"
    pipeline_depth: Optional[int] = None
    chunk_records: int = 65536
    cache_dir: Optional[str] = None

    def __post_init__(self):
        if self.mode not in ("thread", "process"):
            raise ValueError(f"ingest mode must be 'thread' or "
                             f"'process', got {self.mode!r}")
        for name in ("workers", "pipeline_depth"):
            v = getattr(self, name)
            if v is not None and v < 1:
                raise ValueError(f"ingest {name} must be >= 1, got {v}")
        if self.chunk_records < 1:
            raise ValueError(f"ingest chunk_records must be >= 1, "
                             f"got {self.chunk_records}")

    def resolved_workers(self) -> int:
        return max(1, self.workers or os.cpu_count() or 1)

    def resolved_depth(self) -> int:
        return self.pipeline_depth or self.resolved_workers() + 2


def _decode_chunk_task(spec: ChunkSpec, plan: np.ndarray, n_bags: int,
                       cache_dir: Optional[str], key: Optional[str]):
    """One pool task: decode a sync-aligned byte range and (optionally)
    commit its columns to the ingest cache. Module-level so the spawn
    process pool can pickle it; in thread mode it runs in the driver
    process, so the ``ingest.cache_write`` fault site fires there (the
    chaos suite's driver-kill drill)."""
    flt.fire(flt.sites.INGEST_DECODE_BLOCK, index=spec.index)
    d = nd.decode_span(spec.path, spec.header_len, spec.start, spec.end,
                       plan, n_bags)
    if cache_dir and key:
        try:
            ing_cache.save_chunk(cache_dir, key, spec.index, d)
        except OSError as e:
            # The cache is best-effort; ingestion is not.
            logger.warning(
                "ingest cache write for chunk %d failed (%s: %s); "
                "ingestion continues", spec.index, type(e).__name__, e)
    return d


class IngestPipeline:
    """Background decode pipeline over one ingest plan.

    Construction probes the cache and starts a daemon scheduler thread;
    ``chunks()`` yields each chunk's ``DecodedFile`` in plan order as it
    becomes available (blocking), releasing the depth bound as the
    consumer folds — the ingestion analogue of
    ``ProjectionStager.shards()``.
    """

    def __init__(self, chunks: list[ChunkSpec], plans: list[np.ndarray],
                 n_bags: int, config: Optional[IngestConfig] = None,
                 cache_key: Optional[str] = None,
                 emitter: Optional[ev_mod.EventEmitter] = None):
        self.config = config or IngestConfig()
        self.plan = chunks
        self._plans = plans  # per input file, indexed by spec.file_index
        self._n_bags = n_bags
        self._cache_dir = self.config.cache_dir if cache_key else None
        self._cache_key = cache_key
        self._emitter = emitter or ev_mod.default_emitter
        self._futures = [cf.Future() for _ in chunks]
        self._closed = threading.Event()  # consumer abandoned the stream
        self._quarantined = False
        self._q_lock = threading.Lock()
        self._t0 = time.monotonic()

        self._cached: set[int] = set()
        if self._cache_dir:
            for spec in chunks:
                d = ing_cache.load_chunk(self._cache_dir, self._cache_key,
                                         spec.index, n_bags)
                if d is not None and d.num_records == spec.records:
                    self._cached.add(spec.index)
                    self._futures[spec.index].set_result(("cache", d))
        self.num_cached = len(self._cached)

        missing = [s for s in chunks if s.index not in self._cached]
        if missing:
            self._sem = threading.Semaphore(self.config.resolved_depth())
            self._thread = threading.Thread(
                target=self._run, args=(missing,), daemon=True,
                name="pml-ingest-sched")
            self._thread.start()
        else:
            self._thread = None
            if self._cache_dir and chunks:
                self._finalize_meta()

    # -- scheduler ---------------------------------------------------------

    def _run(self, missing: list[ChunkSpec]) -> None:
        cfg = self.config
        ctx: dict = {}
        fplan = flt.current_plan()
        if fplan is not None:
            ctx["fault_plan"] = fplan
        pool = pools.make_pool(cfg.mode, cfg.resolved_workers(), ctx,
                               thread_name_prefix="pml-ingest")
        try:
            for spec in missing:
                while not self._sem.acquire(timeout=0.1):
                    if self._closed.is_set():
                        return
                if self._closed.is_set():
                    return
                self._dispatch(pool, spec)
            # Retire only once every chunk settled (or the consumer
            # abandoned the stream) — cancel_futures below must never
            # cancel work the consumer is still waiting on.
            while (not self._closed.is_set()
                   and not all(f.done() for f in self._futures)):
                time.sleep(0.05)
        finally:
            pool.shutdown(wait=False, cancel_futures=True)
            if self._cache_dir and all(
                    f.done() and not f.cancelled()
                    and f.exception() is None for f in self._futures):
                self._finalize_meta()

    def _dispatch(self, pool, spec: ChunkSpec) -> None:
        args = (spec, self._plans[spec.file_index], self._n_bags,
                self._cache_dir, self._cache_key)
        t_submit = time.monotonic()
        fut = None
        with self._q_lock:
            quarantined = self._quarantined
        if not quarantined:
            try:
                fut = pool.submit(_decode_chunk_task, *args)
            except RuntimeError as e:  # BrokenExecutor / shut-down pool
                self._note_quarantine(spec.index, e)
        if fut is None:  # quarantined: decode inline, bit-identically
            self._settle(spec.index, t_submit,
                         lambda: _decode_chunk_task(*args))
            return
        fut.add_done_callback(
            lambda f, i=spec.index, t=t_submit, a=args:
            self._on_done(i, t, a, f))

    def _on_done(self, index, t_submit, args, fut) -> None:
        # Pool-callback thread: broken pools fall back to an inline
        # re-decode (the staging quarantine rung); real decode errors
        # settle the chunk's future with the exception.
        try:
            res = fut.result()
        except cf.BrokenExecutor as e:
            self._note_quarantine(index, e)
            self._settle(index, t_submit,
                         lambda: _decode_chunk_task(*args))
        except BaseException as e:
            if not self._futures[index].done():
                self._futures[index].set_exception(e)
        else:
            self._publish(index, t_submit, res)

    def _settle(self, index, t_submit, thunk) -> None:
        try:
            res = thunk()
        except BaseException as e:
            if not self._futures[index].done():
                self._futures[index].set_exception(e)
        else:
            self._publish(index, t_submit, res)

    def _publish(self, index, t_submit, res) -> None:
        self._futures[index].set_result(("decoded", res))
        self._emitter.emit(ev_mod.IngestBlock(
            index=index, records=res.num_records,
            seconds=time.monotonic() - t_submit, source="decoded"))

    def _note_quarantine(self, index, exc) -> None:
        with self._q_lock:
            first = not self._quarantined
            self._quarantined = True
        if first:
            logger.warning(
                "ingest: decode pool broken at chunk %d (%s: %s) — "
                "quarantining the pool; remaining chunks decode inline "
                "(bit-identical, slower)", index, type(exc).__name__, exc)

    def _finalize_meta(self) -> None:
        try:
            ing_cache.save_meta(self._cache_dir, self._cache_key,
                                len(self.plan),
                                sum(s.records for s in self.plan))
        except OSError:
            pass

    # -- consumer ----------------------------------------------------------

    def chunks(self):
        """Yield each chunk's DecodedFile in plan order (blocking); the
        depth bound is released as the consumer takes each decoded
        chunk. Emits the IngestStart/IngestFinish pair around the
        stream (finally-guarded: an error mid-fold still closes the
        lifecycle)."""
        cfg = self.config
        self._emitter.emit(ev_mod.IngestStart(
            num_files=len(self._plans), num_chunks=len(self.plan),
            workers=cfg.resolved_workers(), mode=cfg.mode,
            cached_chunks=self.num_cached))
        consumed = 0
        records = 0
        try:
            for i in range(len(self.plan)):
                src, d = self._futures[i].result()
                if src == "cache":
                    self._emitter.emit(ev_mod.IngestBlock(
                        index=i, records=d.num_records, seconds=0.0,
                        source="cache"))
                try:
                    yield d
                finally:
                    consumed += 1
                    records += d.num_records
                    if src == "decoded":
                        self._sem.release()
        finally:
            self._closed.set()
            self._emitter.emit(ev_mod.IngestFinish(
                num_files=len(self._plans), num_chunks=consumed,
                records=records, cached_chunks=self.num_cached,
                wall_seconds=time.monotonic() - self._t0))
