"""Columnar, memory-mapped ingest cache: warm restarts skip Avro decode.

One cache entry holds the DECODED columns of one ingest plan — per
chunk, the exact ``native_decode.DecodedFile`` payload (scalar columns,
per-bag COO triples + key tables, metadataMap entries + string tables)
as plain ``.npy`` files with string tables packed as (bytes, offsets)
pairs. A warm read memory-maps the arrays straight off the page cache
and re-runs only the cheap vectorized fold (index-map lookup + entity
vocabularies), so it produces the SAME GameDataset as a cold decode —
the fold is where read-time parameters (index maps, vocabularies,
shard configs) apply, which is why the cache key covers only what
determines the decoded columns: file identity + the capture plan.

Commit discipline (same v3 contract as game/staging_cache.py):

- ``c<i>.bin`` — ALL columns of chunk i as one 64-byte-aligned blob,
  written atomically (one file per chunk, not one per column: a warm
  load is one open + one mmap, and the page cache sees one sequential
  extent instead of dozens of tiny inodes);
- ``c<i>.ok`` — chunk i's commit marker (column directory: name/dtype/
  shape/offset per column, plus the blob's CRC32 and record count),
  written LAST via atomic rename — a reader never trusts a
  half-written chunk, and silent corruption fails the CRC and degrades
  to a re-decode of exactly that chunk;
- ``meta.json`` — the entry's completion record.

Chunks are written the moment they are decoded, so a killed run leaves
a partial entry whose committed chunks are reused on restart — only
the missing/corrupt ones re-decode (partial credit; the chaos suite
drives a driver SIGKILL through the ``ingest.cache_write`` fault site
and asserts bit-identical final coefficients on resume).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
from typing import Optional

import numpy as np

from photon_ml_tpu import faults as flt
from photon_ml_tpu.avro.native_decode import BagColumns, DecodedFile
from photon_ml_tpu.ingest.blocks import FileBlocks, file_token
from photon_ml_tpu.utils.diskio import atomic_write, file_crc32

logger = logging.getLogger("photon_ml_tpu.ingest")

INGEST_CACHE_VERSION = 1

_SCALARS = ("response", "offsets", "weights", "uid_kind", "uid_long")


def ingest_key(files: list[FileBlocks],
               captures: dict[str, tuple[int, int]], n_bags: int,
               chunk_records: int) -> str:
    """Cache key: every input file's identity token + the capture plan
    (field names -> capture/arg) + bag count + the chunk grouping."""
    h = hashlib.sha1()
    h.update(f"v{INGEST_CACHE_VERSION};chunk={chunk_records};"
             f"bags={n_bags};".encode())
    for fb in files:
        h.update(file_token(fb).encode())
    for name in sorted(captures):
        h.update(f"{name}={captures[name]!r};".encode())
    return h.hexdigest()


def _pack_strings(strs: list[str]) -> tuple[np.ndarray, np.ndarray]:
    """list[str] -> (utf-8 byte pool, cumulative end offsets) — the same
    layout the native decoder's string tables cross the C ABI in."""
    encs = [s.encode("utf-8") for s in strs]
    data = np.frombuffer(b"".join(encs), np.uint8).copy()
    ends = np.cumsum([len(e) for e in encs], dtype=np.int64) \
        if encs else np.zeros(0, np.int64)
    return data, ends


def _unpack_strings(data: np.ndarray, ends: np.ndarray) -> list[str]:
    raw = bytes(np.asarray(data, np.uint8))
    out = []
    prev = 0
    for end in np.asarray(ends, np.int64):
        out.append(raw[prev:int(end)].decode("utf-8"))
        prev = int(end)
    return out


def _chunk_arrays(d: DecodedFile) -> dict[str, np.ndarray]:
    """Flatten one DecodedFile into named arrays (all npy-serializable;
    object columns are re-derived from kind/long/string-pool parts)."""
    n = d.num_records
    kind = np.asarray(d.uid_kind, np.uint8)
    uid_long = np.zeros(n, np.int64)
    long_rows = np.flatnonzero(kind == 2)
    if len(long_rows):
        uid_long[long_rows] = np.asarray(
            [int(d.uids[i]) for i in long_rows], np.int64)
    str_rows = np.flatnonzero(kind == 1)
    uid_bytes, uid_ends = _pack_strings([d.uids[i] for i in str_rows])
    out = {
        "response": np.asarray(d.response, np.float64),
        "offsets": np.asarray(d.offsets, np.float64),
        "weights": np.asarray(d.weights, np.float64),
        "uid_kind": kind,
        "uid_long": uid_long,
        "uid_str_bytes": uid_bytes,
        "uid_str_ends": uid_ends,
        "meta_rows": np.asarray(d.meta_rows, np.int64),
        "meta_keys": np.asarray(d.meta_keys, np.int32),
        "meta_vals": np.asarray(d.meta_vals, np.int32),
    }
    for which, strs in (("metak", d.meta_key_strings),
                        ("metav", d.meta_val_strings)):
        data, ends = _pack_strings(strs)
        out[f"{which}_bytes"], out[f"{which}_ends"] = data, ends
    for b, bag in enumerate(d.bags):
        out[f"bag{b}_rows"] = np.asarray(bag.rows, np.int64)
        out[f"bag{b}_keys"] = np.asarray(bag.keys, np.int32)
        out[f"bag{b}_vals"] = np.asarray(bag.values, np.float64)
        data, ends = _pack_strings(bag.key_strings)
        out[f"bag{b}_keybytes"], out[f"bag{b}_keyends"] = data, ends
    return out


def _chunk_from_arrays(arrs: dict[str, np.ndarray], records: int,
                       n_bags: int) -> DecodedFile:
    n = records
    kind = np.asarray(arrs["uid_kind"], np.uint8)
    uids = np.arange(n).astype(object)
    long_rows = np.flatnonzero(kind == 2)
    if len(long_rows):
        uids[long_rows] = np.asarray(arrs["uid_long"])[long_rows].tolist()
    str_rows = np.flatnonzero(kind == 1)
    if len(str_rows):
        strs = _unpack_strings(arrs["uid_str_bytes"],
                               arrs["uid_str_ends"])
        uids[str_rows] = np.asarray(strs, object)
    bags = []
    for b in range(n_bags):
        bags.append(BagColumns(
            rows=arrs[f"bag{b}_rows"], keys=arrs[f"bag{b}_keys"],
            values=arrs[f"bag{b}_vals"],
            key_strings=_unpack_strings(arrs[f"bag{b}_keybytes"],
                                        arrs[f"bag{b}_keyends"])))
    return DecodedFile(
        num_records=n,
        response=arrs["response"], offsets=arrs["offsets"],
        weights=arrs["weights"], uids=uids, uid_kind=kind, bags=bags,
        meta_rows=arrs["meta_rows"], meta_keys=arrs["meta_keys"],
        meta_vals=arrs["meta_vals"],
        meta_key_strings=_unpack_strings(arrs["metak_bytes"],
                                         arrs["metak_ends"]),
        meta_val_strings=_unpack_strings(arrs["metav_bytes"],
                                         arrs["metav_ends"]))


_ALIGN = 64  # column sections start on cache-line boundaries


def save_chunk(cache_dir: str, key: str, index: int,
               d: DecodedFile) -> None:
    """Persist one decoded chunk as a single aligned blob; the ``.ok``
    marker (column directory + blob CRC32) commits it last."""
    flt.fire(flt.sites.INGEST_CACHE_WRITE, index=index)
    path = os.path.join(cache_dir, key)
    os.makedirs(path, exist_ok=True)
    arrs = _chunk_arrays(d)
    cols = []
    pos = 0
    pieces: list[bytes] = []
    for name in sorted(arrs):
        a = np.ascontiguousarray(arrs[name])
        pad = (-pos) % _ALIGN
        if pad:
            pieces.append(b"\x00" * pad)
            pos += pad
        cols.append({"name": name, "dtype": a.dtype.str,
                     "shape": list(a.shape), "offset": pos})
        pieces.append(a.tobytes())
        pos += a.nbytes
    fpath = os.path.join(path, f"c{index}.bin")
    atomic_write(fpath, lambda f: f.writelines(pieces))
    crc = file_crc32(fpath)
    # Injected bit rot lands AFTER the checksum was taken over the good
    # bytes — the shape a CRC verification must catch.
    flt.corrupt_file(flt.sites.INGEST_CACHE_FILE, fpath, index=index)
    marker = json.dumps({"version": INGEST_CACHE_VERSION,
                         "cols": cols, "crc": crc, "nbytes": pos,
                         "records": int(d.num_records),
                         "n_bags": len(d.bags)}).encode()
    atomic_write(os.path.join(path, f"c{index}.ok"),
                 lambda f: f.write(marker))


def load_chunk(cache_dir: str, key: str, index: int,
               n_bags: int) -> Optional[DecodedFile]:
    """One decoded chunk (columns as read-only views over one mmap), or
    None on any miss: no marker, version/bag-count skew, an unreadable
    blob, or a CRC mismatch against the commit marker (silent
    corruption)."""
    path = os.path.join(cache_dir, key)
    try:
        with open(os.path.join(path, f"c{index}.ok")) as f:
            marker = json.load(f)
        if (marker.get("version") != INGEST_CACHE_VERSION
                or marker.get("n_bags") != n_bags):
            return None
        fpath = os.path.join(path, f"c{index}.bin")
        got = file_crc32(fpath)
        if got != marker["crc"]:
            logger.warning(
                "ingest cache chunk %s is corrupt (crc %08x != "
                "committed %08x) — treating as a miss and re-decoding",
                fpath, got, marker["crc"])
            return None
        blob = np.memmap(fpath, dtype=np.uint8, mode="r",
                         shape=(int(marker["nbytes"]),))
        arrs = {}
        for col in marker["cols"]:
            dt = np.dtype(col["dtype"])
            count = int(np.prod(col["shape"], dtype=np.int64))
            off = int(col["offset"])
            arrs[col["name"]] = np.frombuffer(
                blob, dtype=dt, count=count,
                offset=off).reshape(col["shape"])
        return _chunk_from_arrays(arrs, int(marker["records"]), n_bags)
    except Exception:
        logger.debug("ingest cache miss for %s chunk %d", key, index,
                     exc_info=True)
        return None


def save_meta(cache_dir: str, key: str, num_chunks: int,
              records: int) -> None:
    """Finalize an entry (``meta.json`` written last — its presence
    means COMPLETE; partial entries still give per-chunk credit)."""
    path = os.path.join(cache_dir, key)
    os.makedirs(path, exist_ok=True)
    meta = json.dumps({"version": INGEST_CACHE_VERSION,
                       "num_chunks": int(num_chunks),
                       "records": int(records)}).encode()
    atomic_write(os.path.join(path, "meta.json"),
                 lambda f: f.write(meta))
