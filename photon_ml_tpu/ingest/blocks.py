"""Sync-boundary block scan + chunk planning for parallel Avro ingest.

An Avro object container file is a self-delimiting sequence of data
blocks: ``(record count varint, byte size varint, payload, 16-byte sync
marker)``. Walking just the block HEADERS (two varints + a seek per
block) costs microseconds per block and yields exact byte boundaries at
which the file can be split without decoding anything — the property
the block-parallel decode of ``photon_ml_tpu/ingest`` is built on
(Snap ML's hierarchical data loading makes the same cut: partition the
input at container-format boundaries, decode partitions concurrently).

``scan_file`` produces the boundary table (plus the writer schema and
cheap identity facts for the ingest cache key); ``plan_chunks`` groups
consecutive blocks of each file into decode tasks of roughly
``chunk_records`` records. Chunks never span files and always cover
whole blocks, so a worker decodes its byte range through the same
native block loop as a whole file (``native_decode.decode_span``) and
the in-order concatenation of chunk outputs is bit-identical to the
serial read.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os

from photon_ml_tpu.avro.codec import BinaryDecoder, _read_long
from photon_ml_tpu.avro.container import MAGIC, _META_SCHEMA


@dataclasses.dataclass(frozen=True)
class FileBlocks:
    """One container file's block-boundary table + identity facts."""

    path: str
    header_len: int  # byte offset where data blocks start
    schema: dict  # parsed writer schema (JSON)
    codec: str
    sync: bytes
    # Block i spans bytes [block_offsets[i], block_offsets[i + 1]) and
    # holds block_counts[i] records.
    block_offsets: tuple[int, ...]  # len B + 1
    block_counts: tuple[int, ...]  # len B
    size: int
    mtime_ns: int

    @property
    def num_records(self) -> int:
        return int(sum(self.block_counts))


def scan_file(path: str) -> FileBlocks:
    """Walk one container file's header + block headers (no payload
    decode). Raises ValueError on a malformed/corrupt container — the
    same failure class the serial readers report."""
    st = os.stat(path)
    with open(path, "rb") as f:
        if f.read(4) != MAGIC:
            raise ValueError(f"{path}: not an Avro container file")
        meta = BinaryDecoder(_META_SCHEMA).read(f)
        schema = json.loads(meta["avro.schema"].decode("utf-8"))
        codec = meta.get("avro.codec", b"null").decode("utf-8")
        if codec not in ("null", "deflate"):
            raise ValueError(f"{path}: unsupported codec {codec}")
        sync = f.read(16)
        if len(sync) != 16:
            raise ValueError(f"{path}: truncated header")
        header_len = f.tell()
        offsets = [header_len]
        counts = []
        while True:
            head = f.read(1)
            if not head:
                break
            f.seek(-1, os.SEEK_CUR)
            try:
                count = _read_long(f)
                byte_size = _read_long(f)
            except EOFError as e:
                raise ValueError(f"{path}: truncated block header") from e
            if count < 0 or byte_size < 0:
                raise ValueError(f"{path}: corrupt block header")
            f.seek(byte_size, os.SEEK_CUR)
            if f.read(16) != sync:
                raise ValueError(
                    f"{path}: sync marker mismatch (corrupt block)")
            pos = f.tell()
            if pos > st.st_size:
                raise ValueError(f"{path}: truncated block")
            offsets.append(pos)
            counts.append(int(count))
    return FileBlocks(
        path=path, header_len=header_len, schema=schema, codec=codec,
        sync=sync, block_offsets=tuple(offsets),
        block_counts=tuple(counts), size=st.st_size,
        mtime_ns=st.st_mtime_ns)


def file_token(fb: FileBlocks) -> str:
    """Cheap identity digest of one scanned file for the ingest-cache
    key: absolute path + size + mtime_ns + sync marker + block count.
    Payload bytes are NOT hashed (that would cost a full read, what the
    cache exists to avoid) — the mtime discipline is the same contract
    build caches use."""
    h = hashlib.sha1()
    h.update(os.path.abspath(fb.path).encode())
    h.update(f"|{fb.size}|{fb.mtime_ns}|{len(fb.block_counts)}|".encode())
    h.update(fb.sync)
    return h.hexdigest()


@dataclasses.dataclass(frozen=True)
class ChunkSpec:
    """One decode task: a run of whole blocks of one file. ``index`` is
    the global plan position (the deterministic merge order); ``start``/
    ``end`` are file byte offsets at sync boundaries."""

    index: int
    file_index: int
    path: str
    header_len: int
    start: int
    end: int
    records: int


def plan_chunks(files: list[FileBlocks],
                chunk_records: int) -> list[ChunkSpec]:
    """Group consecutive blocks into decode chunks of >= chunk_records
    records (greedy; the last chunk of a file may be smaller). The plan
    order is file order then byte order — exactly the serial readers'
    record order."""
    chunks: list[ChunkSpec] = []
    for fi, fb in enumerate(files):
        b = 0
        nb = len(fb.block_counts)
        while b < nb:
            recs = 0
            start = fb.block_offsets[b]
            while b < nb and recs < max(1, chunk_records):
                recs += fb.block_counts[b]
                b += 1
            chunks.append(ChunkSpec(
                index=len(chunks), file_index=fi, path=fb.path,
                header_len=fb.header_len, start=start,
                end=fb.block_offsets[b], records=recs))
    return chunks
