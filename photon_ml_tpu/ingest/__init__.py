"""photon-ingest: block-parallel, pipelined Avro->tensor ingestion.

The cold-fit input layer (docs/INGEST.md): Avro container files split
at sync-marker block boundaries (``blocks``), native-decode workers fan
over the resulting chunks with a deterministic in-order merge
(``pipeline``), and a columnar memory-mapped cache lets warm restarts
skip Avro decode entirely with per-chunk partial credit (``cache``).
Consumed by ``avro/data_reader.AvroDataReader.read`` (the default
native path) and configured through ``IngestConfig`` —
``GameEstimator(ingest=...)`` / ``game_train --ingest workers=8``.
"""

from photon_ml_tpu.ingest.blocks import (ChunkSpec, FileBlocks,
                                         file_token, plan_chunks,
                                         scan_file)
from photon_ml_tpu.ingest.cache import (INGEST_CACHE_VERSION, ingest_key,
                                        load_chunk, save_chunk, save_meta)
from photon_ml_tpu.ingest.pipeline import IngestConfig, IngestPipeline

__all__ = [
    "ChunkSpec",
    "FileBlocks",
    "INGEST_CACHE_VERSION",
    "IngestConfig",
    "IngestPipeline",
    "file_token",
    "ingest_key",
    "load_chunk",
    "plan_chunks",
    "save_chunk",
    "save_meta",
    "scan_file",
]
