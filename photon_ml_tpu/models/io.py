"""Model persistence: GAME + GLM models on disk.

Reference parity: photon-client ``data/avro/ModelProcessingUtils.scala`` —
GameModel ↔ HDFS layout ``fixed-effect/<coord>/coefficients.avro`` +
``random-effect/<coord>/...`` (BayesianLinearModelAvro: per-feature
name/term → mean/variance) plus id-info/metadata. This module writes the
same directory SHAPE with npz coefficient payloads + JSON metadata; the
Avro-record path (feature-name-keyed BayesianLinearModelAvro) lives in
photon_ml_tpu/data/avro.py and is used when an index map is supplied.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
from typing import Optional

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.game.models import (FixedEffectModel, GameModel,
                                       RandomEffectModel,
                                       SubspaceRandomEffectModel)
from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.models.glm import GeneralizedLinearModel
from photon_ml_tpu.types import TaskType
from photon_ml_tpu.utils.diskio import atomic_write

_METADATA = "metadata.json"


def coordinate_meta(m) -> dict:
    """Metadata entry for one coordinate model (no file writes)."""
    from photon_ml_tpu.game.factored import FactoredRandomEffectModel

    if isinstance(m, FixedEffectModel):
        return {"type": "fixed", "shard_id": m.shard_id,
                "dim": int(m.coefficients.dim)}
    if isinstance(m, RandomEffectModel):
        return {"type": "random", "shard_id": m.shard_id,
                "re_type": m.re_type, "num_entities": int(m.num_entities),
                "dim": int(m.dim)}
    if isinstance(m, SubspaceRandomEffectModel):
        return {"type": "random-subspace", "shard_id": m.shard_id,
                "re_type": m.re_type, "num_entities": int(m.num_entities),
                "dim": int(m.dim), "subspace_dim": int(m.subspace_dim)}
    if isinstance(m, FactoredRandomEffectModel):
        return {"type": "factored", "shard_id": m.shard_id,
                "re_type": m.re_type, "num_entities": int(m.num_entities),
                "dim": int(m.dim), "rank": int(m.rank)}
    raise TypeError(type(m))  # pragma: no cover


def coordinate_arrays(m) -> dict:
    """One coordinate model's persisted arrays, as host numpy — the ONE
    definition of "the model's bytes", shared by the npz writer and the
    cross-rank digest."""
    meta = coordinate_meta(m)
    if isinstance(m, FixedEffectModel):
        payload = {"means": np.asarray(m.coefficients.means)}
        if m.coefficients.variances is not None:
            payload["variances"] = np.asarray(m.coefficients.variances)
    elif meta["type"] == "factored":
        # Reference layout note: latent factors + projection matrix (the
        # LatentFactorAvro pair) rather than materialized coefficients.
        payload = {"projection": np.asarray(m.projection),
                   "factors": np.asarray(m.factors)}
    elif meta["type"] == "random-subspace":
        # Reference: RandomEffectModelInProjectedSpace — coefficients stay
        # in each entity's active-column subspace on disk too.
        payload = {"cols": np.asarray(m.cols),
                   "means": np.asarray(m.means)}
        if m.variances is not None:
            payload["variances"] = np.asarray(m.variances)
    else:
        payload = {"means": np.asarray(m.means)}
        if m.variances is not None:
            payload["variances"] = np.asarray(m.variances)
    return payload


def save_coordinate(path: str, cid: str, m) -> dict:
    """Atomically write one coordinate's coefficients under a GameModel
    directory; returns its metadata entry. Atomic via tmp + ``os.replace``
    so an interrupted write never corrupts an existing checkpoint file."""
    meta = coordinate_meta(m)
    sub = os.path.join(
        path, "fixed-effect" if meta["type"] == "fixed" else "random-effect",
        cid)
    os.makedirs(sub, exist_ok=True)
    payload = coordinate_arrays(m)
    atomic_write(os.path.join(sub, "coefficients.npz"),
                 lambda f: np.savez(f, **payload))
    return meta


def game_model_digest(model: GameModel) -> str:
    """SHA-256 over every coordinate's persisted arrays in canonical
    order. Two models digest equal iff their trained bytes are IDENTICAL
    — the cross-rank equality probe (`__graft_entry__._dryrun_dcn`
    asserts ranks converge to byte-identical coefficients, not just an
    AUC scalar agreeing to 1e-6; VERDICT Weak #6) and the ``game_train``
    summary's model fingerprint."""
    import hashlib

    h = hashlib.sha256()
    for cid in sorted(model.models):
        m = model.models[cid]
        h.update(json.dumps(coordinate_meta(m), sort_keys=True).encode())
        for key, arr in sorted(coordinate_arrays(m).items()):
            h.update(key.encode())
            h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()


def write_metadata(path: str, task: TaskType,
                   coordinates_meta: dict[str, dict]) -> None:
    """Atomically write a GameModel directory's metadata.json."""
    meta = {"task": TaskType(task).value, "coordinates": coordinates_meta}
    body = json.dumps(meta, indent=2, sort_keys=True)
    atomic_write(os.path.join(path, _METADATA),
                 lambda f: f.write(body.encode()))


def save_game_model(model: GameModel, path: str) -> None:
    """Write a GameModel directory (reference: saveGameModelToHDFS layout)."""
    os.makedirs(path, exist_ok=True)
    meta = {cid: save_coordinate(path, cid, m)
            for cid, m in model.models.items()}
    write_metadata(path, model.task, meta)


def load_game_model(path: str, host: bool = False,
                    mapped: Optional[bool] = None) -> GameModel:
    """Inverse of save_game_model (reference: loadGameModelFromHDFS).

    ``host=True`` keeps every coefficient table as host numpy instead of
    committing it to the default device — the serving path's loader
    (serving/model_store.py re-shards random-effect tables onto the host
    anyway; staging a multi-GB (E, d) table through device memory first
    would defeat the residency design). Scoring works either way
    (``score`` does its own ``jnp.asarray``).

    ``mapped`` routes through the columnar mmap format (boot/mapfmt.py
    — zero-copy host views over the page cache, bit-identical to this
    loader by construction): ``True`` prefers it and FALLS BACK to the
    npz layout when the directory does not carry one; ``None`` (the
    default) auto-detects by layout; ``False`` forces npz. Mapped loads
    are host-resident by nature (the serving contract); ``host=False``
    still works — scoring's ``jnp.asarray`` commits on first use.
    """
    if mapped is not False:
        from photon_ml_tpu.boot import mapfmt

        if mapfmt.is_mapped_model(path):
            return mapfmt.load_mapped_model(path)[0]
        if mapped:
            logging.getLogger("photon_ml_tpu.boot").info(
                "no mapped model at %s — falling back to the npz "
                "layout", path)
    put = np.asarray if host else jnp.asarray
    with open(os.path.join(path, _METADATA)) as f:
        meta = json.load(f)
    models = {}
    for cid, info in meta["coordinates"].items():
        if info["type"] == "fixed":
            z = np.load(os.path.join(path, "fixed-effect", cid,
                                     "coefficients.npz"))
            coef = Coefficients(
                means=put(z["means"]),
                variances=(put(z["variances"])
                           if "variances" in z else None))
            models[cid] = FixedEffectModel(shard_id=info["shard_id"],
                                           coefficients=coef)
        elif info["type"] == "factored":
            from photon_ml_tpu.game.factored import FactoredRandomEffectModel

            z = np.load(os.path.join(path, "random-effect", cid,
                                     "coefficients.npz"))
            models[cid] = FactoredRandomEffectModel(
                re_type=info["re_type"], shard_id=info["shard_id"],
                projection=put(z["projection"]),
                factors=put(z["factors"]))
        elif info["type"] == "random-subspace":
            z = np.load(os.path.join(path, "random-effect", cid,
                                     "coefficients.npz"))
            models[cid] = SubspaceRandomEffectModel(
                re_type=info["re_type"], shard_id=info["shard_id"],
                num_features=int(info["dim"]),
                cols=put(z["cols"]),
                means=put(z["means"]),
                variances=(put(z["variances"])
                           if "variances" in z else None))
        else:
            z = np.load(os.path.join(path, "random-effect", cid,
                                     "coefficients.npz"))
            models[cid] = RandomEffectModel(
                re_type=info["re_type"], shard_id=info["shard_id"],
                means=put(z["means"]),
                variances=(put(z["variances"])
                           if "variances" in z else None))
    return GameModel(task=TaskType(meta["task"]), models=models)


def save_glm(model: GeneralizedLinearModel, path: str) -> None:
    """Write a single GLM (reference: legacy GLMSuite text/Avro output)."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    payload = {"means": np.asarray(model.coefficients.means)}
    if model.coefficients.variances is not None:
        payload["variances"] = np.asarray(model.coefficients.variances)
    atomic_write(path if path.endswith(".npz") else path + ".npz",
                 lambda f: np.savez(f, **payload))
    meta_path = (path[:-4] if path.endswith(".npz") else path) + ".json"
    meta_body = json.dumps({"task": TaskType(model.task).value,
                            "dim": int(model.coefficients.dim)})
    atomic_write(meta_path, lambda f: f.write(meta_body.encode()))


def load_glm(path: str) -> GeneralizedLinearModel:
    base = path[:-4] if path.endswith(".npz") else path
    z = np.load(base + ".npz")
    with open(base + ".json") as f:
        meta = json.load(f)
    return GeneralizedLinearModel(
        task=TaskType(meta["task"]),
        coefficients=Coefficients(
            means=jnp.asarray(z["means"]),
            variances=(jnp.asarray(z["variances"])
                       if "variances" in z else None)))
