"""Coefficients: the model-parameter pytree.

Reference parity: photon-lib ``model/Coefficients.scala`` — means vector plus
optional per-coefficient variances, dot/norm helpers, ``computeScore``.

TPU-first design: a frozen dataclass registered as a JAX pytree so it flows
through ``jit`` / ``vmap`` / ``grad`` / optimizer state machines unchanged.
Dense f32 by default (TPU-friendly); sparse feature spaces are handled at the
data layer (feature shards / index maps), not by sparse coefficient vectors.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

Array = jax.Array


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class Coefficients:
    """GLM coefficients: means (d,) and optional variances (d,)."""

    means: Array
    variances: Optional[Array] = None

    @property
    def dim(self) -> int:
        return self.means.shape[-1]

    def compute_score(self, features: Array) -> Array:
        """w·x for a single vector or a batch (…, d) of feature vectors."""
        return features @ self.means

    def norm(self, ord: int = 2) -> Array:
        if ord == 1:
            return jnp.sum(jnp.abs(self.means))
        if ord == 2:
            return jnp.sqrt(jnp.sum(self.means * self.means))
        raise ValueError(f"unsupported norm order {ord!r} (use 1 or 2)")

    @staticmethod
    def zeros(dim: int, dtype=jnp.float32, with_variances: bool = False
              ) -> "Coefficients":
        means = jnp.zeros((dim,), dtype=dtype)
        variances = jnp.zeros((dim,), dtype=dtype) if with_variances else None
        return Coefficients(means=means, variances=variances)
