"""Generalized linear model classes.

Reference parity: photon-lib ``supervised/model/GeneralizedLinearModel.
scala`` and its subclasses ``classification/LogisticRegressionModel.scala``,
``classification/SmoothedHingeLossLinearSVMModel.scala``,
``regression/LinearRegressionModel.scala``,
``regression/PoissonRegressionModel.scala`` — score = link(wᵀx + offset),
classifiers add a threshold API.

One dataclass parameterized by TaskType rather than a class hierarchy: the
behavior differences are exactly (loss, mean function, classification
threshold), all derivable from the task.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp

from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.ops import losses
from photon_ml_tpu.types import TaskType

Array = jax.Array


@functools.partial(jax.tree_util.register_dataclass,
                   data_fields=("coefficients",), meta_fields=("task",))
@dataclasses.dataclass(frozen=True)
class GeneralizedLinearModel:
    """A trained GLM: task + coefficients (raw/original feature space)."""

    task: TaskType
    coefficients: Coefficients

    def compute_score(self, features: Array,
                      offsets: Optional[Array] = None) -> Array:
        """Linear score wᵀx (+ offset) — reference ``computeScore``."""
        s = features @ self.coefficients.means
        if offsets is not None:
            s = s + offsets
        return s

    def compute_mean(self, features: Array,
                     offsets: Optional[Array] = None) -> Array:
        """E[y|x] through the inverse link — reference ``computeMean``."""
        loss = losses.loss_for_task(self.task)
        return loss.mean(self.compute_score(features, offsets))

    def predict_class(self, features: Array, threshold: float = 0.5,
                      offsets: Optional[Array] = None) -> Array:
        """Binary prediction for classification tasks.

        Logistic thresholds the probability; the SVM thresholds the raw
        margin at 0 when threshold==0.5 semantics (reference behavior).
        """
        task = TaskType(self.task)
        if not task.is_classification:
            raise ValueError(f"{task} is not a classification task")
        if task == TaskType.LOGISTIC_REGRESSION:
            return (self.compute_mean(features, offsets) >= threshold).astype(
                jnp.float32)
        # Smoothed-hinge SVM: margin sign; no probability exists to threshold.
        if threshold != 0.5:
            raise ValueError(
                "smoothed-hinge SVM predictions threshold the raw margin at "
                "0; a probability threshold does not apply")
        return (self.compute_score(features, offsets) >= 0.0).astype(jnp.float32)


# Convenience constructors mirroring the reference's concrete classes.

def logistic_regression_model(coefficients: Coefficients) -> GeneralizedLinearModel:
    return GeneralizedLinearModel(TaskType.LOGISTIC_REGRESSION, coefficients)


def linear_regression_model(coefficients: Coefficients) -> GeneralizedLinearModel:
    return GeneralizedLinearModel(TaskType.LINEAR_REGRESSION, coefficients)


def poisson_regression_model(coefficients: Coefficients) -> GeneralizedLinearModel:
    return GeneralizedLinearModel(TaskType.POISSON_REGRESSION, coefficients)


def smoothed_hinge_svm_model(coefficients: Coefficients) -> GeneralizedLinearModel:
    return GeneralizedLinearModel(TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM,
                                  coefficients)
