// pidx: read-only mmap'd feature-index store.
//
// Reference parity: PalDB (LinkedIn's read-only key-value store, Java) as
// used by photon-ml's PalDBIndexMap for 1e6–1e8-feature maps. Same role,
// native implementation: the store is built once (offline, by the feature
// indexing driver), then opened read-only by every training process. mmap
// keeps the table out of the Python heap and shares pages across processes
// on one host (the TPU-host analogue of per-executor PalDB opens).
//
// File layout (little-endian, built by photon_ml_tpu/index/native_store.py):
//   0:  8  magic "PIDXv01\0"
//   8:  u64 n                 (number of entries)
//   16: u64 slots             (hash-table slots, power of two)
//   24: u64 table_off         (open-addressing table, slots * 24 bytes:
//                              {u64 hash, u64 key_off, u32 key_len,
//                               u32 index_plus1}; index_plus1==0 => empty)
//   32: u64 ridx_off          (reverse index, n * 16 bytes:
//                              {u64 key_off, u32 key_len, u32 pad})
//   40: u64 blob_off          (key-bytes blob)
//   48: u64 blob_size
//
// Exported C API (ctypes-consumed): pidx_open/close/size/get/name.

#include <cstdint>
#include <cstring>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr char kMagic[8] = {'P', 'I', 'D', 'X', 'v', '0', '1', '\0'};

struct Slot {
  uint64_t hash;
  uint64_t key_off;
  uint32_t key_len;
  uint32_t index_plus1;
};

struct RIdx {
  uint64_t key_off;
  uint32_t key_len;
  uint32_t pad;
};

struct Store {
  void* base = nullptr;
  size_t length = 0;
  uint64_t n = 0;
  uint64_t slots = 0;
  const Slot* table = nullptr;
  const RIdx* ridx = nullptr;
  const char* blob = nullptr;
  uint64_t blob_size = 0;
};

inline uint64_t fnv1a(const char* data, uint64_t len) {
  uint64_t h = 1469598103934665603ULL;
  for (uint64_t i = 0; i < len; ++i) {
    h ^= static_cast<unsigned char>(data[i]);
    h *= 1099511628211ULL;
  }
  return h;
}

inline uint64_t read_u64(const char* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);
  return v;
}

}  // namespace

extern "C" {

void* pidx_open(const char* path) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size < 56) {
    ::close(fd);
    return nullptr;
  }
  void* base = mmap(nullptr, st.st_size, PROT_READ, MAP_SHARED, fd, 0);
  ::close(fd);  // mapping persists past close
  if (base == MAP_FAILED) return nullptr;
  const char* p = static_cast<const char*>(base);
  if (std::memcmp(p, kMagic, 8) != 0) {
    munmap(base, st.st_size);
    return nullptr;
  }
  Store* s = new Store;
  s->base = base;
  s->length = st.st_size;
  s->n = read_u64(p + 8);
  s->slots = read_u64(p + 16);
  s->table = reinterpret_cast<const Slot*>(p + read_u64(p + 24));
  s->ridx = reinterpret_cast<const RIdx*>(p + read_u64(p + 32));
  s->blob = p + read_u64(p + 40);
  s->blob_size = read_u64(p + 48);
  return s;
}

void pidx_close(void* handle) {
  Store* s = static_cast<Store*>(handle);
  if (!s) return;
  munmap(s->base, s->length);
  delete s;
}

int64_t pidx_size(void* handle) {
  return static_cast<Store*>(handle)->n;
}

// Returns the feature's column index, or -1 if absent.
int64_t pidx_get(void* handle, const char* key, uint64_t key_len) {
  const Store* s = static_cast<Store*>(handle);
  if (s->slots == 0) return -1;
  const uint64_t h = fnv1a(key, key_len);
  uint64_t i = h & (s->slots - 1);
  for (;;) {
    const Slot& slot = s->table[i];
    if (slot.index_plus1 == 0) return -1;  // empty: not present
    if (slot.hash == h && slot.key_len == key_len &&
        std::memcmp(s->blob + slot.key_off, key, key_len) == 0) {
      return static_cast<int64_t>(slot.index_plus1) - 1;
    }
    i = (i + 1) & (s->slots - 1);
  }
}

// Copies the key for `index` into buf (up to buf_len bytes); returns the
// key's full length, or -1 if index is out of range.
int64_t pidx_name(void* handle, uint64_t index, char* buf,
                  uint64_t buf_len) {
  const Store* s = static_cast<Store*>(handle);
  if (index >= s->n) return -1;
  const RIdx& r = s->ridx[index];
  const uint64_t ncopy = r.key_len < buf_len ? r.key_len : buf_len;
  std::memcpy(buf, s->blob + r.key_off, ncopy);
  return r.key_len;
}

}  // extern "C"
