// Fast LIBSVM text parser (single pass, no per-token Python objects).
//
// Reference parity: the reference's data ingestion runs inside JVM
// executors (AvroDataReader / LIBSVM fixtures parsed natively by Spark);
// this is the rebuild's native ingestion analog for the text path — the
// Python fallback in data/libsvm.py implements identical semantics
// (blank lines and '#' comment lines skipped, "idx:val" tokens, optional
// 1-based indices).
//
// C ABI (ctypes): parse → query sizes → fill caller-allocated numpy
// buffers → free. Errors are reported per-handle (lsvm_error) so the
// Python wrapper can raise with the offending line number.

#include <charconv>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

namespace {

struct Parsed {
  std::vector<float> labels;
  std::vector<int64_t> indptr{0};
  std::vector<int32_t> indices;
  std::vector<float> values;
  int32_t max_index = -1;
  std::string error;
};

inline const char* skip_ws(const char* p, const char* end) {
  while (p < end && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
  return p;
}

// Locale-independent, line-bounded double parse (std::from_chars): never
// reads past eol (strtod would skip the newline and eat the next row),
// never honors LC_NUMERIC, rejects hex floats. Optional leading '+' for
// LIBSVM's "+1" labels ('+-1' style double signs rejected, as Python
// float() does). Out-of-range magnitudes keep strtod/Python semantics:
// overflow → ±inf, underflow → ±0.
inline bool parse_double(const char* q, const char* eol, double* out,
                         const char** next) {
  if (q < eol && *q == '+') {
    ++q;
    if (q < eol && (*q == '+' || *q == '-')) return false;
  }
#if defined(__cpp_lib_to_chars)
  auto res = std::from_chars(q, eol, *out);
  if (res.ec == std::errc()) {
    *next = res.ptr;
    return true;
  }
  if (res.ec == std::errc::result_out_of_range) {
    // from_chars validated the grammar and consumed the token; re-parse a
    // NUL-terminated copy with strtod to get the ±inf / ±0 result Python's
    // float() (and the old strtod path) produce. Heap copy: numerals can
    // be arbitrarily long.
    std::string tmp(q, res.ptr);
    *out = std::strtod(tmp.c_str(), nullptr);
    *next = res.ptr;
    return true;
  }
  return false;
#else
  // libstdc++ < GCC 11 has no floating-point from_chars: strtod on a
  // NUL-bounded copy keeps the native parser alive (line-bounded; the
  // LC_NUMERIC caveat applies only on comma-decimal locales).
  char buf[512];
  size_t len = static_cast<size_t>(eol - q);
  if (len >= sizeof buf) len = sizeof buf - 1;
  std::memcpy(buf, q, len);
  buf[len] = '\0';
  if (buf[0] == ' ' || buf[0] == '\t') return false;
  if (buf[0] == '0' && (buf[1] == 'x' || buf[1] == 'X')) return false;
  char* e = nullptr;
  *out = std::strtod(buf, &e);
  if (e == buf) return false;
  *next = q + (e - buf);
  return true;
#endif
}

inline bool parse_long(const char* q, const char* eol, long* out,
                       const char** next) {
  if (q < eol && *q == '+') {
    ++q;
    if (q < eol && (*q == '+' || *q == '-')) return false;
  }
  auto res = std::from_chars(q, eol, *out, 10);
  if (res.ec != std::errc()) return false;
  *next = res.ptr;
  return true;
}

}  // namespace

extern "C" {

void* lsvm_parse(const char* path, int zero_based) {
  auto* out = new Parsed();
  FILE* f = std::fopen(path, "rb");
  if (!f) {
    out->error = std::string("cannot open ") + path;
    return out;
  }
  std::fseek(f, 0, SEEK_END);
  long size = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  std::string buf(static_cast<size_t>(size), '\0');
  if (size > 0 && std::fread(&buf[0], 1, size, f) != (size_t)size) {
    out->error = "short read";
    std::fclose(f);
    return out;
  }
  std::fclose(f);

  const int off = zero_based ? 0 : 1;
  const char* p = buf.data();
  const char* end = p + buf.size();
  long lineno = 0;
  while (p < end) {
    ++lineno;
    const char* eol = static_cast<const char*>(
        memchr(p, '\n', static_cast<size_t>(end - p)));
    if (!eol) eol = end;
    const char* q = skip_ws(p, eol);
    if (q == eol || *q == '#') {  // blank / comment line
      p = eol + 1;
      continue;
    }
    const char* next = nullptr;
    double label;
    if (!parse_double(q, eol, &label, &next)) {
      char msg[64];
      std::snprintf(msg, sizeof msg, "bad label at line %ld", lineno);
      out->error = msg;
      return out;
    }
    out->labels.push_back(static_cast<float>(label));
    q = next;
    while (true) {
      q = skip_ws(q, eol);
      if (q >= eol) break;
      // '#' mid-line is an error, matching the Python fallback (only a
      // line-initial '#' marks a comment).
      long idx;
      if (*q == '#' || !parse_long(q, eol, &idx, &next)
          || next >= eol || *next != ':') {
        char msg[64];
        std::snprintf(msg, sizeof msg, "bad token at line %ld", lineno);
        out->error = msg;
        return out;
      }
      q = next + 1;  // past ':'
      double val;
      if (!parse_double(q, eol, &val, &next)) {
        char msg[64];
        std::snprintf(msg, sizeof msg, "bad value at line %ld", lineno);
        out->error = msg;
        return out;
      }
      q = next;
      // idx < off guard first: LONG_MIN - off would be signed-overflow UB.
      if (idx < off || idx - off > static_cast<long>(INT32_MAX)) {
        char msg[80];
        std::snprintf(msg, sizeof msg,
                      "feature index out of range at line %ld", lineno);
        out->error = msg;
        return out;
      }
      int32_t col = static_cast<int32_t>(idx - off);
      if (col > out->max_index) out->max_index = col;
      out->indices.push_back(col);
      out->values.push_back(static_cast<float>(val));
    }
    out->indptr.push_back(static_cast<int64_t>(out->indices.size()));
    p = eol + 1;
  }
  return out;
}

long lsvm_num_rows(void* h) {
  return static_cast<long>(static_cast<Parsed*>(h)->labels.size());
}

long lsvm_nnz(void* h) {
  return static_cast<long>(static_cast<Parsed*>(h)->indices.size());
}

int lsvm_max_index(void* h) {
  return static_cast<Parsed*>(h)->max_index;
}

int lsvm_error(void* h, char* buf, int buflen) {
  auto* p = static_cast<Parsed*>(h);
  if (p->error.empty()) return 0;
  std::snprintf(buf, static_cast<size_t>(buflen), "%s", p->error.c_str());
  return 1;
}

void lsvm_fill(void* h, float* labels, int64_t* indptr, int32_t* indices,
               float* values) {
  auto* p = static_cast<Parsed*>(h);
  std::memcpy(labels, p->labels.data(), p->labels.size() * sizeof(float));
  std::memcpy(indptr, p->indptr.data(), p->indptr.size() * sizeof(int64_t));
  std::memcpy(indices, p->indices.data(),
              p->indices.size() * sizeof(int32_t));
  std::memcpy(values, p->values.data(), p->values.size() * sizeof(float));
}

void lsvm_free(void* h) { delete static_cast<Parsed*>(h); }

}  // extern "C"
