// Native Avro container-file decoder for TrainingExample-shaped records.
//
// Reference parity: the reference's ingestion runs as JVM Avro decoding
// inside Spark executors (photon-client data/avro/AvroDataReader.scala);
// this is the rebuild's native data-loader for the Avro path — the hot
// per-record decode loop in C++ instead of pure Python. The Python side
// (avro/native_decode.py) compiles the file's WRITER SCHEMA into a flat
// int32 "plan" that this interpreter executes per record; any schema
// outside the supported family falls back to the Python codec, whose
// semantics this decoder mirrors exactly (block structure, zigzag varints,
// deflate codec, sync-marker checks, fail-fast on truncation).
//
// Plan format (int32 stream), one entry per top-level record field:
//   [n_branches, (type, capture, arg) x n_branches]
// A non-union field is a 1-branch entry. Types:
//   0 null, 1 boolean, 2 int, 3 long, 4 float, 5 double, 6 string,
//   7 bytes, 8 map<string>, 9 array<{name,term?,value}> (arg bit0: has
//   term)
// Captures: 0 skip, 1 response, 2 offset, 3 weight, 4 uid, 5 metadataMap,
//   6 feature bag (arg = bag id; for type 9 the bag id is arg >> 1).
//
// Columnar outputs: per-record scalars (response/offset/weight, uid kind +
// long + string), per-bag COO triples (row, key-id, value) with a
// deduplicated "name\x01term" string table, and metadataMap entries as
// (row, key-id, value-id) over two string tables.
//
// C ABI (ctypes): open -> schema -> decode(plan) -> query sizes -> fill
// caller-allocated numpy buffers -> free. Errors are per-handle strings.

#include <zlib.h>

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <new>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

constexpr int kTypeNull = 0, kTypeBoolean = 1, kTypeInt = 2, kTypeLong = 3,
              kTypeFloat = 4, kTypeDouble = 5, kTypeString = 6,
              kTypeBytes = 7, kTypeMapString = 8, kTypeNtvArray = 9;
constexpr int kCapSkip = 0, kCapResponse = 1, kCapOffset = 2, kCapWeight = 3,
              kCapUid = 4, kCapMeta = 5, kCapBag = 6;

struct Branch {
  int type;
  int capture;
  int arg;
};

struct Field {
  std::vector<Branch> branches;
};

struct StringTable {
  std::unordered_map<std::string, int32_t> ids;
  std::vector<std::string> strs;

  int32_t intern(const std::string& s) {
    auto it = ids.find(s);
    if (it != ids.end()) return it->second;
    int32_t id = static_cast<int32_t>(strs.size());
    ids.emplace(s, id);
    strs.push_back(s);
    return id;
  }

  int64_t total_bytes() const {
    int64_t n = 0;
    for (const auto& s : strs) n += static_cast<int64_t>(s.size());
    return n;
  }
};

struct Bag {
  std::vector<int64_t> rows;
  std::vector<int32_t> keys;
  std::vector<double> values;
  StringTable table;
};

struct Handle {
  std::vector<uint8_t> file;
  std::string schema_json;
  std::string codec = "null";
  uint8_t sync[16];
  size_t blocks_start = 0;
  std::string error;

  // decode outputs
  int64_t n_records = 0;
  std::vector<double> response, offset, weight;
  std::vector<uint8_t> uid_kind;  // 0 none/null, 1 string, 2 long
  std::vector<int64_t> uid_long;
  std::vector<std::string> uid_str;
  std::vector<Bag> bags;
  std::vector<int64_t> meta_rows;
  std::vector<int32_t> meta_keys, meta_vals;
  StringTable meta_key_table, meta_val_table;
};

struct Cursor {
  const uint8_t* p;
  const uint8_t* end;
};

bool need(Handle* h, Cursor* c, size_t n, const char* what) {
  if (static_cast<size_t>(c->end - c->p) < n) {
    h->error = std::string("truncated input while reading ") + what;
    return false;
  }
  return true;
}

bool read_long(Handle* h, Cursor* c, int64_t* out, const char* what) {
  uint64_t acc = 0;
  int shift = 0;
  while (true) {
    if (c->p >= c->end) {
      h->error = std::string("truncated varint while reading ") + what;
      return false;
    }
    uint8_t b = *c->p++;
    // A 64-bit zigzag varint uses at most 10 bytes; the 10th (shift 63)
    // may only carry the final bit. Anything longer/larger is corrupt —
    // reject it like the Python codec's OverflowError instead of silently
    // wrapping the accumulator.
    if (shift > 63 || (shift == 63 && (b & 0x7f) > 1)) {
      h->error = std::string("varint overflows 64 bits while reading ") +
                 what;
      return false;
    }
    acc |= static_cast<uint64_t>(b & 0x7f) << shift;
    if (!(b & 0x80)) break;
    shift += 7;
  }
  // zigzag
  *out = static_cast<int64_t>((acc >> 1) ^ (~(acc & 1) + 1));
  return true;
}

bool read_bytes_span(Handle* h, Cursor* c, const uint8_t** data, int64_t* len,
                     const char* what) {
  if (!read_long(h, c, len, what)) return false;
  if (*len < 0) {
    h->error = std::string("negative length while reading ") + what;
    return false;
  }
  if (!need(h, c, static_cast<size_t>(*len), what)) return false;
  *data = c->p;
  c->p += *len;
  return true;
}

bool skip_value(Handle* h, Cursor* c, int type);

bool read_double_of(Handle* h, Cursor* c, int type, double* out,
                    const char* what) {
  switch (type) {
    case kTypeInt:
    case kTypeLong: {
      int64_t v;
      if (!read_long(h, c, &v, what)) return false;
      *out = static_cast<double>(v);
      return true;
    }
    case kTypeFloat: {
      if (!need(h, c, 4, what)) return false;
      float f;
      std::memcpy(&f, c->p, 4);
      c->p += 4;
      *out = f;
      return true;
    }
    case kTypeDouble: {
      if (!need(h, c, 8, what)) return false;
      std::memcpy(out, c->p, 8);
      c->p += 8;
      return true;
    }
    case kTypeBoolean: {
      if (!need(h, c, 1, what)) return false;
      *out = (*c->p++ != 0) ? 1.0 : 0.0;
      return true;
    }
    default:
      h->error = std::string("type is not numeric: ") + what;
      return false;
  }
}

// Avro block-count header for arrays/maps: negative count is followed by a
// byte size (ignored here); 0 terminates.
bool read_block_count(Handle* h, Cursor* c, int64_t* count,
                      const char* what) {
  if (!read_long(h, c, count, what)) return false;
  if (*count < 0) {
    int64_t byte_size;
    if (!read_long(h, c, &byte_size, what)) return false;
    if (*count == INT64_MIN) {  // -INT64_MIN is signed-overflow UB
      h->error = std::string("absurd block count while reading ") + what;
      return false;
    }
    *count = -*count;
  }
  return true;
}

bool skip_value(Handle* h, Cursor* c, int type) {
  switch (type) {
    case kTypeNull:
      return true;
    case kTypeBoolean:
      return need(h, c, 1, "boolean") && (c->p += 1, true);
    case kTypeInt:
    case kTypeLong: {
      int64_t v;
      return read_long(h, c, &v, "int/long");
    }
    case kTypeFloat:
      return need(h, c, 4, "float") && (c->p += 4, true);
    case kTypeDouble:
      return need(h, c, 8, "double") && (c->p += 8, true);
    case kTypeString:
    case kTypeBytes: {
      const uint8_t* d;
      int64_t n;
      return read_bytes_span(h, c, &d, &n, "string/bytes");
    }
    case kTypeMapString: {
      int64_t count;
      while (true) {
        if (!read_block_count(h, c, &count, "map")) return false;
        if (count == 0) return true;
        for (int64_t i = 0; i < count; ++i) {
          const uint8_t* d;
          int64_t n;
          if (!read_bytes_span(h, c, &d, &n, "map key")) return false;
          if (!read_bytes_span(h, c, &d, &n, "map value")) return false;
        }
      }
    }
    default:
      h->error = "cannot skip unsupported type";
      return false;
  }
}

bool decode_ntv_array(Handle* h, Cursor* c, bool has_term, Bag* bag,
                      int64_t row) {
  int64_t count;
  std::string key;
  while (true) {
    if (!read_block_count(h, c, &count, "feature array")) return false;
    if (count == 0) return true;
    for (int64_t i = 0; i < count; ++i) {
      const uint8_t* name;
      int64_t name_len;
      if (!read_bytes_span(h, c, &name, &name_len, "feature name"))
        return false;
      // Key layout mirrors index/indexmap.py feature_key: bare name when
      // the term is empty, "name\x01term" otherwise.
      key.assign(reinterpret_cast<const char*>(name),
                 static_cast<size_t>(name_len));
      if (has_term) {
        const uint8_t* term;
        int64_t term_len;
        if (!read_bytes_span(h, c, &term, &term_len, "feature term"))
          return false;
        if (term_len > 0) {
          key.push_back('\x01');
          key.append(reinterpret_cast<const char*>(term),
                     static_cast<size_t>(term_len));
        }
      }
      double value;
      if (!need(h, c, 8, "feature value")) return false;
      std::memcpy(&value, c->p, 8);
      c->p += 8;
      if (bag != nullptr) {
        bag->rows.push_back(row);
        bag->keys.push_back(bag->table.intern(key));
        bag->values.push_back(value);
      }
    }
  }
}

bool decode_map_meta(Handle* h, Cursor* c, bool capture, int64_t row) {
  int64_t count;
  std::string key, val;
  while (true) {
    if (!read_block_count(h, c, &count, "metadata map")) return false;
    if (count == 0) return true;
    for (int64_t i = 0; i < count; ++i) {
      const uint8_t* kd;
      int64_t kn;
      if (!read_bytes_span(h, c, &kd, &kn, "metadata key")) return false;
      const uint8_t* vd;
      int64_t vn;
      if (!read_bytes_span(h, c, &vd, &vn, "metadata value")) return false;
      if (capture) {
        key.assign(reinterpret_cast<const char*>(kd),
                   static_cast<size_t>(kn));
        val.assign(reinterpret_cast<const char*>(vd),
                   static_cast<size_t>(vn));
        h->meta_rows.push_back(row);
        h->meta_keys.push_back(h->meta_key_table.intern(key));
        h->meta_vals.push_back(h->meta_val_table.intern(val));
      }
    }
  }
}

bool decode_record(Handle* h, Cursor* c, const std::vector<Field>& fields,
                   int64_t row) {
  bool response_seen = false;
  for (const Field& f : fields) {
    int bi = 0;
    if (f.branches.size() > 1) {
      int64_t b;
      if (!read_long(h, c, &b, "union branch")) return false;
      if (b < 0 || static_cast<size_t>(b) >= f.branches.size()) {
        h->error = "union branch out of range";
        return false;
      }
      bi = static_cast<int>(b);
    }
    const Branch& br = f.branches[bi];
    switch (br.capture) {
      case kCapSkip:
        if (br.type == kTypeNtvArray) {
          if (!decode_ntv_array(h, c, br.arg & 1, nullptr, row))
            return false;
        } else if (!skip_value(h, c, br.type)) {
          return false;
        }
        break;
      case kCapResponse: {
        if (br.type == kTypeNull) break;  // stays unseen -> error below
        double v;
        if (!read_double_of(h, c, br.type, &v, "response")) return false;
        h->response[row] = v;
        response_seen = true;
        break;
      }
      case kCapOffset: {
        if (br.type == kTypeNull) break;  // keep default 0.0
        double v;
        if (!read_double_of(h, c, br.type, &v, "offset")) return false;
        h->offset[row] = v;
        break;
      }
      case kCapWeight: {
        if (br.type == kTypeNull) break;  // keep default 1.0
        double v;
        if (!read_double_of(h, c, br.type, &v, "weight")) return false;
        h->weight[row] = v;
        break;
      }
      case kCapUid: {
        if (br.type == kTypeNull) {
          h->uid_kind[row] = 0;
        } else if (br.type == kTypeString) {
          const uint8_t* d;
          int64_t n;
          if (!read_bytes_span(h, c, &d, &n, "uid")) return false;
          h->uid_kind[row] = 1;
          h->uid_str[row].assign(reinterpret_cast<const char*>(d),
                                 static_cast<size_t>(n));
        } else if (br.type == kTypeInt || br.type == kTypeLong) {
          int64_t v;
          if (!read_long(h, c, &v, "uid")) return false;
          h->uid_kind[row] = 2;
          h->uid_long[row] = v;
        } else {
          h->error = "uid branch type unsupported";
          return false;
        }
        break;
      }
      case kCapMeta:
        if (br.type == kTypeNull) break;
        if (br.type != kTypeMapString) {
          h->error = "metadata capture needs map<string>";
          return false;
        }
        if (!decode_map_meta(h, c, true, row)) return false;
        break;
      case kCapBag: {
        if (br.type == kTypeNull) break;
        if (br.type != kTypeNtvArray) {
          h->error = "bag capture needs an array of name/term/value";
          return false;
        }
        int bag_id = br.arg >> 1;
        if (bag_id < 0 ||
            static_cast<size_t>(bag_id) >= h->bags.size()) {
          h->error = "bag id out of range";
          return false;
        }
        if (!decode_ntv_array(h, c, br.arg & 1, &h->bags[bag_id], row))
          return false;
        break;
      }
      default:
        h->error = "unknown capture";
        return false;
    }
  }
  if (!response_seen) {
    char buf[96];
    std::snprintf(buf, sizeof(buf),
                  "record %lld is missing required response field",
                  static_cast<long long>(row));
    h->error = buf;
    return false;
  }
  return true;
}

bool inflate_raw(Handle* h, const uint8_t* src, size_t n,
                 std::vector<uint8_t>* out) {
  z_stream zs;
  std::memset(&zs, 0, sizeof(zs));
  if (inflateInit2(&zs, -15) != Z_OK) {
    h->error = "zlib init failed";
    return false;
  }
  // avail_in is 32-bit; feed the source in <4 GiB slices so spec-legal
  // multi-GiB blocks decode instead of zlib seeing a truncated prefix.
  size_t fed = 0;
  zs.next_in = const_cast<uint8_t*>(src);
  zs.avail_in = 0;
  out->clear();
  uint8_t buf[1 << 16];
  int rc = Z_OK;
  while (rc != Z_STREAM_END) {
    if (zs.avail_in == 0 && fed < n) {
      const size_t take = std::min(n - fed, size_t{1} << 30);
      zs.next_in = const_cast<uint8_t*>(src + fed);
      zs.avail_in = static_cast<uInt>(take);
      fed += take;
    }
    zs.next_out = buf;
    zs.avail_out = sizeof(buf);
    rc = inflate(&zs, Z_NO_FLUSH);
    if (rc != Z_OK && rc != Z_STREAM_END) {
      inflateEnd(&zs);
      h->error = "deflate block is corrupt";
      return false;
    }
    out->insert(out->end(), buf, buf + (sizeof(buf) - zs.avail_out));
    if (rc == Z_OK && zs.avail_in == 0 && fed >= n && zs.avail_out != 0) {
      inflateEnd(&zs);
      h->error = "deflate block is truncated";
      return false;
    }
  }
  inflateEnd(&zs);
  return true;
}

bool parse_header(Handle* h) {
  Cursor c{h->file.data(), h->file.data() + h->file.size()};
  if (!need(h, &c, 4, "magic")) return false;
  if (std::memcmp(c.p, "Obj\x01", 4) != 0) {
    h->error = "not an Avro object container file (bad magic)";
    return false;
  }
  c.p += 4;
  int64_t count;
  while (true) {
    if (!read_block_count(h, &c, &count, "file metadata")) return false;
    if (count == 0) break;
    for (int64_t i = 0; i < count; ++i) {
      const uint8_t* kd;
      int64_t kn;
      if (!read_bytes_span(h, &c, &kd, &kn, "metadata key")) return false;
      const uint8_t* vd;
      int64_t vn;
      if (!read_bytes_span(h, &c, &vd, &vn, "metadata value")) return false;
      std::string key(reinterpret_cast<const char*>(kd),
                      static_cast<size_t>(kn));
      if (key == "avro.schema") {
        h->schema_json.assign(reinterpret_cast<const char*>(vd),
                              static_cast<size_t>(vn));
      } else if (key == "avro.codec") {
        h->codec.assign(reinterpret_cast<const char*>(vd),
                        static_cast<size_t>(vn));
      }
    }
  }
  if (!need(h, &c, 16, "sync marker")) return false;
  std::memcpy(h->sync, c.p, 16);
  c.p += 16;
  h->blocks_start = static_cast<size_t>(c.p - h->file.data());
  if (h->schema_json.empty()) {
    h->error = "container file has no avro.schema";
    return false;
  }
  if (h->codec != "null" && h->codec != "deflate") {
    h->error = "unsupported codec: " + h->codec;
    return false;
  }
  return true;
}

}  // namespace

extern "C" {

void* pavro_open(const char* path) {
  Handle* h = new Handle();
  FILE* f = std::fopen(path, "rb");
  if (f == nullptr) {
    h->error = std::string("cannot open ") + path;
    return h;
  }
  std::fseek(f, 0, SEEK_END);
  long size = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  h->file.resize(static_cast<size_t>(size < 0 ? 0 : size));
  if (size > 0 &&
      std::fread(h->file.data(), 1, h->file.size(), f) != h->file.size()) {
    h->error = std::string("short read on ") + path;
    std::fclose(f);
    return h;
  }
  std::fclose(f);
  parse_header(h);
  return h;
}

// Range variant for block-parallel ingestion (photon_ml_tpu/ingest): the
// caller has already walked the container's block headers in Python and
// knows (a) where the header ends and (b) a sync-aligned [start, end) byte
// range of whole blocks. Only header + range bytes are read — N workers
// over one file cost one file's worth of I/O total, not N. The spliced
// buffer (header immediately followed by the range) decodes through the
// same block loop as a whole file; sync markers sit in the header, so
// per-block validation is unchanged.
void* pavro_open_range(const char* path, long header_len, long start,
                       long end) {
  Handle* h = new Handle();
  FILE* f = std::fopen(path, "rb");
  if (f == nullptr) {
    h->error = std::string("cannot open ") + path;
    return h;
  }
  std::fseek(f, 0, SEEK_END);
  long size = std::ftell(f);
  if (header_len < 4 || header_len > size || start < header_len ||
      end < start || end > size) {
    h->error = "invalid block range";
    std::fclose(f);
    return h;
  }
  h->file.resize(static_cast<size_t>(header_len + (end - start)));
  std::fseek(f, 0, SEEK_SET);
  bool ok = std::fread(h->file.data(), 1, static_cast<size_t>(header_len),
                       f) == static_cast<size_t>(header_len);
  if (ok && end > start) {
    std::fseek(f, start, SEEK_SET);
    ok = std::fread(h->file.data() + header_len, 1,
                    static_cast<size_t>(end - start),
                    f) == static_cast<size_t>(end - start);
  }
  std::fclose(f);
  if (!ok) {
    h->error = std::string("short read on ") + path;
    return h;
  }
  if (parse_header(h) &&
      h->blocks_start != static_cast<size_t>(header_len)) {
    h->error = "header length does not match the parsed header";
  }
  return h;
}

int pavro_error(void* hv, char* buf, int cap) {
  Handle* h = static_cast<Handle*>(hv);
  if (h->error.empty()) return 0;
  std::snprintf(buf, static_cast<size_t>(cap), "%s", h->error.c_str());
  return 1;
}

long pavro_schema_len(void* hv) {
  return static_cast<long>(static_cast<Handle*>(hv)->schema_json.size());
}

void pavro_schema(void* hv, char* buf) {
  Handle* h = static_cast<Handle*>(hv);
  std::memcpy(buf, h->schema_json.data(), h->schema_json.size());
}

long pavro_decode(void* hv, const int32_t* plan, long plan_len,
                  int n_bags) {
  Handle* h = static_cast<Handle*>(hv);
  if (!h->error.empty()) return -1;
  std::vector<Field> fields;
  long i = 0;
  while (i < plan_len) {
    int nb = plan[i++];
    if (nb < 1 || i + 3L * nb > plan_len) {
      h->error = "malformed decode plan";
      return -1;
    }
    Field f;
    for (int b = 0; b < nb; ++b) {
      f.branches.push_back(Branch{plan[i], plan[i + 1], plan[i + 2]});
      i += 3;
    }
    fields.push_back(std::move(f));
  }
  h->bags.assign(static_cast<size_t>(n_bags), Bag());

  // Pass 1: count records across blocks (cheap varint scan of headers,
  // validating sizes and sync markers before any allocation).
  {
    Cursor c{h->file.data() + h->blocks_start,
             h->file.data() + h->file.size()};
    while (c.p < c.end) {
      int64_t count, byte_size;
      if (!read_long(h, &c, &count, "block count")) return -1;
      if (!read_long(h, &c, &byte_size, "block size")) return -1;
      if (count < 0 || byte_size < 0 ||
          !need(h, &c, static_cast<size_t>(byte_size) + 16, "block")) {
        if (h->error.empty()) h->error = "corrupt block header";
        return -1;
      }
      // A decoded record occupies at least one byte, and deflate expands
      // at most ~1032x, so a block declaring more records than its payload
      // could possibly hold is corrupt (or hostile). Reject it here rather
      // than letting the declared total drive a std::bad_alloc through the
      // extern "C" boundary below (every other corruption path surfaces as
      // a ValueError, not an abort). Overflow-safe form: ceil(count/ratio)
      // bytes are the minimum payload — no byte_size*ratio product, so
      // spec-legal multi-GiB blocks (byte_size already bounded by the real
      // file size via need() above) pass through; a hostile count that
      // still slips past merely lands in the allocation catch below.
      // (count - 1) / ratio cannot overflow for any int64 count, unlike
      // count + ratio - 1.
      const int64_t ratio = (h->codec == "deflate") ? 1032 : 1;
      if (count > 0 && (count - 1) / ratio >= byte_size) {
        h->error = "block declares more records than its payload can hold";
        return -1;
      }
      c.p += byte_size;
      if (std::memcmp(c.p, h->sync, 16) != 0) {
        h->error = "sync marker mismatch (corrupt block)";
        return -1;
      }
      c.p += 16;
      h->n_records += count;
    }
  }

  try {
    h->response.assign(static_cast<size_t>(h->n_records), 0.0);
    h->offset.assign(static_cast<size_t>(h->n_records), 0.0);
    h->weight.assign(static_cast<size_t>(h->n_records), 1.0);
    h->uid_kind.assign(static_cast<size_t>(h->n_records), 0);
    h->uid_long.assign(static_cast<size_t>(h->n_records), 0);
    h->uid_str.assign(static_cast<size_t>(h->n_records), std::string());
  } catch (const std::exception&) {  // bad_alloc or length_error
    h->error = "cannot allocate columns for declared record count";
    return -1;
  }

  int64_t row = 0;
  std::vector<uint8_t> scratch;

  // Decode pass (single traversal, mirrors pass 1). The whole pass sits
  // under the same allocation catch as the column assigns: a hostile
  // deflate block can expand up to ~1032x its (file-size-bounded) payload,
  // and the scratch/string growth it drives must surface as a ValueError
  // through pavro_error, never as an exception escaping the extern "C"
  // frame.
  try {
    Cursor c{h->file.data() + h->blocks_start,
             h->file.data() + h->file.size()};
    while (c.p < c.end) {
      int64_t count, byte_size;
      if (!read_long(h, &c, &count, "block count")) return -1;
      if (!read_long(h, &c, &byte_size, "block size")) return -1;
      const uint8_t* payload = c.p;
      size_t payload_len = static_cast<size_t>(byte_size);
      c.p += byte_size + 16;  // validated in pass 1
      Cursor rc{payload, payload + payload_len};
      if (h->codec == "deflate") {
        if (!inflate_raw(h, payload, payload_len, &scratch)) return -1;
        rc = Cursor{scratch.data(), scratch.data() + scratch.size()};
      }
      for (int64_t k = 0; k < count; ++k, ++row) {
        if (!decode_record(h, &rc, fields, row)) return -1;
      }
      // Trailing payload bytes after the declared records are ignored —
      // the Python DataFileReader accepts such files too (parity).
    }
  } catch (const std::exception&) {
    h->error = "cannot allocate memory while decoding blocks";
    return -1;
  }
  return static_cast<long>(h->n_records);
}

long pavro_num_records(void* hv) {
  return static_cast<long>(static_cast<Handle*>(hv)->n_records);
}

void pavro_fill_scalars(void* hv, double* response, double* offset,
                        double* weight, uint8_t* uid_kind,
                        int64_t* uid_long) {
  Handle* h = static_cast<Handle*>(hv);
  size_t n = static_cast<size_t>(h->n_records);
  std::memcpy(response, h->response.data(), n * sizeof(double));
  std::memcpy(offset, h->offset.data(), n * sizeof(double));
  std::memcpy(weight, h->weight.data(), n * sizeof(double));
  std::memcpy(uid_kind, h->uid_kind.data(), n);
  std::memcpy(uid_long, h->uid_long.data(), n * sizeof(int64_t));
}

long pavro_uid_strs_len(void* hv) {
  Handle* h = static_cast<Handle*>(hv);
  int64_t total = 0;
  for (const auto& s : h->uid_str) total += static_cast<int64_t>(s.size());
  return static_cast<long>(total);
}

void pavro_fill_uid_strs(void* hv, char* buf, int64_t* offsets) {
  Handle* h = static_cast<Handle*>(hv);
  int64_t pos = 0;
  int64_t i = 0;
  for (const auto& s : h->uid_str) {
    std::memcpy(buf + pos, s.data(), s.size());
    pos += static_cast<int64_t>(s.size());
    offsets[i++] = pos;
  }
}

long pavro_bag_nnz(void* hv, int bag) {
  return static_cast<long>(
      static_cast<Handle*>(hv)->bags[static_cast<size_t>(bag)].rows.size());
}

long pavro_bag_nkeys(void* hv, int bag) {
  return static_cast<long>(static_cast<Handle*>(hv)
                               ->bags[static_cast<size_t>(bag)]
                               .table.strs.size());
}

long pavro_bag_keys_len(void* hv, int bag) {
  return static_cast<long>(static_cast<Handle*>(hv)
                               ->bags[static_cast<size_t>(bag)]
                               .table.total_bytes());
}

void pavro_fill_bag(void* hv, int bag, int64_t* rows, int32_t* keys,
                    double* values) {
  Bag& b = static_cast<Handle*>(hv)->bags[static_cast<size_t>(bag)];
  std::memcpy(rows, b.rows.data(), b.rows.size() * sizeof(int64_t));
  std::memcpy(keys, b.keys.data(), b.keys.size() * sizeof(int32_t));
  std::memcpy(values, b.values.data(), b.values.size() * sizeof(double));
}

void pavro_fill_bag_keys(void* hv, int bag, char* buf, int64_t* offsets) {
  Bag& b = static_cast<Handle*>(hv)->bags[static_cast<size_t>(bag)];
  int64_t pos = 0;
  int64_t i = 0;
  for (const auto& s : b.table.strs) {
    std::memcpy(buf + pos, s.data(), s.size());
    pos += static_cast<int64_t>(s.size());
    offsets[i++] = pos;
  }
}

long pavro_meta_count(void* hv) {
  return static_cast<long>(static_cast<Handle*>(hv)->meta_rows.size());
}

void pavro_fill_meta(void* hv, int64_t* rows, int32_t* keys,
                     int32_t* vals) {
  Handle* h = static_cast<Handle*>(hv);
  std::memcpy(rows, h->meta_rows.data(),
              h->meta_rows.size() * sizeof(int64_t));
  std::memcpy(keys, h->meta_keys.data(),
              h->meta_keys.size() * sizeof(int32_t));
  std::memcpy(vals, h->meta_vals.data(),
              h->meta_vals.size() * sizeof(int32_t));
}

long pavro_meta_table_nkeys(void* hv, int which) {
  Handle* h = static_cast<Handle*>(hv);
  StringTable& t = which == 0 ? h->meta_key_table : h->meta_val_table;
  return static_cast<long>(t.strs.size());
}

long pavro_meta_table_len(void* hv, int which) {
  Handle* h = static_cast<Handle*>(hv);
  StringTable& t = which == 0 ? h->meta_key_table : h->meta_val_table;
  return static_cast<long>(t.total_bytes());
}

void pavro_fill_meta_table(void* hv, int which, char* buf,
                           int64_t* offsets) {
  Handle* h = static_cast<Handle*>(hv);
  StringTable& t = which == 0 ? h->meta_key_table : h->meta_val_table;
  int64_t pos = 0;
  int64_t i = 0;
  for (const auto& s : t.strs) {
    std::memcpy(buf + pos, s.data(), s.size());
    pos += static_cast<int64_t>(s.size());
    offsets[i++] = pos;
  }
}

void pavro_free(void* hv) { delete static_cast<Handle*>(hv); }

}  // extern "C"
