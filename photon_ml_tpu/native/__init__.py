"""Native (C++) runtime components.

The compute path is JAX/XLA; these are host-side runtime pieces where the
reference uses native-adjacent code (PalDB). Shared objects build on first
use with g++ and are cached under ``_build/``.
"""

from __future__ import annotations

import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_BUILD_DIR = os.path.join(_HERE, "_build")
_LOCK = threading.Lock()


def build_library(name: str, link: tuple[str, ...] = ()) -> str:
    """Compile ``<name>.cc`` into ``_build/lib<name>.so`` (once) and return
    the path. Rebuilds when the source is newer than the cached object.
    ``link`` appends linker flags (e.g. ``("-lz",)``)."""
    src = os.path.join(_HERE, f"{name}.cc")
    out = os.path.join(_BUILD_DIR, f"lib{name}.so")
    with _LOCK:
        if (not os.path.exists(out)
                or os.path.getmtime(out) < os.path.getmtime(src)):
            os.makedirs(_BUILD_DIR, exist_ok=True)
            # pid-suffixed temp + atomic rename: concurrent builders (e.g.
            # pytest-xdist workers — the threading lock is per-process) each
            # write their own object and the last rename wins intact.
            tmp = f"{out}.{os.getpid()}.tmp"
            try:
                subprocess.run(
                    ["g++", "-std=c++17", "-O2", "-shared", "-fPIC",
                     "-o", tmp, src, *link],
                    check=True, capture_output=True)
                os.replace(tmp, out)
            finally:
                if os.path.exists(tmp):
                    os.unlink(tmp)
    return out
