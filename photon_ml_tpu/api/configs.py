"""Coordinate data/optimization configuration bundles.

Reference parity: photon-api ``data/FixedEffectDataConfiguration.scala``,
``data/RandomEffectDataConfiguration.scala``,
``data/CoordinateDataConfiguration.scala`` and the per-coordinate
optimization bundles of ``optimization/game/*Configuration.scala``; the
reference encodes these as mini-DSL CLI strings parsed by
``parseAndBuild`` — here they are dataclasses with a compact string parser
for CLI use (see photon_ml_tpu/cli/).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Union

from photon_ml_tpu.game.staging import StagingConfig
from photon_ml_tpu.game.sweep import SweepConfig
from photon_ml_tpu.ingest import IngestConfig
from photon_ml_tpu.optim import (OptimizerConfig, OptimizerType,
                                 RegularizationContext, RegularizationType)
from photon_ml_tpu.optim.problem import (GLMOptimizationConfiguration,
                                         VarianceComputationType)

__all__ = [
    "CoordinateConfiguration",
    "CoordinateDataConfiguration",
    "FactoredRandomEffectDataConfiguration",
    "FixedEffectDataConfiguration",
    "IngestConfig",
    "RandomEffectDataConfiguration",
    "StagingConfig",
    "StreamingConfig",
    "SweepConfig",
    "parse_ingest_config",
    "parse_kv",
    "parse_optimizer_config",
    "parse_staging_config",
    "parse_streaming_config",
    "parse_sweep_config",
]


@dataclasses.dataclass(frozen=True)
class StreamingConfig:
    """Row-streamed fixed-effect fit configuration (docs/STREAMING.md).

    When passed to ``GameEstimator(streaming=...)`` (CLI: ``game_train
    --streaming``), sparse fixed-effect coordinates route onto the
    streamed path: the SparseShard stages into host-resident hot-dense/
    cold-ELL chunks, chunk ranges partition over the mesh's ``data``
    axis, and every L-BFGS value/gradient streams each device's range
    with partials merged via ``psum`` — n bounded by host RAM, not HBM.

    ``chunk_rows``: rows per chunk, the streamed transfer unit (every
    chunk shares one compiled program; the flagship uses 5M). ``num_hot``:
    hot-dense columns per chunk (the Zipf head). ``feature_dtype``:
    chunk storage dtype — None inherits the coordinate's
    ``FixedEffectDataConfiguration.feature_dtype``; "bfloat16" halves
    the host→device stream, the steady-state cost of every objective
    evaluation, and "int8" (symmetric per-column quantization, f32
    accumulation — docs/STREAMING.md "Quantized streaming") quarters
    it. ``prefetch_depth``: transfers in flight ahead of compute
    per device. ``pin_chunks``: leading chunks pinned resident PER
    DEVICE (spare HBM traded for stream traffic). ``workers``: staging
    canonicalization threads (None = host cores). ``solver``: the
    streamed driver — "lbfgs" (the batch default), "sdca"
    (duality-gap-certified dual coordinate ascent), or "sgd" (primal
    mini-batch fallback) — docs/STREAMING.md "Stochastic solvers"; a
    per-coordinate ``--opt-config optimizer=SDCA|SGD`` overrides it.
    Under sdca, ``pin_chunks`` becomes the GAP-DRIVEN residency budget
    (the pin set re-ranks by per-chunk gap contribution each epoch).
    """

    chunk_rows: int = 262144
    num_hot: int = 512
    feature_dtype: Optional[str] = None
    prefetch_depth: int = 2
    pin_chunks: int = 0
    workers: Optional[int] = None
    solver: str = "lbfgs"

    def __post_init__(self):
        if self.chunk_rows < 1:
            raise ValueError(
                f"chunk_rows must be >= 1, got {self.chunk_rows}")
        if self.num_hot < 1:
            raise ValueError(f"num_hot must be >= 1, got {self.num_hot}")
        if self.feature_dtype not in (None, "float32", "bfloat16",
                                      "int8"):
            raise ValueError(
                f"unsupported feature_dtype {self.feature_dtype!r}; "
                "expected float32, bfloat16, or int8")
        if self.prefetch_depth < 1:
            raise ValueError(
                f"prefetch_depth must be >= 1, got {self.prefetch_depth}")
        if self.pin_chunks < 0:
            raise ValueError(
                f"pin_chunks must be >= 0, got {self.pin_chunks}")
        if self.workers is not None and self.workers < 1:
            raise ValueError(f"workers must be >= 1, got {self.workers}")
        if self.solver not in ("lbfgs", "sdca", "sgd"):
            raise ValueError(
                f"unsupported streaming solver {self.solver!r}; "
                "expected lbfgs, sdca, or sgd")


@dataclasses.dataclass(frozen=True)
class FixedEffectDataConfiguration:
    """Reference: FixedEffectDataConfiguration (featureShardId, minPartitions
    — partitions have no TPU referent).

    ``feature_sharded`` applies to sparse (ELL) shards only: shard the
    coefficient dimension over the mesh's ``model`` axis (P3, the Criteo
    regime where the feature space is too large to replicate).

    ``feature_dtype``: on-device storage dtype for DENSE shards and for
    the hybrid layout's hot block on sparse shards. ``"bfloat16"`` halves
    HBM traffic on the bandwidth-bound GLM hot loop (margins/gradients
    accumulate in f32 on the MXU); optimizer state and coefficients stay
    f32. Expect coefficient deltas ~1e-2 relative — opt in when
    throughput matters more than the last two digits.

    ``hybrid`` (sparse shards only): the hot-dense / cold-class layout of
    ops/hybrid_sparse.py. ``None`` = automatic (on when the mesh has a
    single data shard and the shard is not feature_sharded); True/False
    force it."""

    feature_shard_id: str
    feature_sharded: bool = False
    feature_dtype: str = "float32"
    hybrid: Optional[bool] = None


@dataclasses.dataclass(frozen=True)
class RandomEffectDataConfiguration:
    """Reference: RandomEffectDataConfiguration (randomEffectType,
    featureShardId, active-data bounds)."""

    random_effect_type: str
    feature_shard_id: str
    active_data_lower_bound: int = 1
    active_data_upper_bound: Optional[int] = None
    # Per-entity feature-subspace projection (reference projectorType:
    # INDEX_MAP builds a LinearSubspaceProjector per entity; RANDOM solves
    # every entity in one shared ``projected_dimension``-dim Gaussian
    # random-projection space (ProjectionMatrixBroadcast); NONE solves at
    # the full shard dimension).
    projector: str = "NONE"
    projected_dimension: Optional[int] = None  # RANDOM only
    # Cap each entity's subspace at ceil(ratio · num_samples) columns by
    # |Pearson corr(feature, label)| (reference
    # RandomEffectDataConfiguration.numFeaturesToSamplesRatio →
    # LocalDataset.filterFeaturesByPearsonCorrelationScore). Implies
    # projection.
    features_to_samples_ratio: Optional[float] = None
    # Keep the trained model in each entity's active-column subspace
    # (reference: RandomEffectModelInProjectedSpace) instead of the dense
    # (num_entities, d) table. None = automatic: on when the dense table
    # would exceed ~1 GiB. Requires a projected coordinate.
    subspace_model: Optional[bool] = None
    # On-device storage dtype for the staged (E_b, cap, d_active) bucket
    # blocks — same contract as the fixed-effect knob: "bfloat16" halves
    # the blocks' HBM and the per-entity solves accumulate in f32 on the
    # MXU; coefficients/optimizer state stay f32.
    feature_dtype: str = "float32"

    def __post_init__(self):
        if self.projector.upper() not in ("NONE", "INDEX_MAP", "RANDOM"):
            raise ValueError(
                f"unknown projector {self.projector!r}; "
                "expected NONE, INDEX_MAP, or RANDOM")
        if self.feature_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                f"unsupported feature_dtype {self.feature_dtype!r}; "
                "expected float32 or bfloat16")
        if self.projector.upper() == "RANDOM":
            if self.projected_dimension is None \
                    or self.projected_dimension < 1:
                raise ValueError(
                    "projector=RANDOM needs projected_dimension >= 1")
            if self.features_to_samples_ratio is not None:
                raise ValueError(
                    "features_to_samples_ratio composes with INDEX_MAP "
                    "projection, not RANDOM (the random projection space "
                    "has no per-feature identity to filter)")
        elif self.projected_dimension is not None:
            raise ValueError(
                "projected_dimension only applies to projector=RANDOM")
        if (self.features_to_samples_ratio is not None
                and not self.features_to_samples_ratio > 0):
            raise ValueError(
                f"features_to_samples_ratio must be > 0, got "
                f"{self.features_to_samples_ratio}")


@dataclasses.dataclass(frozen=True)
class FactoredRandomEffectDataConfiguration:
    """Reference: the pre-fork FactoredRandomEffectDataConfiguration +
    MFOptimizationConfiguration (numLatentFactors → ``rank``,
    numInnerIterations → ``alternations``): per-entity models constrained
    to a shared rank-``rank`` subspace (see game/factored.py)."""

    random_effect_type: str
    feature_shard_id: str
    rank: int = 4
    alternations: int = 2
    active_data_lower_bound: int = 1
    active_data_upper_bound: Optional[int] = None

    def __post_init__(self):
        if self.rank < 1:
            raise ValueError(f"rank must be >= 1, got {self.rank}")
        if self.alternations < 1:
            raise ValueError(
                f"alternations must be >= 1, got {self.alternations}")


CoordinateDataConfiguration = Union[FixedEffectDataConfiguration,
                                    RandomEffectDataConfiguration,
                                    FactoredRandomEffectDataConfiguration]


@dataclasses.dataclass(frozen=True)
class CoordinateConfiguration:
    """One coordinate: its data slice + optimization settings + an optional
    regularization-weight grid (the reference's GameEstimator loops over a
    Seq[GameOptimizationConfiguration] built from per-coordinate grids)."""

    data: CoordinateDataConfiguration
    optimization: GLMOptimizationConfiguration
    reg_weight_grid: tuple[float, ...] = ()

    def expand_grid(self) -> list[GLMOptimizationConfiguration]:
        if not self.reg_weight_grid:
            return [self.optimization]
        out = []
        for w in self.reg_weight_grid:
            reg = dataclasses.replace(self.optimization.regularization,
                                      reg_weight=w)
            out.append(dataclasses.replace(self.optimization,
                                           regularization=reg))
        return out


def parse_kv(spec: str) -> dict[str, str]:
    """Parse the ``key=value,...`` mini-DSL used by reference-style config
    strings (shared by optimizer configs and CLI coordinate specs)."""
    kv: dict[str, str] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        k, sep, v = part.partition("=")
        if not sep:
            raise ValueError(f"bad config token {part!r} in {spec!r}")
        kv[k.strip()] = v.strip()
    return kv


def parse_staging_config(spec: str) -> StagingConfig:
    """Parse ``key=value,...`` mini-DSL for the random-effect staging
    pipeline (game/staging.py).

    Keys: workers (pool size; default = host cores), mode
    (thread|process), depth (max staged-but-unconsumed shard blocks),
    shard_entities (entity lanes per staged shard), retries (bounded
    per-shard retry budget), backoff (base seconds of the jittered
    retry backoff), straggler (straggler deadline in seconds — exceeded
    shards re-stage serially; see docs/ROBUSTNESS.md).
    """
    kv = parse_kv(spec)
    known = {"workers", "mode", "depth", "shard_entities", "retries",
             "backoff", "straggler"}
    unknown = set(kv) - known
    if unknown:
        raise ValueError(f"unknown staging keys {sorted(unknown)}; "
                         f"expected {sorted(known)}")
    defaults = StagingConfig()
    return StagingConfig(
        workers=int(kv["workers"]) if "workers" in kv else None,
        mode=kv.get("mode", "thread").lower(),
        pipeline_depth=int(kv["depth"]) if "depth" in kv else None,
        shard_entities=(int(kv["shard_entities"])
                        if "shard_entities" in kv else None),
        max_retries=(int(kv["retries"]) if "retries" in kv
                     else defaults.max_retries),
        retry_backoff_s=(float(kv["backoff"]) if "backoff" in kv
                         else defaults.retry_backoff_s),
        straggler_timeout_s=(float(kv["straggler"])
                             if "straggler" in kv else None),
    )


def parse_ingest_config(spec: str) -> IngestConfig:
    """Parse ``key=value,...`` mini-DSL for the parallel Avro ingestion
    pipeline (photon_ml_tpu/ingest, docs/INGEST.md).

    Keys: workers (decode pool size; default = host cores), mode
    (thread|process), depth (max decoded-but-unfolded chunks),
    chunk_records (target records per decode task). The columnar ingest
    cache directory is a separate flag (``game_train
    --ingest-cache-dir``), mirroring ``--staging-cache-dir``.
    """
    kv = parse_kv(spec)
    known = {"workers", "mode", "depth", "chunk_records"}
    unknown = set(kv) - known
    if unknown:
        raise ValueError(f"unknown ingest keys {sorted(unknown)}; "
                         f"expected {sorted(known)}")
    defaults = IngestConfig()
    return IngestConfig(
        workers=int(kv["workers"]) if "workers" in kv else None,
        mode=kv.get("mode", "thread").lower(),
        pipeline_depth=int(kv["depth"]) if "depth" in kv else None,
        chunk_records=(int(kv["chunk_records"]) if "chunk_records" in kv
                       else defaults.chunk_records),
    )


def parse_streaming_config(spec: str) -> StreamingConfig:
    """Parse ``key=value,...`` mini-DSL for the row-streamed fixed-effect
    path (docs/STREAMING.md). An empty spec (bare ``--streaming``) takes
    every default.

    Keys: chunk_rows (rows per streamed chunk), num_hot (hot-dense
    columns per chunk), dtype (float32|bfloat16|int8 chunk storage;
    default inherits the coordinate's dtype), depth (prefetch transfers
    in flight per device), pin (leading chunks pinned per device),
    workers (staging canonicalization threads), solver
    (lbfgs|sdca|sgd streamed driver — docs/STREAMING.md "Stochastic
    solvers").
    """
    kv = parse_kv(spec)
    known = {"chunk_rows", "num_hot", "dtype", "depth", "pin", "workers",
             "solver"}
    unknown = set(kv) - known
    if unknown:
        raise ValueError(f"unknown streaming keys {sorted(unknown)}; "
                         f"expected {sorted(known)}")
    defaults = StreamingConfig()
    return StreamingConfig(
        chunk_rows=(int(kv["chunk_rows"]) if "chunk_rows" in kv
                    else defaults.chunk_rows),
        num_hot=int(kv["num_hot"]) if "num_hot" in kv else defaults.num_hot,
        feature_dtype=kv["dtype"].lower() if "dtype" in kv else None,
        prefetch_depth=(int(kv["depth"]) if "depth" in kv
                        else defaults.prefetch_depth),
        pin_chunks=int(kv["pin"]) if "pin" in kv else defaults.pin_chunks,
        workers=int(kv["workers"]) if "workers" in kv else None,
        solver=(kv["solver"].lower() if "solver" in kv
                else defaults.solver),
    )


def parse_sweep_config(spec: str) -> SweepConfig:
    """Parse ``key=value,...`` mini-DSL for dirty-gated incremental
    sweeps (game/sweep.py, docs/SWEEPS.md). An empty spec (bare
    ``--sweep``) takes every default — ``gate=0``, which tracks nothing
    and is bit-identical to an ungated run.

    Keys: theta (mean per-row offset-drift threshold), grad_tol
    (per-entity gradient-norm threshold), min_sweeps_full (leading
    outer iterations forced full, >= 1), final_full (true|false — force
    the last outer iteration full, the parity-band backstop), gram
    (true|false — reuse per-bucket normal-equation Gram blocks for the
    squared-loss bucket solver).
    """
    kv = parse_kv(spec)
    known = {"theta", "grad_tol", "min_sweeps_full", "final_full", "gram"}
    unknown = set(kv) - known
    if unknown:
        raise ValueError(f"unknown sweep keys {sorted(unknown)}; "
                         f"expected {sorted(known)}")
    defaults = SweepConfig()

    def _bool(key: str, default: bool) -> bool:
        if key not in kv:
            return default
        v = kv[key].lower()
        if v not in ("true", "false"):
            raise ValueError(f"{key} must be true or false, got {kv[key]!r}")
        return v == "true"

    return SweepConfig(
        theta=float(kv["theta"]) if "theta" in kv else defaults.theta,
        grad_tol=(float(kv["grad_tol"]) if "grad_tol" in kv
                  else defaults.grad_tol),
        min_sweeps_full=(int(kv["min_sweeps_full"])
                         if "min_sweeps_full" in kv
                         else defaults.min_sweeps_full),
        final_full_sweep=_bool("final_full", defaults.final_full_sweep),
        gram=_bool("gram", defaults.gram),
    )


def parse_optimizer_config(spec: str) -> GLMOptimizationConfiguration:
    """Parse ``key=value,...`` mini-DSL (reference-style config strings).

    Keys: optimizer (LBFGS|OWLQN|TRON), max_iter, tolerance,
    reg (NONE|L1|L2|ELASTIC_NET), reg_weight, alpha, down_sampling_rate,
    variance (NONE|SIMPLE|FULL).
    """
    kv = parse_kv(spec)

    opt = OptimizerConfig(
        optimizer_type=OptimizerType(kv.get("optimizer", "LBFGS").upper()),
        max_iterations=int(kv.get("max_iter", 100)),
        tolerance=float(kv.get("tolerance", 1e-7)),
    )
    reg = RegularizationContext(
        reg_type=RegularizationType(kv.get("reg", "NONE").upper()),
        reg_weight=float(kv.get("reg_weight", 0.0)),
        elastic_net_alpha=float(kv.get("alpha", 0.5)),
    )
    return GLMOptimizationConfiguration(
        optimizer=opt,
        regularization=reg,
        variance_computation=VarianceComputationType(
            kv.get("variance", "NONE").upper()),
        down_sampling_rate=float(kv.get("down_sampling_rate", 1.0)),
    )
