"""GameTransformer: the scoring front door.

Reference parity: photon-api ``transformers/GameTransformer.scala`` —
GameModel + data → scores, with optional evaluation
(``transform(data) → scores``).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.data.game_data import GameDataset
from photon_ml_tpu.evaluation import evaluators as ev
from photon_ml_tpu.game.models import GameModel
from photon_ml_tpu.ops import losses as losses_mod
from photon_ml_tpu.utils.events import ScoringBatch, default_emitter

Array = jax.Array


@dataclasses.dataclass
class ScoringResult:
    """Scores (+ passthrough fields) for output writing.

    Reference parity: ScoringResultAvro (uid, score, label/offset/weight
    passthrough).
    """

    scores: np.ndarray
    uids: np.ndarray
    labels: Optional[np.ndarray] = None
    offsets: Optional[np.ndarray] = None
    weights: Optional[np.ndarray] = None


class GameTransformer:
    """Score datasets with a trained GameModel."""

    def __init__(self, model: GameModel,
                 evaluators: Optional[list[str]] = None):
        self.model = model
        self.evaluators = evaluators or []

    def transform(self, data: GameDataset,
                  as_mean: bool = False) -> ScoringResult:
        t0 = time.perf_counter()
        scores = self.model.score(data)
        default_emitter.emit(ScoringBatch(
            source="game_score", rows=data.num_rows,
            padded_rows=data.num_rows, seconds=time.perf_counter() - t0))
        if as_mean:
            loss = losses_mod.loss_for_task(self.model.task)
            scores = loss.mean(scores)
        return ScoringResult(
            scores=np.asarray(scores),
            uids=np.arange(data.num_rows, dtype=np.int64),
            labels=data.response,
            offsets=data.offsets,
            weights=data.weights,
        )

    def transform_batched(self, data: GameDataset, batch_rows: int,
                          as_mean: bool = False,
                          prefetch_depth: int = 2) -> ScoringResult:
        """Score in bounded device batches with host→device prefetch.

        The scoring-time analogue of the reader's chunked ingestion
        (SURVEY §0): only ``prefetch_depth`` row-chunks are ever device-
        resident, and the next chunk's transfer overlaps the current
        chunk's scoring — large inputs score with flat device memory at
        the same throughput as one-shot staging. Results are identical to
        ``transform`` (same scores, order, passthrough fields).
        """
        from photon_ml_tpu.data.prefetch import (device_prefetch,
                                                 iter_row_chunks,
                                                 stage_dataset)

        parts = []
        for staged in device_prefetch(iter_row_chunks(data, batch_rows),
                                      depth=prefetch_depth,
                                      place=stage_dataset):
            t0 = time.perf_counter()
            parts.append(self.model.score(staged))
            # seconds is dispatch time, not device time — scoring is async
            # under the prefetch pipeline by design.
            default_emitter.emit(ScoringBatch(
                source="game_score", rows=staged.num_rows,
                padded_rows=staged.num_rows, seconds=time.perf_counter() - t0))
        scores = np.concatenate([np.asarray(p) for p in parts]) \
            if parts else np.zeros(0, np.float32)
        if as_mean:
            loss = losses_mod.loss_for_task(self.model.task)
            scores = np.asarray(loss.mean(jnp.asarray(scores)))
        return ScoringResult(
            scores=scores,
            uids=np.arange(data.num_rows, dtype=np.int64),
            labels=data.response,
            offsets=data.offsets,
            weights=data.weights,
        )

    def transform_and_evaluate(self, data: GameDataset, as_mean: bool = False,
                               batch_rows: Optional[int] = None
                               ) -> tuple[ScoringResult, ev.EvaluationResults]:
        """Score + evaluate. Metrics are always computed on raw linear
        scores (AUC is link-invariant; the loss evaluators expect margins);
        the returned ScoringResult honors ``as_mean``. ``batch_rows``
        scores through the bounded-memory prefetch pipeline."""
        if not self.evaluators:
            raise ValueError("no evaluators configured")
        result = (self.transform_batched(data, batch_rows)
                  if batch_rows else self.transform(data))
        # Host arrays pass through as-is — evaluation_suite does its own
        # single-device placement (one transfer per array, no collectives).
        evaluation = ev.evaluation_suite(
            self.evaluators, result.scores, data.response, data.weights,
            group_ids_by_column=dict(data.entity_ids),
            num_groups_by_column=dict(data.num_entities))
        if as_mean:
            loss = losses_mod.loss_for_task(self.model.task)
            result = dataclasses.replace(
                result, scores=np.asarray(loss.mean(jnp.asarray(result.scores))))
        return result, evaluation
