"""GameEstimator: the training front door.

Reference parity: photon-api ``estimators/GameEstimator.scala`` — builds
per-coordinate datasets/coordinates from the input data, runs
``CoordinateDescent`` once per GameOptimizationConfiguration (the
regularization-weight grid), evaluates each candidate on validation data,
and exposes best-model selection
(``fit(data, validationData, configs) → Seq[(GameModel, EvaluationResults,
config)]``).
"""

from __future__ import annotations

import contextlib
import dataclasses
import itertools
import logging
import os
from typing import Optional

import jax.numpy as jnp

from photon_ml_tpu.api.configs import (CoordinateConfiguration,
                                       FactoredRandomEffectDataConfiguration,
                                       FixedEffectDataConfiguration,
                                       IngestConfig,
                                       RandomEffectDataConfiguration,
                                       StagingConfig, StreamingConfig)
from photon_ml_tpu.data.game_data import GameDataset, SparseShard
from photon_ml_tpu.evaluation import evaluators as ev
from photon_ml_tpu.game import descent
from photon_ml_tpu.game.coordinates import (FixedEffectCoordinate,
                                            RandomEffectCoordinate,
                                            SparseFixedEffectCoordinate)
from photon_ml_tpu.game.factored import FactoredRandomEffectCoordinate
from photon_ml_tpu.game.models import GameModel
from photon_ml_tpu.normalization import NormalizationContext
from photon_ml_tpu.ops import losses as losses_mod
from photon_ml_tpu.optim.problem import (GLMOptimizationConfiguration,
                                         VarianceComputationType)
from photon_ml_tpu.types import TaskType

logger = logging.getLogger("photon_ml_tpu.api")


@dataclasses.dataclass
class GameResult:
    model: GameModel
    evaluation: Optional[ev.EvaluationResults]
    configs: dict[str, GLMOptimizationConfiguration]




class GameEstimator:
    """Train GAME models over a device mesh (reference: GameEstimator)."""

    def __init__(
        self,
        task: TaskType,
        coordinates: dict[str, CoordinateConfiguration],
        update_sequence: list[str],
        mesh,
        descent_iterations: int = 1,
        validation_evaluators: Optional[list[str]] = None,
        normalization: Optional[dict[str, NormalizationContext]] = None,
        compute_variances_at_end: bool = True,
        staging_cache_dir: Optional[str] = None,
        staging: Optional[StagingConfig] = None,
        ingest: Optional[IngestConfig] = None,
        streaming: Optional[StreamingConfig] = None,
        sweep=None,
        trace=None,
        ledger_dir: Optional[str] = None,
        watchdog=None,
    ):
        self.task = TaskType(task)
        self.coordinate_configs = coordinates
        self.update_sequence = update_sequence
        self.mesh = mesh
        self.descent_iterations = descent_iterations
        self.validation_evaluators = validation_evaluators or []
        self.normalization = normalization or {}
        self.compute_variances_at_end = compute_variances_at_end
        # Disk cache for projected random-effect staging artifacts
        # (game/staging_cache.py): a warm re-fit of the same dataset in a
        # fresh process memory-maps the staged blocks instead of re-paying
        # the projection pass.
        self.staging_cache_dir = staging_cache_dir
        # Parallel staging pipeline knobs (game/staging.py), shared by
        # every projected random-effect coordinate this estimator builds.
        self.staging = staging
        # Parallel Avro ingestion knobs (photon_ml_tpu/ingest): the
        # estimator consumes already-materialized GameDatasets, so this is
        # the configuration surface for the drivers that read Avro on its
        # behalf (game_train wires --ingest / --ingest-cache-dir through
        # here and into AvroDataReader.read).
        self.ingest = ingest
        # Row-streamed fixed effects (docs/STREAMING.md): when set, every
        # sparse fixed-effect coordinate routes onto the streamed path —
        # chunk ranges sharded over the mesh's data axis, psum-merged
        # partials, n bounded by host RAM instead of HBM.
        self.streaming = streaming
        # Dirty-gated incremental sweeps (docs/SWEEPS.md): a SweepConfig
        # routing random-effect coordinates onto the gated descent path —
        # outer iterations past min_sweeps_full refit only entities whose
        # residual offsets drifted or whose last solve left gradient
        # mass. Deliberately NOT part of the coordinate cache key below:
        # gating changes which lanes dispatch, never how coordinates are
        # constructed/staged.
        self.sweep = sweep
        # Span tracing (docs/OBSERVABILITY.md): an obs.Tracer instance
        # activated for the duration of each fit() — library users get
        # the same timeline `game_train --trace-out` produces, without
        # going through the CLI. None (the default) costs nothing.
        self.trace = trace
        # Run ledger (docs/OBSERVABILITY.md "The run ledger"): when set,
        # each fit() writes convergence telemetry under this directory —
        # manifest + append-as-produced per-iteration rows. Reuses an
        # already-active ledger (the game_train driver's, a tuning
        # trial's parent) instead of opening a second one.
        self.ledger_dir = ledger_dir
        # Convergence watchdogs (obs/watchdog.py): a WatchdogConfig
        # armed for the duration of fit(). None (default) = every
        # optimizer site pays one None check.
        self.watchdog = watchdog
        self.loss = losses_mod.loss_for_task(self.task)
        # (cache key, coords) of the last fit — lets repeated fits on the
        # SAME dataset (hyperparameter tuning trials) swap optimization
        # configs instead of re-running bucketing + device staging. A shared
        # mutable holder, not a plain attribute: tuning fits shallow-copied
        # estimators, and the copies must feed the same cache. The cached
        # coordinates keep the dataset alive, so id() keys are stable.
        self._coord_cache: dict[str, tuple[tuple, dict]] = {}

    # -- coordinate construction ------------------------------------------

    def _build_coordinates(
        self,
        dataset: GameDataset,
        opt_configs: dict[str, GLMOptimizationConfiguration],
    ) -> dict[str, object]:
        coords: dict[str, object] = {}
        streamed: list[str] = []
        for cid, cc in self.coordinate_configs.items():
            opt = opt_configs[cid]
            if isinstance(cc.data, FixedEffectDataConfiguration):
                shard = dataset.feature_shards[cc.data.feature_shard_id]
                if isinstance(shard, SparseShard):
                    if cc.data.feature_shard_id in self.normalization:
                        raise ValueError(
                            f"normalization is not supported on sparse "
                            f"shard {cc.data.feature_shard_id!r}")
                    if self.streaming is not None:
                        if cc.data.feature_sharded:
                            raise ValueError(
                                f"coordinate {cid!r}: streaming and "
                                f"feature_sharded are mutually exclusive "
                                f"— the streamed path shards ROWS over "
                                f"the data axis (docs/STREAMING.md)")
                        from photon_ml_tpu.game.coordinates import \
                            StreamingSparseFixedEffectCoordinate

                        coords[cid] = \
                            StreamingSparseFixedEffectCoordinate.stage(
                                dataset, cc.data.feature_shard_id,
                                self.loss, opt, self.mesh, self.streaming,
                                default_dtype=cc.data.feature_dtype)
                        streamed.append(cid)
                        continue
                    coords[cid] = SparseFixedEffectCoordinate(
                        dataset, cc.data.feature_shard_id, self.loss, opt,
                        self.mesh,
                        feature_sharded=cc.data.feature_sharded,
                        hybrid=cc.data.hybrid,
                        feature_dtype=cc.data.feature_dtype)
                    continue
                coords[cid] = FixedEffectCoordinate(
                    dataset, cc.data.feature_shard_id, self.loss, opt,
                    self.mesh,
                    norm=self.normalization.get(cc.data.feature_shard_id,
                                                NormalizationContext()),
                    feature_dtype=cc.data.feature_dtype)
            elif isinstance(cc.data, RandomEffectDataConfiguration):
                if cc.data.projector.upper() == "RANDOM":
                    # Gaussian random projection = a factored coordinate
                    # with a frozen seeded projection matrix
                    # (ProjectionMatrixBroadcast parity).
                    if cc.data.feature_shard_id in self.normalization:
                        raise ValueError(
                            f"normalization is not supported with "
                            f"projector=RANDOM on shard "
                            f"{cc.data.feature_shard_id!r}")
                    coords[cid] = FactoredRandomEffectCoordinate(
                        dataset, cc.data.random_effect_type,
                        cc.data.feature_shard_id, self.loss, opt, self.mesh,
                        rank=cc.data.projected_dimension,
                        learn_projection=False,
                        lower_bound=cc.data.active_data_lower_bound,
                        upper_bound=cc.data.active_data_upper_bound)
                    continue
                coords[cid] = RandomEffectCoordinate(
                    dataset, cc.data.random_effect_type,
                    cc.data.feature_shard_id, self.loss, opt, self.mesh,
                    lower_bound=cc.data.active_data_lower_bound,
                    upper_bound=cc.data.active_data_upper_bound,
                    norm=self.normalization.get(cc.data.feature_shard_id,
                                                NormalizationContext()),
                    projection=cc.data.projector.upper() == "INDEX_MAP",
                    features_to_samples_ratio=(
                        cc.data.features_to_samples_ratio),
                    subspace_model=cc.data.subspace_model,
                    staging_cache_dir=self.staging_cache_dir,
                    feature_dtype=cc.data.feature_dtype,
                    staging=self.staging)
            elif isinstance(cc.data, FactoredRandomEffectDataConfiguration):
                if cc.data.feature_shard_id in self.normalization:
                    raise ValueError(
                        f"normalization is not supported on factored "
                        f"random-effect shard "
                        f"{cc.data.feature_shard_id!r} (the latent space "
                        f"has no per-feature transform)")
                coords[cid] = FactoredRandomEffectCoordinate(
                    dataset, cc.data.random_effect_type,
                    cc.data.feature_shard_id, self.loss, opt, self.mesh,
                    rank=cc.data.rank,
                    alternations=cc.data.alternations,
                    lower_bound=cc.data.active_data_lower_bound,
                    upper_bound=cc.data.active_data_upper_bound)
            else:  # pragma: no cover
                raise TypeError(type(cc.data))
        if self.streaming is not None and not streamed:
            # A streaming config that routes nothing is a silent no-op
            # pretending to be the biggest-config engine — fail loud.
            raise ValueError(
                "streaming=... was set but no coordinate routed onto the "
                "streamed path: it applies to FIXED-effect coordinates "
                "over SPARSE shards (docs/STREAMING.md)")
        return coords

    # -- evaluation --------------------------------------------------------

    @staticmethod
    def _stage_dataset(dataset: GameDataset) -> GameDataset:
        """Device-resident copy of a dataset for repeated scoring.

        Validation scoring runs once per coordinate-descent step; with
        host numpy shards every ``jnp.asarray`` inside the score/evaluate
        paths would re-upload the whole validation set each step. Staging
        once per fit makes those conversions no-ops — per-step validation
        then adds no host→device traffic at all.
        """
        from photon_ml_tpu.data.prefetch import stage_dataset

        return stage_dataset(dataset)

    def _evaluate(self, model: GameModel, dataset: GameDataset
                  ) -> Optional[ev.EvaluationResults]:
        if not self.validation_evaluators:
            return None
        scores = model.score(dataset)
        return ev.evaluation_suite(
            self.validation_evaluators, scores,
            dataset.response, dataset.weights,
            group_ids_by_column=dict(dataset.entity_ids),
            num_groups_by_column=dict(dataset.num_entities))

    # -- fit ---------------------------------------------------------------

    def fit(
        self,
        data: GameDataset,
        validation_data: Optional[GameDataset] = None,
        initial_models: Optional[dict] = None,
        locked_coordinates: Optional[set[str]] = None,
        checkpoint_dir: Optional[str] = None,
    ) -> list[GameResult]:
        """Train one GAME model per point of the regularization grid.

        Returns one GameResult per grid combination (cartesian product of
        each coordinate's ``reg_weight_grid``), mirroring the reference's
        Seq[GameOptimizationConfiguration] loop.

        With ``checkpoint_dir`` set, each grid point checkpoints its
        coordinate-descent progress under ``<checkpoint_dir>/grid-<i>`` and
        a rerun with the same arguments resumes mid-descent (SURVEY.md §5
        failure-recovery: the Spark-lineage replacement).

        With ``GameEstimator(trace=...)`` set, the whole fit runs under
        that tracer (an ``estimator.fit`` root span; staging, descent
        updates, streamed passes and checkpoint writes nest below it) —
        dump it afterwards with ``trace.dump(path)``.

        With ``GameEstimator(ledger_dir=...)`` set, the fit records a
        run ledger there (resume-appending when one with the same run
        identity already exists); ``GameEstimator(watchdog=...)`` arms
        the convergence watchdogs for the duration
        (docs/OBSERVABILITY.md "The run ledger").
        """
        from photon_ml_tpu import obs

        with contextlib.ExitStack() as stack:
            if self.watchdog is not None:
                prev_wd = obs.set_watchdog(self.watchdog)
                stack.callback(obs.set_watchdog, prev_wd)
            if self.ledger_dir and obs.ledger() is None:
                import jax

                if jax.process_index() == 0:
                    # Open (or resume-append) this fit's run ledger —
                    # unless the driver already installed one, which
                    # every row then lands in (the tuning-trial case).
                    # One writer per shared filesystem: rank 0 only.
                    led = obs.RunLedger.resume(
                        self.ledger_dir, manifest=self.ledger_manifest())
                    prev_led = obs.set_ledger(led)
                    stack.callback(obs.set_ledger, prev_led)

                    # Closed via the stack even when the fit raises — a
                    # crashed fit keeps its curve prefix, stamped with
                    # how it ended.
                    def _close(exc_type, exc, tb, _led=led):
                        _led.close(status="ok" if exc_type is None
                                   else "error")
                        return False

                    stack.push(_close)
            if self.trace is None:
                return self._fit(data, validation_data, initial_models,
                                 locked_coordinates, checkpoint_dir)
            stack.enter_context(obs.activated(trace_obj=self.trace))
            stack.enter_context(
                obs.span("estimator.fit", cat="driver",
                         coordinates=list(self.coordinate_configs)))
            return self._fit(data, validation_data, initial_models,
                             locked_coordinates, checkpoint_dir)

    def ledger_manifest(self) -> dict:
        """Creator-side run-ledger manifest: the configuration this
        estimator can describe up front (game_train reuses it when the
        DRIVER owns the ledger). Run IDENTITY (dataset digest etc.) is
        stamped by descent.run's fingerprint machinery at the first
        update."""
        from photon_ml_tpu.obs.ledger import build_manifest

        config = {
            "task": self.task.value,
            "update_sequence": list(self.update_sequence),
            "iterations": self.descent_iterations,
            "coordinates": {
                cid: {"data": descent._jsonable(cc.data),
                      "optimization": descent._jsonable(cc.optimization),
                      "reg_weight_grid": list(cc.reg_weight_grid)}
                for cid, cc in self.coordinate_configs.items()},
            "streaming": descent._jsonable(self.streaming),
            "sweep": descent._jsonable(self.sweep),
            "normalization": {
                s: descent.normalization_digest(ctx)
                for s, ctx in self.normalization.items()},
        }
        return build_manifest(
            config=config, mesh_shape=dict(self.mesh.shape))

    def _fit(
        self,
        data: GameDataset,
        validation_data: Optional[GameDataset] = None,
        initial_models: Optional[dict] = None,
        locked_coordinates: Optional[set[str]] = None,
        checkpoint_dir: Optional[str] = None,
    ) -> list[GameResult]:
        from photon_ml_tpu.game.checkpoint import CheckpointManager

        if validation_data is not None:
            # Grouped evaluators index per-entity ids against each
            # dataset's own vocabulary; scoring gathers RE rows by id. Both
            # are silently wrong if validation was read with a different
            # vocabulary than training (reference: shared PalDB index maps
            # guarantee this; here it must be asserted).
            for t, n_train in data.num_entities.items():
                n_val = validation_data.num_entities.get(t)
                if n_val is None:
                    continue
                # Provenance tokens (AvroDataReader attaches them) settle
                # alignment exactly: validation's BASE vocabulary must be
                # training's FINAL one — a true extension passes whatever
                # the sizes, an independently-built vocabulary fails even
                # at identical size (counts cannot tell those apart).
                tr_tok = data.vocab_tokens.get(t)
                va_tok = validation_data.vocab_tokens.get(t)
                if tr_tok is not None and va_tok is not None:
                    # Aligned iff validation's vocabulary IS training's
                    # final one (content-identical — e.g. a subset() split)
                    # or extends it (base == training's final).
                    if tr_tok[1] not in va_tok:
                        raise ValueError(
                            f"validation entity vocabulary for {t!r} was "
                            f"not derived from the training vocabulary "
                            f"(provenance mismatch): entity ids would "
                            f"silently misalign. Read validation with the "
                            f"training vocabularies (AvroDataReader "
                            f"entity_vocabs=meta.entity_vocabs, "
                            f"allow_unseen_entities=True)")
                    continue
                # No tokens (hand-built datasets): fall back to counts.
                # An EXTENSION of the training vocabulary is legal
                # (allow_unseen_entities: unseen ids get rows past the
                # frozen range and score with zero RE contribution); a
                # smaller/reshuffled vocabulary is silent id misalignment.
                if n_val < n_train:
                    raise ValueError(
                        f"validation entity vocabulary for {t!r} has size "
                        f"{n_val} < training {n_train}; read validation "
                        f"with the training vocabularies "
                        f"(AvroDataReader entity_vocabs=...)")
                if n_val > n_train:
                    # Counts cannot distinguish a true extension from an
                    # unrelated larger vocabulary — make the assumption
                    # loud so an independently-built validation set is
                    # noticed (ids 0..n_train-1 MUST mean the same
                    # entities in both datasets).
                    logger.warning(
                        "validation %s vocabulary (%d) extends training "
                        "(%d): assuming shared ids for the first %d "
                        "entities — unseen ones score with zero "
                        "random-effect contribution. Read validation with "
                        "the training vocabularies "
                        "(allow_unseen_entities=True) to guarantee this.",
                        t, n_val, n_train, n_train)

        if validation_data is not None and self.validation_evaluators:
            # Without evaluators validation_data is only consulted for the
            # vocabulary checks above — don't hold it in device memory.
            validation_data = self._stage_dataset(validation_data)

        cids = list(self.coordinate_configs)
        grids = [self.coordinate_configs[c].expand_grid() for c in cids]
        results: list[GameResult] = []
        base_coords: Optional[dict[str, object]] = None
        for grid_index, combo in enumerate(itertools.product(*grids)):
            opt_configs = dict(zip(cids, combo))
            if base_coords is None:
                # Coordinates (bucketing, device staging) are built ONCE;
                # later grid points — and later fit() calls on the same
                # dataset, e.g. tuning trials — swap only the optimization
                # config (reference: datasets built once, configs looped).
                # Key everything that shapes coordinate construction: the
                # dataset CONTENT (descent._dataset_digest — so a fresh
                # dataset object with identical content hits the cache,
                # and a same-id object rebuilt with different content
                # cannot poison it), per-coordinate data configs, the task
                # (picks the loss), and the normalization array contents.
                # The digest is memoized on the dataset object, so arrays
                # mutated IN PLACE on a previously-fitted dataset are
                # still not detected — datasets remain immutable by
                # contract once fitted.
                cache_key = (
                    descent._dataset_digest(data),
                    # Metadata the array digest cannot see but that shapes
                    # construction: entity-table sizes (bucketing, model
                    # row counts) and intercept columns (reg masks).
                    tuple(sorted(data.num_entities.items())),
                    tuple(sorted(data.intercept_index.items())),
                    self.task,
                    tuple(sorted(
                        (s, descent.normalization_digest(ctx))
                        for s, ctx in self.normalization.items())),
                    tuple((cid, self.coordinate_configs[cid].data)
                          for cid in cids),
                    # Streaming reshapes coordinate construction (chunked
                    # staging vs device-resident) without touching the
                    # data configs above.
                    self.streaming)
                cached = self._coord_cache.get("last")
                if cached is not None and cached[0] == cache_key:
                    base_coords = {
                        cid: cached[1][cid]
                        .with_optimization_config(opt_configs[cid])
                        for cid in cids}
                else:
                    base_coords = self._build_coordinates(data, opt_configs)
                self._coord_cache["last"] = (cache_key, base_coords)
                coords = base_coords
            else:
                coords = {cid: base_coords[cid].with_optimization_config(
                    opt_configs[cid]) for cid in cids}
            val_fn = None
            if validation_data is not None and self.validation_evaluators:
                def val_fn(m, _vd=validation_data):
                    return self._evaluate(m, _vd).metrics
            manager = (CheckpointManager(
                os.path.join(checkpoint_dir, f"grid-{grid_index}"))
                if checkpoint_dir else None)
            from photon_ml_tpu import obs
            led = obs.ledger()
            bound = (led.bound(grid=grid_index) if led is not None
                     else contextlib.nullcontext())
            with bound:
                # pml: allow[PML012] grid-search outer loop: each call is an ENTIRE coordinate-descent fit; its per-update materialization (validation, checkpoint) amortizes over minutes of device work
                model, history = descent.run(
                    self.task, coords,
                    descent.CoordinateDescentConfig(
                        self.update_sequence, self.descent_iterations),
                    initial_models=initial_models,
                    locked_coordinates=locked_coordinates,
                    validation_fn=val_fn,
                    checkpoint_manager=manager,
                    sweep=self.sweep)
            model = self._finalize_variances(model, coords, data)
            evaluation = (self._evaluate(model, validation_data)
                          if validation_data is not None else None)
            logger.info("GAME fit done for %s: %s",
                        {c: o.regularization.reg_weight
                         for c, o in opt_configs.items()},
                        evaluation.metrics if evaluation else "")
            results.append(GameResult(model=model, evaluation=evaluation,
                                      configs=opt_configs))
        return results

    def _finalize_variances(self, model: GameModel, coords, data: GameDataset
                            ) -> GameModel:
        """Compute per-coordinate coefficient variances at the optimum
        (reference: variance computation happens once after training)."""
        if not self.compute_variances_at_end:
            return model
        any_requested = any(
            VarianceComputationType(c.optimization.variance_computation)
            != VarianceComputationType.NONE
            for c in self.coordinate_configs.values())
        if not any_requested:
            return model
        scores = {cid: coords[cid].score(m)
                  for cid, m in model.models.items()}
        total = jnp.asarray(data.offsets) + sum(scores.values())
        models = dict(model.models)
        for cid, m in model.models.items():
            offsets = total - scores[cid]
            models[cid] = coords[cid].compute_model_variances(m, offsets)
        return dataclasses.replace(model, models=models)

    def select_best_model(self, results: list[GameResult]) -> GameResult:
        """Pick by the primary validation evaluator (reference:
        GameEstimator/driver best-model selection)."""
        best = None
        for r in results:
            if best is None:
                best = r
            elif (r.evaluation is not None
                  and r.evaluation.better_than(best.evaluation)):
                best = r
        if best is None:
            raise ValueError("no results to select from")
        return best
