"""Evaluators: AUC, RMSE, losses, precision@k — plus grouped variants.

Reference parity: photon-api ``evaluation/`` — ``Evaluator.scala``,
``AreaUnderROCCurveEvaluator.scala``, ``RMSEEvaluator.scala``,
``SquaredLossEvaluator.scala``, ``PoissonLossEvaluator.scala``,
``PrecisionAtKEvaluator.scala``, and the grouped ("sharded") evaluators
``MultiAUCEvaluator`` / ``MultiPrecisionAtKEvaluator`` (metric per
user/query entity, then averaged), ``EvaluatorType.scala`` parsing
(``AUC``, ``RMSE``, ``PRECISION@k``, ``AUC@groupCol``...).

TPU-first design: everything is sort/segment math on device. Global AUC is
the tie-averaged rank-sum statistic (one sort). Grouped AUC does NOT loop
over groups (the reference's ``groupBy(id).map(localAUC)``): one lexicographic
sort of (group, score) + segment reductions computes every group's AUC at
once, scaling to hundreds of thousands of groups.
"""

from __future__ import annotations

import dataclasses
import enum
import re
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


# ---------------------------------------------------------------- core metrics


def auc(scores: Array, labels: Array, weights: Optional[Array] = None) -> Array:
    """Area under the ROC curve, tie-averaged rank-sum form (unweighted).

    Reference parity: AreaUnderROCCurveEvaluator (Spark BinaryClassification
    metrics). Weights are accepted for interface parity but ignored unless
    given, in which case a weighted rank-sum is used.
    """
    scores = scores.astype(jnp.float32)
    labels = labels.astype(jnp.float32)
    if weights is None:
        n = scores.shape[0]
        order = jnp.argsort(scores)
        s_sorted = scores[order]
        y_sorted = labels[order]
        pos = jnp.arange(1, n + 1, dtype=jnp.float32)
        # average rank over ties: searchsorted gives [left, right) run bounds
        left = jnp.searchsorted(s_sorted, s_sorted, side="left")
        right = jnp.searchsorted(s_sorted, s_sorted, side="right")
        avg_rank = (left + 1 + right).astype(jnp.float32) / 2.0
        p = jnp.sum(y_sorted)
        nneg = n - p
        rank_sum = jnp.sum(avg_rank * y_sorted)
        return (rank_sum - p * (p + 1) / 2.0) / jnp.maximum(p * nneg, 1e-12)
    # Weighted AUC: P(score+ > score-) with example weights.
    order = jnp.argsort(scores)
    y = labels[order]
    w = weights[order].astype(jnp.float32)
    wpos = w * y
    wneg = w * (1.0 - y)
    cum_neg = jnp.cumsum(wneg) - wneg  # negatives strictly below (by sort pos)
    # tie correction: half credit within equal-score runs
    s_sorted = scores[order]
    left = jnp.searchsorted(s_sorted, s_sorted, side="left")
    right = jnp.searchsorted(s_sorted, s_sorted, side="right")
    total_neg = jnp.cumsum(wneg)
    run_neg = total_neg[right - 1] - jnp.where(left > 0, total_neg[left - 1], 0.0)
    below_run = jnp.where(left > 0, total_neg[left - 1], 0.0)
    credit = jnp.sum(wpos * (below_run + 0.5 * (run_neg - wneg)))
    # subtract own weight only for negatives at identical score — wneg of a
    # positive example is 0, so (run_neg - wneg) == run_neg for positives.
    denom = jnp.sum(wpos) * jnp.sum(wneg)
    return credit / jnp.maximum(denom, 1e-12)


def rmse(scores: Array, labels: Array, weights: Optional[Array] = None) -> Array:
    """Root weighted mean squared error (reference: RMSEEvaluator)."""
    r = scores - labels
    if weights is None:
        return jnp.sqrt(jnp.mean(r * r))
    return jnp.sqrt(jnp.sum(weights * r * r) / jnp.maximum(jnp.sum(weights), 1e-12))


def squared_loss(scores: Array, labels: Array,
                 weights: Optional[Array] = None) -> Array:
    """Mean 0.5(score−label)² (reference: SquaredLossEvaluator)."""
    r = scores - labels
    l = 0.5 * r * r
    if weights is None:
        return jnp.mean(l)
    return jnp.sum(weights * l) / jnp.maximum(jnp.sum(weights), 1e-12)


def poisson_loss(scores: Array, labels: Array,
                 weights: Optional[Array] = None) -> Array:
    """Mean Poisson NLL e^z − y·z at linear scores z (reference:
    PoissonLossEvaluator)."""
    l = jnp.exp(scores) - labels * scores
    if weights is None:
        return jnp.mean(l)
    return jnp.sum(weights * l) / jnp.maximum(jnp.sum(weights), 1e-12)


def logistic_loss(scores: Array, labels: Array,
                  weights: Optional[Array] = None) -> Array:
    """Mean logistic NLL (reference: LogisticLossEvaluator)."""
    l = jax.nn.softplus(scores) - labels * scores
    if weights is None:
        return jnp.mean(l)
    return jnp.sum(weights * l) / jnp.maximum(jnp.sum(weights), 1e-12)


def precision_at_k(scores: Array, labels: Array, k: int) -> Array:
    """Fraction of positives among the k highest-scored examples."""
    n = scores.shape[0]
    kk = min(k, n)
    _, idx = jax.lax.top_k(scores, kk)
    return jnp.mean(labels[idx])


# ------------------------------------------------------------- grouped metrics


def _group_sort(scores: Array, group_ids: Array):
    """Order examples by (group, score asc) via two stable argsorts."""
    order1 = jnp.argsort(scores, stable=True)
    g1 = group_ids[order1]
    order2 = jnp.argsort(g1, stable=True)
    return order1[order2]


def grouped_auc(
    scores: Array,
    labels: Array,
    group_ids: Array,
    num_groups: int,
    weights: Optional[Array] = None,
) -> tuple[Array, Array]:
    """Per-group tie-averaged AUC for ALL groups at once.

    Returns ``(per_group_auc, valid)`` where ``valid`` marks groups having at
    least one positive and one negative (the reference's MultiAUCEvaluator
    skips one-class groups). One sort + segment reductions; no group loop.

    With ``weights``, each group's statistic is the weighted
    P(score+ > score-) with half credit on ties — the same definition the
    global weighted ``auc`` uses (the reference's per-entity evaluators
    run over weighted score RDDs); ``valid`` then requires positive weight
    on both classes.
    """
    order = _group_sort(scores, group_ids)
    g = group_ids[order]
    s = scores[order]
    y = labels[order].astype(jnp.float32)
    n = scores.shape[0]

    # Tie runs within (group, score).
    prev_same = (g == jnp.roll(g, 1)) & (s == jnp.roll(s, 1))
    prev_same = prev_same.at[0].set(False)
    run_id = jnp.cumsum(~prev_same) - 1

    if weights is not None:
        w = weights[order].astype(jnp.float32)
        wpos = w * y
        wneg = w * (1.0 - y)
        # Within-group exclusive cumulative negative weight: the global
        # cumsum already contains every earlier group's total (the layout
        # is group-major), so subtracting each group's exclusive prefix
        # leaves the within-group value.
        cn = jnp.cumsum(wneg)
        grp_tot_neg = jax.ops.segment_sum(wneg, g, num_segments=num_groups)
        grp_prefix = jnp.cumsum(grp_tot_neg) - grp_tot_neg
        within_excl = cn - wneg - grp_prefix[g]
        # Strictly-below credit stops at the tie run's first element; the
        # run's own negatives contribute half credit. within_excl is
        # non-decreasing, so the run minimum IS its first element's value.
        below_run = jax.ops.segment_min(within_excl, run_id,
                                        num_segments=n)[run_id]
        run_neg = jax.ops.segment_sum(wneg, run_id, num_segments=n)[run_id]
        credit = wpos * (below_run + 0.5 * (run_neg - wneg))
        wp = jax.ops.segment_sum(wpos, g, num_segments=num_groups)
        wn = grp_tot_neg
        auc_g = jax.ops.segment_sum(credit, g, num_segments=num_groups) \
            / jnp.maximum(wp * wn, 1e-12)
        valid = (wp > 0) & (wn > 0)
        return auc_g, valid

    pos_idx = jnp.arange(n, dtype=jnp.float32)
    run_pos_sum = jax.ops.segment_sum(pos_idx, run_id, num_segments=n)
    run_count = jax.ops.segment_sum(jnp.ones_like(pos_idx), run_id,
                                    num_segments=n)
    avg_pos = (run_pos_sum / jnp.maximum(run_count, 1.0))[run_id]

    counts = jax.ops.segment_sum(jnp.ones_like(pos_idx), g,
                                 num_segments=num_groups)
    starts = jnp.cumsum(counts) - counts  # exclusive prefix
    rank_in_group = avg_pos - starts[g] + 1.0

    p = jax.ops.segment_sum(y, g, num_segments=num_groups)
    tot = counts
    nneg = tot - p
    rank_sum = jax.ops.segment_sum(rank_in_group * y, g,
                                   num_segments=num_groups)
    auc_g = (rank_sum - p * (p + 1) / 2.0) / jnp.maximum(p * nneg, 1e-12)
    valid = (p > 0) & (nneg > 0)
    return auc_g, valid


def mean_grouped_auc(scores, labels, group_ids, num_groups,
                     weights=None) -> Array:
    """Average per-group AUC over valid groups (MultiAUCEvaluator result)."""
    auc_g, valid = grouped_auc(scores, labels, group_ids, num_groups,
                               weights)
    v = valid.astype(jnp.float32)
    return jnp.sum(auc_g * v) / jnp.maximum(jnp.sum(v), 1.0)


def grouped_precision_at_k(
    scores: Array,
    labels: Array,
    group_ids: Array,
    num_groups: int,
    k: int,
    weights: Optional[Array] = None,
) -> tuple[Array, Array]:
    """Per-group precision@k for all groups at once.

    ``valid`` marks groups with at least k examples (reference:
    MultiPrecisionAtKEvaluator filters groups with < k samples).

    With ``weights``, the k highest-scored examples are still chosen by
    score alone (k is a result-set size, not a weight budget); the
    precision over them is the WEIGHTED positive fraction
    Σ w·y / Σ w, consistent with the weighted score-set semantics of the
    other evaluators.
    """
    order = _group_sort(-scores, group_ids)  # score descending within group
    g = group_ids[order]
    y = labels[order].astype(jnp.float32)
    n = scores.shape[0]
    counts = jax.ops.segment_sum(jnp.ones((n,), jnp.float32), g,
                                 num_segments=num_groups)
    starts = jnp.cumsum(counts) - counts
    pos_in_group = jnp.arange(n, dtype=jnp.float32) - starts[g]
    in_top_k = pos_in_group < k
    if weights is not None:
        w = weights[order].astype(jnp.float32)
        hits = jax.ops.segment_sum(w * y * in_top_k, g,
                                   num_segments=num_groups)
        denom = jax.ops.segment_sum(w * in_top_k, g,
                                    num_segments=num_groups)
        prec = hits / jnp.maximum(denom, 1e-12)
        # An all-zero-weight top-k has no defined precision — exclude the
        # group (the same rule the weighted grouped AUC applies to
        # zero-weight classes) instead of averaging in a spurious 0.
        valid = (counts >= k) & (denom > 0)
    else:
        hits = jax.ops.segment_sum(y * in_top_k, g, num_segments=num_groups)
        denom = jnp.minimum(counts, float(k))
        prec = hits / jnp.maximum(denom, 1.0)
        valid = counts >= k
    return prec, valid


def mean_grouped_precision_at_k(scores, labels, group_ids, num_groups, k,
                                weights=None):
    prec, valid = grouped_precision_at_k(scores, labels, group_ids,
                                         num_groups, k, weights)
    v = valid.astype(jnp.float32)
    return jnp.sum(prec * v) / jnp.maximum(jnp.sum(v), 1.0)


# ---------------------------------------------------------- evaluator objects


class MetricDirection(enum.Enum):
    HIGHER_IS_BETTER = "higher"
    LOWER_IS_BETTER = "lower"


@dataclasses.dataclass(frozen=True)
class EvaluatorType:
    """Parsed evaluator spec (reference: EvaluatorType.scala).

    Accepts: ``AUC``, ``RMSE``, ``SQUARED_LOSS``, ``POISSON_LOSS``,
    ``LOGISTIC_LOSS``, ``PRECISION@k``, and grouped forms ``AUC@col`` /
    ``PRECISION@k@col`` (metric per value of the id column ``col``, averaged).
    """

    name: str
    k: Optional[int] = None
    group_column: Optional[str] = None

    @property
    def direction(self) -> MetricDirection:
        if self.name in ("AUC", "PRECISION"):
            return MetricDirection.HIGHER_IS_BETTER
        return MetricDirection.LOWER_IS_BETTER

    def better_than(self, a: float, b: float) -> bool:
        if self.direction == MetricDirection.HIGHER_IS_BETTER:
            return a > b
        return a < b

    def __str__(self) -> str:
        parts = [self.name]
        if self.k is not None:
            parts.append(str(self.k))
        if self.group_column is not None:
            parts.append(self.group_column)
        return "@".join(parts)

    @staticmethod
    def parse(spec: str) -> "EvaluatorType":
        s = spec.strip()
        m = re.fullmatch(r"(?i)PRECISION@(\d+)(?:@(\w+))?", s)
        if m:
            return EvaluatorType("PRECISION", k=int(m.group(1)),
                                 group_column=m.group(2))
        m = re.fullmatch(r"(?i)(AUC|RMSE|SQUARED_LOSS|POISSON_LOSS|"
                         r"LOGISTIC_LOSS)(?:@(\w+))?", s)
        if m:
            name = m.group(1).upper()
            group = m.group(2)
            if group is not None and name != "AUC":
                raise ValueError(f"grouped form not supported for {name}")
            return EvaluatorType(name, group_column=group)
        raise ValueError(f"unrecognized evaluator spec: {spec!r}")


def evaluate(
    etype: EvaluatorType,
    scores: Array,
    labels: Array,
    weights: Optional[Array] = None,
    group_ids: Optional[Array] = None,
    num_groups: Optional[int] = None,
) -> Array:
    """Compute one metric (reference: Evaluator.evaluate on a score RDD)."""
    if etype.group_column is not None:
        if group_ids is None or num_groups is None:
            raise ValueError(f"{etype} needs group_ids/num_groups")
        if etype.name == "AUC":
            return mean_grouped_auc(scores, labels, group_ids, num_groups,
                                    weights)
        if etype.name == "PRECISION":
            return mean_grouped_precision_at_k(scores, labels, group_ids,
                                               num_groups, etype.k, weights)
        raise ValueError(etype)  # pragma: no cover
    if etype.name == "AUC":
        return auc(scores, labels, weights)
    if etype.name == "RMSE":
        return rmse(scores, labels, weights)
    if etype.name == "SQUARED_LOSS":
        return squared_loss(scores, labels, weights)
    if etype.name == "POISSON_LOSS":
        return poisson_loss(scores, labels, weights)
    if etype.name == "LOGISTIC_LOSS":
        return logistic_loss(scores, labels, weights)
    if etype.name == "PRECISION":
        return precision_at_k(scores, labels, etype.k)
    raise ValueError(etype)  # pragma: no cover


@dataclasses.dataclass
class EvaluationResults:
    """Metric values keyed by evaluator spec; first entry is primary.

    Reference parity: EvaluationResults.scala (primary evaluator drives
    model selection in GameEstimator).
    """

    metrics: dict[str, float]
    primary: str

    @property
    def primary_value(self) -> float:
        return self.metrics[self.primary]

    def better_than(self, other: Optional["EvaluationResults"]) -> bool:
        if other is None:
            return True
        et = EvaluatorType.parse(self.primary)
        return et.better_than(self.primary_value, other.primary_value)


def evaluation_suite(
    specs: list[str],
    scores: Array,
    labels: Array,
    weights: Optional[Array] = None,
    group_ids_by_column: Optional[dict[str, Array]] = None,
    num_groups_by_column: Optional[dict[str, int]] = None,
) -> EvaluationResults:
    """Run several evaluators over one score set (EvaluationSuite.scala).

    Multi-device inputs are re-placed on ONE device first: callers hand in
    mesh-sharded device arrays (device-resident validation scoring), and
    the metric math below is eager sort/gather/cumsum — on a sharded array
    every such op is its own little collective program, and XLA:CPU's
    8-participant rendezvous aborts the whole process if any participant
    thread is starved for 40 s (observed under CPU oversubscription on the
    virtual mesh). Gather to host, then device_put unsharded: each array
    crosses the link exactly twice per evaluation (down + up) instead of
    once per eager op, and every subsequent metric op is single-device —
    no collectives, no rendezvous. The design win being protected —
    features never re-staged host→device — is untouched.

    Inputs that are already host NumPy or single-device jax.Arrays skip
    the round trip entirely. Multi-host (DCN) callers must hand in
    addressable or fully-replicated arrays: a sharded global array whose
    shards live on other processes cannot be gathered here (np.asarray on
    it raises), and the error below says so instead of crashing opaquely.
    """
    # local_devices, not devices: in a multi-process (DCN) run, global
    # device 0 belongs to rank 0 and device_put to a non-addressable
    # device raises on every other rank.
    target = jax.local_devices()[0]

    def _single_device(x):
        if isinstance(x, np.ndarray):
            return jax.device_put(x, target)
        if isinstance(x, jax.Array):
            dset = x.sharding.device_set
            if len(dset) == 1:
                if not x.is_fully_addressable:
                    # A DCN rank with ONE local device still hands other
                    # ranks' arrays here as single-device shardings; the
                    # device-to-device re-place below would fail opaquely
                    # deep inside XLA instead of saying what to do.
                    raise ValueError(
                        "evaluation_suite needs addressable or fully-"
                        "replicated arrays; got a single-device array "
                        "owned by another process. Multi-host callers "
                        "must all-gather (or replicate) scores/labels "
                        "before evaluating.")
                # Already single-device: skip the host round trip. Re-place
                # only if committed elsewhere (device-to-device, no host) —
                # mixed-device inputs would crash the eager metric math.
                return (x if next(iter(dset)) == target
                        else jax.device_put(x, target))
            if not (x.is_fully_addressable or x.is_fully_replicated):
                raise ValueError(
                    "evaluation_suite needs addressable or fully-replicated "
                    "arrays; got a multi-process sharded array. Multi-host "
                    "callers must all-gather (or replicate) scores/labels "
                    "before evaluating.")
        return jax.device_put(np.asarray(x), target)

    scores = _single_device(scores)
    labels = _single_device(labels)
    weights = None if weights is None else _single_device(weights)
    if group_ids_by_column:
        group_ids_by_column = {k: _single_device(v)
                               for k, v in group_ids_by_column.items()}
    metrics: dict[str, float] = {}
    for spec in specs:
        et = EvaluatorType.parse(spec)
        gids = None
        ngroups = None
        if et.group_column is not None:
            gids = (group_ids_by_column or {}).get(et.group_column)
            ngroups = (num_groups_by_column or {}).get(et.group_column)
        metrics[str(et)] = float(evaluate(et, scores, labels, weights,
                                          gids, ngroups))
    return EvaluationResults(metrics=metrics, primary=str(
        EvaluatorType.parse(specs[0])))
