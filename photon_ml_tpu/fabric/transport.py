"""Address-based replica transports (docs/SERVING.md "Multi-host fleet").

``ReplicaSupervisor`` owns the POLICY of replica lifecycle — heartbeat
deadlines, death declaration, bounded restart backoff, the amnesty
ladder. This module owns the MECHANISM: how a replica incarnation is
started, how its address is learned, how process-level liveness is
read, and how it is killed. Splitting the two lets the same supervisor
ladder babysit replicas it cannot ``Popen``:

- ``LocalTransport`` — today's subprocess spawn, verbatim: ``spawn``
  -style children, output to FILES never pipes, generation-named
  ready-file handshake, ``proc.poll()`` liveness, SIGKILL + reap.
- ``RemoteTransport`` — replicas owned by per-machine agents
  (fabric/agent.py), addressed by host:port. Spawn/kill/liveness go
  through the agent's HTTP control plane (every call a finite timeout —
  PML011); an already-running healthy replica is ADOPTED instead of
  respawned (``fabric.adopt``); a dead MACHINE fails the spawn over to
  the next machine, which is how a whole-group SIGKILL turns into a
  bounded cross-machine re-home instead of a dead fleet.

``alive()`` is deliberately tri-state: ``False`` is a positive "the
process is gone" (local ``poll()``, agent-reported exit); ``None`` is
"cannot see the process layer right now" (agent unreachable —
``fabric.heartbeat`` partition), which must NOT count as death: the
supervisor keeps trusting direct ``/healthz`` probes until the
heartbeat deadline says otherwise. A slow agent is a slow agent; only
silence PAST the deadline is a death.

``DeltaArtifactServer`` is the publish chain's wire leg: it serves a
publish directory's CRC-fenced delta artifacts over HTTP so remote
replicas can pull them (serving/publish.fetch_delta) instead of
assuming a shared filesystem.
"""

from __future__ import annotations

import http.server
import json
import logging
import os
import signal
import socketserver
import subprocess
import threading
import time
import urllib.request
from typing import Callable, Optional, Sequence

from photon_ml_tpu import faults as flt
from photon_ml_tpu import obs

logger = logging.getLogger("photon_ml_tpu.serving.fleet")


class ReplicaStartupError(RuntimeError):
    """A replica did not reach ready/healthy within its deadline."""


def _get_json(url: str, timeout_s: float) -> dict:
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        return json.loads(resp.read())


def _post_json(url: str, payload: dict, timeout_s: float) -> dict:
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode("utf-8"),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:
        return json.loads(resp.read())


class Transport:
    """The mechanism seam under ReplicaSupervisor (module docstring).

    ``handle`` is the supervisor's ReplicaHandle; transports read
    ``replica_id``/``generation`` and fill ``proc``/``machine`` — state
    transitions stay the supervisor's job.
    """

    def spawn(self, handle) -> None:
        """Start incarnation ``handle.generation`` of this replica (or
        adopt a running one). Raises ReplicaStartupError when no
        machine can take it."""
        raise NotImplementedError

    def await_ready(self, handle, deadline: float) -> tuple[str, int]:
        """Block until the incarnation is addressable; returns
        ``(host, port)``. Raises ReplicaStartupError on child exit or
        deadline (``time.monotonic()`` instant)."""
        raise NotImplementedError

    def alive(self, handle) -> Optional[bool]:
        """Process-layer liveness: True = running, False = POSITIVELY
        gone, None = cannot see the process layer (not a death)."""
        raise NotImplementedError

    def kill(self, handle) -> None:
        """SIGKILL-equivalent + reap (wedged replicas must not answer a
        stale hedge after their shards re-home)."""
        raise NotImplementedError

    def terminate(self, handle, timeout_s: float = 10.0) -> None:
        """Graceful stop (retire/shutdown), escalating to kill."""
        raise NotImplementedError

    def describe(self, handle) -> str:
        """Human-readable placement for logs ('' when local)."""
        return ""


class LocalTransport(Transport):
    """Today's subprocess spawn, verbatim (moved from ReplicaSupervisor
    — see that module's docstring for the spawn/pipe/ready-file
    rationale)."""

    def __init__(self, make_argv: Callable[[int, str], Sequence[str]],
                 workdir: str):
        self._make_argv = make_argv
        self.workdir = workdir

    def _ready_file(self, rid: int, generation: int) -> str:
        # Generation in the name: a restart must never trust the ready
        # file the DEAD incarnation wrote (its port is gone).
        return os.path.join(self.workdir,
                            f"replica-{rid}.g{generation}.ready")

    def spawn(self, handle) -> None:
        rid = handle.replica_id
        ready = self._ready_file(rid, handle.generation)
        if os.path.exists(ready):
            os.unlink(ready)
        handle.log_path = os.path.join(self.workdir, f"replica-{rid}.log")
        argv = list(self._make_argv(rid, ready))
        # The child's cwd is the workdir (its logs and ready files stay
        # together), so put the package's root on its path explicitly —
        # a dev checkout that was never pip-installed must still fleet.
        import photon_ml_tpu

        pkg_root = os.path.dirname(os.path.dirname(
            os.path.abspath(photon_ml_tpu.__file__)))
        env = dict(os.environ)
        env["PYTHONPATH"] = (pkg_root + os.pathsep + env["PYTHONPATH"]
                             if env.get("PYTHONPATH") else pkg_root)
        log_f = open(handle.log_path, "ab")
        try:
            handle.proc = subprocess.Popen(
                argv, stdout=log_f, stderr=subprocess.STDOUT,
                cwd=self.workdir, env=env)
        finally:
            log_f.close()  # the child holds its own descriptor now
        logger.info("replica %d spawned (pid %d, log %s)", rid,
                    handle.proc.pid, handle.log_path)

    def await_ready(self, handle, deadline: float) -> tuple[str, int]:
        rid = handle.replica_id
        ready = self._ready_file(rid, handle.generation)
        while time.monotonic() < deadline:
            if handle.proc.poll() is not None:
                raise ReplicaStartupError(
                    f"replica {rid} exited rc={handle.proc.returncode} "
                    f"before ready (see {handle.log_path})")
            if os.path.exists(ready):
                try:
                    with open(ready) as f:
                        info = json.load(f)
                    return info.get("host", "127.0.0.1"), int(info["port"])
                except (OSError, ValueError):
                    pass  # torn read of a mid-write file; poll again
            time.sleep(0.02)
        raise ReplicaStartupError(
            f"replica {rid} not ready before its deadline "
            f"(see {handle.log_path})")

    def alive(self, handle) -> Optional[bool]:
        if handle.proc is None:
            return None
        return handle.proc.poll() is None

    def kill(self, handle) -> None:
        if handle.proc is None or handle.proc.poll() is not None:
            return
        try:
            handle.proc.send_signal(signal.SIGKILL)
            handle.proc.wait(timeout=5.0)
        except (OSError, subprocess.TimeoutExpired):
            logger.warning("could not reap replica %d",
                           handle.replica_id)

    def terminate(self, handle, timeout_s: float = 10.0) -> None:
        if handle.proc is None or handle.proc.poll() is not None:
            return
        handle.proc.terminate()
        try:
            handle.proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            handle.proc.kill()
            try:
                handle.proc.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                logger.warning("could not reap replica %d",
                               handle.replica_id)


class RemoteTransport(Transport):
    """Replicas owned by per-machine agents (fabric/agent.py).

    ``machines`` are agent base URLs (``http://host:port``); replica
    ``rid``'s HOME machine is ``rid % len(machines)``, sticky until a
    spawn has to fail over. Every agent call carries ``timeout_s``
    (PML011) and the control-plane edges are injection seams:
    ``fabric.heartbeat`` before each liveness query, ``fabric.adopt``
    at the moment a running replica is adopted instead of respawned.
    """

    def __init__(self, machines: Sequence[str],
                 make_argv: Callable[[int, str], Sequence[str]],
                 timeout_s: float = 5.0):
        if not machines:
            raise ValueError("RemoteTransport needs >= 1 machine agent")
        self.machines = [m.rstrip("/") for m in machines]
        self._make_argv = make_argv
        self.timeout_s = float(timeout_s)
        self._home: dict[int, int] = {}
        self._lock = threading.Lock()

    def _home_of(self, rid: int) -> int:
        with self._lock:
            return self._home.get(rid, rid % len(self.machines))

    def _set_home(self, rid: int, idx: int) -> None:
        with self._lock:
            self._home[rid] = idx

    def _candidates(self, rid: int) -> list[int]:
        start = self._home_of(rid)
        n = len(self.machines)
        return [(start + i) % n for i in range(n)]

    def spawn(self, handle) -> None:
        rid = handle.replica_id
        # Agent replaces argv[0] (its own interpreter) and the
        # --ready-file value (its own workdir); everything else —
        # model args, ports, fault plans — travels verbatim.
        argv = list(self._make_argv(rid, "<agent>"))
        errors = []
        for idx in self._candidates(rid):
            agent = self.machines[idx]
            try:
                if handle.generation <= 1 and handle.restarts == 0:
                    # First contact: a healthy replica already running
                    # under this agent (a previous controller's, or one
                    # that survived its controller) is ADOPTED, not
                    # respawned — restarting a serving replica to learn
                    # its address would be a self-inflicted outage.
                    info = _get_json(f"{agent}/replica/{rid}",
                                     self.timeout_s)
                    if info.get("state") == "up":
                        flt.fire(flt.sites.FABRIC_ADOPT, index=rid)
                        mx = obs.metrics()
                        if mx is not None:
                            mx.counter("photon_fabric_adopt_total").inc()
                        self._set_home(rid, idx)
                        handle.machine = agent
                        logger.info(
                            "replica %d adopted on %s (pid %s, %s:%s)",
                            rid, agent, info.get("pid"),
                            info.get("host"), info.get("port"))
                        return
                _post_json(f"{agent}/spawn",
                           {"replica_id": rid, "argv": argv},
                           self.timeout_s)
                self._set_home(rid, idx)
                handle.machine = agent
                logger.info("replica %d spawned on %s", rid, agent)
                return
            except (OSError, ValueError) as e:
                # Machine unreachable or refused: fail over — this is
                # the cross-machine re-home leg of whole-machine death.
                errors.append(f"{agent}: {e}")
                continue
        raise ReplicaStartupError(
            f"replica {rid}: no machine could take it "
            f"({'; '.join(errors)})")

    def await_ready(self, handle, deadline: float) -> tuple[str, int]:
        rid = handle.replica_id
        agent = self.machines[self._home_of(rid)]
        while time.monotonic() < deadline:
            try:
                info = _get_json(f"{agent}/replica/{rid}",
                                 self.timeout_s)
            except (OSError, ValueError):
                time.sleep(0.05)
                continue
            state = info.get("state")
            if state == "exited":
                raise ReplicaStartupError(
                    f"replica {rid} exited rc={info.get('rc')} on "
                    f"{agent} before ready (see {info.get('log_path')})")
            if state == "up" and info.get("port"):
                return str(info.get("host", "127.0.0.1")), int(info["port"])
            time.sleep(0.05)
        raise ReplicaStartupError(
            f"replica {rid} not ready on {agent} before its deadline")

    def alive(self, handle) -> Optional[bool]:
        rid = handle.replica_id
        agent = self.machines[self._home_of(rid)]
        try:
            # Injection seam: a `partition`/`delay` spec here models the
            # agent control plane dropping out while replicas keep
            # serving — which must read as UNKNOWN, not as death.
            flt.fire(flt.sites.FABRIC_HEARTBEAT, index=rid)
            info = _get_json(f"{agent}/replica/{rid}", self.timeout_s)
        except (OSError, ValueError):
            mx = obs.metrics()
            if mx is not None:
                mx.counter("photon_fabric_heartbeat_miss_total").inc()
            return None
        state = info.get("state")
        if state in ("up", "starting"):
            return True
        if state == "exited":
            return False
        return None  # agent answered but has no record — unknown

    def kill(self, handle) -> None:
        rid = handle.replica_id
        agent = self.machines[self._home_of(rid)]
        try:
            _post_json(f"{agent}/kill", {"replica_id": rid},
                       self.timeout_s)
        except (OSError, ValueError) as e:
            # The machine is gone — its replicas died with it; there is
            # nothing left to reap on this side of the wire.
            logger.warning("could not kill replica %d via %s (%s)",
                           rid, agent, e)

    def terminate(self, handle, timeout_s: float = 10.0) -> None:
        rid = handle.replica_id
        agent = self.machines[self._home_of(rid)]
        try:
            _post_json(f"{agent}/stop",
                       {"replica_id": rid, "timeout_s": timeout_s},
                       max(self.timeout_s, timeout_s + 1.0))
        except (OSError, ValueError) as e:
            logger.warning("could not stop replica %d via %s (%s)",
                           rid, agent, e)

    def describe(self, handle) -> str:
        return self.machines[self._home_of(handle.replica_id)]


# -- publish-over-the-wire (docs/SERVING.md "Multi-host fleet") --------------


class _DeltaHandler(http.server.BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # quiet: the fleet logs routing
        logger.debug("delta server: " + fmt, *args)

    def do_GET(self):
        root = self.server.root  # type: ignore[attr-defined]
        rel = self.path.lstrip("/")
        full = os.path.realpath(os.path.join(root, rel))
        # Traversal fence: only files UNDER the publish root are
        # servable, no matter what the path spells.
        if not full.startswith(os.path.realpath(root) + os.sep):
            self.send_error(404)
            return
        try:
            with open(full, "rb") as f:
                blob = f.read()
        except OSError:
            self.send_error(404)
            return
        self.send_response(200)
        self.send_header("Content-Type", "application/octet-stream")
        self.send_header("Content-Length", str(len(blob)))
        self.end_headers()
        self.wfile.write(blob)


class _ThreadingHTTPServer(socketserver.ThreadingMixIn,
                           http.server.HTTPServer):
    daemon_threads = True
    allow_reuse_address = True


class DeltaArtifactServer:
    """Serves a publish directory's delta artifacts over HTTP (read-
    only, traversal-fenced). The CRC fence stays with the ARTIFACT:
    the fetching replica re-verifies via ``read_delta``, so a torn or
    bit-flipped transfer lands in the same ``DeltaCorrupt`` taxonomy
    as a torn shared-filesystem write."""

    def __init__(self, publish_dir: str, host: str = "127.0.0.1",
                 port: int = 0):
        self._server = _ThreadingHTTPServer((host, port), _DeltaHandler)
        self._server.root = publish_dir  # type: ignore[attr-defined]
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="photon-delta-server", daemon=True)
        self._thread.start()
        self.host, self.port = self._server.server_address[:2]

    @property
    def base_url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
