"""The per-machine replica agent (docs/SERVING.md "Multi-host fleet").

One agent process per "machine" owns that machine's scoring replicas:
it spawns them (same spawn-not-fork, logs-to-files, generation-named
ready-file discipline as the local supervisor), answers the control
plane ``RemoteTransport`` drives (spawn / liveness / kill / stop), and
— deliberately — keeps its replicas in its OWN process group: the
agent is started as a session leader, children inherit the group, so a
whole-machine death is one ``killpg`` in a drill and one power failure
in production. The fleet's supervisor never sees a pid, only states.

Control plane (every response JSON; the transport side carries the
timeouts):

- ``GET /healthz``        — agent liveness + replica state map
- ``GET /replica/<rid>``  — one replica: ``absent`` / ``starting`` /
  ``up`` (address known) / ``exited`` (rc)
- ``POST /spawn``         — ``{"replica_id", "argv"}``; the agent
  substitutes its own interpreter for ``argv[0]`` and its own workdir
  path for the ``--ready-file`` value, then spawns
- ``POST /kill``          — SIGKILL + reap
- ``POST /stop``          — graceful terminate, escalating

The agent itself follows the replica ready-file contract
(``--ready-file`` written atomically after bind), so a harness can
await it exactly like a replica.
"""

from __future__ import annotations

import argparse
import http.server
import json
import logging
import os
import signal
import socketserver
import subprocess
import sys
import threading
from typing import Optional

logger = logging.getLogger("photon_ml_tpu.fabric.agent")


class _Replica:
    """One spawned replica's bookkeeping (guarded by the agent lock for
    map access; the Popen object is thread-safe for poll/signal)."""

    def __init__(self, rid: int):
        self.rid = rid
        self.proc: Optional[subprocess.Popen] = None
        self.generation = 0
        self.ready_file = ""
        self.log_path = ""


class MachineAgent:
    def __init__(self, workdir: str, machine: str = "m0"):
        self.workdir = workdir
        self.machine = machine
        self._replicas: dict[int, _Replica] = {}
        self._lock = threading.Lock()
        os.makedirs(workdir, exist_ok=True)

    # -- state views ---------------------------------------------------------

    def _rec(self, rid: int) -> _Replica:
        with self._lock:
            rec = self._replicas.get(rid)
            if rec is None:
                rec = self._replicas[rid] = _Replica(rid)
            return rec

    def replica_info(self, rid: int) -> dict:
        with self._lock:
            rec = self._replicas.get(rid)
        if rec is None or rec.proc is None:
            return {"state": "absent"}
        rc = rec.proc.poll()
        if rc is not None:
            return {"state": "exited", "rc": rc, "pid": rec.proc.pid,
                    "log_path": rec.log_path,
                    "generation": rec.generation}
        info = {"state": "starting", "pid": rec.proc.pid,
                "log_path": rec.log_path, "generation": rec.generation}
        try:
            with open(rec.ready_file) as f:
                ready = json.load(f)
            info.update({"state": "up",
                         "host": ready.get("host", "127.0.0.1"),
                         "port": int(ready["port"])})
        except (OSError, ValueError, KeyError):
            pass  # not ready yet (or torn mid-write) — still starting
        return info

    def healthz(self) -> dict:
        with self._lock:
            rids = list(self._replicas)
        return {"status": "ok", "machine": self.machine,
                "pid": os.getpid(),
                "replicas": {str(r): self.replica_info(r)["state"]
                             for r in rids}}

    # -- lifecycle -----------------------------------------------------------

    def spawn(self, rid: int, argv: list[str]) -> dict:
        rec = self._rec(rid)
        if rec.proc is not None and rec.proc.poll() is None:
            # Respawn over a live incarnation: kill it first — two
            # processes racing one replica id would split the shard.
            self._kill_proc(rec.proc)
        rec.generation += 1
        rec.ready_file = os.path.join(
            self.workdir, f"replica-{rid}.g{rec.generation}.ready")
        if os.path.exists(rec.ready_file):
            os.unlink(rec.ready_file)
        rec.log_path = os.path.join(self.workdir, f"replica-{rid}.log")
        argv = list(argv)
        argv[0] = sys.executable  # the controller's interpreter path
        for i, a in enumerate(argv):  # ... and its ready-file path ...
            if a == "--ready-file" and i + 1 < len(argv):
                argv[i + 1] = rec.ready_file  # ... are both ours now
        import photon_ml_tpu

        pkg_root = os.path.dirname(os.path.dirname(
            os.path.abspath(photon_ml_tpu.__file__)))
        env = dict(os.environ)
        env["PYTHONPATH"] = (pkg_root + os.pathsep + env["PYTHONPATH"]
                             if env.get("PYTHONPATH") else pkg_root)
        log_f = open(rec.log_path, "ab")
        try:
            # No start_new_session: the replica stays in the AGENT's
            # process group — whole-machine death is one killpg.
            rec.proc = subprocess.Popen(
                argv, stdout=log_f, stderr=subprocess.STDOUT,
                cwd=self.workdir, env=env)
        finally:
            log_f.close()
        logger.info("machine %s: replica %d spawned (pid %d, gen %d)",
                    self.machine, rid, rec.proc.pid, rec.generation)
        return {"ok": True, "generation": rec.generation,
                "pid": rec.proc.pid}

    @staticmethod
    def _kill_proc(proc: subprocess.Popen) -> None:
        try:
            proc.send_signal(signal.SIGKILL)
            proc.wait(timeout=5.0)
        except (OSError, subprocess.TimeoutExpired):
            logger.warning("could not reap pid %d", proc.pid)

    def kill(self, rid: int) -> dict:
        with self._lock:
            rec = self._replicas.get(rid)
        if rec is not None and rec.proc is not None \
                and rec.proc.poll() is None:
            self._kill_proc(rec.proc)
        return {"ok": True}

    def stop(self, rid: int, timeout_s: float = 10.0) -> dict:
        with self._lock:
            rec = self._replicas.get(rid)
        if rec is not None and rec.proc is not None \
                and rec.proc.poll() is None:
            rec.proc.terminate()
            try:
                rec.proc.wait(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                self._kill_proc(rec.proc)
        return {"ok": True}

    def shutdown(self) -> None:
        with self._lock:
            recs = list(self._replicas.values())
        for rec in recs:
            if rec.proc is not None and rec.proc.poll() is None:
                self.stop(rec.rid, timeout_s=5.0)


class _AgentHandler(http.server.BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        logger.debug("agent: " + fmt, *args)

    def _json(self, code: int, body: dict) -> None:
        blob = json.dumps(body).encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(blob)))
        self.end_headers()
        self.wfile.write(blob)

    def do_GET(self):
        agent: MachineAgent = self.server.agent  # type: ignore[attr-defined]
        if self.path == "/healthz":
            self._json(200, agent.healthz())
        elif self.path.startswith("/replica/"):
            try:
                rid = int(self.path.rsplit("/", 1)[-1])
            except ValueError:
                self._json(400, {"error": "bad replica id"})
                return
            self._json(200, agent.replica_info(rid))
        else:
            self._json(404, {"error": f"no route {self.path}"})

    def do_POST(self):
        agent: MachineAgent = self.server.agent  # type: ignore[attr-defined]
        try:
            length = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(length) or b"{}")
        except (ValueError, OSError) as e:
            self._json(400, {"error": f"malformed request: {e}"})
            return
        try:
            if self.path == "/spawn":
                out = agent.spawn(int(payload["replica_id"]),
                                  list(payload["argv"]))
            elif self.path == "/kill":
                out = agent.kill(int(payload["replica_id"]))
            elif self.path == "/stop":
                out = agent.stop(int(payload["replica_id"]),
                                 float(payload.get("timeout_s", 10.0)))
            else:
                self._json(404, {"error": f"no route {self.path}"})
                return
        except (KeyError, TypeError, ValueError) as e:
            self._json(400, {"error": f"malformed request: {e}"})
            return
        self._json(200, out)


class _ThreadingHTTPServer(socketserver.ThreadingMixIn,
                           http.server.HTTPServer):
    daemon_threads = True
    allow_reuse_address = True


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="photon-fabric-agent", description=__doc__.splitlines()[0])
    p.add_argument("--workdir", required=True,
                   help="replica logs + ready files live here")
    p.add_argument("--machine", default="m0",
                   help="machine name reported in logs and /healthz")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0,
                   help="0 = ephemeral (read it from the ready file)")
    p.add_argument("--ready-file",
                   help="write {pid, host, port} here once bound (the "
                        "replica ready-file contract, reused)")
    return p


def main(argv=None) -> int:
    from photon_ml_tpu.utils.logging import setup_logging

    setup_logging()
    args = build_parser().parse_args(argv)
    agent = MachineAgent(args.workdir, machine=args.machine)
    server = _ThreadingHTTPServer((args.host, args.port), _AgentHandler)
    server.agent = agent  # type: ignore[attr-defined]
    host, port = server.server_address[:2]
    if args.ready_file:
        tmp = args.ready_file + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"pid": os.getpid(), "host": host, "port": port}, f)
        os.replace(tmp, args.ready_file)
    logger.info("machine agent %s up at http://%s:%d (workdir %s)",
                args.machine, host, port, args.workdir)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        agent.shutdown()
        server.server_close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
