"""Per-process fabric registration (the ``faults.install`` pattern).

The streamed coordinate is built deep inside the GAME engine
(``game/descent.py`` → ``coordinates/streaming_fixed.py``); threading a
transport handle through every constructor would churn the whole config
surface for one process-wide fact. Instead the CLI arms the process
("this rank participates in a fabric") and the two consumers read it:

- ``StreamingSparseFixedEffectCoordinate`` wraps its chunk stream in a
  ``FabricChunkStream`` when a fabric is active;
- ``game/checkpoint.StreamingStateStore`` gates writes on the PRIMARY
  rank (fabric rank 0), so W hosts never race one checkpoint directory.

Install ``None`` to disarm (tests use the same fixture discipline as
``faults.install``).
"""

from __future__ import annotations

import os
import threading
from typing import Optional, TYPE_CHECKING

if TYPE_CHECKING:  # pragma: no cover - import cycle guard, typing only
    from photon_ml_tpu.fabric.collective import FabricComm

_lock = threading.Lock()
_active: Optional["FabricComm"] = None


def install(comm: Optional["FabricComm"]) -> None:
    """Arm (or disarm, with ``None``) the process-wide fabric."""
    global _active
    with _lock:
        _active = comm


def active() -> Optional["FabricComm"]:
    """The armed fabric, or ``None`` (single-host: every consumer's
    fast path)."""
    return _active


def rank() -> int:
    """This process's fabric rank (0 when no fabric is armed — the
    single-host process IS the primary)."""
    comm = _active
    return comm.rank if comm is not None else 0


def comm_from_env() -> Optional["FabricComm"]:
    """Build a ``FabricComm`` from the launcher environment, or ``None``
    when no fabric is configured. The contract mirrors JAX's own
    coordinator discovery (``JAX_COORDINATOR_ADDRESS`` et al.):

    - ``PHOTON_FABRIC_WORLD``       — host count W (absent/“1” = no fabric)
    - ``PHOTON_FABRIC_RANK``        — this host's rank in [0, W)
    - ``PHOTON_FABRIC_COORDINATOR`` — ``host:port`` of rank 0's data
      plane (rank 0 BINDS this port; every rank dials it)
    - ``PHOTON_FABRIC_TIMEOUT_S``   — optional per-round socket budget
    """
    world = int(os.environ.get("PHOTON_FABRIC_WORLD", "1"))
    if world <= 1:
        return None
    from photon_ml_tpu.fabric.collective import FabricComm

    fabric_rank = int(os.environ["PHOTON_FABRIC_RANK"])
    host, _, port = os.environ["PHOTON_FABRIC_COORDINATOR"].rpartition(":")
    timeout_s = float(os.environ.get("PHOTON_FABRIC_TIMEOUT_S", "30"))
    return FabricComm(fabric_rank, world,
                      coordinator=(host or "127.0.0.1", int(port)),
                      timeout_s=timeout_s)
