"""``FabricComm``: the host-level DCN collective under the ICI psum.

Snap ML's hierarchy (PAPERS.md) and the reference's ``treeAggregate``
both reduce to the same shape: a fast intra-node level under ONE
cross-node aggregation seam. Intra-host that seam is the compiled
``psum`` in ``ops/streaming_sparse._merge_fn``; THIS module is the
cross-host level — and because XLA's multiprocess collectives are not
available on the CPU backend (the CI box, and any ``jax.distributed``
CPU process group), the cross-host allreduce runs at the HOST level
over plain TCP, where it can also be partitioned, delayed, and killed
by the fault injector like any other edge in the system.

Topology: rank 0 hosts the coordinator (one connection per request —
no long-lived streams to half-close), every rank (rank 0 included, via
loopback, so all ranks share one code path) contributes its host
partial and blocks for the reduced result. Contributions are stored
idempotently per ``(tag, seq, rank)`` — a retry after a torn send
overwrites, never double-counts — and the reduction is computed in
RANK ORDER, so the result is deterministic and byte-identical on every
rank. World size 1 returns the contribution unchanged (bit-parity with
the single-host path, asserted by the bench gate).

Failure ladder (the chunk-transfer ladder of
``ops/streaming_sparse._transfer``, extended to the DCN edge):

- every socket operation carries a finite timeout (PML011);
- a dropped/timed-out round retries with bounded DETERMINISTIC backoff
  (``retry_backoff_s * attempt`` — drills must replay exactly), firing
  ``fabric.dcn_allreduce`` per attempt;
- exhaustion raises ``FabricPartitioned`` — loud and defined, because a
  silently dropped partial CHANGES THE OBJECTIVE;
- a rank arriving with the wrong sequence number for a tag, or a
  per-iteration digest that disagrees across ranks, raises
  ``RankDivergence`` on every rank: divergence is detected, not
  assumed away.
"""

from __future__ import annotations

import json
import logging
import socket
import socketserver
import threading
import time
from typing import Optional

import numpy as np

from photon_ml_tpu import faults as flt
from photon_ml_tpu import obs

logger = logging.getLogger("photon_ml_tpu.fabric")

# The DCN edge's retry ladder: bounded, deterministic (no jitter — a
# drill must replay exactly), then loud. Mirrors TRANSFER_MAX_RETRIES /
# TRANSFER_RETRY_BACKOFF_S on the host→device edge.
DCN_MAX_RETRIES = 2
DCN_RETRY_BACKOFF_S = 0.05

_HEADER_LIMIT = 1 << 16  # a header line larger than 64 KiB is a protocol bug


class FabricError(RuntimeError):
    """Base class for fabric transport failures."""


class FabricPartitioned(FabricError):
    """A cross-host round exhausted its retry ladder — the DCN edge is
    (or is injected to be) partitioned. Loud by design: a silently
    dropped partial changes the objective."""


class RankDivergence(FabricError):
    """Ranks disagree — wrong sequence number for a collective tag, or
    mismatched per-iteration digests. The run is wrong on at least one
    host; continuing would average two different optimizations."""


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        part = sock.recv(min(1 << 20, n - len(buf)))
        if not part:
            raise ConnectionError(
                f"peer closed mid-payload ({len(buf)}/{n} bytes)")
        buf += part
    return bytes(buf)


def _recv_header(sock: socket.socket) -> dict:
    buf = bytearray()
    while not buf.endswith(b"\n"):
        if len(buf) > _HEADER_LIMIT:
            raise ConnectionError("oversized fabric header")
        part = sock.recv(1)
        if not part:
            raise ConnectionError("peer closed mid-header")
        buf += part
    return json.loads(buf.decode("utf-8"))


def _send(sock: socket.socket, header: dict, payload: bytes = b"") -> None:
    sock.sendall(json.dumps(header).encode("utf-8") + b"\n" + payload)


class _Round:
    """One in-flight collective round for a tag (coordinator state)."""

    def __init__(self, seq: int):
        self.seq = seq
        self.contrib: dict[int, object] = {}  # rank -> payload (idempotent)
        self.result: Optional[object] = None
        self.error: Optional[str] = None


class _CoordinatorState:
    """Rank-0 reduction state: per-tag open round + last completed
    result (served to retries whose response was lost)."""

    def __init__(self, world: int):
        self.world = world
        self.cond = threading.Condition()
        self.open: dict[str, _Round] = {}
        self.done_seq: dict[str, int] = {}
        self.done_result: dict[str, object] = {}


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):  # noqa: D102 - socketserver contract
        st: _CoordinatorState = self.server.state  # type: ignore[attr-defined]
        sock = self.request
        sock.settimeout(self.server.timeout_s)  # type: ignore[attr-defined]
        try:
            hdr = _recv_header(sock)
            payload = _recv_exact(sock, int(hdr.get("nbytes", 0)))
            self._serve(st, sock, hdr, payload)
        except (OSError, ValueError, KeyError) as e:
            logger.debug("fabric coordinator: dropped request (%s)", e)

    def _serve(self, st: _CoordinatorState, sock, hdr: dict,
               payload: bytes) -> None:
        rank, op = int(hdr["rank"]), str(hdr["op"])
        tag, seq = str(hdr["tag"]), int(hdr["seq"])
        deadline = time.monotonic() + self.server.timeout_s  # type: ignore[attr-defined]
        with st.cond:
            done = st.done_seq.get(tag, 0)
            if seq == done:
                # Retry of a COMPLETED round whose response was lost:
                # serve the cached result — idempotent, never re-reduced.
                self._reply(sock, op, hdr, st.done_result[tag])
                return
            if seq != done + 1:
                # This rank is on a different iteration than the fabric:
                # poison the open round so every waiter learns too.
                msg = (f"rank {rank} sent seq {seq} for tag {tag!r} "
                       f"(fabric is at {done})")
                rnd = st.open.get(tag)
                if rnd is not None:
                    rnd.error = msg
                    st.cond.notify_all()
                _send(sock, {"ok": False, "kind": "divergence",
                             "error": msg})
                return
            rnd = st.open.get(tag)
            if rnd is None or rnd.seq != seq:
                rnd = _Round(seq)
                st.open[tag] = rnd
            rnd.contrib[rank] = (hdr, payload)  # overwrite = retry-safe
            if len(rnd.contrib) == st.world and rnd.result is None:
                rnd.result = _reduce(op, rnd.contrib, st.world)
                st.done_seq[tag] = seq
                st.done_result[tag] = rnd.result
                del st.open[tag]
                st.cond.notify_all()
            while rnd.result is None and rnd.error is None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    # Finite wait (PML011): an absent peer turns into a
                    # timeout the CLIENT ladder retries, not a hang.
                    _send(sock, {"ok": False, "kind": "timeout",
                                 "error": f"round {tag}:{seq} incomplete "
                                          f"({len(rnd.contrib)}/{st.world} "
                                          f"ranks)"})
                    return
                st.cond.wait(timeout=remaining)
            if rnd.error is not None:
                _send(sock, {"ok": False, "kind": "divergence",
                             "error": rnd.error})
                return
            self._reply(sock, op, hdr, rnd.result)

    @staticmethod
    def _reply(sock, op: str, hdr: dict, result) -> None:
        if op == "digest":
            blob = json.dumps(result).encode("utf-8")
            _send(sock, {"ok": True, "nbytes": len(blob)}, blob)
        else:
            arr = result
            _send(sock, {"ok": True, "nbytes": arr.nbytes,
                         "shape": list(arr.shape)},
                  arr.tobytes())


def _reduce(op: str, contrib: dict, world: int):
    """Deterministic rank-order reduction of a complete round."""
    if op == "digest":
        digests = {r: contrib[r][1].decode("utf-8") for r in range(world)}
        return {"digests": digests,
                "match": len(set(digests.values())) == 1}
    arrays = []
    for r in range(world):
        hdr, payload = contrib[r]
        arrays.append(np.frombuffer(payload, dtype=np.float64)
                      .reshape(hdr["shape"]))
    if op == "allgather":
        return np.ascontiguousarray(np.concatenate(arrays, axis=0))
    out = arrays[0].copy()
    for r in range(1, world):  # rank order: byte-identical on every rank
        out += arrays[r]
    return out


class _CoordinatorServer(socketserver.ThreadingTCPServer):
    daemon_threads = True
    allow_reuse_address = True


class FabricComm:
    """One rank's handle on the fabric (coordinator hosted by rank 0).

    ``world == 1`` short-circuits every collective locally — the
    single-host path pays zero sockets and stays bit-identical.
    """

    def __init__(self, rank: int, world: int,
                 coordinator: tuple[str, int] = ("127.0.0.1", 0),
                 timeout_s: float = 10.0,
                 max_retries: int = DCN_MAX_RETRIES,
                 retry_backoff_s: float = DCN_RETRY_BACKOFF_S):
        if not 0 <= rank < world:
            raise ValueError(f"rank {rank} outside world {world}")
        self.rank = int(rank)
        self.world = int(world)
        self.timeout_s = float(timeout_s)
        self.max_retries = int(max_retries)
        self.retry_backoff_s = float(retry_backoff_s)
        self._seq: dict[str, int] = {}
        self._seq_lock = threading.Lock()
        self._server: Optional[_CoordinatorServer] = None
        self._server_thread: Optional[threading.Thread] = None
        if self.world > 1 and self.rank == 0:
            self._server = _CoordinatorServer(coordinator, _Handler)
            self._server.state = _CoordinatorState(self.world)  # type: ignore[attr-defined]
            self._server.timeout_s = self.timeout_s  # type: ignore[attr-defined]
            self._server_thread = threading.Thread(
                target=self._server.serve_forever,
                name="photon-fabric-coordinator", daemon=True)
            self._server_thread.start()
            coordinator = self._server.server_address[:2]
        self.coordinator = (str(coordinator[0]), int(coordinator[1]))
        mx = obs.metrics()
        if mx is not None:
            mx.gauge("photon_fabric_world_size").set(float(self.world))

    # -- collectives ---------------------------------------------------------

    def allreduce(self, x, tag: str) -> np.ndarray:
        """Sum ``x`` across ranks (float64, rank-order reduction; the
        ONE cross-host aggregation per streamed pass)."""
        arr = np.ascontiguousarray(np.asarray(x, dtype=np.float64))
        if self.world == 1:
            return arr
        return np.asarray(self._round("allreduce", tag, arr)) \
            .reshape(arr.shape)

    def allgather(self, x, tag: str) -> np.ndarray:
        """Concatenate ``x`` across ranks along axis 0 in rank order
        (the margins path: each rank's row slice → global row order)."""
        arr = np.ascontiguousarray(np.asarray(x, dtype=np.float64))
        if self.world == 1:
            return arr
        return np.asarray(self._round("allgather", tag, arr))

    def digest_check(self, tag: str, digest: str) -> dict:
        """Exchange per-iteration digests; every rank gets the full
        rank→digest map. A mismatch raises ``RankDivergence`` on EVERY
        rank (after counting it) — divergence is detected, not assumed."""
        if self.world == 1:
            return {"digests": {"0": digest}, "match": True}
        out = self._round("digest", tag, digest.encode("utf-8"))
        if not out["match"]:
            mx = obs.metrics()
            if mx is not None:
                mx.counter("photon_fabric_digest_mismatch_total").inc()
            raise RankDivergence(
                f"rank digests diverged for {tag!r}: {out['digests']}")
        return out

    # -- the DCN retry ladder ------------------------------------------------

    def _round(self, op: str, tag: str, payload) -> object:
        with self._seq_lock:
            seq = self._seq.get(tag, 0) + 1
        t0 = time.perf_counter()
        mx = obs.metrics()
        last_err: Optional[Exception] = None
        for attempt in range(self.max_retries + 1):
            try:
                # Injection seam: a `partition` spec here IS the DCN
                # edge dropping this round (index = sequence number, so
                # plans can target one iteration deterministically).
                flt.fire(flt.sites.FABRIC_DCN_ALLREDUCE, index=seq)
                result = self._exchange(op, tag, seq, payload)
            except OSError as e:  # InjectedPartition is a ConnectionError
                last_err = e
                if attempt < self.max_retries:
                    if mx is not None:
                        mx.counter("photon_fabric_retries_total").inc()
                    logger.warning(
                        "fabric %s %s:%d attempt %d/%d failed (%s); "
                        "retrying", op, tag, seq, attempt + 1,
                        self.max_retries + 1, e)
                    # Deterministic backoff — drills must replay exactly.
                    time.sleep(self.retry_backoff_s * (attempt + 1))
                continue
            with self._seq_lock:
                self._seq[tag] = seq
            if mx is not None:
                mx.counter("photon_fabric_allreduce_total", op=op).inc()
                mx.counter("photon_fabric_allreduce_seconds_total").inc(
                    time.perf_counter() - t0)
            return result
        raise FabricPartitioned(
            f"fabric {op} {tag!r} seq {seq} failed after "
            f"{self.max_retries + 1} attempts "
            f"(coordinator {self.coordinator[0]}:{self.coordinator[1]}): "
            f"{last_err}") from last_err

    def _exchange(self, op: str, tag: str, seq: int, payload) -> object:
        if op == "digest":
            body, shape = payload, []
        else:
            body, shape = payload.tobytes(), list(payload.shape)
        with socket.create_connection(
                self.coordinator, timeout=self.timeout_s) as sock:
            _send(sock, {"rank": self.rank, "op": op, "tag": tag,
                         "seq": seq, "nbytes": len(body),
                         "shape": shape}, body)
            hdr = _recv_header(sock)
            if not hdr.get("ok"):
                if hdr.get("kind") == "divergence":
                    raise RankDivergence(hdr.get("error", "divergence"))
                raise ConnectionError(hdr.get("error", "fabric timeout"))
            blob = _recv_exact(sock, int(hdr["nbytes"]))
        mx = obs.metrics()
        if mx is not None:
            mx.counter("photon_fabric_bytes_total").inc(
                len(body) + len(blob))
        if op == "digest":
            return json.loads(blob.decode("utf-8"))
        return np.frombuffer(blob, dtype=np.float64) \
            .reshape(hdr.get("shape", [-1]))

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        if self._server_thread is not None:
            self._server_thread.join(timeout=5.0)
            self._server_thread = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
