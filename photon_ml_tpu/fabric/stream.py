"""``FabricChunkStream``: the streamed FE pass sharded across hosts.

Same duck type as ``ops/streaming_sparse.ShardedChunkStream`` — the
streaming coordinate swaps one in without touching the driver loop.
The hierarchy is exactly Snap ML's (PAPERS.md): chunk ranges partition
over HOSTS by the same pure ``shard_chunk_ranges`` function that
partitions them over devices (so the elastic-resume contract — ranges
re-derive from ``(num_chunks, W′)`` at construction — holds across
hosts too), each host streams its own range through its LOCAL mesh
(per-host ICI psum via the existing ``_merge_fn``), and the host
partials meet in ONE cross-host ``FabricComm.allreduce`` per pass,
value and gradient packed into a single (1+d,) vector so the DCN edge
is crossed once, not twice.

World size 1 never touches a socket and is bit-identical to the
wrapped local stream (the bench gate's D=1 parity line).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.fabric.collective import FabricComm
from photon_ml_tpu.ops import streaming_sparse as ss


def _slice_chunked(chunked: ss.ChunkedHybrid, lo: int,
                   hi: int) -> ss.ChunkedHybrid:
    """This host's chunk range as a ChunkedHybrid view (shared chunk
    tuples — no copy). ``num_rows`` is the REAL row count of the slice:
    interior slices are fully dense, only the slice holding the global
    final chunk owns the padded tail."""
    cr = chunked.chunk_rows
    real = min(chunked.num_rows, hi * cr) - lo * cr
    return dataclasses.replace(chunked, chunks=chunked.chunks[lo:hi],
                               num_rows=max(0, real))


class FabricChunkStream:
    """Host-sharded chunk stream over a ``FabricComm`` world.

    ``mesh`` is this HOST's local mesh (or ``None`` for the sequential
    single-chip stream) — cross-host traffic never rides XLA, so the
    mesh must span local devices only (``parallel/mesh.make_mesh``
    with ``local=True`` under ``jax.distributed``).
    """

    def __init__(self, chunked: ss.ChunkedHybrid, comm: FabricComm,
                 mesh=None, prefetch_depth: int = 2,
                 pin_device_chunks: int = 0):
        self.chunked = chunked
        self.comm = comm
        self.mesh = mesh
        ranges = ss.shard_chunk_ranges(chunked.num_chunks, comm.world)
        self._lo, self._hi = ranges[comm.rank]
        self._row_lo = self._lo * chunked.chunk_rows
        self._row_hi = self._hi * chunked.chunk_rows
        self._local = _slice_chunked(chunked, self._lo, self._hi)
        self._dim = chunked.dim
        if self._hi == self._lo:
            # More hosts than chunks: this rank contributes zeros (the
            # balanced ranges make that rare; the allreduce still needs
            # every rank's round-trip so seq stays aligned).
            self._stream = None
            self._pinned = ()
        elif mesh is not None:
            self._stream = ss.ShardedChunkStream(
                self._local, mesh, prefetch_depth=prefetch_depth,
                pin_device_chunks=pin_device_chunks)
            self._pinned = ()
        else:
            self._stream = None
            self._pinned = ss.pin_chunks(self._local, pin_device_chunks)
        self._prefetch_depth = prefetch_depth

    @property
    def num_devices(self) -> int:
        """LOCAL device fan-out (the checkpoint environment's D); the
        host fan-out W rides beside it as ``fabric_world``."""
        if self._stream is not None:
            return self._stream.num_devices
        return 1

    def _local_offsets(self, offsets):
        return offsets[self._row_lo:self._row_hi]

    def value_and_gradient(self, loss):
        if self._stream is not None:
            local_vg = self._stream.value_and_gradient(loss)
        elif self._hi > self._lo:
            local_vg = ss.make_value_and_gradient(
                loss, self._local, prefetch_depth=self._prefetch_depth,
                pinned=self._pinned)
        else:
            local_vg = None

        def vg(w, offsets):
            if local_vg is not None:
                value, grad = local_vg(w, self._local_offsets(offsets))
                packed = np.concatenate(
                    [np.asarray(value, np.float64).reshape(1),
                     np.asarray(grad, np.float64)])
            else:
                packed = np.zeros((1 + self._dim,), np.float64)
            # ONE cross-host aggregation per pass: value and gradient
            # share the round, so a partition costs one ladder, not two.
            out = self.comm.allreduce(packed, tag="vg")
            return (jnp.asarray(out[0], jnp.float32),
                    jnp.asarray(out[1:], jnp.float32))

        return vg

    def value_only(self, loss):
        if self._stream is not None:
            local_v = self._stream.value_only(loss)
        elif self._hi > self._lo:
            local_v = ss.make_value_only(
                loss, self._local, prefetch_depth=self._prefetch_depth,
                pinned=self._pinned)
        else:
            local_v = None

        def v(w, offsets):
            if local_v is not None:
                value = np.asarray(
                    local_v(w, self._local_offsets(offsets)),
                    np.float64).reshape(1)
            else:
                value = np.zeros((1,), np.float64)
            out = self.comm.allreduce(value, tag="val")
            return jnp.asarray(out[0], jnp.float32)

        return v

    def margins(self, w, offsets: Optional[object] = None) -> jnp.ndarray:
        """(num_rows,) margins in GLOBAL row order: each host computes
        its row slice, rank-order allgather reassembles (f64 on the
        wire — the f32 margins survive the round-trip bit-exactly)."""
        if self._stream is not None:
            local = np.asarray(self._stream.margins(w), np.float64)
        elif self._hi > self._lo:
            local = np.asarray(
                ss.margins_chunked(self._local, w,
                                   prefetch_depth=self._prefetch_depth,
                                   pinned=self._pinned), np.float64)
        else:
            local = np.zeros((0,), np.float64)
        out = self.comm.allgather(local, tag="margins")
        return jnp.asarray(out[: self.chunked.num_rows], jnp.float32)
