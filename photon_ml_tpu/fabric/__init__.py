"""photon-fabric: the multi-host seam (docs/STREAMING.md "Multi-host
streaming", docs/SERVING.md "Multi-host fleet").

Everything through PR 18 spans chips on ONE host; this package makes
training and serving span machines, and makes every cross-machine edge
survive the established fault kinds (``partition``, ``delay``,
``replica_kill``) plus whole-host death:

- ``collective.py`` — ``FabricComm``, the host-level DCN collective:
  per-host ICI psum partials meet in ONE cross-host allreduce with the
  chunk-transfer retry ladder extended to the DCN edge (bounded
  deterministic backoff, then a loud ``FabricPartitioned`` — a silently
  dropped partial changes the objective), plus per-iteration cross-rank
  digest rows so rank divergence is DETECTED (``RankDivergence``), not
  assumed away.
- ``stream.py`` — ``FabricChunkStream``, the streamed fixed-effect pass
  sharded rank-wise over hosts (same duck type as
  ``ops/streaming_sparse.ShardedChunkStream``).
- ``runtime.py`` — the per-process fabric registration the CLI arms
  (``game_train --fabric``), read by the streaming coordinate and the
  checkpoint store's primary-rank gate.
- ``transport.py`` — the address-based replica transport behind
  ``ReplicaSupervisor`` (``LocalTransport`` = the original subprocess
  spawn, verbatim; ``RemoteTransport`` = probe/adopt/restart replicas
  on machine agents by host:port), plus the HTTP delta artifact server
  remote replicas pull CRC-fenced publish deltas from.
- ``agent.py`` — the per-machine agent process that owns a machine's
  replicas (one process group: whole-group SIGKILL == whole-machine
  death in drills).
"""

from photon_ml_tpu.fabric.collective import (FabricComm, FabricError,
                                             FabricPartitioned,
                                             RankDivergence)
from photon_ml_tpu.fabric.runtime import active, install

__all__ = [
    "FabricComm",
    "FabricError",
    "FabricPartitioned",
    "RankDivergence",
    "active",
    "install",
]
