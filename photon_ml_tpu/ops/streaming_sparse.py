"""Row-streamed sparse GLM aggregates: the Criteo row axis on one chip.

Reference parity: photon-api ``DistributedGLMLossFunction`` computes each
value/gradient as one Spark pass over RDD partitions (``treeAggregate``) —
the n axis never has to fit on any single executor. This module is the
TPU-native equivalent: the example rows live on HOST in fixed-size chunks,
and every objective evaluation streams them through the chip with
double-buffered host→device prefetch, accumulating ``(value, gradient)``
in f32 on device. HBM holds at most ``prefetch_depth`` chunks plus the
accumulators, so n is bounded by host RAM (or disk, via the chunk
iterator), not by the 16 GB of one chip.

**Chunk layout: hot-dense block + cold ELL.** Each chunk densifies its
top-``num_hot`` columns into an (n, H) MXU block (the Zipf head is the
bulk of the nonzeros) and keeps the remaining entries in ELL with their
ORIGINAL column ids (hot entries become inert pad slots). Two hard
lessons at n=100M shape this (both measured on v5e, both aborting
COMPILATION with HBM overflows before any data moved):

  * gathers/scatters must be per-ELL-slot 1-D ops — an index operand
    shaped (n, k) or (n, k, 1) is materialized in a (8, 128)-tiled
    layout whose minor dims pad to 128 (a 51 GB copy at n=100M);
  * no flat concatenated streams — XLA lays a 128M-element 1-D
    intermediate out as (64M, 2) tiled, padding 2→128 (a 33 GB copy).
    This is why the device-resident hybrid's contiguous-class layout
    (ops/hybrid_sparse.py), which wins 6-8× at bench scale, is NOT used
    here: its per-class flat gather/scatter streams cannot compile at
    streamed-chunk scale, and the stream is host→device transfer-bound
    anyway, so the cold formulation's compute rate is immaterial.

Every chunk has identical array shapes by construction ((n, H), (H,),
(n, k)), so the WHOLE stream shares ONE compiled program — per-structure
compiles are multi-minute remote operations in this environment.
"""

from __future__ import annotations

import dataclasses
import gc
from typing import Callable, Iterable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.ops.hybrid_sparse import _hot_matvec, _hot_rmatvec
from photon_ml_tpu.ops.losses import PointwiseLoss

Array = jax.Array


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class CanonicalChunk:
    """One chunk: hot-dense block + cold ELL (leaves may be host numpy —
    device placement happens at stream time)."""

    X_hot: Array  # (n, H) — the chunk's top-H columns, densified
    hot_cols: Array  # (H,) int32 original column ids (pad == d)
    cold_cols: Array  # (n, k) int32 original ids; hot/pad entries == d
    cold_vals: Array  # (n, k); hot/pad entries == 0
    labels: Array  # (n,)
    weights: Array  # (n,); 0 marks pad rows of a short final chunk
    offsets: Array  # (n,)
    num_features: int = dataclasses.field(metadata=dict(static=True))

    @property
    def num_rows(self) -> int:
        return self.labels.shape[0]

    @property
    def num_hot(self) -> int:
        return self.X_hot.shape[1]

    def structure(self):
        """Shape signature — equal signatures share one compiled program.
        Identical across chunks by construction; kept for the invariant
        test."""
        return (self.X_hot.shape, self.cold_cols.shape,
                self.num_features)


@dataclasses.dataclass(frozen=True)
class ChunkedHybrid:
    """Host-resident chunked layout of one logical (n, d) batch.

    Equal row counts per chunk (short final chunk padded with weight-0
    rows — inert in every aggregate; their margins are dropped by
    ``margins_chunked``). ``num_rows`` is the REAL row count.
    """

    chunks: tuple[CanonicalChunk, ...]
    num_rows: int
    chunk_rows: int

    @property
    def dim(self) -> int:
        return self.chunks[0].num_features

    @property
    def num_chunks(self) -> int:
        return len(self.chunks)


def plan_num_hot(chunk_rows: int, hot_block_bytes: int,
                 feature_dtype) -> int:
    """Hot-block width that fits the byte budget: at streaming scale the
    binding constraint is HBM (block bytes = chunk_rows × H × dtype),
    not the throughput-optimal split of hybrid_sparse."""
    bytes_per = 2 if feature_dtype == jnp.bfloat16 else 4
    return max(8, int(hot_block_bytes) // (chunk_rows * bytes_per))


def _build_canonical(raw, d: int, num_hot: int,
                     feature_dtype) -> CanonicalChunk:
    """Stage one ELL chunk into hot-dense + cold-ELL (host numpy)."""
    indices = np.asarray(raw.indices)
    values = np.asarray(raw.values)
    n = indices.shape[0]
    H = num_hot

    flat_col = indices.reshape(-1)
    flat_val = values.reshape(-1)
    live = (flat_col < d) & (flat_val != 0.0)
    counts = np.bincount(flat_col[live], minlength=d)
    # Top-H by count (count ties at the hot boundary break arbitrarily —
    # the hot/cold split is an execution choice, any split is the same
    # objective). Columns with count 0 may land in the tail of hot_cols
    # on tiny chunks — their X_hot columns stay zero and their id is
    # replaced by the sentinel. build_chunked guarantees H <= d.
    order = np.argpartition(-counts, H - 1)[:H].astype(np.int32)
    order = order[np.argsort(-counts[order], kind="stable")]
    hot_live = counts[order] > 0
    hot_cols = np.where(hot_live, order, d).astype(np.int32)

    hot_slot = np.full(d + 1, -1, np.int64)
    hot_slot[hot_cols[hot_cols < d]] = np.flatnonzero(hot_cols < d)

    flat_row = np.repeat(np.arange(n, dtype=np.int32), indices.shape[1])
    slot = hot_slot[np.minimum(flat_col, d)]
    hot_sel = live & (slot >= 0)
    X_hot = np.zeros((n, H), np.float32)
    X_hot[flat_row[hot_sel], slot[hot_sel]] = flat_val[hot_sel]

    # Cold ELL: the original (n, k) arrays with hot entries inert.
    is_hot2d = (slot >= 0).reshape(indices.shape)
    dead = is_hot2d | ~live.reshape(indices.shape)
    cold_cols = np.where(dead, d, indices).astype(np.int32)
    cold_vals = np.where(dead, 0.0, values).astype(np.float32)

    if feature_dtype == jnp.bfloat16:
        # Host-side cast halves the host→device stream — which IS the
        # steady-state cost of every streamed objective evaluation.
        # Values are storage (products upcast to f32 in-kernel).
        import ml_dtypes

        X_hot = X_hot.astype(ml_dtypes.bfloat16)
        cold_vals = cold_vals.astype(ml_dtypes.bfloat16)
    return CanonicalChunk(
        X_hot=X_hot, hot_cols=hot_cols, cold_cols=cold_cols,
        cold_vals=cold_vals, labels=np.asarray(raw.labels),
        weights=np.asarray(raw.weights), offsets=np.asarray(raw.offsets),
        num_features=d)


def build_chunked(
    chunk_iter: Iterable,
    num_features: int,
    chunk_rows: int,
    num_hot: int = 512,
    feature_dtype=jnp.float32,
    log: Optional[Callable[[str], None]] = None,
) -> ChunkedHybrid:
    """Stage a stream of ELL chunks into host-resident canonical layouts.

    ``chunk_iter`` yields objects with ``indices / values / labels /
    weights / offsets`` host arrays (``data/sparse.SparseBatch`` or any
    duck-typed source — the chunked Avro reader, a synthetic generator).
    Peak host memory beyond the staged output is ONE chunk."""
    num_hot = min(num_hot, num_features)
    chunks = []
    total = 0
    short_at = None
    for i, raw in enumerate(chunk_iter):
        if short_at is not None:
            # Row bookkeeping (margins_chunked's z[:num_rows] tail drop,
            # _offsets_for's i*chunk_rows slices) assumes pad rows exist
            # only at the STREAM tail; a mid-stream short chunk would
            # silently misalign residuals.
            raise ValueError(
                f"chunk {short_at} was short but chunk {i} follows — "
                f"only the final chunk may have fewer than chunk_rows="
                f"{chunk_rows} rows")
        n_i = int(np.asarray(raw.labels).shape[0])
        if n_i > chunk_rows:
            raise ValueError(f"chunk {i} has {n_i} rows > chunk_rows="
                             f"{chunk_rows}")
        total += n_i
        if n_i < chunk_rows:
            short_at = i
            raw = _pad_chunk(raw, chunk_rows, num_features)
        ch = _build_canonical(raw, num_features, num_hot, feature_dtype)
        chunks.append(ch)
        if log is not None:
            cold_live = int((np.asarray(ch.cold_cols) <
                             num_features).sum())
            log(f"staged chunk {i} ({n_i:,} rows, {num_hot} hot cols, "
                f"{cold_live:,} cold nnz)")
    if not chunks:
        raise ValueError("empty chunk stream")
    sigs = {ch.structure() for ch in chunks}
    if len(sigs) > 1:
        # Shapes inherit the source's ELL width — a source that pads
        # per-chunk (varying max_nnz) breaks the one-program invariant.
        raise ValueError(
            f"chunks have {len(sigs)} distinct structures {sigs}; pad "
            "every chunk's ELL to one shared max_nnz so the stream "
            "shares a single compiled program")
    return ChunkedHybrid(chunks=tuple(chunks), num_rows=total,
                         chunk_rows=chunk_rows)


def _pad_chunk(raw, chunk_rows: int, d: int):
    """Pad a short (final) chunk with weight-0 rows: every aggregate
    multiplies by weight before reducing, so pad rows add exactly 0 to
    value/gradient, and their margins are dropped by
    ``margins_chunked``."""
    from photon_ml_tpu.data.sparse import SparseBatch

    idx = np.asarray(raw.indices)
    n_i, nnz = idx.shape
    pad = chunk_rows - n_i

    def pad0(a):
        a = np.asarray(a)
        out = np.zeros((chunk_rows,) + a.shape[1:], a.dtype)
        out[:n_i] = a
        return out

    idx_p = np.full((chunk_rows, nnz), d, np.int32)
    idx_p[:n_i] = idx
    return SparseBatch(
        indices=idx_p, values=pad0(raw.values), labels=pad0(raw.labels),
        weights=pad0(raw.weights), offsets=pad0(raw.offsets),
        num_features=d)


# ---------------------------------------------------------------- kernels


def _masked(weights: Array, term: Array) -> Array:
    return jnp.where(weights > 0.0, weights * term, 0.0)


def _chunk_margins_of(ch: CanonicalChunk, w_pad: Array,
                      offsets: Array) -> Array:
    """(n,) wᵀx + offset. Hot: one MXU matvec. Cold: one 1-D gather per
    ELL slot (per-slot, 1-D — see the module docstring's layout rules)."""
    z = offsets + _hot_matvec(ch.X_hot, w_pad[ch.hot_cols])
    for j in range(ch.cold_cols.shape[1]):
        z = z + w_pad[ch.cold_cols[:, j]] * \
            ch.cold_vals[:, j].astype(jnp.float32)
    return z


def _chunk_rowterm_grad(ch: CanonicalChunk, r: Array) -> Array:
    """Σᵢ rᵢ·xᵢ in original space: hot rmatvec + one (d+1,)-table
    scatter-add per cold ELL slot (pad entries land on the sentinel
    column d and are dropped)."""
    acc = jnp.zeros((ch.num_features + 1,), jnp.float32)
    for j in range(ch.cold_cols.shape[1]):
        acc = acc.at[ch.cold_cols[:, j]].add(
            r * ch.cold_vals[:, j].astype(jnp.float32))
    g_hot = _hot_rmatvec(ch.X_hot, r).astype(jnp.float32)
    acc = acc.at[ch.hot_cols].add(g_hot)
    return acc[:ch.num_features]


# Kernels are cached per loss (and the margins kernel is a singleton):
# a fresh @jax.jit wrapper per call would re-trace the chunk program on
# every coordinate-descent update.
_VG_KERNELS: dict = {}
_V_KERNELS: dict = {}


def _chunk_value_grad(loss: PointwiseLoss):
    """One jitted per-chunk pass: original-space w in, original-space
    (value, grad) out — shared by every chunk (identical structures)."""
    f = _VG_KERNELS.get(loss.name)
    if f is not None:
        return f

    @jax.jit
    def f(w: Array, offsets: Array, ch: CanonicalChunk):
        w_pad = jnp.concatenate([w, jnp.zeros((1,), w.dtype)])
        z = _chunk_margins_of(ch, w_pad, offsets)
        l, dl = loss.loss_and_dz(z, ch.labels)
        value = jnp.sum(_masked(ch.weights, l))
        r = _masked(ch.weights, dl)
        return value, _chunk_rowterm_grad(ch, r)

    _VG_KERNELS[loss.name] = f
    return f


def _chunk_value(loss: PointwiseLoss):
    """Value-ONLY per-chunk pass: the margins + loss sum of
    ``_chunk_value_grad`` without the gradient half (the hot rmatvec and
    the per-slot cold scatter-adds — the dominant compute of a chunk
    pass). Armijo line-search probes only need the value to gate
    acceptance (ADVICE r5), so probing with this kernel skips the
    gradient work on every rejected step."""
    f = _V_KERNELS.get(loss.name)
    if f is not None:
        return f

    @jax.jit
    def f(w: Array, offsets: Array, ch: CanonicalChunk):
        w_pad = jnp.concatenate([w, jnp.zeros((1,), w.dtype)])
        z = _chunk_margins_of(ch, w_pad, offsets)
        l, _ = loss.loss_and_dz(z, ch.labels)
        return jnp.sum(_masked(ch.weights, l))

    _V_KERNELS[loss.name] = f
    return f


@jax.jit
def _margins_kernel(w: Array, offsets: Array, ch: CanonicalChunk):
    w_pad = jnp.concatenate([w, jnp.zeros((1,), w.dtype)])
    return _chunk_margins_of(ch, w_pad, offsets)


def _stream(chunked: ChunkedHybrid, depth: int, pinned=()):
    """Yield device-resident chunks with ``depth`` transfers in flight
    ahead of the consumer (same discipline as data/prefetch.py — the
    host→device copy of chunk i+1 overlaps the compute on chunk i).
    ``pinned`` are already-resident leading chunks (yielded as-is, no
    transfer)."""
    import collections

    if depth < 1:
        # depth=0 would silently yield no streamed chunks at all (the
        # priming loop never fills the queue) — a zero value/gradient,
        # not a slower one.
        raise ValueError(f"prefetch_depth must be >= 1, got {depth}")
    for ch in pinned:
        yield ch
    q = collections.deque()
    it = iter(chunked.chunks[len(pinned):])
    try:
        for _ in range(depth):
            q.append(jax.device_put(next(it)))
    except StopIteration:
        pass
    while q:
        ready = q.popleft()
        try:
            q.append(jax.device_put(next(it)))
        except StopIteration:
            pass
        yield ready


def _offsets_for(chunked: ChunkedHybrid, offsets: Optional[Array], i: int,
                 ch: CanonicalChunk):
    if offsets is None:
        return ch.offsets if isinstance(ch.offsets, jax.Array) \
            else jnp.asarray(ch.offsets)
    lo = i * chunked.chunk_rows
    return jax.lax.dynamic_slice_in_dim(
        offsets, lo, chunked.chunk_rows, 0)


def pin_chunks(chunked: ChunkedHybrid, count: int):
    """Place the first ``count`` chunks on device permanently and return
    them — spare HBM traded for stream traffic (the steady-state cost of
    every objective evaluation drops by the pinned fraction). The caller
    owns the sizing decision: pinned bytes compete with whatever else
    the fit keeps resident (e.g. random-effect bucket blocks)."""
    return tuple(jax.device_put(ch)
                 for ch in chunked.chunks[:max(0, count)])


def make_value_and_gradient(
    loss: PointwiseLoss,
    chunked: ChunkedHybrid,
    prefetch_depth: int = 2,
    pinned=(),
) -> Callable[[Array, Optional[Array]], tuple[Array, Array]]:
    """Streamed Σ-over-chunks (value, gradient) in original column space.

    The returned callable is HOST-DRIVEN (a Python loop dispatching one
    jitted pass per chunk) — it cannot be traced into an outer jit; pair
    it with the host-driven optimizer in ``optim/streaming.py``.
    ``offsets``, when given, is the full (padded_n,) device array of
    per-row offsets (coordinate-descent residuals); None uses the offsets
    staged in each chunk. ``pinned`` (from :func:`pin_chunks`) skips the
    host→device transfer for the leading chunks.
    """
    kernel = _chunk_value_grad(loss)

    def value_and_grad(w: Array, offsets: Optional[Array] = None):
        value = jnp.zeros((), jnp.float32)
        grad = jnp.zeros((chunked.dim,), jnp.float32)
        for i, ch in enumerate(_stream(chunked, prefetch_depth, pinned)):
            v, g = kernel(w, _offsets_for(chunked, offsets, i, ch), ch)
            value = value + v
            grad = grad + g
            # Barrier per chunk: the runtime holds every enqueued
            # program's scratch from ENQUEUE time, and a full unsynced
            # pass over the stream exhausts HBM at scale (measured: the
            # 100M-row run died on its first evaluation). The next
            # chunk's host→device copy is already in flight (_stream
            # prefetch), so the barrier costs one tunnel round trip per
            # chunk against a transfer-bound pass.
            jax.block_until_ready(grad)
            _release(ch, i, pinned)
        # Lazily-freed transfer buffers accumulate across evaluations
        # (measured: the 100M-row run's host RSS climbed ~60 GB over 11
        # L-BFGS iterations until the OOM killer fired); one collection
        # per pass keeps the pool bounded.
        gc.collect()
        return value, grad

    return value_and_grad


def make_value_only(
    loss: PointwiseLoss,
    chunked: ChunkedHybrid,
    prefetch_depth: int = 2,
    pinned=(),
) -> Callable[[Array, Optional[Array]], Array]:
    """Streamed Σ-over-chunks VALUE in original column space — the
    line-search probe companion of :func:`make_value_and_gradient` (same
    streaming discipline: prefetch, per-chunk barrier, eager release)."""
    kernel = _chunk_value(loss)

    def value_only(w: Array, offsets: Optional[Array] = None):
        value = jnp.zeros((), jnp.float32)
        for i, ch in enumerate(_stream(chunked, prefetch_depth, pinned)):
            v = kernel(w, _offsets_for(chunked, offsets, i, ch), ch)
            value = value + v
            jax.block_until_ready(value)  # same enqueue-scratch barrier
            _release(ch, i, pinned)
        gc.collect()
        return value

    return value_only


def _release(ch, i: int, pinned) -> None:
    """Drop a STREAMED chunk's device buffers eagerly — reference-count
    laziness is what let per-eval transfer buffers pile up on host."""
    if i < len(pinned):
        return
    for leaf in jax.tree.leaves(ch):
        if isinstance(leaf, jax.Array):
            leaf.delete()


def margins_chunked(
    chunked: ChunkedHybrid,
    w: Array,
    offsets: Optional[Array] = None,
    prefetch_depth: int = 2,
    pinned=(),
) -> Array:
    """(num_rows,) margins (wᵀx + offset), streamed; pad rows dropped."""
    parts = []
    for i, ch in enumerate(_stream(chunked, prefetch_depth, pinned)):
        parts.append(_margins_kernel(
            w, _offsets_for(chunked, offsets, i, ch), ch))
        jax.block_until_ready(parts[-1])  # same enqueue-scratch barrier
        _release(ch, i, pinned)
    gc.collect()
    z = jnp.concatenate(parts)
    return z[:chunked.num_rows]
