"""Row-streamed sparse GLM aggregates: the Criteo row axis on one chip.

Reference parity: photon-api ``DistributedGLMLossFunction`` computes each
value/gradient as one Spark pass over RDD partitions (``treeAggregate``) —
the n axis never has to fit on any single executor. This module is the
TPU-native equivalent: the example rows live on HOST in fixed-size chunks,
and every objective evaluation streams them through the chip with
double-buffered host→device prefetch, accumulating ``(value, gradient)``
in f32 on device. HBM holds at most ``prefetch_depth`` chunks plus the
accumulators, so n is bounded by host RAM (or disk, via the chunk
iterator), not by the 16 GB of one chip.

**Chunk layout: hot-dense block + cold ELL.** Each chunk densifies its
top-``num_hot`` columns into an (n, H) MXU block (the Zipf head is the
bulk of the nonzeros) and keeps the remaining entries in ELL with their
ORIGINAL column ids (hot entries become inert pad slots). Two hard
lessons at n=100M shape this (both measured on v5e, both aborting
COMPILATION with HBM overflows before any data moved):

  * gathers/scatters must be per-ELL-slot 1-D ops — an index operand
    shaped (n, k) or (n, k, 1) is materialized in a (8, 128)-tiled
    layout whose minor dims pad to 128 (a 51 GB copy at n=100M);
  * no flat concatenated streams — XLA lays a 128M-element 1-D
    intermediate out as (64M, 2) tiled, padding 2→128 (a 33 GB copy).
    This is why the device-resident hybrid's contiguous-class layout
    (ops/hybrid_sparse.py), which wins 6-8× at bench scale, is NOT used
    here: its per-class flat gather/scatter streams cannot compile at
    streamed-chunk scale, and the stream is host→device transfer-bound
    anyway, so the cold formulation's compute rate is immaterial.

Every chunk has identical array shapes by construction ((n, H), (H,),
(n, k)), so the WHOLE stream shares ONE compiled program — per-structure
compiles are multi-minute remote operations in this environment.

**int8 quantized storage (docs/STREAMING.md "Quantized streaming").**
The streamed pass is transfer-bound (~95% host→device at n=100M), so
the storage dtype of the chunk payload IS the pass cost. Beyond the
bf16 half-stream, ``feature_dtype="int8"`` stores ``X_hot`` and
``cold_vals`` as symmetric per-column affine int8 — q = round(x / s),
s = max|column| / 127, zero-point pinned at 0 so sparse zeros stay
EXACT — with f32 scale vectors riding each chunk (``hot_scale`` per hot
column, ``cold_scale`` per original column). Dequantization happens
ON DEVICE inside the jitted chunk kernels, and never materializes a
dense f32 block: the margins pass folds the scales into the coefficient
gathers (w·(s·q) = (w·s)·q), and the gradient pass scatters raw r·q
sums and scales the (d+1,) accumulator once at the end — O(d + H)
dequant flops against an O(n·k) transfer saved. Accumulation stays f32
throughout, so the compiled program count is unchanged (the kernel
caches grow a dtype key) and the measured ``photon_transfer_bytes_total``
per pass drops ~4× vs f32 (~2× vs bf16).
"""

from __future__ import annotations

import dataclasses
import gc
import logging
import time
from typing import Callable, Iterable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu import faults as flt
from photon_ml_tpu import obs
from photon_ml_tpu.ops.hybrid_sparse import _hot_matvec, _hot_rmatvec
from photon_ml_tpu.ops.losses import PointwiseLoss

Array = jax.Array

logger = logging.getLogger("photon_ml_tpu.ops")

# Chunk host→device transfer degradation ladder (docs/ROBUSTNESS.md):
# bounded retry with deterministic backoff, then a loud failure — a
# transfer is idempotent (the chunk is host-resident), so retry is always
# safe, and there is no serial fallback below it to degrade to.
TRANSFER_MAX_RETRIES = 2
TRANSFER_RETRY_BACKOFF_S = 0.05


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class CanonicalChunk:
    """One chunk: hot-dense block + cold ELL (leaves may be host numpy —
    device placement happens at stream time).

    Under int8 storage ``X_hot``/``cold_vals`` hold the quantized codes
    and the two scale vectors are present (``quantized`` is True); the
    scheme is symmetric (zero-point ≡ 0), so a zero entry is exactly the
    code 0 and the pad/hot-inert slots stay inert without masks."""

    X_hot: Array  # (n, H) — the chunk's top-H columns, densified
    hot_cols: Array  # (H,) int32 original column ids (pad == d)
    cold_cols: Array  # (n, k) int32 original ids; hot/pad entries == d
    cold_vals: Array  # (n, k); hot/pad entries == 0
    labels: Array  # (n,)
    weights: Array  # (n,); 0 marks pad rows of a short final chunk
    offsets: Array  # (n,)
    num_features: int = dataclasses.field(metadata=dict(static=True))
    # int8 mode only (None otherwise): per-hot-column and per-original-
    # column f32 dequantization scales (x ≈ scale · q, zero-point 0).
    hot_scale: Optional[Array] = None  # (H,)
    cold_scale: Optional[Array] = None  # (d + 1,); sentinel col == 0

    @property
    def num_rows(self) -> int:
        return self.labels.shape[0]

    @property
    def num_hot(self) -> int:
        return self.X_hot.shape[1]

    @property
    def quantized(self) -> bool:
        return self.cold_scale is not None

    def structure(self):
        """Shape signature — equal signatures share one compiled program.
        Identical across chunks by construction (the storage dtype is
        part of the signature: a mixed-dtype stream would silently
        compile two programs); kept for the invariant test."""
        return (self.X_hot.shape, self.cold_cols.shape,
                self.num_features, chunk_dtype(self))


@dataclasses.dataclass(frozen=True)
class ChunkedHybrid:
    """Host-resident chunked layout of one logical (n, d) batch.

    Equal row counts per chunk (short final chunk padded with weight-0
    rows — inert in every aggregate; their margins are dropped by
    ``margins_chunked``). ``num_rows`` is the REAL row count.
    """

    chunks: tuple[CanonicalChunk, ...]
    num_rows: int
    chunk_rows: int

    @property
    def dim(self) -> int:
        return self.chunks[0].num_features

    @property
    def num_chunks(self) -> int:
        return len(self.chunks)


# Chunk-storage dtype → per-value payload bytes. int8 columns also carry
# one f32 scale each (the symmetric-quantization dequant vector), so the
# HBM plan charges it per column — at streaming chunk_rows the 4 bytes
# per column are noise, but a plan that ignores them would overshoot a
# tight budget on many-column/few-row configs.
FEATURE_ITEMSIZE = {"float32": 4, "bfloat16": 2, "int8": 1}
_SCALE_BYTES_PER_COLUMN = {"float32": 0, "bfloat16": 0, "int8": 4}
INT8_QMAX = 127.0  # symmetric: codes span [-127, 127], zero-point 0


def feature_dtype_name(feature_dtype) -> str:
    """Canonical name of a chunk-storage dtype spec (string, numpy/jax
    dtype, or None = float32). Unknown dtypes raise — a silent f32
    fallback would quietly quadruple a stream someone sized for int8."""
    if feature_dtype is None:
        return "float32"
    if isinstance(feature_dtype, str):
        name = feature_dtype.lower()
    else:
        try:
            name = np.dtype(feature_dtype).name
        except TypeError:
            name = str(feature_dtype)
    if name not in FEATURE_ITEMSIZE:
        raise ValueError(
            f"unsupported streaming feature_dtype {feature_dtype!r}; "
            f"expected one of {sorted(FEATURE_ITEMSIZE)}")
    return name


def chunk_dtype(ch: "CanonicalChunk") -> str:
    """The storage dtype a staged chunk actually carries."""
    if ch.cold_scale is not None:
        return "int8"
    if np.dtype(ch.X_hot.dtype) == np.dtype(jnp.bfloat16):
        return "bfloat16"
    return "float32"


def plan_num_hot(chunk_rows: int, hot_block_bytes: int,
                 feature_dtype) -> int:
    """Hot-block width that fits the byte budget: at streaming scale the
    binding constraint is HBM (block bytes = chunk_rows × H × itemsize,
    plus the per-column scale under int8), not the throughput-optimal
    split of hybrid_sparse."""
    name = feature_dtype_name(feature_dtype)
    per_column = (chunk_rows * FEATURE_ITEMSIZE[name]
                  + _SCALE_BYTES_PER_COLUMN[name])
    return max(8, int(hot_block_bytes) // per_column)


def quantize_rows_int8(x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Symmetric per-ROW int8 quantization: q = round(x / s) with
    s = max|row| / 127 (all-zero rows keep scale 0 and code 0, so
    dequantization is exact for them). Shared by the chunk hot block
    (transposed) and the serving device-LRU fill path."""
    x = np.asarray(x, np.float32)
    scale = np.abs(x).max(axis=-1) / INT8_QMAX if x.size else \
        np.zeros(x.shape[:-1], np.float32)
    scale = np.asarray(scale, np.float32)
    denom = np.where(scale > 0.0, scale, 1.0)
    q = np.clip(np.rint(x / denom[..., None]), -INT8_QMAX,
                INT8_QMAX).astype(np.int8)
    return q, scale


def _quantize_cold_int8(cold_vals: np.ndarray, cold_cols: np.ndarray,
                        d: int) -> tuple[np.ndarray, np.ndarray]:
    """Per-ORIGINAL-column symmetric int8 over a chunk's cold ELL: the
    scale table is (d + 1,) so the kernels can gather it exactly like
    the padded coefficient vector (per-slot, 1-D — the layout rules).
    Inert entries all point at the sentinel column d, whose scale stays
    0 by construction (their stored value is exactly 0)."""
    amax = np.zeros(d + 1, np.float32)
    np.maximum.at(amax, cold_cols.reshape(-1),
                  np.abs(cold_vals).reshape(-1))
    scale = amax / INT8_QMAX
    denom = np.where(scale > 0.0, scale, 1.0)
    q = np.clip(np.rint(cold_vals / denom[cold_cols]), -INT8_QMAX,
                INT8_QMAX).astype(np.int8)
    return q, scale


def _build_canonical(raw, d: int, num_hot: int,
                     feature_dtype) -> CanonicalChunk:
    """Stage one ELL chunk into hot-dense + cold-ELL (host numpy)."""
    indices = np.asarray(raw.indices)
    values = np.asarray(raw.values)
    n = indices.shape[0]
    H = num_hot

    flat_col = indices.reshape(-1)
    flat_val = values.reshape(-1)
    live = (flat_col < d) & (flat_val != 0.0)
    counts = np.bincount(flat_col[live], minlength=d)
    # Top-H by count (count ties at the hot boundary break arbitrarily —
    # the hot/cold split is an execution choice, any split is the same
    # objective). Columns with count 0 may land in the tail of hot_cols
    # on tiny chunks — their X_hot columns stay zero and their id is
    # replaced by the sentinel. build_chunked guarantees H <= d.
    order = np.argpartition(-counts, H - 1)[:H].astype(np.int32)
    order = order[np.argsort(-counts[order], kind="stable")]
    hot_live = counts[order] > 0
    hot_cols = np.where(hot_live, order, d).astype(np.int32)

    hot_slot = np.full(d + 1, -1, np.int64)
    hot_slot[hot_cols[hot_cols < d]] = np.flatnonzero(hot_cols < d)

    flat_row = np.repeat(np.arange(n, dtype=np.int32), indices.shape[1])
    slot = hot_slot[np.minimum(flat_col, d)]
    hot_sel = live & (slot >= 0)
    X_hot = np.zeros((n, H), np.float32)
    X_hot[flat_row[hot_sel], slot[hot_sel]] = flat_val[hot_sel]

    # Cold ELL: the original (n, k) arrays with hot entries inert.
    is_hot2d = (slot >= 0).reshape(indices.shape)
    dead = is_hot2d | ~live.reshape(indices.shape)
    cold_cols = np.where(dead, d, indices).astype(np.int32)
    cold_vals = np.where(dead, 0.0, values).astype(np.float32)

    dtype_name = feature_dtype_name(feature_dtype)
    hot_scale = cold_scale = None
    if dtype_name == "bfloat16":
        # Host-side cast halves the host→device stream — which IS the
        # steady-state cost of every streamed objective evaluation.
        # Values are storage (products upcast to f32 in-kernel).
        import ml_dtypes

        X_hot = X_hot.astype(ml_dtypes.bfloat16)
        cold_vals = cold_vals.astype(ml_dtypes.bfloat16)
    elif dtype_name == "int8":
        # Symmetric per-column int8: quarters the stream vs f32. The hot
        # block quantizes per hot column (transpose into the per-row
        # helper); the cold ELL per original column so the scale table
        # gathers like w_pad.
        q_hot, hot_scale = quantize_rows_int8(X_hot.T)
        X_hot = np.ascontiguousarray(q_hot.T)
        cold_vals, cold_scale = _quantize_cold_int8(cold_vals, cold_cols,
                                                    d)
    return CanonicalChunk(
        X_hot=X_hot, hot_cols=hot_cols, cold_cols=cold_cols,
        cold_vals=cold_vals, labels=np.asarray(raw.labels),
        weights=np.asarray(raw.weights), offsets=np.asarray(raw.offsets),
        num_features=d, hot_scale=hot_scale, cold_scale=cold_scale)


def build_chunked(
    chunk_iter: Iterable,
    num_features: int,
    chunk_rows: int,
    num_hot: int = 512,
    feature_dtype=jnp.float32,
    log: Optional[Callable[[str], None]] = None,
    workers: int = 1,
) -> ChunkedHybrid:
    """Stage a stream of ELL chunks into host-resident canonical layouts.

    ``chunk_iter`` yields objects with ``indices / values / labels /
    weights / offsets`` host arrays (``data/sparse.SparseBatch`` or any
    duck-typed source — the chunked Avro reader, a synthetic generator).
    Peak host memory beyond the staged output is ONE chunk serially;
    ``workers > 1`` fans the per-chunk canonicalization (bincount +
    argpartition + scatter — GIL-releasing numpy) over a thread pool
    with a bounded in-flight window of ``workers + 2`` chunks, merged in
    plan order BIT-identically to the serial pass (the per-chunk math is
    independent; only the submission order is pipelined)."""
    import concurrent.futures as cf

    num_hot = min(num_hot, num_features)
    total = 0
    short_at = None
    rows_of: list[int] = []

    def _prepped():
        """Serial validation + tail padding (cheap) ahead of the
        canonicalization fan-out; mutates total/short_at bookkeeping."""
        nonlocal total, short_at
        for i, raw in enumerate(chunk_iter):
            if short_at is not None:
                # Row bookkeeping (margins_chunked's z[:num_rows] tail
                # drop, _offsets_for's i*chunk_rows slices) assumes pad
                # rows exist only at the STREAM tail; a mid-stream short
                # chunk would silently misalign residuals.
                raise ValueError(
                    f"chunk {short_at} was short but chunk {i} follows — "
                    f"only the final chunk may have fewer than chunk_rows="
                    f"{chunk_rows} rows")
            n_i = int(np.asarray(raw.labels).shape[0])
            if n_i > chunk_rows:
                raise ValueError(f"chunk {i} has {n_i} rows > chunk_rows="
                                 f"{chunk_rows}")
            total += n_i
            rows_of.append(n_i)
            if n_i < chunk_rows:
                short_at = i
                raw = _pad_chunk(raw, chunk_rows, num_features)
            yield i, raw

    chunks: list[CanonicalChunk] = []

    def _emit(i: int, ch: CanonicalChunk) -> None:
        chunks.append(ch)
        if log is not None:
            cold_live = int((np.asarray(ch.cold_cols) <
                             num_features).sum())
            log(f"staged chunk {i} ({rows_of[i]:,} rows, {num_hot} hot "
                f"cols, {cold_live:,} cold nnz)")

    if workers <= 1:
        for i, raw in _prepped():
            _emit(i, _build_canonical(raw, num_features, num_hot,
                                      feature_dtype))
    else:
        import collections

        window: collections.deque = collections.deque()
        with cf.ThreadPoolExecutor(
                max_workers=workers,
                thread_name_prefix="pml-stream-stage") as pool:
            for i, raw in _prepped():
                window.append((i, pool.submit(
                    _build_canonical, raw, num_features, num_hot,
                    feature_dtype)))
                if len(window) > workers + 2:
                    j, fut = window.popleft()
                    _emit(j, fut.result())
            while window:
                j, fut = window.popleft()
                _emit(j, fut.result())
    if not chunks:
        raise ValueError("empty chunk stream")
    sigs = {ch.structure() for ch in chunks}
    if len(sigs) > 1:
        # Shapes inherit the source's ELL width — a source that pads
        # per-chunk (varying max_nnz) breaks the one-program invariant.
        raise ValueError(
            f"chunks have {len(sigs)} distinct structures {sigs}; pad "
            "every chunk's ELL to one shared max_nnz so the stream "
            "shares a single compiled program")
    return ChunkedHybrid(chunks=tuple(chunks), num_rows=total,
                         chunk_rows=chunk_rows)


def iter_shard_chunks(shard, labels, weights, chunk_rows: int):
    """SparseBatch chunks over an ELL SparseShard's row ranges, staged
    with ZERO offsets (the streaming contract: in coordinate descent the
    residual arrives via ``train_model``'s offsets argument, never via
    the staged chunks). Feeds :func:`build_chunked` from a materialized
    GameDataset shard — the estimator's route onto the streamed path.
    Slices are views (no copy); _build_canonical owns the real work."""
    from photon_ml_tpu.data.sparse import SparseBatch

    labels = np.asarray(labels)
    weights = np.asarray(weights)
    n = int(shard.indices.shape[0])
    for lo in range(0, n, chunk_rows):
        hi = min(lo + chunk_rows, n)
        yield SparseBatch(
            indices=shard.indices[lo:hi], values=shard.values[lo:hi],
            labels=labels[lo:hi], weights=weights[lo:hi],
            offsets=np.zeros(hi - lo, np.float32),
            num_features=int(shard.num_features))


def _pad_chunk(raw, chunk_rows: int, d: int):
    """Pad a short (final) chunk with weight-0 rows: every aggregate
    multiplies by weight before reducing, so pad rows add exactly 0 to
    value/gradient, and their margins are dropped by
    ``margins_chunked``."""
    from photon_ml_tpu.data.sparse import SparseBatch

    idx = np.asarray(raw.indices)
    n_i, nnz = idx.shape
    pad = chunk_rows - n_i

    def pad0(a):
        a = np.asarray(a)
        out = np.zeros((chunk_rows,) + a.shape[1:], a.dtype)
        out[:n_i] = a
        return out

    idx_p = np.full((chunk_rows, nnz), d, np.int32)
    idx_p[:n_i] = idx
    return SparseBatch(
        indices=idx_p, values=pad0(raw.values), labels=pad0(raw.labels),
        weights=pad0(raw.weights), offsets=pad0(raw.offsets),
        num_features=d)


# ---------------------------------------------------------------- kernels


def _masked(weights: Array, term: Array) -> Array:
    return jnp.where(weights > 0.0, weights * term, 0.0)


def _resolve_stream_fused(dtype: str):
    """(margins, rmatvec) fused-kernel resolutions for this stream's
    programs, or None where the flag is off or the resolve degraded.

    Called from the program BUILDERS only (one resolve per compiled
    program — the one-program-per-stream invariant extends to backend
    choice). Flag off means NO registry traffic: the ledger a flag-off
    run writes is byte-identical to the pre-registry tree, which is what
    keeps the trace_smoke ≤3-builds needle honest."""
    from photon_ml_tpu.ops import kernels
    reg = kernels.registry()
    fused_m = fused_r = None
    if reg.enabled("stream_margins"):
        rk = reg.resolve("stream_margins", dtype=dtype)
        if rk.backend == "pallas":
            fused_m = rk
    if reg.enabled("stream_rmatvec"):
        rk = reg.resolve("stream_rmatvec", dtype=dtype)
        if rk.backend == "pallas":
            fused_r = rk
    return fused_m, fused_r


def _chunk_margins_of(ch: CanonicalChunk, w_pad: Array, offsets: Array,
                      fused_margins=None) -> Array:
    """(n,) wᵀx + offset. Hot: one MXU matvec. Cold: one 1-D gather per
    ELL slot (per-slot, 1-D — see the module docstring's layout rules).

    int8 dequant prologue: the per-column scales FOLD into the
    coefficient side — w·(s·q) = (w·s)·q — so the quantized codes feed
    the same matvec/gathers with f32 accumulation and no dense f32
    block is ever materialized.

    ``fused_margins`` (registry ``stream_margins``, docs/KERNELS.md):
    the cold per-slot terms become the PROLOGUE — summed into ``base``
    first, byte-small by the hot/cold split — and the hot tier runs as
    one Pallas program with the dequant upcast inside the matvec tiles,
    so even the explicit ``astype`` copy below never materializes."""
    if ch.cold_scale is not None:
        w_cold = w_pad * ch.cold_scale
        w_hot = w_pad[ch.hot_cols] * ch.hot_scale
        if fused_margins is not None:
            base = offsets
            for j in range(ch.cold_cols.shape[1]):
                base = base + w_cold[ch.cold_cols[:, j]] * \
                    ch.cold_vals[:, j].astype(jnp.float32)
            return fused_margins(ch.X_hot, w_hot, base)
        z = offsets + _hot_matvec(ch.X_hot.astype(jnp.float32), w_hot)
        for j in range(ch.cold_cols.shape[1]):
            z = z + w_cold[ch.cold_cols[:, j]] * \
                ch.cold_vals[:, j].astype(jnp.float32)
        return z
    if fused_margins is not None:
        base = offsets
        for j in range(ch.cold_cols.shape[1]):
            base = base + w_pad[ch.cold_cols[:, j]] * \
                ch.cold_vals[:, j].astype(jnp.float32)
        return fused_margins(ch.X_hot, w_pad[ch.hot_cols], base)
    z = offsets + _hot_matvec(ch.X_hot, w_pad[ch.hot_cols])
    for j in range(ch.cold_cols.shape[1]):
        z = z + w_pad[ch.cold_cols[:, j]] * \
            ch.cold_vals[:, j].astype(jnp.float32)
    return z


def _chunk_rowterm_grad(ch: CanonicalChunk, r: Array,
                        fused_rmatvec=None) -> Array:
    """Σᵢ rᵢ·xᵢ in original space: hot rmatvec + one (d+1,)-table
    scatter-add per cold ELL slot (pad entries land on the sentinel
    column d and are dropped).

    int8 dequant prologue: scatter the RAW r·q sums, then scale the
    (d+1,) accumulator once per column (g_col = s_col · Σ r·q) — the
    dequant costs O(d + H) per chunk instead of O(n·k).

    ``fused_rmatvec`` (registry ``stream_rmatvec``): the hot tier's
    Xᵀr runs with the int8 upcast inside the tiles (no (n,H) f32 copy);
    the O(H) scale epilogue stays out here either way."""
    if ch.cold_scale is not None:
        acc = jnp.zeros((ch.num_features + 1,), jnp.float32)
        for j in range(ch.cold_cols.shape[1]):
            acc = acc.at[ch.cold_cols[:, j]].add(
                r * ch.cold_vals[:, j].astype(jnp.float32))
        acc = acc * ch.cold_scale
        if fused_rmatvec is not None:
            g_hot = fused_rmatvec(ch.X_hot, r) * ch.hot_scale
        else:
            g_hot = _hot_rmatvec(ch.X_hot.astype(jnp.float32), r) * \
                ch.hot_scale
        acc = acc.at[ch.hot_cols].add(g_hot.astype(jnp.float32))
        return acc[:ch.num_features]
    acc = jnp.zeros((ch.num_features + 1,), jnp.float32)
    for j in range(ch.cold_cols.shape[1]):
        acc = acc.at[ch.cold_cols[:, j]].add(
            r * ch.cold_vals[:, j].astype(jnp.float32))
    if fused_rmatvec is not None:
        g_hot = fused_rmatvec(ch.X_hot, r).astype(jnp.float32)
    else:
        g_hot = _hot_rmatvec(ch.X_hot, r).astype(jnp.float32)
    acc = acc.at[ch.hot_cols].add(g_hot)
    return acc[:ch.num_features]


# Kernels are cached per (loss, storage dtype) — the dtype key is how
# quantized streams keep the one-program-per-stream accounting honest
# (an int8 chunk IS a different compiled program; without the key the
# jit dispatch would compile it silently past the miss counter). The
# margins kernel stays a singleton (jit dispatches on chunk structure).
_VG_KERNELS: dict = {}
_V_KERNELS: dict = {}


def _count_kernel_build(cache: str, dtype: str) -> None:
    """One streamed-kernel program cache missed — a fresh trace/compile.
    Steady state should show exactly one build per (loss, cache, dtype);
    a climbing counter means the one-program-per-stream invariant
    broke."""
    mx = obs.metrics()
    if mx is not None:
        mx.counter("photon_compile_cache_misses_total", cache=cache,
                   dtype=dtype).inc()


def _count_kernel_hit(cache: str, dtype: str) -> None:
    """The hit side of the same ledger: a warm pass re-using its
    compiled program. Boot/warm-restart paths should show HITS climbing
    beside a flat miss counter — silence there means the cache key
    rotated and every restart recompiles (docs/SERVING.md "Sub-second
    restart")."""
    mx = obs.metrics()
    if mx is not None:
        mx.counter("photon_compile_cache_hits_total", cache=cache,
                   dtype=dtype).inc()


def _chunk_value_grad(loss: PointwiseLoss, dtype: str = "float32"):
    """One jitted per-chunk pass: original-space w in, original-space
    (value, grad) out — shared by every chunk (identical structures).

    The cache key carries the resolved fused-kernel state: a flag flip
    mid-process gets a FRESH program (and a counted build) instead of
    silently reusing the other backend's compile."""
    fused_m, fused_r = _resolve_stream_fused(dtype)
    key = (loss.name, dtype, fused_m is not None, fused_r is not None)
    f = _VG_KERNELS.get(key)
    if f is not None:
        _count_kernel_hit("stream_value_grad", dtype)
        return f
    _count_kernel_build("stream_value_grad", dtype)

    @jax.jit
    def f(w: Array, offsets: Array, ch: CanonicalChunk):
        w_pad = jnp.concatenate([w, jnp.zeros((1,), w.dtype)])
        z = _chunk_margins_of(ch, w_pad, offsets, fused_margins=fused_m)
        l, dl = loss.loss_and_dz(z, ch.labels)
        value = jnp.sum(_masked(ch.weights, l))
        r = _masked(ch.weights, dl)
        return value, _chunk_rowterm_grad(ch, r, fused_rmatvec=fused_r)

    _VG_KERNELS[key] = f
    return f


def _chunk_value(loss: PointwiseLoss, dtype: str = "float32"):
    """Value-ONLY per-chunk pass: the margins + loss sum of
    ``_chunk_value_grad`` without the gradient half (the hot rmatvec and
    the per-slot cold scatter-adds — the dominant compute of a chunk
    pass). Armijo line-search probes only need the value to gate
    acceptance (ADVICE r5), so probing with this kernel skips the
    gradient work on every rejected step."""
    fused_m, _ = _resolve_stream_fused(dtype)
    key = (loss.name, dtype, fused_m is not None)
    f = _V_KERNELS.get(key)
    if f is not None:
        _count_kernel_hit("stream_value_only", dtype)
        return f
    _count_kernel_build("stream_value_only", dtype)

    @jax.jit
    def f(w: Array, offsets: Array, ch: CanonicalChunk):
        w_pad = jnp.concatenate([w, jnp.zeros((1,), w.dtype)])
        z = _chunk_margins_of(ch, w_pad, offsets, fused_margins=fused_m)
        l, _ = loss.loss_and_dz(z, ch.labels)
        return jnp.sum(_masked(ch.weights, l))

    _V_KERNELS[key] = f
    return f


# Margins-only programs, keyed by fused-kernel state alone (jit
# dispatches on chunk structure/dtype within each entry — the
# pre-registry singleton behavior, per backend).
_MARGINS_KERNELS: dict = {}


def _margins_kernel(w: Array, offsets: Array, ch: CanonicalChunk):
    fused_m, _ = _resolve_stream_fused(str(jnp.asarray(ch.X_hot).dtype))
    key = fused_m is not None
    f = _MARGINS_KERNELS.get(key)
    if f is None:
        @jax.jit
        def f(w: Array, offsets: Array, ch: CanonicalChunk,
              _fused=fused_m):
            w_pad = jnp.concatenate([w, jnp.zeros((1,), w.dtype)])
            return _chunk_margins_of(ch, w_pad, offsets,
                                     fused_margins=_fused)

        _MARGINS_KERNELS[key] = f
    return f(w, offsets, ch)


def _chunk_nbytes(ch) -> int:
    """Host-side payload bytes of one chunk's leaves — the analytic unit
    the transfer accounting sums (ISSUE 7 satellite 1 asserts the total
    IS this sum, per streamed chunk, per pass)."""
    return int(sum(int(getattr(leaf, "nbytes", 0))
                   for leaf in jax.tree.leaves(ch)))


# Per-pass gc floor: the full collection after a streamed pass exists to
# bound lazily-freed transfer buffers (the n=100M lesson: ~60 GB of host
# RSS over 11 L-BFGS iterations before the OOM killer fired). That only
# matters when a pass actually moves serious bytes; eager per-chunk
# ``leaf.delete()`` already frees the device side, and a FULL gc.collect
# in a long-lived process (the test suite: measured 300s of a single
# test's wall, ~8s standalone) costs seconds per call once the heap is
# big. Collect only when the pass streamed enough for buffer pileup to
# matter — flagship passes (GBs) always collect; test passes (KBs) never.
GC_STREAM_BYTES_FLOOR = 1 << 28  # 256 MiB per pass


def _stream_nbytes(chunked: "ChunkedHybrid") -> int:
    """Total streamed payload per pass, memoized on the ChunkedHybrid."""
    cached = getattr(chunked, "_payload_nbytes", None)
    if cached is None:
        cached = sum(_chunk_nbytes(ch) for ch in chunked.chunks)
        object.__setattr__(chunked, "_payload_nbytes", cached)
    return cached


def _collect_after_pass(chunked: "ChunkedHybrid") -> None:
    if _stream_nbytes(chunked) >= GC_STREAM_BYTES_FLOOR:
        gc.collect()


def _transfer(ch: CanonicalChunk, index: int,
              device: Optional[jax.Device] = None):
    """Host→device chunk copy behind the ``stream.chunk_transfer`` fault
    site, with the bounded-retry ladder: a transfer is idempotent, so a
    transient failure retries with deterministic backoff; exhausted
    retries raise loudly (there is no degraded mode below a lost chunk —
    dropping it would silently change the objective).

    This is ALSO the ``device_put`` accounting seam (docs/OBSERVABILITY
    .md): when obs is on, every successful transfer adds its payload to
    ``photon_transfer_bytes_total``/``photon_transfer_seconds_total``
    and bumps the in-flight chunk gauge; off, the cost is one None check.
    The seconds counter measures the HOST-side ``device_put`` time (the
    enqueue/copy commit) — on a transfer-bound stream that is the wall.
    """
    for attempt in range(TRANSFER_MAX_RETRIES + 1):
        try:
            flt.fire(flt.sites.STREAM_CHUNK_TRANSFER, index=index)
            mx, tr = obs.metrics(), obs.tracer()
            if mx is None and tr is None:
                return (jax.device_put(ch, device) if device is not None
                        else jax.device_put(ch))
            return _accounted_transfer(ch, index, device, mx, tr)
        except Exception as e:
            if attempt >= TRANSFER_MAX_RETRIES:
                raise
            logger.warning(
                "chunk %d transfer failed (%s: %s); retry %d/%d",
                index, type(e).__name__, e, attempt + 1,
                TRANSFER_MAX_RETRIES)
            mx = obs.metrics()
            if mx is not None:
                mx.counter("photon_stream_transfer_retries_total").inc()
            time.sleep(TRANSFER_RETRY_BACKOFF_S * (attempt + 1))


def _accounted_transfer(ch, index: int, device, mx, tr):
    """The traced/metered half of :func:`_transfer` (split out so the
    off path stays one None check). The transfer family is tagged with
    the chunk's storage dtype — `photon-obs summarize` attributes the
    stream per dtype, and the quantization bench's byte claims share
    provenance with these counters (readers that don't care sum the
    label family via ``obs.metric_value``)."""
    nbytes = _chunk_nbytes(ch)
    dtype = chunk_dtype(ch)
    t0 = time.perf_counter()
    if tr is not None:
        with tr.span("stream.chunk_transfer", cat="transfer",
                     index=index, bytes=nbytes, dtype=dtype):
            out = (jax.device_put(ch, device) if device is not None
                   else jax.device_put(ch))
    else:
        out = (jax.device_put(ch, device) if device is not None
               else jax.device_put(ch))
    if mx is not None:
        dt = time.perf_counter() - t0
        mx.counter("photon_transfer_bytes_total", kind="stream",
                   dtype=dtype).inc(nbytes)
        mx.counter("photon_transfer_seconds_total", kind="stream",
                   dtype=dtype).inc(dt)
        mx.counter("photon_transfer_chunks_total", kind="stream",
                   dtype=dtype).inc()
        mx.gauge("photon_stream_inflight_chunks").inc()
    return out


def _delete_chunk(ch) -> None:
    """Eagerly drop one STREAMED chunk's device buffers and step the
    in-flight gauge back down — the gauge's peak is the measured form of
    the n=100M enqueue-scratch bound."""
    for leaf in jax.tree.leaves(ch):
        if isinstance(leaf, jax.Array):
            leaf.delete()
    mx = obs.metrics()
    if mx is not None:
        mx.gauge("photon_stream_inflight_chunks").dec()


def _stream(chunked: ChunkedHybrid, depth: int, pinned=()):
    """Yield device-resident chunks with ``depth`` transfers in flight
    ahead of the consumer (same discipline as data/prefetch.py — the
    host→device copy of chunk i+1 overlaps the compute on chunk i).
    ``pinned`` are already-resident leading chunks (yielded as-is, no
    transfer)."""
    import collections

    if depth < 1:
        # depth=0 would silently yield no streamed chunks at all (the
        # priming loop never fills the queue) — a zero value/gradient,
        # not a slower one.
        raise ValueError(f"prefetch_depth must be >= 1, got {depth}")
    for ch in pinned:
        yield ch
    q = collections.deque()
    it = enumerate(chunked.chunks)
    for _ in range(len(pinned)):
        next(it)
    try:
        for _ in range(depth):
            i, ch = next(it)
            q.append(_transfer(ch, i))
    except StopIteration:
        pass
    while q:
        ready = q.popleft()
        try:
            i, ch = next(it)
            q.append(_transfer(ch, i))
        except StopIteration:
            pass
        yield ready


def _offsets_for(chunked: ChunkedHybrid, offsets: Optional[Array], i: int,
                 ch: CanonicalChunk):
    if offsets is None:
        return ch.offsets if isinstance(ch.offsets, jax.Array) \
            else jnp.asarray(ch.offsets)
    lo = i * chunked.chunk_rows
    return jax.lax.dynamic_slice_in_dim(
        offsets, lo, chunked.chunk_rows, 0)


def pin_chunks(chunked: ChunkedHybrid, count: int):
    """Place the first ``count`` chunks on device permanently and return
    them — spare HBM traded for stream traffic (the steady-state cost of
    every objective evaluation drops by the pinned fraction). The caller
    owns the sizing decision: pinned bytes compete with whatever else
    the fit keeps resident (e.g. random-effect bucket blocks)."""
    return tuple(jax.device_put(ch)
                 for ch in chunked.chunks[:max(0, count)])


# ----------------------------------------------------------- chunk store
#
# Staged-chunk persistence (the staging_cache/ingest-cache v3 discipline,
# docs/ROBUSTNESS.md): one npz per chunk written atomically, a CRC32-
# carrying ``.ok`` commit marker per chunk written after it, and a
# ``meta.json`` completion record written LAST. The payload round-trips
# BIT-stable for every storage dtype (the int8 codes and their scale
# vectors are exact bytes — quantization happens once, at staging). A
# chunk whose bytes fail the committed CRC (bit rot, a torn write, an
# injected ``stream.quantize`` fault) degrades to a re-stage of exactly
# that chunk via the caller's ``rebuild`` hook — never a silently wrong
# objective, never a whole-stream restage.

CHUNK_STORE_VERSION = 1
_CHUNK_FIELDS = ("X_hot", "hot_cols", "cold_cols", "cold_vals", "labels",
                 "weights", "offsets", "hot_scale", "cold_scale")


class ChunkStoreError(RuntimeError):
    """A persisted chunk stream that cannot be served and cannot be
    rebuilt (no ``rebuild`` hook was provided)."""


def save_chunked(directory: str, chunked: ChunkedHybrid) -> None:
    """Persist a staged ``ChunkedHybrid`` under ``directory``."""
    import json
    import os

    from photon_ml_tpu.utils.diskio import atomic_write, file_crc32

    os.makedirs(directory, exist_ok=True)
    for i, ch in enumerate(chunked.chunks):
        path = os.path.join(directory, f"chunk_{i}.npz")
        arrays = {name: np.asarray(getattr(ch, name))
                  for name in _CHUNK_FIELDS
                  if getattr(ch, name) is not None}
        atomic_write(path, lambda f, _a=arrays: np.savez(f, **_a))
        crc = file_crc32(path)
        # Injected bit rot lands AFTER the checksum was taken over the
        # good bytes — the torn-page/bit-rot shape the CRC must catch.
        flt.corrupt_file(flt.sites.STREAM_QUANTIZE, path, index=i)
        marker = json.dumps({
            "version": CHUNK_STORE_VERSION, "crc": crc,
            "fields": sorted(arrays),
            "num_features": int(ch.num_features)}).encode()
        atomic_write(os.path.join(directory, f"chunk_{i}.ok"),
                     lambda f, _m=marker: f.write(_m))
    meta = json.dumps({
        "version": CHUNK_STORE_VERSION, "num_rows": int(chunked.num_rows),
        "chunk_rows": int(chunked.chunk_rows),
        "num_chunks": int(chunked.num_chunks),
        "dtype": chunk_dtype(chunked.chunks[0])}).encode()
    atomic_write(os.path.join(directory, "meta.json"),
                 lambda f: f.write(meta))


def _load_stored_chunk(directory: str, i: int) -> Optional[CanonicalChunk]:
    """One committed chunk, or None on any miss (no marker, version
    skew, CRC mismatch, unreadable npz) — the caller degrades to a
    single-chunk re-stage."""
    import json
    import os

    from photon_ml_tpu.utils.diskio import file_crc32

    path = os.path.join(directory, f"chunk_{i}.npz")
    try:
        with open(os.path.join(directory, f"chunk_{i}.ok")) as f:
            marker = json.load(f)
        if marker.get("version") != CHUNK_STORE_VERSION:
            return None
        got = file_crc32(path)
        if got != int(marker["crc"]):
            logger.warning(
                "chunk store entry %s is corrupt (crc %08x != committed "
                "%08x) — re-staging exactly this chunk", path, got,
                int(marker["crc"]))
            return None
        with np.load(path, allow_pickle=False) as z:
            arrays = {name: z[name] for name in marker["fields"]}
        return CanonicalChunk(
            num_features=int(marker["num_features"]),
            **{name: arrays.get(name) for name in _CHUNK_FIELDS})
    except Exception:
        logger.debug("chunk store miss for chunk %d under %s",
                     i, directory, exc_info=True)
        return None


def load_chunked(directory: str, rebuild=None) -> ChunkedHybrid:
    """Load a persisted chunk stream; a chunk that fails its CRC (or is
    missing) re-stages through ``rebuild(i) -> CanonicalChunk`` —
    exactly that chunk, bit-identical to a fresh staging pass — or
    raises :class:`ChunkStoreError` when no hook was given."""
    import json
    import os

    with open(os.path.join(directory, "meta.json")) as f:
        meta = json.load(f)
    if meta.get("version") != CHUNK_STORE_VERSION:
        raise ChunkStoreError(
            f"chunk store {directory} is version {meta.get('version')}, "
            f"expected {CHUNK_STORE_VERSION}")
    chunks = []
    for i in range(int(meta["num_chunks"])):
        ch = _load_stored_chunk(directory, i)
        if ch is None:
            if rebuild is None:
                raise ChunkStoreError(
                    f"chunk {i} of {directory} is missing or corrupt and "
                    f"no rebuild hook was provided")
            ch = rebuild(i)
        chunks.append(ch)
    return ChunkedHybrid(chunks=tuple(chunks),
                         num_rows=int(meta["num_rows"]),
                         chunk_rows=int(meta["chunk_rows"]))


def make_value_and_gradient(
    loss: PointwiseLoss,
    chunked: ChunkedHybrid,
    prefetch_depth: int = 2,
    pinned=(),
) -> Callable[[Array, Optional[Array]], tuple[Array, Array]]:
    """Streamed Σ-over-chunks (value, gradient) in original column space.

    The returned callable is HOST-DRIVEN (a Python loop dispatching one
    jitted pass per chunk) — it cannot be traced into an outer jit; pair
    it with the host-driven optimizer in ``optim/streaming.py``.
    ``offsets``, when given, is the full (padded_n,) device array of
    per-row offsets (coordinate-descent residuals); None uses the offsets
    staged in each chunk. ``pinned`` (from :func:`pin_chunks`) skips the
    host→device transfer for the leading chunks.
    """
    kernel = _chunk_value_grad(loss, chunk_dtype(chunked.chunks[0]))

    def value_and_grad(w: Array, offsets: Optional[Array] = None):
        with obs.span("stream.pass", cat="stream", kind="value_grad",
                      chunks=chunked.num_chunks):
            return _vg_pass(w, offsets)

    def _vg_pass(w: Array, offsets: Optional[Array]):
        value = jnp.zeros((), jnp.float32)
        grad = jnp.zeros((chunked.dim,), jnp.float32)
        for i, ch in enumerate(_stream(chunked, prefetch_depth, pinned)):
            v, g = kernel(w, _offsets_for(chunked, offsets, i, ch), ch)
            value = value + v
            grad = grad + g
            # Barrier per chunk: the runtime holds every enqueued
            # program's scratch from ENQUEUE time, and a full unsynced
            # pass over the stream exhausts HBM at scale (measured: the
            # 100M-row run died on its first evaluation). The next
            # chunk's host→device copy is already in flight (_stream
            # prefetch), so the barrier costs one tunnel round trip per
            # chunk against a transfer-bound pass.
            jax.block_until_ready(grad)
            _release(ch, i, pinned)
        # Lazily-freed transfer buffers accumulate across evaluations
        # (measured: the 100M-row run's host RSS climbed ~60 GB over 11
        # L-BFGS iterations until the OOM killer fired); one collection
        # per heavyweight pass keeps the pool bounded (gated on bytes —
        # see GC_STREAM_BYTES_FLOOR).
        _collect_after_pass(chunked)
        return value, grad

    return value_and_grad


def make_value_only(
    loss: PointwiseLoss,
    chunked: ChunkedHybrid,
    prefetch_depth: int = 2,
    pinned=(),
) -> Callable[[Array, Optional[Array]], Array]:
    """Streamed Σ-over-chunks VALUE in original column space — the
    line-search probe companion of :func:`make_value_and_gradient` (same
    streaming discipline: prefetch, per-chunk barrier, eager release)."""
    kernel = _chunk_value(loss, chunk_dtype(chunked.chunks[0]))

    def value_only(w: Array, offsets: Optional[Array] = None):
        with obs.span("stream.pass", cat="stream", kind="value_only",
                      chunks=chunked.num_chunks):
            return _v_pass(w, offsets)

    def _v_pass(w: Array, offsets: Optional[Array]):
        value = jnp.zeros((), jnp.float32)
        for i, ch in enumerate(_stream(chunked, prefetch_depth, pinned)):
            v = kernel(w, _offsets_for(chunked, offsets, i, ch), ch)
            value = value + v
            jax.block_until_ready(value)  # same enqueue-scratch barrier
            _release(ch, i, pinned)
        _collect_after_pass(chunked)
        return value

    return value_only


def _release(ch, i: int, pinned) -> None:
    """Drop a STREAMED chunk's device buffers eagerly — reference-count
    laziness is what let per-eval transfer buffers pile up on host."""
    if i < len(pinned):
        return
    _delete_chunk(ch)


def margins_chunked(
    chunked: ChunkedHybrid,
    w: Array,
    offsets: Optional[Array] = None,
    prefetch_depth: int = 2,
    pinned=(),
) -> Array:
    """(num_rows,) margins (wᵀx + offset), streamed; pad rows dropped."""
    with obs.span("stream.pass", cat="stream", kind="margins",
                  chunks=chunked.num_chunks):
        return _margins_pass(chunked, w, offsets, prefetch_depth, pinned)


def _margins_pass(chunked, w, offsets, prefetch_depth, pinned) -> Array:
    parts = []
    for i, ch in enumerate(_stream(chunked, prefetch_depth, pinned)):
        parts.append(_margins_kernel(
            w, _offsets_for(chunked, offsets, i, ch), ch))
        jax.block_until_ready(parts[-1])  # same enqueue-scratch barrier
        _release(ch, i, pinned)
    _collect_after_pass(chunked)
    z = jnp.concatenate(parts)
    return z[:chunked.num_rows]


# ------------------------------------------------------- sharded streaming
#
# The multi-chip composition (ROADMAP item 1, the reference's
# ``treeAggregate`` shape): chunk ranges partition over the mesh's
# ``data`` axis, each device streams ITS range with the same
# double-buffered prefetch + per-round barrier discipline as the
# single-device path, and per-device partial (value, gradient) merge via
# ``psum`` over ICI/DCN — the host-driven L-BFGS in optim/streaming.py
# sees one global objective exactly as photon-api's Breeze driver loop
# sees one treeAggregate result. Snap ML's local-compute/global-merge
# hierarchy and Trofimov–Genkin's distributed GLM descent (PAPERS.md)
# are the same decomposition.


def shard_chunk_ranges(num_chunks: int, num_devices: int
                       ) -> list[tuple[int, int]]:
    """Contiguous, balanced [lo, hi) chunk ranges, one per device.

    Contiguous (not round-robin) so each device's offsets slice is one
    block of the global (padded_n,) residual array and the short padded
    tail chunk stays on the LAST device — the pad-rows-at-stream-tail
    invariant holds per device.

    A pure function of ``(num_chunks, num_devices)`` — nothing about
    the assignment is persisted anywhere. That is the elastic-resume
    contract (docs/STREAMING.md): a StreamingStateStore snapshot
    carries only device-count-free driver state, and the ranges are
    re-derived HERE on every construction, so a fit checkpointed at D
    devices resumes at D′ ≠ D with re-sharded ranges."""
    if num_devices < 1:
        raise ValueError(f"num_devices must be >= 1, got {num_devices}")
    base, rem = divmod(num_chunks, num_devices)
    ranges = []
    lo = 0
    for k in range(num_devices):
        hi = lo + base + (1 if k < rem else 0)
        ranges.append((lo, hi))
        lo = hi
    return ranges


def data_axis_devices(mesh) -> list:
    """The mesh's devices along ``data`` (streaming does not feature-
    shard, so a model axis > 1 is a config error, not a silent drop)."""
    from photon_ml_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

    if mesh.shape[MODEL_AXIS] != 1:
        raise ValueError(
            f"streaming shards rows over the '{DATA_AXIS}' axis only; "
            f"mesh has {MODEL_AXIS}={mesh.shape[MODEL_AXIS]} (feature-"
            f"sharded streaming is not supported — use the device-"
            f"resident feature-sharded path)")
    return list(np.asarray(mesh.devices).reshape(-1))


_MERGE_FNS: dict = {}


def _merge_fn(mesh):
    """shard_map psum merge of per-device partials: (D,) values and
    (D, d) gradients sharded over ``data`` → replicated global sums.
    This IS the treeAggregate reduction, riding ICI within a slice and
    DCN across slices; cached per mesh (one compile per topology)."""
    import functools

    from jax.sharding import PartitionSpec as P

    from photon_ml_tpu.parallel.mesh import DATA_AXIS, shard_map

    cached = _MERGE_FNS.get(mesh)
    if cached is not None:
        _count_kernel_hit("stream_psum_merge", "float32")
        return cached
    # The merge reduces f32 partials regardless of chunk storage dtype.
    _count_kernel_build("stream_psum_merge", "float32")

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS, None)),
        out_specs=(P(), P()))
    def _merge(v, g):
        return (jax.lax.psum(jnp.sum(v), DATA_AXIS),
                jax.lax.psum(jnp.sum(g, axis=0), DATA_AXIS))

    # jit so the merge compiles once per (mesh, shape) instead of
    # re-tracing on every objective evaluation.
    merged = jax.jit(_merge)
    _MERGE_FNS[mesh] = merged
    return merged


class ShardedChunkStream:
    """Multi-device streamed aggregates over one ``ChunkedHybrid``.

    Each data-axis device owns a contiguous chunk range and streams it
    through its own prefetch queue; every objective evaluation runs the
    per-chunk kernel round-robin across devices (so D transfers/computes
    are in flight at once) with ONE dispatch barrier per round — the
    multi-device analogue of the single-device per-chunk barrier, holding
    at most D chunks of enqueue scratch. Per-device partials merge via
    the psum program of :func:`_merge_fn`.

    ``pin_device_chunks`` pins that many LEADING chunks of each device's
    range on that device (the per-device share of the spare-HBM budget).

    A 1-device mesh reproduces the single-device path bit-for-bit: same
    kernel, same chunk order, same accumulation order; the psum over a
    singleton axis is the identity.
    """

    def __init__(self, chunked: ChunkedHybrid, mesh,
                 prefetch_depth: int = 2, pin_device_chunks: int = 0):
        if prefetch_depth < 1:
            raise ValueError(
                f"prefetch_depth must be >= 1, got {prefetch_depth}")
        self.chunked = chunked
        self.mesh = mesh
        self.devices = data_axis_devices(mesh)
        self.ranges = shard_chunk_ranges(chunked.num_chunks,
                                         len(self.devices))
        self.prefetch_depth = prefetch_depth
        # Per-device pinned leading chunks (resident once, streamed never).
        self._pinned = []
        for dev, (lo, hi) in zip(self.devices, self.ranges):
            n_pin = min(max(0, pin_device_chunks), hi - lo)
            self._pinned.append(tuple(
                jax.device_put(chunked.chunks[lo + j], dev)
                for j in range(n_pin)))
        # Offsets split cache: id(offsets) → per-device offset blocks.
        # train_model calls the objective many times with the SAME
        # residual array; splitting once per residual keeps the per-pass
        # transfer at exactly the chunk payloads.
        self._off_cache: tuple = (None, None)

    @property
    def num_devices(self) -> int:
        return len(self.devices)

    # -- per-device plumbing ----------------------------------------------

    def _stream_range(self, k: int):
        """Yield (global chunk index, device-resident chunk, streamed?)
        for device k's range, prefetch_depth transfers ahead."""
        import collections

        lo, hi = self.ranges[k]
        dev = self.devices[k]
        pinned = self._pinned[k]
        for j, ch in enumerate(pinned):
            yield lo + j, ch, False
        q: collections.deque = collections.deque()
        it = iter(range(lo + len(pinned), hi))
        try:
            for _ in range(self.prefetch_depth):
                i = next(it)
                q.append((i, _transfer(self.chunked.chunks[i], i, dev)))
        except StopIteration:
            pass
        while q:
            i, ready = q.popleft()
            try:
                j = next(it)
                q.append((j, _transfer(self.chunked.chunks[j], j, dev)))
            except StopIteration:
                pass
            yield i, ready, True

    def _offsets_by_device(self, offsets: Optional[Array]):
        """Split the full (padded_n,) residual array into per-device
        blocks, placed once (cached on the array's identity)."""
        if offsets is None:
            return None
        key, cached = self._off_cache
        if key is not None and key is offsets:
            return cached
        rows = self.chunked.chunk_rows
        host = np.asarray(offsets, np.float32)
        per_dev = []
        for dev, (lo, hi) in zip(self.devices, self.ranges):
            block = host[lo * rows: hi * rows]
            per_dev.append(jax.device_put(jnp.asarray(block), dev)
                           if block.size else None)
        self._off_cache = (offsets, per_dev)
        return per_dev

    def _chunk_offsets(self, per_dev, k: int, i: int, ch: CanonicalChunk):
        if per_dev is None:
            return ch.offsets if isinstance(ch.offsets, jax.Array) \
                else jnp.asarray(ch.offsets)
        lo = self.ranges[k][0]
        return jax.lax.dynamic_slice_in_dim(
            per_dev[k], (i - lo) * self.chunked.chunk_rows,
            self.chunked.chunk_rows, 0)

    def _round_robin(self, w: Array, offsets: Optional[Array],
                     dispatch, accs):
        """Drive every device's stream one chunk per round; barrier per
        round on each touched accumulator, then release streamed chunks
        (the enqueue-scratch bound, held at ≤ D in-flight chunks)."""
        per_dev = self._offsets_by_device(offsets)
        w = jnp.asarray(w, jnp.float32)
        w_dev = [jax.device_put(w, dev) for dev in self.devices]
        streams = [self._stream_range(k) for k in range(self.num_devices)]
        live = [True] * self.num_devices
        while any(live):
            touched = []
            for k in range(self.num_devices):
                if not live[k]:
                    continue
                item = next(streams[k], None)
                if item is None:
                    live[k] = False
                    continue
                i, ch, streamed = item
                off = self._chunk_offsets(per_dev, k, i, ch)
                dispatch(k, w_dev[k], off, ch)
                touched.append((ch, streamed))
            if touched:
                # One barrier per round: the runtime holds every enqueued
                # program's scratch from ENQUEUE time (the 100M lesson) —
                # blocking on each touched device's accumulator caps the
                # un-executed queue at one chunk per device.
                for k in range(self.num_devices):
                    if accs[k] is not None:
                        jax.block_until_ready(accs[k])
                for ch, streamed in touched:
                    if streamed:
                        _delete_chunk(ch)
        # The single-device transfer-buffer lesson, per pass (gated on
        # bytes: heavyweight streams collect, test-scale ones skip).
        _collect_after_pass(self.chunked)

    # -- streamed aggregates ----------------------------------------------

    def value_and_gradient(self, loss: PointwiseLoss):
        """(w, offsets) → replicated global (value, gradient): each
        device streams its range, partials psum-merge (treeAggregate)."""
        kernel = _chunk_value_grad(loss,
                                   chunk_dtype(self.chunked.chunks[0]))
        d = self.chunked.dim
        merge = _merge_fn(self.mesh)

        def vg(w: Array, offsets: Optional[Array] = None):
            with obs.span("stream.pass", cat="stream", kind="value_grad",
                          chunks=self.chunked.num_chunks,
                          devices=self.num_devices):
                return _vg(w, offsets)

        def _vg(w: Array, offsets: Optional[Array]):
            vals = [jax.device_put(jnp.zeros((1,), jnp.float32), dev)
                    for dev in self.devices]
            grads = [jax.device_put(jnp.zeros((1, d), jnp.float32), dev)
                     for dev in self.devices]

            def dispatch(k, w_k, off, ch):
                v, g = kernel(w_k, off, ch)
                vals[k] = vals[k] + v
                grads[k] = grads[k] + g

            self._round_robin(w, offsets, dispatch, grads)
            with obs.span("stream.psum_merge", cat="compute",
                          devices=self.num_devices):
                value, grad = merge(self._global(vals, (1,)),
                                    self._global(grads, (1, d)))
            # The replicated results re-commit to the lead device so the
            # driver loop's jitted helpers (single-device history math)
            # can mix them with their own state freely.
            return (jax.device_put(value, self.devices[0]),
                    jax.device_put(grad, self.devices[0]))

        return vg

    def value_only(self, loss: PointwiseLoss):
        """(w, offsets) → global value — the Armijo-probe pass."""
        kernel = _chunk_value(loss, chunk_dtype(self.chunked.chunks[0]))
        merge = _merge_fn(self.mesh)
        d = self.chunked.dim

        def v_fn(w: Array, offsets: Optional[Array] = None):
            with obs.span("stream.pass", cat="stream", kind="value_only",
                          chunks=self.chunked.num_chunks,
                          devices=self.num_devices):
                return _v(w, offsets)

        def _v(w: Array, offsets: Optional[Array]):
            vals = [jax.device_put(jnp.zeros((1,), jnp.float32), dev)
                    for dev in self.devices]
            zeros = [jax.device_put(jnp.zeros((1, 1), jnp.float32), dev)
                     for dev in self.devices]

            def dispatch(k, w_k, off, ch):
                vals[k] = vals[k] + kernel(w_k, off, ch)

            self._round_robin(w, offsets, dispatch, vals)
            with obs.span("stream.psum_merge", cat="compute",
                          devices=self.num_devices):
                value, _ = merge(self._global(vals, (1,)),
                                 self._global(zeros, (1, 1)))
            return jax.device_put(value, self.devices[0])

        return v_fn

    def margins(self, w: Array, offsets: Optional[Array] = None) -> Array:
        """(num_rows,) margins in global row order (pad tail dropped).
        Parts come home per chunk (scoring runs once per coordinate
        update; the pass is transfer-bound either way)."""
        with obs.span("stream.pass", cat="stream", kind="margins",
                      chunks=self.chunked.num_chunks,
                      devices=self.num_devices):
            return self._margins_pass(w, offsets)

    def _margins_pass(self, w: Array, offsets: Optional[Array]) -> Array:
        parts: dict[int, np.ndarray] = {}
        per_dev = self._offsets_by_device(offsets)
        w32 = jnp.asarray(w, jnp.float32)
        w_dev = [jax.device_put(w32, dev) for dev in self.devices]
        streams = [self._stream_range(k) for k in range(self.num_devices)]
        live = [True] * self.num_devices
        while any(live):
            released = []
            for k in range(self.num_devices):
                if not live[k]:
                    continue
                item = next(streams[k], None)
                if item is None:
                    live[k] = False
                    continue
                i, ch, streamed = item
                off = self._chunk_offsets(per_dev, k, i, ch)
                z = _margins_kernel(w_dev[k], off, ch)
                jax.block_until_ready(z)  # per-chunk barrier + host copy
                # pml: allow[PML001] score-pass reassembly is BY-DESIGN a per-chunk host copy (global row order spans devices); scoring runs once per coordinate update on a transfer-bound pass
                parts[i] = np.asarray(z)
                if streamed:
                    released.append(ch)
            for ch in released:
                _delete_chunk(ch)
        _collect_after_pass(self.chunked)
        z = np.concatenate([parts[i] for i in range(len(parts))])
        return jnp.asarray(z[:self.chunked.num_rows])

    def _global(self, per_dev: list, local_shape: tuple):
        """Assemble per-device partials into one data-sharded global
        array (the psum merge's input layout)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from photon_ml_tpu.parallel.mesh import DATA_AXIS

        D = self.num_devices
        shape = (D * local_shape[0],) + local_shape[1:]
        sharding = NamedSharding(
            self.mesh, P(DATA_AXIS, *(None,) * (len(local_shape) - 1)))
        return jax.make_array_from_single_device_arrays(
            shape, sharding, per_dev)
