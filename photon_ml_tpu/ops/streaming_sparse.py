"""Row-streamed sparse GLM aggregates: the Criteo row axis on one chip.

Reference parity: photon-api ``DistributedGLMLossFunction`` computes each
value/gradient as one Spark pass over RDD partitions (``treeAggregate``) —
the n axis never has to fit on any single executor. This module is the
TPU-native equivalent: the example rows live on HOST in fixed-size chunks
staged into a hot-dense/cold-class layout (the ``ops/hybrid_sparse.py``
design), and every objective evaluation streams them through the chip
with double-buffered host→device prefetch, accumulating ``(value,
gradient)`` in f32 on device. HBM holds at most ``prefetch_depth`` chunks
plus the accumulators, so n is bounded by host RAM (or disk, via the
chunk iterator), not by the 16 GB of one chip.

**Canonical chunk structure — one compiled program for the whole stream.**
Each jit specialization is a multi-minute remote compile in this
environment, so chunks must share ONE program. Chunk layouts are
therefore canonicalized:

  * the hot block is EXACTLY ``num_hot`` columns (the chunk's top-k by
    count — the hot/cold split is a free execution choice, any split is
    the same objective);
  * cold columns group into power-of-two count classes as in
    hybrid_sparse, and each class's column count is padded UP to a power
    of two with dummy columns (all-pad rowids — inert);
  * dummy hot/cold slots map to an EXTENDED permuted space: ``perm`` is
    (D',) with dummies pointing at the sentinel column ``d`` (so
    ``w_pad[perm]`` reads 0 for them), and ``inv`` maps every original
    column to its extended slot (absent columns → slot D', a reserved
    zero) so gradients come back to original space by pure GATHER — no
    d-sized scatter per chunk.

Chunks are iid rows of one distribution, so the quantized shapes collide
across chunks with overwhelming probability; a chunk that still differs
merely triggers one extra compile (logged by ``build_chunked``).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Iterable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.ops.hybrid_sparse import _hot_matvec, _hot_rmatvec
from photon_ml_tpu.ops.losses import PointwiseLoss

Array = jax.Array


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class CanonicalChunk:
    """One chunk in the canonical hot/cold layout (leaves may be host
    numpy — device placement happens at stream time)."""

    X_hot: Array  # (n, H)
    cold_rowids: tuple[Array, ...]  # per class: (C_pad, L) int32, pad == n
    cold_vals: tuple[Array, ...]  # per class: (C_pad, L) f32, pad == 0
    labels: Array  # (n,)
    weights: Array  # (n,); 0 marks pad rows of a short final chunk
    offsets: Array  # (n,)
    perm: Array  # (D',) int32: extended slot -> original col (dummy == d)
    inv: Array  # (d,) int32: original col -> extended slot (absent == D')
    num_features: int = dataclasses.field(metadata=dict(static=True))
    num_hot: int = dataclasses.field(metadata=dict(static=True))
    # Extended-space offset of each class (0 == first slot after hot).
    class_starts: tuple[int, ...] = dataclasses.field(
        metadata=dict(static=True))

    @property
    def num_rows(self) -> int:
        return self.labels.shape[0]

    def structure(self):
        """Shape signature — equal signatures share one compiled program."""
        return (self.X_hot.shape, self.num_hot,
                tuple(r.shape for r in self.cold_rowids),
                self.class_starts)


@dataclasses.dataclass(frozen=True)
class ChunkedHybrid:
    """Host-resident chunked layout of one logical (n, d) batch.

    Equal row counts per chunk (short final chunk padded with weight-0
    rows — inert in every aggregate; their margins are dropped by
    ``margins_chunked``). ``num_rows`` is the REAL row count.
    """

    chunks: tuple[CanonicalChunk, ...]
    num_rows: int
    chunk_rows: int

    @property
    def dim(self) -> int:
        return self.chunks[0].num_features

    @property
    def num_chunks(self) -> int:
        return len(self.chunks)


def plan_num_hot(chunk_rows: int, hot_block_bytes: int,
                 feature_dtype) -> int:
    """Hot-block width that fits the byte budget: at streaming scale the
    binding constraint is HBM (block bytes = chunk_rows × H × dtype),
    not the throughput-optimal split of hybrid_sparse."""
    bytes_per = 2 if feature_dtype == jnp.bfloat16 else 4
    return max(8, int(hot_block_bytes) // (chunk_rows * bytes_per))


def _next_pow2(x: int) -> int:
    return 1 << max(int(x) - 1, 0).bit_length()


def _build_canonical(raw, d: int, num_hot: int, feature_dtype,
                     min_class_cols: int = 8) -> CanonicalChunk:
    """Stage one ELL chunk into the canonical layout (host numpy)."""
    indices = np.asarray(raw.indices)
    values = np.asarray(raw.values)
    n = indices.shape[0]

    flat_col = indices.reshape(-1)
    flat_row = np.repeat(np.arange(n, dtype=np.int32), indices.shape[1])
    flat_val = values.reshape(-1)
    live = (flat_col < d) & (flat_val != 0.0)
    counts = np.bincount(flat_col[live], minlength=d)
    order_desc = np.argsort(-counts, kind="stable").astype(np.int32)

    H = num_hot
    hot_cols = order_desc[:H]  # top-H by count (some may be count 0)
    hot_live = counts[hot_cols] > 0

    # inv_new: original col -> extended slot (filled as we lay out).
    slot_of = np.full(d + 1, -1, np.int64)
    slot_of[hot_cols] = np.arange(H)

    new_col = slot_of[np.minimum(flat_col, d)]
    X_hot = np.zeros((n, H), np.float32)
    hot_sel = live & (new_col >= 0)
    X_hot[flat_row[hot_sel], new_col[hot_sel]] = flat_val[hot_sel]

    # Cold columns: count-desc after the hot set, pow-2 count classes.
    cold_cols = order_desc[H:]
    cold_counts = counts[cold_cols]
    present = int((cold_counts > 0).sum())
    cold_cols = cold_cols[:present]
    cold_counts = cold_counts[:present]

    cold_sel = live & (new_col < 0)
    c_col = flat_col[cold_sel]
    c_row = flat_row[cold_sel]
    c_val = flat_val[cold_sel]
    # Column-contiguous cold stream (count-desc order of cold columns).
    rank_of = np.full(d, np.iinfo(np.int64).max, np.int64)
    rank_of[cold_cols] = np.arange(present)
    order = np.argsort(rank_of[c_col], kind="stable")
    c_row, c_val = c_row[order], c_val[order]
    col_start = np.concatenate(
        [[0], np.cumsum(cold_counts)[:-1]]).astype(np.int64)

    rowids_cls: list[np.ndarray] = []
    vals_cls: list[np.ndarray] = []
    class_starts: list[int] = []
    perm_cold: list[np.ndarray] = []
    ext_off = 0
    if present:
        cls = np.ceil(np.log2(np.maximum(cold_counts, 1))).astype(np.int64)
        for kk in np.unique(cls)[::-1]:
            sel = np.flatnonzero(cls == kk)
            L = 1 << int(kk)
            C = sel.size
            C_pad = max(_next_pow2(C), min_class_cols)
            rp = np.full((C_pad, L), n, np.int32)
            vp = np.zeros((C_pad, L), np.float32)
            starts = col_start[sel]
            cnts = cold_counts[sel].astype(np.int64)
            total = int(cnts.sum())
            colpos = np.arange(total) - np.repeat(
                np.concatenate([[0], np.cumsum(cnts)[:-1]]), cnts)
            src = np.repeat(starts, cnts) + colpos
            crow = np.repeat(np.arange(C, dtype=np.int64), cnts)
            rp[crow, colpos] = c_row[src]
            vp[crow, colpos] = c_val[src]
            rowids_cls.append(rp)
            vals_cls.append(vp)
            class_starts.append(ext_off)
            p = np.full(C_pad, d, np.int32)  # dummies -> sentinel col d
            p[:C] = cold_cols[sel]
            perm_cold.append(p)
            slot_of[cold_cols[sel]] = H + ext_off + np.arange(C)
            ext_off += C_pad

    hot_perm = np.where(hot_live, hot_cols, d).astype(np.int32)
    perm = np.concatenate([hot_perm] + perm_cold) if perm_cold \
        else hot_perm
    D = perm.shape[0]
    inv = np.where(slot_of[:d] >= 0, slot_of[:d], D).astype(np.int32)

    if feature_dtype == jnp.bfloat16:
        # Host-side cast halves the host→device stream — which IS the
        # steady-state cost of every streamed objective evaluation.
        # Cold values are storage like the hot block (products upcast to
        # f32 in-kernel), so they follow the same dtype contract.
        import ml_dtypes

        X_hot = X_hot.astype(ml_dtypes.bfloat16)
        vals_cls = [v.astype(ml_dtypes.bfloat16) for v in vals_cls]
    return CanonicalChunk(
        X_hot=X_hot,
        cold_rowids=tuple(rowids_cls),
        cold_vals=tuple(vals_cls),
        labels=np.asarray(raw.labels),
        weights=np.asarray(raw.weights),
        offsets=np.asarray(raw.offsets),
        perm=perm,
        inv=inv,
        num_features=d,
        num_hot=H,
        class_starts=tuple(class_starts),
    )


def build_chunked(
    chunk_iter: Iterable,
    num_features: int,
    chunk_rows: int,
    num_hot: int = 512,
    feature_dtype=jnp.float32,
    log: Callable[[str], None] = lambda m: None,
) -> ChunkedHybrid:
    """Stage a stream of ELL chunks into host-resident canonical layouts.

    ``chunk_iter`` yields objects with ``indices / values / labels /
    weights / offsets`` host arrays (``data/sparse.SparseBatch`` or any
    duck-typed source — the chunked Avro reader, a synthetic generator).
    Peak host memory beyond the staged output is ONE chunk."""
    num_hot = min(num_hot, num_features)
    chunks = []
    total = 0
    for i, raw in enumerate(chunk_iter):
        n_i = int(np.asarray(raw.labels).shape[0])
        if n_i > chunk_rows:
            raise ValueError(f"chunk {i} has {n_i} rows > chunk_rows="
                             f"{chunk_rows}")
        total += n_i
        if n_i < chunk_rows:
            raw = _pad_chunk(raw, chunk_rows, num_features)
        ch = _build_canonical(raw, num_features, num_hot, feature_dtype)
        chunks.append(ch)
        log(f"staged chunk {i} ({n_i:,} rows, {ch.perm.shape[0]} extended "
            f"cols, {len(ch.cold_rowids)} cold classes)")
    if not chunks:
        raise ValueError("empty chunk stream")
    # Reconcile to the UNION structure: pow-2 quantization alone flaps at
    # class boundaries between iid chunks, and every distinct structure
    # would be its own multi-minute remote compile. Pad each chunk's
    # classes up to the union (L → max C_pad over chunks; missing classes
    # appear as all-dummy) so the whole stream shares ONE program.
    union: dict[int, int] = {}
    for ch in chunks:
        for rows in ch.cold_rowids:
            C, L = rows.shape
            union[L] = max(union.get(L, 0), C)
    sigs = {ch.structure() for ch in chunks}
    if len(sigs) > 1 or any(
            dict((r.shape[1], r.shape[0]) for r in ch.cold_rowids) != union
            for ch in chunks):
        log(f"reconciling {len(sigs)} chunk structures to the union "
            f"({sorted(union.items(), reverse=True)})")
        chunks = [_repad_to(ch, union) for ch in chunks]
        assert len({ch.structure() for ch in chunks}) == 1
    return ChunkedHybrid(chunks=tuple(chunks), num_rows=total,
                         chunk_rows=chunk_rows)


def _repad_to(ch: CanonicalChunk, union: dict[int, int]) -> CanonicalChunk:
    """Pad a chunk's cold classes to the union structure (L desc order).
    Dummy columns: rowids == n (inert scatter/gather), vals 0, perm slot
    == d (reads the sentinel 0 coefficient); inv is rebuilt from perm."""
    n = ch.labels.shape[0]
    d = ch.num_features
    by_L = {r.shape[1]: (r, v)
            for r, v in zip(ch.cold_rowids, ch.cold_vals)}
    # Per-class perm slices of the ORIGINAL layout.
    perm = np.asarray(ch.perm)
    perm_by_L = {}
    off = ch.num_hot
    for r in ch.cold_rowids:
        C, L = r.shape
        perm_by_L[L] = perm[off: off + C]
        off += C
    rows_out, vals_out, perm_out, starts = [], [], [perm[:ch.num_hot]], []
    ext = 0
    for L in sorted(union, reverse=True):
        C_t = union[L]
        vdt = ch.cold_vals[0].dtype if ch.cold_vals else np.float32
        r, v = by_L.get(L, (np.full((0, L), n, np.int32),
                            np.zeros((0, L), vdt)))
        C = r.shape[0]
        if C < C_t:
            r = np.concatenate(
                [np.asarray(r), np.full((C_t - C, L), n, np.int32)])
            v = np.concatenate(
                [np.asarray(v), np.zeros((C_t - C, L), vdt)])
        p = np.full(C_t, d, np.int32)
        p[:C] = perm_by_L.get(L, np.zeros((0,), np.int32))
        rows_out.append(np.asarray(r))
        vals_out.append(np.asarray(v))
        perm_out.append(p)
        starts.append(ext)
        ext += C_t
    new_perm = np.concatenate(perm_out)
    D = new_perm.shape[0]
    inv = np.full(d, D, np.int32)
    real = new_perm < d
    inv[new_perm[real]] = np.flatnonzero(real).astype(np.int32)
    return dataclasses.replace(
        ch, cold_rowids=tuple(rows_out), cold_vals=tuple(vals_out),
        perm=new_perm, inv=inv, class_starts=tuple(starts))


def _pad_chunk(raw, chunk_rows: int, d: int):
    """Pad a short (final) chunk with weight-0 rows: every aggregate
    multiplies by weight before reducing, so pad rows add exactly 0 to
    value/gradient, and their margins are dropped by
    ``margins_chunked``."""
    from photon_ml_tpu.data.sparse import SparseBatch

    idx = np.asarray(raw.indices)
    n_i, nnz = idx.shape
    pad = chunk_rows - n_i

    def pad0(a):
        a = np.asarray(a)
        out = np.zeros((chunk_rows,) + a.shape[1:], a.dtype)
        out[:n_i] = a
        return out

    idx_p = np.full((chunk_rows, nnz), d, np.int32)
    idx_p[:n_i] = idx
    return SparseBatch(
        indices=idx_p, values=pad0(raw.values), labels=pad0(raw.labels),
        weights=pad0(raw.weights), offsets=pad0(raw.offsets),
        num_features=d)


# ---------------------------------------------------------------- kernels


def _masked(weights: Array, term: Array) -> Array:
    return jnp.where(weights > 0.0, weights * term, 0.0)


def _ext_coefficients(ch: CanonicalChunk, w: Array) -> Array:
    """(D',) extended-space coefficients: dummies read the sentinel 0."""
    w_pad = jnp.concatenate([w, jnp.zeros((1,), w.dtype)])
    return w_pad[ch.perm]


def _chunk_margins_ext(ch: CanonicalChunk, w_ext: Array,
                       offsets: Array) -> Array:
    n = ch.labels.shape[0]
    z = offsets + _hot_matvec(ch.X_hot, w_ext[:ch.num_hot])
    if ch.cold_rowids:
        parts = []
        for start, rows, vals in zip(ch.class_starts, ch.cold_rowids,
                                     ch.cold_vals):
            C = rows.shape[0]
            w_c = w_ext[ch.num_hot + start: ch.num_hot + start + C]
            parts.append((w_c[:, None] * vals).reshape(-1))
        flat_rows = jnp.concatenate(
            [r.reshape(-1) for r in ch.cold_rowids])
        acc = jnp.zeros((n + 1,), jnp.float32).at[flat_rows].add(
            jnp.concatenate(parts))
        z = z + acc[:n]
    return z


def _chunk_rowterm_grad(ch: CanonicalChunk, r: Array) -> Array:
    """Σᵢ rᵢ·xᵢ in ORIGINAL space, via the extended layout + one gather."""
    parts = [_hot_rmatvec(ch.X_hot, r).astype(jnp.float32)]
    if ch.cold_rowids:
        r_pad = jnp.concatenate([r, jnp.zeros((1,), r.dtype)])
        flat_rows = jnp.concatenate(
            [rr.reshape(-1) for rr in ch.cold_rowids])
        gathered = r_pad[flat_rows]
        off = 0
        for rows, vals in zip(ch.cold_rowids, ch.cold_vals):
            C, L = rows.shape
            ru = gathered[off: off + C * L].reshape(C, L)
            parts.append(jnp.sum(ru * vals, axis=1))
            off += C * L
    g_ext = jnp.concatenate(parts)
    g_ext = jnp.concatenate([g_ext, jnp.zeros((1,), jnp.float32)])
    return g_ext[ch.inv]  # absent cols hit the reserved zero slot


# Kernels are cached per loss (and the margins kernel is a singleton):
# a fresh @jax.jit wrapper per call would re-trace the chunk program on
# every coordinate-descent update — exactly the repeated remote compile
# the canonical structure exists to avoid.
_VG_KERNELS: dict = {}


def _chunk_value_grad(loss: PointwiseLoss):
    """One jitted per-chunk pass: original-space w in, original-space
    (value, grad) out — shared by every chunk with the same canonical
    structure."""
    f = _VG_KERNELS.get(loss.name)
    if f is not None:
        return f

    @jax.jit
    def f(w: Array, offsets: Array, ch: CanonicalChunk):
        w_ext = _ext_coefficients(ch, w)
        z = _chunk_margins_ext(ch, w_ext, offsets)
        l, dl = loss.loss_and_dz(z, ch.labels)
        value = jnp.sum(_masked(ch.weights, l))
        r = _masked(ch.weights, dl)
        return value, _chunk_rowterm_grad(ch, r)

    _VG_KERNELS[loss.name] = f
    return f


@jax.jit
def _margins_kernel(w: Array, offsets: Array, ch: CanonicalChunk):
    return _chunk_margins_ext(ch, _ext_coefficients(ch, w), offsets)


def _stream(chunked: ChunkedHybrid, depth: int, pinned=()):
    """Yield device-resident chunks with ``depth`` transfers in flight
    ahead of the consumer (same discipline as data/prefetch.py — the
    host→device copy of chunk i+1 overlaps the compute on chunk i).
    ``pinned`` are already-resident leading chunks (yielded as-is, no
    transfer)."""
    import collections

    for ch in pinned:
        yield ch
    q = collections.deque()
    it = iter(chunked.chunks[len(pinned):])
    try:
        for _ in range(depth):
            q.append(jax.device_put(next(it)))
    except StopIteration:
        pass
    while q:
        ready = q.popleft()
        try:
            q.append(jax.device_put(next(it)))
        except StopIteration:
            pass
        yield ready


def _offsets_for(chunked: ChunkedHybrid, offsets: Optional[Array], i: int,
                 ch: CanonicalChunk):
    if offsets is None:
        return ch.offsets if isinstance(ch.offsets, jax.Array) \
            else jnp.asarray(ch.offsets)
    lo = i * chunked.chunk_rows
    return jax.lax.dynamic_slice_in_dim(
        offsets, lo, chunked.chunk_rows, 0)


def pin_chunks(chunked: ChunkedHybrid, count: int):
    """Place the first ``count`` chunks on device permanently and return
    them — spare HBM traded for stream traffic (the steady-state cost of
    every objective evaluation drops by the pinned fraction). The caller
    owns the sizing decision: pinned bytes compete with whatever else
    the fit keeps resident (e.g. random-effect bucket blocks)."""
    return tuple(jax.device_put(ch)
                 for ch in chunked.chunks[:max(0, count)])


def make_value_and_gradient(
    loss: PointwiseLoss,
    chunked: ChunkedHybrid,
    prefetch_depth: int = 2,
    pinned=(),
) -> Callable[[Array, Optional[Array]], tuple[Array, Array]]:
    """Streamed Σ-over-chunks (value, gradient) in original column space.

    The returned callable is HOST-DRIVEN (a Python loop dispatching one
    jitted pass per chunk) — it cannot be traced into an outer jit; pair
    it with the host-driven optimizer in ``optim/streaming.py``.
    ``offsets``, when given, is the full (padded_n,) device array of
    per-row offsets (coordinate-descent residuals); None uses the offsets
    staged in each chunk. ``pinned`` (from :func:`pin_chunks`) skips the
    host→device transfer for the leading chunks.
    """
    kernel = _chunk_value_grad(loss)

    def value_and_grad(w: Array, offsets: Optional[Array] = None):
        value = jnp.zeros((), jnp.float32)
        grad = jnp.zeros((chunked.dim,), jnp.float32)
        for i, ch in enumerate(_stream(chunked, prefetch_depth, pinned)):
            v, g = kernel(w, _offsets_for(chunked, offsets, i, ch), ch)
            value = value + v
            grad = grad + g
        return value, grad

    return value_and_grad


def margins_chunked(
    chunked: ChunkedHybrid,
    w: Array,
    offsets: Optional[Array] = None,
    prefetch_depth: int = 2,
    pinned=(),
) -> Array:
    """(num_rows,) margins (wᵀx + offset), streamed; pad rows dropped."""
    parts = []
    for i, ch in enumerate(_stream(chunked, prefetch_depth, pinned)):
        parts.append(_margins_kernel(
            w, _offsets_for(chunked, offsets, i, ch), ch))
    z = jnp.concatenate(parts)
    return z[:chunked.num_rows]
