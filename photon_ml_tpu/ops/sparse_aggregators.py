"""Fused sparse (ELL) GLM aggregates: value/gradient, H·v, Hessian diag.

Reference parity: the same ``ValueAndGradientAggregator`` /
``HessianVectorAggregator`` contracts as ops/aggregators.py, but over sparse
batches — the reference's per-example loop over sparse Breeze vectors
(axpy into a dense gradient) becomes, per device:

    margins:  gather  w_pad[indices] · values, summed over slots
    gradient: scatter-add of (weight · dl) ⊗ values back into w-shape

The coefficient vector is padded with one trailing zero slot so ELL padding
(slot index == d) gathers 0 and scatters into a discarded column — no masks
anywhere in the hot path. Zero-weight (padded) ROWS are handled by the
weight mask exactly as in the dense aggregators.

Scatter-adds lower to XLA's sort+segment machinery on TPU; for small and
moderate coefficient dimensions the Pallas compare+accumulate kernel
(ops/kernels/ell_scatter.py, registry name ``ell_scatter``) wins — it is
O(d·nnz), so XLA's scatter takes over for large d. The dimension policy
below picks the CANDIDATE; whether the Pallas program actually runs is
the kernel registry's call (flag + backend), and a registry-level
degradation — flag on but no TPU, or an injected ``kernel.launch`` fault
— is LOUD (KernelFallback event + counter), unlike the silent
TPU-backend guard this module shipped with. Set ``USE_PALLAS`` to force
either path past the dimension policy (tests, benchmarks).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from photon_ml_tpu.data.sparse import SparseBatch
from photon_ml_tpu.ops.losses import PointwiseLoss

Array = jax.Array

# None = auto: Pallas kernel on TPU when dim <= _PALLAS_DIM_MAX, else XLA
# scatter. True/False force one path (tests, benchmarks).
USE_PALLAS: Optional[bool] = None
_PALLAS_DIM_MAX = 2048


def _w_padded(means: Array) -> Array:
    """(d,) -> (d+1,) with a zero sentinel slot for ELL padding."""
    return jnp.concatenate([means, jnp.zeros((1,), means.dtype)])


def ell_matvec(indices: Array, values: Array, means: Array) -> Array:
    """(n,) X @ w for ELL rows — THE sentinel gather-dot; every consumer
    of the ELL layout (objectives, model scoring) goes through here so the
    padding contract lives in one place."""
    w_pad = _w_padded(means)
    return jnp.sum(values * w_pad[indices], axis=-1)


def margins(batch: SparseBatch, means: Array) -> Array:
    """(n,) margins wᵀx + offset via slot gather."""
    return ell_matvec(batch.indices, batch.values, means) + batch.offsets


def _masked(weights: Array, term: Array) -> Array:
    return jnp.where(weights > 0.0, weights * term, 0.0)


def _scatter_rowterm(batch: SparseBatch, r: Array, dim: int) -> Array:
    """Σ_i r_i · x_i as a scatter-add of r ⊗ values into (d,).

    Dimension policy (is the O(d·nnz) kernel even a candidate?) lives
    here; backend policy (flag, TPU vs interpret vs loud XLA fallback)
    is the registry's. When the candidate check or the flag says XLA,
    the inline ``.at[].add`` runs untouched — zero registry traffic, so
    a flag-off process is byte-identical to the pre-registry tree."""
    upd = r[..., None] * batch.values
    use_pallas = USE_PALLAS
    if use_pallas is None or use_pallas:
        from photon_ml_tpu.ops import kernels
        reg = kernels.registry()
        if use_pallas is None:
            use_pallas = (dim <= _PALLAS_DIM_MAX
                          and reg.enabled("ell_scatter"))
        if use_pallas:
            return reg.resolve("ell_scatter")(batch.indices, upd, dim)
    flat = batch.indices.reshape(-1)
    return jnp.zeros((dim + 1,), upd.dtype).at[flat].add(
        upd.reshape(-1))[:dim]


def value_and_gradient(
    loss: PointwiseLoss,
    means: Array,
    batch: SparseBatch,
) -> tuple[Array, Array]:
    """(Σ w·l, Σ w·dl·x) — fused pass, one gather + one scatter."""
    z = margins(batch, means)
    l, dl = loss.loss_and_dz(z, batch.labels)
    value = jnp.sum(_masked(batch.weights, l), axis=-1)
    r = _masked(batch.weights, dl)
    return value, _scatter_rowterm(batch, r, batch.num_features)


def hessian_vector(
    loss: PointwiseLoss,
    means: Array,
    v: Array,
    batch: SparseBatch,
) -> Array:
    """Σ w·d2l·(x·v)·x — TRON's H·v without materializing H."""
    z = margins(batch, means)
    d2 = loss.d2z(z, batch.labels)
    v_pad = _w_padded(v)
    xv = jnp.sum(batch.values * v_pad[batch.indices], axis=-1)
    r = _masked(batch.weights, d2) * xv
    return _scatter_rowterm(batch, r, batch.num_features)


def hessian_diagonal(
    loss: PointwiseLoss,
    means: Array,
    batch: SparseBatch,
) -> Array:
    """diag(H) = Σ w·d2l·x² (SIMPLE variance mode)."""
    z = margins(batch, means)
    d2 = loss.d2z(z, batch.labels)
    r = _masked(batch.weights, d2)
    sq = SparseBatch(
        indices=batch.indices, values=batch.values * batch.values,
        labels=batch.labels, weights=batch.weights, offsets=batch.offsets,
        num_features=batch.num_features)
    return _scatter_rowterm(sq, r, batch.num_features)


def scores(batch: SparseBatch, means: Array,
           offsets: Optional[Array] = None) -> Array:
    s = margins(batch, means) - batch.offsets
    return s if offsets is None else s + offsets
