"""Hybrid hot-dense / cold-class sparse GLM aggregates (the Criteo path).

Reference parity: the same ``ValueAndGradientAggregator`` /
``HessianVectorAggregator`` contracts as ops/sparse_aggregators.py — but
restructured around how a TPU actually moves data.

Why: measured on one v5e chip, XLA's random 4M-element gather runs at
~0.14 Gelem/s and its scatter-add at ~0.16 G-updates/s, and a Mosaic
(8, 128)-window vector shuffle tops out at ~0.84 Gelem/s — so ANY exact
ELL step at d=1e6 pays two ~26 ms random crossings (expand w→entries,
reduce entries→gradient) and lands near 60 ms regardless of formulation
(plain scatter, pre-sorted segment-sum, one-hot matmul tiles, and
butterfly-routed permutations all measured within 1.1× of each other;
see docs/PARITY.md "sparse wall" notes). The only real lever is moving
fewer elements through the random path.

CTR feature spaces are Zipf-distributed: on the benchmark's zipf(1.3)
synthetic, the hottest ~1–2k of 1M columns carry ~85% of all nonzeros.
The hybrid split exploits that:

- **Hot columns** (count ≥ ``hot_threshold``, at most ``max_hot``) are
  densified into an (n, k) matrix: margins and gradient contributions are
  plain MXU matmuls (X_hot @ w, X_hotᵀ r) — the 85% of entries ride the
  365 M-samples/s dense path, with the multiply-by-zero waste costing
  bandwidth, not random access.
- **Cold columns** are relabeled into count-descending order (a static
  permutation of the feature space — the GLM objective is permutation-
  equivariant, so the solve happens in permuted space and maps back once
  per fit) and their entries stored column-contiguous in power-of-two
  count classes, padded (C, L) blocks:
  * margins: w broadcast per column (NO gather — columns are contiguous
    slices), one scatter-add of products by row — the only remaining
    crossing, now ~15% of the volume;
  * gradient: one gather r[rowids] (second crossing, same reduced
    volume), then padded row-sums per class and CONTIGUOUS writes into
    the permuted gradient — no scatter at all.

Pad slots carry rowid == n (a zero sentinel lane) and value 0, so they
are inert in every pass without masks. All layout arrays are static
(computed once at staging from the CSR/ELL structure); per optimizer
iteration only w changes.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.data.sparse import SparseBatch
from photon_ml_tpu.ops.losses import PointwiseLoss

Array = jax.Array


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class HybridSparseBatch:
    """Hot-dense + cold-class layout of one sparse example batch.

    The feature space is PERMUTED: new column order is
    [hot columns (count desc) | cold present columns (count desc) |
    absent columns]. ``perm`` maps new → original column ids;
    ``inv_perm`` maps original → new. Coefficient vectors seen by the
    ops here live in the permuted space.
    """

    X_hot: Array  # (n, k) dense hot block (k may be 0)
    cold_rowids: tuple[Array, ...]  # per class: (C, L) int32, pad == n
    cold_vals: tuple[Array, ...]  # per class: (C, L) f32, pad == 0
    labels: Array  # (n,)
    weights: Array  # (n,)
    offsets: Array  # (n,)
    perm: Array  # (d,) int32: new col -> original col
    inv_perm: Array  # (d,) int32: original col -> new col
    num_features: int = dataclasses.field(metadata=dict(static=True))
    num_hot: int = dataclasses.field(metadata=dict(static=True))
    # Per class: first permuted column id (hot block excluded) and count.
    class_starts: tuple[int, ...] = dataclasses.field(
        metadata=dict(static=True))

    @property
    def num_rows(self) -> int:
        return self.X_hot.shape[0] if self.num_hot else self.labels.shape[0]

    @property
    def dim(self) -> int:
        return self.num_features

    @property
    def num_cold_present(self) -> int:
        return sum(int(r.shape[0]) for r in self.cold_rowids)


def _default_hot_threshold(n: int, feature_dtype) -> int:
    """Dtype-aware hot/cold split point (see build_hybrid docstring): the
    f32 dense block pays 2× the bytes, so fewer columns should densify."""
    return max(8, n // (4096 if feature_dtype == jnp.bfloat16 else 2048))


def build_hybrid(
    batch: SparseBatch,
    hot_threshold: Optional[int] = None,
    max_hot: int = 4096,
    feature_dtype=jnp.float32,
    device: bool = True,
) -> HybridSparseBatch:
    """Stage an ELL SparseBatch into the hybrid layout (host-side, once).

    ``hot_threshold``: columns with at least this many nonzeros densify.
    The default is DTYPE-DEPENDENT (swept on one v5e chip, zipf(1.3)
    bench config, 2026-07-31): under f32 the dense block's bandwidth cost
    dominates, so the optimum sits at max(8, n/2048) (~1.8k hot columns,
    16.0 M samples/s vs 12.0 at n/4096); under bf16 the block streams at
    half the bytes and the optimum flattens across n/4096–n/8192 (~18.8 M
    samples/s) — n/4096 is kept. ``max_hot`` caps the dense block's
    memory (4096 f32 columns at n=131072 is ~2 GB HBM).
    """
    indices = np.asarray(batch.indices)
    values = np.asarray(batch.values)
    n = indices.shape[0]
    d = int(batch.num_features)
    if hot_threshold is None:
        hot_threshold = _default_hot_threshold(n, feature_dtype)

    flat_col = indices.reshape(-1)
    flat_row = np.repeat(np.arange(n, dtype=np.int32),
                         indices.shape[1])
    flat_val = values.reshape(-1)
    live = (flat_col < d) & (flat_val != 0.0)
    counts = np.bincount(flat_col[live], minlength=d)

    # Permuted order: count-descending (stable → ties break on column id).
    order_desc = np.argsort(-counts, kind="stable").astype(np.int32)
    num_hot = int(min(max_hot, (counts >= hot_threshold).sum()))
    k = num_hot

    inv_perm = np.empty(d, np.int32)
    inv_perm[order_desc] = np.arange(d, dtype=np.int32)

    # Hot block: dense (n, k) via one scatter into the new column ids.
    X_hot = np.zeros((n, max(k, 1)), np.float32)
    new_col = inv_perm[np.minimum(flat_col, d - 1)]
    hot_sel = live & (new_col < k)
    if k:
        X_hot[flat_row[hot_sel], new_col[hot_sel]] = flat_val[hot_sel]
    X_hot = X_hot[:, :k]

    # Cold entries, column-contiguous in permuted order.
    cold_sel = live & (new_col >= k)
    c_new = new_col[cold_sel] - k
    c_row = flat_row[cold_sel]
    c_val = flat_val[cold_sel]
    order = np.argsort(c_new, kind="stable")
    c_new, c_row, c_val = c_new[order], c_row[order], c_val[order]
    cold_counts = counts[order_desc][k:]  # descending
    present = int((cold_counts > 0).sum())
    col_start = np.concatenate(
        [[0], np.cumsum(cold_counts[:present])[:-1]]).astype(np.int64)

    # Power-of-two count classes over the present cold columns; counts are
    # descending, so each class is one contiguous slice of columns.
    rowids_cls: list[np.ndarray] = []
    vals_cls: list[np.ndarray] = []
    class_starts: list[int] = []
    if present:
        # Counts are descending, so equal-class columns are contiguous and
        # padding is < 2x within each power-of-two class.
        cls = np.ceil(np.log2(np.maximum(
            cold_counts[:present], 1))).astype(np.int64)
        # Descending class order == the permuted column layout, so the
        # per-class gradient slices concatenate back in place.
        for kk in np.unique(cls)[::-1]:
            sel = np.flatnonzero(cls == kk)
            L = 1 << int(kk)
            C = sel.size
            rp = np.full((C, L), n, np.int32)
            vp = np.zeros((C, L), np.float32)
            # Vectorized fill: position of each entry within its column.
            starts = col_start[sel]
            cnts = cold_counts[sel].astype(np.int64)
            total = int(cnts.sum())
            colpos = np.arange(total) - np.repeat(
                np.concatenate([[0], np.cumsum(cnts)[:-1]]), cnts)
            src = np.repeat(starts, cnts) + colpos
            crow = np.repeat(np.arange(C, dtype=np.int64), cnts)
            rp[crow, colpos] = c_row[src]
            vp[crow, colpos] = c_val[src]
            rowids_cls.append(rp)
            vals_cls.append(vp)
            class_starts.append(int(sel[0]))

    if feature_dtype == jnp.bfloat16:
        # Cast on host: halves the host→device transfer (which dominates
        # staging when the device sits behind a network tunnel).
        import ml_dtypes

        X_hot = X_hot.astype(ml_dtypes.bfloat16)
    # device=False keeps the leaves as host numpy (a valid pytree): the
    # row-streaming path (ops/streaming_sparse.py) holds many chunks on
    # host and device_puts them per objective pass instead of pinning
    # them all in HBM.
    put = jnp.asarray if device else (lambda a: a)
    return HybridSparseBatch(
        X_hot=put(X_hot),
        cold_rowids=tuple(put(a) for a in rowids_cls),
        cold_vals=tuple(put(a) for a in vals_cls),
        labels=put(np.asarray(batch.labels)),
        weights=put(np.asarray(batch.weights)),
        offsets=put(np.asarray(batch.offsets)),
        perm=put(order_desc),
        inv_perm=put(inv_perm),
        num_features=d,
        num_hot=k,
        class_starts=tuple(class_starts),
    )


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class HybridShards:
    """Data-parallel stack of per-shard hybrid layouts (P3 composition).

    The single-shard hybrid layout above owns the whole batch; this is its
    multi-device composition: rows are padded to ``S * rows_per_shard``
    (padding rows carry weight 0) and split CONTIGUOUSLY into S shards,
    and every data array carries a leading shard axis that shards over the
    mesh's ``data`` axis. The feature-space permutation and the cold count
    classes are GLOBAL — computed from global column counts — so the
    permuted coefficient space (what the optimizer sees, replicated) is
    identical across shards, hot gradients psum like the dense
    data-parallel path, and each shard's cold entries reference LOCAL row
    ids (pad == rows_per_shard, the zero sentinel lane).

    A column that happens to have no nonzeros in some shard still owns its
    class row there (all pad lanes) — inert by the pad contract, so the
    data-axis psum over per-shard gradients is exact.
    """

    X_hot: Array  # (S, n_l, k) dense hot blocks
    cold_rowids: tuple[Array, ...]  # per class: (S, C, L) int32, pad == n_l
    cold_vals: tuple[Array, ...]  # per class: (S, C, L) f32, pad == 0
    labels: Array  # (S, n_l)
    weights: Array  # (S, n_l); padding rows weight 0
    offsets: Array  # (S, n_l)
    perm: Array  # (d,) int32: new col -> original col
    inv_perm: Array  # (d,) int32: original col -> new col
    num_features: int = dataclasses.field(metadata=dict(static=True))
    num_hot: int = dataclasses.field(metadata=dict(static=True))
    class_starts: tuple[int, ...] = dataclasses.field(
        metadata=dict(static=True))

    @property
    def num_shards(self) -> int:
        return self.labels.shape[0]

    @property
    def rows_per_shard(self) -> int:
        return self.labels.shape[1]

    @property
    def num_rows(self) -> int:
        """Padded global row count (S * n_l) — flat score/offset length."""
        return self.labels.shape[0] * self.labels.shape[1]

    @property
    def dim(self) -> int:
        return self.num_features


def local_shard(shb: HybridShards, X_hot: Array,
                cold_rowids: tuple[Array, ...],
                cold_vals: tuple[Array, ...], labels: Array,
                weights: Array, offsets: Array) -> HybridSparseBatch:
    """One shard's block (leading axis 1, as shard_map yields it) as a
    HybridSparseBatch, so every aggregate above runs unchanged per shard.

    The perm fields are deliberately empty: the per-shard aggregates never
    touch them (permutation handling happens once, outside the shard_map).
    """
    empty = jnp.zeros((0,), jnp.int32)
    return HybridSparseBatch(
        X_hot=X_hot[0], cold_rowids=tuple(r[0] for r in cold_rowids),
        cold_vals=tuple(v[0] for v in cold_vals), labels=labels[0],
        weights=weights[0], offsets=offsets[0], perm=empty, inv_perm=empty,
        num_features=shb.num_features, num_hot=shb.num_hot,
        class_starts=shb.class_starts)


def build_hybrid_shards(
    batch: SparseBatch,
    n_shards: int,
    hot_threshold: Optional[int] = None,
    max_hot: int = 4096,
    feature_dtype=jnp.float32,
) -> HybridShards:
    """Stage an ELL SparseBatch into S per-shard hybrid layouts (host-side,
    once). Same hot/cold policy as ``build_hybrid`` — global counts decide
    the hot set and the cold classes; only the ROWS split across shards.
    """
    indices = np.asarray(batch.indices)
    values = np.asarray(batch.values)
    n = indices.shape[0]
    d = int(batch.num_features)
    S = int(n_shards)
    n_l = -(-n // S)  # ceil: rows per shard
    n_pad = n_l * S
    if hot_threshold is None:
        hot_threshold = _default_hot_threshold(n, feature_dtype)

    flat_col = indices.reshape(-1)
    flat_row = np.repeat(np.arange(n, dtype=np.int64), indices.shape[1])
    flat_val = values.reshape(-1)
    live = (flat_col < d) & (flat_val != 0.0)
    counts = np.bincount(flat_col[live], minlength=d)

    order_desc = np.argsort(-counts, kind="stable").astype(np.int32)
    k = int(min(max_hot, int((counts >= hot_threshold).sum())))
    inv_perm = np.empty(d, np.int32)
    inv_perm[order_desc] = np.arange(d, dtype=np.int32)

    # Hot blocks: one global dense scatter, then the contiguous row split.
    X_hot = np.zeros((n_pad, max(k, 1)), np.float32)
    new_col = inv_perm[np.minimum(flat_col, d - 1)]
    hot_sel = live & (new_col < k)
    if k:
        X_hot[flat_row[hot_sel], new_col[hot_sel]] = flat_val[hot_sel]
    X_hot = X_hot[:, :k].reshape(S, n_l, k)

    # Cold entries keyed by (shard, permuted column).
    cold_sel = live & (new_col >= k)
    c_new = (new_col[cold_sel] - k).astype(np.int64)
    c_row = flat_row[cold_sel]
    c_val = flat_val[cold_sel]
    c_shard = c_row // n_l
    c_local = (c_row - c_shard * n_l).astype(np.int32)

    cold_counts = counts[order_desc][k:]  # global, descending
    present = int((cold_counts > 0).sum())

    rowids_cls: list[np.ndarray] = []
    vals_cls: list[np.ndarray] = []
    class_starts: list[int] = []
    if present:
        key = c_shard * present + c_new
        order = np.argsort(key, kind="stable")
        key_s = key[order]
        c_new_s = c_new[order]
        loc_s = c_local[order]
        val_s = c_val[order]
        grp_counts = np.bincount(key_s, minlength=S * present)
        grp_starts = (np.cumsum(grp_counts) - grp_counts).astype(np.int64)
        pos = np.arange(key_s.size, dtype=np.int64) - grp_starts[key_s]
        M = grp_counts.reshape(S, present)  # per-shard per-column counts

        # Classes by GLOBAL count (same as build_hybrid), so each class is
        # one contiguous run of the permuted space; the per-shard lane
        # width L fits the largest per-shard column count in the class.
        cls = np.ceil(np.log2(np.maximum(
            cold_counts[:present], 1))).astype(np.int64)
        cls_of_entry = cls[c_new_s]
        for kk in np.unique(cls)[::-1]:
            selc = np.flatnonzero(cls == kk)
            c0 = int(selc[0])
            C = selc.size
            Lmax = int(M[:, selc].max())
            L = 1 << max(0, int(np.ceil(np.log2(max(Lmax, 1)))))
            rp = np.full((S, C, L), n_l, np.int32)
            vp = np.zeros((S, C, L), np.float32)
            e = np.flatnonzero(cls_of_entry == kk)
            sh = key_s[e] // present
            co = c_new_s[e] - c0
            rp[sh, co, pos[e]] = loc_s[e]
            vp[sh, co, pos[e]] = val_s[e]
            rowids_cls.append(rp)
            vals_cls.append(vp)
            class_starts.append(c0)

    def pad1(a):
        return np.concatenate(
            [np.asarray(a, np.float32), np.zeros(n_pad - n, np.float32)])

    if feature_dtype == jnp.bfloat16:
        import ml_dtypes

        X_hot = X_hot.astype(ml_dtypes.bfloat16)
    # Leaves stay HOST numpy: materializing the global hot block on the
    # default device first would allocate the UNSHARDED array there (the
    # exact OOM this composition avoids) and transfer everything twice.
    # shard_hybrid (parallel/sparse_problem.py) device_puts each leaf
    # straight to its mesh sharding.
    return HybridShards(
        X_hot=X_hot,
        cold_rowids=tuple(rowids_cls),
        cold_vals=tuple(vals_cls),
        labels=pad1(batch.labels).reshape(S, n_l),
        weights=pad1(batch.weights).reshape(S, n_l),
        offsets=pad1(batch.offsets).reshape(S, n_l),
        perm=order_desc,
        inv_perm=inv_perm,
        num_features=d,
        num_hot=k,
        class_starts=tuple(class_starts),
    )


def to_permuted_space(hb, w: Array) -> Array:
    """Original-space (d,) vector → permuted space (once per fit).
    Accepts either layout (HybridSparseBatch or HybridShards)."""
    return w[hb.perm]


def to_original_space(hb, w_perm: Array) -> Array:
    """Permuted-space (d,) vector → original space (once per fit).
    Accepts either layout (HybridSparseBatch or HybridShards)."""
    return w_perm[hb.inv_perm]


def _hot_matvec(X: Array, w: Array) -> Array:
    """X_hot @ w with f32 MXU accumulation under bf16 storage (same
    contract as ops/aggregators._matvec)."""
    if X.dtype == jnp.bfloat16:
        return jnp.einsum("nd,d->n", X, w.astype(jnp.bfloat16),
                          preferred_element_type=jnp.float32)
    return X @ w


def _hot_rmatvec(X: Array, r: Array) -> Array:
    if X.dtype == jnp.bfloat16:
        return jnp.einsum("n,nd->d", r.astype(jnp.bfloat16), X,
                          preferred_element_type=jnp.float32)
    return r @ X


def _cold_products(hb: HybridSparseBatch, w_perm: Array,
                   cold_vals: tuple[Array, ...]) -> Array:
    """Flat per-entry w[col]·value products over all classes.

    Column coefficients arrive by contiguous SLICE broadcast (no gather):
    each class's columns are one run of the permuted space.
    """
    parts = []
    for start, rows, vals in zip(hb.class_starts, hb.cold_rowids,
                                 cold_vals):
        C = rows.shape[0]
        w_c = w_perm[hb.num_hot + start: hb.num_hot + start + C]
        parts.append((w_c[:, None] * vals).reshape(-1))
    return jnp.concatenate(parts)


def _cold_flat_rowids(hb: HybridSparseBatch) -> Array:
    return jnp.concatenate([r.reshape(-1) for r in hb.cold_rowids])


def margins(hb: HybridSparseBatch, w_perm: Array) -> Array:
    """(n,) wᵀx + offset. Hot: one MXU matvec. Cold: contiguous-slice
    broadcast products + ONE fused scatter-add by row (the only random
    crossing in this direction)."""
    n = hb.labels.shape[0]
    z = hb.offsets
    if hb.num_hot:
        z = z + _hot_matvec(hb.X_hot, w_perm[:hb.num_hot])
    if hb.cold_rowids:
        prods = _cold_products(hb, w_perm, hb.cold_vals)
        acc = jnp.zeros((n + 1,), jnp.float32).at[
            _cold_flat_rowids(hb)].add(prods)
        z = z + acc[:n]
    return z


def _masked(weights: Array, term: Array) -> Array:
    return jnp.where(weights > 0.0, weights * term, 0.0)


def _cold_grad(hb: HybridSparseBatch, r: Array,
               cold_vals: tuple[Array, ...]) -> list[Array]:
    """Per class, (C,) gradient slice: ONE fused gather r[rowids] (the
    second random crossing), then padded row-sums and contiguous writes."""
    if not hb.cold_rowids:
        return []
    r_pad = jnp.concatenate([r, jnp.zeros((1,), r.dtype)])
    gathered = r_pad[_cold_flat_rowids(hb)]
    out = []
    off = 0
    for rows, vals in zip(hb.cold_rowids, cold_vals):
        C, L = rows.shape
        ru = gathered[off: off + C * L].reshape(C, L)
        out.append(jnp.sum(ru * vals, axis=1))
        off += C * L
    return out


def _assemble_grad(hb: HybridSparseBatch, g_hot: Optional[Array],
                   g_cold: list[Array]) -> Array:
    parts = []
    if hb.num_hot:
        parts.append(g_hot.astype(jnp.float32))
    parts.extend(g_cold)
    if not parts:
        return jnp.zeros((hb.num_features,), jnp.float32)
    dense = jnp.concatenate(parts)
    d = hb.num_features
    if dense.shape[0] == d:
        return dense
    # Absent (zero-count) columns sit at the permuted tail: gradient 0.
    return jnp.zeros((d,), jnp.float32).at[:dense.shape[0]].set(dense)


def _rowterm_gradient(hb: HybridSparseBatch, r: Array) -> Array:
    """Σ_i r_i·x_i in PERMUTED space: hot matvec + cold class sums."""
    g_hot = None
    if hb.num_hot:
        g_hot = _hot_rmatvec(hb.X_hot, r)
    return _assemble_grad(hb, g_hot, _cold_grad(hb, r, hb.cold_vals))


def value_and_gradient(
    loss: PointwiseLoss,
    w_perm: Array,
    hb: HybridSparseBatch,
) -> tuple[Array, Array]:
    """(Σ w·l, Σ w·dl·x) in permuted space — the fused hot/cold pass."""
    z = margins(hb, w_perm)
    l, dl = loss.loss_and_dz(z, hb.labels)
    value = jnp.sum(_masked(hb.weights, l), axis=-1)
    r = _masked(hb.weights, dl)
    return value, _rowterm_gradient(hb, r)


def hessian_vector(
    loss: PointwiseLoss,
    w_perm: Array,
    v_perm: Array,
    hb: HybridSparseBatch,
) -> Array:
    """Σ w·d2l·(x·v)·x in permuted space (TRON's H·v)."""
    z = margins(hb, w_perm)
    xv = margins(hb, v_perm) - hb.offsets
    d2 = loss.d2z(z, hb.labels)
    r = _masked(hb.weights, d2) * xv
    return _rowterm_gradient(hb, r)


def hessian_diagonal(
    loss: PointwiseLoss,
    w_perm: Array,
    hb: HybridSparseBatch,
) -> Array:
    """diag(H) = Σ w·d2l·x² in permuted space (SIMPLE variances)."""
    z = margins(hb, w_perm)
    d2 = loss.d2z(z, hb.labels)
    r = _masked(hb.weights, d2)
    g_hot = None
    if hb.num_hot:
        # Squares upcast to f32: x² underflows/quantizes harshly in bf16.
        Xsq = hb.X_hot.astype(jnp.float32) ** 2
        g_hot = r @ Xsq
    return _assemble_grad(
        hb, g_hot, _cold_grad(hb, r, tuple(v * v for v in hb.cold_vals)))
