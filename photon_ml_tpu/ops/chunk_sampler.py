"""Gap-driven chunk residency: keep the chunks that still matter on HBM.

The DuHL observation ("Large-Scale Stochastic Learning using GPUs",
PAPERS.md): on a transfer-bound stream, the per-chunk DUALITY-GAP
contribution (optim/gap.py — each row's Fenchel–Young term, summed over
the chunk) says exactly how much convergence progress is still available
in that chunk's rows. Chunks near dual-optimal contribute ~0 and can be
streamed (or skipped) cheaply; high-gap chunks are re-visited every
epoch and should sit in the PR 13 pinned device cache so their transfer
cost amortizes to zero.

:class:`GapChunkSampler` generalizes ``streaming_sparse.pin_chunks``
(leading-``count`` pinning) to an ARBITRARY pinned set re-chosen per
epoch: it starts with the leading chunks resident (byte-identical
behavior to ``pin_chunks`` before the first score update), and after
each epoch :meth:`update` re-pins the top-``capacity`` chunks by gap
contribution, evicting the rest. Residency never changes chunk ORDER —
:meth:`stream` always yields global chunk order, resident chunks in
place — so the solver's result is bit-identical for every pin set; only
the transfer bytes move (``photon_transfer_bytes_total`` drops by the
pinned fraction, ``photon_stream_pin_swaps_total`` counts re-pins).

Scores are STALE by one epoch by construction (the gap partials that
rank epoch t's residency were measured during epoch t): DuHL shows the
stale signal is enough — gap contributions shrink monotonically in
expectation, so last epoch's hot set is a good predictor of this
epoch's.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np

from photon_ml_tpu import obs
from photon_ml_tpu.ops.streaming_sparse import (ChunkedHybrid, _delete_chunk,
                                                _transfer)


def _drop_pinned(ch) -> None:
    """Release an evicted PINNED chunk's device buffers. Distinct from
    ``_delete_chunk``: pinned chunks never passed through the accounted
    transfer path, so they must not step the in-flight stream gauge."""
    for leaf in jax.tree.leaves(ch):
        if isinstance(leaf, jax.Array):
            leaf.delete()


class GapChunkSampler:
    """Per-epoch gap-ranked chunk residency over one ``ChunkedHybrid``.

    ``capacity`` is the pinned-chunk budget (0 = pure streaming — the
    sampler degenerates to the plain prefetch loop); ``device`` pins to
    a specific device (None = the default device, the single-device
    stochastic path)."""

    def __init__(self, chunked: ChunkedHybrid, capacity: int,
                 device: Optional[jax.Device] = None):
        self.chunked = chunked
        self.capacity = min(max(0, int(capacity)), chunked.num_chunks)
        self.device = device
        # Leading-chunk start: identical residency to pin_chunks(count)
        # until the first gap scores arrive.
        self._resident: dict = {
            i: jax.device_put(chunked.chunks[i], device)
            for i in range(self.capacity)}

    @property
    def resident_indices(self) -> list:
        return sorted(self._resident)

    def update(self, gap_by_chunk) -> None:
        """Re-pin the top-``capacity`` chunks by gap contribution.

        Ties keep the CURRENT residents (stickiness — a swap that buys
        no gap is pure transfer cost), then break by chunk index so the
        pin set is a deterministic function of (scores, previous set)."""
        if self.capacity == 0:
            return
        scores = np.asarray(gap_by_chunk, np.float64)
        if scores.shape[0] != self.chunked.num_chunks:
            raise ValueError(
                f"gap_by_chunk has {scores.shape[0]} entries, stream "
                f"has {self.chunked.num_chunks} chunks")
        order = sorted(
            range(self.chunked.num_chunks),
            key=lambda i: (-scores[i], 0 if i in self._resident else 1, i))
        want = set(order[:self.capacity])
        evict = [i for i in self._resident if i not in want]
        add = [i for i in want if i not in self._resident]
        for i in evict:
            _drop_pinned(self._resident.pop(i))
        for i in add:
            self._resident[i] = jax.device_put(self.chunked.chunks[i],
                                               self.device)
        if add:
            mx = obs.metrics()
            if mx is not None:
                mx.counter("photon_stream_pin_swaps_total").inc(len(add))

    def stream(self, depth: int):
        """Yield ``(global_index, device_chunk, streamed)`` in global
        chunk order — resident chunks in place (no transfer), the rest
        through the accounted transfer path with ``depth`` copies in
        flight ahead of the consumer (the ``_stream`` discipline).
        Streamed chunks are the CALLER's to release (``_delete_chunk``
        after its per-chunk barrier); resident chunks are this
        sampler's."""
        import collections

        if depth < 1:
            raise ValueError(f"prefetch_depth must be >= 1, got {depth}")
        nonres = iter([i for i in range(self.chunked.num_chunks)
                       if i not in self._resident])
        q: collections.deque = collections.deque()
        for _ in range(depth):
            i = next(nonres, None)
            if i is None:
                break
            q.append((i, _transfer(self.chunked.chunks[i], i,
                                   self.device)))
        for i in range(self.chunked.num_chunks):
            ch = self._resident.get(i)
            if ch is not None:
                yield i, ch, False
                continue
            j, ready = q.popleft()
            assert j == i, f"sampler stream order broke: {j} != {i}"
            nxt = next(nonres, None)
            if nxt is not None:
                q.append((nxt, _transfer(self.chunked.chunks[nxt], nxt,
                                         self.device)))
            yield i, ready, True

    def release(self) -> None:
        """Drop every pinned chunk (end of the optimization — the
        coordinate's staged host chunks stay, only device residency
        goes)."""
        for i in list(self._resident):
            _drop_pinned(self._resident.pop(i))


# Make _delete_chunk importable alongside the sampler for callers that
# drive stream()/release() as a pair (optim/stochastic.py).
__all__ = ["GapChunkSampler", "_delete_chunk"]
