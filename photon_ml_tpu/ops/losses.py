"""Pointwise GLM losses: l(margin, label) and its d/dmargin derivatives.

Reference parity: photon-lib ``function/glm/PointwiseLossFunction.scala``
(``lossAndDzLoss`` / ``DzzLoss``) and its implementations
``LogisticLossFunction.scala``, ``SquaredLossFunction.scala``,
``PoissonLossFunction.scala``, plus the smoothed hinge in
``function/svm/SingleNodeSmoothedHingeLossFunction.scala``.

TPU-first design: each loss is a set of pure elementwise functions of
``(margin, label)`` arrays. XLA fuses these into the surrounding matmul
(margin computation) and reduction, so there is no per-example Python or
"aggregator object" — the reference's mutable add/merge hot loop becomes a
single fused jit region. All functions are ``vmap``/``grad``-compatible.

Labels: logistic and smoothed hinge expect labels in {0, 1}; the hinge
converts internally to {-1, +1}. Poisson expects non-negative counts.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax
import jax.numpy as jnp

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class PointwiseLoss:
    """A pointwise loss l(margin, label) with closed-form margin derivatives.

    ``loss_and_dz`` returns ``(l, dl/dz)`` fused (the common case — value and
    gradient are always needed together); ``d2z`` returns the second
    derivative d²l/dz² used by Hessian-vector / Hessian-diagonal products.
    """

    name: str
    loss_and_dz: Callable[[Array, Array], tuple[Array, Array]]
    d2z: Callable[[Array, Array], Array]
    # The inverse link ("mean function") for scoring: E[y] = mean(margin).
    mean: Callable[[Array], Array]

    def loss(self, margin: Array, label: Array) -> Array:
        return self.loss_and_dz(margin, label)[0]

    def dz(self, margin: Array, label: Array) -> Array:
        return self.loss_and_dz(margin, label)[1]


def _logistic_loss_and_dz(margin: Array, label: Array) -> tuple[Array, Array]:
    # l = log(1 + e^z) - y*z, computed stably as softplus(z) - y*z.
    l = jax.nn.softplus(margin) - label * margin
    dl = jax.nn.sigmoid(margin) - label
    return l, dl


def _logistic_d2z(margin: Array, label: Array) -> Array:
    del label
    s = jax.nn.sigmoid(margin)
    return s * (1.0 - s)


def _squared_loss_and_dz(margin: Array, label: Array) -> tuple[Array, Array]:
    r = margin - label
    return 0.5 * r * r, r


def _squared_d2z(margin: Array, label: Array) -> Array:
    del label
    return jnp.ones_like(margin)


def _poisson_loss_and_dz(margin: Array, label: Array) -> tuple[Array, Array]:
    # Negative log-likelihood up to the label-only constant log(y!):
    # l = e^z - y*z;  dl = e^z - y.
    ez = jnp.exp(margin)
    return ez - label * margin, ez - label


def _poisson_d2z(margin: Array, label: Array) -> Array:
    del label
    return jnp.exp(margin)


def _smoothed_hinge_loss_and_dz(margin: Array, label: Array) -> tuple[Array, Array]:
    # Rennie's smoothed hinge on the product t = y*z with y in {-1,+1}
    # (labels arrive in {0,1}):
    #   l(t) = 1/2 - t        t <= 0
    #   l(t) = (1 - t)^2 / 2  0 < t < 1
    #   l(t) = 0              t >= 1
    y = 2.0 * label - 1.0
    t = y * margin
    l = jnp.where(t <= 0.0, 0.5 - t, jnp.where(t < 1.0, 0.5 * (1.0 - t) ** 2, 0.0))
    dl_dt = jnp.where(t <= 0.0, -1.0, jnp.where(t < 1.0, t - 1.0, 0.0))
    return l, y * dl_dt


def _smoothed_hinge_d2z(margin: Array, label: Array) -> Array:
    y = 2.0 * label - 1.0
    t = y * margin
    return jnp.where((t > 0.0) & (t < 1.0), 1.0, 0.0)


LOGISTIC = PointwiseLoss(
    name="logistic",
    loss_and_dz=_logistic_loss_and_dz,
    d2z=_logistic_d2z,
    mean=jax.nn.sigmoid,
)

SQUARED = PointwiseLoss(
    name="squared",
    loss_and_dz=_squared_loss_and_dz,
    d2z=_squared_d2z,
    mean=lambda z: z,
)

POISSON = PointwiseLoss(
    name="poisson",
    loss_and_dz=_poisson_loss_and_dz,
    d2z=_poisson_d2z,
    mean=jnp.exp,
)

SMOOTHED_HINGE = PointwiseLoss(
    name="smoothed_hinge",
    loss_and_dz=_smoothed_hinge_loss_and_dz,
    d2z=_smoothed_hinge_d2z,
    # Scoring for the linear SVM is the raw margin; classification applies a
    # threshold at 0 (reference: SmoothedHingeLossLinearSVMModel.scala).
    mean=lambda z: z,
)

_BY_NAME = {
    loss.name: loss for loss in (LOGISTIC, SQUARED, POISSON, SMOOTHED_HINGE)
}


def get_loss(name: str) -> PointwiseLoss:
    return _BY_NAME[name]


def loss_for_task(task) -> PointwiseLoss:
    """Map a TaskType to its pointwise loss."""
    from photon_ml_tpu.types import TaskType

    return {
        TaskType.LOGISTIC_REGRESSION: LOGISTIC,
        TaskType.LINEAR_REGRESSION: SQUARED,
        TaskType.POISSON_REGRESSION: POISSON,
        TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM: SMOOTHED_HINGE,
    }[TaskType(task)]
