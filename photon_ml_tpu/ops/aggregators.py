"""Fused batch aggregations: value, gradient, Hessian·v, Hessian diagonal.

Reference parity: photon-lib ``function/glm/ValueAndGradientAggregator.scala``,
``HessianVectorAggregator.scala``, ``HessianDiagonalAggregator.scala``,
``HessianMatrixAggregator.scala`` — the per-partition mutable hot loops of
Photon-ML (axpy/dot per example, merged up a treeAggregate).

TPU-first design: each aggregation is ONE fused XLA region per batch —
margins are a single (n,d)@(d,) matmul on the MXU, the pointwise loss fuses
into it, and the gradient is the transposed matmul Xᵀr. There is no add/merge
object pair: within a shard the "merge" is the matmul reduction itself, and
across shards it is a ``psum`` (see photon_ml_tpu/parallel/objective.py).
Normalization factors/shifts are folded in algebraically
(see photon_ml_tpu/normalization.py) so data is never rewritten.

All functions are pure, jit-safe, and ``vmap``-able — the same code serves
the single big fixed-effect model and thousands of vmapped per-entity
random-effect solves. Zero-weight (padding) rows are masked with ``where`` so
non-finite values in padding cannot poison the sums (e.g. Poisson exp
overflow on garbage rows).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from photon_ml_tpu.data.batch import LabeledBatch
from photon_ml_tpu.normalization import NormalizationContext
from photon_ml_tpu.ops.losses import PointwiseLoss

Array = jax.Array

_IDENTITY = NormalizationContext()


def _matvec(X: Array, w: Array) -> Array:
    """X @ w with f32 accumulation when features are stored bf16.

    bf16 feature storage halves HBM traffic on the bandwidth-bound GLM
    hot loop; the MXU natively multiplies bf16 with f32 accumulation
    (``preferred_element_type``), so the reduction keeps f32 precision.
    Casting the small operand to bf16 (instead of upcasting X) is what
    preserves the bandwidth win.
    """
    if X.dtype == jnp.bfloat16:
        return jnp.einsum("...nd,...d->...n", X, w.astype(jnp.bfloat16),
                          preferred_element_type=jnp.float32)
    return X @ w


def _tmatvec(X: Array, r: Array) -> Array:
    """Xᵀ @ r (the gradient reduction), same dtype discipline."""
    if X.dtype == jnp.bfloat16:
        return jnp.einsum("...nd,...n->...d", X, r.astype(jnp.bfloat16),
                          preferred_element_type=jnp.float32)
    return jnp.einsum("...nd,...n->...d", X, r)


def margins(
    batch: LabeledBatch,
    means: Array,
    norm: NormalizationContext = _IDENTITY,
) -> Array:
    """Transformed-space margins z = X' @ w + offset, X' = (X − s) ∘ f.

    Padded (zero-weight) rows get margin 0, not just weight 0: masking the
    margin *input* keeps garbage in padding out of both the forward loss and
    reverse-mode autodiff (where's transpose would otherwise produce 0·inf
    NaNs from e.g. Poisson exp overflow on junk rows).
    """
    w_eff, shift = norm.effective_coefficients(means)
    z = _matvec(batch.features, w_eff) \
        + jnp.expand_dims(shift, -1) + batch.offsets
    return jnp.where(batch.weights > 0.0, z, 0.0)


def _masked(weights: Array, x: Array) -> Array:
    """weights * x with hard masking of zero-weight (padded) rows."""
    return jnp.where(weights > 0.0, weights * x, 0.0)


def value_and_gradient(
    loss: PointwiseLoss,
    means: Array,
    batch: LabeledBatch,
    norm: NormalizationContext = _IDENTITY,
) -> tuple[Array, Array]:
    """(Σᵢ wᵢ l(zᵢ, yᵢ),  ∇_w) over the batch, in transformed space."""
    z = margins(batch, means, norm)
    l, dl = loss.loss_and_dz(z, batch.labels)
    value = jnp.sum(_masked(batch.weights, l), axis=-1)
    r = _masked(batch.weights, dl)
    xtr = _tmatvec(batch.features, r)
    grad = norm.pullback_gradient(xtr, jnp.sum(r, axis=-1))
    return value, grad


def value_only(
    loss: PointwiseLoss,
    means: Array,
    batch: LabeledBatch,
    norm: NormalizationContext = _IDENTITY,
) -> Array:
    z = margins(batch, means, norm)
    l, _ = loss.loss_and_dz(z, batch.labels)
    return jnp.sum(_masked(batch.weights, l), axis=-1)


def hessian_vector(
    loss: PointwiseLoss,
    means: Array,
    v: Array,
    batch: LabeledBatch,
    norm: NormalizationContext = _IDENTITY,
) -> Array:
    """H·v with H = Σᵢ wᵢ d²l(zᵢ) x'ᵢ x'ᵢᵀ — never materializes H.

    Reference parity: HessianVectorAggregator (used by TRON's CG inner loop).
    """
    z = margins(batch, means, norm)
    d2 = loss.d2z(z, batch.labels)
    # u_i = x'_i · v computed through the same factor/shift algebra.
    v_eff, v_shift = norm.effective_coefficients(v)
    u = _matvec(batch.features, v_eff) + jnp.expand_dims(v_shift, -1)
    r = _masked(batch.weights, d2 * u)
    xtr = _tmatvec(batch.features, r)
    r_sum = jnp.sum(r, axis=-1)
    return norm.pullback_gradient(xtr, r_sum)


def hessian_diagonal(
    loss: PointwiseLoss,
    means: Array,
    batch: LabeledBatch,
    norm: NormalizationContext = _IDENTITY,
) -> Array:
    """diag(H) = Σᵢ wᵢ d²l(zᵢ) (x'ᵢⱼ)² per coordinate j.

    Reference parity: HessianDiagonalAggregator (SIMPLE variance mode).
    """
    z = margins(batch, means, norm)
    d2 = loss.d2z(z, batch.labels)
    r = _masked(batch.weights, d2)
    # Variances are a once-per-fit path: upcast bf16 storage before the
    # squaring (bf16² double-rounds), matching hessian_matrix below.
    Xf = batch.features.astype(jnp.float32)
    x2 = _tmatvec(Xf * Xf, r)
    if norm.is_identity:
        return x2
    f = norm.factors if norm.factors is not None else jnp.ones_like(means)
    if norm.shifts is None:
        return x2 * f * f
    x1 = _tmatvec(Xf, r)
    r_sum = jnp.sum(r, axis=-1)
    if x1.ndim > 1:
        r_sum = r_sum[..., None]
    s = norm.shifts
    return f * f * (x2 - 2.0 * s * x1 + (s * s) * r_sum)


def hessian_matrix(
    loss: PointwiseLoss,
    means: Array,
    batch: LabeledBatch,
    norm: NormalizationContext = _IDENTITY,
) -> Array:
    """Full H = X'ᵀ diag(w d²l) X' — only for small d (FULL variance mode).

    Reference parity: HessianMatrixAggregator.
    """
    z = margins(batch, means, norm)
    d2 = loss.d2z(z, batch.labels)
    r = _masked(batch.weights, d2)
    # FULL variances are a small-d, once-per-fit path: upcast for accuracy.
    Xp = batch.features.astype(jnp.float32)
    if norm.shifts is not None:
        Xp = Xp - norm.shifts
    if norm.factors is not None:
        Xp = Xp * norm.factors
    return jnp.einsum("...nd,...n,...ne->...de", Xp, r, Xp)


def total_weight(batch: LabeledBatch) -> Array:
    return jnp.sum(batch.weights, axis=-1)


def scores(
    batch_features: Array,
    means: Array,
    offsets: Optional[Array] = None,
) -> Array:
    """Raw-space scores X @ w (+ offsets) — used by scoring/eval paths."""
    s = _matvec(batch_features, means)
    if offsets is not None:
        s = s + offsets
    return s
