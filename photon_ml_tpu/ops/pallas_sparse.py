"""Compatibility shim: the Pallas ELL scatter moved to ops/kernels/.

The kernel registry (ops/kernels/registry.py, docs/KERNELS.md) owns
every Pallas program now — the scatter that used to live here is
ops/kernels/ell_scatter.py (registry name ``ell_scatter``), unchanged
tile-for-tile. This module keeps the original import path and the
original jitted ``scatter_rowterm(indices, rowterm_values, dim,
interpret=)`` signature for its existing callers (bench.py, tests);
production dispatch goes through the registry via
ops/sparse_aggregators.py, which is where the flag/fallback policy
lives. Calling this wrapper is an EXPLICIT request for the Pallas
program (a bench lane, a parity fixture) — no flag, no fallback.
"""

from __future__ import annotations

import functools

import jax

from photon_ml_tpu.ops.kernels.ell_scatter import (  # noqa: F401
    _COL_TILE, _ROW_TILE, scatter_rowterm_pallas, scatter_rowterm_xla)

Array = jax.Array


@functools.partial(jax.jit, static_argnames=("dim", "interpret"))
def scatter_rowterm(indices: Array, rowterm_values: Array, dim: int,
                    interpret: bool = False) -> Array:
    """Σᵢ Σₖ rv[i,k] · e(indices[i,k]) into shape (dim,) — see
    ops/kernels/ell_scatter.py for the kernel."""
    return scatter_rowterm_pallas(indices, rowterm_values, dim,
                                  interpret=interpret)
