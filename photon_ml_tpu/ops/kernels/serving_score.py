"""Serving score fusion (registry name ``serving_score``).

One random-effect coordinate's contribution to a serving flush is the
chain gather → int8 dequant → row-dot → per-row scale
(serving/service.py ``_build_score_fn``):

    rows = cache[slots]                         # (n, d) gather, int8
    out  = einsum("nd,nd->n", mat, rows.f32)    # dequantized dot
    out *= scale[slots]                         # per-row dequant scale

As separate XLA programs the gathered rows round-trip HBM as f32 —
4 bytes/element for codes the cache stores at 1 — and at million-entity
stores that f32 materialization is the p99 and device-capacity tax the
int8 cache was built to avoid. The fused program (docs/KERNELS.md memory
diagram) gathers each code row straight into VMEM via scalar-prefetch
block indexing, upcasts in registers, reduces, and applies the scale in
the same grid step: the only HBM traffic is the int8 row read and one
f32 scalar write per example.

Grid: one step per batch row. ``slots`` rides
``PrefetchScalarGridSpec``, so the cache BlockSpec's index_map addresses
block (slots[i], 0) — the gather IS the block schedule, not an op.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from photon_ml_tpu.ops.kernels.ell_scatter import _pad_axis

Array = jax.Array

_LANE = 128


def _score_kernel(slots_ref, mat_ref, row_ref, sc_ref, out_ref):
    del slots_ref  # consumed by the index maps, not the body
    acc = jnp.sum(mat_ref[...] * row_ref[...].astype(jnp.float32))
    out_ref[0, 0] = acc * sc_ref[0, 0]


def score_rows_pallas(mat: Array, slots: Array, cache: Array,
                      scale: Array | None,
                      interpret: bool = False) -> Array:
    """(n,) Σ_d mat[i,d]·dequant(cache[slots[i],d]) in one program.

    ``mat``: (n, d) f32 features. ``slots``: (n,) int32 cache rows (the
    service guarantees in-range: unknown entities resolve to the
    fallback slot). ``cache``: (E, d) int8 codes or f32 rows. ``scale``:
    (E,) f32 per-row dequant scales, or None for f32 caches (the
    fallback slot's scale is 0, so it dequantizes to exactly zero — same
    contract as the XLA chain)."""
    n, d = mat.shape
    mat_p = _pad_axis(jnp.asarray(mat, jnp.float32), _LANE, 1, 0.0)
    cache_p = _pad_axis(cache, _LANE, 1, 0)
    d_pad = mat_p.shape[1]
    slots = jnp.clip(jnp.asarray(slots, jnp.int32), 0,
                     cache.shape[0] - 1)
    if scale is None:
        # f32 cache: fold a unit scale so both modes share one program
        # (×1.0 is bit-exact, and (E,) f32 is noise next to the table).
        scale = jnp.ones((cache.shape[0],), jnp.float32)
    scale_2d = jnp.asarray(scale, jnp.float32).reshape(-1, 1)
    out = pl.pallas_call(
        _score_kernel,
        out_shape=jax.ShapeDtypeStruct((n, 1), jnp.float32),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(n,),
            in_specs=[
                pl.BlockSpec((1, d_pad), lambda i, s: (i, 0)),
                pl.BlockSpec((1, d_pad), lambda i, s: (s[i], 0)),
                pl.BlockSpec((1, 1), lambda i, s: (s[i], 0)),
            ],
            out_specs=pl.BlockSpec((1, 1), lambda i, s: (i, 0)),
        ),
        interpret=interpret,
    )(slots, mat_p, cache_p, scale_2d)
    return out[:, 0]


def score_rows_xla(mat: Array, slots: Array, cache: Array,
                   scale: Array | None) -> Array:
    """The unfused chain exactly as ``_build_score_fn`` inlines it —
    gather, f32 einsum, one per-row scale multiply (x·(s·q) = s·(x·q),
    exact algebra)."""
    rows = cache[slots]
    out = jnp.einsum("nd,nd->n", mat, rows.astype(jnp.float32))
    if scale is not None:
        out = out * scale[slots]
    return out
