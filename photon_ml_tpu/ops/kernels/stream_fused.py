"""Streamed int8 margin/gradient fusion (registry names
``stream_margins`` / ``stream_rmatvec``).

The streamed chunk pass's byte budget is dominated by its hot-dense
tier: an int8 chunk stores ``X_hot`` as (n, H) codes, but the XLA path
(ops/streaming_sparse.py ``_chunk_margins_of`` / ``_chunk_rowterm_grad``)
opens with ``ch.X_hot.astype(jnp.float32)`` — materializing a 4×-larger
f32 copy of the densest block in HBM before the matvec even starts, per
chunk, per pass. These programs fold the dequant into the matvec tiles:
codes stream HBM→VMEM as int8 and upcast in registers, so the f32 hot
block never exists anywhere (docs/KERNELS.md memory diagram).

Scope is deliberate (docs/KERNELS.md "What stays XLA"): the cold-ELL
tier keeps its per-slot 1-D gathers/scatters — they are byte-small by
construction (the hot/cold split put the mass in the hot tier) and an
in-kernel vector gather over a d≈10⁶ table is exactly the layout the
module's (n,k)-operand lesson forbids. The margins program instead takes
the cold contribution pre-reduced as ``base``, so the chunk's margins
are still produced by ONE fused program:

    margins:  out[i] = base[i] + Σ_h X_hot[i,h]·w_hot[h]   (w pre-folded
              with hot_scale: w·(s·q) = (w·s)·q, exact)
    rmatvec:  out[h] = Σ_i X_hot[i,h]·r[i]                 (caller scales
              the (H,) result once — O(H), not O(n·H))

Both tile the hot block (rows × lanes) with the minor grid dimension
accumulating in place — TPU grids iterate sequentially, so ``out_ref``
accumulation over the minor dim is race-free, the ell_scatter pattern.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from photon_ml_tpu.ops.hybrid_sparse import _hot_matvec, _hot_rmatvec
from photon_ml_tpu.ops.kernels.ell_scatter import _pad_axis

Array = jax.Array

# Row tile amortizes grid overhead; the lane tile keeps one VMEM-resident
# (rows × lanes) block per step small enough for any H (large hot tiers
# tile across the minor grid dimension instead of growing the block).
_ROW_TILE = 256
_H_TILE = 512


def _margins_kernel(x_ref, w_ref, base_ref, out_ref):
    """Grid (n_tiles, h_tiles); h is the accumulation (minor) dim."""
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        out_ref[...] = base_ref[...]

    x = x_ref[...].astype(jnp.float32)  # dequant upcast: registers only
    out_ref[...] += jnp.sum(x * w_ref[...], axis=1, keepdims=True)


def hot_margins_pallas(X_hot: Array, w_hot: Array, base: Array,
                       interpret: bool = False) -> Array:
    """(n,) base + X_hot @ w_hot with the upcast fused into the tiles.

    ``X_hot``: (n, H) int8 codes (or f32/bf16 — the upcast is then a
    no-op and the fusion still saves the separate matvec dispatch).
    ``w_hot``: (H,) f32, already folded with the hot dequant scales.
    ``base``: (n,) f32 offsets + cold-tier contribution."""
    n, h = X_hot.shape
    x = _pad_axis(_pad_axis(X_hot, _ROW_TILE, 0, 0), _H_TILE, 1, 0)
    w = _pad_axis(jnp.asarray(w_hot, jnp.float32).reshape(1, -1),
                  _H_TILE, 1, 0.0)
    b = _pad_axis(jnp.asarray(base, jnp.float32).reshape(-1, 1),
                  _ROW_TILE, 0, 0.0)
    n_tiles = x.shape[0] // _ROW_TILE
    h_tiles = x.shape[1] // _H_TILE
    out = pl.pallas_call(
        _margins_kernel,
        out_shape=jax.ShapeDtypeStruct((x.shape[0], 1), jnp.float32),
        grid=(n_tiles, h_tiles),
        in_specs=[
            pl.BlockSpec((_ROW_TILE, _H_TILE), lambda i, j: (i, j)),
            pl.BlockSpec((1, _H_TILE), lambda i, j: (0, j)),
            pl.BlockSpec((_ROW_TILE, 1), lambda i, j: (i, 0)),
        ],
        out_specs=pl.BlockSpec((_ROW_TILE, 1), lambda i, j: (i, 0)),
        interpret=interpret,
    )(x, w, b)
    return out[:n, 0]


def hot_margins_xla(X_hot: Array, w_hot: Array, base: Array) -> Array:
    """The unfused reference: explicit f32 upcast (the HBM copy the
    fused program exists to avoid), then the shared hot matvec."""
    if X_hot.dtype == jnp.int8:
        X_hot = X_hot.astype(jnp.float32)
    return base + _hot_matvec(X_hot, w_hot)


def _rmatvec_kernel(x_ref, r_ref, out_ref):
    """Grid (h_tiles, n_tiles); n is the accumulation (minor) dim."""
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    x = x_ref[...].astype(jnp.float32)
    out_ref[...] += jnp.sum(x * r_ref[...], axis=0, keepdims=True)


def hot_rmatvec_pallas(X_hot: Array, r: Array,
                       interpret: bool = False) -> Array:
    """(H,) X_hotᵀ @ r, upcast fused. Unscaled: the caller multiplies
    the (H,) result by hot_scale once (the gradient path's O(H) dequant
    epilogue, ops/streaming_sparse.py ``_chunk_rowterm_grad``)."""
    n, h = X_hot.shape
    x = _pad_axis(_pad_axis(X_hot, _ROW_TILE, 0, 0), _H_TILE, 1, 0)
    rr = _pad_axis(jnp.asarray(r, jnp.float32).reshape(-1, 1),
                   _ROW_TILE, 0, 0.0)
    h_tiles = x.shape[1] // _H_TILE
    n_tiles = x.shape[0] // _ROW_TILE
    out = pl.pallas_call(
        _rmatvec_kernel,
        out_shape=jax.ShapeDtypeStruct((1, x.shape[1]), jnp.float32),
        grid=(h_tiles, n_tiles),
        in_specs=[
            pl.BlockSpec((_ROW_TILE, _H_TILE), lambda i, j: (j, i)),
            pl.BlockSpec((_ROW_TILE, 1), lambda i, j: (j, 0)),
        ],
        out_specs=pl.BlockSpec((1, _H_TILE), lambda i, j: (0, i)),
        interpret=interpret,
    )(x, rr)
    return out[0, :h]


def hot_rmatvec_xla(X_hot: Array, r: Array) -> Array:
    if X_hot.dtype == jnp.int8:
        X_hot = X_hot.astype(jnp.float32)
    return _hot_rmatvec(X_hot, r)
