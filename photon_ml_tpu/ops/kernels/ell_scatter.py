"""ELL scatter-add kernel (registry name ``ell_scatter``).

The Pallas program moved here verbatim from ops/pallas_sparse.py when
the kernel registry landed (that module is now a compatibility shim over
this one); the algorithm and tile choices are unchanged — see the kernel
docstring. What this module adds is the registry contract: the XLA
reference closure (`scatter_rowterm_xla`, the exact ``.at[].add``
sort+segment path ops/sparse_aggregators.py used to inline) lives NEXT
to the Pallas program, so parity tests and the fallback ladder compare
two implementations with one signature.

Memory shape (docs/KERNELS.md): XLA lowers the scatter to sort + segment
sum — materializing sorted (n·k,) index/value copies in HBM; the Pallas
program streams each (row, col) tile through VMEM once and contracts a
one-hot compare in registers, O(d·nnz) compute but zero intermediate HBM
traffic. BENCH_r05 ``scatter_pallas_d512_us``: 4.6× over XLA at d=512.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

Array = jax.Array

# Column tile = one lane register width; row tile amortizes grid overhead.
_COL_TILE = 128
_ROW_TILE = 256


def _kernel(idx_ref, rv_ref, out_ref, *, col_tile: int):
    """Grid (d_tiles, n_tiles); n is the accumulation (minor) dimension.

    Per cell: unrolled loop over the ELL slots, each a vectorized
    compare + select + add on a (row_tile, col_tile) register block —
    no unaligned reshapes (Mosaic rejects flattening (R, k) ELL blocks),
    same multiply-accumulate count as the explicit one-hot matmul.
    """
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    idx = idx_ref[...]  # (row_tile, max_nnz) int32
    rv = rv_ref[...]  # (row_tile, max_nnz) f32
    rows = idx.shape[0]
    d0 = pl.program_id(0) * col_tile
    cols = d0 + jax.lax.broadcasted_iota(jnp.int32, (rows, col_tile), 1)
    acc = jnp.zeros((rows, col_tile), jnp.float32)
    for k in range(idx.shape[1]):
        acc += jnp.where(idx[:, k:k + 1] == cols, rv[:, k:k + 1], 0.0)
    out_ref[...] += jnp.sum(acc, axis=0, keepdims=True)


def _pad_axis(x, mult, axis, fill):
    pad = (-x.shape[axis]) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths, constant_values=fill)


def scatter_rowterm_pallas(indices: Array, rowterm_values: Array, dim: int,
                           interpret: bool = False) -> Array:
    """Σᵢ Σₖ rv[i,k] · e(indices[i,k]) into shape (dim,).

    ``indices``: (n, max_nnz) int32 ELL indices (padding == any id ≥ dim).
    ``rowterm_values``: (n, max_nnz) f32, typically r[:, None] * values.
    """
    n_tiles_d = -(-dim // _COL_TILE)
    d_pad = n_tiles_d * _COL_TILE
    # Padding rows use an index ≥ d_pad so they match no column tile.
    idx = _pad_axis(jnp.asarray(indices, jnp.int32), _ROW_TILE, 0, d_pad)
    rv = _pad_axis(jnp.asarray(rowterm_values, jnp.float32), _ROW_TILE, 0,
                   0.0)
    n_tiles_r = idx.shape[0] // _ROW_TILE
    # Under shard_map the output varies over the same mesh axes as the
    # inputs (each shard scatters its local rows); propagate the vma so
    # jax's check_vma accepts the kernel.
    try:
        vma = jax.typeof(idx).vma | jax.typeof(rv).vma
        out_aval = jax.ShapeDtypeStruct((1, d_pad), jnp.float32, vma=vma)
    except (AttributeError, TypeError):
        out_aval = jax.ShapeDtypeStruct((1, d_pad), jnp.float32)
    out = pl.pallas_call(
        functools.partial(_kernel, col_tile=_COL_TILE),
        out_shape=out_aval,
        grid=(n_tiles_d, n_tiles_r),
        in_specs=[
            pl.BlockSpec((_ROW_TILE, idx.shape[1]), lambda i, j: (j, 0)),
            pl.BlockSpec((_ROW_TILE, rv.shape[1]), lambda i, j: (j, 0)),
        ],
        out_specs=pl.BlockSpec((1, _COL_TILE), lambda i, j: (0, i)),
        interpret=interpret,
    )(idx, rv)
    return out[0, :dim]


def scatter_rowterm_xla(indices: Array, rowterm_values: Array,
                        dim: int) -> Array:
    """The XLA reference: flatten + ``.at[].add`` into a (dim+1,) table
    whose sentinel column absorbs ELL padding — byte-for-byte the path
    ops/sparse_aggregators.py ran before the registry, so a fallback is
    a policy change, not a numerics change."""
    upd = jnp.asarray(rowterm_values, jnp.float32)
    flat = jnp.asarray(indices, jnp.int32).reshape(-1)
    # Padding indices (== dim by the ELL contract) land on the sentinel
    # column and are sliced off; anything beyond is dropped by XLA's
    # scatter semantics — either way padding contributes nothing.
    return jnp.zeros((dim + 1,), upd.dtype).at[flat].add(
        upd.reshape(-1))[:dim]
