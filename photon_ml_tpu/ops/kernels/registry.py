"""The fused-kernel registry: every Pallas program, behind one seam.

ROADMAP item 4's pattern, made structural: a hand-written kernel only
pays where the sweep says it does, so every fused program in this
package registers here with

* a **per-kernel flag** — default OFF unless a committed ``bench_kernels``
  sweep (docs/KERNELS.md "The sweep workflow") showed the fused program
  winning on the deployment box; overridable per-process
  (:meth:`KernelRegistry.set_enabled`) and per-environment
  (``PHOTON_KERNEL_<NAME>=0|1``);
* an **XLA fallback closure** — the exact math the call site would run
  unfused, so parity tests, the CPU smoke, and the degradation ladder
  all have a reference implementation with the registry's signature;
* an **interpret-mode path** — ``force_interpret()`` runs the Pallas
  program through the interpreter on CPU, which is how tier-1 keeps the
  whole registry exercised without a TPU (never timed: bench stamps
  interpret results invalid);
* **compile-cache counters tagged by backend** — resolving a kernel
  counts into ``photon_compile_cache_misses_total{cache="kernel_<name>",
  dtype=..., backend="pallas"|"xla"}`` on the first resolve per key and
  the hit counter after, so `photon-obs summarize --kernels` can split
  program builds by backend;
* a **loud failure ladder** — the fault site ``kernel.launch`` fires at
  the moment the registry commits to the Pallas backend; a fault there
  (or a non-TPU backend without interpret mode) degrades to the XLA
  closure and emits :class:`~photon_ml_tpu.utils.events.KernelFallback`
  + ``photon_kernel_fallbacks_total`` — the ingest native-fallback
  discipline, applied to kernels.

Resolution happens at program-BUILD time (service init, streamed-kernel
cache fill, bucket-program build), never per launch: the resolved
callable is jit-traceable and the backend choice is baked into the
compiled program, which is what keeps the one-program-per-stream
invariant intact (flag flips require a rebuild, and the per-site kernel
caches key on the resolved backend).

PML017 (docs/ANALYSIS.md) enforces the seam: a direct ``pl.pallas_call``
anywhere outside ``ops/kernels/`` is a lint finding.
"""

from __future__ import annotations

import dataclasses
import os
import threading
from typing import Callable, Optional

import jax

from photon_ml_tpu import obs
from photon_ml_tpu.faults import injector as faults
from photon_ml_tpu.faults import sites
from photon_ml_tpu.utils.events import KernelFallback, default_emitter


@dataclasses.dataclass(frozen=True)
class KernelSpec:
    """One registered kernel: the Pallas program, its XLA reference, and
    the flag default the committed sweep justified."""

    name: str
    pallas_fn: Callable  # (*args, interpret=bool) -> Array
    xla_fn: Callable  # (*args) -> Array, same signature minus interpret
    doc: str
    default_on: bool = False


@dataclasses.dataclass(frozen=True)
class ResolvedKernel:
    """The outcome of one registry resolution: a jit-traceable callable
    plus the backend it landed on. ``interpret`` marks the CPU
    interpreter path (parity-grade, never timing-grade)."""

    name: str
    fn: Callable
    backend: str  # "pallas" | "xla"
    interpret: bool = False

    def __call__(self, *args, **kw):
        return self.fn(*args, **kw)


class KernelRegistry:
    """Name → :class:`KernelSpec`, with per-kernel flag state.

    Thread-safety: registration happens at import time; flag overrides
    and resolves can race with serving threads, so mutation holds the
    lock (the counters have their own locks)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._specs: dict[str, KernelSpec] = {}
        self._overrides: dict[str, Optional[bool]] = {}
        self._force_interpret = False
        self._resolved_keys: set[tuple] = set()

    # -- registration ------------------------------------------------------

    def register(self, spec: KernelSpec) -> KernelSpec:
        with self._lock:
            if spec.name in self._specs:
                raise ValueError(f"kernel {spec.name!r} already registered")
            self._specs[spec.name] = spec
        return spec

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._specs)

    def get(self, name: str) -> KernelSpec:
        with self._lock:
            spec = self._specs.get(name)
        if spec is None:
            raise KeyError(f"unknown kernel {name!r} (registered: "
                           f"{self.names()})")
        return spec

    # -- flags -------------------------------------------------------------

    def set_enabled(self, name: str, value: Optional[bool]) -> None:
        """Override one kernel's flag (None restores the default). Takes
        effect at the next program BUILD — already-compiled programs keep
        the backend they resolved."""
        self.get(name)  # raise on unknown names, not silently no-op
        with self._lock:
            self._overrides[name] = value

    def enabled(self, name: str) -> bool:
        """Override > environment (``PHOTON_KERNEL_<NAME>``) > the
        registered sweep default."""
        spec = self.get(name)
        with self._lock:
            ov = self._overrides.get(name)
        if ov is not None:
            return ov
        env = os.environ.get(f"PHOTON_KERNEL_{name.upper()}")
        if env is not None:
            return env not in ("0", "false", "off", "")
        return spec.default_on

    def force_interpret(self, value: bool = True) -> None:
        """Run Pallas programs through the interpreter on non-TPU
        backends instead of falling back — the tier-1 CPU smoke/test
        mode. Parity-grade only; bench stamps interpret timings
        invalid."""
        with self._lock:
            self._force_interpret = value

    @property
    def interpret_forced(self) -> bool:
        return self._force_interpret

    def reset(self) -> None:
        """Clear overrides + interpret mode + counter first-seen state
        (tests)."""
        with self._lock:
            self._overrides.clear()
            self._force_interpret = False
            self._resolved_keys.clear()

    # -- resolution --------------------------------------------------------

    def resolve(self, name: str, dtype: str = "float32") -> ResolvedKernel:
        """Commit to a backend for ``name`` and hand back the program.

        The decision ladder, in order: flag off → XLA (policy, silent);
        injected ``kernel.launch`` fault → XLA (loud KernelFallback);
        TPU backend → Pallas; interpret forced → Pallas interpreter;
        anything else → XLA (loud KernelFallback — a flag asked for a
        fused program this box cannot run)."""
        spec = self.get(name)
        if not self.enabled(name):
            return self._done(spec, dtype, spec.xla_fn, "xla")
        try:
            faults.fire(sites.KERNEL_LAUNCH)
        except Exception as e:  # injected: degrade, never crash the site
            return self._fallback(spec, dtype,
                                  f"injected fault at kernel.launch "
                                  f"({type(e).__name__}: {e})")
        if jax.default_backend() == "tpu":
            return self._done(spec, dtype, spec.pallas_fn, "pallas")
        if self._force_interpret:
            def interp(*args, _fn=spec.pallas_fn, **kw):
                return _fn(*args, interpret=True, **kw)
            return self._done(spec, dtype, interp, "pallas",
                              interpret=True)
        return self._fallback(
            spec, dtype,
            f"no TPU backend (backend={jax.default_backend()})")

    # -- internals ---------------------------------------------------------

    def _fallback(self, spec: KernelSpec, dtype: str,
                  reason: str) -> ResolvedKernel:
        default_emitter.emit(KernelFallback(
            kernel=spec.name, backend="xla", reason=reason))
        return self._done(spec, dtype, spec.xla_fn, "xla")

    def _done(self, spec: KernelSpec, dtype: str, fn: Callable,
              backend: str, interpret: bool = False) -> ResolvedKernel:
        self._count(spec.name, dtype, backend)
        return ResolvedKernel(name=spec.name, fn=fn, backend=backend,
                              interpret=interpret)

    def _count(self, name: str, dtype: str, backend: str) -> None:
        """First resolve per (kernel, dtype, backend) is a program BUILD
        (the caller compiles a fresh jit program around it); later
        resolves are hits — the same miss/hit ledger the streamed kernel
        caches keep, tagged with the backend the program landed on.
        Fresh resolves also drop a ``kernel.resolve`` timeline instant
        (the raw material of ``photon-obs summarize --kernels``); hit
        resolves stay instant-free — a per-chunk resolve in a streamed
        hot loop must not flood the trace."""
        key = (name, dtype, backend)
        with self._lock:
            fresh = key not in self._resolved_keys
            if fresh:
                self._resolved_keys.add(key)
        if fresh:
            obs.instant("kernel.resolve", cat="kernel", kernel=name,
                        backend=backend, dtype=dtype,
                        interpret=self._force_interpret)
        mx = obs.metrics()
        if mx is None:
            return
        counter = ("photon_compile_cache_misses_total" if fresh
                   else "photon_compile_cache_hits_total")
        mx.counter(counter, cache=f"kernel_{name}", dtype=dtype,
                   backend=backend).inc()


_REGISTRY = KernelRegistry()


def registry() -> KernelRegistry:
    """The process-wide registry (kernels register at import of
    ``photon_ml_tpu.ops.kernels``)."""
    return _REGISTRY
