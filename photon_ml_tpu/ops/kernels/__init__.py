"""Fused Pallas kernels on the measured hot paths (docs/KERNELS.md).

The registry (:mod:`.registry`) is the only way production code reaches
a Pallas program — PML017 flags a raw ``pl.pallas_call`` anywhere else
in the package — and importing THIS package is what populates it: each
kernel module pairs a Pallas program with its XLA reference closure, and
the specs below bind them under a flag.

Flag defaults record the committed ``bench_kernels`` sweep (BENCH.md),
not hope: ``ell_scatter`` ships ON because BENCH_r05 measured the Pallas
scatter 4.6× over XLA on TPU at the bench shape (the auto-dispatch
ops/sparse_aggregators.py has trusted since r05 — the registry keeps
that decision, it just makes the fallback loud); the remaining five ship
OFF until a sweep on a TPU box flips them (this tree's committed sweeps
ran on the CPU host, where Pallas timings are interpret-mode and stamped
invalid — docs/KERNELS.md "The sweep workflow").
"""

from __future__ import annotations

from photon_ml_tpu.ops.kernels import (ell_scatter, re_rows, serving_score,
                                       stream_fused)
from photon_ml_tpu.ops.kernels.registry import (KernelSpec, ResolvedKernel,
                                                registry)

registry().register(KernelSpec(
    name="ell_scatter",
    pallas_fn=ell_scatter.scatter_rowterm_pallas,
    xla_fn=ell_scatter.scatter_rowterm_xla,
    doc="ELL scatter-add as one-hot compare+accumulate tiles "
        "(gradient of the sparse GLM pass)",
    default_on=True,  # BENCH_r05 scatter_pallas_d512_us: 4.6x over XLA
))

registry().register(KernelSpec(
    name="serving_score",
    pallas_fn=serving_score.score_rows_pallas,
    xla_fn=serving_score.score_rows_xla,
    doc="serving gather->int8-dequant->row-dot->scale as one program "
        "(int8 cache rows never materialize as f32 in HBM)",
))

registry().register(KernelSpec(
    name="stream_margins",
    pallas_fn=stream_fused.hot_margins_pallas,
    xla_fn=stream_fused.hot_margins_xla,
    doc="streamed hot-dense margins with int8 dequant fused into the "
        "matvec tiles (no (n,H) f32 HBM copy)",
))

registry().register(KernelSpec(
    name="stream_rmatvec",
    pallas_fn=stream_fused.hot_rmatvec_pallas,
    xla_fn=stream_fused.hot_rmatvec_xla,
    doc="streamed hot-dense gradient rmatvec with fused dequant "
        "(the gradient half of the chunk pass)",
))

registry().register(KernelSpec(
    name="re_gather_rows",
    pallas_fn=re_rows.gather_rows_pallas,
    xla_fn=re_rows.gather_rows_xla,
    doc="RE bucket warm-start row gather via scalar-prefetch block "
        "addressing (bit-exact data movement)",
))

registry().register(KernelSpec(
    name="re_scatter_rows",
    pallas_fn=re_rows.scatter_rows_pallas,
    xla_fn=re_rows.scatter_rows_xla,
    doc="RE bucket fitted-row scatter, table aliased in place "
        "(bit-exact data movement)",
))

__all__ = ["KernelSpec", "ResolvedKernel", "registry"]
