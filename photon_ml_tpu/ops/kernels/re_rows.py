"""RE bucket-solve gather/scatter fusion (registry names
``re_gather_rows`` / ``re_scatter_rows``).

A random-effect bucket wave (game/coordinates/random_effect.py
``_build_fits``) brackets its vmapped per-entity solves with two row
moves over the (num_entities+1, d) coefficient table:

    w0    = W[max(rows, 0)]                      # warm-start gather
    W'    = W.at[safe].set(w_fit, mode="drop")   # fitted-row scatter

XLA compiles each into its own gather/scatter program with the moved
rows staged through HBM between programs. These Pallas programs make
each move ONE grid schedule: the bucket's row ids ride scalar prefetch,
so the table BlockSpec's index_map addresses block (rows[i], 0) directly
— the row id IS the block address, and each row crosses HBM exactly
once. The scatter aliases the table in place (``input_output_aliases``),
so untouched rows are preserved without rewriting the table — the same
donation contract the XLA ``.at[].set`` path gets from
``donate_argnums``.

Both are pure data movement — no arithmetic — so parity with the XLA
path is BIT-exact by construction, which is what lets the refit
bit-identity invariant (docs/STREAMING.md) survive a backend flip.

Padding lanes (row id −1, ``mode="drop"`` on the XLA side) cannot be
"dropped" by a block schedule — every grid step writes somewhere — so
the wrapper redirects them at a valid target row and makes the write
content-identical (the target row's own incoming value), turning "drop"
into "write the same bytes twice": order-independent, hence race-free
even though redirected lanes collide with the real write.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from photon_ml_tpu.ops.kernels.ell_scatter import _pad_axis

Array = jax.Array

_LANE = 128


def _copy_kernel(rows_ref, src_ref, out_ref):
    del rows_ref  # consumed by the index maps, not the body
    out_ref[...] = src_ref[...]


def _scatter_kernel(rows_ref, vals_ref, w_ref, out_ref):
    # The table rides along only for the aliasing (out IS w_ref's
    # buffer); each grid step overwrites its target row with the lane's
    # values — redirected padding lanes write duplicate bytes.
    del rows_ref, w_ref
    out_ref[...] = vals_ref[...]


def gather_rows_pallas(W: Array, rows: Array,
                       interpret: bool = False) -> Array:
    """(B, d) W[max(rows, 0)] — the warm-start gather. Padding lanes
    (row id −1) read row 0, exactly like the XLA ``jnp.maximum`` path
    (the vmapped solve ignores those lanes; the clamp just keeps the
    read in-bounds)."""
    b = rows.shape[0]
    d = W.shape[1]
    w_p = _pad_axis(W, _LANE, 1, 0)
    rr = jnp.maximum(jnp.asarray(rows, jnp.int32), 0)
    out = pl.pallas_call(
        _copy_kernel,
        out_shape=jax.ShapeDtypeStruct((b, w_p.shape[1]), W.dtype),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b,),
            in_specs=[pl.BlockSpec((1, w_p.shape[1]),
                                   lambda i, r: (r[i], 0))],
            out_specs=pl.BlockSpec((1, w_p.shape[1]),
                                   lambda i, r: (i, 0)),
        ),
        interpret=interpret,
    )(rr, w_p)
    return out[:, :d]


def gather_rows_xla(W: Array, rows: Array) -> Array:
    return W[jnp.maximum(rows, 0)]


def scatter_rows_pallas(W: Array, rows: Array, vals: Array,
                        interpret: bool = False) -> Array:
    """W with vals[i] written at rows[i] (rows[i] < 0 dropped);
    untouched rows preserved via in-place aliasing.

    Invalid lanes are redirected at the lane holding the LARGEST row id
    (guaranteed valid when any lane is) and carry that lane's values, so
    the redirected write duplicates a real write byte-for-byte. When the
    whole wave is padding, they instead rewrite row 0 with its own
    current contents — a no-op scatter either way."""
    d = W.shape[1]
    w_p = _pad_axis(W, _LANE, 1, 0)
    v_p = _pad_axis(jnp.asarray(vals, W.dtype), _LANE, 1, 0)
    rows = jnp.asarray(rows, jnp.int32)
    valid = rows >= 0
    i_star = jnp.argmax(rows)  # lane of the largest (hence valid) row id
    row_star = jnp.maximum(rows[i_star], 0)
    any_valid = jnp.any(valid)
    safe_vals = jnp.where(any_valid, v_p[i_star], w_p[row_star])
    rows_fix = jnp.where(valid, rows, row_star)
    vals_fix = jnp.where(valid[:, None], v_p, safe_vals[None, :])
    out = pl.pallas_call(
        _scatter_kernel,
        out_shape=jax.ShapeDtypeStruct(w_p.shape, W.dtype),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(rows.shape[0],),
            in_specs=[
                pl.BlockSpec((1, w_p.shape[1]), lambda i, r: (i, 0)),
                pl.BlockSpec((1, w_p.shape[1]), lambda i, r: (r[i], 0)),
            ],
            out_specs=pl.BlockSpec((1, w_p.shape[1]),
                                   lambda i, r: (r[i], 0)),
        ),
        # Operand indices count the scalar-prefetch arg: 0=rows_fix,
        # 1=vals_fix, 2=w_p → alias the TABLE into the output.
        input_output_aliases={2: 0},
        interpret=interpret,
    )(rows_fix, vals_fix, w_p)
    return out[:, :d]


def scatter_rows_xla(W: Array, rows: Array, vals: Array) -> Array:
    W = jnp.asarray(W)
    safe = jnp.where(jnp.asarray(rows) >= 0, rows, W.shape[0])
    return W.at[safe].set(jnp.asarray(vals, W.dtype), mode="drop")
