"""Device-mesh conventions: the rebuild's "cluster" abstraction.

Reference parity: none file-for-file — this replaces the Spark runtime
(executors, torrent broadcast, netty shuffle, driver-coordinated
``treeAggregate``) with XLA's compiled collectives over a
``jax.sharding.Mesh`` (SURVEY.md §5 "Distributed communication backend").

Axis conventions:

- ``data``   — examples (fixed-effect data parallelism, P1) and entities
               (random-effect entity parallelism, P2). Gradient reductions
               ride ICI as ``psum`` over this axis.
- ``model``  — feature dimension for the sharded sparse path (P3, Criteo
               regime). Usually size 1.

Multi-host (the DCN story, SURVEY.md §2.5 P6 / §5): call
``initialize_distributed()`` (or ``make_mesh(distributed=True)``) once per
process before building the mesh. It wraps ``jax.distributed.initialize``,
which wires every host's local devices into one global device set; XLA then
routes intra-slice collectives over ICI and cross-slice traffic over DCN —
the same ``shard_map``/``psum`` programs compile unchanged from 1 chip to a
multi-host pod (collectives become no-ops at world size 1).

Coordinator discovery follows the standard JAX environment contract
(honored automatically on Cloud TPU metadata; settable explicitly anywhere):

- ``JAX_COORDINATOR_ADDRESS`` (or the ``coordinator_address`` argument)
- ``JAX_NUM_PROCESSES`` / ``JAX_PROCESS_ID`` (or arguments)

Failure model: there is NO lineage re-execution in XLA — a lost host kills
the step. Recovery is restart-from-checkpoint: relaunch the job and pass
``--resume`` to ``cli/game_train.py`` (game/checkpoint.py restores
per-(iteration, coordinate) state; see that module's crash-consistency
notes). This mirrors how the reference's Spark lineage recovery is replaced
throughout the rebuild.
"""

from __future__ import annotations

import logging
import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

logger = logging.getLogger("photon_ml_tpu.parallel")

# ``shard_map`` moved to the jax top level (jax >= 0.4.38); earlier
# releases only ship it under jax.experimental. Resolve once here so every
# call site (parallel/objective.py, parallel/sparse_objective.py, tests)
# stays version-agnostic.
if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map

DATA_AXIS = "data"
MODEL_AXIS = "model"

_distributed_initialized = False


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Join this process to the multi-host world (idempotent).

    Arguments default to the ``JAX_COORDINATOR_ADDRESS`` /
    ``JAX_NUM_PROCESSES`` / ``JAX_PROCESS_ID`` environment variables; on
    Cloud TPU all three are discoverable from metadata and may be omitted
    entirely. Returns True when running multi-process afterwards.

    Reference parity: the Spark cluster bootstrap (SparkSession + executor
    registration) — here one collective-runtime handshake, after which
    ``jax.devices()`` spans every host and ``make_mesh`` lays axes over the
    global device set.
    """
    global _distributed_initialized
    if _distributed_initialized:
        return jax.process_count() > 1
    kwargs = {}
    if coordinator_address or os.environ.get("JAX_COORDINATOR_ADDRESS"):
        kwargs["coordinator_address"] = (
            coordinator_address or os.environ["JAX_COORDINATOR_ADDRESS"])
    if num_processes is not None or os.environ.get("JAX_NUM_PROCESSES"):
        kwargs["num_processes"] = int(
            num_processes if num_processes is not None
            else os.environ["JAX_NUM_PROCESSES"])
    if process_id is not None or os.environ.get("JAX_PROCESS_ID"):
        kwargs["process_id"] = int(
            process_id if process_id is not None
            else os.environ["JAX_PROCESS_ID"])
    jax.distributed.initialize(**kwargs)
    _distributed_initialized = True
    logger.info("distributed runtime up: process %d/%d, %d local / %d "
                "global devices", jax.process_index(), jax.process_count(),
                jax.local_device_count(), jax.device_count())
    return jax.process_count() > 1


def make_mesh(
    num_data: Optional[int] = None,
    num_model: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
    distributed: bool = False,
    local: bool = False,
) -> Mesh:
    """Build a (data, model) mesh over the available devices.

    With ``distributed=True``, first joins the multi-host world (see
    ``initialize_distributed``) so the mesh spans every host's devices;
    shardings over ``data`` then reduce over ICI within a slice and DCN
    across slices, exactly as laid out.

    With ``local=True``, the mesh spans THIS HOST's devices only — the
    fabric topology (fabric/collective.py): intra-host reductions stay
    compiled ICI ``psum`` programs, and the cross-host level is the
    host-driven ``FabricComm`` allreduce instead of an XLA collective
    (mandatory on CPU process groups, where XLA's multiprocess
    collectives are not implemented; on TPU it trades the compiled DCN
    path for a faultable one).
    """
    if distributed:
        initialize_distributed()
    if local and devices is None:
        devices = jax.local_devices()
    devices = list(devices if devices is not None else jax.devices())
    if num_data is None:
        num_data = len(devices) // num_model
    if num_data * num_model != len(devices):
        devices = devices[: num_data * num_model]
    arr = np.asarray(devices).reshape(num_data, num_model)
    return Mesh(arr, (DATA_AXIS, MODEL_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def data_sharded(mesh: Mesh, ndim: int = 1) -> NamedSharding:
    """Shard the leading (example/entity) dim over ``data``."""
    return NamedSharding(mesh, P(DATA_AXIS, *(None,) * (ndim - 1)))


def pad_to_multiple(n: int, k: int) -> int:
    return ((n + k - 1) // k) * k


def shard_batch(batch, mesh: Mesh):
    """Pad a LabeledBatch to a multiple of the data-axis size and place it
    sharded over ``data`` (zero-weight padding rows are inert by design)."""
    k = mesh.shape[DATA_AXIS]
    n = batch.num_rows
    padded = batch.pad_to(pad_to_multiple(n, k))
    return jax.device_put(
        padded,
        jax.tree.map(
            lambda leaf: data_sharded(mesh, np.ndim(leaf)),
            padded,
        ),
    )
