"""Device-mesh conventions: the rebuild's "cluster" abstraction.

Reference parity: none file-for-file — this replaces the Spark runtime
(executors, torrent broadcast, netty shuffle, driver-coordinated
``treeAggregate``) with XLA's compiled collectives over a
``jax.sharding.Mesh`` (SURVEY.md §5 "Distributed communication backend").

Axis conventions:

- ``data``   — examples (fixed-effect data parallelism, P1) and entities
               (random-effect entity parallelism, P2). Gradient reductions
               ride ICI as ``psum`` over this axis.
- ``model``  — feature dimension for the sharded sparse path (P3, Criteo
               regime). Usually size 1.

Multi-host: call ``jax.distributed.initialize()`` before building the mesh;
XLA routes intra-slice collectives over ICI and cross-slice over DCN. The
same code compiles unchanged on 1 device (all collectives become no-ops).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(
    num_data: Optional[int] = None,
    num_model: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a (data, model) mesh over the available devices."""
    devices = list(devices if devices is not None else jax.devices())
    if num_data is None:
        num_data = len(devices) // num_model
    if num_data * num_model != len(devices):
        devices = devices[: num_data * num_model]
    arr = np.asarray(devices).reshape(num_data, num_model)
    return Mesh(arr, (DATA_AXIS, MODEL_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def data_sharded(mesh: Mesh, ndim: int = 1) -> NamedSharding:
    """Shard the leading (example/entity) dim over ``data``."""
    return NamedSharding(mesh, P(DATA_AXIS, *(None,) * (ndim - 1)))


def pad_to_multiple(n: int, k: int) -> int:
    return ((n + k - 1) // k) * k


def shard_batch(batch, mesh: Mesh):
    """Pad a LabeledBatch to a multiple of the data-axis size and place it
    sharded over ``data`` (zero-weight padding rows are inert by design)."""
    k = mesh.shape[DATA_AXIS]
    n = batch.num_rows
    padded = batch.pad_to(pad_to_multiple(n, k))
    return jax.device_put(
        padded,
        jax.tree.map(
            lambda leaf: data_sharded(mesh, np.ndim(leaf)),
            padded,
        ),
    )
