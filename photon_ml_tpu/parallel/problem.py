"""Distributed GLM optimization problem: the fixed-effect training path.

Reference parity: photon-api ``optimization/DistributedOptimizationProblem.
scala`` — binds (optimizer, distributed objective, regularization, variance
mode) and runs the full L-BFGS/TRON/OWL-QN fit over the cluster. Here the
"cluster" is a device mesh and the entire fit is one jit-compiled program:
the optimizer's while_loop body contains the psum-reduced objective, so a
whole training run is a single XLA executable with zero host round-trips.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from photon_ml_tpu.data.batch import LabeledBatch
from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.normalization import NormalizationContext
from photon_ml_tpu.ops.losses import PointwiseLoss
from photon_ml_tpu.optim import (OptResult, OptimizerType,
                                 l1_weights_vector, optimize, with_l2,
                                 with_l2_hvp)
from photon_ml_tpu.optim.problem import (GLMOptimizationConfiguration,
                                         VarianceComputationType,
                                         resolve_optimizer_config,
                                         variances_from_diagonal,
                                         variances_from_matrix)
from photon_ml_tpu.optim.regularization import intercept_mask
from photon_ml_tpu.parallel import objective as dobj
from photon_ml_tpu.parallel.mesh import shard_batch

Array = jax.Array


def run(
    loss: PointwiseLoss,
    batch: LabeledBatch,
    mesh: Mesh,
    config: GLMOptimizationConfiguration,
    initial: Optional[Coefficients] = None,
    norm: NormalizationContext = NormalizationContext(),
    intercept_index: Optional[int] = None,
    already_sharded: bool = False,
) -> tuple[Coefficients, OptResult]:
    """Fit one GLM over the mesh (DistributedOptimizationProblem.run)."""
    if not already_sharded:
        batch = shard_batch(batch, mesh)
    dim = batch.dim
    mask = jnp.asarray(intercept_mask(dim, intercept_index))
    reg = config.regularization
    l2 = reg.l2_weight()

    vg = with_l2(dobj.make_value_and_gradient(loss, mesh, batch, norm), l2, mask)
    hvp = with_l2_hvp(dobj.make_hvp(loss, mesh, batch, norm), l2, mask)

    l1 = reg.l1_weight()
    l1w = l1_weights_vector(l1, dim, intercept_index) if l1 > 0.0 else None
    opt_cfg = resolve_optimizer_config(config.optimizer, l1w is not None)

    w0 = initial.means if initial is not None else jnp.zeros(
        (dim,), batch.features.dtype)
    result = optimize(vg, w0, opt_cfg, hvp=hvp, l1_weights=l1w)

    variances = None
    kind = VarianceComputationType(config.variance_computation)
    if kind == VarianceComputationType.SIMPLE:
        variances = variances_from_diagonal(
            dobj.make_hessian_diagonal(loss, mesh, batch, norm)(result.w),
            l2, mask)
    elif kind == VarianceComputationType.FULL:
        variances = variances_from_matrix(
            dobj.make_hessian_matrix(loss, mesh, batch, norm)(result.w),
            l2, mask)

    return Coefficients(means=result.w, variances=variances), result


def run_grid(
    loss: PointwiseLoss,
    batch: LabeledBatch,
    mesh: Mesh,
    config: GLMOptimizationConfiguration,
    lambdas,
    initial: Optional[Coefficients] = None,
    norm: NormalizationContext = NormalizationContext(),
    intercept_index: Optional[int] = None,
    already_sharded: bool = False,
) -> tuple[Array, OptResult]:
    """Fit the SAME GLM at every L2 weight in ``lambdas`` as ONE compiled
    program — the whole solver ``vmap``-ped over the regularization axis
    (SURVEY §2.5 P5's optional vmap-over-λ; the reference loops its
    reg-weight grid sequentially through Spark jobs).

    Returns ``(W, results)`` with ``W`` of shape (len(lambdas), dim) and a
    stacked OptResult (per-λ leaves). L2/NONE regularization with
    L-BFGS/TRON only — L1 grids (OWL-QN's per-λ orthant sets) and variance
    computation stay on the sequential :func:`run` path.
    """
    reg = config.regularization
    if reg.l1_weight() > 0.0:
        raise ValueError("run_grid handles L2/NONE grids; L1 grids use "
                         "sequential run() (OWL-QN per-λ orthant sets)")
    if OptimizerType(config.optimizer.optimizer_type) == OptimizerType.OWLQN:
        raise ValueError("run_grid supports L-BFGS/TRON; OWL-QN exists for "
                         "L1 objectives, which run_grid does not handle")
    if VarianceComputationType(config.variance_computation) != \
            VarianceComputationType.NONE:
        raise ValueError("run_grid does not compute variances; evaluate "
                         "them per selected model via run()")
    if not already_sharded:
        batch = shard_batch(batch, mesh)
    dim = batch.dim
    mask = jnp.asarray(intercept_mask(dim, intercept_index))
    base_vg = dobj.make_value_and_gradient(loss, mesh, batch, norm)
    base_hvp = dobj.make_hvp(loss, mesh, batch, norm)
    opt_cfg = resolve_optimizer_config(config.optimizer, False)
    w0 = initial.means if initial is not None else jnp.zeros(
        (dim,), batch.features.dtype)

    def solve(lam):
        # λ is a traced vmap lane — fold it inline (with_l2's zero-weight
        # shortcut cannot branch on a tracer).
        def vg(w):
            f, g = base_vg(w)
            wm = w * mask
            return f + 0.5 * lam * jnp.sum(wm * wm), g + lam * wm

        def hvp(w, v):
            return base_hvp(w, v) + lam * (v * mask)

        return optimize(vg, w0, opt_cfg, hvp=hvp)

    results = jax.vmap(solve)(jnp.asarray(lambdas, jnp.float32))
    return results.w, results
