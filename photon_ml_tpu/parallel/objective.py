"""Distributed GLM objectives: per-shard aggregation + psum over ICI.

Reference parity: photon-api ``function/DistributedObjectiveFunction.scala``
and ``function/glm/DistributedGLMLossFunction.scala`` — there, each
evaluation broadcasts coefficients to executors and runs
``RDD[LabeledPoint].treeAggregate(aggregator)(add, merge, depth=2)``; here,
coefficients are replicated by sharding (no explicit broadcast exists), each
device computes its shard's fused aggregate (one MXU matmul pair), and the
tree-merge is a single ``lax.psum`` compiled onto the ICI ring. The entire
optimizer runs inside ONE jit program — there is no per-iteration host
round-trip at all, which is the key structural speedup over the reference
(driver⇄executor RPC per L-BFGS iteration).

``shard_map`` is used (rather than relying on jit's auto-spmd alone) so the
collective placement is explicit and testable: sharded == unsharded numerics
is asserted in tests, mirroring the reference's
``DistributedGLMLossFunctionIntegTest`` (distributed grad == local grad).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from photon_ml_tpu.data.batch import LabeledBatch
from photon_ml_tpu.normalization import NormalizationContext
from photon_ml_tpu.ops import aggregators as agg
from photon_ml_tpu.ops.losses import PointwiseLoss
from photon_ml_tpu.parallel.mesh import DATA_AXIS, shard_map

Array = jax.Array

_IDENTITY = NormalizationContext()


def _batch_specs(batch: LabeledBatch) -> LabeledBatch:
    """PartitionSpecs sharding the example dim of every leaf over ``data``."""
    return jax.tree.map(
        lambda leaf: P(DATA_AXIS, *(None,) * (jnp.ndim(leaf) - 1)), batch)


def make_value_and_gradient(
    loss: PointwiseLoss,
    mesh: Mesh,
    batch: LabeledBatch,
    norm: NormalizationContext = _IDENTITY,
):
    """(w) → (Σ value, Σ grad) over the full sharded batch.

    The returned callable closes over the sharded batch; coefficients are
    replicated in, results are replicated out.
    """
    specs = _batch_specs(batch)

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(P(), specs), out_specs=(P(), P()))
    def _vg(w, b):
        v, g = agg.value_and_gradient(loss, w, b, norm)
        return lax.psum(v, DATA_AXIS), lax.psum(g, DATA_AXIS)

    return lambda w: _vg(w, batch)


def make_hvp(
    loss: PointwiseLoss,
    mesh: Mesh,
    batch: LabeledBatch,
    norm: NormalizationContext = _IDENTITY,
):
    """(w, v) → Σ H·v over the full sharded batch (TRON's inner product)."""
    specs = _batch_specs(batch)

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(P(), P(), specs), out_specs=P())
    def _hvp(w, v, b):
        return lax.psum(agg.hessian_vector(loss, w, v, b, norm), DATA_AXIS)

    return lambda w, v: _hvp(w, v, batch)


def make_hessian_diagonal(
    loss: PointwiseLoss,
    mesh: Mesh,
    batch: LabeledBatch,
    norm: NormalizationContext = _IDENTITY,
):
    specs = _batch_specs(batch)

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(P(), specs), out_specs=P())
    def _hd(w, b):
        return lax.psum(agg.hessian_diagonal(loss, w, b, norm), DATA_AXIS)

    return lambda w: _hd(w, batch)


def make_hessian_matrix(
    loss: PointwiseLoss,
    mesh: Mesh,
    batch: LabeledBatch,
    norm: NormalizationContext = _IDENTITY,
):
    specs = _batch_specs(batch)

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(P(), specs), out_specs=P())
    def _hm(w, b):
        return lax.psum(agg.hessian_matrix(loss, w, b, norm), DATA_AXIS)

    return lambda w: _hm(w, batch)
