"""Distributed sparse-GLM objectives: data-parallel and feature-sharded.

The Criteo-scale seam (SURVEY.md §2.5 P3): examples shard over the ``data``
mesh axis exactly like the dense path; for feature spaces too large to
replicate, the coefficient vector additionally shards over the ``model``
axis (tensor-parallel analogue):

    margins:  each model-rank gathers from its coefficient slice for the
              indices it owns → partial margins → ``psum`` over ``model``
    gradient: each model-rank scatter-adds ONLY into its own slice (no
              model-axis communication at all) → ``psum`` over ``data``

That is, the forward pass all-reduces activations (n,) — tiny — while the
backward pass keeps the (d,) gradient fully sharded; coefficients never
travel. This mirrors how the reference keeps huge feature maps out of
driver memory via PalDB + sparse vectors, re-expressed as sharding.

Reference parity: function/glm/DistributedGLMLossFunction.scala
(treeAggregate → psum), index maps for the huge-d regime.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from photon_ml_tpu.data.sparse import SparseBatch
from photon_ml_tpu.ops import sparse_aggregators as sagg
from photon_ml_tpu.ops.losses import PointwiseLoss
from photon_ml_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, shard_map

Array = jax.Array


def _batch_specs(batch: SparseBatch) -> SparseBatch:
    return jax.tree.map(
        lambda leaf: P(DATA_AXIS, *(None,) * (jnp.ndim(leaf) - 1)), batch)


def _local_margin_terms(batch: SparseBatch, w_local: Array,
                        lo: Array) -> Array:
    """Per-rank partial margins from the locally-owned coefficient slice.

    Out-of-slice indices clip to a masked gather; each nonzero is owned by
    exactly one rank, so the model-axis psum reconstructs the full margin.
    """
    d_local = w_local.shape[0]
    ids = batch.indices - lo
    in_slice = (ids >= 0) & (ids < d_local)
    gathered = w_local[jnp.clip(ids, 0, d_local - 1)]
    return jnp.sum(jnp.where(in_slice, batch.values * gathered, 0.0),
                   axis=-1)


def _local_scatter(batch: SparseBatch, r: Array, d_local: int,
                   lo: Array) -> Array:
    """Scatter r ⊗ values into this rank's slice; others' columns drop."""
    ids = batch.indices - lo
    in_slice = (ids >= 0) & (ids < d_local)
    upd = jnp.where(in_slice, r[..., None] * batch.values, 0.0).reshape(-1)
    flat = jnp.where(in_slice, ids, d_local).reshape(-1)
    return jnp.zeros((d_local + 1,), upd.dtype).at[flat].add(upd)[:d_local]


def make_value_and_gradient(
    loss: PointwiseLoss,
    mesh: Mesh,
    batch: SparseBatch,
    feature_sharded: bool = False,
):
    """(w) → (Σ value, Σ grad) over the sharded sparse batch.

    ``feature_sharded=False``: w replicated (few-M features and below).
    ``feature_sharded=True``: w sharded over ``model`` — w's padded length
    must divide evenly by the model-axis size.
    """
    specs = _batch_specs(batch)

    if not feature_sharded:
        @functools.partial(shard_map, mesh=mesh,
                           in_specs=(P(), specs), out_specs=(P(), P()))
        def _vg(w, b):
            v, g = sagg.value_and_gradient(loss, w, b)
            return lax.psum(v, DATA_AXIS), lax.psum(g, DATA_AXIS)

        return lambda w: _vg(w, batch)

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(P(MODEL_AXIS), specs),
                       out_specs=(P(), P(MODEL_AXIS)))
    def _vg_sharded(w_local, b):
        d_local = w_local.shape[0]
        lo = lax.axis_index(MODEL_AXIS) * d_local
        partial_m = _local_margin_terms(b, w_local, lo)
        z = lax.psum(partial_m, MODEL_AXIS) + b.offsets
        l, dl = loss.loss_and_dz(z, b.labels)
        wmask = b.weights > 0.0
        value = jnp.sum(jnp.where(wmask, b.weights * l, 0.0), axis=-1)
        value = lax.psum(value, DATA_AXIS)
        r = jnp.where(wmask, b.weights * dl, 0.0)
        g_local = _local_scatter(b, r, d_local, lo)
        return value, lax.psum(g_local, DATA_AXIS)

    return lambda w: _vg_sharded(w, batch)


def make_hvp(
    loss: PointwiseLoss,
    mesh: Mesh,
    batch: SparseBatch,
    feature_sharded: bool = False,
):
    """(w, v) → Σ H·v (TRON inner loop) over the sharded sparse batch."""
    specs = _batch_specs(batch)

    if not feature_sharded:
        @functools.partial(shard_map, mesh=mesh,
                           in_specs=(P(), P(), specs), out_specs=P())
        def _hvp(w, v, b):
            return lax.psum(sagg.hessian_vector(loss, w, v, b), DATA_AXIS)

        return lambda w, v: _hvp(w, v, batch)

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(P(MODEL_AXIS), P(MODEL_AXIS), specs),
                       out_specs=P(MODEL_AXIS))
    def _hvp_sharded(w_local, v_local, b):
        d_local = w_local.shape[0]
        lo = lax.axis_index(MODEL_AXIS) * d_local
        z = lax.psum(_local_margin_terms(b, w_local, lo), MODEL_AXIS) \
            + b.offsets
        xv = lax.psum(_local_margin_terms(b, v_local, lo), MODEL_AXIS)
        d2 = loss.d2z(z, b.labels)
        r = jnp.where(b.weights > 0.0, b.weights * d2, 0.0) * xv
        return lax.psum(_local_scatter(b, r, d_local, lo), DATA_AXIS)

    return lambda w, v: _hvp_sharded(w, v, batch)


def _hybrid_leaves(shb):
    """The data-sharded array leaves of a HybridShards (leading axis S)."""
    return (shb.X_hot, shb.cold_rowids, shb.cold_vals, shb.labels,
            shb.weights, shb.offsets)


def _hybrid_specs(leaves):
    return jax.tree.map(
        lambda leaf: P(DATA_AXIS, *(None,) * (jnp.ndim(leaf) - 1)), leaves)


def make_hybrid_value_and_gradient(loss: PointwiseLoss, mesh: Mesh, shb):
    """(w_perm) → (Σ value, Σ grad) over the sharded hybrid layout.

    w is replicated in the GLOBAL permuted space; each shard runs the
    single-device hot/cold aggregate on its local rows and the data-axis
    psum assembles the exact global value/gradient — the same collective
    placement as the dense data-parallel path (hot block) with the cold
    classes' random crossings kept entirely shard-local.
    """
    from photon_ml_tpu.ops import hybrid_sparse as hybrid

    leaves = _hybrid_leaves(shb)

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(P(), _hybrid_specs(leaves)),
                       out_specs=(P(), P()))
    def _vg(w, lv):
        hb = hybrid.local_shard(shb, *lv)
        v, g = hybrid.value_and_gradient(loss, w, hb)
        return lax.psum(v, DATA_AXIS), lax.psum(g, DATA_AXIS)

    return lambda w: _vg(w, leaves)


def make_hybrid_hvp(loss: PointwiseLoss, mesh: Mesh, shb):
    """(w_perm, v_perm) → Σ H·v over the sharded hybrid layout."""
    from photon_ml_tpu.ops import hybrid_sparse as hybrid

    leaves = _hybrid_leaves(shb)

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(P(), P(), _hybrid_specs(leaves)),
                       out_specs=P())
    def _hvp(w, v, lv):
        hb = hybrid.local_shard(shb, *lv)
        return lax.psum(hybrid.hessian_vector(loss, w, v, hb), DATA_AXIS)

    return lambda w, v: _hvp(w, v, leaves)


def make_hybrid_hessian_diagonal(loss: PointwiseLoss, mesh: Mesh, shb):
    """(w_perm) → Σ diag(H) in permuted space (SIMPLE variances)."""
    from photon_ml_tpu.ops import hybrid_sparse as hybrid

    leaves = _hybrid_leaves(shb)

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(P(), _hybrid_specs(leaves)),
                       out_specs=P())
    def _hd(w, lv):
        hb = hybrid.local_shard(shb, *lv)
        return lax.psum(hybrid.hessian_diagonal(loss, w, hb), DATA_AXIS)

    return lambda w: _hd(w, leaves)


def make_hybrid_margins(mesh: Mesh, shb):
    """(w_perm) → (S·n_l,) flat margins (row order = padded global order).

    Scores stay data-sharded on exit (out spec P(data)): no collective at
    all — each shard's rows are scored where they live.
    """
    from photon_ml_tpu.ops import hybrid_sparse as hybrid

    leaves = _hybrid_leaves(shb)

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(P(), _hybrid_specs(leaves)),
                       out_specs=P(DATA_AXIS))
    def _margins(w, lv):
        hb = hybrid.local_shard(shb, *lv)
        return hybrid.margins(hb, w)

    return lambda w: _margins(w, leaves)


def make_hessian_diagonal(
    loss: PointwiseLoss,
    mesh: Mesh,
    batch: SparseBatch,
    feature_sharded: bool = False,
):
    specs = _batch_specs(batch)

    if not feature_sharded:
        @functools.partial(shard_map, mesh=mesh,
                           in_specs=(P(), specs), out_specs=P())
        def _hd(w, b):
            return lax.psum(sagg.hessian_diagonal(loss, w, b), DATA_AXIS)

        return lambda w: _hd(w, batch)

    @functools.partial(shard_map, mesh=mesh,
                       in_specs=(P(MODEL_AXIS), specs),
                       out_specs=P(MODEL_AXIS))
    def _hd_sharded(w_local, b):
        d_local = w_local.shape[0]
        lo = lax.axis_index(MODEL_AXIS) * d_local
        z = lax.psum(_local_margin_terms(b, w_local, lo), MODEL_AXIS) \
            + b.offsets
        d2 = loss.d2z(z, b.labels)
        r = jnp.where(b.weights > 0.0, b.weights * d2, 0.0)
        sq = SparseBatch(
            indices=b.indices, values=b.values * b.values, labels=b.labels,
            weights=b.weights, offsets=b.offsets,
            num_features=b.num_features)
        return lax.psum(_local_scatter(sq, r, d_local, lo), DATA_AXIS)

    return lambda w: _hd_sharded(w, batch)
