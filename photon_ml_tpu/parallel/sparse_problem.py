"""Sparse distributed GLM fit: the Criteo-path optimization problem.

Reference parity: optimization/DistributedOptimizationProblem.scala bound to
a sparse DistributedGLMLossFunction. Same optimizer state machines as the
dense path (L-BFGS / OWL-QN / TRON run on the dense (d,) coefficient
vector); only the objective evaluation is sparse. With
``feature_sharded=True`` the coefficient dimension is padded to a multiple
of the mesh's ``model`` axis and every optimizer array (w, grads, L-BFGS
history) carries that sharding — XLA partitions the two-loop recursion's
dots and axpys automatically.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from photon_ml_tpu.data.sparse import SparseBatch
from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.ops.losses import PointwiseLoss
from photon_ml_tpu.optim import (OptResult, l1_weights_vector, optimize,
                                 with_l2, with_l2_hvp)
from photon_ml_tpu.optim.problem import (GLMOptimizationConfiguration,
                                         VarianceComputationType,
                                         resolve_optimizer_config,
                                         variances_from_diagonal)
from photon_ml_tpu.optim.regularization import intercept_mask
from photon_ml_tpu.parallel import sparse_objective as sobj
from photon_ml_tpu.parallel.mesh import (DATA_AXIS, MODEL_AXIS,
                                         pad_to_multiple)

Array = jax.Array


def shard_sparse_batch(batch: SparseBatch, mesh: Mesh) -> SparseBatch:
    """Pad rows to the data-axis size and place shards on devices."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    k = mesh.shape[DATA_AXIS]
    padded = batch.pad_to(pad_to_multiple(batch.num_rows, k))
    return jax.device_put(
        padded,
        jax.tree.map(
            lambda leaf: NamedSharding(
                mesh, P(DATA_AXIS, *(None,) * (np.ndim(leaf) - 1))),
            padded))


def _pad_features(batch: SparseBatch, d_pad: int) -> SparseBatch:
    """Re-point ELL padding slots at the new one-past-end sentinel.

    Uses jnp ops so an already device-placed batch keeps its sharding
    (np.asarray here would pull shards back to host and silently drop the
    row sharding the caller paid for).
    """
    if d_pad == batch.num_features:
        return batch
    xp = jnp if isinstance(batch.indices, jax.Array) else np
    idx = xp.where(batch.indices == batch.num_features, d_pad,
                   batch.indices).astype(xp.int32)
    return SparseBatch(
        indices=idx, values=batch.values, labels=batch.labels,
        weights=batch.weights, offsets=batch.offsets, num_features=d_pad)


def run_hybrid(
    loss: PointwiseLoss,
    hb,
    config: GLMOptimizationConfiguration,
    initial: Optional[Coefficients] = None,
    intercept_index_permuted: Optional[int] = None,
) -> tuple[Coefficients, OptResult]:
    """Fit one GLM over a HybridSparseBatch (ops/hybrid_sparse.py) —
    the single-device Criteo fast path.

    The whole solve runs in the hybrid layout's PERMUTED feature space
    (count-descending relabeling): the L2 fold, L1 weights, intercept
    mask, optimizer state, and variance diagonal all live there, and only
    the returned Coefficients are mapped back. L2/L1 are permutation-
    equivariant, so this is exact. ``intercept_index_permuted`` is the
    intercept's PERMUTED column (callers map it once at staging).
    """
    from photon_ml_tpu.ops import hybrid_sparse as hybrid

    dim = hb.num_features
    mask = jnp.asarray(intercept_mask(dim, intercept_index_permuted))
    reg = config.regularization
    l2 = reg.l2_weight()

    vg = with_l2(
        lambda w: hybrid.value_and_gradient(loss, w, hb), l2, mask)
    hvp = with_l2_hvp(
        lambda w, v: hybrid.hessian_vector(loss, w, v, hb), l2, mask)

    l1 = reg.l1_weight()
    l1w = (jnp.asarray(l1 * intercept_mask(dim, intercept_index_permuted))
           if l1 > 0.0 else None)
    opt_cfg = resolve_optimizer_config(config.optimizer, l1w is not None)

    if initial is not None:
        w0 = hybrid.to_permuted_space(hb, jnp.asarray(initial.means))
    else:
        w0 = jnp.zeros((dim,), jnp.float32)

    result = optimize(vg, w0, opt_cfg, hvp=hvp, l1_weights=l1w)

    variances = None
    kind = VarianceComputationType(config.variance_computation)
    if kind == VarianceComputationType.SIMPLE:
        diag = hybrid.hessian_diagonal(loss, result.w, hb)
        variances = hybrid.to_original_space(
            hb, variances_from_diagonal(diag, l2, mask))
    elif kind == VarianceComputationType.FULL:
        raise NotImplementedError(
            "FULL variance needs the dense d×d Hessian — not available at "
            "sparse/Criteo scale (use SIMPLE, as the reference does)")

    means = hybrid.to_original_space(hb, result.w)
    return Coefficients(means=means, variances=variances), result


def shard_hybrid(shb, mesh: Mesh):
    """Place a HybridShards on the mesh: data arrays' leading shard axis
    over ``data``, the permutation tables replicated."""
    import dataclasses as dc

    from jax.sharding import NamedSharding, PartitionSpec as P

    def put_data(leaf):
        return jax.device_put(leaf, NamedSharding(
            mesh, P(DATA_AXIS, *(None,) * (np.ndim(leaf) - 1))))

    rep = NamedSharding(mesh, P())
    return dc.replace(
        shb,
        X_hot=put_data(shb.X_hot),
        cold_rowids=tuple(put_data(a) for a in shb.cold_rowids),
        cold_vals=tuple(put_data(a) for a in shb.cold_vals),
        labels=put_data(shb.labels),
        weights=put_data(shb.weights),
        offsets=put_data(shb.offsets),
        perm=jax.device_put(shb.perm, rep),
        inv_perm=jax.device_put(shb.inv_perm, rep),
    )


def run_hybrid_sharded(
    loss: PointwiseLoss,
    shb,
    mesh: Mesh,
    config: GLMOptimizationConfiguration,
    initial: Optional[Coefficients] = None,
    intercept_index_permuted: Optional[int] = None,
) -> tuple[Coefficients, OptResult]:
    """Fit one GLM over a HybridShards — the multi-device Criteo fast path.

    Identical contract to ``run_hybrid``: the whole solve lives in the
    GLOBAL permuted feature space (replicated w; the shard_map objectives
    psum per-shard hot/cold aggregates over ``data``), and only the
    returned Coefficients map back to original column order.
    """
    from photon_ml_tpu.parallel import sparse_objective as sobj_mod

    dim = shb.num_features
    mask = jnp.asarray(intercept_mask(dim, intercept_index_permuted))
    reg = config.regularization
    l2 = reg.l2_weight()

    vg = with_l2(
        sobj_mod.make_hybrid_value_and_gradient(loss, mesh, shb), l2, mask)
    hvp = with_l2_hvp(
        sobj_mod.make_hybrid_hvp(loss, mesh, shb), l2, mask)

    l1 = reg.l1_weight()
    l1w = (jnp.asarray(
        l1 * intercept_mask(dim, intercept_index_permuted))
        if l1 > 0.0 else None)
    opt_cfg = resolve_optimizer_config(config.optimizer, l1w is not None)

    if initial is not None:
        w0 = jnp.asarray(initial.means)[shb.perm]
    else:
        w0 = jnp.zeros((dim,), jnp.float32)

    result = optimize(vg, w0, opt_cfg, hvp=hvp, l1_weights=l1w)

    variances = None
    kind = VarianceComputationType(config.variance_computation)
    if kind == VarianceComputationType.SIMPLE:
        diag = sobj_mod.make_hybrid_hessian_diagonal(
            loss, mesh, shb)(result.w)
        variances = variances_from_diagonal(diag, l2, mask)[shb.inv_perm]
    elif kind == VarianceComputationType.FULL:
        raise NotImplementedError(
            "FULL variance needs the dense d×d Hessian — not available at "
            "sparse/Criteo scale (use SIMPLE, as the reference does)")

    means = result.w[shb.inv_perm]
    return Coefficients(means=means, variances=variances), result


def run(
    loss: PointwiseLoss,
    batch: SparseBatch,
    mesh: Mesh,
    config: GLMOptimizationConfiguration,
    initial: Optional[Coefficients] = None,
    intercept_index: Optional[int] = None,
    feature_sharded: bool = False,
    already_sharded: bool = False,
) -> tuple[Coefficients, OptResult]:
    """Fit one sparse GLM over the mesh; returns original-dim coefficients."""
    dim = batch.num_features
    d_pad = dim
    if feature_sharded:
        d_pad = pad_to_multiple(dim, mesh.shape[MODEL_AXIS])
        batch = _pad_features(batch, d_pad)
    if not already_sharded:
        batch = shard_sparse_batch(batch, mesh)

    mask = np.zeros(d_pad, np.float32)
    mask[:dim] = intercept_mask(dim, intercept_index)
    mask = jnp.asarray(mask)
    reg = config.regularization
    l2 = reg.l2_weight()

    vg = with_l2(
        sobj.make_value_and_gradient(loss, mesh, batch, feature_sharded),
        l2, mask)
    hvp = with_l2_hvp(
        sobj.make_hvp(loss, mesh, batch, feature_sharded), l2, mask)

    l1 = reg.l1_weight()
    if l1 > 0.0:
        # Host-built (jit-safe: no device array ever crosses back to np).
        l1w = np.zeros(d_pad, np.float32)
        l1w[:dim] = l1 * intercept_mask(dim, intercept_index)
        l1w = jnp.asarray(l1w)
    else:
        l1w = None
    opt_cfg = resolve_optimizer_config(config.optimizer, l1w is not None)

    if initial is not None:
        w0 = jnp.zeros((d_pad,), jnp.float32).at[:dim].set(initial.means)
    else:
        w0 = jnp.zeros((d_pad,), jnp.float32)
    if feature_sharded:
        from jax.sharding import NamedSharding, PartitionSpec as P
        w0 = jax.device_put(w0, NamedSharding(mesh, P(MODEL_AXIS)))

    result = optimize(vg, w0, opt_cfg, hvp=hvp, l1_weights=l1w)

    variances = None
    kind = VarianceComputationType(config.variance_computation)
    if kind == VarianceComputationType.SIMPLE:
        diag = sobj.make_hessian_diagonal(loss, mesh, batch,
                                          feature_sharded)(result.w)
        variances = variances_from_diagonal(diag, l2, mask)[:dim]
    elif kind == VarianceComputationType.FULL:
        raise NotImplementedError(
            "FULL variance needs the dense d×d Hessian — not available at "
            "sparse/Criteo scale (use SIMPLE, as the reference does)")

    means = result.w[:dim]
    return Coefficients(means=means, variances=variances), result
