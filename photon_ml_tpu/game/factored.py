"""Factored random effects: low-rank per-entity models (matrix factorization).

Reference parity: photon-api ``algorithm/FactoredRandomEffectCoordinate.
scala`` + ``model/FactoredRandomEffectModel`` / ``LatentFactorAvro`` (the
pre-fork GLMix matrix-factorization coordinate, removed in late upstream):
entity e's coefficient vector is constrained to a rank-r subspace,
``w_e = A z_e`` with a SHARED (d, r) projection matrix A and per-entity
(r,) latent factors z_e. Training alternates between

- the **latent step**: fix A, project features ``X̃ = X A`` and fit every
  entity's z_e — exactly a random-effect solve at dimension r; and
- the **projection step**: fix Z, fit A as one shared GLM whose margin is
  ``x_iᵀ A z_{e(i)}`` — a fixed-effect-like problem in d·r parameters.

TPU-first design: the whole alternation is ONE jitted program over
device-resident X/labels/weights/ids. The latent step reuses the entity
bucketing machinery (vmapped masked-lane solves per padded bucket; the
projected features ``X̃[ex]`` are gathered on device from the current A, so
nothing is re-staged between alternations). The projection step never
materializes the (n, d·r) Kronecker design matrix the reference's math
implies — its value/gradient are two matmuls:

    margin = einsum(nd,dr,nr->n)(X, A, Z[ids])
    grad_A = Xᵀ ((w ∘ dl)[:, None] * Z[ids])        # (d, r)

which is the whole point of running it on the MXU.

Variances are not supported (the reference factored coordinate predates
variance computation and never supported it either).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.data.batch import LabeledBatch
from photon_ml_tpu.data.game_data import GameDataset, SparseShard
from photon_ml_tpu.game import buckets as bkt
from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.normalization import NormalizationContext
from photon_ml_tpu.ops.losses import PointwiseLoss
from photon_ml_tpu.optim import optimize
from photon_ml_tpu.optim.problem import (GLMOptimizationConfiguration,
                                         make_objective,
                                         resolve_optimizer_config)
from photon_ml_tpu.optim.regularization import RegularizationType
from photon_ml_tpu.parallel.mesh import DATA_AXIS, data_sharded, replicated

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class FactoredRandomEffectModel:
    """Low-rank per-entity models: ``w_e = projection @ factors[e]``.

    ``projection`` is the shared (d, r) matrix A; ``factors`` is the
    (num_entities, r) latent table Z (untrained entities hold zero rows, so
    their implied coefficients — and scores — are exactly zero, preserving
    the passive-data semantics of the full-rank RandomEffectModel).
    """

    re_type: str
    shard_id: str
    projection: Array  # (d, r)
    factors: Array  # (num_entities, r)

    @property
    def num_entities(self) -> int:
        return self.factors.shape[0]

    @property
    def dim(self) -> int:
        return self.projection.shape[0]

    @property
    def rank(self) -> int:
        return self.projection.shape[1]

    def entity_rows(self, ids: np.ndarray) -> np.ndarray:
        """Dense (len(ids), dim) implied coefficient rows ``A z_e``
        (RandomEffectModel's ``entity_rows`` contract) — materializes only
        the requested entities, not the full (E, d) table."""
        ids = np.asarray(ids, np.int64)
        return (np.asarray(self.factors, np.float32)[ids]
                @ np.asarray(self.projection, np.float32).T)

    def score(self, dataset: GameDataset) -> Array:
        X = jnp.asarray(dataset.feature_shards[self.shard_id])
        ids = jnp.asarray(dataset.entity_ids[self.re_type])
        # x_i · (A z_e): contract the small rank axis last. Ids beyond the
        # factor table (unseen scoring entities) contribute exactly zero —
        # the same passive semantics as RandomEffectModel.score.
        safe = jnp.minimum(ids, self.factors.shape[0] - 1)
        contrib = jnp.einsum("nr,nr->n", X @ self.projection,
                             self.factors[safe])
        return jnp.where(ids < self.factors.shape[0], contrib, 0.0)

    def to_random_effect_model(self):
        """Materialize the implied full-rank (E, d) table (reference:
        RandomEffectModel conversion used for scoring/persistence)."""
        from photon_ml_tpu.game.models import RandomEffectModel

        return RandomEffectModel(
            re_type=self.re_type, shard_id=self.shard_id,
            means=self.factors @ self.projection.T)


def from_random_effect_model(model, rank: int) -> FactoredRandomEffectModel:
    """Best rank-``rank`` factored initialization of a full-rank model.

    Truncated SVD of the (E, d) coefficient table: ``W ≈ (U_r S_r) V_rᵀ``
    gives factors ``Z = U_r S_r`` and projection ``A = V_r`` — the closest
    rank-r model in Frobenius norm, so a factored coordinate warm-started
    from a trained RandomEffectModel begins at the best low-rank view of
    it (reference: FactoredRandomEffectCoordinate initializes from and
    materializes to RandomEffectModels across coordinate updates).
    """
    W = np.asarray(model.means, np.float32)
    E, d = W.shape
    U, S, Vt = np.linalg.svd(W, full_matrices=False)
    r = min(rank, S.shape[0])
    A = np.zeros((d, rank), np.float32)
    Z = np.zeros((E, rank), np.float32)
    A[:, :r] = Vt[:r].T
    Z[:, :r] = U[:, :r] * S[:r]
    return FactoredRandomEffectModel(
        re_type=model.re_type, shard_id=model.shard_id,
        projection=jnp.asarray(A), factors=jnp.asarray(Z))


class FactoredRandomEffectCoordinate:
    """Alternating matrix-factorization coordinate (reference:
    FactoredRandomEffectCoordinate.trainModel's update loop).

    ``config`` drives the projection (A) step; ``latent_config`` drives the
    per-entity latent (Z) solves and defaults to ``config``; ``rank`` and
    ``alternations`` mirror the reference's MFOptimizationConfiguration
    (numLatentFactors, numInnerIterations).

    ``learn_projection=False`` freezes A at its seeded Gaussian draw and
    runs a single latent pass — this IS the reference's random-projection
    projector (``projector/ProjectionMatrixBroadcast.scala``,
    projectorType=RANDOM): every entity solves in the same k-dim randomly
    projected feature space, and the returned model's implied coefficients
    ``A z_e`` live back in the original space.
    """

    def __init__(
        self,
        dataset: GameDataset,
        re_type: str,
        shard_id: str,
        loss: PointwiseLoss,
        config: GLMOptimizationConfiguration,
        mesh,
        rank: int = 4,
        alternations: int = 2,
        latent_config: Optional[GLMOptimizationConfiguration] = None,
        lower_bound: int = 1,
        upper_bound: Optional[int] = None,
        seed: int = 0,
        learn_projection: bool = True,
    ):
        if isinstance(dataset.feature_shards[shard_id], SparseShard):
            raise TypeError(
                f"factored random-effect shard {shard_id!r} is sparse; "
                f"densify it (the latent step stages (E_b, cap, r) blocks "
                f"from X @ A, which needs a dense X)")
        if rank < 1:
            raise ValueError(f"rank must be >= 1, got {rank}")
        if alternations < 1:
            raise ValueError(f"alternations must be >= 1, got {alternations}")
        self.dataset = dataset
        self.re_type = re_type
        self.shard_id = shard_id
        self.loss = loss
        self.config = config
        self.latent_config = latent_config if latent_config is not None \
            else config
        self._latent_explicit = latent_config is not None
        self.mesh = mesh
        self.rank = int(rank)
        self.alternations = int(alternations)
        self.learn_projection = bool(learn_projection)
        self.num_entities = dataset.num_entities[re_type]
        self.seed = seed
        self.bucketing = bkt.build_bucketing(
            dataset.entity_ids[re_type], self.num_entities,
            lower_bound=lower_bound, upper_bound=upper_bound,
            entity_pad_multiple=max(8,
                                    int(np.prod(list(mesh.shape.values())))),
            rng=np.random.default_rng(seed),
            counts_all=dataset.entity_counts.get(re_type))

        # Stage device-resident arrays once (rows sharded over the data axis
        # when divisible — the projection step is the data-parallel half).
        n_data = mesh.shape[DATA_AXIS]

        def put(a):
            if a.shape[0] % n_data == 0:
                return jax.device_put(a, data_sharded(mesh, a.ndim))
            return jnp.asarray(a)

        X = np.asarray(dataset.feature_shards[shard_id], np.float32)
        self._X = put(X)
        self._y = put(np.asarray(dataset.response, np.float32))
        self._w = put(np.asarray(dataset.weights, np.float32))
        self._ids = put(np.asarray(dataset.entity_ids[re_type], np.int32))
        self._bucket_data = []
        for b in self.bucketing.buckets:
            wb = bkt.bucket_weights(b, np.asarray(dataset.weights))
            (yb,) = bkt.gather_bucket_arrays(b, np.asarray(dataset.response))
            ex = b.example_idx.astype(np.int32)
            rows = b.entity_rows
            self._bucket_data.append(tuple(
                put(np.asarray(a)) for a in (yb, wb, ex, rows)))
        self._build_fit()

    @property
    def dim(self) -> int:
        return self.dataset.shard_dim(self.shard_id)

    # -- jitted alternation ------------------------------------------------

    def _build_fit(self):
        # Guard here, not only in __init__: with_optimization_config swaps
        # configs on a copy (the estimator grid/tuning path) and must hit
        # the same rejection instead of silently dropping the penalty. With
        # a frozen projection (projector=RANDOM) the matrix step never runs
        # and the latent solves fully support L1 — no rejection there.
        reg_kind = RegularizationType(self.config.regularization.reg_type)
        if self.learn_projection and reg_kind in (
                RegularizationType.L1, RegularizationType.ELASTIC_NET):
            raise ValueError(
                "L1/elastic-net on the projection matrix is not supported "
                "(no per-coordinate orthant structure on a shared (d, r) "
                "matrix); use L2 or NONE for the factored coordinate")
        loss = self.loss
        d, r = self.dim, self.rank
        num_entities = self.num_entities
        l2 = self.config.regularization.l2_weight()
        latent_cfg = self.latent_config
        proj_opt_cfg = resolve_optimizer_config(self.config.optimizer, False)
        # L2 skips the intercept feature's ROW of A (the same intercept_mask
        # convention every other coordinate applies): the implied per-entity
        # intercept (A z_e)[intercept] must not be shrunk by the matrix step.
        ii = self.dataset.intercept_index.get(self.shard_id)
        reg_mask = np.ones((d, r), np.float32)
        if ii is not None:
            reg_mask[ii, :] = 0.0
        reg_mask = jnp.asarray(reg_mask.reshape(-1))

        def solve_z_one(Xp_e, y_e, w_e, o_e, z0):
            """One entity's latent solve at dimension r (no intercept — the
            latent space has no distinguished column; the feature-space
            intercept lives in A's rows like every other feature)."""
            batch = LabeledBatch(Xp_e, y_e, w_e, o_e)
            vg, hvp, l1w = make_objective(
                loss, batch, NormalizationContext(),
                latent_cfg.regularization, None, r)
            opt_cfg = resolve_optimizer_config(latent_cfg.optimizer,
                                               l1w is not None)
            return optimize(vg, z0, opt_cfg, hvp=hvp, l1_weights=l1w).w

        vsolve_z = jax.vmap(solve_z_one)

        # Same registry-resolved row moves as the plain RE bucket solvers
        # (game/coordinates/random_effect.py _build_fits) — the latent
        # table Z is just a (num_entities, r) coefficient table, and the
        # kernels are bit-exact data movement, so the flip is free of
        # numerics. Resolved once, at program-build time.
        from photon_ml_tpu.ops import kernels as _kernels
        _reg = _kernels.registry()
        _gather_k = _scatter_k = None
        if _reg.enabled("re_gather_rows"):
            rk = _reg.resolve("re_gather_rows")
            if rk.backend == "pallas":
                _gather_k = rk
        if _reg.enabled("re_scatter_rows"):
            rk = _reg.resolve("re_scatter_rows")
            if rk.backend == "pallas":
                _scatter_k = rk

        def z_step(A, Z, offsets):
            Xp = self._X @ A  # (n_pad, r)
            for yb, wb, ex, rows in self._bucket_data:
                safe_ex = jnp.maximum(ex, 0)
                Xb = Xp[safe_ex] * (ex >= 0)[..., None]
                ob = offsets[safe_ex]
                z0 = (_gather_k(Z, rows) if _gather_k is not None
                      else Z[jnp.maximum(rows, 0)])
                z_fit = vsolve_z(Xb, yb, wb, ob, z0)
                if _scatter_k is not None:
                    Z = _scatter_k(Z, rows, z_fit)
                else:
                    safe_rows = jnp.where(rows >= 0, rows, num_entities)
                    Z = Z.at[safe_rows].set(z_fit, mode="drop")
            return Z

        def a_step(A, Z, offsets):
            Zg = Z[self._ids]  # (n_pad, r); padded rows have weight 0

            def vg(a_flat):
                Am = a_flat.reshape(d, r)
                margin = jnp.einsum("nr,nr->n", self._X @ Am, Zg) + offsets
                l, dl = loss.loss_and_dz(margin, self._y)
                value = jnp.sum(self._w * l) \
                    + 0.5 * l2 * jnp.sum(reg_mask * a_flat * a_flat)
                g = self._X.T @ ((self._w * dl)[:, None] * Zg)
                return value, g.reshape(-1) + l2 * reg_mask * a_flat

            def hvp(a_flat, v_flat):
                # Gauss-Newton-exact HVP (the objective is a GLM in vec(A)):
                # H·v = Kᵀ diag(w·d2l) K v + l2·v with K v computable as
                # einsum without materializing K = X ⊗ Z rows.
                Am = a_flat.reshape(d, r)
                Vm = v_flat.reshape(d, r)
                margin = jnp.einsum("nr,nr->n", self._X @ Am, Zg) + offsets
                d2 = loss.d2z(margin, self._y) * self._w
                kv = jnp.einsum("nr,nr->n", self._X @ Vm, Zg)
                hv = self._X.T @ ((d2 * kv)[:, None] * Zg)
                return hv.reshape(-1) + l2 * reg_mask * v_flat

            res = optimize(vg, A.reshape(-1), proj_opt_cfg, hvp=hvp)
            return res.w.reshape(d, r)

        def fit(A, Z, offsets):
            if not self.learn_projection:
                # Random-projection mode: A is frozen; one latent pass is
                # exact (each entity's solve is convex given A).
                return A, z_step(A, Z, offsets)
            for _ in range(self.alternations):
                Z = z_step(A, Z, offsets)
                A = a_step(A, Z, offsets)
            # One closing latent pass so Z is optimal for the returned A
            # (reference: the latent step is the last inner update).
            Z = z_step(A, Z, offsets)
            return A, Z

        self._fit = jax.jit(fit)
        self._score = jax.jit(
            lambda A, Z: jnp.einsum("nr,nr->n", self._X @ A, Z[self._ids]))

    # -- coordinate contract ----------------------------------------------

    def _padded_offsets(self, offsets: Array) -> Array:
        offsets = jnp.asarray(offsets)
        n_pad = self._X.shape[0]
        if offsets.shape[0] != n_pad:
            offsets = jnp.zeros((n_pad,), offsets.dtype
                                ).at[: offsets.shape[0]].set(offsets)
        # Canonical sharding: the descent loop hands offsets with whatever
        # sharding the last score update produced, which changes between
        # the first and later CD iterations — without this, each distinct
        # input sharding would recompile the (large) alternation program.
        if offsets.shape[0] % self.mesh.shape[DATA_AXIS] == 0:
            offsets = jax.device_put(offsets, data_sharded(self.mesh, 1))
        return offsets

    def initial_model(self) -> FactoredRandomEffectModel:
        """Seeded random projection + zero factors (zero initial scores,
        like every other coordinate's initial model)."""
        rng = np.random.default_rng(self.seed)
        A = (rng.normal(size=(self.dim, self.rank)) /
             np.sqrt(self.dim)).astype(np.float32)
        return FactoredRandomEffectModel(
            re_type=self.re_type, shard_id=self.shard_id,
            projection=jnp.asarray(A),
            factors=jnp.zeros((self.num_entities, self.rank), jnp.float32))

    def adapt_initial(self, initial):
        """Accept a full-rank RandomEffectModel warm start.

        ``learn_projection=True``: truncated-SVD initialization (the best
        rank-r view of the trained table; both A and Z then train).
        ``learn_projection=False`` (projector=RANDOM): the projection is a
        frozen seeded draw that must survive, so the warm start is instead
        least-squares-projected INTO that fixed subspace
        (``z_e = A⁺ w_e``).
        """
        from photon_ml_tpu.game.models import (RandomEffectModel,
                                               SubspaceRandomEffectModel)

        if isinstance(initial, SubspaceRandomEffectModel):
            # Factored coordinates are inherently small-d (they hold a
            # dense (d, r) projection), so materializing is affordable.
            initial = initial.to_random_effect_model()
        if not isinstance(initial, RandomEffectModel):
            return initial
        if self.learn_projection:
            return from_random_effect_model(initial, self.rank)
        frozen = self.initial_model()
        A = np.asarray(frozen.projection)
        Z = np.asarray(initial.means, np.float32) @ np.linalg.pinv(A).T
        return dataclasses.replace(frozen, factors=jnp.asarray(
            Z.astype(np.float32)))

    def train_model(
        self,
        offsets: Array,
        initial: Optional[FactoredRandomEffectModel] = None,
    ) -> FactoredRandomEffectModel:
        if initial is None:
            initial = self.initial_model()
        initial = self.adapt_initial(initial)
        if initial.rank != self.rank:
            raise ValueError(
                f"warm start has rank {initial.rank}, coordinate has rank "
                f"{self.rank}")
        if initial.num_entities != self.num_entities \
                or initial.dim != self.dim:
            # An oversized factors table (e.g. loaded under a larger
            # scoring vocabulary) would make the padding-lane scatter index
            # num_entities IN bounds and silently corrupt that row.
            raise ValueError(
                f"warm start shape ({initial.num_entities} entities, dim "
                f"{initial.dim}) does not match coordinate "
                f"({self.num_entities} entities, dim {self.dim})")
        # Canonical (replicated) placement for the warm start — like the
        # offsets, its sharding otherwise varies between the first and later
        # CD iterations (host arrays vs previous fit outputs) and every
        # variant would recompile the alternation program.
        rep = replicated(self.mesh)
        A, Z = self._fit(jax.device_put(jnp.asarray(initial.projection), rep),
                         jax.device_put(jnp.asarray(initial.factors), rep),
                         self._padded_offsets(offsets))
        return FactoredRandomEffectModel(
            re_type=self.re_type, shard_id=self.shard_id,
            projection=A, factors=Z)

    def score(self, model: FactoredRandomEffectModel) -> Array:
        n = self.dataset.num_rows
        return self._score(jnp.asarray(model.projection),
                           jnp.asarray(model.factors))[:n]

    def compute_model_variances(self, model, offsets):
        """Factored models carry no variances (reference parity: the
        factored coordinate predates and never supported computeVariances);
        returned unchanged."""
        return model

    def with_optimization_config(
        self, config: GLMOptimizationConfiguration
    ) -> "FactoredRandomEffectCoordinate":
        """Cheap copy for the estimator's reg-weight grid: the new config
        drives the projection step and — unless a distinct latent config was
        given at construction — the latent step too."""
        import copy

        c = copy.copy(self)
        c.config = config
        if not self._latent_explicit:
            c.latent_config = config
        c._build_fit()
        return c
