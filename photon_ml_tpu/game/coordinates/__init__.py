"""GAME coordinates: fixed-effect and random-effect training units.

Reference parity: photon-api ``algorithm/Coordinate.scala``,
``algorithm/FixedEffectCoordinate.scala`` (one distributed GLM fit over the
whole dataset), ``algorithm/RandomEffectCoordinate.scala`` (per-entity local
GLM fits inside ``mapValues`` over ``RDD[(REId, LocalDataset)]``).

TPU-first design:
- FixedEffectCoordinate = the data-parallel psum objective + compiled
  optimizer (photon_ml_tpu/parallel/problem.py) over the mesh (P1).
- RandomEffectCoordinate = per-bucket ``vmap``-ped compiled optimizer over
  padded entity blocks (photon_ml_tpu/game/buckets.py), entity axis sharded
  over the mesh, per-lane convergence masks freezing finished entities (P2).

Residency discipline (the point of the rebuild — replaces the reference's
per-L-BFGS-iteration driver⇄executor broadcast/treeAggregate): every array
that survives a coordinate-descent step lives on device for the whole run.
Each coordinate builds its jitted fit program ONCE at construction:

- fixed effect: ``fit(staged_batch, offsets, w0) → w`` — the entire L-BFGS/
  TRON/OWL-QN while_loop plus psum objective is one cached XLA executable;
  per CD step the only new inputs are the (n,) offsets and the warm start.
- random effect: ``fit_bucket(W, offsets, Xb, yb, wb, ex, rows) → W`` —
  offsets gather, warm-start gather, vmapped solve, and trained-row scatter
  all happen on device; the (E, d) coefficient table never visits the host.

Both expose ``train_model(offsets, initial)`` and ``score(model)`` plus
variance computation, mirroring the reference Coordinate contract
(trainModel / score / updateOffset — offsets here are passed explicitly
rather than mutating a dataset).
"""

from photon_ml_tpu.game.coordinates.fixed import FixedEffectCoordinate
from photon_ml_tpu.game.coordinates.sparse_fixed import \
    SparseFixedEffectCoordinate
from photon_ml_tpu.game.coordinates.random_effect import \
    RandomEffectCoordinate
from photon_ml_tpu.game.coordinates.streaming_fixed import \
    StreamingSparseFixedEffectCoordinate

__all__ = [
    "FixedEffectCoordinate",
    "SparseFixedEffectCoordinate",
    "RandomEffectCoordinate",
    "StreamingSparseFixedEffectCoordinate",
]
