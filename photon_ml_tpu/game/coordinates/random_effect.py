"""Random-effect coordinate: per-entity vmapped solves over padded entity
buckets (P2), dense or subspace-projected, with optional sparse-shard input.

See the package docstring (photon_ml_tpu/game/coordinates/__init__.py) for
the residency discipline shared by all coordinate types.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu import obs
from photon_ml_tpu.data.batch import LabeledBatch
from photon_ml_tpu.data.game_data import GameDataset
from photon_ml_tpu.game import buckets as bkt
from photon_ml_tpu.game import projector as prj
from photon_ml_tpu.game import staging as stg
from photon_ml_tpu.game.models import (RandomEffectModel,
                                       SubspaceRandomEffectModel,
                                       _subspace_positions,
                                       sort_subspace_rows)
from photon_ml_tpu.normalization import NormalizationContext
from photon_ml_tpu.ops.losses import PointwiseLoss
from photon_ml_tpu.optim import optimize
from photon_ml_tpu.optim.problem import (GLMOptimizationConfiguration,
                                         VarianceComputationType,
                                         compute_variances, make_objective,
                                         resolve_optimizer_config)
from photon_ml_tpu.parallel.mesh import DATA_AXIS, data_sharded

Array = jax.Array

# Sentinel distinguishing "use the coordinate's intercept" from an explicit
# None (projected buckets with no intercept column).
_UNSET = object()

# Max entity lanes per vmapped random-effect solve dispatch: the solver's
# carry/line-search temps scale with lanes, and one dispatch over ~600k
# lanes OOMs a 16 GB chip. 64k lanes keeps temps ~100 MB at typical widths
# while staying large enough to saturate the chip. Shared with the
# staging pipeline so staged shards == device dispatch chunks (one
# host→device put per produced shard, no re-slicing).
_LANE_CHUNK = stg.LANE_CHUNK


@jax.jit
def _compact_tuple(sel, Xb, yb, wb, ex, rows, *extra):
    """Gather the selected (dirty) lanes of one staged bucket tuple into
    a dense active wave (game/sweep.py; docs/SWEEPS.md).

    ``sel`` is (L',) int32 lane indices, -1-padded to the quantized
    active-wave width. Padding lanes re-gather lane 0's data but are
    neutralized the way bucket padding always is: rows → -1 (scatter
    drop), ex → -1 (delta drop), weights → 0 (benign solve)."""
    live = sel >= 0
    take = jnp.maximum(sel, 0)
    out = (jnp.take(Xb, take, axis=0),
           jnp.take(yb, take, axis=0),
           jnp.where(live[:, None], jnp.take(wb, take, axis=0), 0.0),
           jnp.where(live[:, None], jnp.take(ex, take, axis=0), -1),
           jnp.where(live, jnp.take(rows, take, axis=0), -1))
    return out + tuple(jnp.take(a, take, axis=0) for a in extra)


@jax.jit
def _gram_block(Xb, wb):
    """Per-lane normal-equation Gram block X^T diag(w) X, f32-accumulated
    (the aggregators.hessian_matrix pattern). Built ONCE per staged tuple
    and reused every sweep — the design matrices are fixed across outer
    iterations, only the offsets move (ROADMAP item 4's named target)."""
    Xf = Xb.astype(jnp.float32)
    return jnp.einsum("eck,ec,ecm->ekm", Xf, wb, Xf)


@jax.jit
def _subspace_sparse_scores(W_flat, flatpos, values):
    """Σ_k values[i,k] · W_flat[flatpos[i,k]] with misses (flatpos ≥ |W|)
    contributing zero — one 1-D gather per ELL slot.

    The slot loop is a TPU layout constraint, not style: a single fused
    gather with (n, k, 1)-shaped indices forces the index operand into a
    (8, 128)-tiled copy whose minor dims pad 4→128 — at n=100M that copy
    is 51 GB and the COMPILE itself aborts with an HBM overflow (measured
    on v5e). Per-slot (n,) indices lay out densely; k is ELL-small, so
    the extra gathers cost nothing against the random-access wall.
    """
    lim = W_flat.shape[0]
    acc = jnp.zeros((flatpos.shape[0],), jnp.float32)
    for j in range(flatpos.shape[1]):
        pos = flatpos[:, j]
        g = W_flat[jnp.minimum(pos, lim - 1)] * (pos < lim)
        acc = acc + values[:, j].astype(jnp.float32) * g
    return acc


class RandomEffectCoordinate:
    """Per-entity GLMs trained as vmapped bucket solves.

    Reference parity: RandomEffectCoordinate + SingleNodeOptimizationProblem
    (per-entity local L-BFGS inside mapValues) — here all entities of a
    bucket solve simultaneously under vmap with convergence masks.

    Model-space contract: same as FixedEffectCoordinate — solves run in the
    shard's normalization-transformed space; the RandomEffectModel rows are
    ORIGINAL-space, so scoring is the plain gather + rowwise dot everywhere.

    ``projection=True`` enables the per-entity feature-subspace projector
    (reference: LinearSubspaceProjector + IndexMapProjectorRDD, SURVEY §2.1/
    §2.2): each bucket stages features at d_active ≪ d (the union of columns
    its entities actually use), solves in the projected space, and scatters
    coefficients back to full-space rows — the difference between feasible
    and OOM when the RE feature space is large and per-entity sparse.
    """

    def __init__(
        self,
        dataset: GameDataset,
        re_type: str,
        shard_id: str,
        loss: PointwiseLoss,
        config: GLMOptimizationConfiguration,
        mesh,
        lower_bound: int = 1,
        upper_bound: Optional[int] = None,
        norm: NormalizationContext = NormalizationContext(),
        seed: int = 0,
        projection: bool = False,
        features_to_samples_ratio: Optional[float] = None,
        subspace_model: Optional[bool] = None,
        staging_cache_dir: Optional[str] = None,
        feature_dtype: str = "float32",
        staging: Optional[stg.StagingConfig] = None,
    ):
        from photon_ml_tpu.data.game_data import SparseShard
        if feature_dtype not in ("float32", "bfloat16"):
            # Before staging: at flagship scale the projection pass below
            # costs minutes, and a typo'd dtype must not pay it first.
            raise ValueError(f"unsupported feature_dtype {feature_dtype!r}")
        self.is_sparse = isinstance(dataset.feature_shards[shard_id],
                                    SparseShard)
        if self.is_sparse:
            # Large-d per-entity sparse features are exactly the
            # subspace-projection regime (reference: RandomEffectDataset
            # keeps per-entity sparse Breeze rows and projects them via
            # IndexMapProjectorRDD) — projection is implied; the dense
            # (n, d) shard never exists, buckets stage at d_active ≪ d
            # straight from the ELL triplets.
            projection = True
            if norm.factors is not None or norm.shifts is not None:
                raise ValueError(
                    f"normalization is not supported on sparse random-"
                    f"effect shard {shard_id!r} (scaling sparse values "
                    f"would densify shift terms)")
        self.dataset = dataset
        self.re_type = re_type
        self.shard_id = shard_id
        self.loss = loss
        self.config = config
        self.mesh = mesh
        self.norm = norm
        self.num_entities = dataset.num_entities[re_type]
        self.intercept_index = dataset.intercept_index.get(shard_id)
        self.bucketing = bkt.build_bucketing(
            dataset.entity_ids[re_type], self.num_entities,
            lower_bound=lower_bound, upper_bound=upper_bound,
            entity_pad_multiple=max(8, int(np.prod(list(mesh.shape.values())))),
            rng=np.random.default_rng(seed),
            counts_all=dataset.entity_counts.get(re_type))
        if self.is_sparse:
            shard = dataset.feature_shards[shard_id]
            self._sp_indices = jnp.asarray(shard.indices)
            self._sp_values = jnp.asarray(shard.values)
            self._X = None
        else:
            self._X = jnp.asarray(dataset.feature_shards[shard_id])
        self._ids = jnp.asarray(dataset.entity_ids[re_type])
        # Pearson feature filtering selects per-entity columns, which is
        # exactly what the projection machinery stages — a ratio implies
        # projection (reference: filterFeaturesByPearsonCorrelationScore
        # runs during RandomEffectDataset build when
        # numFeaturesToSamplesRatio is configured).
        self.features_to_samples_ratio = features_to_samples_ratio
        self.projection = bool(projection) or (
            features_to_samples_ratio is not None)
        # Subspace model representation (reference:
        # RandomEffectModelInProjectedSpace): the trained table stays
        # (E, A) in each entity's active-column space instead of the dense
        # (E, d) — mandatory at the scale where E·d is unmaterializable.
        # Auto-on when the dense table would exceed ~1 GiB.
        if subspace_model is None:
            subspace_model = (self.projection and
                              self.num_entities * self.dim > (1 << 28))
        if subspace_model and not self.projection:
            raise ValueError(
                "subspace_model=True requires projection=True (the "
                "subspace IS the per-entity projection)")
        self.subspace = bool(subspace_model)
        # Stage static per-bucket device arrays ONCE: features/labels/weights
        # in (E_b, cap, …) layout plus the gather/scatter index maps. The
        # entity axis is sharded over the mesh's data axis (P2) when the
        # padded entity count divides it. With projection on, features are
        # staged directly at (E_b, cap, d_active) and each tuple carries the
        # (E_b, d_active) column map plus projected normalization arrays —
        # produced by the parallel pipelined stager (game/staging.py) and
        # consumed lazily by the fit stream (_iter_bucket_data), so the
        # first per-entity fits dispatch while later shards still project.
        self._bucket_data = []
        # Host copies of each staged tuple's (E_b,) entity-row map, in
        # fit-stream order: the gated sweep path (train_model_gated)
        # selects dirty lanes on host to build compacted active waves.
        self._host_rows: list[np.ndarray] = []
        self._gram_cache: dict[int, Array] = {}
        # Lazy gating-support caches (see _bucket_census): per-entity row
        # counts, the trained-entity mask, and whether segment rescoring
        # is exact for this bucketing (no passive rows on trained
        # entities — upper_bound capping breaks that).
        self._entity_counts: Optional[np.ndarray] = None
        self._trained_mask: Optional[np.ndarray] = None
        self._segment_rescore_ok: Optional[bool] = None
        self._pending = None
        self._stager = None
        self.staging = staging or stg.StagingConfig()
        self.feature_dtype = feature_dtype
        ds = dataset
        X = ds.feature_shards[shard_id]
        self._n_data = mesh.shape[DATA_AXIS]

        # Shifts without factors cannot occur via build_normalization; guard
        # the manual case so the projected solve has one layout.
        f_full = None if norm.factors is None else np.asarray(norm.factors)
        s_full = None if norm.shifts is None else np.asarray(norm.shifts)
        if s_full is not None and f_full is None:
            f_full = np.ones_like(s_full)

        # Projected staging products persist on disk keyed by dataset
        # content + staging params (photon_ml_tpu/game/staging_cache.py),
        # shard-granular: a warm re-fit of the same data memory-maps the
        # staged blocks instead of re-paying the projection pass, and a
        # partial entry (killed run) restages only its missing shards.
        from photon_ml_tpu.game import staging_cache

        self._staging_cache_key = None
        if staging_cache_dir and self.projection:
            self._staging_cache_key = staging_cache.staging_key(
                dataset, norm, re_type=re_type, shard_id=shard_id,
                lower_bound=lower_bound, upper_bound=upper_bound,
                seed=seed, pad=self.bucketing.entity_pad_multiple,
                ratio=self.features_to_samples_ratio,
                intercept=self.intercept_index, subspace=self.subspace,
                # Declared dimensions the array digest cannot see: the
                # staged entity tables and the subspace join sentinels
                # depend on both. The shard size shapes the per-shard
                # file layout, so it keys too.
                num_entities=self.num_entities, dim=self.dim,
                shard_entities=stg.resolved_shard_entities(
                    self.staging, self.bucketing.entity_pad_multiple))

        if self.projection:
            self._stager = stg.ProjectionStager(
                bucketing=self.bucketing, X=X,
                response=np.asarray(ds.response),
                weights=np.asarray(ds.weights),
                intercept_index=self.intercept_index,
                features_to_samples_ratio=self.features_to_samples_ratio,
                factors=f_full, shifts=s_full,
                config=self.staging,
                cache_dir=staging_cache_dir,
                cache_key=self._staging_cache_key,
                expect_subspace=self.subspace,
                label=f"{re_type}:{shard_id}")
            self._pending = self._stager.shards()
            sub = {}
            if self.subspace:
                sub = self._stager.cached_subspace()
                if sub is not None and self.is_sparse and "flat" not in sub:
                    sub = None  # incomplete record: recompute
                if sub is None:
                    # (E, A) active-column table: each entity lives in
                    # exactly one bucket, so its model row is its bucket
                    # row padded to the widest bucket's d_active. The
                    # PUBLIC model layout sorts each row by column id
                    # (padding last) so SubspaceRandomEffectModel.score
                    # can join new datasets with a device-side
                    # searchsorted; the bucket-internal layout (intercept
                    # slot 0) is reached through the stored permutation
                    # at the train/warm-start boundary. Blocks only on
                    # the pipeline's pair-extraction phase — the feature
                    # gathers keep overlapping with whatever runs next.
                    shard_cols = self._stager.cols_list()
                    A = max((c.shape[1] for c in shard_cols), default=1)
                    cols_tab = np.full((self.num_entities, A), -1,
                                       np.int32)
                    for (bi, lo, hi), c in zip(self._stager.plan,
                                               shard_cols):
                        rows_s = self.bucketing.buckets[bi].entity_rows[
                            lo:hi]
                        live = rows_s >= 0
                        cols_tab[rows_s[live], : c.shape[1]] = c[live]
                    cols_sorted, perm = sort_subspace_rows(cols_tab)
                    sub = {"cols": cols_sorted, "perm": perm}
                    if self.is_sparse:
                        # Stage the score-side join ONCE: data nonzeros →
                        # flat slots of the (E, A) table (E*A = miss/
                        # passive → 0).
                        flat = _subspace_positions(
                            cols_sorted, self.dim,
                            np.asarray(ds.entity_ids[re_type]),
                            np.asarray(
                                dataset.feature_shards[shard_id].indices))
                        fp_dtype = (np.int32
                                    if cols_sorted.size < 2**31 - 1
                                    else np.int64)
                        sub["flat"] = flat.astype(fp_dtype)
                self._stager.set_subspace(sub)
        else:
            # Unprojected path: dense gathers, cheap relative to the
            # projection wall — staged eagerly as before.
            host_buckets: list[tuple] = []
            for b in self.bucketing.buckets:
                wb = bkt.bucket_weights(b, ds.weights)
                ex = b.example_idx.astype(np.int32)  # (E_b, cap); -1 pad
                rows = b.entity_rows  # (E_b,) int32; -1 padding
                Xb, yb = bkt.gather_bucket_arrays(b, X, ds.response)
                host_buckets.append((Xb, yb, wb, ex, rows))
            sub = {}
            for arrays in host_buckets:
                self._stage_host_tuple(arrays)
            self._pending = None
        if self.subspace:
            cols_sorted = np.asarray(sub["cols"])
            perm = np.asarray(sub["perm"])
            self.subspace_cols = cols_sorted
            # Model-adjacent arrays stay process-local (NOT mesh-sharded),
            # mirroring the dense path's W table: the trained model must be
            # host-fetchable on rank 0 for checkpoints/saves, and a
            # mesh-sharded cols table would span non-addressable devices
            # in multi-host runs. Bucket DATA arrays remain sharded.
            self._cols_dev = jnp.asarray(cols_sorted)
            self._perm_dev = jnp.asarray(perm)
            self._inv_perm_dev = jnp.asarray(
                np.argsort(perm, axis=1, kind="stable").astype(np.int32))
            if self.is_sparse:
                # Like _sp_values: score-side arrays stay process-local.
                flat = np.asarray(sub["flat"])
                if flat.dtype == np.int64:
                    # Device arrays are int32 (x64 off): a silent
                    # jnp.asarray downcast would wrap flat positions
                    # ≥ 2^31 into valid-looking wrong indices and score
                    # garbage. Refuse with the actionable alternatives.
                    if flat.max(initial=0) >= np.iinfo(np.int32).max:
                        raise ValueError(
                            f"subspace flat positions exceed int32 "
                            f"(E×A = {int(self.subspace_cols.size)}): "
                            "split this random effect into smaller "
                            "coordinates or reduce active columns "
                            "(features_to_samples_ratio / upper_bound)")
                    flat = flat.astype(np.int32)
                self._sp_flatpos = jnp.asarray(flat)
                # The raw column ids are only needed by the dense-table
                # score path — free the device copy at scale.
                self._sp_indices = None
        self._build_fits()

    def _put(self, a):
        if a.shape[0] % self._n_data == 0:
            return jax.device_put(a, data_sharded(self.mesh, a.ndim))
        return jnp.asarray(a)

    def _stage_host_tuple(self, arrays) -> None:
        """Split one staged host tuple into ≤ _LANE_CHUNK-lane device
        tuples appended to the fit stream.

        The lane bound caps the vmapped-solve footprint: a single
        dispatch over hundreds of thousands of entity lanes exhausts HBM
        on solver temps (the L-BFGS carry and line-search buffers scale
        with lanes). The chunk is rounded UP to a multiple of this
        coordinate's entity pad so every slice keeps the divisibility
        _put() needs to shard. Pipeline shards default to exactly this
        chunk, making the split a no-op slice; bigger explicit
        shard_entities still re-split here.

        bf16 feature STORAGE happens here (same contract as the dense
        fixed path: aggregators accumulate in f32) — after the staging
        cache, which stays f32 and dtype-independent, so only the staged
        bucket blocks shrink."""
        feat_cast = (jnp.bfloat16 if self.feature_dtype == "bfloat16"
                     else None)
        pad = self.bucketing.entity_pad_multiple
        chunk = ((_LANE_CHUNK + pad - 1) // pad) * pad
        E_b = arrays[4].shape[0]
        for lo in range(0, E_b, chunk):
            hi = min(lo + chunk, E_b)
            tup = []
            for ai, a in enumerate(arrays):
                a = np.asarray(a)[lo:hi]
                if ai == 0 and feat_cast is not None:  # Xb block
                    a = a.astype(feat_cast)
                if ai == 4:  # entity rows: keep a host copy for gating
                    self._host_rows.append(np.array(a, copy=True))
                tup.append(self._put(a))
            self._bucket_data.append(tuple(tup))

    def _iter_bucket_data(self):
        """The fit stream: already-staged device tuples first, then — on
        the first full pass — the remaining pipeline shards in plan
        order, device-put as each arrives. This is the consumer side of
        the bounded producer/consumer handoff: while the device fits
        shard i, the worker pool is still projecting shards > i, and at
        most pipeline_depth staged-but-unconsumed host blocks exist.
        Single-consumer by contract (coordinate descent trains
        coordinates sequentially)."""
        i = 0
        while True:
            if i < len(self._bucket_data):
                yield self._bucket_data[i]
                i += 1
                continue
            if self._pending is None:
                return
            try:
                host = next(self._pending)
            except StopIteration:
                self._pending = None
                return
            self._stage_host_tuple(host)

    def wait_staged(self) -> "RandomEffectCoordinate":
        """Barrier: drain the staging pipeline onto the device without
        fitting anything (the pre-pipelining behavior; also what tests
        use to compare pipelined vs barrier staging)."""
        for _ in self._iter_bucket_data():
            pass
        if self._stager is not None:
            self._stager.join()  # staging-cache writes included
        return self

    def _build_fits(self):
        """(Re)build the cached jitted per-bucket fit/variance programs.

        ``fit_bucket`` keeps the whole inner step on device: gather each
        entity's offsets and warm start, run the vmapped masked-lane solve,
        scatter trained rows back into the (E, d) table. Padding lanes
        (rows == -1) are redirected to an out-of-bounds index and dropped by
        the scatter. One executable per bucket SHAPE, cached by jit across
        buckets and coordinate-descent iterations.

        Projected variant: warm starts are gathered through each entity's
        column map (original space, since transforms are per-entity), solved
        at d_active with a per-entity NormalizationContext, mapped back to
        original space in-lane, and scattered through the column map; the
        W table stays in ORIGINAL space throughout.
        """
        num_entities = self.num_entities
        # Gated-sweep programs close over the optimization config too —
        # rebuild lazily after any config swap (with_optimization_config).
        self._fit_bucket_gated = None
        self._fit_bucket_gram = None
        if self.projection:
            self._fit_bucket, self._var_bucket = self._build_projected_fits()
            return
        solve = jax.vmap(self._solve_one)
        var_one = jax.vmap(self._variance_one)
        _gather_rows, _scatter_rows = self._row_movers()

        def fit_bucket(W, offsets, Xb, yb, wb, ex, rows):
            ob = offsets[jnp.maximum(ex, 0)]
            w0 = _gather_rows(W, rows)
            w_fit = solve(Xb, yb, wb, ob, w0)
            return _scatter_rows(W, rows, w_fit)

        def var_bucket(W, V, offsets, Xb, yb, wb, ex, rows):
            ob = offsets[jnp.maximum(ex, 0)]
            w_opt = _gather_rows(W, rows)
            var = var_one(Xb, yb, wb, ob, w_opt)
            return _scatter_rows(V, rows, var)

        # Donate the table being rebuilt (W for fits, V for variances) so the
        # scatter updates in place instead of copying (E, d) per bucket.
        self._fit_bucket = jax.jit(fit_bucket, donate_argnums=(0,))
        self._var_bucket = jax.jit(var_bucket, donate_argnums=(1,))

    def _row_movers(self):
        """The bucket layout's row moves — warm-start gather, fitted-row
        scatter — resolved against the kernel registry at program-build
        time (docs/KERNELS.md): both can run as scalar-prefetch Pallas
        programs (``re_gather_rows``/``re_scatter_rows``). Both are pure
        data movement, so a backend flip is bit-exact by construction and
        the refit bit-identity invariant holds either way. Projected fits
        keep the XLA moves: their gathers route through per-entity column
        maps, a different access pattern (docs/KERNELS.md "What stays
        XLA"). Shared by the full-sweep and gated-sweep program builders
        — compacted active waves reuse the same movers at the quantized
        wave width."""
        num_entities = self.num_entities
        from photon_ml_tpu.ops import kernels as _kernels
        _reg = _kernels.registry()
        gather_k = scatter_k = None
        if _reg.enabled("re_gather_rows"):
            rk = _reg.resolve("re_gather_rows")
            if rk.backend == "pallas":
                gather_k = rk
        if _reg.enabled("re_scatter_rows"):
            rk = _reg.resolve("re_scatter_rows")
            if rk.backend == "pallas":
                scatter_k = rk

        def _gather_rows(W, rows):
            if gather_k is not None:
                return gather_k(W, rows)
            return W[jnp.maximum(rows, 0)]

        def _scatter_rows(W, rows, vals):
            if scatter_k is not None:
                return scatter_k(W, rows, vals)
            safe = jnp.where(rows >= 0, rows, num_entities)
            return W.at[safe].set(vals, mode="drop")

        return _gather_rows, _scatter_rows

    def _build_projected_fits(self):
        """Jitted per-bucket programs for the projected (d_active) path."""
        num_entities = self.num_entities
        dim = self.dim
        has_f = not (self.norm.factors is None and self.norm.shifts is None)
        has_s = self.norm.shifts is not None
        ii_proj = 0 if self.intercept_index is not None else None

        def ctx_for(f, s):
            if not has_f:
                return NormalizationContext()
            return NormalizationContext(factors=f, shifts=s,
                                        intercept_index=ii_proj)

        def solve_one(X, y, w, o, w0_orig, f, s):
            """One entity's projected solve; original space in and out."""
            ctx = ctx_for(f, s)
            w0 = ctx.model_to_transformed_space(w0_orig)
            w_t = self._solve_one(X, y, w, o, w0, norm=ctx,
                                  intercept_index=ii_proj)
            return ctx.model_to_original_space(w_t)

        def var_one(X, y, w, o, w_orig, f, s):
            ctx = ctx_for(f, s)
            w_t = ctx.model_to_transformed_space(w_orig)
            var_t = self._variance_one(X, y, w, o, w_t, norm=ctx,
                                       intercept_index=ii_proj)
            return ctx.variances_to_original_space(var_t)

        # vmap lanes: norm arrays are per-entity when present, else closed
        # over as None (static).
        norm_axes = (0 if has_f else None, 0 if has_s else None)
        vsolve = jax.vmap(solve_one, in_axes=(0, 0, 0, 0, 0) + norm_axes)
        vvar = jax.vmap(var_one, in_axes=(0, 0, 0, 0, 0) + norm_axes)

        def unpack(extra):
            cols = extra[0]
            f = extra[1] if has_f else None
            s = extra[2 if has_f else 1] if has_s else None
            return cols, f, s

        def gathers(W, offsets, ex, rows, cols):
            ob = offsets[jnp.maximum(ex, 0)]
            valid = (cols >= 0).astype(W.dtype)
            w0 = W[jnp.maximum(rows, 0)[:, None],
                   jnp.maximum(cols, 0)] * valid
            safe_rows = jnp.where(rows >= 0, rows, num_entities)
            safe_cols = jnp.where(cols >= 0, cols, dim)
            return ob, w0, safe_rows, safe_cols

        subspace = self.subspace

        def sub_gathers(W, offsets, ex, rows, da):
            """Subspace-table layout: the entity's model row IS its bucket
            row (same active-column order), so warm starts are a plain row
            gather + static slice to this bucket's width."""
            ob = offsets[jnp.maximum(ex, 0)]
            w0 = W[jnp.maximum(rows, 0)][:, :da]
            safe_rows = jnp.where(rows >= 0, rows, num_entities)
            return ob, w0, safe_rows

        def fit_bucket(W, offsets, Xb, yb, wb, ex, rows, *extra):
            cols, f, s = unpack(extra)
            if subspace:
                da = cols.shape[1]
                ob, w0, safe_rows = sub_gathers(W, offsets, ex, rows, da)
                w_fit = vsolve(Xb, yb, wb, ob, w0, f, s)
                # Whole-row set: the padding tail past d_active stays zero.
                w_pad = jnp.pad(w_fit, ((0, 0), (0, W.shape[1] - da)))
                return W.at[safe_rows].set(w_pad, mode="drop")
            ob, w0, safe_rows, safe_cols = gathers(W, offsets, ex, rows, cols)
            w_fit = vsolve(Xb, yb, wb, ob, w0, f, s)
            # projectBackward semantics: a trained entity's FULL row is
            # rewritten — zero it first so inactive-column mass from an
            # external (e.g. unprojected) warm start cannot survive.
            W = W.at[safe_rows].set(0.0, mode="drop")
            return W.at[safe_rows[:, None], safe_cols].set(w_fit, mode="drop")

        def var_bucket(W, V, offsets, Xb, yb, wb, ex, rows, *extra):
            cols, f, s = unpack(extra)
            if subspace:
                da = cols.shape[1]
                ob, w_opt, safe_rows = sub_gathers(W, offsets, ex, rows, da)
                var = vvar(Xb, yb, wb, ob, w_opt, f, s)
                v_pad = jnp.pad(var, ((0, 0), (0, V.shape[1] - da)))
                return V.at[safe_rows].set(v_pad, mode="drop")
            ob, w_opt, safe_rows, safe_cols = gathers(W, offsets, ex, rows,
                                                      cols)
            var = vvar(Xb, yb, wb, ob, w_opt, f, s)
            return V.at[safe_rows[:, None], safe_cols].set(var, mode="drop")

        return (jax.jit(fit_bucket, donate_argnums=(0,)),
                jax.jit(var_bucket, donate_argnums=(1,)))

    def _solve_one(self, X, y, w, o, w0, norm=None, intercept_index=_UNSET):
        """One entity's GLM solve in transformed space (vmapped per bucket).

        The projected path passes a per-entity NormalizationContext and the
        projected intercept slot; the unprojected path uses the coordinate's
        own (closed-over) full-space values.
        """
        norm = self.norm if norm is None else norm
        ii = self.intercept_index if intercept_index is _UNSET \
            else intercept_index
        batch = LabeledBatch(X, y, w, o)
        vg, hvp, l1w = make_objective(
            self.loss, batch, norm, self.config.regularization,
            ii, X.shape[-1])
        opt_cfg = resolve_optimizer_config(
            self.config.optimizer, l1w is not None)
        result = optimize(vg, w0, opt_cfg, hvp=hvp, l1_weights=l1w)
        return result.w

    def _variance_one(self, X, y, w, o, w_opt, norm=None,
                      intercept_index=_UNSET):
        """Variances at the trained optimum (no re-solve; reference
        computeVariances evaluates the Hessian at the model coefficients)."""
        norm = self.norm if norm is None else norm
        ii = self.intercept_index if intercept_index is _UNSET \
            else intercept_index
        batch = LabeledBatch(X, y, w, o)
        return compute_variances(
            self.loss, w_opt, batch, norm,
            self.config.variance_computation, self.config.regularization,
            ii)

    @property
    def dim(self) -> int:
        return self.dataset.shard_dim(self.shard_id)

    def with_optimization_config(
        self, config: GLMOptimizationConfiguration
    ) -> "RandomEffectCoordinate":
        """Cheap copy with a new optimization config, reusing the bucketing
        and the staged per-bucket device arrays (the expensive part of
        __init__). Only the jitted programs are rebuilt."""
        import copy

        c = copy.copy(self)
        c.config = config
        c._build_fits()
        return c

    def adapt_initial(self, initial):
        """Accept a factored warm start by materializing its implied
        full-rank (E, d) table (reference: the factored coordinate hands
        RandomEffectModels to neighboring coordinate updates). In subspace
        mode, dense warm starts are additionally gathered into this
        coordinate's (E, A) active-column layout — inactive-column mass
        cannot survive a projected retrain anyway (projectBackward)."""
        from photon_ml_tpu.game.factored import FactoredRandomEffectModel

        if isinstance(initial, FactoredRandomEffectModel):
            initial = initial.to_random_effect_model()
        if not self.subspace:
            if isinstance(initial, SubspaceRandomEffectModel):
                return initial.to_random_effect_model()
            return initial
        if isinstance(initial, SubspaceRandomEffectModel):
            if initial.cols.shape[0] != self.subspace_cols.shape[0]:
                raise ValueError(
                    f"subspace warm start has {initial.cols.shape[0]} "
                    f"entities, coordinate expects "
                    f"{self.subspace_cols.shape[0]}")
            if initial.num_features != self.dim:
                raise ValueError(
                    f"subspace warm start has {initial.num_features} "
                    f"features, coordinate expects {self.dim} (the "
                    f"searchsorted sentinels would collide with real "
                    f"column ids)")
            if np.array_equal(np.asarray(initial.cols),
                              self.subspace_cols):
                return initial
            # Active sets differ (e.g. bucket bounds changed between
            # runs): re-map per entity via sorted-row searchsorted —
            # coefficients for columns no longer active are dropped
            # (projectBackward semantics), never misattributed.
            src_c = jnp.asarray(initial.cols)
            src_s = jnp.where(src_c < 0, self.dim + 1, src_c)
            tgt = jnp.asarray(self.subspace_cols)
            tgt_q = jnp.where(tgt < 0, self.dim + 2, tgt)  # never matches
            pos = jax.vmap(jnp.searchsorted)(src_s, tgt_q)
            posc = jnp.minimum(pos, src_c.shape[1] - 1)
            hit = jnp.take_along_axis(src_s, posc, axis=1) == tgt_q
            means = jnp.take_along_axis(
                jnp.asarray(initial.means), posc, axis=1) * hit
            return SubspaceRandomEffectModel(
                re_type=self.re_type, shard_id=self.shard_id,
                num_features=self.dim, cols=tgt, means=means)
        # Dense (E, d) → gather the active columns per entity.
        if initial.means.shape[0] != self.subspace_cols.shape[0]:
            raise ValueError(
                f"warm start has {initial.means.shape[0]} entities, "
                f"coordinate expects {self.subspace_cols.shape[0]} "
                f"(a clamped gather would misattribute rows)")
        if initial.means.shape[1] != self.dim:
            raise ValueError(
                f"warm start has {initial.means.shape[1]} features, "
                f"coordinate expects {self.dim} "
                f"(a clamped gather would misattribute columns)")
        cols = jnp.asarray(self.subspace_cols)
        means = jnp.asarray(initial.means)
        ga = means[jnp.arange(cols.shape[0])[:, None],
                   jnp.maximum(cols, 0)] * (cols >= 0)
        return SubspaceRandomEffectModel(
            re_type=self.re_type, shard_id=self.shard_id,
            num_features=self.dim, cols=cols, means=ga)

    def _prepare_table(self, initial):
        """Warm-start table in the space the bucket programs run in.

        Warm starts arrive in original space. Unprojected path: the W
        table is transformed once at entry and mapped back once at exit.
        Projected path: transforms are per-entity inside the bucket fit,
        so W stays in original space throughout. Subspace path: same,
        with the table in (E, A) active-column layout — (E, d) never
        exists. Shared by the full-sweep and gated-sweep train paths."""
        if initial is None:
            shape = (self.subspace_cols.shape if self.subspace
                     else (self.num_entities, self.dim))
            return jnp.zeros(shape, jnp.float32)
        if self.subspace:
            # Model layout is column-sorted; the bucket programs run in
            # bucket layout (intercept slot 0). take_along_axis yields a
            # fresh array, safe under fit_bucket's donation.
            return jnp.take_along_axis(jnp.asarray(initial.means),
                                       self._inv_perm_dev, axis=1)
        if self.projection:
            # Explicit copies: fit_bucket donates W.
            return jnp.array(initial.means, copy=True)
        return jnp.array(
            self.norm.model_to_transformed_space(initial.means), copy=True)

    def _finish_model(self, W):
        """Trained table (bucket space) → the public model."""
        if self.subspace:
            return SubspaceRandomEffectModel(
                re_type=self.re_type, shard_id=self.shard_id,
                num_features=self.dim, cols=self._cols_dev,
                means=jnp.take_along_axis(W, self._perm_dev, axis=1))
        W_raw = W if self.projection else self.norm.model_to_original_space(W)
        return RandomEffectModel(
            re_type=self.re_type, shard_id=self.shard_id, means=W_raw)

    def train_model(
        self,
        offsets: Array,
        initial: Optional[RandomEffectModel] = None,
    ) -> RandomEffectModel:
        if initial is not None:
            initial = self.adapt_initial(initial)
        W = self._prepare_table(initial)
        offsets = jnp.asarray(offsets)
        led = obs.ledger()
        mx = obs.metrics()
        for wave, arrays in enumerate(self._iter_bucket_data()):
            t_wave = time.perf_counter()
            # One span per vmapped entity-fit wave (the dispatch unit the
            # lane bound exists for). Dispatch is async: the span times
            # the submission + any blocking the runtime imposes, not the
            # device execution — the device side belongs to jax.profiler.
            with obs.span("re.fit_wave", cat="train", wave=wave,
                          re_type=self.re_type):
                W = self._fit_bucket(W, offsets, *arrays)
            lanes = int((self._host_rows[wave] >= 0).sum())
            if mx is not None:
                mx.counter("photon_re_entities_refit_total",
                           re_type=self.re_type).inc(lanes)
            if led is not None:
                # Wave-level aggregate (per-entity rows would be 1M-deep
                # noise); seconds are dispatch-side, same caveat as the
                # span above.
                led.record("re_fit_wave", re_type=self.re_type, wave=wave,
                           seconds=round(time.perf_counter() - t_wave, 6),
                           entities_fit=lanes, entities_skipped=0)
        return self._finish_model(W)

    # -- dirty-gated sweeps (game/sweep.py; docs/SWEEPS.md) ------------------

    def _bucket_census(self) -> None:
        """One host pass over the bucketing: per-entity row counts, the
        trained-entity mask, and whether every trained entity's rows are
        reachable through the bucket example maps (segment rescoring is
        exact iff they are — ``upper_bound`` capping leaves passive rows
        that ``score()`` covers but no ``ex`` map reaches)."""
        if self._segment_rescore_ok is not None:
            return
        counts = np.bincount(
            np.asarray(self.dataset.entity_ids[self.re_type]),
            minlength=self.num_entities)
        trained = np.zeros((self.num_entities,), bool)
        active_rows = 0
        for b in self.bucketing.buckets:
            live = b.entity_rows >= 0
            trained[b.entity_rows[live]] = True
            active_rows += int(b.counts[live].sum())
        self._entity_counts = counts
        self._trained_mask = trained
        self._segment_rescore_ok = \
            int(counts[trained].sum()) == active_rows

    def make_sweep_state(self):
        """Fresh dirty-set state for this coordinate (descent start)."""
        from photon_ml_tpu.game import sweep as swp

        self._bucket_census()
        scale = np.maximum(self._entity_counts, 1).astype(np.float32)
        return swp.CoordinateSweepState(
            self.num_entities, self._ids, scale, self._trained_mask)

    def _gram_eligible(self) -> bool:
        """Normal-equation reuse applies when the bucket solve IS a
        ridge-regularized least-squares problem in the staged feature
        space: squared loss, strictly positive L2 (the ridge term is what
        makes the normal matrix positive-definite for entities with fewer
        samples than features — at λ=0 the closed form is singular where
        the iterative solver returns the min-norm solution), no L1 (no
        prox in the closed form), no per-entity projection (G caches per
        full-width lane), identity normalization (transformed == staged
        space), and a Gram footprint that fits (E·d² elements).
        Everything else falls back to the iterative solver — silently,
        per the registry-fallback idiom."""
        return (not self.projection
                and self.loss.name == "squared"
                and self.config.regularization.l2_weight() > 0.0
                and self.config.regularization.l1_weight() == 0.0
                and self.norm.factors is None
                and self.norm.shifts is None
                and self.num_entities * self.dim * self.dim <= (1 << 27))

    def _gram_for_wave(self, wave: int, arrays) -> Array:
        G = self._gram_cache.get(wave)
        if G is None:
            G = _gram_block(arrays[0], arrays[2])
            self._gram_cache[wave] = G
        return G

    def _build_gated_fits(self) -> None:
        """Jitted gated-wave programs: the same per-lane solves as the
        full-sweep program plus (a) final per-lane gradient norms spilled
        into the (E,) evidence vector and (b) the fit lanes' score-segment
        deltas scatter-added into an (n,) delta accumulator — exactly 0.0
        on rows of unfit entities, so ``total += delta`` preserves the f32
        accumulation discipline on clean rows. SEPARATE executables from
        ``_fit_bucket`` by design: the full-sweep program stays
        byte-identical to HEAD, which is what makes the gate=0 rung of the
        parity ladder bit-exact by construction."""
        self._bucket_census()
        num_entities = self.num_entities
        n = int(self.dataset.num_rows)
        seg_ok = bool(self._segment_rescore_ok)

        def seg_scatter(delta, Xb, ex, d_orig):
            if not seg_ok:
                return delta
            if Xb.dtype == jnp.bfloat16:
                seg = jnp.einsum("ecd,ed->ec", Xb,
                                 d_orig.astype(jnp.bfloat16),
                                 preferred_element_type=jnp.float32)
            else:
                seg = jnp.einsum("ecd,ed->ec", Xb, d_orig)
            return delta.at[jnp.where(ex >= 0, ex, n)].add(
                seg, mode="drop")

        if not self.projection:
            norm = self.norm
            cfg = self.config

            def solve_gn(X, y, w, o, w0):
                batch = LabeledBatch(X, y, w, o)
                vg, hvp, l1w = make_objective(
                    self.loss, batch, norm, cfg.regularization,
                    self.intercept_index, X.shape[-1])
                opt_cfg = resolve_optimizer_config(
                    cfg.optimizer, l1w is not None)
                result = optimize(vg, w0, opt_cfg, hvp=hvp,
                                  l1_weights=l1w)
                return result.w, result.grad_norm

            vsolve = jax.vmap(solve_gn)
            _gather_rows, _scatter_rows = self._row_movers()

            def fit_gated(W, delta, gnorms, offsets, Xb, yb, wb, ex,
                          rows):
                ob = offsets[jnp.maximum(ex, 0)]
                w0 = _gather_rows(W, rows)
                w_fit, gn = vsolve(Xb, yb, wb, ob, w0)
                W = _scatter_rows(W, rows, w_fit)
                safe = jnp.where(rows >= 0, rows, num_entities)
                gnorms = gnorms.at[safe].set(gn, mode="drop")
                # Score delta in ORIGINAL space: the staged Xb are raw
                # features, so x·Δw_orig is exactly the per-example
                # score movement score() would report.
                d_orig = (norm.model_to_original_space(w_fit)
                          - norm.model_to_original_space(w0))
                delta = seg_scatter(delta, Xb, ex, d_orig)
                return W, delta, gnorms

            self._fit_bucket_gated = jax.jit(fit_gated,
                                             donate_argnums=(0, 1, 2))
            self._fit_bucket_gram = self._build_gram_fit(seg_scatter)
            return

        # Projected/subspace variant — mirrors _build_projected_fits.
        dim = self.dim
        has_f = not (self.norm.factors is None and self.norm.shifts is None)
        has_s = self.norm.shifts is not None
        ii_proj = 0 if self.intercept_index is not None else None

        def ctx_for(f, s):
            if not has_f:
                return NormalizationContext()
            return NormalizationContext(factors=f, shifts=s,
                                        intercept_index=ii_proj)

        def solve_one_gn(X, y, w, o, w0_orig, f, s):
            ctx = ctx_for(f, s)
            w0 = ctx.model_to_transformed_space(w0_orig)
            batch = LabeledBatch(X, y, w, o)
            vg, hvp, l1w = make_objective(
                self.loss, batch, ctx, self.config.regularization,
                ii_proj, X.shape[-1])
            opt_cfg = resolve_optimizer_config(
                self.config.optimizer, l1w is not None)
            result = optimize(vg, w0, opt_cfg, hvp=hvp, l1_weights=l1w)
            return ctx.model_to_original_space(result.w), result.grad_norm

        norm_axes = (0 if has_f else None, 0 if has_s else None)
        vsolve = jax.vmap(solve_one_gn,
                          in_axes=(0, 0, 0, 0, 0) + norm_axes)
        subspace = self.subspace

        def unpack(extra):
            cols = extra[0]
            f = extra[1] if has_f else None
            s = extra[2 if has_f else 1] if has_s else None
            return cols, f, s

        def fit_gated(W, delta, gnorms, offsets, Xb, yb, wb, ex, rows,
                      *extra):
            cols, f, s = unpack(extra)
            ob = offsets[jnp.maximum(ex, 0)]
            safe_rows = jnp.where(rows >= 0, rows, num_entities)
            if subspace:
                da = cols.shape[1]
                w0 = W[jnp.maximum(rows, 0)][:, :da]
                w_fit, gn = vsolve(Xb, yb, wb, ob, w0, f, s)
                w_pad = jnp.pad(w_fit, ((0, 0), (0, W.shape[1] - da)))
                W = W.at[safe_rows].set(w_pad, mode="drop")
            else:
                valid = (cols >= 0).astype(W.dtype)
                w0 = W[jnp.maximum(rows, 0)[:, None],
                       jnp.maximum(cols, 0)] * valid
                safe_cols = jnp.where(cols >= 0, cols, dim)
                w_fit, gn = vsolve(Xb, yb, wb, ob, w0, f, s)
                W = W.at[safe_rows].set(0.0, mode="drop")
                W = W.at[safe_rows[:, None], safe_cols].set(
                    w_fit, mode="drop")
            gnorms = gnorms.at[safe_rows].set(gn, mode="drop")
            # Active-column delta: exact vs the full-row difference
            # because gated waves always follow >= 1 full sweep
            # (min_sweeps_full), which leaves no inactive-column mass
            # (projectBackward).
            delta = seg_scatter(delta, Xb, ex, w_fit - w0)
            return W, delta, gnorms

        self._fit_bucket_gated = jax.jit(fit_gated,
                                         donate_argnums=(0, 1, 2))
        self._fit_bucket_gram = None

    def _build_gram_fit(self, seg_scatter):
        """Closed-form gated wave for the squared-loss ridge problem:
        (G + λ·diag(mask)) w = X^T(w_ex·(y − o)) with the per-lane Gram
        block G = X^T diag(w_ex) X cached across sweeps (_gram_for_wave).
        The gradient norm spilled as evidence is ‖A w − rhs‖ — the true
        objective gradient at the returned point, so a lane that fell
        back (non-finite solve) stays dirty."""
        if not self._gram_eligible():
            return None
        from photon_ml_tpu.optim.regularization import intercept_mask
        num_entities = self.num_entities
        l2 = float(self.config.regularization.l2_weight())
        maskv = jnp.asarray(intercept_mask(self.dim, self.intercept_index))
        _gather_rows, _scatter_rows = self._row_movers()

        def gram_solve_one(G, X, y, w, o, w0):
            Xf = X.astype(jnp.float32)
            rhs = jnp.einsum("ck,c->k", Xf, w * (y - o))
            A = G + l2 * jnp.diag(maskv)
            w_new = jnp.linalg.solve(A, rhs)
            w_new = jnp.where(jnp.all(jnp.isfinite(w_new)), w_new, w0)
            gn = jnp.linalg.norm(A @ w_new - rhs)
            return w_new, gn

        vsolve = jax.vmap(gram_solve_one)

        def fit_gram(W, delta, gnorms, offsets, G, Xb, yb, wb, ex, rows):
            ob = offsets[jnp.maximum(ex, 0)]
            w0 = _gather_rows(W, rows)
            w_fit, gn = vsolve(G, Xb, yb, wb, ob, w0)
            W = _scatter_rows(W, rows, w_fit)
            safe = jnp.where(rows >= 0, rows, num_entities)
            gnorms = gnorms.at[safe].set(gn, mode="drop")
            delta = seg_scatter(delta, Xb, ex, w_fit - w0)
            return W, delta, gnorms

        return jax.jit(fit_gram, donate_argnums=(0, 1, 2))

    def train_model_gated(self, offsets, state, config, initial=None,
                          force_full=False):
        """Dirty-gated train (docs/SWEEPS.md): refit only entities whose
        residual offsets drifted past ``theta·scale`` or whose last solve
        left gradient mass above ``grad_tol``, compacted into dense
        active waves; a 90%-converged sweep dispatches ~10% of the lanes.

        Returns ``(model, delta, stats)``. ``delta`` is the (n,) score
        delta to add into the residual total — exactly 0.0 on rows of
        unfit entities — or None when segment rescoring is inexact for
        this bucketing (``upper_bound`` leaves passive rows) and the
        caller must rescore via ``score()``. ``force_full`` refits every
        trained entity through the gated (evidence-spilling) programs —
        the forced-full rungs of the parity ladder (warm-up sweeps and
        the final backstop)."""
        from photon_ml_tpu.game import sweep as swp

        if initial is not None:
            initial = self.adapt_initial(initial)
        W = self._prepare_table(initial)
        offsets = jnp.asarray(offsets)
        n = int(self.dataset.num_rows)
        if self._fit_bucket_gated is None:
            self._build_gated_fits()
        use_gram = config.gram and self._fit_bucket_gram is not None
        dirty = drift = dirty_host = None
        if not force_full and state.off_ref is not None:
            dirty, drift = state.gate(offsets, config)
            # Host-side lane selection: compacted wave shapes must be
            # known on host to build/dispatch the programs.
            dirty_host = np.asarray(dirty)
        p99 = state.drift_p99(drift) if drift is not None else 0.0
        delta = jnp.zeros((n,), jnp.float32)
        gnorms = state.grad_norms
        pad = self.bucketing.entity_pad_multiple
        led = obs.ledger()
        mx = obs.metrics()
        total_fit = total_skip = 0
        for wave, arrays in enumerate(self._iter_bucket_data()):
            rows_host = self._host_rows[wave]
            live = rows_host >= 0
            live_n = int(live.sum())
            sel_dev = None
            if dirty_host is None:
                fit_lanes, skip_lanes = live_n, 0
                args = arrays
            else:
                lane_dirty = live & dirty_host[np.maximum(rows_host, 0)]
                fit_lanes = int(lane_dirty.sum())
                skip_lanes = live_n - fit_lanes
            total_fit += fit_lanes
            total_skip += skip_lanes
            if mx is not None:
                if fit_lanes:
                    mx.counter("photon_re_entities_refit_total",
                               re_type=self.re_type).inc(fit_lanes)
                if skip_lanes:
                    mx.counter("photon_re_entities_skipped_total",
                               re_type=self.re_type).inc(skip_lanes)
            if dirty_host is not None and fit_lanes == 0:
                # Fully-converged wave: nothing dispatches at all.
                if led is not None:
                    led.record("re_fit_wave", re_type=self.re_type,
                               wave=wave, seconds=0.0, entities_fit=0,
                               entities_skipped=skip_lanes,
                               drift_p99=round(p99, 9))
                continue
            t_wave = time.perf_counter()
            with obs.span("re.fit_wave", cat="train", wave=wave,
                          re_type=self.re_type):
                if dirty_host is not None:
                    idx = np.flatnonzero(lane_dirty)
                    L = swp.compact_lanes(idx.size, pad, rows_host.size)
                    sel = np.full((L,), -1, np.int32)
                    sel[:idx.size] = idx.astype(np.int32)
                    sel_dev = jnp.asarray(sel)
                    args = _compact_tuple(sel_dev, *arrays)
                if use_gram:
                    G = self._gram_for_wave(wave, arrays)
                    if sel_dev is not None:
                        G = jnp.take(G, jnp.maximum(sel_dev, 0), axis=0)
                    W, delta, gnorms = self._fit_bucket_gram(
                        W, delta, gnorms, offsets, G, *args)
                else:
                    W, delta, gnorms = self._fit_bucket_gated(
                        W, delta, gnorms, offsets, *args)
            if led is not None:
                led.record("re_fit_wave", re_type=self.re_type, wave=wave,
                           seconds=round(time.perf_counter() - t_wave, 6),
                           entities_fit=fit_lanes,
                           entities_skipped=skip_lanes,
                           drift_p99=round(p99, 9))
        state.grad_norms = gnorms
        state.advance(offsets, None if dirty_host is None else dirty)
        stats = {"entities_fit": total_fit,
                 "entities_skipped": total_skip, "drift_p99": p99}
        return (self._finish_model(W),
                delta if self._segment_rescore_ok else None, stats)

    def compute_model_variances(
        self, model: RandomEffectModel, offsets: Array
    ) -> RandomEffectModel:
        """Per-entity coefficient variances at the trained optimum."""
        if VarianceComputationType(self.config.variance_computation) == \
                VarianceComputationType.NONE:
            return model
        if self.subspace:
            # Sorted model layout → bucket layout for the programs.
            W = jnp.take_along_axis(jnp.asarray(model.means),
                                    self._inv_perm_dev, axis=1)
        elif self.projection:
            # Per-entity transforms (and the original-space mapping) happen
            # inside var_bucket; W stays original space.
            W = jnp.asarray(model.means)
        else:
            W = jnp.asarray(self.norm.model_to_transformed_space(model.means))
        V = jnp.zeros(model.means.shape, jnp.float32)
        offsets = jnp.asarray(offsets)
        for arrays in self._iter_bucket_data():
            V = self._var_bucket(W, V, offsets, *arrays)
        if not self.projection and (self.norm.factors is not None
                                    or self.norm.shifts is not None):
            # Same diagonal-approximation transform the projected path and
            # FixedEffectCoordinate use (factor² scaling + intercept
            # shift-mass term).
            V = self.norm.variances_to_original_space(V)
        if self.subspace:
            V = jnp.take_along_axis(V, self._perm_dev, axis=1)
        return dataclasses.replace(model, variances=V)

    def score(self, model) -> Array:
        if self.subspace:
            W_flat = jnp.asarray(model.means).reshape(-1)
            if self.is_sparse:
                # Staged join: each data nonzero's flat slot in the (E, A)
                # table was computed once at __init__ (misses → one past
                # the end → zero contribution).
                return _subspace_sparse_scores(W_flat, self._sp_flatpos,
                                               self._sp_values)
            cols = jnp.asarray(self._cols_dev)[self._ids]  # (n, A)
            xa = jnp.take_along_axis(
                self._X, jnp.maximum(cols, 0), axis=1) * (cols >= 0)
            return jnp.einsum("na,na->n", xa,
                              jnp.asarray(model.means)[self._ids])
        if self.is_sparse:
            # Σ_k v_ik · W[e_i, idx_ik]. ELL padding slots carry value 0
            # by contract, so clamping their sentinel index (== d) into
            # range is exact — no (E, d+1) padded copy of the table.
            W = jnp.asarray(model.means)
            idx = jnp.minimum(self._sp_indices, W.shape[1] - 1)
            return jnp.sum(
                self._sp_values * W[self._ids[:, None], idx], axis=-1)
        return jnp.einsum("nd,nd->n", self._X, model.means[self._ids])

    def initial_model(self):
        if self.subspace:
            return SubspaceRandomEffectModel(
                re_type=self.re_type, shard_id=self.shard_id,
                num_features=self.dim, cols=self._cols_dev,
                means=jnp.zeros(self.subspace_cols.shape, jnp.float32))
        return RandomEffectModel(
            re_type=self.re_type, shard_id=self.shard_id,
            means=jnp.zeros((self.num_entities, self.dim), jnp.float32))
