"""Sparse fixed-effect coordinate: ELL / hybrid layouts (the Criteo path).

See the package docstring (photon_ml_tpu/game/coordinates/__init__.py) for
the residency discipline shared by all coordinate types.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu import obs
from photon_ml_tpu.data.game_data import GameDataset
from photon_ml_tpu.game.coordinates._down_sampling import (
    _advance_down_sampling, draw_down_sample)
from photon_ml_tpu.game.models import FixedEffectModel
from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.obs.ledger import spill_history
from photon_ml_tpu.ops.losses import PointwiseLoss
from photon_ml_tpu.optim.problem import (GLMOptimizationConfiguration,
                                         VarianceComputationType,
                                         variances_from_diagonal)
from photon_ml_tpu.optim.regularization import intercept_mask
from photon_ml_tpu.parallel.mesh import DATA_AXIS, pad_to_multiple

Array = jax.Array


class SparseFixedEffectCoordinate:
    """Fixed-effect GLM over an ELL sparse shard (the Criteo path).

    Reference parity: same FixedEffectCoordinate contract, but the
    objective is the sparse gather/scatter pipeline
    (parallel/sparse_objective.py) instead of dense matmuls — the analogue
    of the reference training on sparse Breeze vectors + PalDB index maps.
    With ``feature_sharded=True`` the coefficient dimension additionally
    shards over the mesh's ``model`` axis (P3) for feature spaces too large
    to replicate.

    Residency discipline matches the dense coordinate: the staged batch
    lives on device once; per CD step only (n,) offsets and the warm
    start move.

    Two execution layouts:
    - ``hybrid`` (default whenever coefficients replicate): the hot-dense /
      cold-class layout of ops/hybrid_sparse.py — the Zipf head of the
      feature space rides the MXU as a dense block and the cold tail's
      random crossings shrink to ~15% of the volume (measured ~4-10× the
      ELL step at d=1M on one v5e chip). Exact, not approximate: the
      solve happens in a statically permuted feature space and maps back.
      On a multi-data-shard mesh the rows split contiguously into
      per-shard hybrid layouts under one GLOBAL permutation
      (HybridShards): hot/cold aggregates run shard-local and psum over
      ``data``, so the fast path composes with data parallelism.
    - ELL shard_map pipeline (parallel/sparse_objective.py): required for
      ``feature_sharded=True`` (P3), where the coefficient dimension
      itself shards over ``model`` and the hybrid layout's replicated
      permuted space does not exist.

    Normalization is not supported here (the reference normalizes dense
    shards only; scaling sparse values would densify shift terms).
    Sparse RANDOM effects are deliberately not a separate class: large-d
    sparse per-entity features are exactly the regime the per-entity
    subspace projection handles (RandomEffectCoordinate stages dense
    d_active buckets straight from the ELL triplets).
    """

    def __init__(
        self,
        dataset: GameDataset,
        shard_id: str,
        loss: PointwiseLoss,
        config: GLMOptimizationConfiguration,
        mesh,
        feature_sharded: bool = False,
        down_sampling_seed: int = 0,
        hybrid: Optional[bool] = None,
        feature_dtype: str = "float32",
    ):
        from photon_ml_tpu.data.game_data import SparseShard
        from photon_ml_tpu.data.sparse import SparseBatch
        from photon_ml_tpu.parallel import sparse_problem as sp

        shard = dataset.feature_shards[shard_id]
        if not isinstance(shard, SparseShard):
            raise TypeError(f"shard {shard_id!r} is not sparse")
        self.dataset = dataset
        self.shard_id = shard_id
        self.loss = loss
        self.config = config
        self.mesh = mesh
        self.feature_sharded = bool(feature_sharded)
        self.intercept_index = dataset.intercept_index.get(shard_id)
        self._down_sampling_seed = down_sampling_seed
        self._rng = np.random.default_rng(down_sampling_seed)
        self._dim = int(shard.num_features)
        self.feature_dtype = feature_dtype

        single_shard = mesh.shape[DATA_AXIS] == 1
        if hybrid is None:
            self.hybrid = not self.feature_sharded
        else:
            self.hybrid = bool(hybrid)
            if self.hybrid and self.feature_sharded:
                raise ValueError(
                    "hybrid=True is incompatible with feature_sharded "
                    "(the hybrid layout needs the permuted coefficient "
                    "space replicated on every shard)")
        self._hybrid_sharded = self.hybrid and not single_shard

        batch = SparseBatch(
            indices=np.asarray(shard.indices),
            values=np.asarray(shard.values),
            labels=np.asarray(dataset.response),
            weights=np.asarray(dataset.weights),
            offsets=np.zeros(dataset.num_rows, np.float32),
            num_features=self._dim)
        if self.hybrid:
            import jax.numpy as _jnp

            from photon_ml_tpu.ops import hybrid_sparse as hybrid_mod

            dt = (_jnp.bfloat16 if feature_dtype == "bfloat16"
                  else _jnp.float32)
            if self._hybrid_sharded:
                shb = hybrid_mod.build_hybrid_shards(
                    batch, mesh.shape[DATA_AXIS], feature_dtype=dt)
                self._staged = sp.shard_hybrid(shb, mesh)
            else:
                self._staged = hybrid_mod.build_hybrid(
                    batch, feature_dtype=dt)
            self._ii_perm = (
                None if self.intercept_index is None else int(
                    np.asarray(self._staged.inv_perm)[self.intercept_index]))
        else:
            if self.feature_sharded:
                from photon_ml_tpu.parallel.mesh import MODEL_AXIS
                batch = sp._pad_features(
                    batch,
                    pad_to_multiple(self._dim, mesh.shape[MODEL_AXIS]))
            self._staged = sp.shard_sparse_batch(batch, mesh)
        self._build_fits()

    # -- jitted programs ---------------------------------------------------

    def _padded_offsets(self, offsets: jax.Array) -> jax.Array:
        offsets = jnp.asarray(offsets)
        n = self.dataset.num_rows
        return jnp.zeros((self._staged.num_rows,), offsets.dtype
                         ).at[:n].set(offsets)

    def _build_fits(self):
        if self.hybrid:
            self._build_hybrid_fits()
            return
        from photon_ml_tpu.ops import sparse_aggregators as sagg
        from photon_ml_tpu.parallel import sparse_problem as sp

        cfg = dataclasses.replace(
            self.config, variance_computation=VarianceComputationType.NONE)
        loss, mesh, fs = self.loss, self.mesh, self.feature_sharded
        ii = self.intercept_index
        d_true = self._dim
        d_staged = self._staged.num_features

        def lift(w0):
            """True-dim warm start → staged (possibly feature-padded) dim."""
            if d_staged == d_true:
                return w0
            return jnp.zeros((d_staged,), w0.dtype).at[:d_true].set(w0)

        def fit(staged, offsets, w0):
            batch = dataclasses.replace(
                staged, offsets=self._padded_offsets(offsets))
            coef, res = sp.run(loss, batch, mesh, cfg,
                               initial=Coefficients(lift(w0)),
                               intercept_index=ii,
                               feature_sharded=fs, already_sharded=True)
            # Histories ride along for the run ledger's post-fit spill
            # (tiny, device-resident, free when no ledger is active).
            return (coef.means[:d_true], res.value_history,
                    res.grad_norm_history)

        def fit_sampled(staged, idx, mult, offsets, w0):
            sub = dataclasses.replace(
                staged,
                indices=staged.indices[idx],
                values=staged.values[idx],
                labels=staged.labels[idx],
                weights=staged.weights[idx] * mult,
                offsets=offsets[idx],
            ).pad_to(pad_to_multiple(idx.shape[0], mesh.shape[DATA_AXIS]))
            coef, res = sp.run(loss, sub, mesh, cfg,
                               initial=Coefficients(lift(w0)),
                               intercept_index=ii,
                               feature_sharded=fs, already_sharded=True)
            return (coef.means[:d_true], res.value_history,
                    res.grad_norm_history)

        def score_fn(staged, means):
            # Staged offsets are zeros, so margins == X @ w exactly.
            return sagg.margins(staged, means)

        self._fit = jax.jit(fit)
        self._fit_sampled = jax.jit(fit_sampled)
        self._score = jax.jit(score_fn)

    def _build_hybrid_fits(self):
        """Jitted hybrid-layout programs. Per CD step only (n,) offsets and
        the warm start move; the staged HybridSparseBatch / HybridShards is
        a jit argument (never a baked constant) so the big hot block stays
        device-resident across compilations. Down-sampling masks weights in
        place of the ELL path's row gather — the objective is identical
        (dropped rows get weight 0, kept rows scale by the rate
        multiplier)."""
        from photon_ml_tpu.ops import hybrid_sparse as hybrid_mod
        from photon_ml_tpu.parallel import sparse_problem as sp

        cfg = dataclasses.replace(
            self.config, variance_computation=VarianceComputationType.NONE)
        loss = self.loss
        ii_perm = self._ii_perm

        if self._hybrid_sharded:
            self._build_hybrid_sharded_fits(cfg, ii_perm)
            return

        def fit(hb, offsets, w0):
            hbo = dataclasses.replace(hb, offsets=jnp.asarray(offsets))
            coef, res = sp.run_hybrid(loss, hbo, cfg,
                                      initial=Coefficients(w0),
                                      intercept_index_permuted=ii_perm)
            return coef.means, res.value_history, res.grad_norm_history

        def fit_sampled(hb, idx, mult, offsets, w0):
            w_masked = jnp.zeros_like(hb.weights).at[idx].set(
                hb.weights[idx] * mult)
            hbo = dataclasses.replace(hb, weights=w_masked,
                                      offsets=jnp.asarray(offsets))
            coef, res = sp.run_hybrid(loss, hbo, cfg,
                                      initial=Coefficients(w0),
                                      intercept_index_permuted=ii_perm)
            return coef.means, res.value_history, res.grad_norm_history

        def score_fn(hb, means):
            # Staged offsets are zeros, so margins == X @ w exactly.
            return hybrid_mod.margins(
                hb, hybrid_mod.to_permuted_space(hb, means))

        def hess_diag(hb, offsets, means):
            hbo = dataclasses.replace(hb, offsets=jnp.asarray(offsets))
            return hybrid_mod.to_original_space(
                hbo, hybrid_mod.hessian_diagonal(
                    loss, hybrid_mod.to_permuted_space(hbo, means), hbo))

        self._fit = jax.jit(fit)
        self._fit_sampled = jax.jit(fit_sampled)
        self._score = jax.jit(score_fn)
        self._hess_diag = jax.jit(hess_diag)

    def _build_hybrid_sharded_fits(self, cfg, ii_perm):
        """Jitted programs over the data-sharded hybrid layout.

        Offsets/weights keep the contract of the rest of the class — flat
        padded global row order — and reshape to the (S, n_l) grid at the
        jit boundary (padding sits at the global tail, so flat index ==
        original row id)."""
        from photon_ml_tpu.parallel import sparse_objective as sobj
        from photon_ml_tpu.parallel import sparse_problem as sp

        loss = self.loss
        mesh = self.mesh
        S = self._staged.num_shards
        n_l = self._staged.rows_per_shard
        n = self.dataset.num_rows

        def grid(offsets):
            # fit() passes raw (n,) offsets; fit_sampled already padded
            # them to the staged length via _padded_offsets.
            offsets = jnp.asarray(offsets)
            flat = (offsets if offsets.shape[0] == S * n_l
                    else self._padded_offsets(offsets))
            return flat.reshape(S, n_l)

        def fit(shb, offsets, w0):
            shbo = dataclasses.replace(shb, offsets=grid(offsets))
            coef, res = sp.run_hybrid_sharded(
                loss, shbo, mesh, cfg, initial=Coefficients(w0),
                intercept_index_permuted=ii_perm)
            return coef.means, res.value_history, res.grad_norm_history

        def fit_sampled(shb, idx, mult, offsets, w0):
            wf = shb.weights.reshape(-1)
            w_masked = jnp.zeros_like(wf).at[idx].set(
                wf[idx] * mult).reshape(shb.weights.shape)
            shbo = dataclasses.replace(shb, weights=w_masked,
                                       offsets=grid(offsets))
            coef, res = sp.run_hybrid_sharded(
                loss, shbo, mesh, cfg, initial=Coefficients(w0),
                intercept_index_permuted=ii_perm)
            return coef.means, res.value_history, res.grad_norm_history

        def score_fn(shb, means):
            # Staged offsets are zeros, so margins == X @ w exactly; rows
            # come back in flat padded global order.
            return sobj.make_hybrid_margins(mesh, shb)(means[shb.perm])

        def hess_diag(shb, offsets, means):
            shbo = dataclasses.replace(shb, offsets=grid(offsets))
            diag = sobj.make_hybrid_hessian_diagonal(
                loss, mesh, shbo)(means[shbo.perm])
            return diag[shbo.inv_perm]

        self._fit = jax.jit(fit)
        self._fit_sampled = jax.jit(fit_sampled)
        self._score = jax.jit(score_fn)
        self._hess_diag = jax.jit(hess_diag)

    # -- coordinate contract ----------------------------------------------

    @property
    def dim(self) -> int:
        return self._dim

    def with_optimization_config(
        self, config: GLMOptimizationConfiguration
    ) -> "SparseFixedEffectCoordinate":
        import copy

        c = copy.copy(self)
        c.config = config
        c._rng = np.random.default_rng(self._down_sampling_seed)
        c._build_fits()
        return c

    def train_model(
        self,
        offsets: jax.Array,
        initial: Optional[FixedEffectModel] = None,
    ) -> FixedEffectModel:
        if initial is not None:
            w0 = jnp.asarray(initial.coefficients.means)
        else:
            w0 = jnp.zeros((self.dim,), jnp.float32)
        offsets = jnp.asarray(offsets)
        rate = self.config.down_sampling_rate
        if rate < 1.0:
            idx, mult = draw_down_sample(self, rate)
            w, vals, gns = self._fit_sampled(self._staged,
                                             jnp.asarray(idx),
                                             jnp.asarray(mult),
                                             self._padded_offsets(offsets),
                                             w0)
        else:
            w, vals, gns = self._fit(self._staged, offsets, w0)
        led = obs.ledger()
        if led is not None:
            # Post-fit spill of the compiled histories (one host read,
            # once per coordinate update) — docs/OBSERVABILITY.md.
            spill_history(
                led, np.asarray(vals), np.asarray(gns),
                opt=self.config.optimizer.optimizer_type.value.lower())
        return FixedEffectModel(shard_id=self.shard_id,
                                coefficients=Coefficients(w))

    def compute_model_variances(
        self, model: FixedEffectModel, offsets: jax.Array
    ) -> FixedEffectModel:
        from photon_ml_tpu.parallel import sparse_objective as sobj

        kind = VarianceComputationType(self.config.variance_computation)
        if kind == VarianceComputationType.NONE:
            return model
        if kind == VarianceComputationType.FULL:
            raise NotImplementedError(
                "FULL variance needs the dense d×d Hessian — use SIMPLE at "
                "sparse scale (as the reference does)")
        if self.hybrid:
            diag = self._hess_diag(self._staged,
                                   self._padded_offsets(offsets),
                                   jnp.asarray(model.coefficients.means))
            var = variances_from_diagonal(
                diag, self.config.regularization.l2_weight(),
                jnp.asarray(intercept_mask(self.dim, self.intercept_index)))
            return dataclasses.replace(
                model,
                coefficients=Coefficients(model.coefficients.means, var))
        batch = dataclasses.replace(
            self._staged, offsets=self._padded_offsets(offsets))
        d_staged = batch.num_features
        w = jnp.zeros((d_staged,), jnp.float32
                      ).at[:self.dim].set(model.coefficients.means)
        diag = sobj.make_hessian_diagonal(
            self.loss, self.mesh, batch, self.feature_sharded)(w)
        mask = np.zeros(d_staged, np.float32)
        mask[:self.dim] = intercept_mask(self.dim, self.intercept_index)
        var = variances_from_diagonal(
            diag, self.config.regularization.l2_weight(),
            jnp.asarray(mask))[:self.dim]
        return dataclasses.replace(
            model,
            coefficients=Coefficients(model.coefficients.means, var))

    def score(self, model: FixedEffectModel) -> jax.Array:
        n = self.dataset.num_rows
        means = jnp.asarray(model.coefficients.means)
        d_staged = self._staged.num_features
        if d_staged != self.dim:
            means = jnp.zeros((d_staged,), means.dtype
                              ).at[:self.dim].set(means)
        return self._score(self._staged, means)[:n]

    def initial_model(self) -> FixedEffectModel:
        return FixedEffectModel(
            shard_id=self.shard_id,
            coefficients=Coefficients.zeros(self.dim))

    def advance_down_sampling(self, steps: int) -> None:
        """See FixedEffectCoordinate.advance_down_sampling."""
        _advance_down_sampling(self, steps)


