"""Dense fixed-effect coordinate: one shared GLM, data-parallel (P1).

See the package docstring (photon_ml_tpu/game/coordinates/__init__.py) for
the residency discipline shared by all coordinate types.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu import obs
from photon_ml_tpu.data.batch import LabeledBatch
from photon_ml_tpu.data.game_data import GameDataset
from photon_ml_tpu.game.coordinates._down_sampling import (
    _advance_down_sampling, draw_down_sample)
from photon_ml_tpu.game.models import FixedEffectModel
from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.normalization import NormalizationContext
from photon_ml_tpu.obs.ledger import spill_history
from photon_ml_tpu.ops.losses import PointwiseLoss
from photon_ml_tpu.optim.problem import (GLMOptimizationConfiguration,
                                         VarianceComputationType,
                                         variances_from_diagonal,
                                         variances_from_matrix)
from photon_ml_tpu.optim.regularization import intercept_mask
from photon_ml_tpu.parallel import objective as dobj
from photon_ml_tpu.parallel import problem as dist_problem
from photon_ml_tpu.parallel.mesh import (DATA_AXIS, pad_to_multiple,
                                         shard_batch)

Array = jax.Array


class FixedEffectCoordinate:
    """One shared GLM trained data-parallel over the mesh.

    Reference parity: FixedEffectCoordinate + DistributedOptimizationProblem.

    Model-space contract: the optimizer runs in the normalization-transformed
    space, but the FixedEffectModel handed out ALWAYS holds ORIGINAL-space
    coefficients (converted at the train boundary, reconverted for warm
    starts) so every scorer — GameModel.score, the transformer, the CLIs,
    save/load — is a plain X @ w. The two are algebraically identical:
    X @ (w∘f) − (w∘f)·s == X @ model_to_original_space(w).
    """

    def __init__(
        self,
        dataset: GameDataset,
        shard_id: str,
        loss: PointwiseLoss,
        config: GLMOptimizationConfiguration,
        mesh,
        norm: NormalizationContext = NormalizationContext(),
        down_sampling_seed: int = 0,
        feature_dtype: str = "float32",
    ):
        self.dataset = dataset
        self.shard_id = shard_id
        self.loss = loss
        self.config = config
        self.mesh = mesh
        self.norm = norm
        self.intercept_index = dataset.intercept_index.get(shard_id)
        self._down_sampling_seed = down_sampling_seed
        self._rng = np.random.default_rng(down_sampling_seed)
        self.feature_dtype = feature_dtype
        # Stage the full training batch on device ONCE (offsets are a
        # placeholder — they are the per-CD-step input). shard_batch pads to
        # a multiple of the data-axis size with zero-weight rows. Scoring
        # reuses the staged features — no second device copy of X.
        # feature_dtype="bfloat16" stores X at half width (see
        # ops/aggregators._matvec for the f32-accumulation contract).
        self._staged = shard_batch(
            LabeledBatch.build(dataset.feature_shards[shard_id],
                               dataset.response, dataset.weights,
                               feature_dtype=feature_dtype),
            mesh)
        self._build_fits()

    def _padded_offsets(self, offsets: Array) -> Array:
        """Extend (n,) offsets with zeros to the staged padded length
        (padding rows have weight 0, so their offsets are inert)."""
        offsets = jnp.asarray(offsets)
        n = self.dataset.num_rows
        return jnp.zeros((self._staged.num_rows,), offsets.dtype
                         ).at[:n].set(offsets)

    def _build_fits(self):
        """(Re)build the cached jitted fit programs for the current config."""
        cfg = dataclasses.replace(
            self.config, variance_computation=VarianceComputationType.NONE)
        loss, mesh, norm = self.loss, self.mesh, self.norm
        ii = self.intercept_index

        def fit(staged: LabeledBatch, offsets: Array, w0: Array):
            batch = dataclasses.replace(staged,
                                        offsets=self._padded_offsets(offsets))
            coef, res = dist_problem.run(
                loss, batch, mesh, cfg, initial=Coefficients(w0), norm=norm,
                intercept_index=ii, already_sharded=True)
            # Histories ride along for the run ledger's post-fit spill
            # (tiny (max_it+1,) vectors; they stay on device — and cost
            # nothing — unless a ledger is active).
            return coef.means, res.value_history, res.grad_norm_history

        def fit_sampled(staged: LabeledBatch, idx: Array, mult: Array,
                        offsets: Array, w0: Array):
            # Down-sampled pass: gather the subsample on device, rescale
            # weights, pad back to a data-axis multiple (static shapes: the
            # samplers return deterministic sizes).
            sub = LabeledBatch(
                features=staged.features[idx],
                labels=staged.labels[idx],
                weights=staged.weights[idx] * mult,
                offsets=offsets[idx],
            ).pad_to(pad_to_multiple(idx.shape[0], mesh.shape[DATA_AXIS]))
            coef, res = dist_problem.run(
                loss, sub, mesh, cfg, initial=Coefficients(w0), norm=norm,
                intercept_index=ii, already_sharded=True)
            return coef.means, res.value_history, res.grad_norm_history

        self._fit = jax.jit(fit)
        self._fit_sampled = jax.jit(fit_sampled)

    @property
    def dim(self) -> int:
        return self.dataset.shard_dim(self.shard_id)

    def with_optimization_config(
        self, config: GLMOptimizationConfiguration
    ) -> "FixedEffectCoordinate":
        """Cheap copy with a new optimization config (same data/device
        arrays) — the estimator's reg-weight grid loop swaps configs without
        re-staging data (reference: datasets built once per coordinate,
        reused across the GameOptimizationConfiguration grid)."""
        import copy

        c = copy.copy(self)
        c.config = config
        # Fresh, identically-seeded RNG so every grid point trains on the
        # SAME down-sampled subsets (grid comparison must not depend on how
        # far a shared RNG advanced in earlier grid points).
        c._rng = np.random.default_rng(self._down_sampling_seed)
        c._build_fits()
        return c

    def train_model(
        self,
        offsets: Array,
        initial: Optional[FixedEffectModel] = None,
    ) -> FixedEffectModel:
        if initial is not None:
            w0 = self.norm.model_to_transformed_space(
                initial.coefficients.means)
        else:
            w0 = jnp.zeros((self.dim,), jnp.float32)
        offsets = jnp.asarray(offsets)
        rate = self.config.down_sampling_rate
        if rate < 1.0:
            # Reference: DownSampler subsamples the fixed-effect coordinate's
            # data each training pass, rescaling weights by 1/rate. Index
            # draw is host-side (cheap, label metadata only); the data
            # gather happens on device.
            idx, mult = draw_down_sample(self, rate)
            w_t, vals, gns = self._fit_sampled(self._staged,
                                               jnp.asarray(idx),
                                               jnp.asarray(mult),
                                               offsets, w0)
        else:
            w_t, vals, gns = self._fit(self._staged, offsets, w0)
        led = obs.ledger()
        if led is not None:
            # Post-fit spill of the compiled optimizer's NaN-padded
            # histories — the run ledger's view of a solve that lives
            # inside one XLA program (one host read, once per update).
            spill_history(
                led, np.asarray(vals), np.asarray(gns),
                opt=self.config.optimizer.optimizer_type.value.lower())
        raw = Coefficients(self.norm.model_to_original_space(w_t))
        return FixedEffectModel(shard_id=self.shard_id, coefficients=raw)

    def compute_model_variances(
        self, model: FixedEffectModel, offsets: Array
    ) -> FixedEffectModel:
        """Coefficient variances at the optimum (post-descent pass).

        Variances are computed in the transformed space and mapped back by
        the factor² scaling implied by w_orig = w∘f (the intercept's extra
        shift term is a location change and does not rescale its variance).
        """
        kind = VarianceComputationType(self.config.variance_computation)
        if kind == VarianceComputationType.NONE:
            return model
        batch = dataclasses.replace(self._staged,
                                    offsets=self._padded_offsets(offsets))
        w_t = self.norm.model_to_transformed_space(model.coefficients.means)
        mask = jnp.asarray(intercept_mask(self.dim, self.intercept_index))
        l2 = self.config.regularization.l2_weight()
        if kind == VarianceComputationType.SIMPLE:
            diag = dobj.make_hessian_diagonal(
                self.loss, self.mesh, batch, self.norm)(w_t)
            var_t = variances_from_diagonal(diag, l2, mask)
        else:
            H = dobj.make_hessian_matrix(
                self.loss, self.mesh, batch, self.norm)(w_t)
            var_t = variances_from_matrix(H, l2, mask)
        var_t = self.norm.variances_to_original_space(var_t)
        return dataclasses.replace(
            model, coefficients=Coefficients(model.coefficients.means, var_t))

    def score(self, model: FixedEffectModel) -> Array:
        """Raw-space score (identical to the training margins by algebra)."""
        from photon_ml_tpu.ops.aggregators import scores as agg_scores

        n = self.dataset.num_rows
        return agg_scores(self._staged.features,
                          model.coefficients.means)[:n]

    def initial_model(self) -> FixedEffectModel:
        return FixedEffectModel(
            shard_id=self.shard_id,
            coefficients=Coefficients.zeros(self.dim))

    def advance_down_sampling(self, steps: int) -> None:
        """Fast-forward the down-sampling RNG past ``steps`` completed
        train_model calls (checkpoint resume must subsample the remaining
        steps exactly as the uninterrupted run would have)."""
        _advance_down_sampling(self, steps)


