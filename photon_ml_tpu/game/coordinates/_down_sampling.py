"""Shared down-sampling draw + per-CD-step advance (reference: DownSampler
implementations consumed by both fixed-effect coordinate types).

The sampler is picked by TASK (reference behavior), not by inspecting label
values. ``draw_down_sample`` is the ONE place that dispatch lives:
``train_model`` uses its (idx, mult) to gather the sampled rows on device,
and checkpoint resume replays the same RNG stream through
``_advance_down_sampling`` — both must consume the generator identically or
resume determinism silently breaks.
"""

from __future__ import annotations

import numpy as np

from photon_ml_tpu.game.sampling import (binary_classification_down_sample,
                                         default_down_sample)


def draw_down_sample(coord, rate: float) -> tuple[np.ndarray, np.ndarray]:
    """One sampling draw for a fixed-effect coordinate: (row indices, weight
    multipliers), advancing ``coord._rng`` exactly one step."""
    if coord.loss.name in ("logistic", "smoothed_hinge"):
        return binary_classification_down_sample(
            coord._rng, coord.dataset.response, rate)
    return default_down_sample(coord._rng, coord.dataset.num_rows, rate)


def _advance_down_sampling(coord, steps: int) -> None:
    rate = coord.config.down_sampling_rate
    if rate >= 1.0:
        return
    for _ in range(steps):
        draw_down_sample(coord, rate)
