"""Row-streamed sparse fixed-effect coordinate: the Criteo row axis.

Reference parity: photon-api ``FixedEffectCoordinate`` +
``DistributedGLMLossFunction`` — the fixed-effect fit is a driver-loop
optimization whose every value/gradient is one pass over RDD partitions,
so n never has to fit on one executor. Here the partitions are host-
resident hybrid chunks (``ops/streaming_sparse.ChunkedHybrid``) streamed
through the chip per evaluation with double-buffered prefetch, and the
driver loop is the host-driven L-BFGS (``optim/streaming.py``). Use this
coordinate when the staged layout exceeds HBM (n in the hundreds of
millions on one 16 GB chip); the device-resident
``SparseFixedEffectCoordinate`` is strictly faster whenever it fits.

Streaming contract: the chunks must be staged with ZERO offsets — in
coordinate descent the full residual (base offsets + other coordinates'
scores) arrives as the ``offsets`` argument of ``train_model``, and
``score`` must return pure wᵀx margins.

Not supported at streaming scale (all raise with the reason): L1/OWL-QN
(the orthant bookkeeping needs the compiled optimizer), normalization
(Criteo-style sparse binary features train unnormalized; in-kernel factor
application to the chunk stream is a straightforward extension),
down-sampling, and SIMPLE/FULL variances.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.game.models import FixedEffectModel
from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.ops import streaming_sparse as ss
from photon_ml_tpu.ops.losses import PointwiseLoss
from photon_ml_tpu.optim.problem import (GLMOptimizationConfiguration,
                                         VarianceComputationType)
from photon_ml_tpu.optim.regularization import (intercept_mask, with_l2,
                                                with_l2_value)
from photon_ml_tpu.optim.streaming import minimize_streaming

Array = jax.Array


class StreamingSparseFixedEffectCoordinate:
    """Drop-in coordinate for ``game/descent.run`` over a chunk stream."""

    def __init__(
        self,
        dataset,
        chunked: ss.ChunkedHybrid,
        shard_id: str,
        loss: PointwiseLoss,
        config: GLMOptimizationConfiguration,
        intercept_index: Optional[int] = None,
        prefetch_depth: int = 2,
        pin_device_chunks: int = 0,
        log=lambda m: None,
    ):
        if chunked.num_rows != dataset.num_rows:
            raise ValueError(
                f"chunk stream has {chunked.num_rows} rows, dataset "
                f"{dataset.num_rows}")
        for i, ch in enumerate(chunked.chunks):
            # Enforce the documented staging contract at construction
            # (ADVICE r5): a chunk staged with nonzero offsets would
            # silently DOUBLE-COUNT residuals in coordinate descent —
            # score() must return pure wᵀx margins while train_model
            # receives the full residual via its offsets argument. The
            # check is one cheap host pass over (chunk_rows,) arrays.
            off = np.asarray(ch.offsets)
            if off.size and np.any(off != 0.0):
                raise ValueError(
                    f"chunk {i} was staged with nonzero offsets. "
                    "Streaming contract: the chunks must be staged with "
                    "ZERO offsets — in coordinate descent the full "
                    "residual (base offsets + other coordinates' scores) "
                    "arrives as the ``offsets`` argument of "
                    "``train_model``, and ``score`` must return pure "
                    "wᵀx margins; staged offsets would be double-counted."
                )
        if config.regularization.l1_weight() != 0.0:
            raise ValueError(
                "L1/OWL-QN is not supported on the streaming path (the "
                "orthant bookkeeping lives in the compiled optimizer); "
                "use L2, or the device-resident SparseFixedEffectCoordinate")
        if config.down_sampling_rate < 1.0:
            raise ValueError("down-sampling is not supported on the "
                             "streaming path")
        if VarianceComputationType(config.variance_computation) != \
                VarianceComputationType.NONE:
            raise ValueError(
                "variance computation is not supported on the streaming "
                "path (a diagonal-Hessian stream pass is a straightforward "
                "extension if needed)")
        self.dataset = dataset
        self.chunked = chunked
        self.shard_id = shard_id
        self.loss = loss
        self.config = config
        self.intercept_index = intercept_index
        self._log = log
        # Spare-HBM chunk pinning: the caller sizes this against whatever
        # else the fit keeps resident (e.g. RE bucket blocks).
        self._pinned = ss.pin_chunks(chunked, pin_device_chunks)
        self._vg = ss.make_value_and_gradient(
            loss, chunked, prefetch_depth=prefetch_depth,
            pinned=self._pinned)
        # Value-only streamed pass for Armijo probes: rejected steps skip
        # the gradient half of the chunk kernel (optim/streaming.py).
        self._v = ss.make_value_only(
            loss, chunked, prefetch_depth=prefetch_depth,
            pinned=self._pinned)
        self._prefetch_depth = prefetch_depth
        self._padded_n = chunked.num_chunks * chunked.chunk_rows

    @property
    def dim(self) -> int:
        return self.chunked.dim

    def _pad_offsets(self, offsets: Array) -> Array:
        offsets = jnp.asarray(offsets, jnp.float32)
        pad = self._padded_n - offsets.shape[0]
        if pad:
            offsets = jnp.concatenate(
                [offsets, jnp.zeros((pad,), jnp.float32)])
        return offsets

    def train_model(
        self,
        offsets: Array,
        initial: Optional[FixedEffectModel] = None,
    ) -> FixedEffectModel:
        w0 = (initial.coefficients.means if initial is not None
              else jnp.zeros((self.dim,), jnp.float32))
        off = self._pad_offsets(offsets)
        mask = jnp.asarray(intercept_mask(self.dim, self.intercept_index))
        l2 = self.config.regularization.l2_weight()
        vg = with_l2(lambda w: self._vg(w, off), l2, mask)
        v = with_l2_value(lambda w: self._v(w, off), l2, mask)
        result = minimize_streaming(vg, w0, self.config.optimizer,
                                    log=self._log, value_only=v)
        return FixedEffectModel(shard_id=self.shard_id,
                                coefficients=Coefficients(result.w))

    def score(self, model: FixedEffectModel) -> Array:
        """(n,) wᵀx margins, streamed (chunks staged with zero offsets)."""
        return ss.margins_chunked(self.chunked, model.coefficients.means,
                                  prefetch_depth=self._prefetch_depth,
                                  pinned=self._pinned)

    def initial_model(self) -> FixedEffectModel:
        return FixedEffectModel(shard_id=self.shard_id,
                                coefficients=Coefficients.zeros(self.dim))
