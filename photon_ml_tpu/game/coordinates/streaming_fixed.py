"""Row-streamed sparse fixed-effect coordinate: the Criteo row axis.

Reference parity: photon-api ``FixedEffectCoordinate`` +
``DistributedGLMLossFunction`` — the fixed-effect fit is a driver-loop
optimization whose every value/gradient is one pass over RDD partitions,
so n never has to fit on one executor. Here the partitions are host-
resident hybrid chunks (``ops/streaming_sparse.ChunkedHybrid``) streamed
through the chip per evaluation with double-buffered prefetch, and the
driver loop is the host-driven L-BFGS (``optim/streaming.py``). Use this
coordinate when the staged layout exceeds HBM (n in the hundreds of
millions on one 16 GB chip); the device-resident
``SparseFixedEffectCoordinate`` is strictly faster whenever it fits.

Multi-chip (docs/STREAMING.md): pass a ``mesh`` and the chunk ranges
partition over its ``data`` axis — each device streams its own range and
per-device partial (value, gradient) merge via ``psum``
(``ops/streaming_sparse.ShardedChunkStream``), the reference's
``treeAggregate`` over partitions. A 1-device mesh is bit-identical to
the mesh-less path.

Crash-resume: when coordinate descent binds a step checkpoint
(``bind_step_checkpoint``, wired by game/descent.py from the
CheckpointManager), every accepted L-BFGS iteration persists the full
driver-loop state through game/checkpoint.py's StreamingStateStore
(CRC + two generations), and a killed fit resumes mid-optimization with
BIT-identical final coefficients.

Device-ELASTIC resume (docs/STREAMING.md "Elastic resume"): the
snapshot is pure driver-loop state — ``(d,)`` vectors and the ``(M, d)``
curvature ring, nothing sharded — and ``shard_chunk_ranges`` re-derives
each device's chunk range from ``(num_chunks, D′)`` at construction, so
a checkpoint written at D devices resumes at D′ ≠ D: D′ = D stays
byte-equal, D → D′ agrees within the established sharded-parity
tolerance (accumulation order moves with the psum lanes). This is what
lets the n=100M flagship run on preemptible/resizable hardware.

Streaming contract: the chunks must be staged with ZERO offsets — in
coordinate descent the full residual (base offsets + other coordinates'
scores) arrives as the ``offsets`` argument of ``train_model``, and
``score`` must return pure wᵀx margins.

Solvers (docs/STREAMING.md "Stochastic solvers"): the default driver
loop is the host-driven L-BFGS — now including L1/OWL-QN via
pseudo-gradient direction + orthant-projected probes in the same
streamed Armijo loop. ``solver=sdca`` / ``solver=sgd``
(optim/stochastic.py) run behind the SAME train_model contract over the
same chunk feed, emitting a per-epoch duality-gap certificate; a
per-coordinate ``--opt-config optimizer=SDCA|SGD`` override wins over
the streaming-level default, and SDCA on a loss without a cheap
conjugate falls back to SGD (logged). Under the stochastic solvers the
``pin_chunks`` budget becomes the gap-driven device-residency budget
(ops/chunk_sampler.py) instead of static leading-chunk pins.

Not supported at streaming scale (all raise with the reason):
normalization (Criteo-style sparse binary features train unnormalized;
in-kernel factor application to the chunk stream is a straightforward
extension), down-sampling, SIMPLE/FULL variances; for the stochastic
solvers additionally L1 (they need plain L2), meshes (the sequential
dual update has no psum decomposition), and — SDCA only — an intercept
excluded from regularization (w ≡ w(α) needs the all-ones L2 mask;
use ``solver=sgd``).
"""

from __future__ import annotations

import hashlib
import logging
import os
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu import obs
from photon_ml_tpu.fabric import runtime as fabric_runtime
from photon_ml_tpu.fabric.stream import FabricChunkStream
from photon_ml_tpu.game.models import FixedEffectModel
from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.ops import streaming_sparse as ss
from photon_ml_tpu.ops.losses import PointwiseLoss
from photon_ml_tpu.optim.common import OptimizerType
from photon_ml_tpu.optim.gap import CONJUGATE_LOSSES
from photon_ml_tpu.optim.problem import (GLMOptimizationConfiguration,
                                         VarianceComputationType)
from photon_ml_tpu.optim.regularization import (intercept_mask,
                                                l1_weights_vector, with_l2,
                                                with_l2_value)
from photon_ml_tpu.optim.stochastic import minimize_stochastic
from photon_ml_tpu.optim.streaming import minimize_streaming
from photon_ml_tpu.utils import events as ev_mod

Array = jax.Array

logger = logging.getLogger("photon_ml_tpu.game")

_SOLVERS = ("lbfgs", "sdca", "sgd")


def _resolve_solver(solver: str, config: GLMOptimizationConfiguration,
                    loss: PointwiseLoss, log=lambda m: None) -> str:
    """Effective solver for a fit: a per-coordinate ``--opt-config
    optimizer=SDCA|SGD`` override wins over the streaming-level
    ``solver=`` default, and SDCA on a loss without a cheap conjugate
    falls back to SGD (logged — the gap column degrades to the
    ‖∇P‖²/2λ surrogate)."""
    t = OptimizerType(config.optimizer.optimizer_type)
    if t in (OptimizerType.SDCA, OptimizerType.SGD):
        solver = t.value.lower()
    if solver == "sdca" and loss.name not in CONJUGATE_LOSSES:
        log(f"solver=sdca needs a conjugate-form loss "
            f"({sorted(CONJUGATE_LOSSES)}); falling back to sgd for "
            f"loss {loss.name!r}")
        return "sgd"
    return solver


def _validate_streaming_config(config: GLMOptimizationConfiguration,
                               solver: str = "lbfgs") -> None:
    """The streamed path's feature envelope, enforced at construction AND
    at every config swap (the estimator's grid/tuning path)."""
    if solver not in _SOLVERS:
        raise ValueError(f"streaming solver must be one of {_SOLVERS}, "
                         f"got {solver!r}")
    if config.regularization.l1_weight() != 0.0 and solver != "lbfgs":
        raise ValueError(
            "L1/OWL-QN rides the streamed L-BFGS driver only; the "
            "stochastic solvers need plain L2 (the dual and the 1/λt "
            "step size both assume it) — use solver=lbfgs")
    if solver in ("sdca", "sgd") and \
            config.regularization.l2_weight() <= 0.0:
        raise ValueError(
            f"solver={solver} requires l2_weight > 0 (SDCA's dual and "
            f"SGD's 1/λt step size both need strong convexity)")
    if config.down_sampling_rate < 1.0:
        raise ValueError("down-sampling is not supported on the "
                         "streaming path")
    if VarianceComputationType(config.variance_computation) != \
            VarianceComputationType.NONE:
        raise ValueError(
            "variance computation is not supported on the streaming "
            "path (a diagonal-Hessian stream pass is a straightforward "
            "extension if needed)")


class StreamingSparseFixedEffectCoordinate:
    """Drop-in coordinate for ``game/descent.run`` over a chunk stream."""

    def __init__(
        self,
        dataset,
        chunked: ss.ChunkedHybrid,
        shard_id: str,
        loss: PointwiseLoss,
        config: GLMOptimizationConfiguration,
        intercept_index: Optional[int] = None,
        prefetch_depth: int = 2,
        pin_device_chunks: int = 0,
        solver: str = "lbfgs",
        mesh=None,
        log=lambda m: None,
    ):
        if chunked.num_rows != dataset.num_rows:
            raise ValueError(
                f"chunk stream has {chunked.num_rows} rows, dataset "
                f"{dataset.num_rows}")
        for i, ch in enumerate(chunked.chunks):
            # Enforce the documented staging contract at construction
            # (ADVICE r5): a chunk staged with nonzero offsets would
            # silently DOUBLE-COUNT residuals in coordinate descent —
            # score() must return pure wᵀx margins while train_model
            # receives the full residual via its offsets argument. The
            # check is one cheap host pass over (chunk_rows,) arrays.
            off = np.asarray(ch.offsets)
            if off.size and np.any(off != 0.0):
                raise ValueError(
                    f"chunk {i} was staged with nonzero offsets. "
                    "Streaming contract: the chunks must be staged with "
                    "ZERO offsets — in coordinate descent the full "
                    "residual (base offsets + other coordinates' scores) "
                    "arrives as the ``offsets`` argument of "
                    "``train_model``, and ``score`` must return pure "
                    "wᵀx margins; staged offsets would be double-counted."
                )
        self.solver = (solver or "lbfgs").lower()
        effective = _resolve_solver(self.solver, config, loss, log)
        _validate_streaming_config(config, effective)
        if effective in ("sdca", "sgd") and mesh is not None:
            # The sequential dual/primal update has no psum
            # decomposition: the stochastic solvers are single-chip by
            # design. Drivers that always build a mesh (the CLI) get the
            # mesh-less path; giving up real parallelism is logged.
            n_dev = int(np.prod(list(mesh.shape.values())))
            if n_dev > 1:
                log(f"solver={effective} is single-chip (the sequential "
                    f"dual update has no psum decomposition); ignoring "
                    f"the {n_dev}-device mesh for this coordinate")
            mesh = None
        if effective == "sdca" and intercept_index is not None:
            raise ValueError(
                "solver=sdca regularizes every coordinate (w ≡ w(α) "
                "needs the all-ones L2 mask, so an intercept excluded "
                "from regularization has no dual representation) — use "
                "solver=sgd, or include the intercept in the L2 term")
        fab = fabric_runtime.active()
        if fab is not None and fab.world > 1 and \
                effective in ("sdca", "sgd"):
            # Unlike the mesh demotion above, a fabric demotion would
            # run W redundant copies of the SAME sequential fit (and the
            # dual update has no cross-host decomposition either) — a
            # silently wasted fleet is worse than a loud config error.
            raise ValueError(
                f"solver={effective} is single-host (the sequential "
                f"dual update has no cross-host decomposition); run "
                f"this coordinate without --fabric, or use solver=lbfgs")
        self.dataset = dataset
        self.chunked = chunked
        self.shard_id = shard_id
        self.loss = loss
        self.config = config
        self.intercept_index = intercept_index
        self.mesh = mesh
        self._log = log
        if fab is not None:
            # Multi-host streaming (docs/STREAMING.md "Multi-host
            # streaming"): chunk ranges partition over HOSTS first,
            # each host's slice streams through its local mesh (ICI
            # psum), host partials meet in ONE DCN allreduce per pass.
            self._stream = FabricChunkStream(
                chunked, fab, mesh=mesh, prefetch_depth=prefetch_depth,
                pin_device_chunks=pin_device_chunks)
            self._vg = self._stream.value_and_gradient(loss)
            self._v = self._stream.value_only(loss)
            log(f"fabric streaming: rank {fab.rank}/{fab.world} owns "
                f"chunks [{self._stream._lo}, {self._stream._hi}) of "
                f"{chunked.num_chunks}")
        elif mesh is not None:
            # Sharded streaming: chunk ranges partition over the mesh's
            # data axis, per-device partials psum-merge (treeAggregate).
            # pin_device_chunks here is PER DEVICE (each chip's share of
            # the spare-HBM budget).
            self._stream = ss.ShardedChunkStream(
                chunked, mesh, prefetch_depth=prefetch_depth,
                pin_device_chunks=pin_device_chunks)
            self._vg = self._stream.value_and_gradient(loss)
            self._v = self._stream.value_only(loss)
        else:
            self._stream = None
            # Spare-HBM chunk pinning: the caller sizes this against
            # whatever else the fit keeps resident (e.g. RE buckets).
            # Under the stochastic solvers the same budget funds the
            # gap-driven sampler's residency set instead (the solver
            # re-pins by gap contribution each epoch), so nothing is
            # statically pinned here.
            self._pinned = ss.pin_chunks(
                chunked,
                0 if effective in ("sdca", "sgd") else pin_device_chunks)
            self._vg = ss.make_value_and_gradient(
                loss, chunked, prefetch_depth=prefetch_depth,
                pinned=self._pinned)
            # Value-only streamed pass for Armijo probes: rejected steps
            # skip the gradient half of the chunk kernel
            # (optim/streaming.py).
            self._v = ss.make_value_only(
                loss, chunked, prefetch_depth=prefetch_depth,
                pinned=self._pinned)
        self._prefetch_depth = prefetch_depth
        self._pin_budget = pin_device_chunks
        self._padded_n = chunked.num_chunks * chunked.chunk_rows
        # Mid-optimization checkpoint binding (game/descent.py wires the
        # CheckpointManager's per-step stream dir through here).
        self._ckpt_store = None
        self._ckpt_step = None

    @classmethod
    def stage(
        cls,
        dataset,
        shard_id: str,
        loss: PointwiseLoss,
        config: GLMOptimizationConfiguration,
        mesh,
        streaming,
        default_dtype: Optional[str] = None,
        log=lambda m: None,
    ) -> "StreamingSparseFixedEffectCoordinate":
        """Build the coordinate from a GameDataset's SparseShard: slice
        the shard into zero-offset row chunks and canonicalize them into
        the hot-dense/cold-ELL layout (``workers``-parallel, bit-identical
        to the serial pass) — the estimator's route onto the streamed
        path (``GameEstimator(streaming=...)`` / ``game_train
        --streaming``). ``streaming`` is an api/configs.StreamingConfig;
        its ``feature_dtype=None`` inherits ``default_dtype`` (the
        coordinate data config's dtype knob).
        """
        dtype = streaming.feature_dtype or default_dtype or "float32"
        shard = dataset.feature_shards[shard_id]
        n = int(shard.indices.shape[0])
        workers = streaming.workers or os.cpu_count() or 1
        num_chunks = (n + streaming.chunk_rows - 1) // streaming.chunk_rows
        emitter = ev_mod.default_emitter
        emitter.emit(ev_mod.StreamStageStart(
            shard_id=shard_id, num_rows=n,
            chunk_rows=streaming.chunk_rows, num_chunks=num_chunks,
            workers=workers))
        t0 = time.perf_counter()
        chunked = None
        try:
            chunked = ss.build_chunked(
                ss.iter_shard_chunks(shard, dataset.response,
                                     dataset.weights,
                                     streaming.chunk_rows),
                int(shard.num_features), streaming.chunk_rows,
                num_hot=streaming.num_hot,
                feature_dtype=ss.feature_dtype_name(dtype),
                workers=workers, log=log)
        finally:
            # Balanced lifecycle (PML007): staging failures still close
            # the scope for listeners tracking it.
            emitter.emit(ev_mod.StreamStageFinish(
                shard_id=shard_id,
                num_chunks=chunked.num_chunks if chunked else 0,
                seconds=time.perf_counter() - t0))
        return cls(
            dataset, chunked, shard_id, loss, config,
            intercept_index=dataset.intercept_index.get(shard_id),
            prefetch_depth=streaming.prefetch_depth,
            pin_device_chunks=streaming.pin_chunks,
            solver=streaming.solver, mesh=mesh, log=log)

    def with_optimization_config(
        self, config: GLMOptimizationConfiguration
    ) -> "StreamingSparseFixedEffectCoordinate":
        """Same staged chunk stream, new optimization config (the
        estimator's grid/tuning swap — staging is the expensive part)."""
        import copy

        effective = _resolve_solver(self.solver, config, self.loss,
                                    self._log)
        _validate_streaming_config(config, effective)
        if effective == "sdca" and self.intercept_index is not None:
            raise ValueError(
                "solver=sdca regularizes every coordinate — use "
                "solver=sgd, or include the intercept in the L2 term")
        if effective in ("sdca", "sgd") and self.mesh is not None:
            # Swapping a mesh-sharded L-BFGS coordinate onto a
            # single-chip solver: rebuild on the mesh-less stream (the
            # constructor logs the demotion).
            return type(self)(
                self.dataset, self.chunked, self.shard_id, self.loss,
                config, intercept_index=self.intercept_index,
                prefetch_depth=self._prefetch_depth,
                pin_device_chunks=self._pin_budget,
                solver=self.solver, mesh=self.mesh, log=self._log)
        c = copy.copy(self)
        c.config = config
        c._ckpt_store = None
        c._ckpt_step = None
        return c

    @property
    def dim(self) -> int:
        return self.chunked.dim

    # -- mid-optimization checkpointing -----------------------------------

    def bind_step_checkpoint(self, directory: str, step: int) -> None:
        """Arm mid-L-BFGS checkpointing for the NEXT train_model call
        (game/descent.py binds one directory per descent step)."""
        from photon_ml_tpu.game.checkpoint import StreamingStateStore

        self._ckpt_store = StreamingStateStore(directory)
        self._ckpt_step = step

    def clear_step_checkpoint(self) -> None:
        """Drop the committed step's mid-step state (descent calls this
        after the step-level checkpoint commits — stale stream state
        must not leak into a later step's resume)."""
        if self._ckpt_store is not None:
            self._ckpt_store.clear()
        self._ckpt_store = None
        self._ckpt_step = None

    def _stream_fingerprint(self, offsets: Array, w0: Array,
                            solver: str) -> dict:
        """What a mid-step snapshot must agree on to be resumable: the
        step identity, the optimizer config, the EFFECTIVE solver (an
        L-BFGS curvature ring and an SDCA dual vector are not each
        other's state — a solver swap must discard, not reinterpret),
        and digests of the residual offsets and warm start (the
        objective the snapshot was taken under — resuming against a
        different residual would silently continue the wrong
        optimization)."""
        from photon_ml_tpu.game.descent import _jsonable

        h = hashlib.sha1()
        h.update(np.ascontiguousarray(np.asarray(offsets)).tobytes())
        h.update(np.ascontiguousarray(np.asarray(w0)).tobytes())
        return {
            "step": self._ckpt_step,
            "shard": self.shard_id,
            "config": _jsonable(self.config),
            "dim": self.dim,
            "solver": solver,
            "objective_digest": h.hexdigest(),
        }

    def _fabric_digest_hook(self):
        """Per-accepted-iteration cross-rank digest exchange (``None``
        without an armed fabric — the single-host fast path).

        Every rank digests its (w, f, |g|) after the update; the
        fabric compares them and rank 0 — the ledger owner — records a
        ``fabric_digest`` row carrying the full rank→digest map plus
        the cumulative DCN provenance counters. A mismatch raises
        ``RankDivergence`` on EVERY rank: divergence is detected at the
        iteration it happens, not discovered at scoring time."""
        fab = fabric_runtime.active()
        if fab is None:
            return None
        from photon_ml_tpu.obs.ledger import fabric_totals

        led = obs.ledger()
        tag = f"digest/{self.shard_id}"

        def on_accept(it, w, fv, gn):
            h = hashlib.sha1()
            h.update(np.ascontiguousarray(
                np.asarray(w, np.float32)).tobytes())
            h.update(np.float32(fv).tobytes())
            h.update(np.float32(gn).tobytes())
            out = fab.digest_check(tag, h.hexdigest())
            if fab.rank == 0 and led is not None:
                led.record("fabric_digest", iteration=it,
                           digest=h.hexdigest(), world=fab.world,
                           match=bool(out["match"]),
                           **fabric_totals())

        return on_accept

    def _pad_offsets(self, offsets: Array) -> Array:
        offsets = jnp.asarray(offsets, jnp.float32)
        pad = self._padded_n - offsets.shape[0]
        if pad:
            offsets = jnp.concatenate(
                [offsets, jnp.zeros((pad,), jnp.float32)])
        return offsets

    def train_model(
        self,
        offsets: Array,
        initial: Optional[FixedEffectModel] = None,
    ) -> FixedEffectModel:
        solver = _resolve_solver(self.solver, self.config, self.loss,
                                 self._log)
        w0 = (initial.coefficients.means if initial is not None
              else jnp.zeros((self.dim,), jnp.float32))
        off = self._pad_offsets(offsets)
        mask = jnp.asarray(intercept_mask(self.dim, self.intercept_index))
        l2 = self.config.regularization.l2_weight()
        l1 = self.config.regularization.l1_weight()
        vg = with_l2(lambda w: self._vg(w, off), l2, mask)
        v = with_l2_value(lambda w: self._v(w, off), l2, mask)
        checkpoint_save = None
        resume_state = None
        if self._ckpt_store is not None:
            fp = self._stream_fingerprint(off, w0, solver)
            # The device environment rides BESIDE the fingerprint, never
            # inside it: a snapshot written at D devices must resume at
            # D′ ≠ D (the preemptible/resize contract — chunk ranges
            # re-shard at construction), so device count can never be a
            # reason to discard driver-loop state.
            env = {"num_devices": (self._stream.num_devices
                                   if self._stream is not None else 1)}
            fab_env = fabric_runtime.active()
            if fab_env is not None:
                # The host fan-out rides beside the fingerprint for the
                # same reason device count does: a snapshot written at
                # W hosts must resume at W′ ≠ W (a SIGKILL'd host
                # becomes a logged W→W′ ELASTIC resume, not a dead
                # run) — chunk ranges re-derive from (num_chunks, W′).
                env["fabric_world"] = fab_env.world
            store = self._ckpt_store
            resume_state = store.load(expected_fingerprint=fp,
                                      environment=env)
            if resume_state is not None:
                self._log(f"resuming streamed fit from iteration "
                          f"{int(resume_state['it'])} checkpoint")

            def checkpoint_save(state, _store=store, _fp=fp, _env=env):
                _store.save(state, fingerprint=_fp, environment=_env)

        if solver in ("sdca", "sgd"):
            result = minimize_stochastic(
                vg, w0, self.config.optimizer,
                chunked=self.chunked, loss=self.loss, l2_weight=l2,
                solver=solver, offsets=off,
                reg_mask=(None if solver == "sdca" else mask),
                log=self._log, value_only=v,
                checkpoint_save=checkpoint_save,
                resume_state=resume_state,
                prefetch_depth=self._prefetch_depth,
                # The pin budget funds the gap-driven sampler; when
                # static pins exist (a coordinate built for L-BFGS then
                # config-swapped onto a stochastic solver) the budget
                # stays with them — double-pinning would double the
                # HBM bill.
                pin_budget=(0 if self._pinned else self._pin_budget))
        else:
            l1w = (l1_weights_vector(l1, self.dim, self.intercept_index)
                   if l1 else None)
            result = minimize_streaming(vg, w0, self.config.optimizer,
                                        log=self._log, value_only=v,
                                        checkpoint_save=checkpoint_save,
                                        resume_state=resume_state,
                                        l1_weights=l1w,
                                        on_accept=self._fabric_digest_hook())
        return FixedEffectModel(shard_id=self.shard_id,
                                coefficients=Coefficients(result.w))

    def score(self, model: FixedEffectModel) -> Array:
        """(n,) wᵀx margins, streamed (chunks staged with zero offsets)."""
        if self._stream is not None:
            return self._stream.margins(model.coefficients.means)
        return ss.margins_chunked(self.chunked, model.coefficients.means,
                                  prefetch_depth=self._prefetch_depth,
                                  pinned=self._pinned)

    def initial_model(self) -> FixedEffectModel:
        return FixedEffectModel(shard_id=self.shard_id,
                                coefficients=Coefficients.zeros(self.dim))
