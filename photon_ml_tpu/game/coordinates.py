"""GAME coordinates: fixed-effect and random-effect training units.

Reference parity: photon-api ``algorithm/Coordinate.scala``,
``algorithm/FixedEffectCoordinate.scala`` (one distributed GLM fit over the
whole dataset), ``algorithm/RandomEffectCoordinate.scala`` (per-entity local
GLM fits inside ``mapValues`` over ``RDD[(REId, LocalDataset)]``).

TPU-first design:
- FixedEffectCoordinate = the data-parallel psum objective + compiled
  optimizer (photon_ml_tpu/parallel/problem.py) over the mesh (P1).
- RandomEffectCoordinate = per-bucket ``vmap``-ped compiled optimizer over
  padded entity blocks (photon_ml_tpu/game/buckets.py), entity axis sharded
  over the mesh, per-lane convergence masks freezing finished entities (P2).
  One compiled solve per bucket shape, cached across coordinate-descent
  iterations (shapes are static once bucketing is fixed).

Both expose ``train_model(offsets, initial)`` and ``score(model)`` plus
variance computation, mirroring the reference Coordinate contract
(trainModel / score / updateOffset — offsets here are passed explicitly
rather than mutating a dataset).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.data.batch import LabeledBatch
from photon_ml_tpu.data.game_data import GameDataset
from photon_ml_tpu.game import buckets as bkt
from photon_ml_tpu.game.models import FixedEffectModel, RandomEffectModel
from photon_ml_tpu.game.sampling import (binary_classification_down_sample,
                                         default_down_sample)
from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.normalization import NormalizationContext
from photon_ml_tpu.ops.losses import PointwiseLoss
from photon_ml_tpu.optim import optimize
from photon_ml_tpu.optim.problem import (GLMOptimizationConfiguration,
                                         VarianceComputationType,
                                         compute_variances, make_objective,
                                         resolve_optimizer_config,
                                         variances_from_diagonal,
                                         variances_from_matrix)
from photon_ml_tpu.optim.regularization import intercept_mask
from photon_ml_tpu.parallel import objective as dobj
from photon_ml_tpu.parallel import problem as dist_problem
from photon_ml_tpu.parallel.mesh import data_sharded, shard_batch

Array = jax.Array


class FixedEffectCoordinate:
    """One shared GLM trained data-parallel over the mesh.

    Reference parity: FixedEffectCoordinate + DistributedOptimizationProblem.

    Model-space contract: the optimizer runs in the normalization-transformed
    space, but the FixedEffectModel handed out ALWAYS holds ORIGINAL-space
    coefficients (converted at the train boundary, reconverted for warm
    starts) so every scorer — GameModel.score, the transformer, the CLIs,
    save/load — is a plain X @ w. The two are algebraically identical:
    X @ (w∘f) − (w∘f)·s == X @ model_to_original_space(w).
    """

    def __init__(
        self,
        dataset: GameDataset,
        shard_id: str,
        loss: PointwiseLoss,
        config: GLMOptimizationConfiguration,
        mesh,
        norm: NormalizationContext = NormalizationContext(),
        down_sampling_seed: int = 0,
    ):
        self.dataset = dataset
        self.shard_id = shard_id
        self.loss = loss
        self.config = config
        self.mesh = mesh
        self.norm = norm
        self.intercept_index = dataset.intercept_index.get(shard_id)
        self._down_sampling_seed = down_sampling_seed
        self._rng = np.random.default_rng(down_sampling_seed)
        self._X = jnp.asarray(dataset.feature_shards[shard_id])

    @property
    def dim(self) -> int:
        return self.dataset.shard_dim(self.shard_id)

    def with_optimization_config(
        self, config: GLMOptimizationConfiguration
    ) -> "FixedEffectCoordinate":
        """Cheap copy with a new optimization config (same data/device
        arrays) — the estimator's reg-weight grid loop swaps configs without
        re-staging data (reference: datasets built once per coordinate,
        reused across the GameOptimizationConfiguration grid)."""
        import copy

        c = copy.copy(self)
        c.config = config
        # Fresh, identically-seeded RNG so every grid point trains on the
        # SAME down-sampled subsets (grid comparison must not depend on how
        # far a shared RNG advanced in earlier grid points).
        c._rng = np.random.default_rng(self._down_sampling_seed)
        return c

    def train_model(
        self,
        offsets: Array,
        initial: Optional[FixedEffectModel] = None,
    ) -> FixedEffectModel:
        ds = self.dataset
        rate = self.config.down_sampling_rate
        if rate < 1.0:
            # Reference: DownSampler subsamples the fixed-effect coordinate's
            # data each training pass, rescaling weights by 1/rate. The
            # sampler is picked by TASK (reference behavior), not by
            # inspecting label values.
            if self.loss.name in ("logistic", "smoothed_hinge"):
                idx, mult = binary_classification_down_sample(
                    self._rng, ds.response, rate)
            else:
                idx, mult = default_down_sample(self._rng, ds.num_rows, rate)
            batch = LabeledBatch.build(
                ds.feature_shards[self.shard_id][idx], ds.response[idx],
                ds.weights[idx] * mult, np.asarray(offsets)[idx])
        else:
            batch = LabeledBatch.build(
                ds.feature_shards[self.shard_id], ds.response, ds.weights,
                offsets)
        init = None
        if initial is not None:
            init = Coefficients(self.norm.model_to_transformed_space(
                initial.coefficients.means))
        # Variances are computed once after descent (compute_model_variances),
        # not on every training pass.
        cfg = dataclasses.replace(
            self.config, variance_computation=VarianceComputationType.NONE)
        coef, _ = dist_problem.run(
            self.loss, batch, self.mesh, cfg, initial=init,
            norm=self.norm, intercept_index=self.intercept_index)
        raw = Coefficients(self.norm.model_to_original_space(coef.means))
        return FixedEffectModel(shard_id=self.shard_id, coefficients=raw)

    def compute_model_variances(
        self, model: FixedEffectModel, offsets: Array
    ) -> FixedEffectModel:
        """Coefficient variances at the optimum (post-descent pass).

        Variances are computed in the transformed space and mapped back by
        the factor² scaling implied by w_orig = w∘f (the intercept's extra
        shift term is a location change and does not rescale its variance).
        """
        kind = VarianceComputationType(self.config.variance_computation)
        if kind == VarianceComputationType.NONE:
            return model
        batch = shard_batch(LabeledBatch.build(
            self.dataset.feature_shards[self.shard_id], self.dataset.response,
            self.dataset.weights, offsets), self.mesh)
        w_t = self.norm.model_to_transformed_space(model.coefficients.means)
        mask = jnp.asarray(intercept_mask(self.dim, self.intercept_index))
        l2 = self.config.regularization.l2_weight()
        if kind == VarianceComputationType.SIMPLE:
            diag = dobj.make_hessian_diagonal(
                self.loss, self.mesh, batch, self.norm)(w_t)
            var_t = variances_from_diagonal(diag, l2, mask)
        else:
            H = dobj.make_hessian_matrix(
                self.loss, self.mesh, batch, self.norm)(w_t)
            var_t = variances_from_matrix(H, l2, mask)
        var_t = self.norm.variances_to_original_space(var_t)
        return dataclasses.replace(
            model, coefficients=Coefficients(model.coefficients.means, var_t))

    def score(self, model: FixedEffectModel) -> Array:
        """Raw-space score (identical to the training margins by algebra)."""
        return self._X @ model.coefficients.means

    def initial_model(self) -> FixedEffectModel:
        return FixedEffectModel(
            shard_id=self.shard_id,
            coefficients=Coefficients.zeros(self.dim))


class RandomEffectCoordinate:
    """Per-entity GLMs trained as vmapped bucket solves.

    Reference parity: RandomEffectCoordinate + SingleNodeOptimizationProblem
    (per-entity local L-BFGS inside mapValues) — here all entities of a
    bucket solve simultaneously under vmap with convergence masks.

    Model-space contract: same as FixedEffectCoordinate — solves run in the
    shard's normalization-transformed space; the RandomEffectModel rows are
    ORIGINAL-space, so scoring is the plain gather + rowwise dot everywhere.
    """

    def __init__(
        self,
        dataset: GameDataset,
        re_type: str,
        shard_id: str,
        loss: PointwiseLoss,
        config: GLMOptimizationConfiguration,
        mesh,
        lower_bound: int = 1,
        upper_bound: Optional[int] = None,
        norm: NormalizationContext = NormalizationContext(),
        seed: int = 0,
    ):
        self.dataset = dataset
        self.re_type = re_type
        self.shard_id = shard_id
        self.loss = loss
        self.config = config
        self.mesh = mesh
        self.norm = norm
        self.num_entities = dataset.num_entities[re_type]
        self.intercept_index = dataset.intercept_index.get(shard_id)
        self.bucketing = bkt.build_bucketing(
            dataset.entity_ids[re_type], self.num_entities,
            lower_bound=lower_bound, upper_bound=upper_bound,
            entity_pad_multiple=max(8, int(np.prod(list(mesh.shape.values())))),
            rng=np.random.default_rng(seed))
        self._X = jnp.asarray(dataset.feature_shards[shard_id])
        self._ids = jnp.asarray(dataset.entity_ids[re_type])
        # Pre-gather static per-bucket arrays (features/labels/weights).
        self._bucket_data = []
        ds = dataset
        X = ds.feature_shards[shard_id]
        for b in self.bucketing.buckets:
            Xb, yb = bkt.gather_bucket_arrays(b, X, ds.response)
            wb = bkt.bucket_weights(b, ds.weights)
            self._bucket_data.append(
                (jnp.asarray(Xb), jnp.asarray(yb), jnp.asarray(wb)))
        self._solver = self._make_solver(compute_variance=False)
        self._var_solver = None  # built lazily if variances requested

    @property
    def dim(self) -> int:
        return self.dataset.shard_dim(self.shard_id)

    def with_optimization_config(
        self, config: GLMOptimizationConfiguration
    ) -> "RandomEffectCoordinate":
        """Cheap copy with a new optimization config, reusing the bucketing
        and the staged per-bucket device arrays (the expensive part of
        __init__). Only the jitted solver is rebuilt."""
        import copy

        c = copy.copy(self)
        c.config = config
        c._solver = c._make_solver(compute_variance=False)
        c._var_solver = None
        return c

    def _make_solver(self, compute_variance: bool):
        loss = self.loss
        config = self.config
        intercept_index = self.intercept_index
        dim = self.dim
        norm = self.norm

        def solve_one(X, y, w, o, w0):
            batch = LabeledBatch(X, y, w, o)
            vg, hvp, l1w = make_objective(
                loss, batch, norm, config.regularization, intercept_index, dim)
            opt_cfg = resolve_optimizer_config(config.optimizer, l1w is not None)
            result = optimize(vg, w0, opt_cfg, hvp=hvp, l1_weights=l1w)
            if compute_variance:
                var = compute_variances(
                    loss, result.w, batch, norm, config.variance_computation,
                    config.regularization, intercept_index)
            else:
                var = jnp.zeros_like(result.w)
            return result.w, var

        return jax.jit(jax.vmap(solve_one))

    def train_model(
        self,
        offsets: Array,
        initial: Optional[RandomEffectModel] = None,
    ) -> RandomEffectModel:
        # Warm starts arrive in original space; solve in transformed space.
        if initial is None:
            W = np.zeros((self.num_entities, self.dim), np.float32)
        else:
            W = np.array(
                self.norm.model_to_transformed_space(initial.means))
        offsets_np = np.asarray(offsets)
        for b, (Xb, yb, wb) in zip(self.bucketing.buckets, self._bucket_data):
            ob = jnp.asarray(offsets_np[np.maximum(b.example_idx, 0)])
            w0 = jnp.asarray(W[np.maximum(b.entity_rows, 0)])
            w_fit, _ = self._solver(Xb, yb, wb, ob, w0)
            w_fit = np.asarray(w_fit)
            live = b.entity_rows >= 0
            W[b.entity_rows[live]] = w_fit[live]
        W_raw = self.norm.model_to_original_space(jnp.asarray(W))
        return RandomEffectModel(
            re_type=self.re_type, shard_id=self.shard_id, means=W_raw)

    def compute_model_variances(
        self, model: RandomEffectModel, offsets: Array
    ) -> RandomEffectModel:
        """Per-entity coefficient variances at the trained optimum."""
        if VarianceComputationType(self.config.variance_computation) == \
                VarianceComputationType.NONE:
            return model
        if self._var_solver is None:
            self._var_solver = self._make_solver(compute_variance=True)
        W = np.array(self.norm.model_to_transformed_space(model.means))
        V = np.zeros_like(W)
        offsets_np = np.asarray(offsets)
        for b, (Xb, yb, wb) in zip(self.bucketing.buckets, self._bucket_data):
            ob = jnp.asarray(offsets_np[np.maximum(b.example_idx, 0)])
            w0 = jnp.asarray(W[np.maximum(b.entity_rows, 0)])
            _, var = self._var_solver(Xb, yb, wb, ob, w0)
            var = np.asarray(var)
            live = b.entity_rows >= 0
            V[b.entity_rows[live]] = var[live]
        if self.norm.factors is not None:
            V = V * np.asarray(self.norm.factors) ** 2
        return dataclasses.replace(model, variances=jnp.asarray(V))

    def score(self, model: RandomEffectModel) -> Array:
        return jnp.einsum("nd,nd->n", self._X, model.means[self._ids])

    def initial_model(self) -> RandomEffectModel:
        return RandomEffectModel(
            re_type=self.re_type, shard_id=self.shard_id,
            means=jnp.zeros((self.num_entities, self.dim), jnp.float32))
