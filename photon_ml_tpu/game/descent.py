"""Block coordinate descent: the GAME training loop.

Reference parity: photon-api ``algorithm/CoordinateDescent.scala`` — for
each iteration, for each coordinate in the update sequence: subtract the
coordinate's current scores from the residual, train it against the
remaining offsets, add its new scores back; track per-iteration validation
metrics; support locked (pretrained, partial-retraining) coordinates.

TPU-first notes: coordinates are trained SEQUENTIALLY by design (the block
residual dependency — SURVEY.md §2.5 P4: no pipeline parallelism exists in
this workload); the parallelism is inside each coordinate (data-parallel
psum for fixed effects, vmapped entity blocks for random effects). Score
bookkeeping is elementwise adds on stable-order (n,) device arrays instead
of the reference's outer-join RDD arithmetic (CoordinateDataScores +/-).
"""

from __future__ import annotations

import contextlib
import dataclasses
import enum
import hashlib
import logging
import time
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu import obs
from photon_ml_tpu.game.models import CoordinateModel, GameModel
from photon_ml_tpu.types import TaskType
from photon_ml_tpu.utils import events as ev_mod

logger = logging.getLogger("photon_ml_tpu.game")


@dataclasses.dataclass
class CoordinateDescentConfig:
    """Update sequence + outer iterations (reference: GameTrainingDriver
    params ``coordinateUpdateSequence`` / ``coordinateDescentIterations``)."""

    update_sequence: list[str]
    iterations: int = 1
    # Per-update dispatch-stream barrier: None = auto (estimate the
    # enqueue-held scratch in bytes and sync when it could plausibly
    # exhaust HBM), True/False = force. See the gate in run().
    sync_updates: Optional[bool] = None


@dataclasses.dataclass
class CoordinateDescentHistory:
    """Per-(iteration, coordinate) timing and validation records."""

    records: list[dict] = dataclasses.field(default_factory=list)


def run(
    task: TaskType,
    coordinates: dict[str, object],
    config: CoordinateDescentConfig,
    *,
    initial_models: Optional[dict[str, CoordinateModel]] = None,
    locked_coordinates: Optional[set[str]] = None,
    validation_fn: Optional[Callable[[GameModel], dict]] = None,
    checkpoint_manager=None,
    sweep=None,
) -> tuple[GameModel, CoordinateDescentHistory]:
    """Run block coordinate descent (reference: CoordinateDescent.run).

    ``coordinates`` maps coordinate id → Fixed/RandomEffectCoordinate (all
    sharing one GameDataset's example order). ``locked_coordinates`` are
    scored but never retrained (reference partial retraining).
    ``validation_fn`` is called after each coordinate update with the
    current GameModel (reference: per-iteration EvaluationSuite logging).

    ``checkpoint_manager`` (game/checkpoint.py) persists models + progress
    after every coordinate update and, when an existing checkpoint is found
    under its directory, resumes from it: already-completed (iteration,
    coordinate) updates are skipped and the checkpointed models replace the
    warm starts. Restart state is models + a linear step counter + the
    (n,) residual score total. Restoring the saved total (instead of
    re-summing per-coordinate scores, which changes the f32 accumulation
    order) makes a resumed run BIT-exact with an uninterrupted one; the
    restored total is validated against the re-summed one and discarded if
    they disagree beyond accumulation noise (a kill between the model and
    residual writes can leave a newer model directory with older
    residuals — re-summation is always consistent with the model files).

    ``sweep`` (game/sweep.py SweepConfig) turns on dirty-gated sweeps for
    random-effect coordinates: outer iterations past ``min_sweeps_full``
    refit only entities whose residual offsets drifted or whose last
    solve left gradient mass, and the residual total updates
    incrementally (``total += delta``, delta exactly 0.0 on clean rows).
    ``gate=0`` (theta=0, grad_tol=0) is normalized to ``sweep=None`` so
    the run takes THIS function's unmodified full-sweep expressions and
    is bit-identical to an ungated run — the base rung of the parity
    ladder (docs/SWEEPS.md).
    """
    if sweep is not None and sweep.gate_zero:
        sweep = None
    seq = list(config.update_sequence)
    unknown = [c for c in seq if c not in coordinates]
    if unknown:
        raise ValueError(f"update sequence references unknown coordinates "
                         f"{unknown}")
    locked = set(locked_coordinates or ())
    for c in locked:
        if initial_models is None or c not in initial_models:
            raise ValueError(f"locked coordinate {c!r} needs an initial model")

    some = coordinates[seq[0]]
    n = some.dataset.num_rows

    led = obs.ledger()
    fingerprint = None
    resume = None
    if checkpoint_manager is not None or led is not None:
        fingerprint = _fingerprint(task, coordinates, seq, config, locked, n)
        if sweep is not None:
            # Gated runs take different training steps (skipped entities,
            # incremental rescoring), so their checkpoints are not
            # interchangeable with full-sweep ones. Only added when
            # tracking is on: sweep=None (and the gate=0 normalization
            # above) keeps the fingerprint byte-identical to HEAD's.
            fingerprint["sweep"] = _jsonable(sweep)
    if led is not None:
        # Stamp (or validate, on a --resume append) the run ledger's
        # identity from the SAME fingerprint machinery the checkpoint
        # trusts — a ledger never silently continues a different run's
        # curve (obs/ledger.py).
        led.bind_fingerprint(fingerprint)
    if checkpoint_manager is not None:
        resume = checkpoint_manager.load(expected_fingerprint=fingerprint)
    history = CoordinateDescentHistory()
    done_steps = 0
    if resume is not None:
        initial_models = {**(initial_models or {}), **resume.models}
        done_steps = resume.done_steps
        history.records = list(resume.records)
        logger.info("resuming coordinate descent from checkpoint: "
                    "%d updates already done", done_steps)
        if resume.complete:
            return (GameModel(task=task, models=dict(resume.models)),
                    history)
        # Fast-forward per-coordinate down-sampling RNGs past the completed
        # train calls so the remaining steps draw the SAME subsamples as an
        # uninterrupted run would have.
        completed: dict[str, int] = {}
        for rec in resume.records:
            completed[rec["coordinate"]] = \
                completed.get(rec["coordinate"], 0) + 1
        for cid, k in completed.items():
            advance = getattr(coordinates.get(cid), "advance_down_sampling",
                              None)
            if advance is not None:
                advance(k)

    # Dirty-set gating state, one per unlocked coordinate that supports
    # it (RandomEffectCoordinate.make_sweep_state); fixed-effect and
    # factored coordinates simply keep taking the full-sweep path.
    sweep_states: dict[str, object] = {}
    if sweep is not None:
        for cid in seq:
            if cid in locked:
                continue
            mk = getattr(coordinates[cid], "make_sweep_state", None)
            if mk is not None:
                sweep_states[cid] = mk()
        if resume is not None and resume.sweep_states:
            # Restore drift references + gradient evidence so the gated
            # resume takes the SAME skip decisions an unkilled run would
            # (bit-identical gated resume). A coordinate whose artifact
            # was missing/unreadable keeps off_ref=None and re-tracks
            # from a forced full sweep — correct, just less incremental.
            for cid, st in sweep_states.items():
                arrays = resume.sweep_states.get(cid)
                if arrays is not None:
                    st.restore(arrays)

    models: dict[str, CoordinateModel] = {}
    scores: dict[str, jnp.ndarray] = {}
    base = jnp.asarray(some.dataset.offsets)
    total = jnp.zeros((n,), jnp.float32)

    # At scale, synchronize the dispatch stream once per coordinate
    # update. JAX enqueues every fit/score program ahead of execution, and
    # the runtime holds each queued program's output and scratch buffers
    # from ENQUEUE time — a full un-synced descent sweep at 19M rows
    # reproducibly exhausts HBM even though the same programs run fine
    # back-to-back with a barrier between them (and the resident arrays
    # total only a few GB). The barrier costs one tunnel round trip per
    # coordinate update, so it is gated on an ESTIMATE of the scratch a
    # fully un-synced descent would hold: per queued update, O(n) score
    # outputs plus working buffers scaling with the coordinate's feature
    # dim (capped — sparse/tiled formulations never materialize n×d), for
    # every update the whole descent enqueues. Small configs keep full
    # dispatch pipelining; config.sync_updates forces either way.
    if config.sync_updates is not None:
        sync_updates = bool(config.sync_updates)
    else:
        # The byte estimate only ever ADDS protection beyond the empirical
        # n >= 4.2M row floor (where the 19M OOM was reproduced): the
        # estimate undercounts RE training scratch, so it must not be able
        # to turn the barrier OFF in the regime the floor covers.
        est_bytes = 0
        for cid in seq:
            dim = int(getattr(coordinates[cid], "dim", 8) or 8)
            est_bytes += n * 4 * (2 + min(dim, 4096))
        est_bytes *= max(1, config.iterations)
        sync_updates = n >= (1 << 22) or est_bytes >= (1 << 30)

    def _sync(x):
        if sync_updates:
            jax.block_until_ready(x)

    # Initialize models (warm starts / checkpoint state) and their scores.
    for cid in seq:
        coord = coordinates[cid]
        if initial_models and cid in initial_models:
            # Cross-type warm starts (full-rank ↔ factored random effects)
            # convert here so scoring and training see the coordinate's
            # own model type.
            adapt = getattr(coord, "adapt_initial", None)
            models[cid] = (adapt(initial_models[cid]) if adapt
                           else initial_models[cid])
        else:
            models[cid] = coord.initial_model()
        s = coord.score(models[cid])
        scores[cid] = s
        total = total + s
        _sync(total)

    if resume is not None and resume.residual_total is not None:
        restored = np.asarray(resume.residual_total)
        # Benign mismatch vs the fresh sum is f32 accumulation-order noise
        # (~1e-6); a kill between the model-dir and residual writes leaves
        # a step-sized gap instead. Restore only in the former case — the
        # fresh sum is always consistent with the model files.
        if restored.shape == total.shape and np.allclose(
                np.asarray(total), restored, rtol=1e-5, atol=1e-5):
            total = jnp.asarray(restored)
        else:
            logger.warning(
                "checkpoint residuals disagree with re-summed scores; "
                "using the re-summed total (resume stays correct but is "
                "no longer bit-exact)")

    emitter = ev_mod.default_emitter
    emitter.emit(ev_mod.TrainingStart(
        task=TaskType(task).value, update_sequence=tuple(seq),
        iterations=config.iterations))

    step = 0
    try:
        for it in range(config.iterations):
            for cid in seq:
                if cid in locked:
                    continue
                step += 1
                if step <= done_steps:
                    continue  # already covered by the checkpoint
                coord = coordinates[cid]
                t0 = time.monotonic()
                # Ledger context: every telemetry row the update's
                # optimizer produces (live opt_iter rows, compiled
                # spills, RE waves) carries which coordinate/step it
                # belongs to.
                bound = (led.bound(coordinate=cid, outer_iteration=it,
                                   step=step)
                         if led is not None
                         else contextlib.nullcontext())
                # One span per coordinate update — the descent
                # waterfall's unit; the coordinate's own spans (streamed
                # passes, fit waves, checkpoint writes) nest under it.
                with bound, obs.span("descent.update", cat="train",
                                     iteration=it, coordinate=cid,
                                     step=step):
                    if checkpoint_manager is not None:
                        # Streamed coordinates checkpoint INSIDE the
                        # update too (their fit is the multi-hour unit at
                        # flagship scale): bind this step's stream-state
                        # directory so a kill mid-L-BFGS resumes
                        # mid-optimization.
                        bind = getattr(coord, "bind_step_checkpoint",
                                       None)
                        if bind is not None:
                            bind(checkpoint_manager.stream_dir(step),
                                 step)
                    # Residual offsets: everything except this
                    # coordinate.
                    offsets = base + total - scores[cid]
                    st = sweep_states.get(cid)
                    if st is None:
                        model = coord.train_model(offsets,
                                                  initial=models[cid])
                        new_scores = coord.score(model)
                        total = total + new_scores - scores[cid]
                        scores[cid] = new_scores
                    else:
                        # Parity-ladder rungs: warm-up sweeps seed the
                        # drift/gradient evidence, the final full sweep
                        # is the correctness backstop.
                        force_full = (
                            it < sweep.min_sweeps_full
                            or (sweep.final_full_sweep
                                and it == config.iterations - 1))
                        model, delta, _sstats = coord.train_model_gated(  # pml: allow[PML012] one loop iteration IS one whole gated sweep of the coordinate; its (E,) dirty-mask fetch selects the wave shapes and amortizes over every vmapped bucket solve it dispatches
                            offsets, state=st, config=sweep,
                            initial=models[cid], force_full=force_full)
                        new_scores = coord.score(model)
                        if delta is None:
                            # Segment rescoring is inexact for this
                            # bucketing (passive rows under upper_bound):
                            # rescore fully. Unchanged entity rows give
                            # bitwise-equal scores, so the difference is
                            # still exactly 0.0 on clean rows.
                            delta = new_scores - scores[cid]
                        total = total + delta
                        # The per-coordinate bookkeeping takes the FRESH
                        # score, not scores[cid] + delta: resume rebuilds
                        # scores from score(model), so the live run must
                        # hold the same values or a killed-and-resumed
                        # gated run drifts from an unkilled one by f32
                        # association noise. Only the residual total is
                        # incremental.
                        scores[cid] = new_scores
                    models[cid] = model
                    _sync(total)
                elapsed = time.monotonic() - t0
                rec = {"iteration": it, "coordinate": cid,
                       "train_seconds": elapsed}
                if validation_fn is not None:
                    rec["validation"] = validation_fn(
                        GameModel(task=task, models=dict(models)))
                logger.info("CD iter %d coordinate %s: %.2fs %s", it, cid,
                            elapsed, rec.get("validation", ""))
                history.records.append(rec)
                emitter.emit(ev_mod.CoordinateUpdate(
                    iteration=it, coordinate=cid, train_seconds=elapsed,
                    validation=rec.get("validation")))
                if led is not None:
                    led.record("coordinate_update", coordinate=cid,
                               outer_iteration=it, step=step,
                               seconds=round(elapsed, 6),
                               validation=rec.get("validation"))
                if checkpoint_manager is not None:
                    checkpoint_manager.save(
                        task, models, done_steps=step,
                        records=history.records, fingerprint=fingerprint,
                        # pml: allow[PML001] checkpoint persistence NEEDS the
                        # host copy, once per coordinate update (seconds of
                        # device work), and _sync already drained the stream
                        updated=[cid], residual_total=np.asarray(total),
                        sweep_states=_sweep_arrays(sweep_states))
                    # The step committed: its mid-step stream state is
                    # stale (a later resume starts AFTER this step).
                    clear = getattr(coord, "clear_step_checkpoint", None)
                    if clear is not None:
                        clear()
    finally:
        # Balanced lifecycle (PML007): a raise mid-descent must still
        # close the training scope for listeners tracking it.
        emitter.emit(ev_mod.TrainingFinish(task=TaskType(task).value,
                                           total_updates=step))
    if checkpoint_manager is not None:
        checkpoint_manager.save(task, models, done_steps=step,
                                records=history.records, complete=True,
                                fingerprint=fingerprint,
                                residual_total=np.asarray(total),
                                sweep_states=_sweep_arrays(sweep_states))
    return GameModel(task=task, models=models), history


def _sweep_arrays(sweep_states: dict) -> Optional[dict]:
    """Serialize live gating states for a checkpoint commit (None when
    gating is off, keeping the artifact set byte-identical to HEAD's)."""
    if not sweep_states:
        return None
    return {cid: st.to_arrays() for cid, st in sweep_states.items()}


def _dataset_digest(ds) -> str:
    """Content digest of a GameDataset (responses, offsets, weights,
    feature shards, entity assignments) — anything that changes the
    training objectives. Memoized on the dataset object: at Criteo scale
    this is a full pass over tens of GB, and a reg-weight grid would
    otherwise repeat it once per grid point. (Datasets are treated as
    immutable throughout — see the estimator's coordinate-cache contract.)
    """
    cached = getattr(ds, "_content_digest", None)
    if cached is not None:
        return cached
    h = hashlib.sha1()

    def _feed(arr):
        _feed_array(h, arr)

    for arr in (ds.response, ds.offsets, ds.weights):
        _feed(arr)
    for sid in sorted(ds.feature_shards):
        shard = ds.feature_shards[sid]
        if hasattr(shard, "indices"):  # SparseShard
            _feed(shard.indices)
            _feed(shard.values)
        else:
            _feed(shard)
    for re_type in sorted(ds.entity_ids):
        _feed(ds.entity_ids[re_type])
    digest = h.hexdigest()
    try:
        ds._content_digest = digest
    except (AttributeError, TypeError):
        pass  # frozen/slotted datasets: just recompute next time
    return digest


def _feed_array(h, arr) -> None:
    """The ONE array-content hashing convention (None gets a marker so
    (None, x) never collides with (x, None)) — shared by the dataset
    digest, the checkpoint fingerprint, and normalization_digest."""
    if arr is None:
        h.update(b"\x00none")
    else:
        h.update(np.ascontiguousarray(np.asarray(arr)).tobytes())


def normalization_digest(ctx) -> str:
    """Content digest of a NormalizationContext — pairs with
    ``_dataset_digest`` as the estimator's coordinate-cache key."""
    h = hashlib.sha1()
    _feed_array(h, ctx.factors)
    _feed_array(h, ctx.shifts)
    h.update(repr(ctx.intercept_index).encode())
    return h.hexdigest()


def _jsonable(obj):
    """Dataclass/enum tree → plain JSON-comparable values."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {f.name: _jsonable(getattr(obj, f.name))
                for f in dataclasses.fields(obj)}
    if isinstance(obj, enum.Enum):
        return obj.value
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    return obj


def _fingerprint(task, coordinates, seq, config, locked, n) -> dict:
    """What a checkpoint must agree on to be resumable: anything that
    changes the sequence of training steps or their objectives — the FULL
    per-coordinate optimization config (tolerance, elastic-net alpha, …),
    the loop shape, and a digest of the training responses/offsets/weights
    (num_rows alone cannot tell two datasets apart)."""
    per_coord = {}
    for cid in seq:
        c = getattr(coordinates[cid], "config", None)
        per_coord[cid] = {
            "config": _jsonable(c) if c is not None else None,
            "down_sampling_seed": getattr(
                coordinates[cid], "_down_sampling_seed", None),
        }
    ds = coordinates[seq[0]].dataset
    h = hashlib.sha1()
    h.update(_dataset_digest(ds).encode())
    for cid in seq:
        norm = getattr(coordinates[cid], "norm", None)
        if norm is not None:
            _feed_array(h, getattr(norm, "factors", None))
            _feed_array(h, getattr(norm, "shifts", None))
    return {
        "task": TaskType(task).value,
        "sequence": list(seq),
        "iterations": int(config.iterations),
        "locked": sorted(locked),
        "num_rows": int(n),
        "data_digest": h.hexdigest(),
        "coordinates": per_coord,
    }
