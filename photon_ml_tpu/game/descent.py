"""Block coordinate descent: the GAME training loop.

Reference parity: photon-api ``algorithm/CoordinateDescent.scala`` — for
each iteration, for each coordinate in the update sequence: subtract the
coordinate's current scores from the residual, train it against the
remaining offsets, add its new scores back; track per-iteration validation
metrics; support locked (pretrained, partial-retraining) coordinates.

TPU-first notes: coordinates are trained SEQUENTIALLY by design (the block
residual dependency — SURVEY.md §2.5 P4: no pipeline parallelism exists in
this workload); the parallelism is inside each coordinate (data-parallel
psum for fixed effects, vmapped entity blocks for random effects). Score
bookkeeping is elementwise adds on stable-order (n,) device arrays instead
of the reference's outer-join RDD arithmetic (CoordinateDataScores +/-).
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Callable, Optional

import jax.numpy as jnp

from photon_ml_tpu.game.models import CoordinateModel, GameModel
from photon_ml_tpu.types import TaskType

logger = logging.getLogger("photon_ml_tpu.game")


@dataclasses.dataclass
class CoordinateDescentConfig:
    """Update sequence + outer iterations (reference: GameTrainingDriver
    params ``coordinateUpdateSequence`` / ``coordinateDescentIterations``)."""

    update_sequence: list[str]
    iterations: int = 1


@dataclasses.dataclass
class CoordinateDescentHistory:
    """Per-(iteration, coordinate) timing and validation records."""

    records: list[dict] = dataclasses.field(default_factory=list)


def run(
    task: TaskType,
    coordinates: dict[str, object],
    config: CoordinateDescentConfig,
    *,
    initial_models: Optional[dict[str, CoordinateModel]] = None,
    locked_coordinates: Optional[set[str]] = None,
    validation_fn: Optional[Callable[[GameModel], dict]] = None,
) -> tuple[GameModel, CoordinateDescentHistory]:
    """Run block coordinate descent (reference: CoordinateDescent.run).

    ``coordinates`` maps coordinate id → Fixed/RandomEffectCoordinate (all
    sharing one GameDataset's example order). ``locked_coordinates`` are
    scored but never retrained (reference partial retraining).
    ``validation_fn`` is called after each coordinate update with the
    current GameModel (reference: per-iteration EvaluationSuite logging).
    """
    seq = list(config.update_sequence)
    unknown = [c for c in seq if c not in coordinates]
    if unknown:
        raise ValueError(f"update sequence references unknown coordinates "
                         f"{unknown}")
    locked = set(locked_coordinates or ())
    for c in locked:
        if initial_models is None or c not in initial_models:
            raise ValueError(f"locked coordinate {c!r} needs an initial model")

    models: dict[str, CoordinateModel] = {}
    scores: dict[str, jnp.ndarray] = {}
    some = coordinates[seq[0]]
    n = some.dataset.num_rows
    base = jnp.asarray(some.dataset.offsets)
    total = jnp.zeros((n,), jnp.float32)

    # Initialize models (warm starts) and their scores.
    for cid in seq:
        coord = coordinates[cid]
        if initial_models and cid in initial_models:
            models[cid] = initial_models[cid]
        else:
            models[cid] = coord.initial_model()
        s = coord.score(models[cid])
        scores[cid] = s
        total = total + s

    history = CoordinateDescentHistory()
    for it in range(config.iterations):
        for cid in seq:
            if cid in locked:
                continue
            coord = coordinates[cid]
            t0 = time.monotonic()
            # Residual offsets: everything except this coordinate.
            offsets = base + total - scores[cid]
            model = coord.train_model(offsets, initial=models[cid])
            new_scores = coord.score(model)
            total = total + new_scores - scores[cid]
            scores[cid] = new_scores
            models[cid] = model
            elapsed = time.monotonic() - t0
            rec = {"iteration": it, "coordinate": cid,
                   "train_seconds": elapsed}
            if validation_fn is not None:
                rec["validation"] = validation_fn(
                    GameModel(task=task, models=dict(models)))
            logger.info("CD iter %d coordinate %s: %.2fs %s", it, cid,
                        elapsed, rec.get("validation", ""))
            history.records.append(rec)

    return GameModel(task=task, models=models), history
