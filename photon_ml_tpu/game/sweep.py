"""photon-sweep: dirty-gated incremental coordinate descent
(docs/SWEEPS.md).

The GAME outer loop refits every random-effect entity every outer
iteration, yet after the first sweep most entities' residual offsets have
barely moved and their local solves already sit at their optima. This
module holds the gating state and math that lets outer iterations >= 2
refit only *dirty* entities:

    dirty_e  =  drift_e > theta * scale_e   OR   grad_norm_e > grad_tol

where ``drift_e`` is the segment-summed |delta offset| over entity e's
rows since e was last fit (computed on device from the same (n,) score
vectors the descent loop already holds), ``scale_e`` is e's row count
(so ``theta`` reads as a mean per-row offset-drift threshold), and
``grad_norm_e`` is the final per-lane gradient norm spilled from the
vmapped bucket solver at e's last fit.

Parity ladder (docs/SWEEPS.md):

* ``gate=0`` (theta=0, grad_tol=0) bypasses the gated machinery entirely
  — the descent runs HEAD's full-sweep expressions and is BIT-IDENTICAL
  to an ungated run (coefficients and residual total).
* Gated runs use an incremental residual update (``total += delta`` with
  delta exactly 0.0 on clean rows) and land inside the repo's 5e-3
  coefficient band, with a mandatory final full sweep as the correctness
  backstop (``final_full_sweep``).
* The dirty-set state (``off_ref`` offsets-at-last-fit + per-entity grad
  norms) rides in the descent checkpoint (``sweep/<cid>.npz``, fault
  site ``sweep.gate_state``) so a SIGKILL'd gated run resumes
  bit-identical to an unkilled gated run.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass(frozen=True)
class SweepConfig:
    """Dirty-gated sweep knobs (``GameEstimator(sweep=...)``, CLI
    ``game_train --sweep "theta=...,grad_tol=..."``).

    ``theta``: mean per-row residual-offset drift above which an entity
    is refit (0 = drift never skips). ``grad_tol``: per-entity gradient
    norm above which an entity is refit regardless of drift (0 = grad
    evidence never skips; entities without evidence are always dirty).
    ``min_sweeps_full``: leading outer iterations forced full — at least
    1, both to seed the drift/grad evidence and to uphold the projected
    path's active-column invariant (a full projected sweep rewrites
    whole rows, so later active-column deltas are exact).
    ``final_full_sweep``: force the last outer iteration full (the
    parity-band backstop). ``gram``: reuse per-bucket normal-equation
    (X^T W X) Gram blocks across sweeps for the squared-loss bucket
    solver (built once at stage time; ineligible configurations fall
    back to the iterative solver — see docs/SWEEPS.md).
    """

    theta: float = 0.0
    grad_tol: float = 0.0
    min_sweeps_full: int = 1
    final_full_sweep: bool = True
    gram: bool = False

    def __post_init__(self):
        if self.theta < 0:
            raise ValueError(f"theta must be >= 0, got {self.theta}")
        if self.grad_tol < 0:
            raise ValueError(
                f"grad_tol must be >= 0, got {self.grad_tol}")
        if self.min_sweeps_full < 1:
            raise ValueError(
                f"min_sweeps_full must be >= 1, got "
                f"{self.min_sweeps_full} (gated sweeps need one full "
                "sweep of drift/gradient evidence first)")

    @property
    def gate_zero(self) -> bool:
        """theta=0 AND grad_tol=0: every entity is always dirty — the
        descent takes HEAD's bit-identical full-sweep path."""
        return self.theta == 0.0 and self.grad_tol == 0.0


def next_pow2(k: int) -> int:
    """Smallest power of two >= k (k >= 1)."""
    return 1 << (max(int(k), 1) - 1).bit_length()


def compact_lanes(selected: int, pad: int, total: int) -> int:
    """Quantized lane count for a compacted fit wave: power-of-two
    growth (bounding the jit program-cache to O(log lanes) shapes per
    staged tuple), floored at the coordinate's entity pad multiple and
    capped at the tuple's own lane count."""
    return max(int(pad), min(next_pow2(selected), int(total)))


@functools.partial(jax.jit, static_argnames=("num_entities",))
def _drift(offsets, off_ref, ids, num_entities):
    return jax.ops.segment_sum(jnp.abs(offsets - off_ref), ids,
                               num_segments=num_entities)


@jax.jit
def _dirty(drift, grad_norms, scale, trained, theta, grad_tol):
    return trained & ((drift > theta * scale) | (grad_norms > grad_tol))


@jax.jit
def _advance_off_ref(off_ref, offsets, dirty, ids):
    return jnp.where(dirty[ids], offsets, off_ref)


class CoordinateSweepState:
    """One random-effect coordinate's dirty-set evidence.

    ``off_ref``: (n,) residual offsets each row's entity saw at its last
    fit (None until the first tracked sweep). ``grad_norms``: (E,) final
    solver gradient norms from each entity's last fit (+inf until
    evidence exists, so unevidenced entities are always dirty).
    ``scale``/``trained`` are derived from the coordinate's bucketing at
    construction and are NOT checkpointed — they are a function of the
    dataset, which the descent fingerprint already pins.
    """

    def __init__(self, num_entities: int, ids, scale, trained):
        self.num_entities = int(num_entities)
        self.ids = jnp.asarray(ids)
        self.scale = jnp.asarray(scale, jnp.float32)
        self._trained_host = np.asarray(trained, bool)
        self.trained = jnp.asarray(self._trained_host)
        self.grad_norms = jnp.full((self.num_entities,), jnp.inf,
                                   jnp.float32)
        self.off_ref: Optional[jax.Array] = None

    def gate(self, offsets, config: SweepConfig):
        """(dirty (E,) bool, drift (E,) f32) for the coming sweep.
        Requires evidence (``off_ref`` set by a prior tracked sweep)."""
        drift = _drift(jnp.asarray(offsets), self.off_ref, self.ids,
                       self.num_entities)
        dirty = _dirty(drift, self.grad_norms, self.scale, self.trained,
                       config.theta, config.grad_tol)
        return dirty, drift

    def advance(self, offsets, dirty=None) -> None:
        """Move refit entities' offset references to the offsets they
        were just fit against (all trained entities when ``dirty`` is
        None — a full sweep)."""
        offsets = jnp.asarray(offsets)
        if dirty is None or self.off_ref is None:
            self.off_ref = offsets
        else:
            self.off_ref = _advance_off_ref(self.off_ref, offsets,
                                            dirty, self.ids)

    def drift_p99(self, drift) -> float:
        """p99 of per-entity drift over trained entities (telemetry)."""
        d = np.asarray(drift)[self._trained_host]
        return float(np.percentile(d, 99)) if d.size else 0.0

    # -- checkpoint serialization (game/checkpoint.py sweep/<cid>.npz) --

    def to_arrays(self) -> dict:
        out = {"grad_norms": np.asarray(self.grad_norms)}
        if self.off_ref is not None:
            out["off_ref"] = np.asarray(self.off_ref)
        return out

    def restore(self, arrays: dict) -> None:
        if "grad_norms" in arrays:
            self.grad_norms = jnp.asarray(arrays["grad_norms"])
        if "off_ref" in arrays:
            self.off_ref = jnp.asarray(arrays["off_ref"])
