"""Down-samplers for fixed-effect coordinate throughput.

Reference parity: photon-api ``sampling/DownSampler.scala``,
``sampling/DefaultDownSampler.scala`` (uniform subsample, weights rescaled
by 1/rate) and ``sampling/BinaryClassificationDownSampler.scala`` (keep all
positives, sample negatives at the rate, rescale negative weights).

TPU note: the subsample is drawn host-side to a FIXED target size (rounded
once from the rate) so the per-iteration training batch keeps one static
shape — no recompilation across coordinate-descent iterations.
"""

from __future__ import annotations

import numpy as np


def default_down_sample(
    rng: np.random.Generator,
    n: int,
    rate: float,
) -> tuple[np.ndarray, np.ndarray]:
    """Uniform subsample; returns (indices, weight_multipliers)."""
    k = max(1, int(round(n * rate)))
    idx = rng.choice(n, size=k, replace=False)
    mult = np.full(k, 1.0 / rate, np.float32)
    return idx, mult


def binary_classification_down_sample(
    rng: np.random.Generator,
    labels: np.ndarray,
    rate: float,
) -> tuple[np.ndarray, np.ndarray]:
    """Keep all positives; sample negatives at ``rate`` with 1/rate weights.

    The returned index set has a deterministic size given (labels, rate):
    num_pos + round(num_neg*rate), so batch shapes stay static across
    iterations with a fixed dataset.
    """
    pos = np.where(labels > 0)[0]
    neg = np.where(labels <= 0)[0]
    k = max(1, int(round(len(neg) * rate)))
    sampled_neg = rng.choice(len(neg), size=min(k, len(neg)), replace=False)
    idx = np.concatenate([pos, neg[sampled_neg]])
    mult = np.concatenate([
        np.ones(len(pos), np.float32),
        np.full(len(sampled_neg), 1.0 / rate, np.float32),
    ])
    return idx, mult
