"""GAME model classes: fixed-effect and random-effect submodels.

Reference parity: photon-api ``model/GameModel.scala``
(``Map[CoordinateId, DatumScoringModel]``), ``model/FixedEffectModel.scala``
(a broadcast GLM), ``model/RandomEffectModel.scala``
(``RDD[(REId, GeneralizedLinearModel)]``), ``model/DatumScoringModel.scala``.

TPU-first design: a RandomEffectModel is ONE dense (num_entities, d) matrix
(plus optional variances) instead of an RDD of per-entity models — scoring
is a row gather + rowwise dot (one fused kernel), and "broadcast" of the
fixed-effect model is just replicated sharding. Entities without a trained
model keep zero rows, matching the reference's passive-data scoring (no
random-effect contribution).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.data.game_data import GameDataset
from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.types import TaskType

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class FixedEffectModel:
    """One shared GLM over a feature shard (reference: FixedEffectModel)."""

    shard_id: str
    coefficients: Coefficients

    @property
    def dim(self) -> int:
        return self.coefficients.dim

    def score(self, dataset: GameDataset) -> Array:
        from photon_ml_tpu.data.game_data import SparseShard

        shard = dataset.feature_shards[self.shard_id]
        means = self.coefficients.means
        if isinstance(shard, SparseShard):
            from photon_ml_tpu.ops.sparse_aggregators import ell_matvec
            return ell_matvec(jnp.asarray(shard.indices),
                              jnp.asarray(shard.values), means)
        return jnp.asarray(shard) @ means


@dataclasses.dataclass(frozen=True)
class RandomEffectModel:
    """Per-entity coefficient table (reference: RandomEffectModel).

    ``means`` is (num_entities, d); untrained entities hold zero rows.
    """

    re_type: str
    shard_id: str
    means: Array  # (num_entities, d)
    variances: Optional[Array] = None  # (num_entities, d)

    @property
    def num_entities(self) -> int:
        return self.means.shape[0]

    @property
    def dim(self) -> int:
        return self.means.shape[1]

    def score(self, dataset: GameDataset) -> Array:
        from photon_ml_tpu.data.game_data import SparseShard

        shard = dataset.feature_shards[self.shard_id]
        ids = jnp.asarray(dataset.entity_ids[self.re_type])
        # Row-gather then fused rowwise dot: score_i = x_i · W[e_i].
        # Ids beyond the model's entity table (validation/scoring data read
        # with allow_unseen_entities=True) contribute exactly zero — the
        # reference's passive/unseen-entity semantics (fixed effect only).
        safe = jnp.minimum(ids, self.means.shape[0] - 1)
        if isinstance(shard, SparseShard):
            # ELL padding slots carry value 0 by contract, so clamping
            # their sentinel index (== d) into range is exact — no
            # (E, d+1) padded copy of the table.
            W = jnp.asarray(self.means)
            idx = jnp.minimum(jnp.asarray(shard.indices), W.shape[1] - 1)
            contrib = jnp.sum(
                jnp.asarray(shard.values) * W[safe[:, None], idx], axis=-1)
        else:
            contrib = jnp.einsum("nd,nd->n", jnp.asarray(shard),
                                 self.means[safe])
        return jnp.where(ids < self.means.shape[0], contrib, 0.0)


# FactoredRandomEffectModel (game/factored.py) also satisfies this contract
# (score(dataset) + re_type/shard_id); kept out of the Union to avoid an
# import cycle — use duck typing where models are dispatched.
CoordinateModel = Union[FixedEffectModel, RandomEffectModel]


@dataclasses.dataclass
class GameModel:
    """Additive combination of coordinate models (reference: GameModel)."""

    task: TaskType
    models: dict[str, CoordinateModel]  # CoordinateId -> model

    def score(self, dataset: GameDataset,
              include_offsets: bool = True) -> Array:
        total = jnp.asarray(dataset.offsets) if include_offsets else jnp.zeros(
            dataset.num_rows, jnp.float32)
        for model in self.models.values():
            total = total + model.score(dataset)
        return total

    def coordinate_scores(self, dataset: GameDataset) -> dict[str, Array]:
        return {cid: m.score(dataset) for cid, m in self.models.items()}
