"""GAME model classes: fixed-effect and random-effect submodels.

Reference parity: photon-api ``model/GameModel.scala``
(``Map[CoordinateId, DatumScoringModel]``), ``model/FixedEffectModel.scala``
(a broadcast GLM), ``model/RandomEffectModel.scala``
(``RDD[(REId, GeneralizedLinearModel)]``), ``model/DatumScoringModel.scala``.

TPU-first design: a RandomEffectModel is ONE dense (num_entities, d) matrix
(plus optional variances) instead of an RDD of per-entity models — scoring
is a row gather + rowwise dot (one fused kernel), and "broadcast" of the
fixed-effect model is just replicated sharding. Entities without a trained
model keep zero rows, matching the reference's passive-data scoring (no
random-effect contribution).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.data.game_data import GameDataset
from photon_ml_tpu.models.coefficients import Coefficients
from photon_ml_tpu.types import TaskType

Array = jax.Array


@dataclasses.dataclass(frozen=True)
class FixedEffectModel:
    """One shared GLM over a feature shard (reference: FixedEffectModel)."""

    shard_id: str
    coefficients: Coefficients

    @property
    def dim(self) -> int:
        return self.coefficients.dim

    def score(self, dataset: GameDataset) -> Array:
        from photon_ml_tpu.data.game_data import SparseShard

        shard = dataset.feature_shards[self.shard_id]
        means = self.coefficients.means
        if isinstance(shard, SparseShard):
            from photon_ml_tpu.ops.sparse_aggregators import ell_matvec
            return ell_matvec(jnp.asarray(shard.indices),
                              jnp.asarray(shard.values), means)
        return jnp.asarray(shard) @ means


@dataclasses.dataclass(frozen=True)
class RandomEffectModel:
    """Per-entity coefficient table (reference: RandomEffectModel).

    ``means`` is (num_entities, d); untrained entities hold zero rows.
    """

    re_type: str
    shard_id: str
    means: Array  # (num_entities, d)
    variances: Optional[Array] = None  # (num_entities, d)

    @property
    def num_entities(self) -> int:
        return self.means.shape[0]

    @property
    def dim(self) -> int:
        return self.means.shape[1]

    def entity_rows(self, ids: np.ndarray) -> np.ndarray:
        """Dense (len(ids), dim) coefficient rows for trained entities —
        the host-side fetch contract shared by every random-effect model
        type (serving/model_store.py's cache-fill path). Caller guarantees
        0 <= id < num_entities."""
        return np.asarray(self.means)[np.asarray(ids, np.int64)]

    def score(self, dataset: GameDataset) -> Array:
        from photon_ml_tpu.data.game_data import SparseShard

        shard = dataset.feature_shards[self.shard_id]
        ids = jnp.asarray(dataset.entity_ids[self.re_type])
        # Row-gather then fused rowwise dot: score_i = x_i · W[e_i].
        # Ids beyond the model's entity table (validation/scoring data read
        # with allow_unseen_entities=True) contribute exactly zero — the
        # reference's passive/unseen-entity semantics (fixed effect only).
        safe = jnp.minimum(ids, self.means.shape[0] - 1)
        if isinstance(shard, SparseShard):
            # ELL padding slots carry value 0 by contract, so clamping
            # their sentinel index (== d) into range is exact — no
            # (E, d+1) padded copy of the table.
            W = jnp.asarray(self.means)
            idx = jnp.minimum(jnp.asarray(shard.indices), W.shape[1] - 1)
            contrib = jnp.sum(
                jnp.asarray(shard.values) * W[safe[:, None], idx], axis=-1)
        else:
            contrib = jnp.einsum("nd,nd->n", jnp.asarray(shard),
                                 self.means[safe])
        return jnp.where(ids < self.means.shape[0], contrib, 0.0)


def dense_rows_from_subspace(cols: np.ndarray, means: np.ndarray,
                             num_features: int) -> np.ndarray:
    """Scatter (k, A) subspace rows into dense (k, num_features) rows.

    THE densification semantic for subspace coefficients — shared by
    ``SubspaceRandomEffectModel.entity_rows`` and the serving host store's
    cache-fill path, which densifies only the hot entities it fetches
    (never the whole (E, d) table).
    """
    cols = np.asarray(cols)
    means = np.asarray(means, np.float32)
    W = np.zeros((cols.shape[0], num_features), np.float32)
    r, c = np.nonzero(cols >= 0)
    W[r, cols[r, c]] = means[r, c]
    return W


def sort_subspace_rows(cols: np.ndarray, *tables: Optional[np.ndarray]):
    """Canonicalize subspace rows: sort each row by column id with padding
    (-1) last, permuting the parallel coefficient tables identically.

    This IS the SubspaceRandomEffectModel layout invariant — ``score()``'s
    per-row searchsorted requires it — shared by the coordinate's staging
    and the Avro loader. Returns (cols_sorted, order, *tables_sorted);
    ``order`` is the sorted←unsorted permutation; None tables pass
    through.
    """
    order = np.argsort(
        np.where(cols < 0, np.iinfo(np.int32).max, cols),
        axis=1, kind="stable").astype(np.int32)
    out = [np.take_along_axis(cols, order, axis=1), order]
    for t in tables:
        out.append(None if t is None
                   else np.take_along_axis(np.asarray(t), order, axis=1))
    return tuple(out)


def _subspace_positions(cols: np.ndarray, num_features: int,
                        entity_ids: np.ndarray,
                        indices: np.ndarray) -> np.ndarray:
    """Map data nonzeros into per-entity subspace slots.

    ``cols`` is the model's (E, A) active-column table (-1 padding);
    ``indices`` the dataset's (n, k) ELL column ids (sentinel
    ``num_features`` for padding). Returns (n, k) FLAT indices into
    ``cols``-shaped tables, with misses (column inactive for that entity,
    entity beyond the table, ELL padding) mapped to E*A (one past the end).

    One sorted join over (entity, column) keys — vectorized host numpy, no
    per-entity work; the (E, d) dense table this replaces never exists.
    """
    E, A = cols.shape
    d1 = np.int64(num_features + 1)
    valid_m = cols >= 0
    mkeys = (np.repeat(np.arange(E, dtype=np.int64), A) * d1
             + np.where(valid_m, cols, -1).astype(np.int64).reshape(-1))
    flat_slots = np.arange(E * A, dtype=np.int64)
    keep = valid_m.reshape(-1)
    mkeys, flat_slots = mkeys[keep], flat_slots[keep]
    order = np.argsort(mkeys, kind="stable")
    mkeys, flat_slots = mkeys[order], flat_slots[order]

    ids = np.asarray(entity_ids, np.int64)
    dkeys = (np.minimum(ids, E - 1)[:, None] * d1
             + np.minimum(np.asarray(indices, np.int64), num_features))
    if not len(mkeys):  # no active columns anywhere: every lookup misses
        return np.full(dkeys.shape, E * A, np.int64)
    pos = np.searchsorted(mkeys, dkeys)
    pos_c = np.minimum(pos, len(mkeys) - 1)
    hit = (mkeys[pos_c] == dkeys) & (ids[:, None] < E)
    return np.where(hit, flat_slots[pos_c], E * A).astype(np.int64)


@dataclasses.dataclass(frozen=True)
class SubspaceRandomEffectModel:
    """Per-entity models kept in their active-column subspaces.

    Reference parity: photon-api ``model/RandomEffectModelInProjectedSpace
    .scala`` — models live in each entity's projected space and only
    project back for output. Here that is the PRIMARY representation for
    the large-scale sparse regime: ``cols`` (num_entities, A) holds each
    entity's active global columns (-1 padding, A = max subspace width)
    and ``means`` the coefficients for exactly those columns, in original
    space — so a 10⁶-entity × 10⁶-feature random effect stores E·A
    coefficients, not the impossible dense (E, d) table.
    """

    re_type: str
    shard_id: str
    num_features: int  # full feature-space dimension d
    cols: Array  # (num_entities, A) int32 active columns; -1 padding
    means: Array  # (num_entities, A) coefficients for those columns
    variances: Optional[Array] = None  # (num_entities, A)

    @property
    def num_entities(self) -> int:
        return self.cols.shape[0]

    @property
    def dim(self) -> int:
        return int(self.num_features)

    @property
    def subspace_dim(self) -> int:
        return self.cols.shape[1]

    def entity_rows(self, ids: np.ndarray) -> np.ndarray:
        """Dense (len(ids), num_features) rows (RandomEffectModel's
        ``entity_rows`` contract) — densifies ONLY the requested entities."""
        ids = np.asarray(ids, np.int64)
        return dense_rows_from_subspace(
            np.asarray(self.cols)[ids], np.asarray(self.means)[ids],
            self.num_features)

    def score(self, dataset: GameDataset) -> Array:
        """Score without ever materializing (E, d).

        ``cols`` rows are SORTED by column id (padding -1 at the end, by
        construction in RandomEffectCoordinate), so mapping a dataset's
        columns into each entity's subspace is a per-row device
        ``searchsorted`` — no host-side join, staged datasets stay
        device-resident across repeated validation scoring.
        """
        from photon_ml_tpu.data.game_data import SparseShard

        shard = dataset.feature_shards[self.shard_id]
        ids = jnp.asarray(dataset.entity_ids[self.re_type])
        E, A = self.cols.shape
        safe_e = jnp.minimum(ids, E - 1)
        if isinstance(shard, SparseShard):
            C = jnp.asarray(self.cols)[safe_e]  # (n, A)
            Cs = jnp.where(C < 0, self.num_features + 1, C)
            idx = jnp.asarray(shard.indices)  # (n, k); sentinel d padding
            pos = jax.vmap(jnp.searchsorted)(Cs, idx)
            posc = jnp.minimum(pos, A - 1)
            hit = ((jnp.take_along_axis(Cs, posc, axis=1) == idx)
                   & (ids[:, None] < E))
            Wn = jnp.asarray(self.means)[safe_e]
            return jnp.sum(jnp.asarray(shard.values)
                           * jnp.take_along_axis(Wn, posc, axis=1) * hit,
                           axis=-1)
        # Dense shard: gather each row's entity-active columns of X.
        cols = jnp.asarray(self.cols)[safe_e]  # (n, A)
        X = jnp.asarray(shard)
        xa = jnp.take_along_axis(
            X, jnp.maximum(cols, 0), axis=1) * (cols >= 0)
        contrib = jnp.einsum("na,na->n", xa,
                             jnp.asarray(self.means)[safe_e])
        return jnp.where(ids < E, contrib, 0.0)

    def to_random_effect_model(self) -> "RandomEffectModel":
        """Materialize the dense (E, d) table (small-d interop only)."""
        E, A = self.cols.shape
        cols = jnp.asarray(self.cols)
        safe_c = jnp.where(cols >= 0, cols, self.num_features)
        rows = jnp.repeat(jnp.arange(E), A)

        def scatter(tab):
            if tab is None:
                return None
            W = jnp.zeros((E, self.num_features + 1), jnp.float32)
            W = W.at[rows, safe_c.reshape(-1)].set(
                jnp.asarray(tab).reshape(-1))
            return W[:, : self.num_features]

        return RandomEffectModel(
            re_type=self.re_type, shard_id=self.shard_id,
            means=scatter(self.means), variances=scatter(self.variances))


# FactoredRandomEffectModel (game/factored.py) also satisfies this contract
# (score(dataset) + re_type/shard_id); kept out of the Union to avoid an
# import cycle — use duck typing where models are dispatched.
CoordinateModel = Union[FixedEffectModel, RandomEffectModel,
                        SubspaceRandomEffectModel]


@dataclasses.dataclass
class GameModel:
    """Additive combination of coordinate models (reference: GameModel)."""

    task: TaskType
    models: dict[str, CoordinateModel]  # CoordinateId -> model

    def score(self, dataset: GameDataset,
              include_offsets: bool = True) -> Array:
        total = jnp.asarray(dataset.offsets) if include_offsets else jnp.zeros(
            dataset.num_rows, jnp.float32)
        for model in self.models.values():
            total = total + model.score(dataset)
        return total

    def coordinate_scores(self, dataset: GameDataset) -> dict[str, Array]:
        return {cid: m.score(dataset) for cid, m in self.models.items()}
