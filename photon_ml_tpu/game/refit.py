"""Incremental per-entity refit: the cheap half of the production loop.

A GLMix deployment splits its training hierarchically (Snap ML's
resource-matching design, PAPERS.md): a HEAVY offline fit produces the
base model, and per-entity random effects refresh continuously as
traffic arrives. Each random-effect row is an independent tiny solve
against fixed offsets — exactly the warm-started per-coordinate solves
of distributed coordinate descent (Trofimov–Genkin, PAPERS.md) — so a
refresh is embarrassingly parallel over the DIRTY entity set and reuses
the existing vmapped bucket solvers verbatim (game/coordinates/
random_effect.py): build a tiny dataset from the logged tuples, bucket
it, solve every dirty entity simultaneously, and cut the changed rows
into a versioned delta (serving/publish.py).

The refit CONTRACT that makes served scores provable (the continuity
proof tests/test_publish.py runs):

* a refit batch carries an entity's COMPLETE logged history ``(features,
  label, offset[, weight])``, in a stable per-entity order — the
  incremental unit is the ENTITY, not the example;
* every solve warm-starts from the BASE model's row (the offline fit the
  log accumulates against), with the same optimizer configuration;
* solves are quantized into FIXED-size lane groups (``lane_group``,
  default = the bucketing pad multiple): the dirty set is chunked by
  sorted entity id and each chunk solves against a compact
  ``lane_group``-row table, so every entity's compiled program shape is
  ``(lane_group, its own pow-2 capacity, d)`` — INDEPENDENT of how many
  other entities happened to be dirty. Without this, a bigger dirty set
  changes the vmap lane count, XLA vectorizes the solve differently,
  and 1-ulp input jitter amplifies through L-BFGS into ~1e-5 row drift
  (measured; the per-lane math is only bit-stable at a fixed shape).

Together these make the row an entity gets from publish k bit-identical
to the row an offline FULL refit over the union of all logged tuples
would give it — incremental publication never drifts from the offline
answer, no matter how the dirty sets were batched. Group program
shapes repeat, so the persistent compilation cache
(utils/compile_cache) serves every group after the first of a given
capacity with a disk hit instead of an XLA compile.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Optional

import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.data.game_data import GameDataset
from photon_ml_tpu.game.models import GameModel, RandomEffectModel
from photon_ml_tpu.ops import losses as losses_mod
from photon_ml_tpu.optim.problem import GLMOptimizationConfiguration
from photon_ml_tpu.utils.diskio import atomic_write

logger = logging.getLogger("photon_ml_tpu.game")


@dataclasses.dataclass(frozen=True)
class RefitBatch:
    """Logged scoring traffic for one random-effect coordinate: the
    ``(features, label, offset)`` tuples of every DIRTY entity (offset =
    the rest of the model's score for that example — the fixed effects
    and other coordinates the per-entity solve holds constant)."""

    re_type: str
    shard_id: str
    entity_ids: np.ndarray  # (n,) int64 vocabulary rows
    features: np.ndarray  # (n, d) float32 dense feature rows
    labels: np.ndarray  # (n,)
    offsets: np.ndarray  # (n,) rest-of-model scores
    weights: Optional[np.ndarray] = None  # (n,); ones when None

    @property
    def num_rows(self) -> int:
        return int(self.entity_ids.shape[0])

    @property
    def dirty_entities(self) -> np.ndarray:
        return np.unique(np.asarray(self.entity_ids, np.int64))


def save_refit_batch(path: str, batch: RefitBatch) -> None:
    """Persist one logged-tuple batch atomically (the npz handoff
    between the traffic logger and ``photon-game-publish``)."""
    payload = {
        "re_type": np.asarray(batch.re_type),
        "shard_id": np.asarray(batch.shard_id),
        "entity_ids": np.asarray(batch.entity_ids, np.int64),
        "features": np.asarray(batch.features, np.float32),
        "labels": np.asarray(batch.labels, np.float32),
        "offsets": np.asarray(batch.offsets, np.float32),
    }
    if batch.weights is not None:
        payload["weights"] = np.asarray(batch.weights, np.float32)
    atomic_write(path, lambda f: np.savez(f, **payload))


def load_refit_batch(path: str) -> RefitBatch:
    with np.load(path, allow_pickle=False) as z:
        return RefitBatch(
            re_type=str(z["re_type"]),
            shard_id=str(z["shard_id"]),
            entity_ids=np.asarray(z["entity_ids"], np.int64),
            features=np.asarray(z["features"], np.float32),
            labels=np.asarray(z["labels"], np.float32),
            offsets=np.asarray(z["offsets"], np.float32),
            weights=(np.asarray(z["weights"], np.float32)
                     if "weights" in z.files else None))


def refit_rows(
    model: GameModel,
    cid: str,
    batch: RefitBatch,
    config: Optional[GLMOptimizationConfiguration] = None,
    mesh=None,
    lane_group: Optional[int] = None,
) -> tuple[np.ndarray, np.ndarray, dict]:
    """Refit the dirty entities of coordinate ``cid`` from logged tuples.

    Returns ``(entity_ids, rows, stats)``: the refit vocabulary rows (the
    delta payload serving/publish.py versions) plus refit accounting.
    The base coordinate model provides the warm starts; entities absent
    from the batch are untouched (their rows are not in the delta).

    ``lane_group`` is the batch-invariance quantum (module docstring):
    keep it at its default (the mesh's entity pad multiple) unless every
    publisher in the deployment agrees on another value — rows are only
    bit-comparable between refits run with the SAME group size.
    """
    from photon_ml_tpu.game.coordinates.random_effect import \
        RandomEffectCoordinate
    from photon_ml_tpu.parallel.mesh import make_mesh

    base = model.models.get(cid)
    if base is None:
        raise ValueError(f"model has no coordinate {cid!r} "
                         f"(has {sorted(model.models)})")
    if not isinstance(base, RandomEffectModel):
        raise ValueError(
            f"coordinate {cid!r} is {type(base).__name__}; incremental "
            f"refit serves dense RandomEffectModel coordinates (subspace/"
            f"factored refit needs the full staging path)")
    if batch.num_rows == 0:
        raise ValueError("refit batch carries no logged tuples")
    if batch.features.shape[1] != base.dim:
        raise ValueError(
            f"logged features are {batch.features.shape[1]}-dimensional, "
            f"coordinate {cid!r} expects {base.dim}")
    t0 = time.perf_counter()
    mesh = mesh if mesh is not None else make_mesh()
    if lane_group is None:
        # The same pad multiple RandomEffectCoordinate buckets with —
        # every group's lane axis pads to exactly this.
        lane_group = max(8, int(np.prod(list(mesh.shape.values()))))
    all_ids = np.asarray(batch.entity_ids, np.int64)
    if all_ids.size and (int(all_ids.min()) < 0
                         or int(all_ids.max()) >= base.num_entities):
        raise ValueError(
            f"logged entity ids outside [0, {base.num_entities})")
    weights = (np.ones(batch.num_rows, np.float32)
               if batch.weights is None
               else np.asarray(batch.weights, np.float32))
    labels = np.asarray(batch.labels, np.float32)
    offsets = np.asarray(batch.offsets, np.float32)
    features = np.asarray(batch.features, np.float32)
    base_means = np.asarray(base.means, np.float32)
    loss = losses_mod.loss_for_task(model.task)
    config = config or GLMOptimizationConfiguration()
    dirty = np.unique(all_ids)
    parts: list = []  # (k, (lane_group, d) device table) per group
    groups = 0
    for lo in range(0, dirty.shape[0], lane_group):
        group = dirty[lo: lo + lane_group]
        k = group.shape[0]
        sel = np.isin(all_ids, group)
        # Compact local table: entity i of the group is row i; the
        # table pads to lane_group rows so the compiled scatter shape
        # never depends on the group's fill (zero rows never train —
        # no examples reference them).
        local = np.searchsorted(group, all_ids[sel])
        warm = np.zeros((lane_group, base.dim), np.float32)
        warm[:k] = base_means[group]
        data = GameDataset(
            response=labels[sel],
            offsets=offsets[sel],
            weights=weights[sel],
            feature_shards={batch.shard_id: features[sel]},
            entity_ids={batch.re_type: local},
            num_entities={batch.re_type: int(lane_group)},
        )
        coord = RandomEffectCoordinate(
            data, batch.re_type, batch.shard_id, loss, config, mesh)
        initial = RandomEffectModel(
            re_type=batch.re_type, shard_id=batch.shard_id,
            means=jnp.asarray(warm))
        refit = coord.train_model(jnp.asarray(data.offsets),
                                  initial=initial)
        parts.append((k, refit.means))
        groups += 1
    # ONE device->host transfer for the whole dirty set (the group
    # results stay on device until here).
    out_rows = np.asarray(jnp.concatenate(
        [means[:k] for k, means in parts], axis=0), np.float32)
    stats = {
        "coordinate": cid,
        "dirty_entities": int(dirty.shape[0]),
        "logged_rows": batch.num_rows,
        "lane_group": int(lane_group),
        "groups": groups,
        "refit_seconds": round(time.perf_counter() - t0, 6),
    }
    logger.info("refit %s: %d dirty entit(ies) from %d logged row(s) "
                "in %d group(s), %.3fs", cid, stats["dirty_entities"],
                batch.num_rows, groups, stats["refit_seconds"])
    return dirty.astype(np.int64), out_rows, stats
